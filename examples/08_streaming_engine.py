"""The fused-iteration HBM-streaming engine: past the VMEM boundary.

The VMEM-resident engine (example 07) ends where the CG working set
outgrows VMEM (~128^3 f32).  Beyond it - BASELINE's 256^3 north star,
67 MB per vector - each iteration of the general solver crosses HBM at
every XLA fusion boundary (~16 plane-passes/iter measured).  The
streaming engine runs each iteration as TWO slab-streaming pallas
launches (the two inner products are global barriers, so two passes is
the CG data-flow minimum): pass A fuses the deferred p-update with the
matvec and p.Ap; pass B recomputes Ap from p_new's halo slabs and
updates x/r in place while reducing ||r||^2 - 8 HBM plane-passes per
iteration, ~2x projected at 256^3.

Iteration counts match the general solver EXACTLY at equal tolerances;
the convergence check rides the while_loop carry every iteration for
free.  The distributed form keeps the same kernels as the per-shard
local step: neighbor halos ride ppermute into the kernels' edge slabs,
the slab-accumulated dots psum.

On TPU the kernels run compiled; elsewhere this example uses pallas
interpret mode (slow, small grid) - semantics are identical.

Run: python examples/08_streaming_engine.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np

from cuda_mpi_parallel_tpu import cg_streaming, solve
from cuda_mpi_parallel_tpu.models import poisson

on_tpu = jax.default_backend() == "tpu"
# On hardware, use a grid past the VMEM-resident ceiling (e.g. 256^3 or
# 4096^2); in interpret mode keep it tiny.
nx, ny = (4096, 4096) if on_tpu else (16, 128)
print(f"== fused-iteration streaming CG on a {nx}x{ny} grid "
      f"({'compiled' if on_tpu else 'interpret mode'})")

op = poisson.poisson_2d_operator(nx, ny, dtype=jnp.float32)
rng = np.random.default_rng(0)
b = jnp.asarray(rng.standard_normal(nx * ny).astype(np.float32))

res = solve(op, b, tol=0.0, rtol=1e-4, maxiter=300, engine="streaming")
print(f"streaming engine : {int(res.iterations)} iters, "
      f"||r|| = {float(res.residual_norm):.3e}, "
      f"converged={bool(res.converged)}")

ref = solve(op, b, tol=0.0, rtol=1e-4, maxiter=300, check_every=1)
print(f"general solver   : {int(ref.iterations)} iters "
      f"(iteration counts match: "
      f"{int(res.iterations) == int(ref.iterations)})")

# per-iteration residual history at the general solver's granularity
res_h = cg_streaming(op, b, tol=0.0, rtol=1e-4, maxiter=300,
                     check_every=1, record_history=True,
                     interpret=not on_tpu)
hist = np.asarray(res_h.residual_history)
k = int(res_h.iterations)
print(f"history          : ||r0|| = {hist[0]:.3e} -> "
      f"||r_{k}|| = {hist[k]:.3e}")

assert int(res.iterations) == int(ref.iterations)
print("ok")
