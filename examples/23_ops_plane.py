"""Ops plane: scrape a live service, watch /readyz flip, merge a fleet.

Four acts against mesh-4 Poisson services, all over real loopback
HTTP (the stdlib ops plane, `serve.ops` - no new dependencies):

1. **Scrape mid-replay**: start a service with
   ``ServiceConfig(ops_port=0)`` (0 = ephemeral port), submit a
   workload, and curl ``/metrics`` (Prometheus text exposition
   v0.0.4), ``/readyz`` (the typed readiness verdict), ``/stats`` and
   ``/usage`` WHILE requests are in flight.  Scrapes are host-side
   reads: the solve stream is bitwise identical with or without them
   (tests/test_ops_plane.py asserts this; here we just watch).
2. **Causal tree over HTTP**: pull one request's rendered span tree
   from ``/traces/<trace_id>`` - the span store is fed by the NEW
   in-process event subscriber bus (`telemetry.events.subscribe`),
   never by tailing files.
3. **Kill a lane, watch /readyz flip**: a second service carries a
   sticky reduction-site `FaultPlan`; two breakdowns open its circuit
   breaker, and the very next ``/readyz`` answers 503 with
   ``failing: ["breakers"]`` - the machine-readable signal ROADMAP
   item 2's replica router routes on.
4. **Fleet-merge two replicas**: `telemetry.fleet.merge_snapshots`
   over both services' ``/snapshot`` payloads - counters summed
   exactly, histogram buckets summed bucket-wise (quantiles stay
   correct), gauges kept per-replica under a ``replica`` label.

Run:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu
      python examples/23_ops_plane.py
"""
import json
import os
import sys
import urllib.error
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8").strip()
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

from cuda_mpi_parallel_tpu.models import poisson
from cuda_mpi_parallel_tpu.parallel import make_mesh
from cuda_mpi_parallel_tpu.robust import FaultPlan
from cuda_mpi_parallel_tpu.serve import ServiceConfig, SolverService
from cuda_mpi_parallel_tpu.telemetry import fleet


def get(url, *, as_json=True):
    """GET url; 4xx/5xx responses are verdicts, not exceptions."""
    try:
        with urllib.request.urlopen(url, timeout=10) as r:
            status, body = r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        status, body = e.code, e.read().decode()
    return status, json.loads(body) if as_json else body


def main():
    rng = np.random.default_rng(0)
    a = poisson.poisson_2d_csr(16, 16)

    # ---- act 1: scrape a live replay --------------------------------
    print("=" * 64)
    print("act 1: concurrent scrapes of a live mesh-4 service")
    print("=" * 64)
    svc = SolverService(ServiceConfig(
        max_batch=4, max_wait_s=0.002, usage=True, ops_port=0))
    base = svc.ops_server().url
    print(f"ops plane listening on {base}")
    h = svc.register(a, mesh=make_mesh(4))
    futs = [svc.submit(h, np.asarray(a @ rng.standard_normal(256)),
                       tenant="acme")
            for _ in range(12)]
    # scrape WHILE the replay is in flight
    status, verdict = get(base + "/readyz")
    print(f"\nmid-replay GET /readyz -> {status} "
          f"status={verdict['status']} failing={verdict['failing']}")
    _, metrics = get(base + "/metrics", as_json=False)
    head = [ln for ln in metrics.splitlines()
            if ln.startswith(("# TYPE serve_requests",
                              "serve_requests"))]
    print("mid-replay GET /metrics (serve_requests_* lines):")
    for ln in head[:4]:
        print(f"  {ln}")
    results = [f.result(timeout=60) for f in futs]
    assert all(r.converged for r in results)
    _, stats = get(base + "/stats")
    print(f"\nafter replay: /stats completed={stats['completed']} "
          f"batches={stats['batches']}")
    _, usage = get(base + "/usage")
    print(f"/usage totals: {usage['totals']['batches']} batches, "
          f"{usage['totals']['device_seconds']:.4f} device-s, "
          f"tenants={sorted(usage['per_tenant'])}")

    # ---- act 2: one request's causal tree over HTTP -----------------
    print()
    print("=" * 64)
    print("act 2: GET /traces/<trace_id> (fed by the subscriber bus)")
    print("=" * 64)
    spans = svc.ops_server().span_records()
    trace_id = spans[-1]["trace_id"]
    _, tree = get(f"{base}/traces/{trace_id}", as_json=False)
    print(f"GET /traces/{trace_id[:16]}... ->")
    print(tree)

    # ---- act 3: kill a lane, watch /readyz flip ---------------------
    print("=" * 64)
    print("act 3: breaker opens -> /readyz flips to 503")
    print("=" * 64)
    faulty = SolverService(ServiceConfig(
        max_batch=1, max_wait_s=0.002, breaker_threshold=2,
        breaker_cooldown_s=60.0, ops_port=0))
    fbase = faulty.ops_server().url
    fh = faulty.register(a, mesh=make_mesh(4), inject=FaultPlan(
        site="reduction", iteration=1, sticky=True))
    status, verdict = get(fbase + "/readyz")
    print(f"before faults: GET /readyz -> {status} "
          f"({verdict['status']})")
    for _ in range(2):
        r = faulty.submit(fh, np.asarray(
            a @ rng.standard_normal(256))).result(timeout=60)
        print(f"  poisoned dispatch -> {r.status}")
    status, verdict = get(fbase + "/readyz")
    print(f"after 2 breakdowns: GET /readyz -> {status} "
          f"status={verdict['status']} failing={verdict['failing']}")
    print(f"  open breakers: {verdict['gates']['breakers']['open']}")
    assert status == 503 and verdict["failing"] == ["breakers"]

    # ---- act 4: fleet-merge the two replicas ------------------------
    print()
    print("=" * 64)
    print("act 4: fleet view over both replicas' /snapshot")
    print("=" * 64)
    _, snap_a = get(base + "/snapshot")
    _, snap_b = get(fbase + "/snapshot")
    # NOTE: in-process replicas share one global registry, so this
    # demonstrates the ALGEBRA; across real processes each snapshot is
    # distinct (tools/fleet_scrape.py is the multi-process driver)
    merged = fleet.merge_snapshots({"replica-a": snap_a,
                                    "replica-b": snap_b})
    reqs = merged["serve_requests_total"]["series"]
    print("merged serve_requests_total:")
    for s in reqs:
        print(f"  {s['labels']} = {s['value']}")
    lat = merged.get("serve_request_latency_seconds")
    if lat is not None:
        p = lat["series"][0]["percentiles"]
        print(f"merged latency percentiles (union-stream exact): "
              f"p50={p['p50']:.4g}s p99={p['p99']:.4g}s")
    depth = merged.get("serve_queue_depth")
    if depth is not None:
        print("per-replica queue depth gauges:")
        for s in depth["series"]:
            print(f"  replica={s['labels'].get('replica')} -> "
                  f"{s['value']}")

    svc.close()
    faulty.close()
    print("\nboth planes torn down with their services; "
          "scrapes now refuse:")
    try:
        urllib.request.urlopen(base + "/healthz", timeout=2)
    except Exception as e:
        print(f"  GET /healthz -> {type(e).__name__}")


if __name__ == "__main__":
    main()
