"""Overload-safe serving: ramp past capacity, shed before collapse.

Drives the multi-tenant solver service (``serve.admission`` +
``serve.sched``) through an open-loop saturation ramp and shows:

1. the shed ladder firing IN ORDER as offered load passes capacity -
   tolerance degraded first, ``bulk`` dispatch deferred second,
   admission rejection (with a ``retry_after_s`` hint) last, and
   accepted ``gold`` work never timing out;
2. goodput degrading smoothly instead of collapsing: in-SLO
   solved-RHS/s at 0.5x / 1x / 2x the measured capacity;
3. the starving-tenant rescue: a 10:1 hot ``bulk`` tenant beside a
   1-request ``gold`` tenant - weighted-fair (deficit-round-robin)
   dispatch bounds the cold tenant's wait where PR 10's
   oldest-queue-first pop would have parked it behind the whole hot
   backlog.

Run: python examples/19_overload.py
"""
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

from cuda_mpi_parallel_tpu.models import poisson
from cuda_mpi_parallel_tpu.serve import (
    AdmissionConfig,
    SchedConfig,
    ServiceConfig,
    ShedConfig,
    SolverService,
    TokenBucket,
    replay_workload,
    rhs_for,
    synthetic_tenant_mix,
)
from cuda_mpi_parallel_tpu.telemetry.report import service_lines

GRID = 48            # 2304 unknowns - quick on CPU, real enough to time
TOL = 1e-6
TENANTS = (("hot-farm", 10.0, "bulk"),      # the flooder
           ("web", 4.0, "silver"),
           ("checkout", 1.0, "gold"))


def build_service(capacity_hint=None):
    """Full protection stack: per-tenant buckets (the hot farm capped
    hardest), weighted-fair dispatch, auto shed ladder, 2 workers."""
    admission = None
    if capacity_hint:
        admission = AdmissionConfig(
            default=TokenBucket(rate=capacity_hint,
                                burst=max(capacity_hint, 8.0)),
            tenants=(("hot-farm",
                      TokenBucket(rate=max(0.6 * capacity_hint, 1.0),
                                  burst=max(0.6 * capacity_hint,
                                            8.0))),))
    return SolverService(ServiceConfig(
        max_batch=8, max_wait_s=0.002, queue_limit=256, maxiter=600,
        check_every=8, workers=2, admission=admission,
        shed=ShedConfig(auto=True)))


def run(a, rate, seed, capacity_hint=None, n=48):
    svc = build_service(capacity_hint)
    try:
        h = svc.register(a)
        reqs = synthetic_tenant_mix(n, rate, TENANTS, seed=seed)
        bs = [rhs_for(a, r.seed, dtype=np.float32)[0] for r in reqs]
        summary = replay_workload(svc, h, reqs, bs, tol=TOL)
        stats = svc.stats()
    finally:
        svc.close()
    return summary, stats


def main():
    a = poisson.poisson_2d_csr(GRID, GRID, dtype=np.float32)

    # -- measure raw capacity with one unmetered burst ----------------
    print("== probe: burst replay measures raw capacity ==")
    probe, _ = run(a, rate=1e6, seed=1, n=32)
    capacity = probe.solved / max(probe.window_s, 1e-9)
    print(f"drained {probe.solved} RHS in {probe.window_s:.3f} s "
          f"-> capacity ~{capacity:.0f} RHS/s\n")

    # -- the ramp: 0.5x, 1x, 2x through the protection stack ----------
    print("== saturation ramp (goodput = in-SLO solved RHS/s) ==")
    print(f"{'offered':>10} {'goodput':>9} {'in-SLO':>7} {'degr':>5} "
          f"{'defer':>6} {'rejected':>9} {'gold-TO':>8}")
    rows = {}
    for i, mult in enumerate((0.5, 1.0, 2.0)):
        rate = max(mult * capacity, 1.0)
        s, stats = run(a, rate=rate, seed=10 + i,
                       capacity_hint=capacity)
        shed = stats.get("shed") or {}
        gold = s.by_class.get("gold", {})
        rows[mult] = s
        print(f"{rate:>8.0f}/s {s.goodput_rhs_per_sec:>9.1f} "
              f"{s.in_slo:>4}/{s.offered:<3} {s.degraded:>5} "
              f"{shed.get('deferred_flows', 0):>6} {s.rejected:>9} "
              f"{gold.get('timeouts', 0):>8}")
        assert gold.get("timeouts", 0) == 0, \
            "accepted gold work must never time out"
    g1 = rows[1.0].goodput_rhs_per_sec
    g2 = rows[2.0].goodput_rhs_per_sec
    print(f"\ngoodput retention at 2x overload: "
          f"{100.0 * g2 / max(g1, 1e-9):.0f}% of the 1x goodput "
          f"(>= 80% = degrades instead of collapsing; > 100% means "
          f"deeper queues batched better)\n")

    # -- starving-tenant rescue ---------------------------------------
    print("== starving-tenant rescue (10:1 hot bulk vs 1 gold) ==")
    for fair, label in ((False, "PR 10 oldest-queue-first"),
                        (True, "weighted-fair DRR")):
        svc = SolverService(ServiceConfig(
            max_batch=4, max_wait_s=0.002, maxiter=600,
            check_every=8, sched=SchedConfig(fair=fair)))
        try:
            h = svc.register(a)
            rng = np.random.default_rng(99)
            hot_b = [np.asarray(
                a @ rng.standard_normal(a.shape[0]).astype(np.float32))
                for _ in range(24)]
            cold_b = np.asarray(
                a @ rng.standard_normal(a.shape[0]).astype(np.float32))
            hot = [svc.submit(h, b, tol=TOL, tenant="hot-farm",
                              slo_class="bulk") for b in hot_b]
            t0 = time.perf_counter()
            cold = svc.submit(h, cold_b, tol=TOL, tenant="checkout",
                              slo_class="gold")
            cold_res = cold.result(timeout=60)
            cold_wall = time.perf_counter() - t0
            svc.drain()
            assert cold_res.converged
            assert all(f.result(timeout=60).status for f in hot)
        finally:
            svc.close()
        print(f"  {label:<28}: gold answered in "
              f"{cold_wall * 1e3:7.1f} ms behind a 24-request hot "
              f"backlog")

    print("\n== service report (2x run) ==")
    # re-run 2x briefly for a report snapshot with the full stack
    s, stats = run(a, rate=max(2.0 * capacity, 2.0), seed=42,
                   capacity_hint=capacity, n=32)
    for line in service_lines(stats):
        print(f"  {line}")


if __name__ == "__main__":
    main()
