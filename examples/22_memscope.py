"""Memory observatory: per-shard HBM accounting, fit prediction, and
the over-budget refusal.

Three acts:

1. **Measure a real solve**: a mesh-4 distributed solve with telemetry
   active computes the static per-shard footprint (exact pinned
   partition bytes + the modeled solver working set + the
   jaxpr-liveness transient peak) and asserts it BYTE-EXACT against
   the dispatcher-held device arrays' summed global ``.nbytes`` - the
   same numbers from two independent derivations.
2. **Price the 256^3 target without touching a device**:
   ``predict_footprint`` prices the pod-scale 3-D Poisson system
   (16.8M unknowns) from geometry alone and
   ``smallest_fitting_mesh`` names the minimum pod slice per lane -
   including the cautionary allgather k=256 lane whose extended-x
   block never shrinks with the mesh.
3. **Refuse before compiling**: a serve registration whose widest
   batch bucket would overflow ``ServiceConfig.hbm_budget`` raises
   ``MemoryBudgetError`` BEFORE any partition or compile work, naming
   the smallest mesh that would fit; lifting the budget registers the
   same operator with a FITS memory profile.

Run:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu
      python examples/22_memscope.py
"""
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8").strip()
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

from cuda_mpi_parallel_tpu import telemetry
from cuda_mpi_parallel_tpu.models import poisson
from cuda_mpi_parallel_tpu.parallel import make_mesh, solve_distributed
from cuda_mpi_parallel_tpu.serve import ServiceConfig, SolverService
from cuda_mpi_parallel_tpu.telemetry import events, memscope


def fmt(v):
    for unit, scale in (("GiB", 2 ** 30), ("MiB", 2 ** 20),
                        ("KiB", 2 ** 10)):
        if v >= scale:
            return f"{v / scale:.2f} {unit}"
    return f"{int(v)} B"


# -- act 1: the measured twin on a real mesh-4 solve --------------------
print("== act 1: byte-exact footprint of a mesh-4 solve ==")
a = poisson.poisson_2d_csr(24, 24, dtype=np.float32)
b = np.random.default_rng(0).standard_normal(a.shape[0])
mesh = make_mesh(4)
memscope.reset_last_memory_profile()
try:
    with events.capture():
        telemetry.force_active(True)
        res = solve_distributed(a, b, mesh=mesh, tol=1e-6, maxiter=500)
finally:
    telemetry.force_active(False)
prof = memscope.last_memory_profile()
fp = prof["footprint"]
assert prof["measured_bytes"] == int(fp.matrix_bytes.sum()), \
    "static model disagrees with the device arrays"
print(f"  {fp.kind} x {fp.n_shards} shards: "
      f"{fmt(int(fp.persistent_bytes.max()))}/shard persistent "
      f"({fmt(int(fp.matrix_bytes.max()))} matrix + "
      f"{fmt(int(fp.solver_bytes.max()))} solver), "
      f"transient peak {fmt(fp.peak_bytes)} -> {fp.classification}")
print(f"  measured on device: {fmt(prof['measured_bytes'])} "
      f"== model, asserted ({int(res.iterations)} iterations)")

# -- act 2: the 256^3 feasibility table, zero device work ---------------
print("\n== act 2: pricing the 256^3 Poisson target (16.8M rows) ==")
n = 256 ** 3
nnz = n + 6 * 256 * 256 * 255         # 7-point stencil, exact
hbm = 16.0 * 2 ** 30
for label, kw in (
        ("f32 k=1 ring     ", dict(exchange="ring")),
        ("df64 k=1 ring    ", dict(exchange="ring", df64=True)),
        ("f32 k=32 ring    ", dict(exchange="ring", n_rhs=32)),
        ("f32 k=256 allgath", dict(exchange="allgather", n_rhs=256))):
    for p in (1, 2, 8):
        pred = memscope.predict_footprint(
            n=n, n_shards=p, nnz=nnz, itemsize=4, hbm_bytes=hbm, **kw)
        print(f"  {label} P={p:>3}: "
              f"{fmt(int(pred.persistent_bytes.max())):>11}/shard "
              f"-> {pred.classification}")
    fit = memscope.smallest_fitting_mesh(
        n=n, budget_bytes=hbm, nnz=nnz, itemsize=4,
        n_rhs=kw.get("n_rhs", 1), exchange=kw["exchange"],
        df64=kw.get("df64", False))
    print(f"  {label} minimum pod slice: "
          f"{fit if fit is not None else 'never fits'}")

# -- act 3: serve refuses an over-budget registration -------------------
print("\n== act 3: over-budget registration refused pre-compile ==")
wide = memscope.predict_footprint(
    n=a.shape[0], n_shards=4, indptr=np.asarray(a.indptr), itemsize=4,
    n_rhs=8, exchange="allgather", hbm_bytes=None)
budget = float(int(wide.peak_bytes) - 1)   # one byte short, on purpose
svc = SolverService(ServiceConfig(clock=lambda: 0.0, max_batch=8,
                                  hbm_budget=budget))
try:
    try:
        svc.register(a, mesh=mesh)
        raise SystemExit("refusal did not fire")
    except memscope.MemoryBudgetError as e:
        print(f"  refused: needs {fmt(e.required_bytes)}/device, "
              f"budget {fmt(e.budget_bytes)}; smallest fitting mesh "
              f"{e.smallest_fitting_mesh} shards")
finally:
    svc.close()
svc = SolverService(ServiceConfig(clock=lambda: 0.0, max_batch=8,
                                  hbm_budget=hbm))
try:
    memscope.reset_last_memory_profile()
    svc.register(a, mesh=mesh, warm=False)
    fp = memscope.last_memory_profile()["footprint"]
    print(f"  budget lifted to {fmt(hbm)}: registered, "
          f"{fp.classification} with "
          f"{fp.headroom_frac * 100:.1f}% headroom")
finally:
    svc.close()

print("\nall contracts held")
