"""Request observatory: causal traces, SLO burn rates, metered usage.

Three acts on a mesh-4 Poisson operator under a fake service clock:

1. **Trace a mixed outcome workload**: three tenants submit against a
   tight admission bucket; some requests converge, one is turned away
   at admission.  Every request leaves a causal span chain
   (``submit -> admission -> queue_wait -> sched -> solve -> result``)
   on the event stream, each span carrying a W3C ``traceparent`` and
   the ``solve`` span carrying the REAL ``solve_id`` of its batch
   dispatch - the join key into the solve-level telemetry.  The
   forest is rebuilt from the JSONL alone and rendered; the asserted
   contract is ZERO orphan spans.
2. **Burn the error budget**: the rejected tenant's flow trips the
   fast-window SLO burn tracker (budget 1%, threshold 2x) and emits
   an edge-triggered ``slo_burn`` event - deterministic on the fake
   clock, because burn rates are computed on service time, not wall
   time.
3. **Reconcile the meter**: the usage ledger apportions each batch's
   device-seconds / iterations / wire bytes across its lanes; the
   per-tenant roll-up is re-summed against the batch totals and must
   agree to float round-off (< 1e-9 relative).

Run:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu
      python examples/21_request_observatory.py
"""
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8").strip()
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

from cuda_mpi_parallel_tpu.models import poisson
from cuda_mpi_parallel_tpu.parallel import make_mesh
from cuda_mpi_parallel_tpu.serve import (
    AdmissionConfig,
    ServiceConfig,
    SolverService,
    TokenBucket,
)
from cuda_mpi_parallel_tpu.telemetry import events, tracing
from cuda_mpi_parallel_tpu.telemetry.slo import SLOConfig, SLOWindow


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def main() -> int:
    clock = FakeClock()
    a = poisson.poisson_2d_csr(16, 16, dtype=np.float64)
    rng = np.random.default_rng(0)
    mk_b = lambda: np.asarray(a @ rng.standard_normal(a.shape[0]))  # noqa: E731

    with events.capture() as buf:
        svc = SolverService(ServiceConfig(
            clock=clock, max_batch=4, max_wait_s=0.01, maxiter=500,
            usage=True,
            # per-tenant buckets: 2 tokens each, no meaningful refill,
            # so tenant "lab"'s third request is turned away
            admission=AdmissionConfig(
                default=TokenBucket(rate=0.001, burst=2)),
            # "lab" sees 3 samples total (2 good + the rejection), so
            # the sample floor must sit at 3 for the trip to arm
            slo=SLOConfig(windows=(SLOWindow("fast", 5.0, 2.0),),
                          budget=0.01, min_samples=3)))
        h = svc.register(a, mesh=make_mesh(4))

        print("== act 1: traced mixed-outcome workload ==")
        futs = []
        for i in range(6):
            futs.append(svc.submit(
                h, mk_b(), tol=1e-8,
                tenant=["acme", "bulkco", "lab"][i % 3]))
        rejected = svc.submit(h, mk_b(), tol=1e-8, tenant="lab")
        clock.t = 0.011
        svc.pump()
        results = [f.result(timeout=60) for f in futs]
        rej = rejected.result(timeout=60)
        assert all(r.converged for r in results)
        assert rej.status == "ADMISSION_REJECTED", rej.status
        stats = svc.stats()
        svc.close()

    recs = [json.loads(ln) for ln in buf.getvalue().splitlines()
            if ln.strip()]
    spans = tracing.span_events(recs)
    orphans = tracing.orphan_spans(recs)
    forest = tracing.build_forest(recs)
    print(f"  {len(spans)} spans in {len(forest)} traces, "
          f"{len(orphans)} orphans")
    assert len(forest) == 7 and not orphans
    dispatch_ids = {e["solve_id"] for e in recs
                    if e["event"] == "batch_dispatch"}
    solve_ids = {s["solve_id"] for s in spans if s["name"] == "solve"}
    assert solve_ids <= dispatch_ids
    print(f"  solve spans join batch telemetry: "
          f"{sorted(solve_ids)} <= {sorted(dispatch_ids)}")
    # render one converged trace and the rejected one
    rej_tid = next(s["trace_id"] for s in spans
                   if s.get("status") == "ADMISSION_REJECTED")
    ok_tid = next(s["trace_id"] for s in spans
                  if s.get("status") == "CONVERGED")
    for tid, tag in ((ok_tid, "converged"), (rej_tid, "rejected")):
        print(f"  -- {tag} request --")
        for line in tracing.render_tree(recs, tid).splitlines():
            print(f"  {line}")

    print("== act 2: SLO burn on the rejected flow ==")
    burns = [e for e in recs if e["event"] == "slo_burn"]
    assert burns, "expected the rejection to trip the fast window"
    for b in burns:
        print(f"  slo_burn tenant={b['tenant']} window={b['window']} "
              f"burn_rate={b['burn_rate']:.1f}x budget "
              f"at t_service={b['t_service']}")

    print("== act 3: usage ledger reconciliation ==")
    usage = stats["usage"]
    err = usage["reconcile_max_rel_err"]
    print(f"  totals: {usage['totals']['requests']} requests, "
          f"{usage['totals']['device_seconds']:.4f} device-s, "
          f"{usage['totals']['wire_bytes']:.0f} wire bytes")
    for tenant, row in sorted(usage["per_tenant"].items()):
        print(f"  {tenant:8s} {row['requests']:2d} req "
              f"{row['device_seconds']:.4f} device-s "
              f"{row['wire_bytes']:10.0f} wire B")
    print(f"  reconcile max rel err: {err:.3e}")
    assert err < 1e-9

    print("request observatory example OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
