"""The reference's hardcoded 3x3 system, end to end.

Reproduces CUDACG.cu's entire behavior (solve + print x) in four lines,
plus everything it never reported: iteration count, residual, status.
Run: python examples/01_oracle.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from cuda_mpi_parallel_tpu import solve
from cuda_mpi_parallel_tpu.models import poisson

a, b, x_expected = poisson.oracle_system()
res = solve(a, b)  # defaults = reference semantics (tol 1e-7 abs, maxit 2000)
print(f"x          = {res.x}")
print(f"expected   = {x_expected}")
print(f"iterations = {int(res.iterations)} (reference: 3)")
print(f"||r||      = {float(res.residual_norm):.3e}")
print(f"status     = {res.status_enum().name}")
print(f"indefinite = {bool(res.indefinite)}  (quirk Q1: p.Ap < 0 at iter 2)")

# The matrix is symmetric INDEFINITE (quirk Q1) - CG converges on it by
# luck.  MINRES is the principled algorithm for this matrix class:
res_mr = solve(a, b, method="minres")
print(f"minres     = {int(res_mr.iterations)} iters, "
      f"||r|| = {float(res_mr.residual_norm):.3e}, "
      f"indefinite certified = {bool(res_mr.indefinite)}")
