"""Many-RHS batching: one matrix sweep (and one halo exchange) serves
every right-hand side.

SpMV is memory-bound - its throughput is sustained stream bandwidth -
so a CG iteration's cost barely moves when k RHS columns ride one
SpMM.  This example solves the same Poisson-2D operator for k = 8
right-hand sides three ways and prints the measured amortization:

1. a SEQUENTIAL loop of 8 single-RHS ``solve()`` calls (the baseline
   a service without the batched tier would run);
2. MASKED BATCHED CG (``solve_many``): 8 independent recurrences in
   one ``lax.while_loop``, per-lane convergence masks - every lane's
   answer is bit-identical to its single-RHS solve;
3. TRUE BLOCK-CG (``solve_many(method="block")``): one coupled
   k-dimensional Krylov space - fewer iterations, same exchanges per
   iteration - with automatic masked-batched fallback on Gram
   breakdown (demonstrated with a duplicated column).

Run: python examples/14_many_rhs.py
"""
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np

from cuda_mpi_parallel_tpu import solve
from cuda_mpi_parallel_tpu.models import poisson
from cuda_mpi_parallel_tpu.solver import solve_many

GRID = 96          # 9216 unknowns - quick on CPU, real enough to time
K = 8
TOL = 1e-8


def timed(fn):
    jax.block_until_ready(fn().x)     # warmup (compile)
    t0 = time.perf_counter()
    out = fn()
    jax.block_until_ready(out.x)
    return time.perf_counter() - t0, out


def main():
    a = poisson.poisson_2d_csr(GRID, GRID, dtype=np.float64)
    rng = np.random.default_rng(14)
    n = a.shape[0]
    x_true = rng.standard_normal((n, K))
    b = np.array(a.matmat(jnp.asarray(x_true)))

    print(f"Poisson-2D {GRID}x{GRID} (n={n}), k={K} right-hand sides, "
          f"tol={TOL:g}\n")

    # 1) the sequential loop
    def sequential():
        results = [solve(a, b[:, j], tol=TOL, maxiter=800)
                   for j in range(K)]
        jax.block_until_ready(results[-1].x)
        return results

    sequential()                      # warmup all K compiles (one shape)
    t0 = time.perf_counter()
    seq = sequential()
    t_seq = time.perf_counter() - t0
    it_seq = sum(int(r.iterations) for r in seq)
    print(f"sequential loop : {t_seq * 1e3:8.1f} ms, {it_seq} total "
          f"iterations, {it_seq / t_seq:,.0f} lane-iters/s")

    # 2) masked batched CG
    t_bat, bat = timed(lambda: solve_many(a, b, tol=TOL, maxiter=800))
    it_bat = int(np.asarray(bat.iterations).sum())
    print(f"masked batched  : {t_bat * 1e3:8.1f} ms, {it_bat} total "
          f"iterations, {it_bat / t_bat:,.0f} lane-iters/s "
          f"({t_seq / t_bat:.1f}x faster than the loop)")
    for j in (0, K - 1):
        same = np.array_equal(np.asarray(seq[j].x),
                              np.asarray(bat.x[:, j]))
        print(f"  lane {j}: bit-identical to its single solve: {same}")

    # 3) true block-CG: the coupled Krylov space
    t_blk, blk = timed(lambda: solve_many(a, b, tol=TOL, maxiter=800,
                                          method="block"))
    print(f"block-CG        : {t_blk * 1e3:8.1f} ms, "
          f"{int(np.asarray(blk.iterations).max())} iterations to the "
          f"last lane (masked batched took "
          f"{int(np.asarray(bat.iterations).max())}); fallback: "
          f"{bool(blk.fallback)}")
    err = float(np.max(np.abs(np.asarray(blk.x) - x_true)))
    print(f"  max |x - x_true| over all lanes: {err:.2e}")

    # Gram breakdown -> masked-batched fallback, no abort
    b_dup = b.copy()
    b_dup[:, 1] = b_dup[:, 0]
    dup = solve_many(a, b_dup, tol=TOL, maxiter=800, method="block")
    print(f"\nduplicate-column stack: Gram rank collapses at step one; "
          f"fallback={bool(dup.fallback)}, all lanes converged: "
          f"{bool(np.asarray(dup.converged).all())}")

    print(f"\namortization: {t_seq / t_bat:.2f}x (batched) / "
          f"{t_seq / t_blk:.2f}x (block) over the sequential loop - "
          f"one matrix sweep per iteration serves all {K} columns.")


if __name__ == "__main__":
    main()
