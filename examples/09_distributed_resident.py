"""The distributed VMEM-resident engine + streamed Chebyshev (round 5).

Two round-5 capabilities on top of examples 07/08:

1. **Streamed Chebyshev**: the streaming engine accepts a
   ``ChebyshevPreconditioner`` - degree 1 folds into the existing
   passes (zero extra HBM traffic), degree k >= 2 runs fused
   slab-streamed cheb steps with the PCG reduction fused into the last
   one.  Measured at 256^3 on v5e: 0.396 s to rtol 1e-6 vs 1.149 s for
   the general cheb-CG (BASELINE.md round-5 notes).

2. **Distributed resident**: the single-kernel CG engine's multi-chip
   form.  Every chip pins its slab in VMEM and runs the WHOLE solve in
   one kernel launch; per-iteration halo exchange and both scalar
   allreduces ride remote DMA (``pltpu.make_async_remote_copy``) from
   inside the kernel - zero per-iteration launches, zero XLA
   collectives, traffic on ICI.  This is the TPU-native answer to the
   MPI tier the reference's repo name promises and never implements
   (no ``MPI_*`` anywhere in ``CUDACG.cu``).

Off-TPU this runs the TPU-interpret simulator (remote DMAs and
semaphores modeled, including an optional happens-before race
detector) on virtual CPU devices - slow, so grids are tiny; semantics
are identical.

Run: python examples/09_distributed_resident.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

if jax.default_backend() != "tpu" and jax.device_count() < 4:
    # provision virtual CPU devices before first backend use
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=4")
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

from cuda_mpi_parallel_tpu import cg_resident, cg_streaming, solve
from cuda_mpi_parallel_tpu.models import poisson
from cuda_mpi_parallel_tpu.models.precond import ChebyshevPreconditioner
from cuda_mpi_parallel_tpu.parallel import make_mesh
from cuda_mpi_parallel_tpu.parallel.resident import (
    solve_distributed_resident,
)

on_tpu = jax.default_backend() == "tpu"
interp = not on_tpu

# -- 1: streamed Chebyshev ----------------------------------------------------
nx, ny = (1024, 1024) if on_tpu else (16, 128)
op = poisson.poisson_2d_operator(nx, ny, dtype=jnp.float32)
rng = np.random.default_rng(0)
b = jnp.asarray(rng.standard_normal(nx * ny).astype(np.float32))

m = ChebyshevPreconditioner.from_operator(op, degree=4)
plain = cg_streaming(op, b, tol=0.0, rtol=1e-4, maxiter=8000,
                     interpret=interp)
cheb = cg_streaming(op, b, tol=0.0, rtol=1e-4, maxiter=8000, m=m,
                    interpret=interp)
ref = solve(op, b, tol=0.0, rtol=1e-4, maxiter=8000, m=m)
print(f"streaming plain : {int(plain.iterations)} iters")
print(f"streaming cheb4 : {int(cheb.iterations)} iters "
      f"(general cheb-CG: {int(ref.iterations)} - counts must match)")
assert int(cheb.iterations) == int(ref.iterations)

# -- 2: distributed resident --------------------------------------------------
n_dev = min(4, jax.device_count())
gx, gy = (1024, 1024) if on_tpu else (8 * n_dev, 128)
op2 = poisson.poisson_2d_operator(gx, gy, dtype=jnp.float32)
b2 = rng.standard_normal(gx * gy).astype(np.float32)

dist = solve_distributed_resident(op2, b2, mesh=make_mesh(n_dev),
                                  tol=1e-3, maxiter=4000, check_every=32)
single = cg_resident(op2, b2, tol=1e-3, maxiter=4000, check_every=32,
                     interpret=interp)
print(f"distributed resident ({n_dev} devices): "
      f"{int(dist.iterations)} iters, converged={bool(dist.converged)}")
print(f"single-device resident kernel        : "
      f"{int(single.iterations)} iters (parity check)")
assert int(dist.iterations) == int(single.iterations)
print("ok: one kernel per chip, RDMA halos + allreduces inside")
