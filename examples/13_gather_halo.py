"""Sparse gather halo exchange: ship only the coupled x entries.

The legacy distributed CSR matvec all-gathers the FULL padded x every
iteration - a fixed (P-1) * n_local payload per device however weakly
the shards couple.  ``exchange="gather"`` (parallel.exchange) compiles
a halo schedule at partition time that ships exactly the coupled
entries as packed per-neighbor ``ppermute`` rounds, padded per round
to the max over shards (the padding fraction is reported, never
hidden).  This example measures the wire before/after on the repo's
committed skewed fixture, shows the auto fallback declining on dense
coupling, and proves the solutions are BIT-identical - the gather
matvec sums the same entries in the same order, it just moves fewer
bytes.

On a multi-chip host this spans real devices; on CPU set
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu
(or just run tests/, whose conftest does it for you).
Run: python examples/13_gather_halo.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import numpy as np

from cuda_mpi_parallel_tpu import telemetry
from cuda_mpi_parallel_tpu.balance import plan_partition
from cuda_mpi_parallel_tpu.models import mmio, random_spd
from cuda_mpi_parallel_tpu.parallel import (
    build_gather_schedule,
    make_mesh,
    partition_csr,
    solve_distributed,
)
from cuda_mpi_parallel_tpu.parallel import dist_cg
from cuda_mpi_parallel_tpu.parallel.exchange import (
    allgather_wire_bytes,
    choose_exchange,
)

FIXTURE = os.path.join(os.path.dirname(__file__), "..", "tests",
                       "fixtures", "skewed_spd_240.mtx")

ndev = min(4, len(jax.devices()))
if ndev < 2:
    raise SystemExit(
        "a halo exchange needs a mesh: run with\n  JAX_PLATFORMS=cpu "
        "XLA_FLAGS=--xla_force_host_platform_device_count=8 "
        "python examples/13_gather_halo.py")
a = mmio.load_matrix_market(FIXTURE)
rng = np.random.default_rng(0)
b = rng.standard_normal(a.shape[0])
mesh = make_mesh(ndev)
itemsize = np.asarray(a.data).dtype.itemsize

print(f"system: n={a.shape[0]}, nnz={a.nnz}, mesh={ndev}")

# --- the schedule, inspected before any solve ----------------------------
parts = partition_csr(a, ndev, exchange="gather")
sched = parts.halo
dense_wire = allgather_wire_bytes(ndev, parts.n_local, itemsize)
print(f"\n== gather halo schedule (even split) ==")
for r in sched.rounds:
    print(f"  round shift={r.shift}: {r.m} entries/device (live per "
          f"sender: {[int(c) for c in r.counts]})")
print(f"  coupled entries {sched.coupled_entries}, padding "
      f"{sched.padding_fraction() * 100:.1f}%")
print(f"  wire: {sched.wire_bytes_per_matvec(itemsize)} B/device/matvec"
      f" vs {dense_wire} B allgather "
      f"({sched.wire_bytes_per_matvec(itemsize) / dense_wire * 100:.0f}"
      f"% of the dense payload)")

# --- measured: the jaxpr-derived wire bytes of both lanes ----------------
wire = {}
results = {}
telemetry.force_active(True)
try:
    for mode in ("allgather", "gather"):
        dist_cg.reset_last_comm_cost()
        results[mode] = solve_distributed(a, b, mesh=mesh, tol=1e-10,
                                          maxiter=2000, exchange=mode)
        cost, ctx = dist_cg.last_comm_cost()
        wire[mode] = cost.per_iteration.wire_bytes
        pad = ctx.get("halo_padding_fraction")
        print(f"{mode:10s}: {wire[mode]:5d} wire B/iter"
              + (f" (halo padding {pad * 100:.1f}%)" if pad else ""))
finally:
    telemetry.force_active(False)

x_ag, x_g = np.asarray(results["allgather"].x), np.asarray(results["gather"].x)
assert np.array_equal(x_ag, x_g), "gather must be bit-identical"
print(f"solutions bit-identical at "
      f"{int(results['gather'].iterations)} iters; wire "
      f"{wire['allgather']} -> {wire['gather']} B/iter "
      f"({100 * (1 - wire['gather'] / wire['allgather']):.1f}% less)")

# --- the planner searches the lane (and RCM shrinks the coupling) --------
plan = plan_partition(a, ndev)
print(f"\nplanned lane: {plan.label} (exchange={plan.exchange}, "
      f"fingerprint {plan.fingerprint()})")

# --- auto declines on dense coupling so stencil-like systems never lose --
dense = random_spd.random_spd_sparse(64, density=0.6, seed=1)
dparts = partition_csr(dense, ndev)
dsched, _ = build_gather_schedule(dparts.data, dparts.cols,
                                  dparts.n_local, ndev)
ditem = np.asarray(dense.data).dtype.itemsize
print(f"\ndense probe: gather wire "
      f"{dsched.wire_bytes_per_matvec(ditem)} B vs allgather "
      f"{allgather_wire_bytes(ndev, dparts.n_local, ditem)} B -> "
      f"auto picks '{choose_exchange(dsched, ditem)}'")
