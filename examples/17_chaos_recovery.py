"""Chaos drill: inject a halo NaN, watch the solve self-heal.

Three acts on the committed skewed SPD fixture (240 rows, mesh 4):

1. **Inject**: a ``robust.FaultPlan`` arms the compiled distributed
   solve to corrupt the halo payload shard 2 receives at iteration 10
   (in-trace ``lax.cond`` - the production executable plus one armed
   select).  The while-loop health predicate catches the poisoned
   recurrence within one check block and exits with a typed
   ``CGStatus.BREAKDOWN`` - never a silent wrong answer.
2. **Recover**: ``robust.solve_with_recovery`` detects the breakdown,
   emits ``solve_fault``/``solve_recovery`` events, disarms the
   transient fault, and restarts from the last finite iterate; the
   recovered solution matches the fault-free solve.
3. **Serve**: a poisoned handle (sticky fault baked into every
   dispatch) drives the service's per-handle circuit breaker: two
   consecutive failed dispatches open it, submits refuse with typed
   REFUSED results, and the post-cooldown half-open probe re-opens it
   when the handle is still bad.

Run:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu
      python examples/17_chaos_recovery.py
"""
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8").strip()
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

from cuda_mpi_parallel_tpu.models import mmio
from cuda_mpi_parallel_tpu.parallel import make_mesh, solve_distributed
from cuda_mpi_parallel_tpu.robust import FaultPlan, solve_with_recovery
from cuda_mpi_parallel_tpu.solver.status import CGStatus

FIXTURE = os.path.join(os.path.dirname(__file__), "..", "tests",
                       "fixtures", "skewed_spd_240.mtx")


def main():
    a = mmio.load_matrix_market(FIXTURE)
    b = np.random.default_rng(0).standard_normal(a.shape[0])
    mesh = make_mesh(4)

    clean = solve_distributed(a, b, mesh=mesh, tol=1e-8, maxiter=500)
    print(f"fault-free : {CGStatus(int(clean.status)).name} in "
          f"{int(clean.iterations)} iterations")

    # -- act 1: typed detection ---------------------------------------
    fault = FaultPlan(site="halo", iteration=10, shard=2)
    print(f"\ninjecting  : {fault.describe()}")
    broken = solve_distributed(a, b, mesh=mesh, tol=1e-8, maxiter=500,
                               inject=fault)
    print(f"detected   : {CGStatus(int(broken.status)).name} at "
          f"iteration {int(broken.iterations)} "
          f"(latency {int(broken.iterations) - fault.iteration} "
          f"iteration past the fault)")

    # -- act 2: self-healing ------------------------------------------
    rr = solve_with_recovery(a, b, mesh=mesh, tol=1e-8, maxiter=500,
                             inject=fault)
    err = float(np.max(np.abs(np.asarray(rr.result.x)
                              - np.asarray(clean.x))))
    print(f"recovered  : {rr.restarts} restart(s) -> "
          f"{CGStatus(int(rr.result.status)).name}, max |dx| vs "
          f"fault-free = {err:.2e}")

    # -- act 3: the serve circuit breaker -----------------------------
    from cuda_mpi_parallel_tpu.serve import ServiceConfig, SolverService

    t = [0.0]
    svc = SolverService(ServiceConfig(
        clock=lambda: t[0], max_batch=1, max_wait_s=0.0,
        breaker_threshold=2, breaker_cooldown_s=5.0))
    try:
        poisoned = svc.register(
            a, inject=FaultPlan(site="reduction", iteration=1,
                                sticky=True))
        print("\nserve      : poisoned handle registered "
              "(sticky reduction fault)")
        for i in range(2):
            fut = svc.submit(poisoned, b)
            svc.pump()
            print(f"  dispatch {i + 1}: "
                  f"{fut.result(timeout=30).status}")
        print(f"  breaker  : {svc.breaker_state(poisoned)}")
        refused = svc.submit(poisoned, b).result(timeout=30)
        print(f"  submit   : {refused.status} "
              f"(failure_kind={refused.failure_kind})")
        t[0] = 6.0   # cooldown elapsed: one half-open probe admitted
        probe = svc.submit(poisoned, b)
        print(f"  cooldown : breaker {svc.breaker_state(poisoned)}, "
              f"probe admitted")
        svc.pump()
        print(f"  probe    : {probe.result(timeout=30).status} -> "
              f"breaker {svc.breaker_state(poisoned)}")
    finally:
        svc.close()


if __name__ == "__main__":
    main()
