"""Elastic solves: kill a mesh-4 checkpointed solve, resume on mesh 2.

Three acts on the committed skewed SPD fixture (240 rows):

1. **Checkpoint + preempt**: ``solve_resumable_distributed`` runs the
   mesh-4 solve in 15-iteration segments, persisting the full
   per-shard recurrence state (with LAYOUT metadata - mesh shape,
   partition plan, exchange lane) after each; a ``robust.Preemption``
   kills the worker after segment 1, the deterministic stand-in for a
   host reclaim.
2. **Migrate + resume**: the replacement "pod" is mesh 2.  With
   ``elastic=True`` the resume lifts the checkpoint's padded
   plan-permuted vectors back to global row order, re-plans for 2
   shards, re-pads through the same ``partition.pad_vector_ranges``
   pipeline, and continues - the asserted contract is RESIDUAL
   CONTINUITY across the seam (the first post-migration ``||r||`` is
   the checkpointed one; bitwise is impossible, psum order changed).
3. **Verify**: the migrated run converges to the same answer as an
   uninterrupted run (max|dx| ~ 1e-16 measured on CPU), and the
   ``solve_migration`` event carries the measured seam error.

Run:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu
      python examples/20_elastic.py
"""
import io
import json
import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8").strip()
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

from cuda_mpi_parallel_tpu.models import mmio
from cuda_mpi_parallel_tpu.parallel import make_mesh, solve_distributed
from cuda_mpi_parallel_tpu.robust import PreemptedError, Preemption
from cuda_mpi_parallel_tpu.telemetry import events
from cuda_mpi_parallel_tpu.utils.checkpoint import (
    CheckpointMismatch,
    solve_resumable_distributed,
)

FIXTURE = os.path.join(os.path.dirname(__file__), "..", "tests",
                       "fixtures", "skewed_spd_240.mtx")


def main() -> int:
    a = mmio.load_matrix_market(FIXTURE)
    b = np.random.default_rng(0).standard_normal(240)
    ck = os.path.join(tempfile.mkdtemp(prefix="elastic-"), "solve.npz")

    print("== act 1: mesh-4 checkpointed solve, killed after "
          "segment 1 ==")
    clean = solve_distributed(a, b, mesh=make_mesh(4), tol=1e-8,
                              maxiter=500)
    print(f"uninterrupted mesh-4 run: {int(clean.iterations)} iters, "
          f"||r|| = {float(clean.residual_norm):.3e}")
    try:
        solve_resumable_distributed(
            a, b, ck, mesh=make_mesh(4), segment_iters=15, tol=1e-8,
            maxiter=500, preempt=Preemption(after_segments=1))
    except PreemptedError as e:
        print(f"preempted: {e}")

    print()
    print("== act 2: the replacement topology is mesh 2 ==")
    try:
        solve_resumable_distributed(
            a, b, ck, mesh=make_mesh(2), segment_iters=15, tol=1e-8,
            maxiter=500)
    except CheckpointMismatch as e:
        print(f"without elastic=True: typed refusal "
              f"(migratable={e.migratable})")

    buf = io.StringIO()
    events.configure(buf)
    res = solve_resumable_distributed(
        a, b, ck, mesh=make_mesh(2), segment_iters=15, tol=1e-8,
        maxiter=500, elastic=True)
    events.configure(None)
    migs = [json.loads(ln) for ln in buf.getvalue().splitlines()
            if ln.strip()
            and json.loads(ln)["event"] == "solve_migration"]
    m = migs[0]
    print(f"elastic=True: migrated mesh {m['n_shards_from']} -> "
          f"{m['n_shards_to']} at k={m['k']}")
    print(f"seam: checkpointed ||r|| = {m['checkpoint_r_norm']:.6e}, "
          f"lifted ||r|| = {m['r_norm']:.6e} "
          f"(rel err {m['seam_rel_err']:.2e})")

    print()
    print("== act 3: the migrated run is the same solve ==")
    dx = float(np.max(np.abs(np.asarray(res.x) - np.asarray(clean.x))))
    print(f"resumed on mesh 2: {int(res.iterations)} iters "
          f"(uninterrupted ran {int(clean.iterations)}), "
          f"converged={bool(res.converged)}")
    print(f"max|dx| vs the uninterrupted mesh-4 run: {dx:.3e}")
    # f32 here (no x64 flag): the psum'd rr and the host-recomputed
    # norm agree to f32 rounding; the asserted contract is the
    # module's DEFAULT_SEAM_RTOL
    ok = bool(res.converged) and dx < 1e-5 \
        and m["seam_rel_err"] < 1e-5
    print("OK" if ok else "FAILED")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
