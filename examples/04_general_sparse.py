"""General sparse matrices on TPU: RCM reordering + fast formats.

TPU vector memory has no efficient random access, so the gather-based
CSR path is slow.  Two fast layouts replace it after RCM reordering:

* DIA - gather-free shifted FMAs, for matrices whose RCM band is a
  handful of diagonals;
* shift-ELL - the pallas lane-gather kernel (`ops/pallas/spmv.py`),
  for ANY sparsity: ~100 us/CG-iteration at 1M rows (~800x over csr).

Run: python examples/04_general_sparse.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax.numpy as jnp
import numpy as np
import scipy.sparse as sp

from cuda_mpi_parallel_tpu import solve
from cuda_mpi_parallel_tpu.models.operators import CSRMatrix

# a banded SPD system, scrambled (as if numbered badly by a mesh tool)
n = 5000
m = sp.diags([np.ones(n - 1), 4 * np.ones(n), np.ones(n - 1)],
             [-1, 0, 1], format="csr")
rng = np.random.default_rng(0)
scramble = rng.permutation(n).astype(np.int32)
a = CSRMatrix.from_scipy(m.tocsr()).permuted(scramble)
print(f"scrambled bandwidth: {a.bandwidth()}")

perm = a.rcm_permutation()          # native C++ RCM
banded = a.permuted(perm)
print(f"after RCM:           {banded.bandwidth()}")

dia = banded.to_dia()               # gather-free layout
print(f"DIA diagonals:       {dia.n_diags}")

b = rng.standard_normal(n)          # rhs of the (scrambled) system A x = b
res = solve(dia, jnp.asarray(b[perm]), tol=0.0, rtol=1e-8, maxiter=5000)
x = np.empty(n)
x[perm] = np.asarray(res.x)         # scatter back to the original ordering
print(f"DIA solve:      iters={int(res.iterations)} "
      f"converged={bool(res.converged)}")

sell = banded.to_shiftell()         # pallas lane-gather kernel, auto h
print(f"shift-ELL:      {sell.n_sheets} sheets, h={sell.h}")
res2 = solve(sell, jnp.asarray(b[perm]), tol=0.0, rtol=1e-8, maxiter=5000)
x2 = np.empty(n)
x2[perm] = np.asarray(res2.x)
print(f"shift-ELL solve: iters={int(res2.iterations)} "
      f"converged={bool(res2.converged)}")
print(f"residual check: {np.linalg.norm(b - np.asarray(a.to_dense()) @ x):.2e}")
