"""General sparse matrices on TPU: RCM reordering + the DIA format.

TPU vector memory has no efficient random access, so the gather-based
CSR path is slow; the RCM -> DIA pipeline turns a banded-able matrix
into gather-free shifted FMAs (~340x faster at 1M rows).
Run: python examples/04_general_sparse.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax.numpy as jnp
import numpy as np
import scipy.sparse as sp

from cuda_mpi_parallel_tpu import solve
from cuda_mpi_parallel_tpu.models.operators import CSRMatrix

# a banded SPD system, scrambled (as if numbered badly by a mesh tool)
n = 5000
m = sp.diags([np.ones(n - 1), 4 * np.ones(n), np.ones(n - 1)],
             [-1, 0, 1], format="csr")
rng = np.random.default_rng(0)
scramble = rng.permutation(n).astype(np.int32)
a = CSRMatrix.from_scipy(m.tocsr()).permuted(scramble)
print(f"scrambled bandwidth: {a.bandwidth()}")

perm = a.rcm_permutation()          # native C++ RCM
banded = a.permuted(perm)
print(f"after RCM:           {banded.bandwidth()}")

dia = banded.to_dia()               # gather-free layout
print(f"DIA diagonals:       {dia.n_diags}")

b = rng.standard_normal(n)          # rhs of the (scrambled) system A x = b
res = solve(dia, jnp.asarray(b[perm]), tol=0.0, rtol=1e-8, maxiter=5000)
x = np.empty(n)
x[perm] = np.asarray(res.x)         # scatter back to the original ordering
print(f"solve: iters={int(res.iterations)} converged={bool(res.converged)}")
print(f"residual check: {np.linalg.norm(b - np.asarray(a.to_dense()) @ x):.2e}")
