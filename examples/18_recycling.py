"""Krylov recycling: repeat traffic gets faster every solve.

A serving workload solves the SAME operator again and again with
fresh right-hand sides.  Every CG solve is a Lanczos process in
disguise - it *pays* for spectral information and then throws it
away.  ``solver.recycle`` keeps it: the solve carries a small basis
ring of normalized residuals, the flight recorder carries the
CG-Lanczos tridiagonal, and ``harvest_space`` combines them into a
``RecycleSpace`` (approximate extreme Ritz vectors W, A W, and the
Cholesky factor of W^T A W) that later solves DEFLATE - the recycled
part of the spectrum simply stops costing iterations.  Harvests
accumulate across solves, so the space converges toward the true
extreme invariant subspace and iters/solve keeps falling.

This example replays a 6-solve fresh-RHS workload against the
committed skewed fixture and a 2-D Poisson operator, printing the
measured iterations-per-solve trajectory (solve 1 = the harvest
source, solves 2+ deflated), the harvest overhead, and the final
Ritz values against the operator's true extreme eigenvalues.

Run: python examples/18_recycling.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

from cuda_mpi_parallel_tpu.models import mmio, poisson
from cuda_mpi_parallel_tpu.solver.recycle import recycled_sequence

REPEATS = 6
TOL = 1e-8


def replay(name, a, k):
    n = int(a.shape[0])
    rng = np.random.default_rng(7)
    rhs = [rng.standard_normal(n) for _ in range(REPEATS)]
    seq = recycled_sequence(a, rhs[0], repeats=REPEATS, k=k,
                            maxiter=2000, tol=TOL,
                            rhs_for=lambda i: rhs[i])
    print(f"== {name} (n={n}, k={k}, tol={TOL:g}) ==")
    for line in seq.describe_lines():
        print(f"  {line}")
    summary = seq.summary()
    print(f"  harvest overhead: {summary['harvest_overhead_pct']:.1f}% "
          f"of solve wall (host Ritz extraction - amortizes over the "
          f"workload and freezes once the space settles)")
    info = seq.entries[-1].info
    if info is not None:
        print(f"  final space: k={info.k}, ritz "
              f"[{info.ritz[0]:.4g} .. {info.ritz[-1]:.4g}], "
              f"worst pair quality {max(info.quality):.2e}")
    if n <= 1024:
        lam = np.sort(np.linalg.eigvalsh(np.asarray(a.to_dense(),
                                                    dtype=np.float64)))
        print(f"  true smallest eigenvalues: "
              f"{np.round(lam[:4], 4).tolist()}")
    print()
    return summary


def main():
    import jax

    jax.config.update("jax_enable_x64", True)

    a_skew = mmio.load_matrix_market(
        os.path.join(os.path.dirname(__file__), "..",
                     "tests/fixtures/skewed_spd_240.mtx"))
    s1 = replay("skewed_spd_240 fixture", a_skew, k=12)
    a_poi = poisson.poisson_2d_csr(24, 24, dtype=np.float64)
    s2 = replay("Poisson 24x24", a_poi, k=8)

    for name, s in (("skewed", s1), ("poisson", s2)):
        assert s["final_solve_iterations"] < s["first_solve_iterations"], name
    print("recycling verdict: iters/solve fell on both operators - "
          "the longer the workload, the cheaper each solve")


if __name__ == "__main__":
    main()
