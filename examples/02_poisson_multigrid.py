"""Large 2D Poisson with the preconditioner ladder.

Compares unpreconditioned / Chebyshev / multigrid CG on a 1M-unknown
system - multigrid's iteration count is flat in grid size.
Run: python examples/02_poisson_multigrid.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax.numpy as jnp
import numpy as np

from cuda_mpi_parallel_tpu import solve
from cuda_mpi_parallel_tpu.models import poisson
from cuda_mpi_parallel_tpu.models.multigrid import MultigridPreconditioner
from cuda_mpi_parallel_tpu.models.precond import ChebyshevPreconditioner

n = 1024
op = poisson.poisson_2d_operator(n, n, dtype=jnp.float32)
rng = np.random.default_rng(0)
x_true = rng.standard_normal(n * n).astype(np.float32)
b = op @ jnp.asarray(x_true)

for name, m in [
    ("plain", None),
    ("chebyshev(4)", ChebyshevPreconditioner.from_operator(op, degree=4)),
    ("multigrid", MultigridPreconditioner.from_operator(op)),
]:
    res = solve(op, b, tol=0.0, rtol=1e-5, maxiter=5000, m=m)
    err = float(jnp.max(jnp.abs(res.x - jnp.asarray(x_true))))
    print(f"{name:14s} iters={int(res.iterations):5d} "
          f"converged={bool(res.converged)} max_err={err:.2e}")
