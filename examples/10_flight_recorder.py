"""The convergence flight recorder + solve-health diagnostics (PR 3).

The reference prints "Success" whether CG converged or silently ran out
of iterations (``CUDACG.cu:365``, SURVEY Q4/Q7).  The flight recorder
is the fix: a fixed-size, stride-decimated ring buffer of
``(iteration, ||r||^2, alpha, beta)`` rows carried *inside* the
``lax.while_loop`` of every engine and fetched ONCE post-solve - so the
hot loop keeps its zero-host-round-trip property, and the recorder-off
jaxpr is bit-identical to a build without it.

On top of the record, ``telemetry.health`` reconstructs the CG-Lanczos
tridiagonal from the recorded alpha/beta (CG *is* Lanczos in disguise),
estimates the extreme Ritz values / condition number, and classifies
the trace: still-converging MAXITER vs STAGNATED (decay flatlined above
tolerance - the f32 attainable-accuracy floor) vs DIVERGED.

This example diagnoses two solves the reference would both call
"Success":

1. a healthy 2D Poisson solve - CONVERGED, kappa estimate matching the
   operator;
2. a near-singular system (eigenvalues spanning 1e8, solved in f32 with
   a tolerance below its attainable accuracy) - the solver reports
   MAXITER; the health verdict upgrades that to STAGNATED with the
   plateau iteration and the kappa that explains it.

Same CLI surface: ``--flight-record [STRIDE]`` (+ ``--history`` now
works with ``--mesh N`` and the resident/streaming engines through the
recorder), e.g.::

    python -m cuda_mpi_parallel_tpu.cli --problem poisson2d --n 64 \
        --matrix-free --mesh 4 --flight-record 2 --history
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

import jax.numpy as jnp

from cuda_mpi_parallel_tpu.models.operators import Stencil2D
from cuda_mpi_parallel_tpu.solver.cg import solve
from cuda_mpi_parallel_tpu.telemetry.flight import (
    FlightConfig,
    FlightRecord,
)
from cuda_mpi_parallel_tpu.telemetry.health import assess_solve_health


def diagnose(title, a, b, *, tol, maxiter):
    cfg = FlightConfig.for_solve(maxiter, stride=1)
    res = solve(a, b, tol=tol, maxiter=maxiter, flight=cfg)

    # the ONE post-solve fetch of the carried ring buffer
    rec = FlightRecord.from_buffer(res.flight, stride=1)
    health = assess_solve_health(
        rec, converged=bool(res.converged), status=int(res.status),
        iterations=int(res.iterations))

    print(f"--- {title} ---")
    print(f"solver status : {res.status_enum().name} "
          f"({res.status_enum().describe()})")
    print(f"iterations    : {int(res.iterations)}  "
          f"||r|| = {float(res.residual_norm):.3e}")
    print(f"health verdict: {health.classification.name}")
    print(f"  {health.message}")
    if health.kappa_estimate is not None:
        print(f"  Ritz interval [{health.ritz_min:.3e}, "
              f"{health.ritz_max:.3e}]  kappa >= "
              f"{health.kappa_estimate:.3e}")
    if health.decay_rate is not None:
        # tail_decay_rate can be None even when decay_rate is not
        # (too few finite residuals in the tail window)
        tail = ("n/a" if health.tail_decay_rate is None
                else f"{health.tail_decay_rate:+.2e}")
        print(f"  residual decay {health.decay_rate:+.2e} "
              f"decades/iteration (tail {tail})")
    if health.plateau_iteration is not None:
        print(f"  plateau at iteration {health.plateau_iteration}")
    print()
    return health


def main():
    # 1) healthy: 48x48 Poisson, f32, a reachable tolerance
    n = 48
    a = Stencil2D.create(n, n, dtype=jnp.float32)
    rng = np.random.default_rng(0)
    b = jnp.asarray(rng.standard_normal(n * n).astype(np.float32))
    healthy = diagnose("healthy Poisson solve", a, b,
                       tol=1e-5, maxiter=2000)
    assert healthy.classification.name == "CONVERGED"

    # 2) stagnating: kappa = 1e8 diagonal system in f32 with a
    # tolerance below the f32 attainable-accuracy floor.  CG is not
    # broken - the floor is a property of the precision; the verdict
    # says so instead of a bare MAXITER.
    eigs = np.logspace(0, -8, 64)
    a_bad = jnp.asarray(np.diag(eigs).astype(np.float32))
    b_bad = jnp.ones(64, jnp.float32)
    stagnated = diagnose("near-singular f32 solve (kappa = 1e8)",
                         a_bad, b_bad, tol=1e-12, maxiter=500)
    assert stagnated.classification.name != "CONVERGED"

    print("the reference would have printed 'Success' for both.")


if __name__ == "__main__":
    main()
