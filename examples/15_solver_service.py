"""The solver service: bursty traffic in, block solves out.

Registers ONE fixture operator with the microbatching solver service
(``cuda_mpi_parallel_tpu.serve``), replays a bursty workload of
single-RHS requests against it, and prints:

1. the occupancy / latency report - how the queue coalesced arrivals
   into padded lane buckets;
2. the zero-retrace proof - the per-bucket warmup at registration is
   the ONLY time the solve is traced/compiled; every later dispatch
   is a cache hit (counted via the jit-signature caches);
3. the throughput win vs a max_batch=1 service on the SAME workload
   (what dispatch-per-request serving would do).

Run: python examples/15_solver_service.py
"""
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

from cuda_mpi_parallel_tpu.models import poisson
from cuda_mpi_parallel_tpu.serve import (
    ServiceConfig,
    SolverService,
    rhs_for,
    synthetic_poisson,
)
from cuda_mpi_parallel_tpu.telemetry.report import service_lines

GRID = 64            # 4096 unknowns - quick on CPU, real enough to time
REQUESTS = 48
RATE_HZ = 1500.0     # bursty open-loop Poisson arrivals
TOL = 1e-8


def replay(a, workload, prepared, max_batch):
    svc = SolverService(ServiceConfig(
        max_batch=max_batch, max_wait_s=0.003, maxiter=800))
    try:
        handle = svc.register(a)     # plan + per-bucket warmup, ONCE
        t0 = time.perf_counter()
        futures = []
        for req, b in prepared:
            delay = (t0 + req.t) - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            futures.append(svc.submit(handle, b, tol=TOL))
        svc.drain()
        window = time.perf_counter() - t0
        results = [f.result() for f in futures]
        stats = svc.stats()
    finally:
        svc.close()
    solved = sum(1 for r in results if r.converged)
    stats["solved_rhs_per_sec"] = solved / window
    stats["replay_window_s"] = window
    return results, stats


def trace_count():
    """Total traced calls of the single-device batched solver - the
    retrace probe (jit re-traces exactly when a new (shape, static)
    signature appears)."""
    from cuda_mpi_parallel_tpu.solver.many import _solve_many_jit

    info = _solve_many_jit._cache_size()
    return info


def main():
    a = poisson.poisson_2d_csr(GRID, GRID, dtype=np.float64)
    workload = synthetic_poisson(REQUESTS, RATE_HZ, seed=15)
    prepared = [(r, rhs_for(a, r.seed)[0]) for r in workload]
    print(f"Poisson-2D {GRID}x{GRID} (n={a.shape[0]}), "
          f"{REQUESTS} requests @ ~{RATE_HZ:.0f}/s, tol={TOL:g}\n")

    print("-- microbatched service (max_batch=8) --")
    results, stats = replay(a, workload, prepared, max_batch=8)
    compiled_after_replay = trace_count()
    for line in service_lines(stats):
        print(line)
    worst = max(
        float(np.max(np.abs(r.x - rhs_for(a, req.seed)[1])))
        for (req, _), r in zip(prepared, results))
    print(f"accuracy: max request error {worst:.3e}")

    # zero-retrace proof: replay the same workload again - the
    # compiled-signature count must not move (every bucket was warmed
    # at registration; repeat traffic only ever hits caches)
    _, stats2 = replay(a, workload, prepared, max_batch=8)
    print(f"zero-retrace: compiled signatures {compiled_after_replay} "
          f"after replay 1 -> {trace_count()} after replay 2 "
          f"(second replay compiled nothing new)")

    print("\n-- the same workload, max_batch=1 (no batching) --")
    _, stats1 = replay(a, workload, prepared, max_batch=1)
    for line in service_lines(stats1):
        print(line)

    speedup = stats["solved_rhs_per_sec"] / stats1["solved_rhs_per_sec"]
    print(f"\nbatched dispatch: {stats['solved_rhs_per_sec']:.1f} vs "
          f"{stats1['solved_rhs_per_sec']:.1f} solved RHS/s unbatched "
          f"-> {speedup:.1f}x")


if __name__ == "__main__":
    main()
