"""The VMEM-resident engine: the entire CG solve as ONE pallas kernel.

The reference's loop pays 8 kernel launches + 2 blocking host syncs +
1 cudaMalloc per iteration (CUDACG.cu:269-352).  The general solver here
already runs the whole solve as one jitted lax.while_loop; the resident
engine goes further - for grids whose CG working set fits VMEM, the
solve is a single pallas kernel with b/x/r/p pinned on-chip, the 5-point
stencil applied as in-register shifts, and both inner products reduced
to SMEM.  Measured on TPU v5e at 1024x1024 f32: 6.65 us/iteration, 2.9x
the general solver.  Chebyshev polynomial preconditioning and the df64
(f64-class) precision tier run in-kernel too.

On TPU the kernel runs compiled; elsewhere this example uses pallas
interpret mode (slow, small grid) - semantics are identical.

Run: python examples/07_resident_engine.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np

from cuda_mpi_parallel_tpu import (
    cg_resident,
    cg_resident_df64,
    solve,
    supports_resident,
)
from cuda_mpi_parallel_tpu.models import poisson
from cuda_mpi_parallel_tpu.models.precond import ChebyshevPreconditioner

on_tpu = jax.default_backend() == "tpu"
interpret = not on_tpu
n = 512 if on_tpu else 16
ny = 512 if on_tpu else 128

op = poisson.poisson_2d_operator(n, ny, dtype=jnp.float32)
assert supports_resident(op)
rng = np.random.default_rng(0)
x_true = rng.standard_normal(n * ny).astype(np.float32)
b = op @ jnp.asarray(x_true)

# -- 1. plain resident CG vs the general solver -------------------------------
ref = solve(op, b, tol=0.0, rtol=1e-5, maxiter=2000, check_every=8)
res = cg_resident(op, b, tol=0.0, rtol=1e-5, maxiter=2000, check_every=8,
                  interpret=interpret)
print(f"general while_loop solver: {int(ref.iterations)} iters, "
      f"||r|| = {float(ref.residual_norm):.3e}")
print(f"resident one-kernel solve: {int(res.iterations)} iters, "
      f"||r|| = {float(res.residual_norm):.3e}")
assert int(res.iterations) == int(ref.iterations)

# -- 2. in-kernel Chebyshev preconditioning -----------------------------------
m = ChebyshevPreconditioner.from_operator(op, degree=4)
pcg = cg_resident(op, b, tol=0.0, rtol=1e-5, maxiter=2000, check_every=8,
                  m=m, interpret=interpret)
print(f"resident + Chebyshev(4):   {int(pcg.iterations)} iters "
      f"({int(res.iterations) / max(int(pcg.iterations), 1):.1f}x fewer), "
      f"||r|| = {float(pcg.residual_norm):.3e}")

# -- 3. df64: f64-class precision in the same one-kernel shape ----------------
b64 = np.asarray(b, np.float64)
deep = cg_resident_df64(op, b64, tol=0.0, rtol=1e-10, maxiter=3000,
                        check_every=8, interpret=interpret)
print(f"resident df64 (rtol 1e-10): {int(deep.iterations)} iters, "
      f"||r|| = {deep.residual_norm():.3e}  "
      f"(a depth plain f32 cannot reach)")
assert deep.residual_norm() < 1e-9 * np.linalg.norm(b64)

# -- 4. df64 + in-kernel Chebyshev: fewer iterations at the same depth --------
deep_pcg = cg_resident_df64(op, b64, tol=0.0, rtol=1e-10, maxiter=3000,
                            check_every=8, preconditioner="chebyshev",
                            precond_degree=4, interpret=interpret)
print(f"resident df64 + Chebyshev(4): {int(deep_pcg.iterations)} iters "
      f"({int(deep.iterations) / max(int(deep_pcg.iterations), 1):.1f}x "
      f"fewer), ||r|| = {deep_pcg.residual_norm():.3e}")

# -- 5. warm start: reuse a previous solution as x0 ---------------------------
# NOTE: use an ABSOLUTE tol when warm-starting - rtol is relative to the
# new ||r0|| = ||b - A x0||, which a good x0 makes tiny, so an rtol
# threshold silently becomes a much deeper target than the cold solve's.
target = float(res.residual_norm) * 2
warm = cg_resident(op, b, np.asarray(res.x).ravel(), tol=target,
                   maxiter=2000, check_every=8, interpret=interpret)
print(f"warm-started from the earlier solution: {int(warm.iterations)} "
      f"iters to the same absolute depth (vs {int(res.iterations)} cold)")
assert int(warm.iterations) <= 8
