"""Distributed 3D Poisson: slab and pencil partitions.

On a multi-chip host this spans real devices; on CPU set
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu
(or just run tests/, whose conftest does it for you).
Run: python examples/03_distributed.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np

from cuda_mpi_parallel_tpu.models.operators import Stencil3D
from cuda_mpi_parallel_tpu.parallel import (
    make_mesh,
    make_mesh_2d,
    solve_distributed,
)

ndev = len(jax.devices())
nx = 8 * ndev
op = Stencil3D.create(nx, 16, 16, dtype=jnp.float32)
rng = np.random.default_rng(0)
x_true = rng.standard_normal(op.shape[0]).astype(np.float32)
b = op @ jnp.asarray(x_true)

res = solve_distributed(op, b, mesh=make_mesh(ndev), tol=1e-3,
                        preconditioner="mg")
print(f"slab   mesh={ndev}: iters={int(res.iterations)} "
      f"converged={bool(res.converged)}")

if ndev >= 4 and ndev % 2 == 0:
    res = solve_distributed(op, b, mesh=make_mesh_2d((ndev // 2, 2)),
                            tol=1e-3)
    print(f"pencil mesh=({ndev // 2},2): iters={int(res.iterations)} "
          f"converged={bool(res.converged)}")

# round 3: the same meshes at f64-class precision (df64 pairs; the
# reference's CUDA_R_64F x the MPI its name promises)
from cuda_mpi_parallel_tpu.parallel import solve_distributed_df64

res = solve_distributed_df64(op, np.asarray(b, np.float64),
                             mesh=make_mesh(ndev), tol=0.0, rtol=1e-10)
print(f"slab   mesh={ndev} df64: iters={int(res.iterations)} "
      f"||r||={res.residual_norm():.2e}")
if ndev >= 4 and ndev % 2 == 0:
    res = solve_distributed_df64(op, np.asarray(b, np.float64),
                                 mesh=make_mesh_2d((ndev // 2, 2)),
                                 tol=0.0, rtol=1e-10, method="cg1")
    print(f"pencil mesh=({ndev // 2},2) df64 cg1: "
          f"iters={int(res.iterations)} ||r||={res.residual_norm():.2e}")
