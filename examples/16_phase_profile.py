"""Measured phase profiling: where a distributed iteration's time goes.

Every timing signal before ``telemetry.phasetrace`` was ONE wall time
per solve: the calibrator fit two bandwidths from whole-solve
observations (one solve could only reach the degraded ``fixed-net``
tier), and the Perfetto timeline rendered a static-schedule MODEL of
the iteration.  The phase profiler measures instead: it compiles
phase-isolated step functions from the partitioned operator's own
building blocks - the halo exchange alone (each gather round
individually), the local CSR SpMV alone (per shard), the dot+psum
reduction alone - and times each under the real mesh.

This example profiles a mesh-4 solve of the repo's committed skewed
fixture and shows:

* measured per-shard / per-phase walls and the measured (not modeled)
  SpMV stall factor, next to the static model's prediction;
* per-link wire bandwidths fitted from individually timed gather
  rounds (the payloads differ per round, so the links separate);
* the calibration-tier upgrade: one profiled solve reaches the
  ``lstsq2`` CONFIDENT tier that previously needed ``--repeat 2``.

On a multi-chip host this spans real devices; on CPU set
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu
(or just run tests/, whose conftest does it for you).
Run: python examples/16_phase_profile.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import numpy as np

from cuda_mpi_parallel_tpu.models import mmio
from cuda_mpi_parallel_tpu.parallel import make_mesh, solve_distributed
from cuda_mpi_parallel_tpu.telemetry import calibrate, phasetrace
from cuda_mpi_parallel_tpu.telemetry.report import phase_lines
from cuda_mpi_parallel_tpu.telemetry.shardscope import report_for_ranges
from cuda_mpi_parallel_tpu.balance.nnz_split import even_ranges
from cuda_mpi_parallel_tpu.utils.timing import time_fn


def main():
    if len(jax.devices()) < 4:
        print("needs >= 4 devices (set "
              "XLA_FLAGS=--xla_force_host_platform_device_count=8)")
        return
    fixture = os.path.join(os.path.dirname(__file__), "..", "tests",
                           "fixtures", "skewed_spd_240.mtx")
    a = mmio.load_matrix_market(fixture)
    b = np.random.default_rng(7).standard_normal(a.shape[0])
    mesh = make_mesh(4)

    # 1) a measured solve (warmup excluded), gather halo wire
    elapsed, res = time_fn(
        lambda: solve_distributed(a, b, mesh=mesh, tol=1e-8,
                                  maxiter=500, exchange="gather"),
        warmup=1, repeats=1)
    print(f"solve: {int(res.iterations)} iters in "
          f"{elapsed * 1e3:.1f} ms "
          f"({elapsed / int(res.iterations) * 1e6:.1f} us/iter)")

    # 2) the measured phase profile of the SAME partition
    prof = phasetrace.profile_distributed(
        a, mesh=mesh, exchange="gather",
        solve_iterations=int(res.iterations),
        solve_elapsed_s=float(elapsed))
    print()
    print("-- measured phase profile --")
    for line in phase_lines(prof.to_json()):
        print(line)

    # 3) measured vs modeled stall factor: the static shard accounting
    # predicts the straggler from nnz; the profiler MEASURED it.  The
    # padded slot layout equalizes per-shard multiply work, so the
    # measured factor is far milder than the nnz skew suggests.
    rep = report_for_ranges(a, even_ranges(a.shape[0], 4))
    print()
    print(f"stall factor: modeled (nnz max/mean) "
          f"{rep.imbalance()['nnz_max_over_mean']:.3f} vs measured "
          f"(spmv walls) {prof.stall_factors()['spmv']:.3f}")

    # 4) the calibration-tier upgrade from ONE profiled solve
    whole = calibrate.fit_machine_model([calibrate.observation_for(
        rep, int(res.iterations), float(elapsed), itemsize=8,
        exchange="gather")])
    phased = calibrate.fit_machine_model(
        calibrate.observations_from_profile(prof),
        per_link=prof.links)
    print()
    print(f"whole-solve fit (the old single-solve ceiling): "
          f"{whole.method}, "
          f"{'confident' if whole.confident else 'LOW CONFIDENCE'}")
    print(f"phase-resolved fit (one profiled solve):         "
          f"{phased.method}, "
          f"{'confident' if phased.confident else 'LOW CONFIDENCE'}")
    print(f"per-link wire: " + ", ".join(
        f"shift {s}: {bps / 1e6:.2f} MB/s"
        for s, bps in phased.model.per_link))


if __name__ == "__main__":
    main()
