"""f64-class CG on TPU hardware: the df64 (double-float) solver.

The reference solves in float64 (CUDA_R_64F); TPUs have no f64 units.
cg_df64 stores every vector and scalar as an (hi, lo) pair of f32 arrays
(~48-bit significands, error-free transformations throughout), reaching
tolerances plain f32 cannot - at ~4x the f32 cost, on real TPUs.

Run: python examples/06_df64_precision.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax.numpy as jnp
import numpy as np

from cuda_mpi_parallel_tpu import cg_df64, solve
from cuda_mpi_parallel_tpu.models import poisson

n = 256
op = poisson.poisson_2d_operator(n, n, dtype=jnp.float32)
rng = np.random.default_rng(0)
x_true = rng.standard_normal(n * n)

# build the rhs in full f64 on the host so the deep tolerance is meaningful
from cuda_mpi_parallel_tpu.ops import df64 as df

xdf = tuple(jnp.asarray(w) for w in df.split_f64(x_true))
bh, bl = df.stencil2d_matvec(xdf, (n, n), df.const(1.0))
b64 = df.to_f64(bh, bl)

# plain f32: the recursive residual converges, but the true residual
# floors near 1e-6 relative - f32 storage cannot do better
r32 = solve(op, jnp.asarray(b64, jnp.float32), tol=0.0, rtol=1e-12,
            maxiter=20000)
err32 = np.abs(np.asarray(r32.x, dtype=np.float64) - x_true).max()

# df64: same hardware, f64-class trajectory and solution
rdf = cg_df64(op, b64, tol=0.0, rtol=1e-12, maxiter=20000)
errdf = np.abs(rdf.x() - x_true).max()

print(f"f32  : iters={int(r32.iterations):5d} {r32.status_enum().name:9s} "
      f"max|x - x_true| = {err32:.2e}")
print(f"df64 : iters={int(rdf.iterations):5d} {rdf.status_enum().name:9s} "
      f"max|x - x_true| = {errdf:.2e}")

# round 3: the ASSEMBLED path at pallas speed - df64 shift-ELL (the
# reference's CUDA_R_64F CSR SpMV, CUDACG.cu:216,288).  Compiled on TPU;
# pallas interpret mode on CPU hosts, hence the smaller demo system.
m = 48
a_csr = poisson.poisson_2d_csr(m, m, dtype=np.float64)
xs_true = rng.standard_normal(m * m)
bs64 = np.asarray(a_csr.to_dense(), np.float64) @ xs_true
rsell = cg_df64(a_csr.to_shiftell_df64(), bs64, tol=0.0, rtol=1e-11,
                maxiter=5000)
errs = np.abs(rsell.x() - xs_true).max()
print(f"df64 shift-ELL ({m}x{m}): iters={int(rsell.iterations):4d} "
      f"max|x - x_true| = {errs:.2e}")

# single-reduction recurrence: every inner product in ONE collective
rcg1 = cg_df64(op, b64, tol=0.0, rtol=1e-12, maxiter=20000, method="cg1",
               check_every=16)
print(f"df64 cg1 ck16  : iters={int(rcg1.iterations):5d} "
      f"max|x - x_true| = {np.abs(rcg1.x() - x_true).max():.2e}")
