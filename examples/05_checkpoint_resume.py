"""Preemption-safe solving: checkpoint every k iterations, resume exactly.

The checkpoint carries the full recurrence state (x, r, p, rho), so the
resumed run continues the EXACT trajectory - not a restart from x.
Run: python examples/05_checkpoint_resume.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
import tempfile

import jax.numpy as jnp
import numpy as np

from cuda_mpi_parallel_tpu import solve
from cuda_mpi_parallel_tpu.models import poisson
from cuda_mpi_parallel_tpu.utils import checkpoint as ckpt

n = 128
op = poisson.poisson_2d_operator(n, n, dtype=jnp.float64)
b = jnp.asarray(np.random.default_rng(0).standard_normal(n * n))

path = os.path.join(tempfile.mkdtemp(), "cg.ckpt")
res = ckpt.solve_resumable(op, b, path, segment_iters=50, tol=0.0,
                           rtol=1e-8, maxiter=2000)   # backend="orbax" for
                                                      # sharded multi-host
full = solve(op, b, tol=0.0, rtol=1e-8, maxiter=2000)
print(f"segmented: {int(res.iterations)} iters, "
      f"uninterrupted: {int(full.iterations)} iters (must match)")
