"""Imbalance-aware partition planning on a skewed unstructured system.

A row-partitioned CG runs at the speed of its heaviest shard: every
psum waits for whoever owns the fattest rows.  This example loads the
repo's committed skewed fixture (a 60-row dense coupling block over a
180-row sparse tail), shows the even split's per-shard skew, lets
``balance.plan_partition`` pick a (reorder x split) layout, and solves
distributed both ways - same solution, one with a ~3.2x nnz stall
factor and one with ~1.3x.

On a multi-chip host this spans real devices; on CPU set
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu
(or just run tests/, whose conftest does it for you).
Run: python examples/11_partition_planning.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np

from cuda_mpi_parallel_tpu import plan_partition, solve
from cuda_mpi_parallel_tpu.balance import even_ranges
from cuda_mpi_parallel_tpu.models import mmio
from cuda_mpi_parallel_tpu.parallel import make_mesh, solve_distributed
from cuda_mpi_parallel_tpu.telemetry import shardscope

FIXTURE = os.path.join(os.path.dirname(__file__), "..", "tests",
                       "fixtures", "skewed_spd_240.mtx")

ndev = min(4, len(jax.devices()))
a = mmio.load_matrix_market(FIXTURE)
rng = np.random.default_rng(0)
x_true = rng.standard_normal(a.shape[0])
b = np.asarray(a @ jnp.asarray(x_true))

print(f"system: n={a.shape[0]}, nnz={a.nnz}, mesh={ndev}")

# --- what the legacy even split would pay --------------------------------
even = shardscope.report_for_ranges(a, even_ranges(a.shape[0], ndev),
                                    plan="none+even")
print("\n== even split (static prediction) ==")
print(even.table())

# --- plan: enumerate (reorder x split), score, take the minimizer --------
plan = plan_partition(a, ndev)
print(f"\n== planned: {plan.describe()} ==")
print(plan.report.table())

# --- both solve to the same answer, in the caller's row ordering ---------
mesh = make_mesh(ndev)
ref = solve(a, jnp.asarray(b), tol=1e-10, maxiter=2000)
res_even = solve_distributed(a, b, mesh=mesh, tol=1e-10, maxiter=2000)
res_plan = solve_distributed(a, b, mesh=mesh, tol=1e-10, maxiter=2000,
                             plan=plan)
for name, res in (("even", res_even), ("planned", res_plan)):
    err = float(np.max(np.abs(np.asarray(res.x) - x_true)))
    print(f"{name:8s}: iters={int(res.iterations):3d} "
          f"converged={bool(res.converged)} max|x - x_true|={err:.2e}")
assert np.allclose(np.asarray(res_plan.x), np.asarray(ref.x), atol=1e-7)

stall_even = even.imbalance()["nnz_max_over_mean"]
stall_plan = plan.report.imbalance()["nnz_max_over_mean"]
print(f"\nnnz stall factor: {stall_even:.3f} (even) -> "
      f"{stall_plan:.3f} (planned), {stall_even / stall_plan:.1f}x better")
