"""Network data plane: authenticated wire-format solves over loopback.

Four acts against one mesh-4 Poisson service behind `serve.net` (the
stdlib HTTP data plane - no new dependencies, client included):

1. **Submit over the wire**: start a service with
   ``ServiceConfig(net_port=0, net_keyring=...)``, then drive it with
   ``serve.client.NetClient`` - discover the handle via
   ``GET /v1/handles``, POST a base64 little-endian float64 vector,
   long-poll the result.  The decoded answer is BIT-exact: the bytes
   that come back are the bytes the solver produced.
2. **Tenant identity is derived, never claimed**: the bearer token
   maps to a tenant server-side.  A request claiming someone else's
   tenant gets a typed 403 BEFORE admission - the spoofed tag never
   reaches the scheduler, the SLO tracker, or the usage meter.
3. **Stream terminal results**: submit a burst asynchronously and
   read them off ``GET /v1/stream`` (Server-Sent Events) as the
   service finishes them.
4. **Measure the wire**: solve the same right-hand side in-process
   and over loopback; report the wire overhead and verify the two
   solutions agree byte for byte (same service, same lane, so the
   solve itself is identical - only the envelope differs).

Run:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu
      python examples/24_net_client.py
"""
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8").strip()
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

from cuda_mpi_parallel_tpu.models import poisson
from cuda_mpi_parallel_tpu.parallel import make_mesh
from cuda_mpi_parallel_tpu.serve import (
    NetClient,
    NetError,
    ServiceConfig,
    SolverService,
    TokenKeyring,
)
from cuda_mpi_parallel_tpu.serve.workload import rhs_for


def main():
    import jax

    jax.config.update("jax_enable_x64", True)

    # -- 1: a service behind the wire ---------------------------------
    ring = (TokenKeyring()
            .add("tok-acme", "acme")
            .add("tok-beta", "beta"))
    svc = SolverService(ServiceConfig(
        max_batch=4, maxiter=800, net_port=0, net_keyring=ring))
    a = poisson.poisson_2d_csr(24, 24, dtype=np.float64)
    handle = svc.register(a, mesh=make_mesh(4), method="batched",
                          precond=None)
    url = svc.net_server().url
    print(f"data plane: {url}  (tenants: {ring.tenants()})")

    acme = NetClient(url, "tok-acme")
    row = acme.handles()[0]
    print(f"GET /v1/handles -> key={row['key']} n={row['n']} "
          f"dtype={row['dtype']} mesh={row['mesh']}")

    b, x_true = rhs_for(a, seed=7)
    res = acme.solve(row["key"], b, tol=1e-9)
    err = float(np.max(np.abs(np.asarray(res.x) - x_true)))
    print(f"wire solve: {res.status} in {res.iterations} iters, "
          f"tenant={res.tenant!r} (derived from the token), "
          f"max|x - x_true| = {err:.2e}")

    # -- 2: spoofing is a typed 403, before admission ------------------
    beta = NetClient(url, "tok-beta")
    try:
        beta.submit(row["key"], b, tenant="acme")
        raise SystemExit("spoof was accepted?!")
    except NetError as e:
        print(f"tok-beta claiming tenant 'acme' -> HTTP {e.status} "
              f"code={e.code!r} (never reached admission: "
              f"stats tenants = "
              f"{sorted(svc.stats().get('tenants', {'acme': 1}))})")

    # -- 3: async burst + SSE stream -----------------------------------
    ids = []
    for seed in (11, 12, 13):
        out = acme.submit(row["key"], rhs_for(a, seed=seed)[0],
                          tol=1e-8)
        ids.append(out if isinstance(out, str) else out.request_id)
    print(f"submitted {len(ids)} async -> {ids}; streaming:")
    for result in acme.stream(ids=ids, timeout_s=60):
        print(f"  SSE: {result.request_id} {result.status} "
              f"({result.iterations} iters, "
              f"{result.latency_s * 1e3:.1f} ms)")

    # -- 4: the price of the envelope ----------------------------------
    reps = 5
    t0 = time.perf_counter()
    for _ in range(reps):
        fut = svc.submit(handle, b, tol=1e-9)
        local = fut.result()
    t_local = (time.perf_counter() - t0) / reps
    t0 = time.perf_counter()
    for _ in range(reps):
        wired = acme.solve(row["key"], b, tol=1e-9)
    t_wire = (time.perf_counter() - t0) / reps
    same = np.asarray(wired.x).tobytes() == np.asarray(local.x).tobytes()
    print(f"in-process {t_local * 1e3:.1f} ms vs wire "
          f"{t_wire * 1e3:.1f} ms per solve "
          f"(+{(t_wire - t_local) * 1e3:.1f} ms envelope); "
          f"solutions byte-identical: {same}")
    assert same, "wire and in-process solves diverged"

    svc.close()
    print("service closed; plane torn down")


if __name__ == "__main__":
    main()
