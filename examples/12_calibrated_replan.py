"""Runtime calibration and replanning across a solve sequence.

The partition planner prices layouts with a fixed reference machine
model - a table gather-slowdown of 8, a table net bandwidth.  Real
workloads solve the same operator hundreds of times, so the FIRST
solve's measured wall time can fit those parameters and the SECOND
solve can already run on a runtime-corrected plan.  This example runs
a 2-solve sequence on the committed skewed fixture: solve 1 runs the
even split under the reference model, its timing calibrates an
effective gather slowdown + net bandwidth (telemetry.calibrate), the
replan decision is made on the calibrated model, and solve 2 runs on
the plan that model chose - with the model's own error (drift)
printed for both solves.

On a multi-chip host this spans real devices; on CPU set
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu
(or just run tests/, whose conftest does it for you).
Run: python examples/12_calibrated_replan.py
"""
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# keep this demo's 240-row calibration out of the host's real
# measured-model cache (a production sequence would persist it so the
# NEXT process plans calibrated from its first solve)
os.environ.setdefault("CUDA_MPI_PARALLEL_TPU_CACHE_DIR",
                      tempfile.mkdtemp(prefix="cmpt-example-"))

import jax
import numpy as np

from cuda_mpi_parallel_tpu.models import mmio
from cuda_mpi_parallel_tpu.parallel import make_mesh, solve_sequence
from cuda_mpi_parallel_tpu.telemetry import calibrate

FIXTURE = os.path.join(os.path.dirname(__file__), "..", "tests",
                       "fixtures", "skewed_spd_240.mtx")

ndev = min(4, len(jax.devices()))
a = mmio.load_matrix_market(FIXTURE)
rng = np.random.default_rng(0)
b = rng.standard_normal(a.shape[0])

print(f"system: n={a.shape[0]}, nnz={a.nnz}, mesh={ndev}")
print("solve 1 runs the even split scored by the REFERENCE model;")
print("solve 2 re-plans on the model calibrated from solve 1.\n")

seq = solve_sequence(a, b, mesh=make_mesh(ndev), repeats=2,
                     replan=True, tol=1e-10, maxiter=2000)
for line in seq.describe_lines():
    print(line)

fit = seq.final.fit
print(f"\nmeasured gather slowdown: x{fit.model.gather_slowdown:.1f} "
      f"(the table guessed x8.0)")
print(f"solve-2 plan scored by  : {seq.final.plan.scored_by}"
      if seq.final.plan is not None else "solve-2 kept the even split")

# the calibration is on disk now: a fresh process on this host would
# prefer it for any plan='auto' solve (when the fit is confident)
preferred = calibrate.preferred_model()
print(f"preferred model on disk : "
      f"{preferred.name if preferred is not None else None} "
      f"(confident fit: {fit.confident})")

drift1 = seq.entries[0].drift.drift_pct
drift2 = seq.entries[1].drift.drift_pct
print(f"\nmodel error (drift)     : {drift1:+.0f}% under the reference "
      f"model -> {drift2:+.0f}% under the calibrated one")
