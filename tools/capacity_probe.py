"""On-chip VMEM capacity probe for the resident engine.

The `_PLANES_BOUND = 12` gate (`ops/pallas/resident.py`) is deliberately
pessimistic: the measured footprint at 1024^2 f32 was ~16.1 MB (~4
planes), so grids up to ~2048^2 may compile and run resident.  This
probe (run on REAL hardware only - each step compiles a Mosaic kernel)
walks grid sizes upward under a raised `CMP_RESIDENT_VMEM_BYTES` and
reports which compile + solve correctly, giving the evidence to relax
the bound.

Run: python tools/capacity_probe.py            (in a tunnel window)
Writes one JSON line per probe to stdout; safe to ^C between probes.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# lift the gate so the KERNEL is the thing being probed, not the gate
os.environ.setdefault("CMP_RESIDENT_VMEM_BYTES", str(512 * 1024 * 1024))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402


def main() -> int:
    if jax.default_backend() != "tpu":
        print(json.dumps({"error": "needs a compiled TPU backend"}))
        return 1
    from cuda_mpi_parallel_tpu import cg_resident
    from cuda_mpi_parallel_tpu.models import poisson

    rng = np.random.default_rng(0)
    # 1024^2 is the known-good headline size; 1448x1408 is non-square
    # because 1448 % 128 != 0 (the lane-tiling rule) - it probes the
    # largest near-1448^2 footprint the tiling admits.
    for nx, ny in [(1024, 1024), (1280, 1280), (1448, 1408),
                   (1536, 1536), (1792, 1792), (2048, 2048)]:
        rec = {"grid": [nx, ny],
               "planes_mb": round(nx * ny * 4 / 2**20, 1)}
        try:
            op = poisson.poisson_2d_operator(nx, ny, dtype=jnp.float32)
            b = jnp.asarray(
                rng.standard_normal(nx * ny).astype(np.float32))
            t0 = time.monotonic()
            res = cg_resident(op, b, tol=0.0, rtol=1e-4, maxiter=2000,
                              check_every=32)
            res.x.block_until_ready()
            rec["compile_plus_run_s"] = round(time.monotonic() - t0, 1)
            rec["iterations"] = int(res.iterations)
            # CORRECTNESS, not just finiteness: the true residual via
            # the independent XLA stencil path must agree with the
            # kernel's convergence claim - compiling is not solving,
            # and _PLANES_BOUND only gets relaxed on this evidence.
            true_r = float(jnp.linalg.norm(b - op @ res.x))
            nrm_b = float(jnp.linalg.norm(b))
            rec["true_rel_residual"] = true_r / nrm_b
            rec["ok"] = bool(res.converged) and true_r / nrm_b < 5e-4
            # rough rate only - a single phase-separated call, which the
            # repo's measurement protocol explicitly distrusts (tunnel
            # service-rate drift); re-measure any interesting size with
            # paired_delta_rate before quoting it anywhere.
            b2 = b * np.float32(1.0001)
            t1 = time.monotonic()
            r2 = cg_resident(op, b2, tol=0.0, iter_cap=200, maxiter=2000,
                             check_every=32)
            r2.x.block_until_ready()
            rec["run2_200it_s_NOT_PROTOCOL_GRADE"] = round(
                time.monotonic() - t1, 3)
        except Exception as e:  # compile failure IS the measurement
            rec["ok"] = False
            rec["error"] = str(e)[-300:]
        print(json.dumps(rec))
        sys.stdout.flush()
    return 0


if __name__ == "__main__":
    sys.exit(main())
