#!/usr/bin/env python
"""CI gate: validate a solve-trace JSONL and/or a Perfetto timeline.

Used by ``tools/lint.sh`` after its mesh-4 CLI solve::

    python tools/validate_trace.py events.jsonl trace.json
    python tools/validate_trace.py events.jsonl
    python tools/validate_trace.py --perfetto-only trace.json

Every JSONL line must parse as strict JSON and pass
``telemetry.events.validate_event`` (known type, envelope + required
fields); the Perfetto file must pass
``telemetry.report.validate_perfetto`` (loadable event array,
``ph``/``ts``/``pid``/``tid`` on every event, monotone ``ts`` per
track).  Exit 0 on success, 1 on any violation (with the offending
line/event named).
"""
from __future__ import annotations

import argparse
import json
import sys

sys.path.insert(0, ".")  # repo-root invocation, like tools/bench_compare

from cuda_mpi_parallel_tpu.telemetry.events import (  # noqa: E402
    read_events,
)
from cuda_mpi_parallel_tpu.telemetry.report import (  # noqa: E402
    validate_perfetto,
)


def check_events(path: str) -> int:
    """Validate every line; returns the event count."""
    return len(read_events(path))


def check_perfetto(path: str) -> int:
    """Validate the timeline structurally; returns the event count.

    Beyond the library's structural contract
    (``telemetry.report.validate_perfetto``), every timeline THIS repo
    exports must declare how its per-shard spans were produced: the
    metadata ``span_source`` field, ``"measured"`` (phase-profiler
    walls) or ``"modeled"`` (static-schedule rendering).  A bare
    top-level event array cannot carry metadata and is rejected here -
    the exporters always write the object form.
    """
    with open(path, encoding="utf-8") as f:
        try:
            trace = json.load(f)
        except json.JSONDecodeError as e:
            raise ValueError(f"{path}: not valid JSON: {e}") from e
    try:
        validate_perfetto(trace)
    except ValueError as e:
        raise ValueError(f"{path}: {e}") from e
    if not isinstance(trace, dict):
        raise ValueError(
            f"{path}: bare event array carries no metadata - exported "
            f"timelines must be the object form with a span_source "
            f"field")
    source = (trace.get("metadata") or {}).get("span_source")
    if source not in ("measured", "modeled"):
        raise ValueError(
            f"{path}: metadata.span_source must be 'measured' or "
            f"'modeled', got {source!r} (every exported timeline "
            f"declares its span renderer)")
    return len(trace["traceEvents"])


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="validate a solve-trace JSONL (+ optional Perfetto "
                    "timeline) for CI")
    ap.add_argument("events", nargs="?", default=None,
                    help="events JSONL path")
    ap.add_argument("perfetto", nargs="?", default=None,
                    help="Perfetto/Chrome-trace JSON path")
    ap.add_argument("--perfetto-only", default=None, metavar="PATH",
                    help="validate only this timeline file")
    args = ap.parse_args(argv)
    if args.perfetto_only is None and args.events is None:
        ap.error("nothing to validate")
    try:
        if args.events is not None:
            n = check_events(args.events)
            print(f"{args.events}: {n} events, all schema-valid")
        target = args.perfetto_only or args.perfetto
        if target is not None:
            n = check_perfetto(target)
            print(f"{target}: {n} trace events, structure valid")
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
