#!/usr/bin/env python
"""CI gate: validate a solve-trace JSONL and/or a Perfetto timeline.

Used by ``tools/lint.sh`` after its mesh-4 CLI solve::

    python tools/validate_trace.py events.jsonl trace.json
    python tools/validate_trace.py events.jsonl
    python tools/validate_trace.py --perfetto-only trace.json

Every JSONL line must parse as strict JSON and pass
``telemetry.events.validate_event`` (known type, envelope + required
fields); the Perfetto file must pass
``telemetry.report.validate_perfetto`` (loadable event array,
``ph``/``ts``/``pid``/``tid`` on every event, monotone ``ts`` per
track).  When the JSONL carries request ``span`` events (a traced
serve replay) the span forest is checked too: well-formed W3C ids,
known span names, one root per trace, and ZERO orphans - every span
must be reachable from its trace's ``submit`` root
(``--require-spans`` makes an empty forest an error, the serve lint
gate's mode).  Exit 0 on success, 1 on any violation (with the
offending line/event named).
"""
from __future__ import annotations

import argparse
import json
import re
import sys

sys.path.insert(0, ".")  # repo-root invocation, like tools/bench_compare

from cuda_mpi_parallel_tpu.telemetry import tracing  # noqa: E402
from cuda_mpi_parallel_tpu.telemetry.events import (  # noqa: E402
    read_events,
)
from cuda_mpi_parallel_tpu.telemetry.report import (  # noqa: E402
    validate_perfetto,
)

_TRACE_ID = re.compile(r"^[0-9a-f]{32}$")
_SPAN_ID = re.compile(r"^[0-9a-f]{16}$")


def check_events(path: str) -> int:
    """Validate every line; returns the event count."""
    return len(read_events(path))


def check_spans(path: str, require: bool = False) -> tuple:
    """Validate the request-span forest in an events JSONL.

    Returns ``(n_spans, n_traces)``.  Checks each span's id formats
    (32-hex trace id, 16-hex span id, parent 16-hex or null) and name
    against ``tracing.SPAN_NAMES``, then the forest property: exactly
    one root span per trace and no span unreachable from its root.
    """
    records = read_events(path)
    spans = tracing.span_events(records)
    if not spans:
        if require:
            raise ValueError(
                f"{path}: no request span events (traced serve replay "
                f"expected to emit a span forest)")
        return 0, 0
    for i, s in enumerate(spans):
        where = f"{path}: span[{i}] ({s.get('span_id')!r})"
        if not _TRACE_ID.match(str(s.get("trace_id", ""))):
            raise ValueError(f"{where}: malformed trace_id "
                             f"{s.get('trace_id')!r}")
        if not _SPAN_ID.match(str(s.get("span_id", ""))):
            raise ValueError(f"{where}: malformed span_id")
        parent = s.get("parent_span_id")
        if parent is not None and not _SPAN_ID.match(str(parent)):
            raise ValueError(f"{where}: malformed parent_span_id "
                             f"{parent!r}")
        if s.get("name") not in tracing.SPAN_NAMES:
            raise ValueError(f"{where}: unknown span name "
                             f"{s.get('name')!r}")
    forest = tracing.build_forest(records)
    for trace_id, tree in sorted(forest.items()):
        roots = [s for s in tree["spans"].values()
                 if s.get("parent_span_id") is None]
        if len(roots) != 1:
            raise ValueError(
                f"{path}: trace {trace_id} has {len(roots)} root "
                f"spans (exactly one 'submit' root expected)")
    orphans = tracing.orphan_spans(records)
    if orphans:
        o = orphans[0]
        raise ValueError(
            f"{path}: {len(orphans)} orphan span(s) - e.g. "
            f"{o.get('name')!r} span {o.get('span_id')} in trace "
            f"{o.get('trace_id')} is unreachable from its root")
    return len(spans), len(forest)


def check_perfetto(path: str) -> int:
    """Validate the timeline structurally; returns the event count.

    Beyond the library's structural contract
    (``telemetry.report.validate_perfetto``), every timeline THIS repo
    exports must declare how its per-shard spans were produced: the
    metadata ``span_source`` field, ``"measured"`` (phase-profiler
    walls) or ``"modeled"`` (static-schedule rendering).  A bare
    top-level event array cannot carry metadata and is rejected here -
    the exporters always write the object form.
    """
    with open(path, encoding="utf-8") as f:
        try:
            trace = json.load(f)
        except json.JSONDecodeError as e:
            raise ValueError(f"{path}: not valid JSON: {e}") from e
    try:
        validate_perfetto(trace)
    except ValueError as e:
        raise ValueError(f"{path}: {e}") from e
    if not isinstance(trace, dict):
        raise ValueError(
            f"{path}: bare event array carries no metadata - exported "
            f"timelines must be the object form with a span_source "
            f"field")
    source = (trace.get("metadata") or {}).get("span_source")
    if source not in ("measured", "modeled"):
        raise ValueError(
            f"{path}: metadata.span_source must be 'measured' or "
            f"'modeled', got {source!r} (every exported timeline "
            f"declares its span renderer)")
    return len(trace["traceEvents"])


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="validate a solve-trace JSONL (+ optional Perfetto "
                    "timeline) for CI")
    ap.add_argument("events", nargs="?", default=None,
                    help="events JSONL path")
    ap.add_argument("perfetto", nargs="?", default=None,
                    help="Perfetto/Chrome-trace JSON path")
    ap.add_argument("--perfetto-only", default=None, metavar="PATH",
                    help="validate only this timeline file")
    ap.add_argument("--require-spans", action="store_true",
                    dest="require_spans",
                    help="fail unless the events JSONL carries a "
                         "non-empty, fully-parented request span "
                         "forest (the serve lint gate's mode)")
    args = ap.parse_args(argv)
    if args.perfetto_only is None and args.events is None:
        ap.error("nothing to validate")
    if args.require_spans and args.events is None:
        ap.error("--require-spans needs an events JSONL")
    try:
        if args.events is not None:
            n = check_events(args.events)
            print(f"{args.events}: {n} events, all schema-valid")
            n_spans, n_traces = check_spans(
                args.events, require=args.require_spans)
            if n_spans:
                print(f"{args.events}: {n_spans} spans in {n_traces} "
                      f"traces, one root each, zero orphans")
        target = args.perfetto_only or args.perfetto
        if target is not None:
            n = check_perfetto(target)
            print(f"{target}: {n} trace events, structure valid")
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
