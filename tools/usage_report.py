#!/usr/bin/env python
"""Render and cross-check a metered-usage ledger export.

Reads the JSONL that ``cli serve --usage PATH`` (or
``UsageLedger.export_jsonl``) wrote - ``kind="request"`` lines,
``kind="batch"`` lines, and a final ``kind="summary"`` - re-derives
the per-tenant roll-up from the raw request lines, and verifies the
accounting identity independently of the exporter:

* summed per-tenant device-seconds / wire bytes == batch totals
  (relative mismatch gated at 1e-9, same bar as the library's
  ``UsageLedger.reconcile``);
* the re-derived roll-up matches the file's own summary line.

Used by ``tools/lint.sh`` after its traced mesh-4 serve replay::

    python tools/usage_report.py usage.jsonl
    python tools/usage_report.py usage.jsonl --json

Exit 0 when the ledger reconciles, 1 on any mismatch or malformed
line.
"""
from __future__ import annotations

import argparse
import json
import math
import sys

RECONCILE_GATE = 1e-9


def load_ledger(path):
    """Parse the export into (requests, batches, summary)."""
    requests, batches, summary = [], [], None
    with open(path, encoding="utf-8") as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                raise ValueError(f"{path}:{i}: not valid JSON: {e}")
            kind = rec.get("kind")
            if kind == "request":
                requests.append(rec)
            elif kind == "batch":
                batches.append(rec)
            elif kind == "summary":
                if summary is not None:
                    raise ValueError(f"{path}:{i}: duplicate summary "
                                     f"line")
                summary = rec
            else:
                raise ValueError(f"{path}:{i}: unknown kind "
                                 f"{kind!r} (expected request/batch/"
                                 f"summary)")
    if summary is None:
        raise ValueError(f"{path}: no summary line (truncated "
                         f"export?)")
    return requests, batches, summary


def roll_up(requests):
    """Re-derive the per-tenant totals from raw request lines."""
    acc = {}
    for rec in requests:
        t = acc.setdefault(str(rec.get("tenant", "default")), {
            "requests": 0, "device_seconds": [], "wire_bytes": [],
            "batch_iterations_share": []})
        t["requests"] += 1
        t["device_seconds"].append(float(rec["device_seconds"]))
        t["wire_bytes"].append(float(rec["wire_bytes"]))
        t["batch_iterations_share"].append(
            float(rec.get("batch_iterations_share", 0.0)))
    return {
        tenant: {
            "requests": v["requests"],
            "device_seconds": math.fsum(v["device_seconds"]),
            "wire_bytes": math.fsum(v["wire_bytes"]),
            "batch_iterations_share": math.fsum(
                v["batch_iterations_share"]),
        }
        for tenant, v in sorted(acc.items())
    }


def reconcile(per_tenant, batches):
    """Max relative mismatch of summed shares vs batch totals."""
    worst = 0.0
    for field in ("device_seconds", "wire_bytes"):
        total = math.fsum(float(b[field]) for b in batches)
        summed = math.fsum(v[field] for v in per_tenant.values())
        worst = max(worst,
                    abs(summed - total) / max(abs(total), 1.0))
    return worst


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="render + cross-check a serve usage ledger export")
    ap.add_argument("ledger", help="usage JSONL path (cli serve "
                                   "--usage output)")
    ap.add_argument("--json", action="store_true",
                    help="emit one JSON record instead of the table")
    args = ap.parse_args(argv)
    try:
        requests, batches, summary = load_ledger(args.ledger)
        per_tenant = roll_up(requests)
        residual = reconcile(per_tenant, batches)
        problems = []
        if residual > RECONCILE_GATE:
            problems.append(
                f"per-tenant shares do not reconcile with batch "
                f"totals: max rel err {residual:.3e} > "
                f"{RECONCILE_GATE:.0e}")
        filed = summary.get("per_tenant") or {}
        if sorted(filed) != sorted(per_tenant):
            problems.append(
                f"summary tenants {sorted(filed)} != re-derived "
                f"{sorted(per_tenant)}")
        else:
            for tenant, mine in per_tenant.items():
                theirs = filed[tenant]
                for field in ("requests", "device_seconds",
                              "wire_bytes"):
                    a, b = float(mine[field]), float(theirs[field])
                    if abs(a - b) > RECONCILE_GATE * max(abs(a), 1.0):
                        problems.append(
                            f"summary disagrees for {tenant}.{field}: "
                            f"file {b!r} vs re-derived {a!r}")
        if problems:
            raise ValueError("; ".join(problems))
    except (OSError, ValueError, KeyError, TypeError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1

    totals = {
        "batches": len(batches),
        "requests": len(requests),
        "device_seconds": math.fsum(float(b["device_seconds"])
                                    for b in batches),
        "wire_bytes": math.fsum(float(b["wire_bytes"])
                                for b in batches),
    }
    if args.json:
        print(json.dumps({
            "ledger": args.ledger, "totals": totals,
            "per_tenant": per_tenant,
            "reconcile_max_rel_err": residual, "ok": True},
            sort_keys=True))
        return 0
    print(f"usage ledger {args.ledger}: {totals['batches']} "
          f"batch(es), {totals['requests']} request(s)")
    print(f"{'tenant':<16} {'requests':>8} {'device-s':>14} "
          f"{'wire bytes':>14} {'iter share':>12}")
    for tenant, v in per_tenant.items():
        print(f"{tenant:<16} {v['requests']:>8d} "
              f"{v['device_seconds']:>14.6f} "
              f"{v['wire_bytes']:>14.3e} "
              f"{v['batch_iterations_share']:>12.1f}")
    print(f"{'TOTAL':<16} {totals['requests']:>8d} "
          f"{totals['device_seconds']:>14.6f} "
          f"{totals['wire_bytes']:>14.3e}")
    print(f"reconcile: max rel err {residual:.3e} "
          f"(gate {RECONCILE_GATE:.0e}) - OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
