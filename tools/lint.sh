#!/usr/bin/env bash
# The pre-hardware gate: graftlint over the package, then the tier-1
# test suite (ROADMAP.md).  New multi-chip kernels must pass BOTH
# before a capacity probe burns chip time.
#
# Usage:  tools/lint.sh [--lint-only]
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== graftlint (cuda_mpi_parallel_tpu.analysis) =="
python -m cuda_mpi_parallel_tpu.analysis cuda_mpi_parallel_tpu
echo "graftlint: clean"

if [[ "${1:-}" == "--lint-only" ]]; then
    exit 0
fi

echo "== tier-1 tests (ROADMAP.md) =="
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log \
    | tr -cd . | wc -c)"
exit "$rc"
