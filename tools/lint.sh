#!/usr/bin/env bash
# The pre-hardware gate: graftlint over the package, then the tier-1
# test suite (ROADMAP.md).  New multi-chip kernels must pass BOTH
# before a capacity probe burns chip time.
#
# Usage:  tools/lint.sh [--lint-only]
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== graftlint (cuda_mpi_parallel_tpu.analysis) =="
python -m cuda_mpi_parallel_tpu.analysis cuda_mpi_parallel_tpu
echo "graftlint: clean"

# Telemetry must NEVER force a device sync inside a solve loop: hold
# the telemetry package to GL105 (host-sync) explicitly, failing on any
# finding.  (The package-wide run above already includes telemetry/ for
# all rules; this names the observability contract and keeps it from
# being relaxed by a future --ignore.)
echo "== graftlint telemetry/ (GL105 host-sync, zero findings) =="
python -m cuda_mpi_parallel_tpu.analysis --select GL105 --fail-on info \
    cuda_mpi_parallel_tpu/telemetry
echo "telemetry: GL105 clean"

# The flight recorder lives INSIDE the solvers' hot loops - it is the
# one telemetry component where a host sync would be catastrophic, so
# its modules are named explicitly (the directory gate above would
# also catch them, but this line keeps the contract visible and
# survives any future --ignore on the directory run).
echo "== graftlint flight recorder (GL105, zero findings) =="
python -m cuda_mpi_parallel_tpu.analysis --select GL105 --fail-on info \
    cuda_mpi_parallel_tpu/telemetry/flight.py \
    cuda_mpi_parallel_tpu/telemetry/health.py
echo "flight recorder: GL105 clean"

# The partition planner is pure host-side layout work - it must never
# grow a device sync (GL105) or any other finding.  The package-wide
# run above covers balance/ for all rules; this names the contract.
echo "== graftlint balance/ (GL105 host-sync, zero findings) =="
python -m cuda_mpi_parallel_tpu.analysis --select GL105 --fail-on info \
    cuda_mpi_parallel_tpu/balance
echo "balance: GL105 clean"

# graftverify gate: the TRACE half of the static gate (ISSUE 16).
# The package-wide graftlint run above already holds the shipped code
# to the new GL106-GL109 rules; this adds the whole-trace contracts -
# the SPMD verifier must be green on the exact mesh-4 CSR solve bodies
# the solver cache would compile (allgather/gather/ring exchange,
# deflated, fault-armed), and the differential cache-key audit must
# prove every static lane of solve_distributed/ManyRHSDispatcher moves
# the cache key whenever it moves the traced program.  Trace-only:
# jax.make_jaxpr, never a compile or a device run, so it stays in the
# cheap (--lint-only) phase.
echo "== graftverify (SPMD contracts + cache-key audit, mesh-4) =="
JAX_PLATFORMS=cpu python tools/graftverify.py

if [[ "${1:-}" == "--lint-only" ]]; then
    exit 0
fi

# Observability contract gate: one small mesh-4 CLI solve with the full
# reporting surface on, then schema-validate EVERY emitted event line
# (telemetry.events.validate_event) and structurally validate the
# Perfetto timeline (ph/ts/pid/tid on every event, monotone ts per
# track) plus the report's required sections.  This is the end-to-end
# proof that the event stream, shard profile, roofline and timeline
# exporters still compose - unit tests cover each piece, this covers
# the seam.
echo "== solve-report gate (mesh-4 CLI: event schema + Perfetto) =="
scratch=$(mktemp -d)
trap 'rm -rf "$scratch"' EXIT
# Every CLI gate below pins the measured-artifact cache to the scratch
# dir: --plan auto (and the roofline's CPU model) reads this host's
# calibration cache since PR 6, and a leftover confident calibration
# would make these assertions host-state-dependent.
export CUDA_MPI_PARALLEL_TPU_CACHE_DIR="$scratch/cache"
JAX_PLATFORMS=cpu python -m cuda_mpi_parallel_tpu.cli \
    --problem poisson2d --n 16 --mesh 4 --device cpu \
    --tol 1e-6 --maxiter 200 \
    --trace-events "$scratch/events.jsonl" \
    --report "$scratch/report.txt" \
    --trace-perfetto "$scratch/trace.json" > /dev/null
python tools/validate_trace.py "$scratch/events.jsonl" \
    "$scratch/trace.json"
grep -q "imbalance" "$scratch/report.txt"
grep -q "roofline" "$scratch/report.txt"
grep -q "efficiency" "$scratch/report.txt"
echo "solve-report gate: clean"

# Planner gate: the balance/ subsystem must actually beat the even
# split where it claims to - the committed skewed unstructured SPD
# fixture at mesh 4.  Two CLI solves (legacy even split, then
# --plan auto), then compare the measured per-shard nnz stall factor
# each report carries.  End-to-end: MatrixMarket parse -> planner ->
# plan-driven partition -> distributed solve -> shardscope report.
echo "== planner gate (mesh-4 CLI: --plan auto beats --plan even) =="
JAX_PLATFORMS=cpu python -m cuda_mpi_parallel_tpu.cli \
    --problem mm --file tests/fixtures/skewed_spd_240.mtx --mesh 4 \
    --device cpu --tol 1e-8 --maxiter 500 \
    --plan even --report "$scratch/plan_even.txt" > /dev/null
JAX_PLATFORMS=cpu python -m cuda_mpi_parallel_tpu.cli \
    --problem mm --file tests/fixtures/skewed_spd_240.mtx --mesh 4 \
    --device cpu --tol 1e-8 --maxiter 500 \
    --plan auto --report "$scratch/plan_auto.txt" > /dev/null
python - "$scratch/plan_even.txt" "$scratch/plan_auto.txt" <<'PY'
import re
import sys


def imbalance(path):
    with open(path, encoding="utf-8") as f:
        m = re.search(r"nnz max/mean ([0-9.]+)", f.read())
    assert m, f"{path}: no shard-profile imbalance line"
    return float(m.group(1))


even, auto = imbalance(sys.argv[1]), imbalance(sys.argv[2])
assert auto < even, \
    f"--plan auto imbalance {auto} does not beat --plan even {even}"
print(f"planner gate: nnz max/mean {even} (even) -> {auto} (auto)")
PY
echo "planner gate: clean"

# Memscope gate: the device-memory observatory end-to-end on the same
# committed skewed fixture - one mesh-4 CLI solve with --memory-report
# must (a) emit a schema-valid memory_profile event, (b) carry a
# memory payload in --json whose MEASURED dispatcher-held device bytes
# equal the static model's summed per-shard partition bytes EXACTLY
# (the byte-exact contract the dispatch hook itself asserts), with the
# per-shard persistent bytes reconciling as matrix + solver working
# set and a jaxpr-derived transient peak present, and (c) render the
# report's memory section.  Then the model-only feasibility sweep
# (tools/hbm_plan.py, the ROADMAP item 7 answer at 256^3) must price a
# 64^3 smoke grid and name a finite minimum mesh for the baseline lane
# - zero device work, pure geometry.
echo "== memscope gate (mesh-4 CLI: --memory-report byte-exact) =="
JAX_PLATFORMS=cpu python -m cuda_mpi_parallel_tpu.cli \
    --problem mm --file tests/fixtures/skewed_spd_240.mtx --mesh 4 \
    --device cpu --tol 1e-8 --maxiter 500 --json \
    --memory-report \
    --trace-events "$scratch/mem_events.jsonl" \
    --report "$scratch/mem_report.txt" \
    > "$scratch/mem.json"
python tools/validate_trace.py "$scratch/mem_events.jsonl"
python - "$scratch" <<'PY'
import json
import sys

scratch = sys.argv[1]
with open(f"{scratch}/mem.json") as f:
    rec = json.load(f)
events = [json.loads(ln)
          for ln in open(f"{scratch}/mem_events.jsonl")
          if ln.strip()]

mem = rec["memory"]
assert mem is not None, "no memory payload in the --json record"
assert mem["n_shards"] == 4, mem["n_shards"]
# the byte-exact contract: the dispatcher-held device arrays measure
# exactly what the static partition model predicted
assert mem["measured_bytes"] == sum(mem["matrix_bytes"]), \
    (mem["measured_bytes"], mem["matrix_bytes"])
# per-shard persistent = pinned matrix slots + modeled solver stacks
assert mem["persistent_bytes"] == [
    m + s for m, s in zip(mem["matrix_bytes"], mem["solver_bytes"])], \
    mem
assert mem["classification"] in ("FITS", "TIGHT", "OVERFLOW"), mem
assert mem["jaxpr_peak_bytes"], \
    "no jaxpr-derived transient peak in the memory payload"

profs = [e for e in events if e["event"] == "memory_profile"]
assert profs, "no memory_profile event emitted"
prof = profs[-1]
assert prof["measured_bytes"] == mem["measured_bytes"], prof
assert prof["classification"] == mem["classification"], prof
print(f"memscope gate: {mem['kind']} x {mem['n_shards']} shards, "
      f"measured {mem['measured_bytes']} B == model (exact), "
      f"peak {mem['peak_bytes']} B -> {mem['classification']}")
PY
grep -q "memory (per-shard HBM accounting)" "$scratch/mem_report.txt"
python tools/hbm_plan.py --n 64 > "$scratch/hbm_plan.txt"
grep -q "minimum pod slice per lane" "$scratch/hbm_plan.txt"
grep -qE "64\^3 f32 k=1 ring +-> [0-9]+ shard" "$scratch/hbm_plan.txt"
echo "memscope gate: clean"

# Calibra gate: the runtime-calibration + replan loop end-to-end on
# the same skewed fixture - a mesh-4 CLI sequence (--repeat 2 --replan)
# must emit a schema-valid `replan` event (the kept/switched decision)
# and the drift-extended `partition_plan` event (predicted-vs-measured
# model error), and the report must carry the calibration/drift
# section.  The calibration cache is pointed at the scratch dir so the
# gate never reads or writes this host's real measured-model cache.
echo "== calibra gate (mesh-4 CLI: --repeat 2 --replan) =="
JAX_PLATFORMS=cpu CUDA_MPI_PARALLEL_TPU_CACHE_DIR="$scratch/cache" \
    python -m cuda_mpi_parallel_tpu.cli \
    --problem mm --file tests/fixtures/skewed_spd_240.mtx --mesh 4 \
    --device cpu --tol 1e-8 --maxiter 500 \
    --repeat 2 --replan \
    --trace-events "$scratch/replan_events.jsonl" \
    --report "$scratch/replan_report.txt" > /dev/null
python tools/validate_trace.py "$scratch/replan_events.jsonl"
python - "$scratch/replan_events.jsonl" <<'PY'
import json
import sys

events = [json.loads(line) for line in open(sys.argv[1])
          if line.strip()]
replans = [e for e in events if e["event"] == "replan"]
assert replans, "no replan event emitted"
assert all(e["decision"] in ("kept", "switched") for e in replans), \
    f"bad replan decision: {replans}"
drifts = [e for e in events
          if e["event"] == "partition_plan" and "drift_pct" in e]
assert drifts, "no drift-extended partition_plan event emitted"
for e in drifts:
    assert "predicted_s_per_iteration" in e \
        and "measured_s_per_iteration" in e, f"drift event truncated: {e}"
print(f"calibra gate: {len(replans)} replan + {len(drifts)} drift "
      f"events, decision={replans[0]['decision']}")
PY
grep -qi "calibration" "$scratch/replan_report.txt"
grep -qi "drift" "$scratch/replan_report.txt"
echo "calibra gate: clean"

# Gather-exchange gate: the sparse gather halo wire must actually beat
# the allgather payload where it claims to - the committed skewed
# fixture at mesh 4.  Two CLI solves of the IDENTICAL system (same
# seed, same rhs): the legacy allgather wire, then --exchange gather.
# Every event line of both traces is schema-validated; the comm_cost
# events' jaxpr-derived wire bytes must be STRICTLY lower on the
# gather run; and the solutions must match - the gather matvec sums
# the same entries in the same order, so iterations and the final
# residual are bit-identical (the jaxpr-level proof lives in
# tests/test_exchange.py::TestZeroPerturbation).
echo "== gather-exchange gate (mesh-4 CLI: --exchange gather) =="
JAX_PLATFORMS=cpu python -m cuda_mpi_parallel_tpu.cli \
    --problem mm --file tests/fixtures/skewed_spd_240.mtx --mesh 4 \
    --device cpu --tol 1e-8 --maxiter 500 --json \
    --trace-events "$scratch/ex_allgather.jsonl" \
    > "$scratch/ex_allgather.json"
JAX_PLATFORMS=cpu python -m cuda_mpi_parallel_tpu.cli \
    --problem mm --file tests/fixtures/skewed_spd_240.mtx --mesh 4 \
    --device cpu --tol 1e-8 --maxiter 500 --json \
    --exchange gather \
    --trace-events "$scratch/ex_gather.jsonl" \
    > "$scratch/ex_gather.json"
python tools/validate_trace.py "$scratch/ex_allgather.jsonl"
python tools/validate_trace.py "$scratch/ex_gather.jsonl"
python - "$scratch" <<'PY'
import json
import sys

scratch = sys.argv[1]


def record(name):
    with open(f"{scratch}/{name}.json") as f:
        return json.load(f)


def wire(name):
    events = [json.loads(ln) for ln in open(f"{scratch}/{name}.jsonl")
              if ln.strip()]
    costs = [e for e in events if e["event"] == "comm_cost"]
    assert costs, f"{name}: no comm_cost event"
    return max(e["wire_bytes_per_iteration"] for e in costs)


allgather, gather = wire("ex_allgather"), wire("ex_gather")
assert gather < allgather, \
    f"gather wire {gather} B/iter is not below allgather {allgather}"
ra, rg = record("ex_allgather"), record("ex_gather")
assert ra["converged"] and rg["converged"], (ra, rg)
assert ra["iterations"] == rg["iterations"], \
    f"iteration counts differ: {ra['iterations']} vs {rg['iterations']}"
assert abs(ra["residual_norm"] - rg["residual_norm"]) \
    <= 1e-12 * max(abs(ra["residual_norm"]), 1e-300), \
    f"residuals differ: {ra['residual_norm']} vs {rg['residual_norm']}"
assert rg["comm"]["exchange"] == "gather", rg["comm"]
print(f"gather-exchange gate: wire {allgather} -> {gather} B/iter "
      f"({100.0 * (1 - gather / allgather):.1f}% less), solutions "
      f"match at {ra['iterations']} iters")
PY
echo "gather-exchange gate: clean"

# Many-RHS batched gate: a mesh-4 CLI --rhs 8 block-CG solve of the
# committed skewed fixture, with every event line schema-validated,
# against a single-RHS solve of the same system.  Asserts (a) every
# lane's solution reaches its known X_true column (the CLI builds
# B = A @ X_true and reports per-lane max_abs_error), (b) single-RHS
# solves of the same operator land at the same accuracy - so the lanes
# match 8 independent solves to tolerance transitively, and (c) the
# batched solve's WHOLE-SOLVE comm_cost wire bytes are STRICTLY below
# 8x the single-RHS solve's (block-CG's shared Krylov space needs
# fewer iterations; the per-iteration wire carries all 8 columns).
echo "== many-RHS gate (mesh-4 CLI: --rhs 8 batched wire + accuracy) =="
JAX_PLATFORMS=cpu python -m cuda_mpi_parallel_tpu.cli \
    --problem mm --file tests/fixtures/skewed_spd_240.mtx --mesh 4 \
    --device cpu --tol 1e-8 --maxiter 500 --json \
    --rhs 8 --rhs-method block --exchange gather \
    --trace-events "$scratch/rhs_batched.jsonl" \
    > "$scratch/rhs_batched.json"
JAX_PLATFORMS=cpu python -m cuda_mpi_parallel_tpu.cli \
    --problem mm --file tests/fixtures/skewed_spd_240.mtx --mesh 4 \
    --device cpu --tol 1e-8 --maxiter 500 --json \
    --exchange gather \
    --trace-events "$scratch/rhs_single.jsonl" \
    > "$scratch/rhs_single.json"
python tools/validate_trace.py "$scratch/rhs_batched.jsonl"
python tools/validate_trace.py "$scratch/rhs_single.jsonl"
python - "$scratch" <<'PY'
import json
import sys

scratch = sys.argv[1]


def record(name):
    with open(f"{scratch}/{name}.json") as f:
        return json.load(f)


batched, single = record("rhs_batched"), record("rhs_single")
assert batched["n_rhs"] == 8 and batched["rhs_method"] == "block"
assert batched["converged"] and single["converged"]
lanes = batched["lanes"]
assert len(lanes["iterations"]) == 8
assert all(s == "CONVERGED" for s in lanes["status"]), lanes["status"]
# (a) every lane hit its known solution to tolerance - the per-lane
# bitwise-vs-independent-solves proof lives in tests/test_many_rhs.py;
# (b) the single-RHS reference solve of the same operator converged
# at the same bar
assert all(e < 1e-5 for e in lanes["max_abs_error"]), \
    f"lane errors too large: {lanes['max_abs_error']}"
assert single["residual_norm"] < 1e-8, single["residual_norm"]
# (c) whole-solve wire: one exchange per iteration served all 8
# columns AND block-CG needed fewer iterations, so the batched solve
# moves strictly fewer bytes than 8 sequential solves would
wire_batched = batched["comm"]["wire_bytes"]
wire_single8 = 8 * single["comm"]["wire_bytes"]
assert wire_batched < wire_single8, \
    f"batched wire {wire_batched} not below 8x single {wire_single8}"
# per-iteration collective count unchanged: one gather round set
per_b = batched["comm"]["per_iteration"]["ops"]
per_s = single["comm"]["per_iteration"]["ops"]
assert per_b.get("ppermute", 0) == per_s.get("ppermute", 0), \
    (per_b, per_s)
print(f"many-RHS gate: {batched['iterations']} block iters vs "
      f"{single['iterations']} single; wire/solve {wire_batched} B < "
      f"8x single {wire_single8} B "
      f"({100.0 * (1 - wire_batched / wire_single8):.1f}% less)")
PY
echo "many-RHS gate: clean"

# Solver-service gate: a mesh-4 CLI `serve` replay of 32 Poisson-
# arrival requests against one registered operator, with the full
# event stream on.  Asserts (a) every event line is schema-valid,
# (b) every non-timeout request CONVERGED and its answer matched the
# known per-seed solution, (c) at least one dispatched batch coalesced
# >= 2 requests (the microbatcher actually batched), and (d) ZERO
# dist_cache_miss events outside the registration warmup - post-warmup
# traffic runs entirely on the compiled-solver cache (the service's
# zero-retrace acceptance).
echo "== serve gate (mesh-4 CLI serve: replay batches, zero retrace) =="
JAX_PLATFORMS=cpu python -m cuda_mpi_parallel_tpu.cli serve \
    --problem mm --file tests/fixtures/skewed_spd_240.mtx --mesh 4 \
    --requests 32 --rate 2000 --max-batch 8 --tol 1e-8 --maxiter 500 \
    --seed 3 --json \
    --trace-events "$scratch/serve_events.jsonl" \
    > "$scratch/serve.json"
python tools/validate_trace.py "$scratch/serve_events.jsonl"
python - "$scratch" <<'PY'
import json
import sys

scratch = sys.argv[1]
with open(f"{scratch}/serve.json") as f:
    rec = json.load(f)
events = [json.loads(ln)
          for ln in open(f"{scratch}/serve_events.jsonl")
          if ln.strip()]
assert rec["stats"]["rejected"] == 0, rec["stats"]
live = [r for r in rec["requests"]
        if not r["timed_out"] and r["status"] != "REJECTED"]
assert live, "no completed requests"
assert all(r["status"] == "CONVERGED" for r in live), \
    [r["status"] for r in rec["requests"]]
assert all(r["max_abs_error"] < 1e-5 for r in live), \
    max(r["max_abs_error"] for r in live)
dispatches = [e for e in events if e["event"] == "batch_dispatch"
              and e.get("phase") != "warmup"]
assert dispatches, "no batch_dispatch events"
best = max(e["n_requests"] for e in dispatches)
assert best >= 2, f"no batch coalesced >= 2 requests (best {best})"
misses = [e for e in events if e["event"] == "dist_cache_miss"
          and e.get("phase") != "warmup"]
assert not misses, \
    f"{len(misses)} post-warmup dist_cache_miss events (retrace!)"
stats = rec["stats"]
assert stats["dist_cache_misses_postwarm"] == 0, stats
print(f"serve gate: {stats['completed']} requests in "
      f"{stats['batches']} batches (best occupancy {best} lanes, "
      f"mean {stats['occupancy_mean']:.2f}), p95 "
      f"{stats['latency']['p95_s'] * 1e3:.1f} ms, "
      f"{stats['solved_rhs_per_sec']:.1f} solved RHS/s, "
      f"0 post-warmup cache misses")
PY
echo "serve gate: clean"

# Recycle gate: Krylov-subspace recycling end-to-end on the committed
# skewed fixture - a mesh-4 CLI `serve` replay with --recycle must
# (a) emit a schema-valid event stream including recycle_harvest +
# recycle_applied events, (b) solve every request CONVERGED with
# max_abs_error < 1e-5 (deflation never breaks convergence), and
# (c) show the final solve's iteration count STRICTLY below the first
# solve's - the service measurably speeds up within one replay.
echo "== recycle gate (mesh-4 CLI serve --recycle: iters/solve falls) =="
JAX_PLATFORMS=cpu python -m cuda_mpi_parallel_tpu.cli serve \
    --problem mm --file tests/fixtures/skewed_spd_240.mtx --mesh 4 \
    --requests 24 --rate 2000 --max-batch 4 --tol 1e-8 --maxiter 500 \
    --seed 5 --recycle 12 --json \
    --trace-events "$scratch/recycle_events.jsonl" \
    > "$scratch/recycle.json"
python tools/validate_trace.py "$scratch/recycle_events.jsonl"
python - "$scratch" <<'PY'
import json
import sys

scratch = sys.argv[1]
with open(f"{scratch}/recycle.json") as f:
    rec = json.load(f)
events = [json.loads(ln)
          for ln in open(f"{scratch}/recycle_events.jsonl")
          if ln.strip()]

live = [r for r in rec["requests"]
        if not r["timed_out"] and r["status"] != "REJECTED"]
assert live, "no completed requests"
assert all(r["status"] == "CONVERGED" for r in live), \
    [r["status"] for r in rec["requests"]]
assert all(r["max_abs_error"] < 1e-5 for r in live), \
    max(r["max_abs_error"] for r in live)

harvests = [e for e in events if e["event"] == "recycle_harvest"]
applied = [e for e in events if e["event"] == "recycle_applied"]
assert harvests, "no recycle_harvest event emitted"
assert applied, "no recycle_applied event emitted"

r = rec["recycle"]
assert r["harvests"] >= 1, r
first, last = r["first_solve_iterations"], r["last_solve_iterations"]
assert first is not None and last is not None, r
assert last < first, \
    f"final-solve iterations {last} not strictly below first-solve " \
    f"{first} - recycling bought nothing"
print(f"recycle gate: {len(live)} requests CONVERGED, "
      f"{r['harvests']} harvest(s) / {r['applied']} deflated "
      f"dispatch(es), iters/solve {first} -> {last} "
      f"({len(harvests)}+{len(applied)} recycle events schema-valid)")
PY
echo "recycle gate: clean"

# Overload gate: the shed-before-collapse ladder end-to-end on a
# deterministic fake clock (tools/overload_drill.py) - a scripted
# ~2x-reject-depth overload must fire the ladder IN ORDER (degraded
# results before any deferral, deferrals before any admission
# rejection), never time out an accepted gold request, and walk the
# shed levels 1 -> 2 -> 3 without skipping a rung; the SLO burn-rate
# tracker must trip at least one deterministic slo_burn on the fake
# clock; then every emitted event (admission / sched_dispatch / shed /
# span / slo_burn included) must be schema-valid with a fully-parented
# span forest.  The weighted-fair starvation bound and the legacy
# bit-for-bit compat proof live in tests/test_serve_sched.py.
echo "== overload gate (fake-clock shed ladder fires in order) =="
JAX_PLATFORMS=cpu python tools/overload_drill.py \
    "$scratch/overload_events.jsonl"
python tools/validate_trace.py "$scratch/overload_events.jsonl" \
    --require-spans
echo "overload gate: clean"

# Observatory gate: causal tracing + metered usage end-to-end on the
# committed skewed fixture - a traced mesh-4 CLI serve replay with
# --usage must produce (a) a schema-valid event stream whose span
# forest has one submit root per trace and ZERO orphans
# (validate_trace.py --require-spans), (b) a result span for EVERY
# request_done event - the trace-completeness contract: no request
# finishes untraced, (c) a usage ledger whose per-tenant shares
# reconcile with the batch totals within 1e-9, independently
# re-derived by tools/usage_report.py from the raw export.
echo "== observatory gate (mesh-4 serve: span forest + usage ledger) =="
JAX_PLATFORMS=cpu python -m cuda_mpi_parallel_tpu.cli serve \
    --problem mm --file tests/fixtures/skewed_spd_240.mtx --mesh 4 \
    --requests 24 --rate 2000 --max-batch 8 --tol 1e-8 --maxiter 500 \
    --seed 11 --json \
    --trace-events "$scratch/obs_events.jsonl" \
    --usage "$scratch/obs_usage.jsonl" \
    > "$scratch/obs.json"
python tools/validate_trace.py "$scratch/obs_events.jsonl" \
    --require-spans
python tools/usage_report.py "$scratch/obs_usage.jsonl"
JAX_PLATFORMS=cpu python - "$scratch" <<'PY'
import json
import sys

scratch = sys.argv[1]
events = [json.loads(ln)
          for ln in open(f"{scratch}/obs_events.jsonl")
          if ln.strip()]

from cuda_mpi_parallel_tpu.telemetry import tracing

spans = tracing.span_events(events)
dones = [e for e in events if e["event"] == "request_done"]
assert dones, "no request_done events"
result_rids = {s["request_id"] for s in spans if s["name"] == "result"}
undone = [e["request_id"] for e in dones
          if e["request_id"] not in result_rids]
assert not undone, \
    f"{len(undone)} request_done without a terminal result span: " \
    f"{undone[:4]}"
solve_ids = {e["solve_id"] for e in events
             if e["event"] == "batch_dispatch"}
span_solves = {s["solve_id"] for s in spans if s["name"] == "solve"}
assert span_solves <= solve_ids, \
    f"solve spans name unknown solve_ids: {span_solves - solve_ids}"
usages = [e for e in events if e["event"] == "usage"]
assert usages, "no usage events in the stream"
forest = tracing.build_forest(events)
print(f"observatory gate: {len(spans)} spans in {len(forest)} traces "
      f"cover {len(dones)} request_done events, {len(span_solves)} "
      f"solve(s) joined to batch telemetry, {len(usages)} usage "
      f"events")
PY
echo "observatory gate: clean"

# Phasetrace gate: measured per-shard per-phase timing end-to-end on
# the committed skewed fixture - one mesh-4 CLI solve with
# --phase-profile must produce (a) a MEASURED Perfetto timeline
# (metadata span_source="measured" - validate_trace.py now requires
# the field on every exported timeline), (b) a schema-valid
# phase_profile event carrying per-neighbor (per-link) bandwidth
# estimates for the gather rounds, (c) a phase-resolved CalibrationFit
# reaching the lstsq2 CONFIDENT tier from this single solve (baseline:
# one wall-time observation only reaches fixed-net), and (d) a phase
# sum explaining the measured per-iteration wall within 30%.
echo "== phasetrace gate (mesh-4 CLI: --phase-profile measured) =="
JAX_PLATFORMS=cpu python -m cuda_mpi_parallel_tpu.cli \
    --problem mm --file tests/fixtures/skewed_spd_240.mtx --mesh 4 \
    --device cpu --tol 1e-8 --maxiter 500 --json \
    --exchange gather --phase-profile \
    --trace-events "$scratch/phase_events.jsonl" \
    --trace-perfetto "$scratch/phase_trace.json" \
    > "$scratch/phase.json"
python tools/validate_trace.py "$scratch/phase_events.jsonl" \
    "$scratch/phase_trace.json"
# JAX_PLATFORMS pinned: this checker imports the package (for the
# profiler's own explained-fraction tolerance constant), and a bare
# jax import must not try to reach a TPU tunnel
JAX_PLATFORMS=cpu python - "$scratch" <<'PY'
import json
import sys

scratch = sys.argv[1]
with open(f"{scratch}/phase.json") as f:
    rec = json.load(f)
with open(f"{scratch}/phase_trace.json") as f:
    trace = json.load(f)
events = [json.loads(ln)
          for ln in open(f"{scratch}/phase_events.jsonl")
          if ln.strip()]

# (a) measured Perfetto spans
meta = trace["metadata"]
assert meta["span_source"] == "measured", meta
spans = [e for e in trace["traceEvents"]
         if e.get("ph") == "X"
         and (e.get("args") or {}).get("span_source") == "measured"]
assert spans, "no measured per-shard spans in the timeline"

# (b) phase_profile event with per-link bandwidths
profs = [e for e in events if e["event"] == "phase_profile"]
assert profs, "no phase_profile event emitted"
links = profs[-1].get("links") or []
assert links, "phase_profile event carries no per-link entries"
assert all(l["bytes_per_s"] > 0 for l in links), links
assert len(links) >= 2, \
    f"gather lane should time >= 2 rounds on the fixture: {links}"

# (c) lstsq2 confident calibration from ONE profiled solve
pp = rec["phase_profile"]
fit = pp["calibration"]
assert fit["method"] == "lstsq2", fit
assert fit["confident"] is True, fit
assert fit["model"]["per_link"], fit["model"]
assert len(pp["links"]) == len(links), (pp["links"], links)

# (d) the phase sum explains the measured iteration wall within the
# profiler's own stated tolerance (one constant, no drifting copies)
from cuda_mpi_parallel_tpu.telemetry.phasetrace import (
    EXPLAINED_FRACTION_FLOOR as FLOOR,
)

ef = pp["explained_fraction"]
assert FLOOR <= ef <= 2.0 - FLOOR, \
    f"phase sum explains {ef * 100:.1f}% of the measured iteration " \
    f"wall (need {FLOOR * 100:.0f}-{(2.0 - FLOOR) * 100:.0f}%)"
shares = pp["phases"]
print(f"phasetrace gate: halo {shares['halo_s'] * 1e6:.1f}us + spmv "
      f"{shares['spmv_s'] * 1e6:.1f}us + 2x reduction "
      f"{shares['reduction_s'] * 1e6:.1f}us = "
      f"{ef * 100:.1f}% of the measured iteration; "
      f"{len(links)} links fitted; calibration {fit['method']} "
      f"(confident), {len(spans)} measured spans")
PY
echo "phasetrace gate: clean"

# Chaos gate: deterministic fault injection + self-healing end-to-end
# on the committed skewed fixture - a mesh-4 CLI solve with a NaN
# injected into the halo payload at iteration 10 (--inject halo:10)
# and bounded-restart recovery (--recover) must (a) emit schema-valid
# solve_fault + solve_recovery events, (b) finish CONVERGED with the
# recovery record saying so, and (c) produce a solution within 1e-5 of
# the fault-free run's (saved via --save-x).  The no-FaultPlan
# jaxpr-bit-identity proof lives in tests/test_robust.py.
echo "== chaos gate (mesh-4 CLI: --inject halo:10 --recover) =="
JAX_PLATFORMS=cpu python -m cuda_mpi_parallel_tpu.cli \
    --problem mm --file tests/fixtures/skewed_spd_240.mtx --mesh 4 \
    --device cpu --tol 1e-8 --maxiter 500 --json \
    --save-x "$scratch/chaos_clean.npy" \
    > "$scratch/chaos_clean.json"
JAX_PLATFORMS=cpu python -m cuda_mpi_parallel_tpu.cli \
    --problem mm --file tests/fixtures/skewed_spd_240.mtx --mesh 4 \
    --device cpu --tol 1e-8 --maxiter 500 --json \
    --inject halo:10 --recover \
    --save-x "$scratch/chaos_rec.npy" \
    --trace-events "$scratch/chaos_events.jsonl" \
    > "$scratch/chaos_rec.json"
python tools/validate_trace.py "$scratch/chaos_events.jsonl"
python - "$scratch" <<'PY'
import json
import sys

import numpy as np

scratch = sys.argv[1]
with open(f"{scratch}/chaos_rec.json") as f:
    rec = json.load(f)
with open(f"{scratch}/chaos_clean.json") as f:
    clean = json.load(f)
events = [json.loads(ln)
          for ln in open(f"{scratch}/chaos_events.jsonl")
          if ln.strip()]

assert clean["status"] == "CONVERGED", clean["status"]
assert rec["status"] == "CONVERGED", \
    f"injected run did not recover: {rec['status']}"
assert rec["fault"]["site"] == "halo", rec["fault"]
recovery = rec["recovery"]
assert recovery["recovered"] and recovery["restarts"] >= 1, recovery
assert recovery["faults"], recovery
# detection latency: the fault fired at iteration 10, the health
# predicate must catch it within one check_every(=1) block
det = recovery["faults"][0]["iteration"]
assert 10 <= det <= 11, f"breakdown detected at {det}, injected at 10"

faults = [e for e in events if e["event"] == "solve_fault"]
recovs = [e for e in events if e["event"] == "solve_recovery"]
assert faults, "no solve_fault event emitted"
assert any(e["site"] == "halo" for e in faults), faults
assert any(e["action"] == "restart" for e in recovs), recovs
assert any(e["action"] == "recovered" for e in recovs), recovs

x_clean = np.load(f"{scratch}/chaos_clean.npy")
x_rec = np.load(f"{scratch}/chaos_rec.npy")
err = float(np.max(np.abs(x_clean - x_rec)))
assert err < 1e-5, f"recovered solution off by {err}"
print(f"chaos gate: fault at iter 10 detected at iter {det}, "
      f"{recovery['restarts']} restart(s), recovered solution within "
      f"{err:.1e} of the fault-free run; {len(faults)} solve_fault + "
      f"{len(recovs)} solve_recovery events schema-valid")
PY
echo "chaos gate: clean"

# Elastic gate: checkpoint migration across mesh shapes + the
# straggler-watchdog drill, end to end on the committed skewed
# fixture.  Leg A: a mesh-4 checkpointed solve with the shard_slow
# drill armed - the watchdog must detect the (doctored-but-really-
# measured) straggler and emit schema-valid shard_degraded events,
# the elastic loop must checkpoint-now-and-migrate off its mesh
# (solve_migration), then the preemption kills the worker (exit 3,
# state on disk); resuming at mesh 2 must migrate again, finish
# CONVERGED, and land within 1e-5 of a clean mesh-2 run.  Leg B: the
# same 2->4 on the GATHER exchange lane with plan=auto - both wire
# lanes are proven migratable.  Residual continuity across every seam
# is asserted from the solve_migration events' seam_rel_err.
echo "== elastic gate (shard_slow drill 4->3->2 + gather 2->4) =="
JAX_PLATFORMS=cpu python -m cuda_mpi_parallel_tpu.cli \
    --problem mm --file tests/fixtures/skewed_spd_240.mtx --mesh 2 \
    --device cpu --tol 1e-8 --maxiter 500 --json \
    --save-x "$scratch/el_clean.npy" > "$scratch/el_clean.json"
rc=0
JAX_PLATFORMS=cpu python -m cuda_mpi_parallel_tpu.cli \
    --problem mm --file tests/fixtures/skewed_spd_240.mtx --mesh 4 \
    --device cpu --tol 1e-8 --maxiter 500 --json \
    --checkpoint "$scratch/el.npz" --segment-iters 15 --keep-last 2 \
    --elastic --watchdog --inject shard_slow:1:1 --preempt-after 2 \
    --trace-events "$scratch/el_events.jsonl" \
    > "$scratch/el_run1.json" || rc=$?
if [[ "$rc" -ne 3 ]]; then
    echo "elastic gate FAILED: run 1 expected preemption exit 3, got $rc"
    exit 1
fi
JAX_PLATFORMS=cpu python -m cuda_mpi_parallel_tpu.cli \
    --problem mm --file tests/fixtures/skewed_spd_240.mtx --mesh 2 \
    --device cpu --tol 1e-8 --maxiter 500 --json \
    --checkpoint "$scratch/el.npz" --segment-iters 15 --keep-last 2 \
    --elastic --save-x "$scratch/el_x.npy" \
    --trace-events "$scratch/el_events.jsonl" > "$scratch/el_run2.json"
rc=0
JAX_PLATFORMS=cpu python -m cuda_mpi_parallel_tpu.cli \
    --problem mm --file tests/fixtures/skewed_spd_240.mtx --mesh 2 \
    --device cpu --tol 1e-8 --maxiter 500 --json --exchange gather \
    --checkpoint "$scratch/elg.npz" --segment-iters 20 --plan auto \
    --preempt-after 1 \
    --trace-events "$scratch/el_events.jsonl" \
    > "$scratch/el_g1.json" || rc=$?
if [[ "$rc" -ne 3 ]]; then
    echo "elastic gate FAILED: gather leg expected exit 3, got $rc"
    exit 1
fi
JAX_PLATFORMS=cpu python -m cuda_mpi_parallel_tpu.cli \
    --problem mm --file tests/fixtures/skewed_spd_240.mtx --mesh 4 \
    --device cpu --tol 1e-8 --maxiter 500 --json --exchange gather \
    --checkpoint "$scratch/elg.npz" --segment-iters 20 --plan auto \
    --elastic --save-x "$scratch/elg_x.npy" \
    --trace-events "$scratch/el_events.jsonl" > "$scratch/el_g2.json"
python tools/validate_trace.py "$scratch/el_events.jsonl"
python - "$scratch" <<'PY'
import json
import sys

import numpy as np

scratch = sys.argv[1]
events = [json.loads(ln)
          for ln in open(f"{scratch}/el_events.jsonl") if ln.strip()]
with open(f"{scratch}/el_run2.json") as f:
    run2 = json.load(f)
with open(f"{scratch}/el_g2.json") as f:
    g2 = json.load(f)

degs = [e for e in events if e["event"] == "shard_degraded"]
migs = [e for e in events if e["event"] == "solve_migration"]
assert degs, "watchdog emitted no shard_degraded event"
assert any(d["shard"] == 1 and d["phase"] == "spmv" for d in degs), degs
reasons = {m["reason"] for m in migs}
assert "shard_degraded" in reasons, reasons   # in-run trigger fired
assert "resume_mesh_change" in reasons, reasons  # cross-run migration
hops = sorted((m["n_shards_from"], m["n_shards_to"]) for m in migs)
assert (4, 3) in hops, hops     # off the slow shard's mesh
# residual continuity across EVERY seam
for m in migs:
    assert m["seam_rel_err"] < 1e-8, m

assert run2["status"] == "CONVERGED", run2["status"]
assert g2["status"] == "CONVERGED", g2["status"]
x_clean = np.load(f"{scratch}/el_clean.npy")
err_a = float(np.max(np.abs(np.load(f"{scratch}/el_x.npy") - x_clean)))
err_b = float(np.max(np.abs(np.load(f"{scratch}/elg_x.npy") - x_clean)))
assert err_a < 1e-5, f"allgather-leg migrated x off by {err_a}"
assert err_b < 1e-5, f"gather-leg migrated x off by {err_b}"
print(f"elastic gate: {len(degs)} shard_degraded + {len(migs)} "
      f"solve_migration events schema-valid (hops {hops}), both legs "
      f"CONVERGED within {max(err_a, err_b):.1e} of the clean run, "
      f"max seam rel err "
      f"{max(m['seam_rel_err'] for m in migs):.1e}")
PY
echo "elastic gate: clean"

# Ops gate: the network-facing ops plane scraped DURING a live mesh-4
# replay must (a) answer concurrent /metrics + /snapshot + /readyz
# scrapes with valid Prometheus text and a schema-valid typed verdict,
# (b) enforce its bearer token (401 without it, mid-replay), and
# (c) perturb NOTHING: the same saved workload replayed with and
# without --ops-port produces exactly equal per-request outcomes
# (status, iterations, residual norm, error) - sound regardless of
# batch-composition jitter because lanes are bitwise independent of
# their co-batched neighbors (test_many_rhs).  The strict fake-clock
# bitwise batch-log proof lives in
# tests/test_ops_plane.py::TestZeroPerturbation.
echo "== ops gate (mesh-4 CLI serve --ops-port: live scrapes, zero perturbation) =="
JAX_PLATFORMS=cpu python - "$scratch" <<'PY'
import json
import os
import re
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

scratch = sys.argv[1]
env = dict(os.environ, JAX_PLATFORMS="cpu")
base = [sys.executable, "-m", "cuda_mpi_parallel_tpu.cli", "serve",
        "--problem", "mm", "--file", "tests/fixtures/skewed_spd_240.mtx",
        "--mesh", "4", "--max-batch", "8", "--tol", "1e-8",
        "--maxiter", "500", "--json"]

# reference replay: synthesize + save the workload, NO ops plane
ref = subprocess.run(
    base + ["--requests", "24", "--rate", "200", "--seed", "5",
            "--save-workload", f"{scratch}/ops_wl.json"],
    env=env, capture_output=True, text=True)
assert ref.returncode == 0, ref.stderr[-2000:]
off = json.loads(ref.stdout)

# ops replay: the SAME saved workload, plane on an ephemeral port
proc = subprocess.Popen(
    base + ["--workload", f"{scratch}/ops_wl.json",
            "--ops-port", "0", "--ops-token", "lintgate", "--metrics"],
    env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
url = None
stderr_tail = []


def _drain():
    global url
    for ln in proc.stderr:
        stderr_tail.append(ln)
        m = re.search(r"ops plane: (http://\S+)", ln)
        if m and url is None:
            url = m.group(1)


threading.Thread(target=_drain, daemon=True).start()
deadline = time.monotonic() + 120
while url is None and time.monotonic() < deadline \
        and proc.poll() is None:
    time.sleep(0.05)
assert url, "ops plane URL never announced on stderr:\n" \
    + "".join(stderr_tail)[-2000:]


def get(path, token="lintgate"):
    req = urllib.request.Request(url + path)
    if token:
        req.add_header("Authorization", f"Bearer {token}")
    try:
        with urllib.request.urlopen(req, timeout=10) as r:
            return (r.status, r.headers.get("Content-Type", ""),
                    r.read().decode())
    except urllib.error.HTTPError as e:
        return (e.code, e.headers.get("Content-Type", ""),
                e.read().decode())


# auth enforced while the replay is live
assert get("/metrics", token=None)[0] == 401
assert get("/metrics", token="wrong")[0] == 401
st, _, body = get("/usage")
assert st == 404 and "usage metering disabled" in body, (st, body)

rounds = 0
last_metrics = last_snapshot = last_readyz = None
while proc.poll() is None:
    try:
        s1, ct, text = get("/metrics")
        s2, _, snap = get("/snapshot")
        s3, _, ready = get("/readyz")
    except (urllib.error.URLError, OSError):
        break  # plane tore down with the service at replay end
    if s1 == s2 == 200 and s3 in (200, 503):
        assert ct == "text/plain; version=0.0.4; charset=utf-8", ct
        last_metrics, last_snapshot, last_readyz = text, snap, ready
        rounds += 1
    time.sleep(0.1)
out, _ = proc.communicate(timeout=300)
assert proc.returncode == 0, "".join(stderr_tail)[-2000:]
assert rounds >= 3, f"only {rounds} successful scrape rounds mid-replay"

# typed readiness verdict: exact schema
verdict = json.loads(last_readyz)
assert set(verdict) == {"ready", "status", "gates", "failing", "t"}, \
    sorted(verdict)
assert set(verdict["gates"]) \
    == {"accepting", "breakers", "shed", "slo_burn"}
assert verdict["status"] in ("ready", "degraded", "closed")
assert isinstance(verdict["failing"], list)

# every scraped metric family resolves to a registry snapshot entry
snap = json.loads(last_snapshot)
names = set()
for ln in last_metrics.splitlines():
    if ln and not ln.startswith("#"):
        names.add(re.match(r"[A-Za-z_:][A-Za-z0-9_:]*", ln).group(0))
unknown = [n for n in sorted(names)
           if n not in snap
           and not any(n.endswith(suf) and n[:-len(suf)] in snap
                       for suf in ("_bucket", "_sum", "_count",
                                   "_p50", "_p95", "_p99"))]
assert not unknown, f"scraped families missing from snapshot: {unknown}"

# zero perturbation: identical per-request outcomes, plane on vs off
on = json.loads(out)


def outcomes(rec):
    return sorted(
        (r["seed"], r["status"], r.get("iterations"),
         r.get("residual_norm"), r.get("max_abs_error"))
        for r in rec["requests"])


assert outcomes(on) == outcomes(off), \
    "ops plane perturbed the solve stream"
assert on["converged_all"] and off["converged_all"]
print(f"ops gate: {rounds} scrape rounds mid-replay "
      f"({len(names)} metric families), readyz '{verdict['status']}', "
      f"401 without token, {len(on['requests'])} request outcomes "
      f"identical with the plane on vs off")
PY
echo "ops gate: clean"

# Net gate: the authenticated data plane (cli serve --listen) must
# carry a mesh-4 replay of the committed skewed fixture's workload
# with ZERO drift: every live request CONVERGED with
# max_abs_error < 1e-5, and the per-request outcomes
# (status, iterations, residual_norm, max_abs_error) EXACTLY equal
# the no-network replay of the same saved workload.  --max-batch 1
# pins batch composition (every request its own bucket-1 batch), so
# exact equality is sound despite network arrival jitter - the
# bitwise lane contract covers batchmates within a bucket, not a
# request that jitter moves BETWEEN buckets.  Auth is exercised live:
# one spoofed-tenant submit (token B claiming tenant A) must come
# back a typed 403 without reaching admission (the spoofed tenant
# never appears in the server's stats), and an unauthenticated
# submit a 401.  The emitted event stream must stay schema-valid and
# carry one "net" hop span per wire request.
echo "== net gate (mesh-4 CLI serve --listen: loopback replay, auth, zero drift) =="
JAX_PLATFORMS=cpu python - "$scratch" <<'PY'
import json
import os
import re
import signal
import subprocess
import sys
import threading
import time

import numpy as np

scratch = sys.argv[1]
env = dict(os.environ, JAX_PLATFORMS="cpu")
base = [sys.executable, "-m", "cuda_mpi_parallel_tpu.cli", "serve",
        "--problem", "mm", "--file", "tests/fixtures/skewed_spd_240.mtx",
        "--mesh", "4", "--max-batch", "1", "--tol", "1e-8",
        "--maxiter", "500", "--json"]

# reference replay: synthesize + save the workload, NO network
ref = subprocess.run(
    base + ["--requests", "16", "--rate", "200", "--seed", "7",
            "--save-workload", f"{scratch}/net_wl.json"],
    env=env, capture_output=True, text=True)
assert ref.returncode == 0, ref.stderr[-2000:]
off = {r["seed"]: (r["status"], r["iterations"], r["residual_norm"],
                   r["max_abs_error"])
       for r in json.loads(ref.stdout)["requests"]}

# the same operator behind a live data plane
proc = subprocess.Popen(
    base + ["--listen", "--net-tokens", "lintgate:default,spoof:beta",
            "--listen-duration", "600",
            "--trace-events", f"{scratch}/net_events.jsonl"],
    env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
url = None
stderr_tail = []


def _drain():
    global url
    for ln in proc.stderr:
        stderr_tail.append(ln)
        m = re.search(r"data plane: (http://\S+)", ln)
        if m and url is None:
            url = m.group(1)


threading.Thread(target=_drain, daemon=True).start()
deadline = time.monotonic() + 120
while url is None and time.monotonic() < deadline \
        and proc.poll() is None:
    time.sleep(0.05)
assert url, "data plane URL never announced on stderr:\n" \
    + "".join(stderr_tail)[-2000:]

sys.path.insert(0, os.getcwd())
import jax                                                  # noqa: E402

# the reference replay solved in float64 (cli --dtype auto on CPU);
# the RHS this gate rebuilds must be the SAME bytes
jax.config.update("jax_enable_x64", True)
from cuda_mpi_parallel_tpu.models import mmio               # noqa: E402
from cuda_mpi_parallel_tpu.serve import workload as wl      # noqa: E402
from cuda_mpi_parallel_tpu.serve.client import (            # noqa: E402
    NetClient,
    NetError,
)

a = mmio.load_matrix_market("tests/fixtures/skewed_spd_240.mtx",
                            dtype="float64")
requests = wl.load_workload(f"{scratch}/net_wl.json")
cli = NetClient(url, "lintgate", timeout_s=120)

# auth, live: unauthenticated 401; spoofed tenant typed 403
b0, _ = wl.rhs_for(a, requests[0].seed)
try:
    NetClient(url, "wrong").solve("x", b0)
    raise AssertionError("unauthenticated submit was accepted")
except NetError as e:
    assert e.status == 401, (e.status, e.code)
handle_key = cli.handles()[0]["key"]
try:
    NetClient(url, "spoof").submit(handle_key, b0, tenant="default")
    raise AssertionError("spoofed-tenant submit was accepted")
except NetError as e:
    assert e.status == 403 and e.code == "tenant_mismatch", \
        (e.status, e.code)

# the wire replay: same workload, same tolerances, open loop
net_rows = {}
outcomes = []
for r in requests:
    b, x_true = wl.rhs_for(a, r.seed)
    res = cli.solve(handle_key, b, tol=1e-8, timeout_s=300)
    err = float(np.max(np.abs(np.asarray(res.x) - x_true)))
    net_rows[r.seed] = (res.status, res.iterations,
                        res.residual_norm, err)
    outcomes.append(res)

proc.send_signal(signal.SIGTERM)
out, _ = proc.communicate(timeout=300)
assert proc.returncode == 0, "".join(stderr_tail)[-2000:]
rec = json.loads(out)
assert rec["mode"] == "serve-listen", rec.get("mode")

# zero drift: per-request outcomes exactly equal to the no-network
# replay, everything live CONVERGED under the error bar
assert set(net_rows) == set(off)
assert all(row[0] == "CONVERGED" for row in net_rows.values()), \
    {s: r[0] for s, r in net_rows.items() if r[0] != "CONVERGED"}
assert all(row[3] < 1e-5 for row in net_rows.values()), \
    max(r[3] for r in net_rows.values())
drift = {s: (off[s], net_rows[s]) for s in off if off[s] != net_rows[s]}
assert not drift, f"network replay drifted from in-process: {drift}"

# the spoofed tenant never reached admission: no trace of it in the
# server's accounting
tenants = rec["stats"].get("tenants", {})
assert "beta" not in tenants, tenants

# event stream: schema-valid, one net hop span per wire request
events = [json.loads(ln)
          for ln in open(f"{scratch}/net_events.jsonl")
          if ln.strip()]
from cuda_mpi_parallel_tpu.telemetry.events import validate_event  # noqa: E402
for e in events:
    validate_event(e)          # raises on any schema violation
net_spans = [e for e in events
             if e.get("event") == "span" and e.get("name") == "net"]
assert len(net_spans) == len(requests), \
    f"{len(net_spans)} net spans for {len(requests)} wire requests"
assert all(e.get("route") == "/v1/submit" and e.get("bytes_in", 0) > 0
           for e in net_spans)

print(f"net gate: {len(requests)} wire requests, outcomes identical "
      f"to the no-network replay, spoof 403 + unauthenticated 401 "
      f"live, {len(net_spans)} net spans schema-valid, "
      f"{rec['http_requests']} HTTP requests served")
PY
python tools/validate_trace.py "$scratch/net_events.jsonl"
echo "net gate: clean"

# Fleet gate: two serve replicas in SEPARATE processes (each its own
# registry, its own ops plane on an ephemeral port), scraped mid-
# replay by tools/fleet_scrape.py --check, which re-sums every merged
# counter against the per-replica scrapes and exits non-zero on any
# mismatch or unreachable replica.
echo "== fleet gate (2-replica fleet_scrape --check) =="
JAX_PLATFORMS=cpu python - <<'PY'
import json
import os
import re
import subprocess
import sys
import threading
import time

env = dict(os.environ, JAX_PLATFORMS="cpu")


def launch(seed):
    return subprocess.Popen(
        [sys.executable, "-m", "cuda_mpi_parallel_tpu.cli", "serve",
         "--problem", "poisson2d", "--n", "16", "--mesh", "1",
         "--requests", "32", "--rate", "30", "--seed", str(seed),
         "--tol", "1e-8", "--maxiter", "500",
         "--ops-port", "0", "--json"],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.PIPE,
        text=True)


procs = [launch(11), launch(12)]
urls = [None, None]


def drain(i):
    for ln in procs[i].stderr:
        m = re.search(r"ops plane: (http://\S+)", ln)
        if m and urls[i] is None:
            urls[i] = m.group(1)


for i in range(2):
    threading.Thread(target=drain, args=(i,), daemon=True).start()
deadline = time.monotonic() + 120
while not all(urls) and time.monotonic() < deadline \
        and all(p.poll() is None for p in procs):
    time.sleep(0.05)
assert all(urls), f"ops plane URLs never announced: {urls}"

check = subprocess.run(
    [sys.executable, "tools/fleet_scrape.py", urls[0], urls[1],
     "--check", "--json"], env=env, capture_output=True, text=True)
for p in procs:
    p.wait(timeout=300)
assert check.returncode == 0, \
    check.stdout[-2000:] + check.stderr[-2000:]
view = json.loads(check.stdout)
assert all(r["reachable"] for r in view["replicas"]), view["replicas"]
print(f"fleet gate: scraped {len(view['replicas'])} live replicas, "
      f"merged {len(view['merged'])} metrics, every counter re-summed "
      f"exactly (fleet_scrape --check rc 0)")
PY
echo "fleet gate: clean"

echo "== tier-1 tests (ROADMAP.md) =="
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'not slow' --durations=25 --continue-on-collection-errors \
    -p no:cacheprovider \
    -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log \
    | tr -cd . | wc -c)"

# Duration audit: any test in the tier-1 (not-slow) selection that runs
# longer than 120 s belongs behind pytest.mark.slow - unmarked, it eats
# the 870 s budget and silently shrinks DOTS_PASSED for every later
# test (the PR-2 lesson: df64-dist tests at minutes each dropped the
# gate from 302 to 185 passes).  Parsed from the --durations report.
echo "== tier-1 duration audit (unmarked test > 120 s fails) =="
overlong=$(grep -aE '^[0-9]+\.[0-9]+s (call|setup|teardown)' /tmp/_t1.log \
    | awk '$1 + 0 > 120 { print }' || true)
if [[ -n "$overlong" ]]; then
    echo "duration audit FAILED - mark these pytest.mark.slow:"
    echo "$overlong"
    exit 1
fi
echo "duration audit: clean"
exit "$rc"
