#!/usr/bin/env python
"""Deterministic shed-ladder drill (the tools/lint.sh overload gate).

Drives a manual-clock (fake time) SolverService through a scripted
overload at ~2x the reject-rung depth and asserts the
shed-before-collapse ordering contract from the event stream alone:

1. degraded results appear BEFORE any deferral (the ladder widens
   tolerance first),
2. deferrals appear BEFORE any admission rejection (bulk is held
   before anyone is turned away),
3. ZERO accepted-then-TIMEOUT requests for the ``gold`` class (the
   ladder's whole point: overload is answered by shedding the classes
   below gold, never by letting accepted gold work rot in queue),
4. the ladder's level transitions are an ascending 1 -> 2 -> 3 walk
   on the way up (no rung skipped silently on first engagement),
5. the SLO burn-rate tracker fires at least one ``slo_burn`` event:
   the overload's rejection burns the error budget of the flow it
   turned away, and on the fake clock the trip is bit-deterministic.

Every decision is fake-clock + queue-depth driven, so the drill is
bit-deterministic; the solves themselves run for real and must all
come back typed.  The emitted trace lands in the JSONL file named by
argv[1] - tools/lint.sh schema-validates it with validate_trace.py
afterwards, so every new event type (admission / sched_dispatch /
shed) is proven schema-valid in the same run.

Usage: python tools/overload_drill.py EVENTS_OUT.jsonl
"""
from __future__ import annotations

import json
import sys

import numpy as np

sys.path.insert(0, ".")  # repo-root invocation, like validate_trace

from cuda_mpi_parallel_tpu.models import poisson  # noqa: E402
from cuda_mpi_parallel_tpu.serve import (  # noqa: E402
    AdmissionConfig,
    ServiceConfig,
    ShedConfig,
    SolverService,
    TokenBucket,
)
from cuda_mpi_parallel_tpu import telemetry  # noqa: E402
from cuda_mpi_parallel_tpu.telemetry.slo import (  # noqa: E402
    SLOConfig,
    SLOWindow,
)

DEGRADE_DEPTH, DEFER_DEPTH, REJECT_DEPTH = 4, 8, 12


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t


def main() -> int:
    if len(sys.argv) != 2:
        print(__doc__, file=sys.stderr)
        return 2
    events_path = sys.argv[1]
    telemetry.configure(events_path)

    clock = FakeClock()
    a = poisson.poisson_2d_csr(12, 12, dtype=np.float64)
    svc = SolverService(ServiceConfig(
        clock=clock, max_batch=4, max_wait_s=0.01, queue_limit=64,
        maxiter=500,
        # the bucket is generous on purpose: the drill's rejections
        # must come from the ladder's reject rung, not token exhaustion
        admission=AdmissionConfig(
            default=TokenBucket(rate=500.0, burst=200)),
        shed=ShedConfig(degrade_depth=DEGRADE_DEPTH,
                        defer_depth=DEFER_DEPTH,
                        reject_depth=REJECT_DEPTH),
        # a tight window + low sample floor so the single scripted
        # rejection trips a deterministic slo_burn on the fake clock
        slo=SLOConfig(windows=(SLOWindow("fast", 5.0, 2.0),),
                      budget=0.01, min_samples=4)))
    h = svc.register(a)
    rng = np.random.default_rng(7)
    mk_b = lambda: np.asarray(a @ rng.standard_normal(a.shape[0]))  # noqa: E731

    futs, gold_futs = [], []

    def submit(n, slo_class, tenant="hot", deadline_s=None):
        for _ in range(n):
            f = svc.submit(h, mk_b(), tol=1e-8, tenant=tenant,
                           slo_class=slo_class, deadline_s=deadline_s)
            futs.append(f)
            if slo_class == "gold":
                gold_futs.append(f)

    # phase A (t=0): silver past the degrade rung - submits 5 and 6
    # arrive at depth >= 4 and come back degraded
    submit(6, "silver")
    # phase B (t=0): bulk past the defer rung (depth 6..9)
    submit(4, "bulk")
    # first pump after max_wait: the pass notes the held bulk flow
    # (sched_dispatch decision="defer") BEFORE dispatching, then
    # drains - the ladder steps back down as depth falls
    clock.t = 0.011
    svc.pump()
    # phase C (t=0.02): flood to the reject rung and past it - 13
    # non-gold admits climb depth 0..12, the next bulk submit is
    # turned away with a retry_after_s hint
    clock.t = 0.02
    submit(9, "silver")
    submit(4, "bulk", tenant="batch-farm")
    rejected = svc.submit(h, mk_b(), tol=1e-8, tenant="batch-farm",
                          slo_class="bulk")
    futs.append(rejected)
    # gold is still welcome at reject level (and must never TIMEOUT)
    submit(2, "gold", tenant="tenant-b", deadline_s=0.5)
    clock.t = 0.04
    svc.pump()
    svc.drain()
    svc.close()
    telemetry.configure(None)          # flush/close the sink

    # ---- assertions, from the trace + the typed results -------------
    results = [f.result(timeout=30) for f in futs]
    failures = []

    rej = rejected.result()
    if rej.status != "ADMISSION_REJECTED":
        failures.append(f"expected ADMISSION_REJECTED at depth >= "
                        f"{REJECT_DEPTH}, got {rej.status}")
    elif not (rej.retry_after_s and rej.retry_after_s > 0):
        failures.append(f"rejection carries no retry_after_s hint: "
                        f"{rej.retry_after_s}")
    untyped = [r for r in results if not r.status]
    if untyped:
        failures.append(f"{len(untyped)} futures without typed status")
    gold = [f.result() for f in gold_futs]
    gold_timeouts = [r for r in gold if r.status == "TIMEOUT"]
    if gold_timeouts:
        failures.append(f"{len(gold_timeouts)} accepted gold requests "
                        f"timed out - the ladder's core contract")
    if not all(r.status == "CONVERGED" for r in gold):
        failures.append(f"gold statuses: {[r.status for r in gold]}")
    if any(r.degraded for r in gold):
        failures.append("a gold request was tolerance-degraded")

    with open(events_path, encoding="utf-8") as f:
        lines = [json.loads(ln) for ln in f if ln.strip()]
    first = {}
    for i, e in enumerate(lines):
        kind = None
        if e["event"] == "request_enqueued" and e.get("degraded"):
            kind = "degrade"
        elif e["event"] == "sched_dispatch" \
                and e.get("decision") == "defer":
            kind = "defer"
        elif e["event"] == "admission" \
                and e.get("decision") == "rejected":
            kind = "reject"
        if kind is not None and kind not in first:
            first[kind] = i
    for kind in ("degrade", "defer", "reject"):
        if kind not in first:
            failures.append(f"ladder rung {kind!r} never fired")
    if len(first) == 3 and not (
            first["degrade"] < first["defer"] < first["reject"]):
        failures.append(f"ladder fired out of order: {first}")
    gold_to = [e for e in lines if e["event"] == "request_done"
               and e.get("status") == "TIMEOUT"
               and e.get("slo_class") == "gold"]
    if gold_to:
        failures.append(f"{len(gold_to)} gold TIMEOUT events in trace")
    ups = []
    for e in lines:
        if e["event"] == "shed" and e["level"] > (ups[-1] if ups
                                                  else 0):
            ups.append(e["level"])
        if len(ups) == 3:
            break
    if ups[:3] != [1, 2, 3]:
        failures.append(f"ascending shed walk is {ups}, want [1, 2, 3]")
    burns = [e for e in lines if e["event"] == "slo_burn"]
    if not burns:
        failures.append("no slo_burn event: the rejection's budget "
                        "burn never tripped the fast-window threshold")

    if failures:
        for msg in failures:
            print(f"overload drill FAILED: {msg}", file=sys.stderr)
        return 1
    n_def = sum(1 for e in lines if e["event"] == "sched_dispatch"
                and e.get("decision") == "defer")
    n_rej = sum(1 for e in lines if e["event"] == "admission"
                and e.get("decision") == "rejected")
    n_deg = sum(1 for r in results if r.degraded)
    print(f"overload drill: ladder fired in order "
          f"(degrade@{first['degrade']} < defer@{first['defer']} < "
          f"reject@{first['reject']} by trace line), "
          f"{n_deg} degraded / {n_def} defer event(s) / {n_rej} "
          f"rejection(s), retry_after {rej.retry_after_s:.3f}s, "
          f"{len(gold)} gold CONVERGED with 0 timeouts, "
          f"{len(burns)} slo_burn trip(s) "
          f"(worst burn rate {max(b['burn_rate'] for b in burns):.1f}x "
          f"budget), {len(lines)} events")
    return 0


if __name__ == "__main__":
    sys.exit(main())
