#!/usr/bin/env python
"""The 256^3 HBM feasibility report: model-only memory planning.

ROADMAP item 7 asks when the N=256^3 Poisson solve (16.8M unknowns)
stops fitting one device and what pod slice it needs.  This tool
answers with ZERO device work: ``telemetry.memscope.predict_footprint``
prices every (grid, mesh, lane) combination from geometry alone - the
same per-shard accounting the dispatch-time measured twin asserts
byte-exact against device arrays - and classifies each against the
device HBM budget (the planner's reference TPU model, 16 GiB, unless
``--hbm-gib`` overrides).

Lanes swept (the ones whose footprints SCALE differently):

* ``f32 k=1``  - the BASELINE configuration (ring exchange: the
  extended-x buffer shrinks with the mesh);
* ``f32 k=1 allgather`` - the legacy lane whose extended-x block is
  the FULL vector on every shard (it never shrinks with the mesh: the
  lane that forces sharding to help nothing);
* ``df64 k=1`` - double-double storage (every value plane doubled);
* ``f32 k=32`` - the serve tier's widest bucket (the 5-stack working
  set scales by k: the lane where vectors, not the matrix, overflow).

Usage::

    python tools/hbm_plan.py                 # full 64^3/128^3/256^3 sweep
    python tools/hbm_plan.py --n 64          # smoke (lint gate)
    python tools/hbm_plan.py --hbm-gib 8     # smaller device
    python tools/hbm_plan.py --json          # machine-readable

Exit status 0 always (this is a report, not a gate); unfittable lanes
print ``never fits`` with the reason.
"""
from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from cuda_mpi_parallel_tpu.telemetry import memscope  # noqa: E402


def poisson3d_nnz(n: int) -> int:
    """Exact nonzero count of the 7-point N^3 Poisson operator: one
    diagonal per row plus two off-diagonals per interior face in each
    of the three dimensions."""
    return n ** 3 + 6 * n * n * (n - 1)


#: (label, dict of predict_footprint overrides) - the swept lanes
LANES = (
    ("f32 k=1 ring", dict(itemsize=4, n_rhs=1, exchange="ring")),
    ("f32 k=1 allgather", dict(itemsize=4, n_rhs=1,
                               exchange="allgather")),
    ("df64 k=1 ring", dict(itemsize=4, n_rhs=1, exchange="ring",
                           df64=True)),
    ("f32 k=32 ring", dict(itemsize=4, n_rhs=32, exchange="ring")),
    # the cautionary lane: allgather's extended-X block is n x k on
    # EVERY shard regardless of mesh size, so once n*k*itemsize alone
    # exceeds the budget, no pod slice ever fits - the sweep prints
    # "never fits" instead of a mesh size
    ("f32 k=256 allgather", dict(itemsize=4, n_rhs=256,
                                 exchange="allgather")),
)


def fmt_bytes(v) -> str:
    if v is None:
        return "n/a"
    for unit, scale in (("GiB", 2 ** 30), ("MiB", 2 ** 20),
                        ("KiB", 2 ** 10)):
        if abs(v) >= scale:
            return f"{v / scale:.2f} {unit}"
    return f"{int(v)} B"


def sweep(grids, meshes, hbm_bytes):
    """One row per (grid, lane, mesh): worst-shard persistent bytes +
    verdict, plus the smallest fitting mesh per (grid, lane)."""
    rows = []
    minimums = []
    for n in grids:
        n_rows = n ** 3
        nnz = poisson3d_nnz(n)
        for label, kw in LANES:
            for p in meshes:
                if p > n_rows:
                    continue
                fp = memscope.predict_footprint(
                    n=n_rows, n_shards=p, nnz=nnz,
                    hbm_bytes=hbm_bytes, **kw)
                worst = int(fp.persistent_bytes.max())
                rows.append({
                    "grid": f"{n}^3", "n": n_rows, "lane": label,
                    "n_shards": p, "worst_shard_bytes": worst,
                    "classification": fp.classification,
                    "headroom_frac": fp.headroom_frac,
                })
            fit = memscope.smallest_fitting_mesh(
                n=n_rows, budget_bytes=hbm_bytes, nnz=nnz,
                itemsize=kw["itemsize"], n_rhs=kw["n_rhs"],
                exchange=kw["exchange"], df64=kw.get("df64", False))
            minimums.append({
                "grid": f"{n}^3", "lane": label,
                "min_shards": fit,
            })
    return rows, minimums


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="memscope model-only HBM feasibility sweep for "
                    "3-D Poisson grids")
    ap.add_argument("--n", type=int, action="append", default=None,
                    metavar="N",
                    help="grid edge(s) to sweep (N^3 unknowns); "
                         "repeatable; default 64 128 256")
    ap.add_argument("--mesh", type=int, action="append", default=None,
                    metavar="P",
                    help="mesh size(s); repeatable; default "
                         "1 2 4 ... 256")
    ap.add_argument("--hbm-gib", type=float, default=None,
                    help="device HBM budget in GiB (default: the "
                         "planner's reference TPU model, 16)")
    ap.add_argument("--json", action="store_true",
                    help="emit the sweep as JSON instead of the table")
    args = ap.parse_args(argv)

    grids = args.n or [64, 128, 256]
    meshes = args.mesh or [2 ** k for k in range(9)]
    if args.hbm_gib is not None:
        hbm = args.hbm_gib * 2 ** 30
    else:
        from cuda_mpi_parallel_tpu.balance.plan import reference_model

        hbm = reference_model().hbm_bytes
    rows, minimums = sweep(grids, meshes, hbm)

    if args.json:
        print(json.dumps({"hbm_bytes": hbm, "rows": rows,
                          "minimum_mesh": minimums}, indent=2))
        return 0

    print(f"device HBM budget: {fmt_bytes(hbm)} "
          f"(memscope static model; persistent = exact partition "
          f"slots + modeled solver working set)")
    print()
    print(f"{'grid':>6} {'lane':<18} {'shards':>6} "
          f"{'worst shard':>12} {'verdict':<8} {'headroom':>8}")
    for r in rows:
        hr = (f"{r['headroom_frac'] * 100:.1f}%"
              if r["headroom_frac"] is not None else "n/a")
        print(f"{r['grid']:>6} {r['lane']:<18} {r['n_shards']:>6} "
              f"{fmt_bytes(r['worst_shard_bytes']):>12} "
              f"{r['classification']:<8} {hr:>8}")
    print()
    print("minimum pod slice per lane:")
    for m in minimums:
        fit = m["min_shards"]
        verdict = f"{fit} shard(s)" if fit is not None else \
            "never fits (a per-shard term does not shrink with the " \
            "mesh: shrink k or the budget target)"
        print(f"  {m['grid']:>6} {m['lane']:<18} -> {verdict}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
