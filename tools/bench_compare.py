#!/usr/bin/env python
"""Diff two bench result files and gate on regressions.

The bench trajectory was unguarded: ``bench.py`` flushes
``bench_results.json`` (and the driver snapshots ``BENCH_rNN.json``),
but nothing ever COMPARED two of them - a 30% headline regression or a
section whose solve stopped converging would ride into the record
unremarked.  This tool is the gate::

    python tools/bench_compare.py OLD.json NEW.json
    python tools/bench_compare.py --threshold 0.05 OLD.json NEW.json

It accepts both shapes the repo produces:

* ``bench_results.json`` sweeps - a mapping of section name to entry
  (``__``-prefixed bookkeeping and ``*__done``/``*__error`` markers are
  skipped);
* single headline records (``BENCH_rNN.json`` / ``bench.py``'s stdout
  line) - ``{"metric": ..., "value": ...}``, treated as a one-section
  file keyed by the headline section name.

For every section present in BOTH files it prints a per-metric delta
table over the known numeric metrics (throughput, latency,
time-to-tolerance, iteration counts, and the flight-recorder
convergence columns ``decay_rate``/``kappa_estimate``).  Exit status:

* ``1`` if the HEADLINE metric regressed by more than ``--threshold``
  (default 10%), or any shared section's ``converged`` flipped
  true -> false, or any shared lower-is-better metric listed in
  ``GATED_METRICS`` regressed past the threshold;
* ``2`` on unreadable/shapeless input;
* ``0`` otherwise (including "nothing comparable" - an empty
  intersection is reported, not failed: early trajectories legitimately
  share no sections).
"""
from __future__ import annotations

import argparse
import json
import sys

# The single gate metric of the repo (bench.py headline): higher-better
# iters/s on the 1M-unknown 2D Poisson stencil solve.
HEADLINE_KEY = "poisson2d_1M_stencil"

#: metric name -> True when higher is better.  Anything not listed is
#: reported in the table but never gates.
METRIC_DIRECTION = {
    "value": True,
    "iters_per_sec": True,
    "vs_baseline": True,
    "us_per_iter": False,
    "time_to_tol_s": False,
    "time_to_tol_s_derived": False,
    "elapsed_s": False,
    "iterations": False,
    # flight-recorder convergence columns: decay_rate is log10||r|| per
    # iteration (MORE NEGATIVE is better -> lower-is-better);
    # kappa_estimate is a conditioning ESTIMATE, reported but ungated
    # (it tracks the problem, not the code).
    "decay_rate": False,
    "flight.decay_rate": False,
    "kappa_estimate": None,
    "flight.kappa_estimate": None,
    # roofline columns (PR 4): achieved-vs-peak efficiency is reported
    # but never gates - it tracks tunnel weather and machine-model
    # calibration as much as code.
    "efficiency_pct": None,
    "roofline.efficiency_pct": None,
    "arithmetic_intensity": None,
    "roofline.arithmetic_intensity": None,
    # partition-planner columns (PR 5): predicted stall factors of the
    # even vs planned split (balance.plan_partition).  Reported, never
    # gated - they track the bench problem's structure, not the code;
    # old result files simply lack them (rendered n/a).
    "planner.nnz_imbalance_even": None,
    "planner.nnz_imbalance_planned": None,
    "planner.plan_time_s": None,
    # runtime-calibration / replan columns (PR 6,
    # telemetry.calibrate + solve_sequence): the calibrated model's
    # predicted replan gain, the measured gather slowdown, and the
    # model-error (drift) % of the final sequence solve.  Reported,
    # never gated - drift tracks host/tunnel weather as much as code,
    # and pre-PR-6 files simply lack them (rendered n/a).
    "replan.predicted_gain_pct": None,
    "replan.drift_pct": None,
    "replan.gather_slowdown": None,
    "drift_pct": None,
    # gather-exchange columns (PR 7, parallel.exchange): the measured
    # per-iteration interconnect bytes of each halo wire and the
    # gather schedule's pad-to-max-neighbor fraction.  Reported, never
    # gated - wire bytes track the bench problem's coupling structure
    # and mesh size, not the code; pre-PR-7 files simply lack them
    # (rendered n/a).
    "comm.wire_bytes_per_iter": None,
    "halo.padding_fraction": None,
    "exchange.allgather_wire_bytes_per_iter": None,
    "exchange.gather_wire_bytes_per_iter": None,
    "exchange.allgather_iters_per_sec": None,
    "exchange.gather_iters_per_sec": None,
    "exchange.padding_fraction": None,
    # many-RHS columns (PR 8, solver.many): aggregate lane-iterations
    # per second at k = 1/8/32, the sequential-loop baseline, block-CG
    # vs masked-batched iteration counts, and the per-solve wire
    # amortization of a batched mesh solve.  Reported, never gated -
    # throughput tracks host weather, iteration counts track the bench
    # problem; pre-PR-8 files simply lack them (rendered n/a).
    "rhs_iters_per_sec_k1": None,
    "rhs_iters_per_sec_k8": None,
    "rhs_iters_per_sec_k32": None,
    "sequential_rhs_iters_per_sec_k8": None,
    "amortization_x_k8": None,
    "batched_iterations_k8": None,
    "block_iterations_k8": None,
    "block_rhs_iters_per_sec_k8": None,
    "many_wire.wire_bytes_per_solve_batched": None,
    "many_wire.wire_bytes_per_solve_sequential8": None,
    "many_wire.wire_amortization_x": None,
    # solver-service columns (PR 10, serve/): offered-load replay
    # throughput, latency percentiles, batch occupancy and the
    # service-vs-max_batch=1 speedup.  Reported, never gated - replay
    # walls track host scheduling weather as much as code; pre-PR-10
    # files simply lack them (rendered n/a).
    "serve.solved_rhs_per_sec": None,
    "serve.unbatched_rhs_per_sec": None,
    "serve.speedup_vs_unbatched": None,
    "serve.p50_latency_s": None,
    "serve.p95_latency_s": None,
    "serve.p99_latency_s": None,
    "serve.occupancy_mean": None,
    "serve.padding_fraction": None,
    "serve.timeouts": None,
    # overload-serving columns (serve.admission + serve.sched): the
    # saturation ramp's measured capacity, max sustained in-SLO
    # goodput, and the 2x-overload goodput retention.  RETENTION
    # GATES (higher-better, listed in GATED_METRICS): it is the one
    # dimensionless number that says the service degrades instead of
    # collapsing, and it divides out host weather (both runs ride the
    # same host).  The rest are reported, never gated - absolute
    # rates track host scheduling weather; pre-overload files simply
    # lack them (rendered n/a).
    "serve_overload.probe_capacity_rhs_per_sec": None,
    "serve_overload.max_sustained_rhs_per_sec": None,
    "serve_overload.goodput_retention_2x": True,
    "serve_overload.gold_p99_s": None,
    "serve_overload.gold_timeouts_2x": None,
    "serve_overload.rejected_2x": None,
    "serve_overload.degraded_2x": None,
    "serve_overload.timeouts_2x": None,
    "serve_overload.shed_transitions_2x": None,
    "serve_overload.workers": None,
    # measured phase-profile columns (PR 11, telemetry.phasetrace):
    # per-phase seconds-per-iteration shares, the measured per-shard
    # SpMV stall factor, and the explained-fraction residual of the
    # phase decomposition.  Reported, never gated - phase walls track
    # host scheduling weather as much as code; pre-PR-11 files simply
    # lack them (rendered n/a).
    "phase.halo_s_per_iter": None,
    "phase.spmv_s_per_iter": None,
    "phase.reduction_s_per_iter": None,
    "phase.halo_share": None,
    "phase.spmv_share": None,
    "phase.reduction_share": None,
    "phase.spmv_stall_factor": None,
    "phase.explained_fraction": None,
    # robustness columns (robust/): the armed-FaultPlan in-loop
    # overhead, breakdown detection latency, and wall time/overhead of
    # an injected-fault recovery on the mesh-4 fixture.  Reported,
    # never gated - overheads track host scheduling weather, and
    # pre-robustness files simply lack them (rendered n/a).
    "robust.guarded_iters_per_sec": None,
    "robust.armed_iters_per_sec": None,
    "robust.armed_overhead_pct": None,
    "robust.detection_latency_iters": None,
    "robust.time_to_recover_s": None,
    "robust.recovery_overhead_pct": None,
    # elastic-migration columns (robust.elastic): wall to recover a
    # preempted mesh-4 resumable solve by migrating its checkpoint to
    # mesh 2, and the interrupted+migrated total vs the uninterrupted
    # resumable solve.  Reported, never gated - both walls include
    # compile and track host scheduling weather; pre-elastic files
    # simply lack them (rendered n/a).
    "elastic.time_to_recover_s": None,
    "elastic.migration_overhead_pct": None,
    "elastic.max_abs_dx": None,
    # Krylov-recycling columns (solver.recycle): iters/solve of the
    # first vs final solve of a replayed fresh-RHS workload on the
    # skewed fixture and a Poisson operator, the saved fraction, and
    # the harvest's host overhead vs solve wall.  Reported, never
    # gated - iteration counts track the bench problem's spectrum and
    # the harvest overhead tracks host weather; pre-recycling files
    # simply lack them (rendered n/a).
    "recycle.first_solve_iters_skewed": None,
    "recycle.final_solve_iters_skewed": None,
    "recycle.iters_saved_pct_skewed": None,
    "recycle.first_solve_iters_poisson": None,
    "recycle.final_solve_iters_poisson": None,
    "recycle.iters_saved_pct_poisson": None,
    "recycle.harvest_overhead_pct_skewed": None,
    "recycle.harvest_overhead_pct_poisson": None,
    # request-observatory columns (telemetry.tracing + serve.usage):
    # the tracing-on overhead % of a serve replay, span volume, and
    # the metered per-batch usage totals of the traced replay.
    # Reported, never gated - the overhead rides replay walls (host
    # scheduling weather) and the usage totals track the bench
    # workload, not the code; pre-observatory files simply lack them
    # (rendered n/a).
    "trace.overhead_pct": None,
    "trace.spans_per_request": None,
    "trace.traced_rhs_per_sec": None,
    "usage.device_seconds": None,
    "usage.wire_bytes": None,
    "usage.device_seconds_per_request": None,
    # device-memory observatory columns (telemetry.memscope): predicted
    # worst-shard persistent bytes (with its measured device-array
    # twin), the jaxpr-liveness transient peak, headroom % against the
    # detected device memory, and the headline row's modeled working
    # set / allocator peak.  Reported, never gated - footprints track
    # the bench problem's geometry and the host's memory size, not the
    # code; pre-memscope files simply lack them (rendered n/a).
    "mem.persistent_bytes_worst": None,
    "mem.matrix_bytes_worst": None,
    "mem.measured_matrix_bytes": None,
    "mem.jaxpr_peak_bytes": None,
    "mem.peak_bytes": None,
    "mem.headroom_pct": None,
    "mem.device_peak_bytes": None,
    "mem.model_working_set_bytes": None,
    # ops-plane column (serve.ops): serve-replay wall overhead % with
    # a scraper hammering /metrics + /readyz during the replay vs the
    # same workload unscraped.  Reported, never gated - it rides host
    # scheduling weather (the contract that scrapes change no ANSWER
    # is the ops lint gate's job, not a wall-clock diff's); pre-ops
    # files simply lack it (rendered n/a).
    "ops.scrape_overhead_pct": None,
    # data-plane columns (serve.net): the serve replay driven THROUGH
    # the loopback network plane (bearer auth + wire codec both ways)
    # vs in-process submit on the same service config.  Reported,
    # never gated - loopback RPC walls ride host scheduling weather
    # (the contract that wire answers are bit-exact is the net lint
    # gate's job, not a wall-clock diff's); pre-net files simply lack
    # them (rendered n/a).
    "net.networked_rhs_per_sec": None,
    "net.wire_overhead_pct": None,
    "net.networked_solved": None,
}

#: metrics (besides the headline) whose per-section regression past the
#: threshold fails the gate.  Deliberately the wall-clock/convergence
#: ones - a slower solve or one needing more iterations to tolerance is
#: a real regression even when the headline row survived - plus the
#: overload bench's goodput retention at 2x (dimensionless, host-
#: weather-divided: a service that starts collapsing under overload is
#: a regression no throughput number can buy back).
GATED_METRICS = ("time_to_tol_s", "iterations",
                 "serve_overload.goodput_retention_2x")


def load_sections(path: str) -> dict:
    """Normalize one results file into ``{section: {metric: value}}``."""
    with open(path) as f:
        data = json.load(f)
    if not isinstance(data, dict):
        raise ValueError(f"{path}: expected a JSON object at top level")
    if "metric" in data and "value" in data:
        # single headline record (BENCH_rNN.json / bench.py stdout)
        return {HEADLINE_KEY: data}
    sections = {k: v for k, v in data.items()
                if isinstance(v, dict)
                and not k.startswith("__")
                and not k.endswith("__done")
                and not k.endswith("__error")}
    if not sections:
        raise ValueError(f"{path}: no bench sections found (empty sweep?)")
    return sections


#: nested dicts flattened one level into dotted metric names
_NESTED = {
    "flight": ("decay_rate", "kappa_estimate"),
    "roofline": ("efficiency_pct", "arithmetic_intensity"),
    "planner": ("nnz_imbalance_even", "nnz_imbalance_planned",
                "plan_time_s"),
    "replan": ("predicted_gain_pct", "drift_pct", "gather_slowdown"),
    "comm": ("wire_bytes_per_iter",),
    "halo": ("padding_fraction",),
    "exchange": ("allgather_wire_bytes_per_iter",
                 "gather_wire_bytes_per_iter",
                 "allgather_iters_per_sec", "gather_iters_per_sec",
                 "padding_fraction"),
    "many_wire": ("wire_bytes_per_solve_batched",
                  "wire_bytes_per_solve_sequential8",
                  "wire_amortization_x"),
    "serve": ("solved_rhs_per_sec", "unbatched_rhs_per_sec",
              "speedup_vs_unbatched", "p50_latency_s", "p95_latency_s",
              "p99_latency_s", "occupancy_mean", "padding_fraction",
              "timeouts"),
    "serve_overload": ("probe_capacity_rhs_per_sec",
                       "max_sustained_rhs_per_sec",
                       "goodput_retention_2x", "gold_p99_s",
                       "gold_timeouts_2x", "rejected_2x",
                       "degraded_2x", "timeouts_2x",
                       "shed_transitions_2x", "workers"),
    "phase": ("halo_s_per_iter", "spmv_s_per_iter",
              "reduction_s_per_iter", "halo_share", "spmv_share",
              "reduction_share", "spmv_stall_factor",
              "explained_fraction"),
    "robust": ("guarded_iters_per_sec", "armed_iters_per_sec",
               "armed_overhead_pct", "detection_latency_iters",
               "time_to_recover_s", "recovery_overhead_pct"),
    "elastic": ("time_to_recover_s", "migration_overhead_pct",
                "max_abs_dx"),
    "recycle": ("first_solve_iters_skewed", "final_solve_iters_skewed",
                "iters_saved_pct_skewed", "first_solve_iters_poisson",
                "final_solve_iters_poisson", "iters_saved_pct_poisson",
                "harvest_overhead_pct_skewed",
                "harvest_overhead_pct_poisson"),
    "mem": ("persistent_bytes_worst", "matrix_bytes_worst",
            "measured_matrix_bytes", "jaxpr_peak_bytes", "peak_bytes",
            "headroom_pct", "device_peak_bytes",
            "model_working_set_bytes"),
    "ops": ("scrape_overhead_pct",),
    "net": ("networked_rhs_per_sec", "wire_overhead_pct",
            "networked_solved"),
}


def _metrics(entry: dict) -> dict:
    """Flatten one section entry to its comparable numeric metrics
    (one level of nesting for the ``flight``/``roofline`` summaries).
    Tolerant of any row shape: a pre-PR-3 entry simply contributes
    fewer metrics (the caller renders the gap as "n/a")."""
    out = {}
    if not isinstance(entry, dict):
        return out
    for key, val in entry.items():
        if key in _NESTED and isinstance(val, dict):
            for fk in _NESTED[key]:
                fv = val.get(fk)
                if isinstance(fv, (int, float)) \
                        and not isinstance(fv, bool):
                    out[f"{key}.{fk}"] = float(fv)
            continue
        if key in METRIC_DIRECTION and isinstance(val, (int, float)) \
                and not isinstance(val, bool):
            out[key] = float(val)
    return out


def _fmt(v: float) -> str:
    return f"{v:.6g}"


def compare(old: dict, new: dict, threshold: float,
            out=sys.stdout) -> int:
    """Print the delta table; return the exit status (0 ok / 1 gate)."""
    shared = [k for k in old if k in new]
    only_old = sorted(set(old) - set(new))
    only_new = sorted(set(new) - set(old))
    failures = []

    rows = []
    warnings = []
    for section in shared:
        m_old, m_new = _metrics(old[section]), _metrics(new[section])
        # union, not intersection: a metric one side lacks (an old-
        # format row predating the flight/iterations columns, e.g.
        # bench_results_r03.json) renders as an "n/a" cell and a
        # warning - a silent drop reads as "nothing changed", and a
        # KeyError traceback is how this tool used to greet history
        missing_old = sorted(k for k in m_new if k not in m_old)
        if missing_old:
            warnings.append(
                f"{section}: OLD row predates metric(s) "
                f"{', '.join(missing_old)} (old-format file); shown "
                f"as n/a, not compared")
        missing_new = sorted(k for k in m_old if k not in m_new)
        if missing_new:
            warnings.append(
                f"{section}: NEW row lacks metric(s) "
                f"{', '.join(missing_new)}; shown as n/a, not "
                f"compared")
        for name in sorted(set(m_old) | set(m_new)):
            a, b = m_old.get(name), m_new.get(name)
            if a is None or b is None:
                rows.append((section, name, a, b, None))
                continue
            delta = None if a == 0 else (b - a) / abs(a)
            rows.append((section, name, a, b, delta))
            higher_better = METRIC_DIRECTION.get(
                name, METRIC_DIRECTION.get(name.split(".", 1)[-1]))
            if higher_better is None or delta is None:
                continue
            regressed = (delta < -threshold if higher_better
                         else delta > threshold)
            gate = (section == HEADLINE_KEY and name == "value") \
                or name in GATED_METRICS
            if regressed and gate:
                failures.append(
                    f"{section}.{name}: {_fmt(a)} -> {_fmt(b)} "
                    f"({delta:+.1%}, threshold {threshold:.0%})")
        # convergence flip: a section that stopped converging is a
        # regression no throughput number can buy back
        if old[section].get("converged") is True \
                and new[section].get("converged") is False:
            failures.append(f"{section}: converged true -> false")
        cls_old = (old[section].get("flight") or {}).get("classification")
        cls_new = (new[section].get("flight") or {}).get("classification")
        if cls_old == "CONVERGED" and cls_new not in (None, "CONVERGED"):
            failures.append(f"{section}: solve health CONVERGED -> "
                            f"{cls_new}")

    if rows:
        w_sec = max(len("section"), max(len(r[0]) for r in rows))
        w_met = max(len("metric"), max(len(r[1]) for r in rows))
        print(f"{'section':<{w_sec}}  {'metric':<{w_met}}  "
              f"{'old':>12}  {'new':>12}  {'delta':>8}", file=out)
        for section, name, a, b, delta in rows:
            d = "n/a" if delta is None else f"{delta:+.1%}"
            fa = "n/a" if a is None else _fmt(a)
            fb = "n/a" if b is None else _fmt(b)
            print(f"{section:<{w_sec}}  {name:<{w_met}}  "
                  f"{fa:>12}  {fb:>12}  {d:>8}", file=out)
    else:
        print("no comparable metrics in shared sections", file=out)
    if only_old:
        print(f"only in OLD: {', '.join(only_old)}", file=out)
    if only_new:
        print(f"only in NEW: {', '.join(only_new)}", file=out)
    for w in warnings:
        print(f"warning: {w}", file=out)

    if failures:
        print("\nREGRESSIONS:", file=out)
        for f in failures:
            print(f"  {f}", file=out)
        return 1
    print("\nno gated regressions", file=out)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="diff two bench_results/BENCH_*.json files and exit "
                    "nonzero on a gated regression")
    ap.add_argument("old", help="baseline results file")
    ap.add_argument("new", help="candidate results file")
    ap.add_argument("--threshold", type=float, default=0.10,
                    help="relative regression that fails the gate "
                         "(default 0.10 = 10%%)")
    args = ap.parse_args(argv)
    if not 0.0 < args.threshold < 10.0:
        print(f"error: implausible --threshold {args.threshold}",
              file=sys.stderr)
        return 2
    try:
        old = load_sections(args.old)
        new = load_sections(args.new)
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    return compare(old, new, args.threshold)


if __name__ == "__main__":
    sys.exit(main())
