#!/usr/bin/env python
"""Open-loop network load generator for the serve-tier data plane.

Replays a saved workload file (``serve.workload``) against a REMOTE
``serve.net`` endpoint - the drill tool for ROADMAP item 2's
two-replica acceptance, and the network twin of
``cli.py serve --workload``:

* arrivals fire at their recorded offsets on the real clock (open
  loop: offered load is the independent variable - arrivals never
  wait for results, so a past-capacity drill actually overloads);
* right-hand sides are rebuilt locally from each request's seed
  against the same operator the server registered (``rhs_for``:
  ``b = A @ x_true(seed)``), so every answer is verified against a
  known solution without shipping vectors in the workload file;
* each tenant tag in the workload submits through its own bearer
  token (``--tokens token:tenant,...``) - the server DERIVES tenant
  identity from the credential, so a drill cannot spoof its way past
  admission any more than a real client can;
* outcomes are classified by ``serve.workload.summarize_replay`` -
  the same definition the in-process replay and the bench use, so
  "goodput" means one thing repo-wide.

Examples::

    python tools/loadgen.py --url http://127.0.0.1:8780 \
        --workload drill.json --problem poisson2d --n 32 \
        --tokens tok1:acme,tok2:beta --json

    python tools/loadgen.py --url http://replica-0:8780 \
        --workload saturation.json --problem mm \
        --file tests/fixtures/skewed_spd_240.mtx --time-scale 0.5
"""
from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

sys.path.insert(
    0, __import__("os").path.dirname(
        __import__("os").path.dirname(
            __import__("os").path.abspath(__file__))))

from cuda_mpi_parallel_tpu.serve.client import NetClient, NetError  # noqa: E402
from cuda_mpi_parallel_tpu.serve.sched import DEFAULT_CLASSES, class_table  # noqa: E402
from cuda_mpi_parallel_tpu.serve import workload as wl  # noqa: E402


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="loadgen",
        description="open-loop network load generator for the "
                    "serve.net data plane")
    p.add_argument("--url", required=True,
                   help="data-plane base URL, e.g. "
                        "http://127.0.0.1:8780")
    p.add_argument("--workload", required=True, metavar="PATH",
                   help="saved workload file (serve.workload JSON)")
    p.add_argument("--tokens", required=True, metavar="SPEC",
                   help="bearer tokens by tenant: 'token:tenant' "
                        "entries, comma-separated; requests tagged "
                        "with a tenant submit through its token, "
                        "untagged requests through the FIRST entry")
    p.add_argument("--problem", default="poisson2d",
                   choices=["poisson2d", "mm"],
                   help="operator family the server registered (for "
                        "local RHS construction)")
    p.add_argument("--n", type=int, default=32,
                   help="grid extent per axis (poisson2d)")
    p.add_argument("--file", default=None,
                   help="Matrix Market path (--problem mm)")
    p.add_argument("--dtype", default="float64",
                   choices=["float32", "float64"])
    p.add_argument("--handle", default=None, metavar="KEY",
                   help="handle key to submit against (default: the "
                        "plane's only handle, via GET /v1/handles)")
    p.add_argument("--tol", type=float, default=1e-7)
    p.add_argument("--deadline", type=float, default=None,
                   metavar="S",
                   help="per-request deadline for requests the "
                        "workload does not tag")
    p.add_argument("--time-scale", type=float, default=1.0,
                   dest="time_scale", metavar="F",
                   help="multiply every arrival offset by F "
                        "(0.5 = drill at twice the recorded rate)")
    p.add_argument("--timeout", type=float, default=300.0,
                   help="per-result collection timeout, seconds")
    p.add_argument("--json", action="store_true",
                   help="emit one JSON record instead of text")
    return p


def _parse_tokens(spec: str):
    """'token:tenant,...' -> ordered {tenant: token}."""
    out = {}
    for i, entry in enumerate(str(spec).split(",")):
        entry = entry.strip()
        if not entry:
            continue
        parts = entry.split(":")
        if len(parts) != 2 or not parts[0] or not parts[1]:
            raise SystemExit(f"--tokens entry {i} must be "
                             f"'token:tenant', got {entry!r}")
        out[parts[1]] = parts[0]
    if not out:
        raise SystemExit("--tokens names no tokens")
    return out


def _build_operator(args):
    from cuda_mpi_parallel_tpu.models import mmio, poisson

    if args.problem == "mm":
        if not args.file:
            raise SystemExit("--problem mm requires --file")
        return mmio.load_matrix_market(args.file, dtype=args.dtype)
    return poisson.poisson_2d_csr(args.n, args.n, dtype=args.dtype)


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.time_scale <= 0:
        raise SystemExit(f"--time-scale must be > 0, got "
                         f"{args.time_scale}")
    tokens = _parse_tokens(args.tokens)
    default_tenant = next(iter(tokens))
    requests = wl.load_workload(args.workload)
    a = _build_operator(args)

    clients = {tenant: NetClient(args.url, token)
               for tenant, token in tokens.items()}
    for r in requests:
        if r.tenant is not None and r.tenant not in clients:
            raise SystemExit(
                f"workload tags tenant {r.tenant!r} but --tokens "
                f"names only {sorted(clients)}")

    first = clients[default_tenant]
    handle_key = args.handle
    if handle_key is None:
        handles = first.handles()
        if len(handles) != 1:
            raise SystemExit(
                f"plane serves {len(handles)} handle(s); pick one "
                f"with --handle "
                f"({[h['key'] for h in handles]})")
        handle_key = handles[0]["key"]

    # pre-build every RHS so the arrival loop does nothing but sleep
    # and submit (same rule as the in-process replay)
    prepared = [wl.rhs_for(a, r.seed, dtype=np.dtype(args.dtype))[0]
                for r in requests]

    t0 = time.monotonic()
    outcomes = []                    # str net_id | RequestResult | None
    owners = []                      # which client collects it
    for r, b in zip(requests, prepared):
        delay = (t0 + r.t * args.time_scale) - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        cli = clients[r.tenant or default_tenant]
        owners.append(cli)
        try:
            outcomes.append(cli.submit(
                handle_key, b,
                tol=r.tol if r.tol is not None else args.tol,
                deadline_s=(r.deadline_s if r.deadline_s is not None
                            else args.deadline),
                slo_class=r.slo_class,
                retry=False))        # a rejection is an OUTCOME here
        except NetError as e:
            if e.code == "queue_full":
                outcomes.append(None)   # hard backpressure shed
            else:
                raise SystemExit(f"submit failed: {e} "
                                 f"(HTTP {e.status})")
    results = []
    for cli, out in zip(owners, outcomes):
        if isinstance(out, str):
            results.append(cli.result(out, timeout_s=args.timeout))
        else:
            results.append(out)
    window_s = time.monotonic() - t0

    summary = wl.summarize_replay(
        requests, results, window_s,
        classes=class_table(DEFAULT_CLASSES))

    by_tenant = {}
    for r, res in zip(requests, results):
        row = by_tenant.setdefault(
            r.tenant or default_tenant,
            {"offered": 0, "solved": 0, "rejected": 0})
        row["offered"] += 1
        if res is None or res.status == "ADMISSION_REJECTED":
            row["rejected"] += 1
        elif res.converged and not res.timed_out:
            row["solved"] += 1

    record = {
        "mode": "loadgen",
        "url": args.url,
        "workload": args.workload,
        "handle": handle_key,
        "time_scale": args.time_scale,
        "window_s": summary.window_s,
        "offered": summary.offered,
        "solved": summary.solved,
        "in_slo": summary.in_slo,
        "timeouts": summary.timeouts,
        "rejected": summary.rejected,
        "errors": summary.errors,
        "degraded": summary.degraded,
        "goodput_rhs_per_sec": summary.goodput_rhs_per_sec,
        "by_class": summary.by_class,
        "by_tenant": by_tenant,
    }
    if args.json:
        json.dump(record, f := sys.stdout, sort_keys=True)
        f.write("\n")
    else:
        print(f"== loadgen: {args.workload} -> {args.url} ==")
        print(f"offered {summary.offered} in {summary.window_s:.3f}s "
              f"| solved {summary.solved} | in-SLO {summary.in_slo} "
              f"| goodput {summary.goodput_rhs_per_sec:.1f} rhs/s")
        print(f"timeouts {summary.timeouts} | rejected "
              f"{summary.rejected} | errors {summary.errors} | "
              f"degraded {summary.degraded}")
        for name in sorted(summary.by_class):
            row = summary.by_class[name]
            p99 = row["p99_latency_s"]
            print(f"  class {name:<8} offered {row['offered']:>4} "
                  f"in-SLO {row['in_slo']:>4} "
                  f"timeouts {row['timeouts']:>4} "
                  f"rejected {row['rejected']:>4} "
                  f"p99 {p99 * 1e3:.1f} ms" if p99 is not None else
                  f"  class {name:<8} offered {row['offered']:>4} "
                  f"in-SLO {row['in_slo']:>4} "
                  f"timeouts {row['timeouts']:>4} "
                  f"rejected {row['rejected']:>4} p99 n/a")
        for tenant in sorted(by_tenant):
            row = by_tenant[tenant]
            print(f"  tenant {tenant:<8} offered {row['offered']:>4} "
                  f"solved {row['solved']:>4} "
                  f"rejected {row['rejected']:>4}")
    # a drill is green when everything offered either solved or was
    # HONESTLY shed; silent loss (errors) is the failure
    return 0 if summary.errors == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
