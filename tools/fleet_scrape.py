#!/usr/bin/env python
"""Scrape M live ops planes and emit one fleet view.

The cross-process half of the ops plane (ISSUE 19 / ROADMAP item 2):
each replica's ``/snapshot`` is one registry snapshot and its
``/readyz`` is one routing verdict; this tool merges the snapshots
through ``telemetry.fleet`` (counters summed exactly, histogram
buckets summed bucket-wise so quantiles stay correct, gauges kept
per-replica) and prints the aggregate plus a per-replica readiness
table::

    python tools/fleet_scrape.py http://127.0.0.1:9100 \\
        http://127.0.0.1:9101 --token sekrit

    replica                   ready  status     failing gates
    http://127.0.0.1:9100     yes    ready      -
    http://127.0.0.1:9101     NO     degraded   breakers

``--json`` dumps ``{"merged": ..., "replicas": ...}`` for machine
consumers; ``--watch SECONDS`` rescrapes forever (the readiness table
flips a replica within one interval of its breaker opening);
``--check`` re-verifies the merge algebra against the live scrape
(every merged counter equals the sum of the per-replica counters,
exactly) and exits 1 on any violation or unreachable replica - the
lint-gate mode.

Read-only, stdlib-only (urllib), and safe to run against a serving
fleet: scrapes are host-side reads on the replica side.
"""
from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.error
import urllib.request

sys.path.insert(0, ".")  # repo-root invocation, like tools/bench_compare

from cuda_mpi_parallel_tpu.telemetry import fleet  # noqa: E402


def _get_json(url: str, token=None, timeout: float = 5.0):
    req = urllib.request.Request(url)
    if token:
        req.add_header("Authorization", f"Bearer {token}")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read().decode("utf-8"))
    except urllib.error.HTTPError as e:
        # a 503 /readyz is a VERDICT, not a transport failure
        try:
            return e.code, json.loads(e.read().decode("utf-8"))
        except Exception:
            return e.code, None


def scrape_replica(base: str, token=None, timeout: float = 5.0) -> dict:
    """One replica's ``/snapshot`` + ``/readyz``, with transport
    errors folded into a NOT-ready verdict (an unreachable replica is
    not ready - that is the router's whole question)."""
    base = base.rstrip("/")
    out = {"url": base, "reachable": True, "snapshot": None,
           "readyz": None, "ready": False, "status": "unreachable",
           "failing": ["unreachable"]}
    try:
        st, snap = _get_json(base + "/snapshot", token, timeout)
        if st != 200 or not isinstance(snap, dict):
            raise urllib.error.URLError(f"/snapshot -> HTTP {st}")
        st, verdict = _get_json(base + "/readyz", token, timeout)
        if verdict is None or "ready" not in verdict:
            raise urllib.error.URLError(f"/readyz -> HTTP {st}")
    except Exception as e:  # noqa: BLE001 - fold ANY failure to not-ready
        out["reachable"] = False
        out["error"] = str(e)
        return out
    out.update(snapshot=snap, readyz=verdict,
               ready=bool(verdict["ready"]),
               status=str(verdict.get("status", "?")),
               failing=list(verdict.get("failing", [])))
    return out


def readiness_table(replicas) -> str:
    width = max([len(r["url"]) for r in replicas] + [len("replica")])
    lines = [f"{'replica':<{width}}  ready  status       failing gates"]
    for r in replicas:
        failing = ", ".join(r["failing"]) if r["failing"] else "-"
        lines.append(f"{r['url']:<{width}}  "
                     f"{'yes' if r['ready'] else 'NO ':<5}  "
                     f"{r['status']:<11}  {failing}")
    return "\n".join(lines)


def check_merge(replicas, merged) -> list:
    """Re-verify the merge against the scrape it came from: every
    merged counter value must equal the float sum of the per-replica
    series, exactly (same additions a single registry would have
    done).  Returns a list of violation strings (empty = pass)."""
    bad = []
    for name, entry in merged.items():
        if entry.get("kind") != "counter":
            continue
        for series in entry["series"]:
            key = tuple(sorted(series["labels"].items()))
            total = 0.0
            for r in replicas:
                for s in r["snapshot"].get(name, {}).get("series", ()):
                    if tuple(sorted(s["labels"].items())) == key:
                        total += s["value"]
            if total != series["value"]:
                bad.append(f"counter {name}{dict(series['labels'])}: "
                           f"merged {series['value']!r} != per-replica "
                           f"sum {total!r}")
    return bad


def scrape_once(urls, token=None, timeout: float = 5.0):
    replicas = [scrape_replica(u, token, timeout) for u in urls]
    live = {r["url"]: r["snapshot"] for r in replicas
            if r["reachable"]}
    merged = fleet.merge_snapshots(live)
    return replicas, merged


def _summarize(merged) -> str:
    kinds = {"counter": 0, "gauge": 0, "histogram": 0}
    for entry in merged.values():
        kinds[entry.get("kind", "?")] = kinds.get(
            entry.get("kind", "?"), 0) + 1
    return (f"merged {len(merged)} metrics "
            f"({kinds.get('counter', 0)} counters, "
            f"{kinds.get('gauge', 0)} gauges, "
            f"{kinds.get('histogram', 0)} histograms)")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="merge N ops-plane snapshots into one fleet view")
    ap.add_argument("urls", nargs="+",
                    help="replica ops-plane base URLs "
                         "(http://host:port)")
    ap.add_argument("--token", default=None,
                    help="static bearer token (all replicas)")
    ap.add_argument("--timeout", type=float, default=5.0)
    ap.add_argument("--json", action="store_true",
                    help="dump {'merged', 'replicas'} JSON instead of "
                         "the human tables")
    ap.add_argument("--watch", type=float, default=None, metavar="S",
                    help="rescrape every S seconds until interrupted")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 unless every replica is reachable "
                         "AND every merged counter re-sums exactly "
                         "(lint-gate mode)")
    args = ap.parse_args(argv)

    while True:
        replicas, merged = scrape_once(args.urls, args.token,
                                       args.timeout)
        if args.json:
            print(json.dumps(
                {"merged": merged,
                 "replicas": [{k: v for k, v in r.items()
                               if k != "snapshot"}
                              for r in replicas]},
                sort_keys=True))
        else:
            print(readiness_table(replicas))
            print(_summarize(merged))
        rc = 0
        if args.check:
            unreachable = [r["url"] for r in replicas
                           if not r["reachable"]]
            for u in unreachable:
                print(f"CHECK FAIL: replica {u} unreachable",
                      file=sys.stderr)
            violations = check_merge(
                [r for r in replicas if r["reachable"]], merged)
            for v in violations:
                print(f"CHECK FAIL: {v}", file=sys.stderr)
            rc = 1 if (unreachable or violations) else 0
            if rc == 0 and not args.json:
                print("check: every merged counter re-sums exactly")
        if args.watch is None:
            return rc
        time.sleep(args.watch)


if __name__ == "__main__":
    sys.exit(main())
