#!/usr/bin/env python
"""Render a solve report (and optionally a Perfetto timeline) from a
solve-trace events JSONL file.

The in-process path is the CLI's ``--report`` / ``--trace-perfetto``
(it has the live objects); this tool is the OFFLINE path - point it at
the file ``--trace-events PATH`` appended to and get the same fused
report back, hours later, on another machine::

    python tools/solve_report.py trace.jsonl
    python tools/solve_report.py trace.jsonl --solve-id s000002-...
    python tools/solve_report.py trace.jsonl --perfetto trace.json
    python tools/solve_report.py trace.jsonl --json

It groups events by ``solve_id``, picks the LAST solve that reached
``solve_end`` with a non-warmup phase (``--solve-id`` overrides), and
fuses whatever that solve emitted: ``solve_start``/``solve_end``
(status, iterations, wall time), ``comm_cost`` (per-iteration
collectives), ``shard_profile`` (the per-shard table), and
``solve_health``.  Events the solve never emitted simply leave their
section out - an old trace file from PR 2 still renders.
"""
from __future__ import annotations

import argparse
import json
import sys

sys.path.insert(0, ".")  # repo-root invocation, like tools/bench_compare

from cuda_mpi_parallel_tpu.telemetry import events as tevents  # noqa: E402
from cuda_mpi_parallel_tpu.telemetry import report as treport  # noqa: E402
from cuda_mpi_parallel_tpu.telemetry import (  # noqa: E402
    shardscope,
)


def pick_solve(evs, solve_id=None):
    """Events of the requested (or last completed, non-warmup) solve."""
    if solve_id is None:
        for ev in reversed(evs):
            if ev["event"] == "solve_end" and ev.get("solve_id") \
                    and ev.get("phase") != "warmup":
                solve_id = ev["solve_id"]
                break
        if solve_id is None:
            raise ValueError("no completed solve (solve_end) in trace")
    picked = [ev for ev in evs if ev.get("solve_id") == solve_id
              and ev.get("phase") != "warmup"]
    if not picked:
        raise ValueError(f"no events for solve_id {solve_id!r}")
    return solve_id, picked


def _last(evs, etype):
    for ev in reversed(evs):
        if ev["event"] == etype:
            return ev
    return None


def build_report(evs) -> treport.SolveReport:
    start = _last(evs, "solve_start") or {}
    end = _last(evs, "solve_end") or {}
    record = {
        "problem": end.get("label") or start.get("label", "?"),
        "status": end.get("status", "?"),
        "iterations": end.get("iterations", 0),
        "residual_norm": end.get("residual_norm"),
        "elapsed_s": end.get("elapsed_s"),
        "device": start.get("device", "?"),
        "mesh": start.get("mesh", 1),
        "dtype": start.get("dtype", "?"),
        "engine": end.get("engine") or start.get("engine", "?"),
    }
    if record["elapsed_s"] and record["iterations"]:
        record["iters_per_sec"] = (record["iterations"]
                                   / record["elapsed_s"])
    shard = None
    prof = _last(evs, "shard_profile")
    if prof is not None:
        shard = shardscope.ShardReport.from_json(prof)
    comm = None
    cc = _last(evs, "comm_cost")
    if cc is not None:
        its = int(record["iterations"] or 0)
        # the comm_cost event carries only the while-body per-iteration
        # rates; the one-time setup collectives (SolveCost.setup) are
        # not in the event stream, so these totals run a few ops short
        # of the CLI's inline report - say so rather than silently
        # disagreeing with it
        comm = {
            "psum": cc["psum_per_iteration"] * its,
            "ppermute": cc["ppermute_per_iteration"] * its,
            "all_gather": cc.get("all_gather_per_iteration", 0) * its,
            "comm_bytes": cc["comm_bytes_per_iteration"] * its,
            "note": "iteration-phase collectives only - one-time "
                    "setup ops are not in the event stream",
        }
        # wire semantics + exchange lane (PR 7) - n/a-safe on pre-PR-7
        # trace files, which simply lack these fields
        if cc.get("wire_bytes_per_iteration") is not None:
            comm["wire_bytes"] = cc["wire_bytes_per_iteration"] * its
        if cc.get("exchange") is not None:
            comm["exchange"] = cc["exchange"]
        if cc.get("halo_padding_fraction") is not None:
            comm["halo_padding_fraction"] = cc["halo_padding_fraction"]
    # measured phase profile (telemetry.phasetrace): the phase_profile
    # event carries PhaseProfile.to_json() verbatim - render its phase
    # columns offline, and reuse it for measured Perfetto spans
    phase = _last(evs, "phase_profile")
    if phase is not None:
        phase = {k: v for k, v in phase.items()
                 if k not in ("event", "t", "solve_id", "phase")}
    health = _last(evs, "solve_health")
    if health is not None:
        # drop the event envelope so the offline report's health JSON
        # has the same shape as the CLI's inline SolveHealth.to_json()
        health = {k: v for k, v in health.items()
                  if k not in ("event", "t", "solve_id", "phase")}
    # calibration & drift (PR 6): the drift-extended partition_plan
    # emission (stage="drift") and any replan decisions of this solve
    calibration = None
    drift_ev = next((ev for ev in reversed(evs)
                     if ev["event"] == "partition_plan"
                     and ev.get("stage") == "drift"), None)
    replans = [ev for ev in evs if ev["event"] == "replan"]
    if drift_ev is not None or replans:
        calibration = {}
        if drift_ev is not None:
            calibration["drift"] = {
                k: drift_ev.get(k)
                for k in ("drift_pct", "predicted_s_per_iteration",
                          "measured_s_per_iteration", "model")}
            lane = drift_ev.get("exchange")
            calibration["drift"]["plan"] = (
                f"{drift_ev.get('reorder')}+{drift_ev.get('split')}"
                + (f"+{lane}" if lane and lane != "allgather" else ""))
        if replans:
            calibration["decisions"] = [
                {k: ev.get(k) for k in ("solve_index", "decision",
                                        "predicted_gain_pct", "model")}
                for ev in replans]
    sections = tuple((end.get("sections") or {}).items())
    return treport.SolveReport(record=record, shard=shard, comm=comm,
                               health=health, calibration=calibration,
                               phase=phase, sections=sections)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="render a solve report from a --trace-events JSONL "
                    "file")
    ap.add_argument("trace", help="events JSONL path (--trace-events)")
    ap.add_argument("--solve-id", default=None,
                    help="render this solve (default: last completed)")
    ap.add_argument("--json", action="store_true",
                    help="emit the fused report as JSON instead of text")
    ap.add_argument("--perfetto", default=None, metavar="PATH",
                    help="additionally export the Perfetto timeline "
                         "JSON to PATH")
    args = ap.parse_args(argv)
    try:
        evs = tevents.read_events(args.trace)
        solve_id, picked = pick_solve(evs, args.solve_id)
        rep = build_report(picked)
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.json:
        out = rep.to_json()
        out["solve_id"] = solve_id
        print(json.dumps(out, allow_nan=False, sort_keys=True))
    else:
        print(f"solve_id: {solve_id}")
        print(rep.to_text(), end="")
    if args.perfetto:
        elapsed = rep.record.get("elapsed_s") or 0.0
        trace = treport.perfetto_trace(
            iterations=int(rep.record.get("iterations") or 0),
            elapsed_s=float(elapsed), shard=rep.shard,
            n_shards=rep.shard.n_shards if rep.shard else 1,
            sections=rep.sections,
            # a recorded phase_profile event upgrades the offline
            # timeline to measured spans, hours later, on any machine
            phase_profile=rep.phase,
            label=str(rep.record.get("problem", "solve")))
        treport.validate_perfetto(trace)
        treport.write_perfetto(args.perfetto, trace)
        print(f"# perfetto timeline -> {args.perfetto}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
