#!/usr/bin/env python
"""graftverify gate: whole-trace SPMD contracts + cache-key soundness.

The static half of the pre-hardware gate is graftlint (pure AST, run
separately by tools/lint.sh); this script is the TRACE half.  It never
compiles and never executes a solve - every check works on
``jax.make_jaxpr`` output captured at the ``dist_cg._cached_solver``
choke point:

1. **SPMD verifier** (``analysis.spmd.verify_spmd``) - the exact solve
   bodies the solver cache would compile for the mesh-4 CSR lanes
   (allgather / gather / ring exchange, deflated, fault-armed) must be
   replication-consistent (no shard-varying ``while`` predicate or
   ``cond`` selector) and their collectives/permutation endpoints must
   match the actual mesh geometry.

2. **Cache-key audit** (``analysis.cachekey``) - perturbing any static
   argument of ``solve_distributed`` or ``ManyRHSDispatcher`` that
   changes the traced program must change the solver-cache key (same
   key + different jaxpr = a second caller silently reuses the wrong
   compiled solver).

Runs on CPU with 4 virtual devices; exit 0 = both contracts hold.
"""
import os

# env must be set before jax is imported (conftest.py discipline)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = \
        (_flags + " --xla_force_host_platform_device_count=4").strip()

import sys  # noqa: E402

sys.path.insert(0, ".")  # repo-root invocation, like overload_drill


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    if jax.device_count() < 4:
        print(f"graftverify: need >= 4 devices, have {jax.device_count()}",
              file=sys.stderr)
        return 2

    import numpy as np

    from cuda_mpi_parallel_tpu.analysis import (
        CacheKeyAuditError,
        SpmdViolation,
        audit_many_rhs,
        audit_solve_distributed,
        probe_dispatch,
        verify_spmd,
    )
    from cuda_mpi_parallel_tpu.analysis.cachekey import _synthetic_space
    from cuda_mpi_parallel_tpu.models import poisson
    from cuda_mpi_parallel_tpu.parallel import make_mesh, solve_distributed
    from cuda_mpi_parallel_tpu.robust.inject import FaultPlan

    a = poisson.poisson_2d_csr(12, 12)
    rng = np.random.default_rng(0)
    b = rng.standard_normal(int(a.shape[0]))
    mesh = make_mesh(4)
    failures = 0

    print("== SPMD verifier (mesh-4 CSR lanes, trace-only) ==")
    lanes = [
        ("allgather", {}),
        ("gather", {"exchange": "gather"}),
        ("ring", {"exchange": "ring"}),
        ("deflated", {"deflate": _synthetic_space(a)}),
        ("fault-armed", {"inject": FaultPlan(site="reduction",
                                             iteration=2)}),
    ]
    for name, kw in lanes:
        probe = probe_dispatch(
            lambda: solve_distributed(a, b, mesh=mesh, tol=1e-8,
                                      maxiter=200, **kw))
        try:
            report = verify_spmd(probe.build(), *probe.args, mesh=mesh)
        except SpmdViolation as exc:
            print(f"  {name}: FAIL\n{exc}", file=sys.stderr)
            failures += 1
        else:
            print(f"  {name}: clean (axes {', '.join(report.axes_used)})")

    print("== cache-key soundness audit (differential, trace-only) ==")
    try:
        report = audit_solve_distributed(a, b, mesh)
    except CacheKeyAuditError as exc:
        print(f"  solve_distributed: FAIL\n{exc}", file=sys.stderr)
        failures += 1
    else:
        print(f"  solve_distributed: {len(report.cases)} static lanes "
              f"sound")
    b_stack = np.stack([b, 2 * b, 3 * b, 4 * b], axis=1)
    try:
        report = audit_many_rhs(a, b_stack, mesh)
    except CacheKeyAuditError as exc:
        print(f"  ManyRHSDispatcher: FAIL\n{exc}", file=sys.stderr)
        failures += 1
    else:
        print(f"  ManyRHSDispatcher: {len(report.cases)} static lanes "
              f"sound")

    if failures:
        print(f"graftverify: {failures} contract(s) violated",
              file=sys.stderr)
        return 1
    print("graftverify: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
