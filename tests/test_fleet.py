"""Fleet aggregation tests: telemetry.fleet's snapshot-merge algebra
and tools/fleet_scrape.py against live ops planes.

The exactness contracts of the cross-replica half of ISSUE 19:

* merged counters equal the per-replica sums EXACTLY (same float
  additions a single registry would have performed);
* merged histogram quantiles equal the quantiles the registry itself
  reports for the union observation stream (the regression the
  ``snapshot()`` ``bucket_bounds`` satellite exists for);
* gauges never sum - each replica's series survives under a
  ``replica`` label;
* the merge is pure, associative, and refuses to guess: kind
  mismatches, bucket-bound mismatches, and pre-fleet snapshots
  (no ``bucket_bounds``) raise instead of silently mixing;
* two live replicas scraped over HTTP: exact counter sums end to end,
  and the readiness table flips a replica to NOT-ready on the very
  next scrape after its breaker opens.
"""
from __future__ import annotations

import copy
import importlib.util
import pathlib

import numpy as np
import pytest

from cuda_mpi_parallel_tpu.serve.service import (
    ServiceConfig,
    SolverService,
    _Breaker,
)
from cuda_mpi_parallel_tpu.telemetry import fleet
from cuda_mpi_parallel_tpu.telemetry.registry import (
    MetricsRegistry,
    quantile_from_buckets,
)

_TOOLS = pathlib.Path(__file__).resolve().parent.parent / "tools"


def _load_fleet_scrape():
    spec = importlib.util.spec_from_file_location(
        "fleet_scrape", _TOOLS / "fleet_scrape.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


BUCKETS = (0.1, 0.5, 1.0, 2.0, 5.0, 10.0)


def _replica(seed: int, n: int) -> tuple:
    """(registry, observations) for one synthetic replica."""
    rng = np.random.default_rng(seed)
    reg = MetricsRegistry()
    xs = [float(x) for x in rng.uniform(0.0, 8.0, size=n)]
    h = reg.histogram("latency_seconds", "x", buckets=BUCKETS)
    for x in xs:
        h.observe(x)
    reg.counter("requests_total", "n").inc(float(n))
    reg.counter("by_tenant_total", "n",
                labelnames=("tenant",)).inc(
        float(seed + 1), tenant="acme")
    reg.gauge("queue_depth", "d").set(float(seed * 10))
    return reg, xs


class TestMergeAlgebra:
    def test_counters_sum_exactly(self):
        r1, _ = _replica(0, 100)
        r2, _ = _replica(1, 250)
        merged = fleet.merge_snapshots(
            {"a": r1.snapshot(), "b": r2.snapshot()})
        assert merged["requests_total"]["series"][0]["value"] \
            == 350.0
        # labeled counters merge per label set
        (series,) = merged["by_tenant_total"]["series"]
        assert series["labels"] == {"tenant": "acme"}
        assert series["value"] == 1.0 + 2.0

    def test_merged_p99_equals_union_stream_p99(self):
        """THE regression the bucket_bounds satellite exists for:
        quantiles of the merged view are exactly what one registry
        would have reported seeing every observation."""
        r1, xs1 = _replica(0, 200)
        r2, xs2 = _replica(1, 300)
        merged = fleet.merge_snapshots(
            {"a": r1.snapshot(), "b": r2.snapshot()})
        union = MetricsRegistry()
        h = union.histogram("latency_seconds", "x", buckets=BUCKETS)
        for x in xs1 + xs2:
            h.observe(x)
        want = union.snapshot()["latency_seconds"]["series"][0]
        got = merged["latency_seconds"]["series"][0]
        assert got["percentiles"] == want["percentiles"]
        assert got["buckets"] == want["buckets"]
        assert got["count"] == want["count"]
        assert got["sum"] == pytest.approx(want["sum"])

    def test_gauges_keep_replica_identity(self):
        r1, _ = _replica(0, 10)
        r2, _ = _replica(2, 10)
        merged = fleet.merge_snapshots(
            {"west": r1.snapshot(), "east": r2.snapshot()})
        series = {s["labels"]["replica"]: s["value"]
                  for s in merged["queue_depth"]["series"]}
        assert series == {"west": 0.0, "east": 20.0}
        assert "replica" in merged["queue_depth"]["labelnames"]

    def test_merge_is_pure(self):
        snap = _replica(0, 50)[0].snapshot()
        frozen = copy.deepcopy(snap)
        fleet.merge_snapshots({"a": snap, "b": frozen})
        assert snap == frozen  # inputs never mutated

    def test_merge_is_associative(self):
        lifted = [fleet.lift(_replica(s, 40 + s)[0].snapshot(),
                             f"r{s}") for s in range(3)]
        la, lb, lc = lifted
        left = fleet.merge_two(fleet.merge_two(la, lb), lc)
        right = fleet.merge_two(la, fleet.merge_two(lb, lc))
        assert left == right

    def test_fleet_of_fleets(self):
        """An aggregate of aggregates equals the flat merge: scrape
        aggregators, then aggregate the aggregators."""
        snaps = {f"r{s}": _replica(s, 30 + 7 * s)[0].snapshot()
                 for s in range(4)}
        flat = fleet.merge_snapshots(snaps)
        west = fleet.merge_snapshots(
            {k: snaps[k] for k in ("r0", "r1")})
        east = fleet.merge_snapshots(
            {k: snaps[k] for k in ("r2", "r3")})
        rollup = fleet.merge_two(west, east)
        assert rollup == flat

    def test_empty_and_disjoint(self):
        assert fleet.merge_snapshots({}) == {}
        r1 = MetricsRegistry()
        r1.counter("only_here_total", "n").inc(3)
        r2 = MetricsRegistry()
        r2.counter("only_there_total", "n").inc(4)
        merged = fleet.merge_snapshots(
            {"a": r1.snapshot(), "b": r2.snapshot()})
        assert merged["only_here_total"]["series"][0]["value"] == 3.0
        assert merged["only_there_total"]["series"][0]["value"] == 4.0

    def test_kind_mismatch_refused(self):
        r1 = MetricsRegistry()
        r1.counter("thing", "n").inc()
        r2 = MetricsRegistry()
        r2.gauge("thing", "n").set(1)
        with pytest.raises(ValueError, match="kind"):
            fleet.merge_snapshots(
                {"a": r1.snapshot(), "b": r2.snapshot()})

    def test_bucket_bounds_mismatch_refused(self):
        r1 = MetricsRegistry()
        r1.histogram("h", "x", buckets=(1.0, 2.0)).observe(1.5)
        r2 = MetricsRegistry()
        r2.histogram("h", "x", buckets=(1.0, 4.0)).observe(1.5)
        with pytest.raises(ValueError, match="bucket bounds"):
            fleet.merge_snapshots(
                {"a": r1.snapshot(), "b": r2.snapshot()})

    def test_pre_fleet_snapshot_refused(self):
        """A snapshot without serialized bucket_bounds (the pre-ISSUE-19
        format) is refused, never guessed at."""
        r1 = MetricsRegistry()
        r1.histogram("h", "x", buckets=(1.0, 2.0)).observe(1.5)
        old = r1.snapshot()
        for entry in old.values():
            entry.pop("bucket_bounds", None)
        with pytest.raises(ValueError, match="bucket_bounds"):
            fleet.merge_snapshots({"a": old, "b": r1.snapshot()})

    def test_gauge_duplicate_series_refused(self):
        snap = _replica(0, 10)[0].snapshot()
        lifted = fleet.lift(snap, "same")
        with pytest.raises(ValueError, match="duplicate"):
            fleet.merge_two(lifted, lifted)

    def test_lift_idempotent_on_labeled_gauges(self):
        snap = _replica(0, 10)[0].snapshot()
        once = fleet.lift(snap, "r1")
        twice = fleet.lift(once, "r2")  # replica label already there
        assert once == twice


class TestQuantileFromBuckets:
    def test_interpolation_matches_histogram_readout(self):
        reg = MetricsRegistry()
        h = reg.histogram("h", "x", buckets=BUCKETS)
        rng = np.random.default_rng(7)
        for x in rng.uniform(0, 12, size=500):
            h.observe(float(x))
        series = reg.snapshot()["h"]["series"][0]
        cum = [series["buckets"][k] for k in series["buckets"]]
        bounds = reg.snapshot()["h"]["bucket_bounds"]
        for pname, q in (("p50", 0.5), ("p95", 0.95), ("p99", 0.99)):
            assert quantile_from_buckets(
                bounds, cum, series["count"], q) \
                == series["percentiles"][pname]

    def test_empty_histogram(self):
        assert quantile_from_buckets([1.0, 2.0], [0, 0], 0, 0.99) \
            is None


class TestLiveFleetScrape:
    def test_two_replicas_exact_sums_and_readiness_flip(self):
        """Two live ops planes: merged counters re-sum exactly over
        HTTP, and the very next scrape after a breaker opens shows
        that replica NOT-ready with the breakers gate named."""
        fs = _load_fleet_scrape()
        s1 = SolverService(ServiceConfig(ops_port=0))
        s2 = SolverService(ServiceConfig(ops_port=0))
        try:
            urls = [s1.ops_server().url, s2.ops_server().url]
            replicas, merged = fs.scrape_once(urls)
            assert all(r["reachable"] and r["ready"]
                       for r in replicas)
            assert fs.check_merge(replicas, merged) == []
            table = fs.readiness_table(replicas)
            assert table.count("ready") >= 2

            # open a breaker on replica 2 - the NEXT scrape flips it
            s2._breakers["poisson:w1"] = _Breaker(state="open")
            replicas, merged = fs.scrape_once(urls)
            by_url = {r["url"]: r for r in replicas}
            assert by_url[urls[0]]["ready"]
            assert not by_url[urls[1]]["ready"]
            assert by_url[urls[1]]["status"] == "degraded"
            assert by_url[urls[1]]["failing"] == ["breakers"]
            table = fs.readiness_table(replicas)
            assert "NO" in table and "breakers" in table
        finally:
            s1.close()
            s2.close()

    def test_unreachable_replica_not_ready(self):
        fs = _load_fleet_scrape()
        s1 = SolverService(ServiceConfig(ops_port=0))
        try:
            url = s1.ops_server().url
        finally:
            s1.close()
        replicas, merged = fs.scrape_once([url])
        assert not replicas[0]["reachable"]
        assert replicas[0]["failing"] == ["unreachable"]
