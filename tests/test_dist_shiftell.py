"""Distributed ring-shiftell: ppermute x-rotation + pallas slab SpMV.

Runs on the 8-virtual-CPU-device mesh (conftest); the pallas kernel runs
in interpret mode inside shard_map - the same code path the TPU compiles.
"""
import jax.numpy as jnp
import numpy as np
import pytest

from cuda_mpi_parallel_tpu import solve
from cuda_mpi_parallel_tpu.models import poisson
from cuda_mpi_parallel_tpu.models.fem import random_fem_2d
from cuda_mpi_parallel_tpu.parallel import make_mesh, solve_distributed
from cuda_mpi_parallel_tpu.parallel import partition as part


class TestRingPartitionShiftELL:
    def test_uniform_shapes_per_step(self):
        a = random_fem_2d(900, seed=4)
        parts = part.ring_partition_shiftell(a, 4, h=2, kc=4)
        assert len(parts.vals) == 4
        for t in range(4):
            n_owners, c, kc, h1, lanes = parts.vals[t].shape
            assert (n_owners, kc, h1, lanes) == (4, parts.kc,
                                                 parts.h + 1, 128)
            assert parts.lane_idx[t].shape == (4, c, parts.kc, parts.h, 128)
            assert parts.chunk_blocks[t].shape == (4, c)

    def test_slab_values_conserved(self):
        """Total stored value mass across all slabs == matrix total."""
        a = poisson.poisson_2d_csr(24, 24)
        parts = part.ring_partition_shiftell(a, 4, h=2)
        total = sum(float(v[:, :, :, :parts.h, :].sum())
                    for v in parts.vals)
        # padding rows add unit diagonals for rows beyond n
        n_pad_rows = parts.n_global_padded - parts.n_global
        np.testing.assert_allclose(
            total, float(np.asarray(a.data).sum()) + n_pad_rows, rtol=1e-12)

    def test_diag_matches(self):
        a = random_fem_2d(600, seed=5)
        parts = part.ring_partition_shiftell(a, 8, h=2)
        diag = parts.diag.reshape(-1)[: a.shape[0]]
        np.testing.assert_allclose(diag, np.asarray(a.diagonal()),
                                   rtol=1e-12)


# The ring-shiftell pallas-in-interpret shard_map solves cost ~3 min of
# XLA:CPU work on a small host - past the tier-1 870s budget; they run
# in the untimed full suite.  The partition tests above are pure-host
# and stay in the tier-1 gate.
@pytest.mark.slow
class TestSolveRingShiftELL:
    def test_trajectory_matches_single_device(self, rng):
        a = poisson.poisson_2d_csr(24, 24)
        x_true = rng.standard_normal(576)
        b = a @ jnp.asarray(x_true)
        r1 = solve(a, b, tol=0.0, rtol=1e-10, maxiter=2000)
        r8 = solve_distributed(a, b, mesh=make_mesh(8), tol=0.0,
                               rtol=1e-10, maxiter=2000,
                               csr_comm="ring-shiftell")
        assert bool(r8.converged)
        assert abs(int(r8.iterations) - int(r1.iterations)) <= 2
        np.testing.assert_allclose(np.asarray(r8.x), x_true, atol=1e-6)

    def test_matches_ring_csr(self, rng):
        """Same schedule, different local kernel: identical math."""
        a = random_fem_2d(700, seed=6)
        x_true = rng.standard_normal(700)
        b = a @ jnp.asarray(x_true)
        r_csr = solve_distributed(a, b, mesh=make_mesh(8), tol=0.0,
                                  rtol=1e-9, maxiter=4000, csr_comm="ring")
        r_sell = solve_distributed(a, b, mesh=make_mesh(8), tol=0.0,
                                   rtol=1e-9, maxiter=4000,
                                   csr_comm="ring-shiftell")
        assert bool(r_sell.converged)
        assert abs(int(r_sell.iterations) - int(r_csr.iterations)) <= 2
        np.testing.assert_allclose(np.asarray(r_sell.x),
                                   np.asarray(r_csr.x), atol=1e-5)

    @pytest.mark.parametrize("pre", [None, "jacobi", "chebyshev"])
    def test_preconditioners(self, rng, pre):
        a = poisson.poisson_2d_csr(16, 16)
        x_true = rng.standard_normal(256)
        b = a @ jnp.asarray(x_true)
        r = solve_distributed(a, b, mesh=make_mesh(8), tol=0.0, rtol=1e-9,
                              maxiter=2000, csr_comm="ring-shiftell",
                              preconditioner=pre)
        assert bool(r.converged)
        np.testing.assert_allclose(np.asarray(r.x), x_true, atol=1e-5)

    def test_n_not_divisible(self, rng):
        """Padding rows (unit diagonal) flow through the shiftell slabs."""
        a = random_fem_2d(333, seed=7)
        x_true = rng.standard_normal(333)
        b = a @ jnp.asarray(x_true)
        r = solve_distributed(a, b, mesh=make_mesh(8), tol=0.0, rtol=1e-9,
                              maxiter=4000, csr_comm="ring-shiftell")
        assert bool(r.converged)
        assert r.x.shape == (333,)
        np.testing.assert_allclose(np.asarray(r.x), x_true, atol=1e-4)

    def test_second_call_no_retrace(self, rng):
        from cuda_mpi_parallel_tpu.parallel import dist_cg

        a = poisson.poisson_2d_csr(16, 16)
        b = a @ jnp.asarray(rng.standard_normal(256))
        kw = dict(mesh=make_mesh(8), tol=0.0, rtol=1e-8, maxiter=500,
                  csr_comm="ring-shiftell")
        solve_distributed(a, b, **kw)
        before = dist_cg._TRACE_COUNT[0]
        solve_distributed(a, b, **kw)
        assert dist_cg._TRACE_COUNT[0] == before
