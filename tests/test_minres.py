"""MINRES (``solver/minres.py``): the symmetric-indefinite solver.

The reference's own hardcoded matrix is symmetric INDEFINITE (quirk Q1,
``CUDACG.cu:76-78``) - CG converges on it by luck.  MINRES is the
principled algorithm; these tests check it against scipy's minres on
random indefinite systems, the oracle, monotone residuals, blocked
predicates, and the distributed mesh path.
"""
import numpy as np
import pytest
import scipy.sparse.linalg as spla

import jax.numpy as jnp

from cuda_mpi_parallel_tpu import solve
from cuda_mpi_parallel_tpu.models import poisson
from cuda_mpi_parallel_tpu.solver.status import CGStatus


def _indefinite_system(n=200, n_neg=40, seed=3):
    rng = np.random.default_rng(seed)
    q, _ = np.linalg.qr(rng.standard_normal((n, n)))
    eigs = np.concatenate([rng.uniform(0.5, 3.0, n - n_neg),
                           -rng.uniform(0.2, 1.0, n_neg)])
    a = (q * eigs) @ q.T
    a = 0.5 * (a + a.T)
    return a, rng.standard_normal(n)


class TestOracle:
    def test_oracle_three_iterations(self):
        # the reference's indefinite 3x3 system: MINRES solves it
        # without relying on CG's luck, and certifies indefiniteness
        a, b, x_exp = poisson.oracle_system()
        r = solve(a, b, method="minres", tol=1e-10, maxiter=50)
        assert bool(r.converged)
        assert int(r.iterations) == 3
        assert bool(r.indefinite)  # negative Rayleigh quotient observed
        np.testing.assert_allclose(np.asarray(r.x), np.asarray(x_exp),
                                   atol=1e-8)

    def test_oracle_blocked_past_exact_solve(self):
        # iterations past Krylov exhaustion inside a check block must
        # freeze, not NaN
        a, b, _ = poisson.oracle_system()
        r = solve(a, b, method="minres", tol=1e-12, maxiter=64,
                  check_every=8)
        assert bool(r.converged)
        assert np.all(np.isfinite(np.asarray(r.x)))


class TestIndefinite:
    def test_matches_scipy_on_indefinite(self):
        a, b = _indefinite_system()
        res = solve(a, jnp.asarray(b), method="minres", tol=0.0,
                    rtol=1e-9, maxiter=2000)
        x_sp, info = spla.minres(a, b, rtol=1e-9, maxiter=2000)
        assert info == 0 and bool(res.converged)
        resid = np.linalg.norm(b - a @ np.asarray(res.x))
        resid_sp = np.linalg.norm(b - a @ x_sp)
        # at least scipy's quality on the TRUE residual
        assert resid <= max(resid_sp * 2, 1e-8 * np.linalg.norm(b))

    def test_monotone_residual(self):
        a, b = _indefinite_system(seed=7)
        res = solve(a, jnp.asarray(b), method="minres", tol=0.0,
                    rtol=1e-9, maxiter=2000, record_history=True)
        h = np.asarray(res.residual_history)
        h = h[np.isfinite(h)]
        assert np.all(np.diff(h) <= 1e-12 + 1e-7 * h[:-1])

    def test_cg_vs_minres_on_spd(self):
        # on an SPD system both converge; MINRES needs no more than a
        # few extra iterations (same Krylov space, different optimality)
        op = poisson.poisson_2d_operator(16, 16, dtype=jnp.float64)
        rng = np.random.default_rng(11)
        b = jnp.asarray(rng.standard_normal(256))
        r_cg = solve(op, b, tol=0.0, rtol=1e-9, maxiter=600)
        r_mr = solve(op, b, method="minres", tol=0.0, rtol=1e-9,
                     maxiter=600)
        assert bool(r_cg.converged) and bool(r_mr.converged)
        assert abs(int(r_mr.iterations) - int(r_cg.iterations)) <= 5


class TestSemantics:
    def test_check_every_overshoots_only(self):
        a, b = _indefinite_system(seed=5)
        r1 = solve(a, jnp.asarray(b), method="minres", tol=0.0,
                   rtol=1e-9, maxiter=2000, check_every=1)
        r32 = solve(a, jnp.asarray(b), method="minres", tol=0.0,
                    rtol=1e-9, maxiter=2000, check_every=32)
        assert int(r32.iterations) >= int(r1.iterations)
        assert int(r32.iterations) % 32 == 0
        assert bool(r32.converged)

    def test_maxiter_status(self):
        a, b = _indefinite_system(seed=9)
        r = solve(a, jnp.asarray(b), method="minres", tol=1e-30,
                  maxiter=10)
        assert not bool(r.converged)
        assert r.status_enum() is CGStatus.MAXITER
        assert int(r.iterations) == 10

    def test_iter_cap_traced(self):
        a, b = _indefinite_system(seed=13)
        r = solve(a, jnp.asarray(b), method="minres", tol=0.0,
                  maxiter=100, iter_cap=17)
        assert int(r.iterations) == 17

    def test_x0_warm_start(self):
        a, b = _indefinite_system(seed=15)
        x_sp, _ = spla.minres(a, b, rtol=1e-12, maxiter=2000)
        warm = solve(a, jnp.asarray(b), jnp.asarray(x_sp),
                     method="minres", tol=1e-6, maxiter=200)
        cold = solve(a, jnp.asarray(b), method="minres", tol=1e-6,
                     maxiter=200)
        assert bool(warm.converged)
        assert int(warm.iterations) < int(cold.iterations)

    def test_exhaustion_consistent_singular(self):
        # Krylov exhaustion on a CONSISTENT singular system: b entirely
        # in the range - exhaustion collapses phibar to 0 and the
        # least-squares solution in the subspace is the exact solution.
        a = np.diag([1.0, 2.0, 0.0])
        b = np.array([1.0, 2.0, 0.0])
        r = solve(a, jnp.asarray(b), method="minres", tol=1e-10,
                  maxiter=50)
        assert bool(r.converged)
        assert np.all(np.isfinite(np.asarray(r.x)))
        np.testing.assert_allclose(np.asarray(r.x)[:2], [1.0, 1.0],
                                   atol=1e-10)

    def test_rejects_preconditioner(self):
        from cuda_mpi_parallel_tpu.models.operators import (
            JacobiPreconditioner,
        )

        op = poisson.poisson_2d_operator(16, 16, dtype=jnp.float64)
        m = JacobiPreconditioner.from_operator(op)
        with pytest.raises(ValueError, match="minres"):
            solve(op, jnp.ones(256), method="minres", m=m)

    def test_rejects_checkpointing(self):
        op = poisson.poisson_2d_operator(16, 16, dtype=jnp.float64)
        with pytest.raises(ValueError, match="minres"):
            solve(op, jnp.ones(256), method="minres",
                  return_checkpoint=True)

    def test_history_endpoints(self):
        a, b = _indefinite_system(seed=17)
        res = solve(a, jnp.asarray(b), method="minres", tol=0.0,
                    rtol=1e-8, maxiter=2000, record_history=True)
        h = np.asarray(res.residual_history)
        k = int(res.iterations)
        assert np.isclose(h[0], np.linalg.norm(b), rtol=1e-10)
        assert np.isclose(h[k], float(res.residual_norm), rtol=1e-10)
        assert np.isnan(h[k + 1:]).all()


class TestDF64Minres:
    """f64-class MINRES on double-float pairs (``minres_df64``): the
    reference's defining precision x the right algorithm for its
    indefinite matrix class."""

    def test_oracle(self):
        from cuda_mpi_parallel_tpu.solver.minres import minres_df64

        a, b, x_exp = poisson.oracle_system()
        r = minres_df64(a, np.asarray(b, np.float64), tol=1e-12,
                        maxiter=50)
        assert bool(r.converged) and int(r.iterations) == 3
        assert bool(r.indefinite)
        np.testing.assert_allclose(r.x(), np.asarray(x_exp), atol=1e-10)

    def test_reaches_f64_depth_and_matches_f64_trajectory(self):
        from cuda_mpi_parallel_tpu.solver.df64 import cg_df64

        op32 = poisson.poisson_2d_operator(16, 16, dtype=jnp.float32)
        rng = np.random.default_rng(2)
        b = rng.standard_normal(256)
        rd = cg_df64(op32, b, tol=0.0, rtol=1e-12, maxiter=2000,
                     method="minres")
        assert bool(rd.converged)
        ad = np.asarray(
            poisson.poisson_2d_csr(16, 16, dtype=np.float64).to_dense())
        true_rel = (np.linalg.norm(b - ad @ rd.x())
                    / np.linalg.norm(b))
        assert true_rel < 1e-11  # far below f32's ~1e-7 floor
        # trajectory parity vs true-f64 minres (x64 CPU oracle)
        rf = solve(poisson.poisson_2d_operator(16, 16, dtype=jnp.float64),
                   jnp.asarray(b), method="minres", tol=0.0, rtol=1e-12,
                   maxiter=2000)
        assert abs(int(rf.iterations) - int(rd.iterations)) <= 2
        assert np.abs(rd.x() - np.asarray(rf.x)).max() < 1e-10

    def test_indefinite_df64(self):
        from cuda_mpi_parallel_tpu.models.operators import CSRMatrix
        from cuda_mpi_parallel_tpu.solver.df64 import cg_df64
        import scipy.sparse as sp

        a_np, b = _indefinite_system(n=96, n_neg=20, seed=21)
        a_ell = CSRMatrix.from_scipy(sp.csr_matrix(a_np),
                                     dtype=np.float64).to_ell()
        rd = cg_df64(a_ell, b, tol=0.0, rtol=1e-10, maxiter=2000,
                     method="minres")
        assert bool(rd.converged)
        true_rel = (np.linalg.norm(b - a_np @ rd.x())
                    / np.linalg.norm(b))
        assert true_rel < 1e-8

    def test_rejections(self):
        from cuda_mpi_parallel_tpu.solver.df64 import cg_df64

        op32 = poisson.poisson_2d_operator(16, 16, dtype=jnp.float32)
        with pytest.raises(ValueError, match="minres"):
            cg_df64(op32, np.ones(256), method="minres",
                    preconditioner="jacobi")
        with pytest.raises(ValueError, match="minres"):
            cg_df64(op32, np.ones(256), method="minres",
                    return_checkpoint=True)

    def test_df64_sqrt_accuracy(self):
        from cuda_mpi_parallel_tpu.ops import df64 as df

        rng = np.random.default_rng(0)
        vals = np.abs(rng.standard_normal(1000)) \
            * 10.0 ** rng.uniform(-20, 20, 1000)
        h, l = df.split_f64(vals)
        sh, sl = df.sqrt((jnp.asarray(h), jnp.asarray(l)))
        rel = np.abs(df.to_f64(sh, sl) - np.sqrt(vals)) / np.sqrt(vals)
        assert rel.max() < 1e-14
        z = df.sqrt((jnp.zeros(3, jnp.float32), jnp.zeros(3, jnp.float32)))
        assert np.all(np.asarray(z[0]) == 0)


@pytest.mark.skipif(
    len(__import__("jax").devices()) < 8,
    reason="needs 8 virtual devices")
class TestDistributed:
    def test_mesh_matches_single_device(self):
        from cuda_mpi_parallel_tpu.parallel import (
            make_mesh,
            solve_distributed,
        )

        op = poisson.poisson_2d_operator(16, 16, dtype=jnp.float64)
        rng = np.random.default_rng(1)
        b = jnp.asarray(rng.standard_normal(256))
        single = solve(op, b, method="minres", tol=0.0, rtol=1e-9,
                       maxiter=600)
        dist = solve_distributed(op, b, mesh=make_mesh(8),
                                 method="minres", tol=0.0, rtol=1e-9,
                                 maxiter=600)
        assert bool(dist.converged)
        assert int(dist.iterations) == int(single.iterations)
        np.testing.assert_allclose(np.asarray(dist.x),
                                   np.asarray(single.x), atol=1e-9)

    def test_df64_mesh_matches_single_device(self):
        # VERDICT r4 item 7: minres_df64 through solve_distributed_df64
        # (the reference's CUDA_R_64F precision x its own indefinite
        # matrix class, distributed)
        from cuda_mpi_parallel_tpu.parallel import make_mesh
        from cuda_mpi_parallel_tpu.parallel.df64 import (
            solve_distributed_df64,
        )
        from cuda_mpi_parallel_tpu.solver.df64 import cg_df64

        op = poisson.poisson_2d_operator(16, 16, dtype=jnp.float32)
        rng = np.random.default_rng(2)
        b64 = rng.standard_normal(256)
        single = cg_df64(op, b64, method="minres", tol=0.0, rtol=1e-11,
                         maxiter=600)
        dist = solve_distributed_df64(op, b64, mesh=make_mesh(8),
                                      method="minres", tol=0.0,
                                      rtol=1e-11, maxiter=600)
        assert bool(dist.converged)
        assert int(dist.iterations) == int(single.iterations)
        np.testing.assert_allclose(dist.x(), single.x(), atol=1e-11)

    def test_df64_minres_gating(self):
        from cuda_mpi_parallel_tpu.parallel import make_mesh
        from cuda_mpi_parallel_tpu.parallel.df64 import (
            solve_distributed_df64,
        )

        op = poisson.poisson_2d_operator(16, 16, dtype=jnp.float32)
        b64 = np.ones(256)
        with pytest.raises(ValueError, match="unpreconditioned"):
            solve_distributed_df64(op, b64, mesh=make_mesh(8),
                                   method="minres",
                                   preconditioner="jacobi")
