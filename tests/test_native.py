"""Native C++ data-layer tests: every routine cross-checked against the
pure-Python/scipy path (the native layer is an accelerator, results must be
identical)."""
import numpy as np
import pytest
import scipy.io
import scipy.sparse as sp

from cuda_mpi_parallel_tpu.models import mmio, poisson
from cuda_mpi_parallel_tpu.native import bindings

pytestmark = pytest.mark.skipif(
    not bindings.available(), reason="native library unavailable (no g++)")


def _write_mm(tmp_path, m, name="m.mtx", symmetry="general"):
    path = str(tmp_path / name)
    scipy.io.mmwrite(path, m, symmetry=symmetry)
    return path


class TestMMRead:
    def test_general_matches_scipy(self, tmp_path, rng):
        m = sp.random(40, 40, density=0.1,
                      random_state=np.random.RandomState(3), format="coo")
        path = _write_mm(tmp_path, m)
        vals, indices, indptr, shape = bindings.mm_read(path)
        got = sp.csr_matrix((vals, indices, indptr), shape=shape)
        want = sp.csr_matrix(scipy.io.mmread(path))
        assert (abs(got - want)).max() < 1e-12

    def test_symmetric_expansion(self, tmp_path):
        a = poisson.poisson_2d_csr(5, 5)
        m = sp.csr_matrix(
            (np.asarray(a.data), np.asarray(a.indices),
             np.asarray(a.indptr)), shape=a.shape)
        path = _write_mm(tmp_path, m.tocoo(), symmetry="symmetric")
        # file stores the lower triangle only; native parse must mirror it
        vals, indices, indptr, shape = bindings.mm_read(path)
        got = sp.csr_matrix((vals, indices, indptr), shape=shape)
        assert (abs(got - m)).max() < 1e-12

    def test_columns_sorted(self, tmp_path):
        m = sp.random(30, 30, density=0.2,
                      random_state=np.random.RandomState(5), format="coo")
        path = _write_mm(tmp_path, m)
        _, indices, indptr, _ = bindings.mm_read(path)
        for i in range(30):
            row = indices[indptr[i]:indptr[i + 1]]
            assert (np.diff(row) > 0).all()

    def test_missing_file(self):
        with pytest.raises(ValueError, match="could not open"):
            bindings.mm_read("/nonexistent/file.mtx")

    def test_loader_integration(self, tmp_path):
        """load_matrix_market(native=True) == (native=False)."""
        a = poisson.poisson_2d_csr(7, 6)
        path = str(tmp_path / "p.mtx")
        mmio.save_matrix_market(path, a)
        a_native = mmio.load_matrix_market(path, native=True)
        a_scipy = mmio.load_matrix_market(path, native=False)
        np.testing.assert_allclose(np.asarray(a_native.to_dense()),
                                   np.asarray(a_scipy.to_dense()),
                                   rtol=1e-14)


class TestCooToCsr:
    def test_matches_scipy_with_duplicates(self, rng):
        n, nnz = 25, 300
        rows = rng.integers(0, n, nnz).astype(np.int32)
        cols = rng.integers(0, n, nnz).astype(np.int32)
        vals = rng.standard_normal(nnz)
        out_vals, out_cols, indptr = bindings.coo_to_csr(n, rows, cols, vals)
        got = sp.csr_matrix((out_vals, out_cols, indptr), shape=(n, n))
        want = sp.coo_matrix((vals, (rows, cols)), shape=(n, n)).tocsr()
        assert (abs(got - want)).max() < 1e-12

    def test_out_of_bounds(self, rng):
        with pytest.raises(ValueError, match="out of bounds"):
            bindings.coo_to_csr(4, np.array([0, 9], np.int32),
                                np.array([0, 1], np.int32),
                                np.array([1.0, 2.0]))


class TestCsrToEll:
    def test_matches_python_path(self, rng):
        m = sp.random(50, 50, density=0.1,
                      random_state=np.random.RandomState(7), format="csr")
        m.sort_indices()
        vals, cols = bindings.csr_to_ell(m.indptr, m.indices, m.data)
        # reconstruct and compare
        n = 50
        recon = np.zeros((n, n))
        for i in range(n):
            for k in range(vals.shape[1]):
                recon[i, cols[i, k]] += vals[i, k]
        np.testing.assert_allclose(recon, m.toarray(), rtol=1e-12)

    def test_width_too_small(self):
        a = poisson.poisson_2d_csr(4, 4)
        with pytest.raises(ValueError, match="width"):
            bindings.csr_to_ell(np.asarray(a.indptr),
                                np.asarray(a.indices),
                                np.asarray(a.data), width=2)

    def test_operator_to_ell_uses_native(self, rng):
        """CSRMatrix.to_ell via the native path matches SpMV semantics."""
        import jax.numpy as jnp

        a = poisson.poisson_2d_csr(9, 8)
        e = a.to_ell()
        x = jnp.asarray(rng.standard_normal(72))
        np.testing.assert_allclose(np.asarray(e @ x), np.asarray(a @ x),
                                   rtol=1e-12, atol=1e-12)


class TestRCM:
    """Reverse Cuthill-McKee reordering (native) + CSRMatrix integration."""

    def _poisson_csr(self, n=24):
        from cuda_mpi_parallel_tpu.models import poisson

        return poisson.poisson_2d_csr(n, n, dtype=np.float64)

    def test_perm_is_permutation(self):
        a = self._poisson_csr()
        perm = bindings.rcm_order(np.asarray(a.indptr),
                                  np.asarray(a.indices))
        n = a.shape[0]
        assert perm.shape == (n,)
        assert np.array_equal(np.sort(perm), np.arange(n))

    def test_scrambled_poisson_bandwidth_restored(self):
        """Random symmetric permutation explodes the Laplacian's bandwidth;
        RCM must bring it back to O(grid) (scipy's RCM is the quality
        reference: within 2x)."""
        import scipy.sparse as sp
        from scipy.sparse.csgraph import reverse_cuthill_mckee

        a = self._poisson_csr()
        n = a.shape[0]
        rng = np.random.default_rng(21)
        scramble = rng.permutation(n).astype(np.int32)
        scrambled = a.permuted(scramble)
        bw_scrambled = scrambled.bandwidth()
        assert bw_scrambled > 5 * a.bandwidth()

        perm = scrambled.rcm_permutation()
        restored = scrambled.permuted(perm)
        bw_native = restored.bandwidth()

        m = sp.csr_matrix((np.asarray(scrambled.data),
                           np.asarray(scrambled.indices),
                           np.asarray(scrambled.indptr)), shape=(n, n))
        sperm = np.asarray(reverse_cuthill_mckee(m, symmetric_mode=True))
        srestored = scrambled.permuted(sperm)
        assert bw_native <= 2 * srestored.bandwidth()
        assert bw_native < bw_scrambled / 4

    def test_permuted_solve_equivalence(self):
        """Solving P A P^T x' = P b and scattering back equals solving the
        original system."""
        import jax.numpy as jnp

        from cuda_mpi_parallel_tpu import solve

        a = self._poisson_csr(12)
        n = a.shape[0]
        rng = np.random.default_rng(22)
        x_true = rng.standard_normal(n)
        b = np.asarray(a @ jnp.asarray(x_true))
        perm = a.rcm_permutation()
        ap = a.permuted(perm)
        res = solve(ap, jnp.asarray(b[perm]), tol=1e-10, maxiter=2000)
        assert bool(res.converged)
        x = np.empty(n)
        x[perm] = np.asarray(res.x)
        np.testing.assert_allclose(x, x_true, atol=1e-7)

    def test_permute_roundtrip_values(self):
        a = self._poisson_csr(8)
        n = a.shape[0]
        rng = np.random.default_rng(23)
        perm = rng.permutation(n).astype(np.int32)
        ap = a.permuted(perm)
        dense = np.asarray(a.to_dense())
        densep = np.asarray(ap.to_dense())
        np.testing.assert_allclose(densep, dense[np.ix_(perm, perm)])

    def test_python_fallback_matches_native(self, monkeypatch):
        a = self._poisson_csr(8)
        n = a.shape[0]
        rng = np.random.default_rng(24)
        perm = rng.permutation(n).astype(np.int32)
        native = a.permuted(perm)
        monkeypatch.setattr(bindings, "available", lambda: False)
        fallback = a.permuted(perm)
        np.testing.assert_array_equal(np.asarray(native.indptr),
                                      np.asarray(fallback.indptr))
        np.testing.assert_array_equal(np.asarray(native.indices),
                                      np.asarray(fallback.indices))
        np.testing.assert_allclose(np.asarray(native.data),
                                   np.asarray(fallback.data))

    def test_disconnected_components(self):
        """Block-diagonal graph: RCM must order every component."""
        import scipy.sparse as sp

        from cuda_mpi_parallel_tpu.models.operators import CSRMatrix

        blocks = [sp.diags([np.ones(4), 2 * np.ones(5), np.ones(4)],
                           [-1, 0, 1]) for _ in range(3)]
        m = sp.block_diag(blocks, format="csr")
        m.sort_indices()
        a = CSRMatrix.from_scipy(m)
        perm = bindings.rcm_order(np.asarray(a.indptr),
                                  np.asarray(a.indices))
        assert np.array_equal(np.sort(perm), np.arange(15))
        assert a.permuted(perm).bandwidth() <= 1


class TestRCMAsymmetric:
    """Regression tests for the asymmetric-pattern bugs (review findings):
    rcm_order used to emit a non-bijective perm for asymmetric patterns,
    and csr_permute_sym used to overflow its output buffers given one."""

    def test_asymmetric_pattern_still_yields_permutation(self):
        # row 0 lists col 2, but row 2 does not list col 0
        indptr = np.array([0, 2, 3, 4], dtype=np.int32)
        indices = np.array([0, 2, 1, 2], dtype=np.int32)
        perm = bindings.rcm_order(indptr, indices)
        assert np.array_equal(np.sort(perm), np.arange(3))

    def test_permute_sym_rejects_non_bijective_perm(self):
        indptr = np.array([0, 2, 3, 4], dtype=np.int32)
        indices = np.array([0, 2, 1, 2], dtype=np.int32)
        vals = np.ones(4)
        with pytest.raises(ValueError):
            bindings.csr_permute_sym(indptr, indices, vals,
                                     np.array([0, 0, 0], dtype=np.int32))
