"""Geometric multigrid V-cycle tests.

Oracles: transfer-operator adjointness (R = P^T / 2^d), V-cycle symmetry
and positive definiteness (required for use inside plain CG),
grid-INDEPENDENT PCG iteration counts (the property that distinguishes MG
from every other preconditioner here), and 1-vs-8-device parity of the
distributed cycle.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cuda_mpi_parallel_tpu import solve
from cuda_mpi_parallel_tpu.models import poisson
from cuda_mpi_parallel_tpu.models.multigrid import (
    MultigridPreconditioner,
    _prolong,
    _restrict,
)
from cuda_mpi_parallel_tpu.models.operators import Stencil2D, Stencil3D


class TestTransfers:
    @pytest.mark.parametrize("grid", [(16, 16), (32, 8)])
    def test_adjoint_2d(self, rng, grid):
        """<P e, f> == 2^d <e, R f> (R = P^T / 4 in 2D)."""
        nc = (grid[0] // 2) * (grid[1] // 2)
        e = jnp.asarray(rng.standard_normal(nc))
        f = jnp.asarray(rng.standard_normal(grid[0] * grid[1]))
        lhs = float(jnp.vdot(_prolong(e, grid), f))
        rhs = 4.0 * float(jnp.vdot(e, _restrict(f, grid)))
        assert abs(lhs - rhs) < 1e-10 * max(1.0, abs(lhs))

    def test_adjoint_3d(self, rng):
        grid = (8, 8, 8)
        e = jnp.asarray(rng.standard_normal(4 * 4 * 4))
        f = jnp.asarray(rng.standard_normal(8 * 8 * 8))
        lhs = float(jnp.vdot(_prolong(e, grid), f))
        rhs = 8.0 * float(jnp.vdot(e, _restrict(f, grid)))
        assert abs(lhs - rhs) < 1e-10 * max(1.0, abs(lhs))

    def test_prolong_preserves_constants_in_interior(self):
        """Bilinear interpolation reproduces constants away from the
        Dirichlet boundary (where the zero halo correctly decays)."""
        grid = (16, 16)
        e = jnp.ones(64)
        p = np.asarray(_prolong(e, grid)).reshape(grid)
        np.testing.assert_allclose(p[2:-2, 2:-2], 1.0, rtol=1e-14)


class TestVCycle:
    def test_symmetric_positive_definite(self, rng):
        n = 16
        a = poisson.poisson_2d_operator(n, n, dtype=jnp.float64)
        m = MultigridPreconditioner.from_operator(a)
        v = jnp.asarray(rng.standard_normal(n * n))
        w = jnp.asarray(rng.standard_normal(n * n))
        sym_l = float(jnp.vdot(w, m @ v))
        sym_r = float(jnp.vdot(v, m @ w))
        assert abs(sym_l - sym_r) < 1e-11 * max(1.0, abs(sym_l))
        assert float(jnp.vdot(v, m @ v)) > 0

    def test_hierarchy_depth(self):
        a = poisson.poisson_2d_operator(64, 64, dtype=jnp.float64)
        m = MultigridPreconditioner.from_operator(a)
        # 64 -> 32 -> 16 -> 8 -> 4 -> 2
        assert m.n_levels == 6
        assert m.ops[-1].grid == (2, 2)

    def test_odd_extent_stops_coarsening(self):
        a = poisson.poisson_2d_operator(48, 48, dtype=jnp.float64)
        m = MultigridPreconditioner.from_operator(a)
        # 48 -> 24 -> 12 -> 6 -> 3; 3 is odd so coarsening stops there
        assert m.ops[-1].grid == (3, 3)

    def test_grid_independent_iterations_2d(self):
        """THE multigrid property: iteration count does not grow with n."""
        rng = np.random.default_rng(5)
        iters = {}
        for n in (64, 128, 256):
            a = poisson.poisson_2d_operator(n, n, dtype=jnp.float64)
            b = jnp.asarray(rng.standard_normal(n * n))
            m = MultigridPreconditioner.from_operator(a)
            res = solve(a, b, tol=0.0, rtol=1e-8, maxiter=200, m=m)
            assert bool(res.converged)
            iters[n] = int(res.iterations)
        assert iters[256] <= 25
        assert iters[256] <= iters[64] + 5

    def test_grid_independent_iterations_3d(self):
        rng = np.random.default_rng(6)
        iters = {}
        for n in (16, 32):
            a = poisson.poisson_3d_operator(n, n, n, dtype=jnp.float64)
            b = jnp.asarray(rng.standard_normal(n ** 3))
            m = MultigridPreconditioner.from_operator(a)
            res = solve(a, b, tol=0.0, rtol=1e-8, maxiter=200, m=m)
            assert bool(res.converged)
            iters[n] = int(res.iterations)
        assert iters[32] <= 25
        assert iters[32] <= iters[16] + 5

    def test_solution_correct(self):
        n = 64
        a = poisson.poisson_2d_operator(n, n, dtype=jnp.float64)
        x_true = np.random.default_rng(7).standard_normal(n * n)
        b = a @ jnp.asarray(x_true)
        m = MultigridPreconditioner.from_operator(a)
        res = solve(a, b, tol=0.0, rtol=1e-10, maxiter=200, m=m)
        assert bool(res.converged)
        np.testing.assert_allclose(np.asarray(res.x), x_true, atol=1e-7)

    def test_coarse_levels_force_xla_backend(self):
        """Pallas tile constraints do not survive halving; coarse levels
        must always fall back to the fused-XLA stencil path."""
        a = poisson.poisson_2d_operator(256, 256, dtype=jnp.float32,
                                        backend="pallas")
        m = MultigridPreconditioner.from_operator(a)
        assert m.ops[0].backend == "pallas"
        assert all(op.backend == "xla" for op in m.ops[1:])

    def test_jit_once(self):
        """The whole MG-PCG solve lives inside one jitted while_loop."""
        n = 32
        a = poisson.poisson_2d_operator(n, n, dtype=jnp.float64)
        b = jnp.ones(n * n)
        m = MultigridPreconditioner.from_operator(a)
        res = jax.jit(
            lambda op, rhs, mm: solve(op, rhs, tol=1e-8, maxiter=100, m=mm)
        )(a, b, m)
        assert bool(res.converged)


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")
class TestDistributedMultigrid:
    def test_matches_single_device(self):
        from cuda_mpi_parallel_tpu.parallel import make_mesh, solve_distributed

        n = 64
        a = Stencil2D.create(n, n, dtype=jnp.float64)
        x_true = np.random.default_rng(8).standard_normal(n * n)
        b = a @ jnp.asarray(x_true)

        single = solve(a, b, tol=0.0, rtol=1e-9, maxiter=200,
                       m=MultigridPreconditioner.from_operator(a))
        dist = solve_distributed(a, b, mesh=make_mesh(8), tol=0.0,
                                 rtol=1e-9, maxiter=200,
                                 preconditioner="mg")
        assert bool(dist.converged)
        # Same algorithm: halo-exchanging transfers plus the gather-level
        # continuation make the distributed V-cycle EXACTLY the
        # single-device cycle up to psum rounding.
        assert abs(int(dist.iterations) - int(single.iterations)) <= 1
        np.testing.assert_allclose(np.asarray(dist.x), x_true, atol=1e-7)

    def test_gather_level_restores_full_hierarchy(self):
        """Over 8 shards of a 128^2 grid the local extent halves only
        128/8=16 -> 2; the hierarchy must continue on the replicated
        global grid to the single-device depth (this config diverged -
        17 vs 15 iterations - before the gather level existed)."""
        from cuda_mpi_parallel_tpu.parallel import make_mesh, solve_distributed

        n = 128
        a = Stencil2D.create(n, n, dtype=jnp.float64)
        x_true = np.random.default_rng(10).standard_normal(n * n)
        b = a @ jnp.asarray(x_true)
        single = solve(a, b, tol=0.0, rtol=1e-9, maxiter=200,
                       m=MultigridPreconditioner.from_operator(a))
        dist = solve_distributed(a, b, mesh=make_mesh(8), tol=0.0,
                                 rtol=1e-9, maxiter=200,
                                 preconditioner="mg")
        assert bool(dist.converged)
        assert abs(int(dist.iterations) - int(single.iterations)) <= 1
        np.testing.assert_allclose(np.asarray(dist.x), x_true, atol=1e-7)

    def test_3d_distributed(self):
        from cuda_mpi_parallel_tpu.parallel import make_mesh, solve_distributed

        n = 32
        a = Stencil3D.create(n, n, n, dtype=jnp.float64)
        x_true = np.random.default_rng(9).standard_normal(n ** 3)
        b = a @ jnp.asarray(x_true)
        dist = solve_distributed(a, b, mesh=make_mesh(8), tol=0.0,
                                 rtol=1e-9, maxiter=200,
                                 preconditioner="mg")
        assert bool(dist.converged)
        assert int(dist.iterations) <= 25
        np.testing.assert_allclose(np.asarray(dist.x), x_true, atol=1e-6)
