"""Many-RHS solver tier (solver.many + parallel.solve_distributed_many).

The tier's claims are all checkable numbers: batched BLAS-1 columns
must be BIT-identical to the single-RHS ops on those columns, a k=1
masked batched solve must reproduce ``solve()``'s iterates bit-for-bit,
per-lane convergence masks must freeze each lane exactly where its own
single-RHS solve would stop, block-CG must converge in measurably
fewer iterations than the independent recurrences (and fall back to
them on Gram breakdown without aborting), and a mesh-4 batched solve
must ship ONE halo exchange per iteration serving all k columns -
asserted against the jaxpr-derived comm account.
"""
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cuda_mpi_parallel_tpu import solve, telemetry
from cuda_mpi_parallel_tpu.models import mmio, poisson
from cuda_mpi_parallel_tpu.models.operators import (
    CSRMatrix,
    JacobiPreconditioner,
    Stencil2D,
)
from cuda_mpi_parallel_tpu.ops import blas1
from cuda_mpi_parallel_tpu.solver import CGStatus, solve_many
from cuda_mpi_parallel_tpu.solver.many import cg_many
from cuda_mpi_parallel_tpu.telemetry import events
from cuda_mpi_parallel_tpu.telemetry.flight import (
    FlightConfig,
    lanes_from_buffer,
)
from cuda_mpi_parallel_tpu.telemetry.health import assess_lanes
from cuda_mpi_parallel_tpu.utils import compat

needs_mesh = pytest.mark.skipif(
    not compat.has_shard_map() or len(jax.devices()) < 4,
    reason="needs shard_map and >= 4 (virtual) devices")

FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures",
                       "skewed_spd_240.mtx")


def _stack_system(a, k, seed=3, dtype=None):
    """(X_true, B) with B = A @ X_true, per-lane known solutions."""
    rng = np.random.default_rng(seed)
    n = int(a.shape[0])
    x_true = rng.standard_normal((n, k))
    if dtype is not None:
        x_true = x_true.astype(dtype)
    b = np.array(a.matmat(jnp.asarray(x_true)))  # writable host copy
    return x_true, b


class TestBlas1Many:
    """Satellite: column j of every batched op equals the single-RHS
    op on column j - bit-for-bit, f32 and df64 (compensated) lanes."""

    def _stacks(self, dtype, n=1037, k=5):
        rng = np.random.default_rng(11)
        x = rng.standard_normal((n, k)).astype(dtype)
        y = rng.standard_normal((n, k)).astype(dtype)
        return jnp.asarray(x), jnp.asarray(y)

    @pytest.mark.parametrize("dtype", [np.float32, np.float64])
    def test_dot_many_column_bitwise(self, dtype):
        x, y = self._stacks(dtype)
        batched = np.asarray(jax.jit(blas1.dot_many)(x, y))
        for j in range(x.shape[1]):
            single = jax.jit(blas1.dot)(x[:, j], y[:, j])
            assert batched[j] == float(single)

    @pytest.mark.parametrize("dtype", [np.float32, np.float64])
    def test_dot_many_compensated_column_bitwise(self, dtype):
        x, y = self._stacks(dtype)
        batched = np.asarray(jax.jit(blas1.dot_many_compensated)(x, y))
        for j in range(x.shape[1]):
            single = jax.jit(blas1.dot_compensated)(x[:, j], y[:, j])
            assert batched[j] == float(single)

    def test_dot_many_compensated_beats_plain_f32(self):
        # the df64 lane's reason to exist: a sign-cancelling f32 dot
        rng = np.random.default_rng(5)
        big = rng.standard_normal(4096) * 1e4
        x = np.stack([big, big], axis=1).astype(np.float32)
        y = np.stack([big, -big], axis=1).astype(np.float32)
        y[1::2, 1] = big[1::2].astype(np.float32)  # partial cancel
        exact = np.einsum("nk,nk->k", x.astype(np.float64),
                          y.astype(np.float64))
        comp = np.asarray(blas1.dot_many_compensated(
            jnp.asarray(x), jnp.asarray(y))).astype(np.float64)
        plain = np.asarray(blas1.dot_many(
            jnp.asarray(x), jnp.asarray(y))).astype(np.float64)
        err_comp = np.abs(comp - exact)
        err_plain = np.abs(plain - exact)
        assert err_comp[1] <= err_plain[1]
        assert err_comp[1] <= 4 * np.abs(exact[1]) * 2 ** -24 \
            + 1e-30

    @pytest.mark.parametrize("dtype", [np.float32, np.float64])
    def test_axpy_xpby_many_column_bitwise(self, dtype):
        x, y = self._stacks(dtype)
        alpha = jnp.asarray(
            np.asarray([0.37, -1.25, 3.0, 1e-3, -7.5], dtype))
        ax = np.asarray(jax.jit(blas1.axpy_many)(alpha, x, y))
        xb = np.asarray(jax.jit(blas1.xpby_many)(x, alpha, y))
        for j in range(x.shape[1]):
            np.testing.assert_array_equal(
                ax[:, j],
                np.asarray(jax.jit(blas1.axpy)(alpha[j], x[:, j],
                                               y[:, j])))
            np.testing.assert_array_equal(
                xb[:, j],
                np.asarray(jax.jit(blas1.xpby)(x[:, j], alpha[j],
                                               y[:, j])))

    def test_axpy_many_hand_checked(self):
        x = jnp.asarray([[1.0, 10.0], [2.0, 20.0]])
        y = jnp.asarray([[100.0, 1000.0], [200.0, 2000.0]])
        out = np.asarray(blas1.axpy_many(jnp.asarray([2.0, -1.0]),
                                         x, y))
        np.testing.assert_array_equal(
            out, [[102.0, 990.0], [204.0, 1980.0]])

    def test_gram_matches_dense(self):
        x, y = self._stacks(np.float64, n=64, k=3)
        g = np.asarray(blas1.gram(x, y))
        np.testing.assert_allclose(g, np.asarray(x).T @ np.asarray(y),
                                   rtol=1e-13)


class TestMatmatParity:
    """SpMM formats: column j of matmat == matvec of column j."""

    @pytest.mark.parametrize("convert", [
        lambda a: a,                      # CSR
        lambda a: a.to_ell(),             # padded ELL
        lambda a: a.to_dia(),             # gather-free DIA
    ])
    def test_assembled_formats_bitwise(self, convert):
        a = convert(poisson.poisson_2d_csr(12, 12, dtype=np.float64))
        rng = np.random.default_rng(2)
        x = jnp.asarray(rng.standard_normal((a.shape[0], 4)))
        batched = np.asarray(jax.jit(a.matmat)(x))
        for j in range(4):
            np.testing.assert_array_equal(
                batched[:, j], np.asarray(jax.jit(a.matvec)(x[:, j])))

    def test_default_vmap_matmat_stencil(self):
        a = Stencil2D.create(8, 8, dtype=jnp.float64)
        rng = np.random.default_rng(2)
        x = jnp.asarray(rng.standard_normal((64, 3)))
        batched = np.asarray(a.matmat(x))
        for j in range(3):
            np.testing.assert_allclose(
                batched[:, j], np.asarray(a.matvec(x[:, j])),
                rtol=1e-14)


class TestMaskedBatched:
    def test_k1_bitwise_matches_solve(self):
        """ISSUE acceptance: k=1 masked-batched == solve() bit-for-bit
        (iterates, count, residual)."""
        a = poisson.poisson_2d_csr(16, 16, dtype=np.float64)
        _, b = _stack_system(a, 1)
        single = solve(a, b[:, 0], tol=1e-10, maxiter=500)
        many = solve_many(a, b, tol=1e-10, maxiter=500)
        np.testing.assert_array_equal(np.asarray(single.x),
                                      np.asarray(many.x[:, 0]))
        assert int(single.iterations) == int(many.iterations[0])
        # the scalar ||r||^2 reduce may fuse differently inside the
        # batched loop (same summation order, different FMA
        # contraction) - ulp-level only, the ITERATES are exact
        np.testing.assert_allclose(float(many.residual_norm[0]),
                                   float(single.residual_norm),
                                   rtol=1e-12)
        assert bool(many.converged[0])

    def test_k1_bitwise_matches_solve_f32(self):
        a = poisson.poisson_2d_csr(16, 16, dtype=np.float32)
        _, b = _stack_system(a, 1, dtype=np.float32)
        single = solve(a, b[:, 0], tol=1e-4, maxiter=500)
        many = solve_many(a, b, tol=1e-4, maxiter=500)
        np.testing.assert_array_equal(np.asarray(single.x),
                                      np.asarray(many.x[:, 0]))
        assert int(single.iterations) == int(many.iterations[0])

    def test_k1_bitwise_matches_solve_jacobi(self):
        a = poisson.poisson_2d_csr(16, 16, dtype=np.float64)
        m = JacobiPreconditioner.from_operator(a)
        _, b = _stack_system(a, 1)
        single = solve(a, b[:, 0], tol=1e-10, maxiter=500, m=m)
        many = solve_many(a, b, tol=1e-10, maxiter=500, m=m)
        np.testing.assert_array_equal(np.asarray(single.x),
                                      np.asarray(many.x[:, 0]))
        assert int(single.iterations) == int(many.iterations[0])

    def test_lanes_bitwise_match_singles(self):
        """Each lane of a k=6 batch freezes exactly where - and with
        exactly the bits - its own single-RHS solve stops.  Batching
        changes nothing about any answer."""
        a = poisson.poisson_2d_csr(16, 16, dtype=np.float64)
        _, b = _stack_system(a, 6)
        many = solve_many(a, b, tol=1e-10, maxiter=500)
        for j in range(6):
            single = solve(a, b[:, j], tol=1e-10, maxiter=500)
            np.testing.assert_array_equal(np.asarray(single.x),
                                          np.asarray(many.x[:, j]))
            assert int(single.iterations) == int(many.iterations[j])

    def test_zero_rhs_lane_converges_at_iteration_zero(self):
        """A b=0 column is solved exactly by x0=0: its lane must
        freeze at 0 iterations, CONVERGED, while its neighbors run."""
        a = poisson.poisson_2d_csr(12, 12, dtype=np.float64)
        _, b = _stack_system(a, 3)
        b[:, 1] = 0.0
        res = solve_many(a, b, tol=1e-10, maxiter=500)
        iters = np.asarray(res.iterations)
        assert iters[1] == 0
        assert iters[0] > 0 and iters[2] > 0
        assert np.asarray(res.converged).all()
        assert np.asarray(res.status)[1] == int(CGStatus.CONVERGED)
        np.testing.assert_array_equal(np.asarray(res.x[:, 1]),
                                      np.zeros(a.shape[0]))

    def test_mixed_tolerances_freeze_per_lane(self):
        """Per-lane tol arrays: each lane stops on ITS bar, and the
        frozen lane bit-matches a single solve at that same bar."""
        a = poisson.poisson_2d_csr(16, 16, dtype=np.float64)
        _, b = _stack_system(a, 3)
        tols = np.asarray([1e-4, 1e-8, 1e-11])
        res = solve_many(a, b, tol=tols, maxiter=500)
        iters = np.asarray(res.iterations)
        assert iters[0] < iters[1] < iters[2]
        assert np.asarray(res.converged).all()
        rn = np.asarray(res.residual_norm)
        assert (rn < tols).all()
        for j, t in enumerate(tols):
            single = solve(a, b[:, j], tol=float(t), maxiter=500)
            np.testing.assert_array_equal(np.asarray(single.x),
                                          np.asarray(res.x[:, j]))
            assert int(single.iterations) == int(iters[j])

    def test_stagnating_lane_classified_while_others_converge(self):
        """ISSUE acceptance: one lane hits a non-CONVERGED trace
        verdict (STAGNATED/DIVERGED - the f32 attainable floor on a
        kappa=1e8 system) while a lane whose RHS lives in the
        well-conditioned subspace converges - per-lane CGStatus
        asserted through the per-lane flight records."""
        eigs = np.logspace(0, -8, 48)
        a = jnp.asarray(np.diag(eigs).astype(np.float32))
        b = np.zeros((48, 2), np.float32)
        b[:, 0] = 1.0                  # touches the 1e-8 eigenvalues
        b[:4, 1] = 1.0                 # large-eigenvalue subspace only
        res = solve_many(a, b, tol=np.asarray([1e-12, 1e-5],
                                              np.float32),
                         maxiter=400, flight=FlightConfig.for_solve(400))
        conv = np.asarray(res.converged)
        assert not conv[0] and conv[1]
        assert np.asarray(res.status)[0] == int(CGStatus.MAXITER)
        recs = lanes_from_buffer(res.flight, 2)
        healths = assess_lanes(recs, converged=res.converged,
                               statuses=res.status,
                               iterations=res.iterations)
        assert healths[0].classification in (CGStatus.STAGNATED,
                                             CGStatus.DIVERGED)
        assert healths[1].classification == CGStatus.CONVERGED

    def test_flight_lane_records_match_single_rhs_recorder(self):
        """The batched recorder's per-lane rows carry the same
        (rr, alpha, beta) scalars the single-RHS recorder writes."""
        a = poisson.poisson_2d_csr(12, 12, dtype=np.float64)
        _, b = _stack_system(a, 2)
        cfg = FlightConfig.for_solve(300)
        many = solve_many(a, b, tol=1e-9, maxiter=300, flight=cfg)
        recs = lanes_from_buffer(many.flight, 2, stride=cfg.stride)
        for j in range(2):
            from cuda_mpi_parallel_tpu.telemetry.flight import (
                FlightRecord,
            )

            single = solve(a, b[:, j], tol=1e-9, maxiter=300,
                           flight=cfg)
            srec = FlightRecord.from_buffer(single.flight,
                                            stride=cfg.stride)
            m = len(srec)
            np.testing.assert_array_equal(recs[j].iterations[:m],
                                          srec.iterations)
            np.testing.assert_array_equal(recs[j].residual_sq[:m],
                                          srec.residual_sq)
            np.testing.assert_array_equal(recs[j].alphas[1:m],
                                          srec.alphas[1:])

    def test_check_every_converges_identically_frozen(self):
        a = poisson.poisson_2d_csr(16, 16, dtype=np.float64)
        x_true, b = _stack_system(a, 4)
        res = solve_many(a, b, tol=1e-10, maxiter=500, check_every=8)
        assert np.asarray(res.converged).all()
        assert np.max(np.abs(np.asarray(res.x) - x_true)) < 1e-7

    def test_compensated_batched_runs(self):
        a = poisson.poisson_2d_csr(12, 12, dtype=np.float32)
        x_true, b = _stack_system(a, 3, dtype=np.float32)
        res = solve_many(a, b, tol=1e-4, maxiter=500, compensated=True)
        assert np.asarray(res.converged).all()

    def test_shape_and_method_validation(self):
        a = poisson.poisson_2d_csr(8, 8, dtype=np.float64)
        b1 = np.ones(64)
        with pytest.raises(ValueError, match="column stack"):
            solve_many(a, b1)
        with pytest.raises(ValueError, match="unknown method"):
            solve_many(a, np.ones((64, 2)), method="minres")
        with pytest.raises(ValueError, match="batched flight"):
            cg_many(a, jnp.ones((64, 2)), method="block",
                    flight=FlightConfig(capacity=8))


class TestBlockCG:
    def test_fewer_iterations_than_batched(self):
        """ISSUE acceptance: on a well-conditioned SPD system the
        coupled k-dim Krylov space converges in measurably fewer
        iterations than the independent masked recurrences."""
        a = poisson.poisson_2d_csr(24, 24, dtype=np.float64)
        x_true, b = _stack_system(a, 8)
        batched = solve_many(a, b, tol=1e-9, maxiter=800)
        block = solve_many(a, b, tol=1e-9, maxiter=800, method="block")
        assert np.asarray(block.converged).all()
        assert not bool(block.fallback)
        it_block = int(np.asarray(block.iterations).max())
        it_batched = int(np.asarray(batched.iterations).max())
        assert it_block < it_batched
        assert np.max(np.abs(np.asarray(block.x) - x_true)) < 1e-6

    def test_gram_collapse_deflates_in_lane(self):
        """ISSUE 13 satellite: duplicate RHS columns collapse the Gram
        rank at step one; the eigenvalue pseudo-inverse deflates the
        collapsed direction IN-LANE (no restart, fallback stays False)
        and the block Krylov space keeps converging every lane."""
        a = poisson.poisson_2d_csr(16, 16, dtype=np.float64)
        x_true, b = _stack_system(a, 4)
        b[:, 1] = b[:, 0]                      # exact rank collapse
        x_true[:, 1] = x_true[:, 0]
        res = solve_many(a, b, tol=1e-9, maxiter=800, method="block")
        assert not bool(res.fallback)          # deflated, not restarted
        assert np.asarray(res.converged).all()
        assert np.max(np.abs(np.asarray(res.x) - x_true)) < 1e-6
        # identical lanes got identical answers
        np.testing.assert_array_equal(np.asarray(res.x[:, 0]),
                                      np.asarray(res.x[:, 1]))
        # and the collapse cost no iteration-count regression vs the
        # distinct-column solve of the same operator
        _, b_distinct = _stack_system(a, 4)
        distinct = solve_many(a, b_distinct, tol=1e-9, maxiter=800,
                              method="block")
        assert int(np.asarray(res.iterations).max()) \
            <= int(np.asarray(distinct.iterations).max()) + 8

    def test_gram_breakdown_terminal_fallback_survives(self, monkeypatch):
        """Regression (ISSUE 13 satellite): when even the in-lane
        deflation cannot produce a finite Gram solve, the TERMINAL
        tier - freeze one step before poisoning + masked-batched
        continuation - still finishes the solve and flags the
        fallback (the pre-deflation contract)."""
        from cuda_mpi_parallel_tpu.solver import many as many_mod
        from cuda_mpi_parallel_tpu.solver.many import cg_many

        def broken_gram_solve(gram_mat, rhs):
            nan = jnp.full_like(rhs, jnp.nan)
            return nan, jnp.asarray(True)

        monkeypatch.setattr(many_mod, "_gram_solve", broken_gram_solve)
        a = poisson.poisson_2d_csr(16, 16, dtype=np.float64)
        x_true, b = _stack_system(a, 4)
        res = cg_many(a, b, tol=1e-9, maxiter=800, method="block")
        assert bool(res.fallback)              # terminal tier fired
        assert np.asarray(res.converged).all()
        assert np.max(np.abs(np.asarray(res.x) - x_true)) < 1e-6

    def test_block_with_jacobi(self):
        a = poisson.poisson_2d_csr(16, 16, dtype=np.float64)
        m = JacobiPreconditioner.from_operator(a)
        x_true, b = _stack_system(a, 4)
        res = solve_many(a, b, tol=1e-9, maxiter=800, method="block",
                         m=m)
        assert np.asarray(res.converged).all()
        assert np.max(np.abs(np.asarray(res.x) - x_true)) < 1e-6


@needs_mesh
class TestDistributedMany:
    def setup_method(self):
        from cuda_mpi_parallel_tpu.parallel import dist_cg

        telemetry.configure(None)
        telemetry.force_active(False)
        dist_cg.clear_solver_cache()

    teardown_method = setup_method

    def _mesh(self):
        from cuda_mpi_parallel_tpu.parallel import make_mesh

        return make_mesh(4)

    def test_one_exchange_serves_all_columns(self):
        """ISSUE acceptance: the comm account of a k=8 batched solve
        shows ONE all_gather per iteration (same collective count as a
        single-RHS solve) whose wire carries all 8 columns, and each
        lane bit-matches its single-RHS distributed solve."""
        from cuda_mpi_parallel_tpu.parallel import (
            dist_cg,
            solve_distributed,
            solve_distributed_many,
        )

        from cuda_mpi_parallel_tpu.analysis.spmd import (
            verify_collective_budget,
        )

        a = mmio.load_matrix_market(FIXTURE)
        _, b = _stack_system(a, 8, seed=5)
        mesh = self._mesh()
        telemetry.force_active(True)
        try:
            dist_cg.reset_last_comm_cost()
            many = solve_distributed_many(a, b, mesh=mesh, tol=1e-9,
                                          maxiter=500)
            sc_many, ctx_many = dist_cg.last_comm_cost()
            dist_cg.reset_last_comm_cost()
            single = solve_distributed(a, b[:, 0], mesh=mesh, tol=1e-9,
                                       maxiter=500)
            sc_one, _ = dist_cg.last_comm_cost()
        finally:
            telemetry.force_active(False)
        assert ctx_many["n_rhs"] == 8
        # same per-iteration psum/ppermute/all_gather inventory as the
        # single-RHS lane (the named budget API over the captured costs)
        report = verify_collective_budget(
            sc_many, sc_one, what="k=8 batched vs single-RHS")
        assert report.ok
        assert report.variant.all_gather == 1
        # wire-bytes stay a hand assert: the budget is about collective
        # COUNTS; the k-column wire scaling is this test's own claim
        assert sc_many.per_iteration.wire_bytes \
            == 8 * sc_one.per_iteration.wire_bytes
        np.testing.assert_array_equal(np.asarray(single.x),
                                      np.asarray(many.x[:, 0]))
        assert int(single.iterations) == int(many.iterations[0])

    def test_gather_exchange_bitwise_and_wire(self):
        """extended-x becomes extended-X: the gather rounds carry all
        columns, the schedule (and solution bits) unchanged."""
        from cuda_mpi_parallel_tpu.parallel import (
            dist_cg,
            solve_distributed_many,
        )

        a = mmio.load_matrix_market(FIXTURE)
        _, b = _stack_system(a, 4, seed=5)
        mesh = self._mesh()
        telemetry.force_active(True)
        try:
            allg = solve_distributed_many(a, b, mesh=mesh, tol=1e-9,
                                          maxiter=500,
                                          exchange="allgather")
            dist_cg.reset_last_comm_cost()
            gath = solve_distributed_many(a, b, mesh=mesh, tol=1e-9,
                                          maxiter=500,
                                          exchange="gather")
            sc, ctx = dist_cg.last_comm_cost()
        finally:
            telemetry.force_active(False)
        np.testing.assert_array_equal(np.asarray(allg.x),
                                      np.asarray(gath.x))
        assert ctx["exchange"] == "gather"
        # skewed fixture at mesh 4: 1160 coupled-wire B/iter per lane
        assert sc.per_iteration.wire_bytes == 4 * 1160
        assert ctx["halo_wire_bytes_per_matvec"] == 4 * 1160

    def test_block_wire_per_solve_beats_sequential(self):
        """ISSUE acceptance: k=8 block-CG's whole-solve wire bytes land
        strictly below 8x a single-RHS solve's (fewer iterations, same
        per-lane wire)."""
        from cuda_mpi_parallel_tpu.parallel import (
            dist_cg,
            solve_distributed,
            solve_distributed_many,
        )

        a = mmio.load_matrix_market(FIXTURE)
        x_true, b = _stack_system(a, 8, seed=5)
        mesh = self._mesh()
        telemetry.force_active(True)
        try:
            dist_cg.reset_last_comm_cost()
            blk = solve_distributed_many(a, b, mesh=mesh, tol=1e-9,
                                         maxiter=500, method="block",
                                         exchange="gather")
            sc_blk, _ = dist_cg.last_comm_cost()
            dist_cg.reset_last_comm_cost()
            single = solve_distributed(a, b[:, 0], mesh=mesh, tol=1e-9,
                                       maxiter=500, exchange="gather")
            sc_one, _ = dist_cg.last_comm_cost()
        finally:
            telemetry.force_active(False)
        assert np.asarray(blk.converged).all()
        wire_blk = sc_blk.totals(
            int(np.asarray(blk.iterations).max())).wire_bytes
        wire_seq = 8 * sc_one.totals(int(single.iterations)).wire_bytes
        assert wire_blk < wire_seq
        assert np.max(np.abs(np.asarray(blk.x) - x_true)) < 1e-6

    def test_plan_auto_composes(self):
        from cuda_mpi_parallel_tpu.parallel import solve_distributed_many

        a = mmio.load_matrix_market(FIXTURE)
        x_true, b = _stack_system(a, 3, seed=5)
        res = solve_distributed_many(a, b, mesh=self._mesh(), tol=1e-9,
                                     maxiter=500, plan="auto")
        assert np.asarray(res.converged).all()
        assert np.max(np.abs(np.asarray(res.x) - x_true)) < 1e-6

    def test_jacobi_lanes_match_singles(self):
        from cuda_mpi_parallel_tpu.parallel import (
            solve_distributed,
            solve_distributed_many,
        )

        a = mmio.load_matrix_market(FIXTURE)
        _, b = _stack_system(a, 3, seed=5)
        mesh = self._mesh()
        many = solve_distributed_many(a, b, mesh=mesh, tol=1e-9,
                                      maxiter=500,
                                      preconditioner="jacobi")
        single = solve_distributed(a, b[:, 1], mesh=mesh, tol=1e-9,
                                   maxiter=500,
                                   preconditioner="jacobi")
        assert int(single.iterations) == int(many.iterations[1])
        np.testing.assert_allclose(np.asarray(single.x),
                                   np.asarray(many.x[:, 1]),
                                   rtol=0, atol=1e-12)

    def test_refusals(self):
        from cuda_mpi_parallel_tpu.parallel import solve_distributed_many

        a = mmio.load_matrix_market(FIXTURE)
        mesh = self._mesh()
        s = Stencil2D.create(16, 16, dtype=jnp.float64)
        with pytest.raises(TypeError, match="CSRMatrix"):
            solve_distributed_many(s, np.ones((256, 2)), mesh=mesh)
        with pytest.raises(ValueError, match="column stack"):
            solve_distributed_many(a, np.ones(240), mesh=mesh)
        with pytest.raises(ValueError, match="jacobi"):
            solve_distributed_many(a, np.ones((240, 2)), mesh=mesh,
                                   preconditioner="chebyshev")
        with pytest.raises(ValueError, match="ring"):
            solve_distributed_many(a, np.ones((240, 2)), mesh=mesh,
                                   exchange="ring")
        with pytest.raises(ValueError, match="batched flight"):
            solve_distributed_many(
                a, np.ones((240, 2)), mesh=mesh, method="block",
                flight=FlightConfig(capacity=8))


@needs_mesh
class TestManyRhsCLI:
    def _clean(self):
        from cuda_mpi_parallel_tpu.parallel import dist_cg
        from cuda_mpi_parallel_tpu.telemetry.shardscope import (
            reset_last_shard_report,
        )

        telemetry.configure(None)
        telemetry.force_active(False)
        dist_cg.clear_solver_cache()
        reset_last_shard_report()

    def test_mesh4_rhs_record(self, capsys):
        from cuda_mpi_parallel_tpu import cli

        try:
            rc = cli.main(["--problem", "mm", "--file", FIXTURE,
                           "--dtype", "float64", "--mesh", "4",
                           "--rhs", "4", "--rhs-method", "block",
                           "--exchange", "gather", "--tol", "1e-8",
                           "--metrics", "--json"])
        finally:
            self._clean()
        assert rc == 0
        rec = json.loads(capsys.readouterr().out)
        assert rec["n_rhs"] == 4
        assert rec["rhs_method"] == "block"
        assert rec["converged"] is True
        assert rec["rhs_fallback"] is False
        lanes = rec["lanes"]
        assert len(lanes["iterations"]) == 4
        assert all(s == "CONVERGED" for s in lanes["status"])
        assert all(e < 1e-5 for e in lanes["max_abs_error"])
        assert rec["comm"]["exchange"] == "gather"
        assert rec["comm"]["n_shards"] == 4
        assert rec["rhs_iters_per_sec"] > 0

    def test_single_device_rhs_flight_record(self, capsys):
        from cuda_mpi_parallel_tpu import cli

        try:
            rc = cli.main(["--problem", "poisson2d", "--n", "16",
                           "--dtype", "float64", "--rhs", "3",
                           "--flight-record", "--tol", "1e-9",
                           "--json"])
        finally:
            self._clean()
        assert rc == 0
        rec = json.loads(capsys.readouterr().out)
        assert rec["n_rhs"] == 3
        assert rec["lanes"]["health"] == ["CONVERGED"] * 3
        assert rec["flight"]["n_records"] > 2

    def test_refusal_matrix(self):
        from cuda_mpi_parallel_tpu import cli

        cases = [
            (["--rhs-method", "block"], "needs --rhs"),
            (["--rhs", "2", "--engine", "resident"], "resident"),
            (["--rhs", "2", "--engine", "streaming"], "streaming"),
            (["--rhs", "2", "--dtype", "df64"], "df64"),
            (["--rhs", "2", "--history"], "flight-record"),
            (["--rhs", "2", "--method", "cg1"], "batched"),
            (["--rhs", "2", "--mesh", "4", "--csr-comm",
              "ring-shiftell"], "ring"),
            (["--rhs", "2", "--format", "shiftell"], "shiftell"),
            (["--rhs", "2", "--rhs-method", "block",
              "--flight-record"], "block"),
            (["--rhs", "2", "--flight-record", "--flight-heartbeat",
              "50"], "heartbeat"),
            (["--rhs", "2", "--mesh", "4", "--repeat", "2"],
             "repeat"),
            (["--rhs", "2", "--mesh", "4", "--precond", "chebyshev"],
             "jacobi or none"),
        ]
        base = ["--problem", "poisson2d", "--n", "8",
                "--dtype", "float64"]
        for extra, needle in cases:
            with pytest.raises(SystemExit, match=needle):
                cli.main(base + extra)
        # stencil operators refuse on a mesh (no batched halo path)
        with pytest.raises(SystemExit, match="matrix-free"):
            cli.main(base + ["--rhs", "2", "--mesh", "4",
                             "--matrix-free"])
