"""Solver tests (SURVEY SS4 'Solver' tier).

The sharpest test is the reference-trajectory oracle: the hardcoded 3x3
system (CUDACG.cu:74-117) must converge in exactly 3 iterations to
x = [0.5, 0.75, 1.0] with final ||r|| ~ 8.2e-15, *despite* p.Ap going
negative at iteration 2 (the matrix is indefinite, SURVEY quirk Q1).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cuda_mpi_parallel_tpu import (
    CGStatus,
    JacobiPreconditioner,
    cg,
    solve,
)
from cuda_mpi_parallel_tpu.models import poisson, random_spd


class TestOracle:
    """Reference-parity regression tests (CUDACG.cu loop semantics)."""

    def test_3x3_solution(self):
        a, b, x_expected = poisson.oracle_system()
        res = solve(a, b)  # defaults: tol=1e-7 abs, maxiter=2000 (CUDACG.cu:244-245)
        np.testing.assert_allclose(np.asarray(res.x), x_expected, atol=1e-10)
        assert bool(res.converged)
        assert res.status_enum() == CGStatus.CONVERGED

    def test_3x3_trajectory(self):
        """3 iterations, final ||r|| ~ 8.2e-15, indefiniteness observed."""
        a, b, _ = poisson.oracle_system()
        res = solve(a, b, record_history=True)
        assert int(res.iterations) == 3
        assert float(res.residual_norm) < 1e-13
        assert bool(res.indefinite)  # p.Ap < 0 at iteration 2 (quirk Q1)
        hist = np.asarray(res.residual_history)
        assert np.isfinite(hist[:4]).all()
        assert np.isnan(hist[4:]).all()
        # ||r0|| = ||b|| since x0 = 0 (copy-only init, CUDACG.cu:247-259)
        np.testing.assert_allclose(hist[0], np.linalg.norm([3.5, 1.5, 2.0]),
                                   rtol=1e-14)
        assert hist[3] < 1e-13

    def test_tolerance_is_absolute(self):
        """Quirk Q3: comment says relative, code is absolute ||r|| < tol."""
        a, b, _ = poisson.oracle_system()
        loose = solve(a, b, tol=1.0, record_history=True)
        # ||r0|| ~ 4.2 > 1.0, one iteration drops it below 1.0? Verify
        # against trajectory: whatever happens, threshold must not have been
        # scaled by ||r0||.
        hist = np.asarray(loose.residual_history)
        k = int(loose.iterations)
        assert hist[k] < 1.0
        if k > 0:
            assert hist[k - 1] >= 1.0

    def test_maxiter_reported_not_silent(self):
        """Reference prints 'Success' on maxit exhaustion (quirk Q4/Q7);
        we report CGStatus.MAXITER."""
        a, b, _ = poisson.oracle_system()
        res = solve(a, b, tol=1e-30, maxiter=2)
        assert not bool(res.converged)
        assert res.status_enum() == CGStatus.MAXITER
        assert int(res.iterations) == 2


class TestDenseSPD:
    def test_random_spd_matches_numpy(self):
        op = random_spd.random_spd_dense(64, cond=50.0, seed=3)
        a = np.asarray(op.a)
        rng = np.random.default_rng(0)
        b = rng.standard_normal(64)
        res = solve(op, jnp.asarray(b), tol=1e-10)
        expected = np.linalg.solve(a, b)
        np.testing.assert_allclose(np.asarray(res.x), expected, rtol=1e-6,
                                   atol=1e-8)
        assert bool(res.converged)

    def test_raw_array_accepted(self):
        rng = np.random.default_rng(5)
        q, _ = np.linalg.qr(rng.standard_normal((16, 16)))
        a = (q * np.linspace(1, 10, 16)) @ q.T
        b = rng.standard_normal(16)
        res = solve(jnp.asarray(a), jnp.asarray(b), tol=1e-10)
        np.testing.assert_allclose(np.asarray(res.x), np.linalg.solve(a, b),
                                   rtol=1e-6)

    def test_nonzero_x0(self):
        """General r0 = b - A x0 path (absent from the reference)."""
        op = random_spd.random_spd_dense(32, cond=10.0, seed=9)
        rng = np.random.default_rng(1)
        b = jnp.asarray(rng.standard_normal(32))
        x0 = jnp.asarray(rng.standard_normal(32))
        res = solve(op, b, x0, tol=1e-10)
        np.testing.assert_allclose(np.asarray(op @ res.x), np.asarray(b),
                                   atol=1e-8)

    def test_exact_start_converges_immediately(self):
        op = random_spd.random_spd_dense(16, seed=2)
        x_true = jnp.asarray(np.random.default_rng(2).standard_normal(16))
        b = op @ x_true
        res = solve(op, b, x_true, tol=1e-8)
        assert int(res.iterations) == 0
        assert bool(res.converged)


class TestSparsePoisson:
    def test_2d_poisson_csr(self):
        a = poisson.poisson_2d_csr(16, 16)
        n = a.shape[0]
        x_true = np.random.default_rng(4).standard_normal(n)
        b = a @ jnp.asarray(x_true)
        res = solve(a, b, tol=1e-9, maxiter=500)
        assert bool(res.converged)
        np.testing.assert_allclose(np.asarray(res.x), x_true, atol=1e-6)

    def test_2d_stencil_matches_csr_solution(self):
        nx = ny = 12
        a_csr = poisson.poisson_2d_csr(nx, ny)
        a_st = poisson.poisson_2d_operator(nx, ny, dtype=jnp.float64)
        b = jnp.asarray(np.random.default_rng(8).standard_normal(nx * ny))
        r1 = solve(a_csr, b, tol=1e-10, maxiter=500)
        r2 = solve(a_st, b, tol=1e-10, maxiter=500)
        np.testing.assert_allclose(np.asarray(r1.x), np.asarray(r2.x),
                                   atol=1e-7)

    def test_3d_stencil(self):
        a = poisson.poisson_3d_operator(8, 8, 8, dtype=jnp.float64)
        x_true = np.random.default_rng(6).standard_normal(512)
        b = a @ jnp.asarray(x_true)
        res = solve(a, b, tol=1e-9, maxiter=500)
        assert bool(res.converged)
        np.testing.assert_allclose(np.asarray(res.x), x_true, atol=1e-6)


class TestPreconditioning:
    def test_jacobi_reduces_iterations(self):
        """BASELINE config #3: Jacobi-PCG on an ill-scaled system."""
        rng = np.random.default_rng(11)
        n = 128
        q, _ = np.linalg.qr(rng.standard_normal((n, n)))
        a = (q * np.geomspace(1, 1e4, n)) @ q.T
        # Badly scaled diagonal amplifies what Jacobi can fix.
        d = np.geomspace(1, 100, n)
        a = a * np.outer(d, d)
        a = 0.5 * (a + a.T)
        b = jnp.asarray(rng.standard_normal(n))
        a_j = jnp.asarray(a)
        plain = solve(a_j, b, tol=1e-8, maxiter=4000)
        from cuda_mpi_parallel_tpu import DenseOperator
        op = DenseOperator(a=a_j)
        pre = solve(op, b, tol=1e-8, maxiter=4000,
                    m=JacobiPreconditioner.from_operator(op))
        assert bool(pre.converged)
        assert int(pre.iterations) < int(plain.iterations)

    def test_jacobi_same_solution(self):
        a = poisson.poisson_2d_csr(10, 10)
        b = jnp.asarray(np.random.default_rng(3).standard_normal(100))
        m = JacobiPreconditioner.from_operator(a)
        r1 = solve(a, b, tol=1e-10, maxiter=500)
        r2 = solve(a, b, tol=1e-10, maxiter=500, m=m)
        np.testing.assert_allclose(np.asarray(r1.x), np.asarray(r2.x),
                                   atol=1e-7)


class TestRobustness:
    def test_relative_tolerance(self):
        a = poisson.poisson_2d_csr(12, 12)
        b = jnp.asarray(np.random.default_rng(7).standard_normal(144)) * 1e6
        res = solve(a, b, tol=0.0, rtol=1e-8, maxiter=1000,
                    record_history=True)
        hist = np.asarray(res.residual_history)
        assert bool(res.converged)
        assert hist[int(res.iterations)] < 1e-8 * hist[0]

    def test_breakdown_detected_on_singular(self):
        """A singular system with b outside range(A) cannot converge; the
        solver must stop with a typed status, never iterate on NaNs
        silently (quirk Q4)."""
        a = jnp.zeros((4, 4), dtype=jnp.float64)
        b = jnp.ones(4, dtype=jnp.float64)
        res = solve(a, b, maxiter=10)
        assert not bool(res.converged)
        assert res.status_enum() in (CGStatus.BREAKDOWN, CGStatus.MAXITER)
        assert res.status_enum() == CGStatus.BREAKDOWN

    def test_zero_rhs(self):
        a = poisson.poisson_2d_csr(5, 5)
        b = jnp.zeros(25, dtype=jnp.float64)
        res = solve(a, b)
        assert int(res.iterations) == 0
        np.testing.assert_array_equal(np.asarray(res.x), np.zeros(25))

    def test_int_rhs_keeps_tolerance(self):
        """Integer b must not zero out the tolerance via dtype casting:
        the oracle still converges in 3 iterations for a float-equivalent
        rhs."""
        a, b, _ = poisson.oracle_system()
        res_f = solve(a, b * 2)
        res_i = solve(a, jnp.asarray([7, 3, 4]))  # 2*b as ints
        assert int(res_i.iterations) == int(res_f.iterations)
        np.testing.assert_allclose(np.asarray(res_i.x), np.asarray(res_f.x),
                                   atol=1e-10)

    def test_float32(self):
        """TPU-default dtype path: f32 solve with looser tolerance."""
        a = poisson.poisson_2d_csr(8, 8, dtype=np.float32)
        x_true = np.random.default_rng(12).standard_normal(64).astype(np.float32)
        b = a @ jnp.asarray(x_true)
        res = solve(a, b, tol=1e-4, maxiter=300)
        assert bool(res.converged)
        assert res.x.dtype == jnp.float32
        np.testing.assert_allclose(np.asarray(res.x), x_true, atol=1e-2)


class TestJitIntegration:
    def test_cg_inside_user_jit(self):
        """cg() must compose with an outer jit (pure traceable function)."""
        a = poisson.poisson_2d_csr(6, 6)

        @jax.jit
        def solve_shifted(shift):
            b = jnp.full(36, shift, dtype=jnp.float64)
            return cg(a, b, tol=1e-9, maxiter=200).x

        x1 = solve_shifted(1.0)
        x2 = solve_shifted(2.0)
        np.testing.assert_allclose(np.asarray(x2), 2 * np.asarray(x1),
                                   rtol=1e-6)

    def test_grad_through_solve(self):
        """Differentiability: d/db of x(b) = A^-1 b is A^-1 g - CG is pure
        JAX so implicit-function-free autodiff through the loop works for
        fixed iteration counts via checkpointing-free unrolled vjp is NOT
        supported through while_loop; instead verify jax.linearize on
        matvec path works (smoke)."""
        a = poisson.poisson_2d_csr(4, 4)
        x = jnp.ones(16, dtype=jnp.float64)
        y, jvp = jax.linearize(lambda v: a @ v, x)
        np.testing.assert_allclose(np.asarray(jvp(x)), np.asarray(y),
                                   rtol=1e-12)
