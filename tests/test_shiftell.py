"""Shift-ELL pallas SpMV: packing, matvec parity, and CG integration.

The kernel runs compiled on TPU and in pallas interpret mode here (CPU
test env) - same code path as the stencil kernels' test strategy.
"""
import jax.numpy as jnp
import numpy as np
import pytest

from cuda_mpi_parallel_tpu import solve
from cuda_mpi_parallel_tpu.models import poisson
from cuda_mpi_parallel_tpu.models.fem import random_fem_2d
from cuda_mpi_parallel_tpu.models.operators import ShiftELLMatrix
from cuda_mpi_parallel_tpu.ops.pallas import spmv as pk


def _parity(a_csr, h, rng, rtol=1e-12):
    n = a_csr.shape[0]
    a_sell = a_csr.to_shiftell(h=h)
    x = jnp.asarray(rng.standard_normal(n))
    y_ref = np.asarray(a_csr @ x)
    y = np.asarray(a_sell @ x)
    np.testing.assert_allclose(y, y_ref, rtol=rtol, atol=1e-12)
    return a_sell


class TestPacking:
    def test_slot_conservation(self, rng):
        """Every nonzero lands in exactly one sheet slot; empty slots are
        zero-valued."""
        a = random_fem_2d(500, seed=2)
        packed = pk.pack_shift_ell(np.asarray(a.indptr),
                                   np.asarray(a.indices),
                                   np.asarray(a.data), a.shape[0], h=4)
        assert packed.lane_idx.shape == (packed.n_chunks, packed.kc,
                                         packed.h, 128)
        assert packed.vals.shape == (packed.n_chunks, packed.kc,
                                     packed.h + 1, 128)
        # sum of all slot values == sum of all matrix values (0-padding)
        slot_vals = packed.vals[:, :, :packed.h, :]
        np.testing.assert_allclose(slot_vals.sum(),
                                   np.asarray(a.data).sum(), rtol=1e-12)
        nonzero_slots = np.count_nonzero(slot_vals)
        assert nonzero_slots == np.count_nonzero(np.asarray(a.data))

    def test_padding_sheets_marked_and_ragged(self, rng):
        a = random_fem_2d(400, seed=3)
        packed = pk.pack_shift_ell(np.asarray(a.indptr),
                                   np.asarray(a.indices),
                                   np.asarray(a.data), a.shape[0], h=2,
                                   kc=4)
        assert packed.vals.shape == (packed.n_chunks, packed.kc,
                                     packed.h + 1, 128)
        # ragged layout: chunks ordered by block, every block present
        blocks = packed.chunk_blocks
        nb = packed.nch_pad // packed.h
        assert np.all(np.diff(blocks) >= 0)
        assert set(np.unique(blocks)) == set(range(nb))
        ws = packed.vals[:, :, packed.h, 0]
        # padding sheets carry ws = -1 and zero values
        assert np.all(packed.vals[ws < 0][:, :packed.h, :] == 0)
        # real sheet count matches the cost model
        assert int((ws >= 0).sum()) == packed.n_sheets

    def test_sheet_count_matches_pack(self):
        a = poisson.poisson_2d_csr(40, 40)
        total, avg = pk.sheet_count(np.asarray(a.indptr),
                                    np.asarray(a.indices), a.shape[0], h=4)
        packed = pk.pack_shift_ell(np.asarray(a.indptr),
                                   np.asarray(a.indices),
                                   np.asarray(a.data), a.shape[0], h=4)
        assert packed.n_sheets == total

    def test_sheet_count_matches_pack_with_empty_blocks(self):
        """Rows 512+ empty: the cost model must not count the dummy
        sheets pack adds for empty blocks (regression)."""
        n = 1024
        indptr = np.concatenate([np.arange(513), np.full(512, 512)])
        indices = np.arange(512, dtype=np.int32)
        data = np.ones(512)
        total, _ = pk.sheet_count(indptr, indices, n, h=4)
        packed = pk.pack_shift_ell(indptr, indices, data, n, h=4)
        assert packed.n_sheets == total

    def test_choose_h_respects_vmem_budget(self):
        """Near the size cap, large h pads x past the VMEM budget; the
        auto-pick must fall back to a height that still fits (regression:
        auto-h once chose h=128 and made conversions fail that h<=64
        handled)."""
        n = 2_598_544  # boundary: h<=64 fits the 10 MB f32 budget, 128 not
        budget = 10 * 2 ** 20  # the v5e-class budget, pinned explicitly
        indptr = np.arange(n + 1, dtype=np.int64)
        indices = np.arange(n, dtype=np.int32)
        h = pk.choose_h(indptr, indices, n, itemsize=4, x_budget=budget)
        nch = -(-n // 128)
        nch_pad = -(-nch // h) * h
        assert (nch_pad + 2 * h) * 128 * 4 <= budget

    def test_poisson_sheet_count_is_bandwidth_free(self):
        """Natural-order 2D Poisson needs ~K sheets per block regardless
        of n: chunk distances take at most a handful of values."""
        a = poisson.poisson_2d_csr(64, 64)
        total, avg = pk.sheet_count(np.asarray(a.indptr),
                                    np.asarray(a.indices), a.shape[0], h=8)
        assert avg <= 8.0  # 5-point stencil: ~5-7 distances


class TestMatvecParity:
    def test_small_dense_block(self, rng):
        a = poisson.poisson_2d_csr(8, 8)  # n=64 < one chunk
        _parity(a, 2, rng)

    def test_poisson2d(self, rng):
        _parity(poisson.poisson_2d_csr(40, 40), 4, rng)

    def test_poisson3d(self, rng):
        _parity(poisson.poisson_3d_csr(12, 12, 12), 4, rng)

    @pytest.mark.parametrize("h", [1, 2, 8])
    def test_fem_h_sweep(self, rng, h):
        a = random_fem_2d(700, seed=5)
        _parity(a, h, rng)

    def test_fem_rcm(self, rng):
        a = random_fem_2d(900, seed=6)
        ap = a.permuted(a.rcm_permutation())
        sell = _parity(ap, 4, rng)
        # RCM order needs fewer sheets than natural order
        nat, _ = pk.sheet_count(np.asarray(a.indptr),
                                np.asarray(a.indices), a.shape[0], h=4)
        assert sell.n_sheets <= nat

    def test_nonsquare_chunk_tail(self, rng):
        """n not a multiple of 128*h exercises the padded tail blocks."""
        a = random_fem_2d(333, seed=7)
        _parity(a, 4, rng)

    def test_dtype_float32(self, rng):
        a = poisson.poisson_2d_csr(24, 24, dtype=jnp.float32)
        a_sell = a.to_shiftell(h=2)
        assert a_sell.dtype == jnp.float32
        x = jnp.asarray(rng.standard_normal(576).astype(np.float32))
        np.testing.assert_allclose(np.asarray(a_sell @ x),
                                   np.asarray(a @ x), rtol=2e-6)

    def test_diagonal(self):
        a = poisson.poisson_2d_csr(16, 16)
        np.testing.assert_allclose(np.asarray(a.to_shiftell(h=2).diagonal()),
                                   np.asarray(a.diagonal()), rtol=1e-14)

    def test_vmem_budget_rejected(self, monkeypatch):
        """Oversized systems must fail loudly, not spill VMEM.  The
        budget is pinned to the v5e value: the CPU test environment's
        table entry is deliberately huge (interpret mode has no VMEM)."""
        monkeypatch.setenv(pk._ENV_OVERRIDE, str(10 * 2 ** 20))
        a = poisson.poisson_2d_csr(8, 8)
        sell = a.to_shiftell(h=2)
        import dataclasses

        big = dataclasses.replace(sell, shape=(6_000_000, 6_000_000),
                                  nch=46875, nch_pad=46876, pad=2)
        with pytest.raises(ValueError, match="VMEM"):
            big @ jnp.zeros(6_000_000)


class TestFuzz:
    @pytest.mark.parametrize("seed", range(6))
    def test_random_sparsity_parity_vs_scipy(self, seed):
        """Random sparsity patterns (including empty rows, a dense row,
        and a hot column) must pack and multiply exactly."""
        import scipy.sparse as sp

        rng = np.random.default_rng(seed)
        n = int(rng.integers(50, 700))
        density = float(rng.uniform(0.002, 0.05))
        m = sp.random(n, n, density=density, random_state=seed,
                      format="lil")
        m[0, :] = rng.standard_normal(n)        # dense row
        m[:, n // 2] = rng.standard_normal(n)[:, None]  # hot column
        m[n - 1, :] = 0.0                       # empty row
        m = sp.csr_matrix(m)
        m.eliminate_zeros()
        from cuda_mpi_parallel_tpu.models.operators import CSRMatrix

        a = CSRMatrix.from_scipy(m)
        h = int(rng.choice([1, 2, 4, 8]))
        sell = a.to_shiftell(h=h)
        assert sell.n_sheets >= 1
        x = rng.standard_normal(n)
        want = m @ x
        got = np.asarray(sell @ jnp.asarray(x))
        np.testing.assert_allclose(got, want, rtol=1e-12, atol=1e-12)


class TestRobustness:
    def test_solve_under_debug_nans(self, rng):
        """The kernel's skipped padding sheets gather from index 0 with
        zero values; jax_debug_nans must see no NaN anywhere."""
        import jax

        a = random_fem_2d(500, seed=9)
        sell = a.to_shiftell(h=4)
        b = sell @ jnp.asarray(rng.standard_normal(500))
        with jax.debug_nans(True):
            r = solve(sell, b, tol=0.0, rtol=1e-8, maxiter=3000)
        assert bool(r.converged)


class TestCG:
    def test_cg_trajectory_matches_csr(self, rng):
        """Same matrix, same b: shift-ELL CG must converge to the same
        solution in a comparable iteration count."""
        a = poisson.poisson_2d_csr(24, 24)
        x_true = rng.standard_normal(576)
        b = a @ jnp.asarray(x_true)
        r_csr = solve(a, b, tol=0.0, rtol=1e-10, maxiter=2000)
        r_sell = solve(a.to_shiftell(h=2), b, tol=0.0, rtol=1e-10,
                       maxiter=2000)
        assert bool(r_sell.converged)
        assert abs(int(r_sell.iterations) - int(r_csr.iterations)) <= 2
        np.testing.assert_allclose(np.asarray(r_sell.x), x_true, atol=1e-6)

    def test_cg_fem_jacobi(self, rng):
        from cuda_mpi_parallel_tpu.models.operators import (
            JacobiPreconditioner,
        )

        a = random_fem_2d(600, seed=8)
        ap = a.permuted(a.rcm_permutation())
        sell = ap.to_shiftell(h=4)
        x_true = rng.standard_normal(600)
        b = sell @ jnp.asarray(x_true)
        res = solve(sell, b, tol=0.0, rtol=1e-9, maxiter=4000,
                    m=JacobiPreconditioner.from_operator(sell))
        assert bool(res.converged)
        np.testing.assert_allclose(np.asarray(res.x), x_true, atol=1e-4)
