"""Checkpoint/resume tests (SURVEY SS5: absent from the reference).

The load-bearing property: a solve split into segments (with a disk
round-trip between them) follows the SAME iterate trajectory as an
uninterrupted solve.
"""
import os

import jax.numpy as jnp
import numpy as np
import pytest

from cuda_mpi_parallel_tpu import solve
from cuda_mpi_parallel_tpu.models import poisson
from cuda_mpi_parallel_tpu.utils import checkpoint as ckpt


class TestResume:
    def test_segmented_equals_uninterrupted(self):
        a = poisson.poisson_2d_csr(12, 12)
        b = jnp.asarray(np.random.default_rng(0).standard_normal(144))

        full = solve(a, b, tol=1e-10, maxiter=400, record_history=True)

        part1 = solve(a, b, tol=1e-10, maxiter=20, return_checkpoint=True)
        assert not bool(part1.converged)
        part2 = solve(a, b, tol=1e-10, maxiter=400,
                      resume_from=part1.checkpoint, record_history=True)

        assert bool(part2.converged)
        assert int(part2.iterations) == int(full.iterations)
        np.testing.assert_allclose(np.asarray(part2.x), np.asarray(full.x),
                                   rtol=1e-14, atol=1e-14)
        # residual trace continues seamlessly past the seam
        h_full = np.asarray(full.residual_history)
        h_part = np.asarray(part2.residual_history)
        k = int(full.iterations)
        np.testing.assert_allclose(h_part[20:k + 1], h_full[20:k + 1],
                                   rtol=1e-12)

    def test_checkpoint_counts_toward_total_maxiter(self):
        a = poisson.poisson_2d_csr(10, 10)
        b = jnp.asarray(np.random.default_rng(1).standard_normal(100))
        part = solve(a, b, tol=1e-12, maxiter=15, return_checkpoint=True)
        res = solve(a, b, tol=1e-12, maxiter=25,
                    resume_from=part.checkpoint)
        assert int(res.iterations) <= 25

    def test_rtol_uses_original_nrm0(self):
        """The relative-tolerance threshold must be anchored at the
        ORIGINAL ||r0||, not the residual at the resume point."""
        a = poisson.poisson_2d_csr(12, 12)
        b = jnp.asarray(np.random.default_rng(2).standard_normal(144)) * 1e3
        full = solve(a, b, tol=0.0, rtol=1e-9, maxiter=400)
        part = solve(a, b, tol=0.0, rtol=1e-9, maxiter=30,
                     return_checkpoint=True)
        res = solve(a, b, tol=0.0, rtol=1e-9, maxiter=400,
                    resume_from=part.checkpoint)
        assert int(res.iterations) == int(full.iterations)


class TestDiskRoundtrip:
    def test_save_load(self, tmp_path):
        a = poisson.poisson_2d_csr(8, 8)
        b = jnp.asarray(np.random.default_rng(3).standard_normal(64))
        part = solve(a, b, tol=1e-12, maxiter=10, return_checkpoint=True)
        path = str(tmp_path / "state.npz")
        ckpt.save_checkpoint(path, part.checkpoint)
        loaded = ckpt.load_checkpoint(path)
        for field in ("x", "r", "p", "rho", "rr", "nrm0", "k",
                      "indefinite"):
            np.testing.assert_array_equal(
                np.asarray(getattr(loaded, field)),
                np.asarray(getattr(part.checkpoint, field)))

    def test_version_mismatch(self, tmp_path):
        path = str(tmp_path / "bad.npz")
        np.savez(path[:-4] + ".tmp", version=999, x=np.zeros(3))
        os.replace(path[:-4] + ".tmp.npz", path)
        with pytest.raises(ValueError, match="format version"):
            ckpt.load_checkpoint(path)

    def test_solve_resumable_end_to_end(self, tmp_path):
        a = poisson.poisson_2d_csr(14, 14)
        b = jnp.asarray(np.random.default_rng(4).standard_normal(196))
        path = str(tmp_path / "run.npz")

        full = solve(a, b, tol=1e-10, maxiter=600)
        res = ckpt.solve_resumable(a, b, path, segment_iters=25, tol=1e-10,
                                   maxiter=600)
        assert bool(res.converged)
        assert int(res.iterations) == int(full.iterations)
        np.testing.assert_allclose(np.asarray(res.x), np.asarray(full.x),
                                   rtol=1e-13, atol=1e-13)
        assert not os.path.exists(path)  # removed on convergence

    def test_segments_reuse_one_executable(self, tmp_path):
        """Per-segment caps are traced (iter_cap), so many segments must
        not trigger per-segment recompilation of the solve."""
        from cuda_mpi_parallel_tpu.solver.cg import _solve_jit

        a = poisson.poisson_2d_csr(12, 12)
        b = jnp.asarray(np.random.default_rng(8).standard_normal(144))
        path = str(tmp_path / "seg.npz")
        ckpt.solve_resumable(a, b, path, segment_iters=10, tol=1e-10,
                             maxiter=400)
        n0 = _solve_jit._cache_size()
        b2 = jnp.asarray(np.random.default_rng(9).standard_normal(144))
        ckpt.solve_resumable(a, b2, str(tmp_path / "seg2.npz"),
                             segment_iters=7, tol=1e-10, maxiter=400)
        # same structures -> zero new compilations for the second run
        assert _solve_jit._cache_size() == n0

    def test_wrong_problem_rejected(self, tmp_path):
        a = poisson.poisson_2d_csr(10, 10)
        b1 = jnp.asarray(np.random.default_rng(10).standard_normal(100))
        b2 = jnp.asarray(np.random.default_rng(11).standard_normal(100))
        path = str(tmp_path / "fp.npz")
        ckpt.solve_resumable(a, b1, path, segment_iters=5, tol=1e-12,
                             maxiter=10)  # leaves a checkpoint
        with pytest.raises(ValueError, match="different problem"):
            ckpt.solve_resumable(a, b2, path, segment_iters=5, tol=1e-10,
                                 maxiter=100)

    def test_bad_segment_iters(self, tmp_path):
        a = poisson.poisson_2d_csr(4, 4)
        with pytest.raises(ValueError, match="segment_iters"):
            ckpt.solve_resumable(a, jnp.ones(16), str(tmp_path / "x.npz"),
                                 segment_iters=0)

    def test_x0_and_resume_conflict(self):
        a = poisson.poisson_2d_csr(6, 6)
        b = jnp.ones(36)
        part = solve(a, b, maxiter=3, return_checkpoint=True)
        with pytest.raises(ValueError, match="not both"):
            solve(a, b, x0=jnp.zeros(36), resume_from=part.checkpoint)

    def test_solve_resumable_survives_interruption(self, tmp_path):
        """Simulate preemption: run a few segments, 'crash', start over -
        the resumed run must finish with the same trajectory."""
        a = poisson.poisson_2d_csr(14, 14)
        b = jnp.asarray(np.random.default_rng(5).standard_normal(196))
        path = str(tmp_path / "run.npz")
        full = solve(a, b, tol=1e-10, maxiter=600)

        # first attempt: artificially cap total iterations (simulated kill)
        res1 = ckpt.solve_resumable(a, b, path, segment_iters=20,
                                    tol=1e-10, maxiter=40)
        assert not bool(res1.converged)
        assert os.path.exists(path)

        # "new process": resumes from disk, runs to convergence
        res2 = ckpt.solve_resumable(a, b, path, segment_iters=50,
                                    tol=1e-10, maxiter=600)
        assert bool(res2.converged)
        assert int(res2.iterations) == int(full.iterations)
        np.testing.assert_allclose(np.asarray(res2.x), np.asarray(full.x),
                                   rtol=1e-13, atol=1e-13)


class TestDF64DiskRoundtrip:
    def test_save_load_resume(self, tmp_path, rng):
        import numpy as np

        from cuda_mpi_parallel_tpu import cg_df64
        from cuda_mpi_parallel_tpu.models import poisson
        from cuda_mpi_parallel_tpu.utils.checkpoint import (
            load_checkpoint,
            load_checkpoint_df64,
            problem_fingerprint,
            save_checkpoint_df64,
        )

        a = poisson.poisson_2d_csr(16, 16)
        import jax.numpy as jnp

        b = np.asarray(a @ jnp.asarray(rng.standard_normal(256)),
                       dtype=np.float64)
        part = cg_df64(a, b, tol=0.0, rtol=1e-10, maxiter=20,
                       return_checkpoint=True)
        fp = problem_fingerprint(a, b)
        path = str(tmp_path / "df64.npz")
        save_checkpoint_df64(path, part.checkpoint, fp)
        ck = load_checkpoint_df64(path, expect_fingerprint=fp)
        resumed = cg_df64(a, b, tol=0.0, rtol=1e-10, maxiter=2000,
                          resume_from=ck)
        full = cg_df64(a, b, tol=0.0, rtol=1e-10, maxiter=2000)
        assert int(resumed.iterations) == int(full.iterations)
        np.testing.assert_array_equal(np.asarray(resumed.x_hi),
                                      np.asarray(full.x_hi))
        # kind mismatch is loud in both directions
        import pytest

        with pytest.raises(ValueError, match="df64"):
            load_checkpoint(path)
        with pytest.raises(ValueError, match="not a df64"):
            save_dir = str(tmp_path / "f32.npz")
            from cuda_mpi_parallel_tpu import solve
            from cuda_mpi_parallel_tpu.utils.checkpoint import (
                save_checkpoint,
            )

            r32 = solve(a, jnp.asarray(b), tol=0.0, rtol=1e-8, maxiter=10,
                        return_checkpoint=True)
            save_checkpoint(save_dir, r32.checkpoint, fp)
            load_checkpoint_df64(save_dir)


class TestFingerprintUnverifiable:
    """A checkpoint saved WITHOUT a fingerprint cannot be verified: when
    the caller asks for verification it must warn loudly, not silently
    accept (round-2 advice item)."""

    def test_npz_warns_on_empty_stored_fingerprint(self, tmp_path, rng):
        import warnings

        import jax.numpy as jnp
        import numpy as np

        from cuda_mpi_parallel_tpu import solve
        from cuda_mpi_parallel_tpu.models import poisson
        from cuda_mpi_parallel_tpu.utils.checkpoint import (
            load_checkpoint,
            save_checkpoint,
        )

        a = poisson.poisson_2d_csr(8, 8)
        b = jnp.asarray(rng.standard_normal(64))
        part = solve(a, b, tol=0.0, maxiter=5, return_checkpoint=True)
        path = str(tmp_path / "nofp.npz")
        save_checkpoint(path, part.checkpoint)  # no fingerprint
        with pytest.warns(UserWarning, match="UNVERIFIED"):
            load_checkpoint(path, expect_fingerprint="deadbeef")
        # no expectation -> no warning
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            load_checkpoint(path)

    def test_df64_warns_on_empty_stored_fingerprint(self, tmp_path, rng):
        import jax.numpy as jnp
        import numpy as np

        from cuda_mpi_parallel_tpu import cg_df64
        from cuda_mpi_parallel_tpu.models import poisson
        from cuda_mpi_parallel_tpu.utils.checkpoint import (
            load_checkpoint_df64,
            save_checkpoint_df64,
        )

        a = poisson.poisson_2d_csr(8, 8)
        b = np.asarray(a @ jnp.asarray(rng.standard_normal(64)),
                       dtype=np.float64)
        part = cg_df64(a, b, tol=0.0, maxiter=5, return_checkpoint=True)
        path = str(tmp_path / "nofp64.npz")
        save_checkpoint_df64(path, part.checkpoint)  # no fingerprint
        with pytest.warns(UserWarning, match="UNVERIFIED"):
            load_checkpoint_df64(path, expect_fingerprint="deadbeef")

    def test_mismatch_still_raises(self, tmp_path, rng):
        import jax.numpy as jnp

        from cuda_mpi_parallel_tpu import solve
        from cuda_mpi_parallel_tpu.models import poisson
        from cuda_mpi_parallel_tpu.utils.checkpoint import (
            load_checkpoint,
            save_checkpoint,
        )

        a = poisson.poisson_2d_csr(8, 8)
        b = jnp.asarray(rng.standard_normal(64))
        part = solve(a, b, tol=0.0, maxiter=5, return_checkpoint=True)
        path = str(tmp_path / "fp.npz")
        save_checkpoint(path, part.checkpoint, fingerprint="aaaa")
        with pytest.raises(ValueError, match="different problem"):
            load_checkpoint(path, expect_fingerprint="bbbb")


class TestDF64Resumable:
    def test_segmented_matches_single_run(self, tmp_path, rng):
        """solve_resumable_df64 segments produce the exact trajectory of
        one uninterrupted df64 solve, surviving a mid-run 'preemption'
        (fresh call against the on-disk checkpoint)."""
        import numpy as np

        import jax.numpy as jnp

        from cuda_mpi_parallel_tpu import cg_df64
        from cuda_mpi_parallel_tpu.models import poisson
        from cuda_mpi_parallel_tpu.utils.checkpoint import (
            solve_resumable_df64,
        )

        a = poisson.poisson_2d_csr(16, 16, dtype=np.float64)
        x_true = rng.standard_normal(256)
        b = np.asarray(a.to_dense(), np.float64) @ x_true
        path = str(tmp_path / "df64_seg.npz")

        full = cg_df64(a, b, tol=0.0, rtol=1e-10, maxiter=2000)
        res = solve_resumable_df64(a, b, path, segment_iters=20,
                                   tol=0.0, rtol=1e-10, maxiter=2000)
        assert bool(res.converged)
        assert int(res.iterations) == int(full.iterations)
        np.testing.assert_array_equal(np.asarray(res.x_hi),
                                      np.asarray(full.x_hi))
        # converged run cleans its checkpoint up
        import os

        assert not os.path.exists(path)

    def test_preemption_resume(self, tmp_path, rng):
        """Kill the solve after one segment; a fresh call resumes from
        disk and still matches the uninterrupted trajectory."""
        import numpy as np

        from cuda_mpi_parallel_tpu import cg_df64
        from cuda_mpi_parallel_tpu.models import poisson
        from cuda_mpi_parallel_tpu.utils.checkpoint import (
            solve_resumable_df64,
        )

        a = poisson.poisson_2d_csr(12, 12, dtype=np.float64)
        x_true = rng.standard_normal(144)
        b = np.asarray(a.to_dense(), np.float64) @ x_true
        path = str(tmp_path / "df64_pre.npz")

        # "preempted" run: cap the total at one segment's worth
        solve_resumable_df64(a, b, path, segment_iters=10, tol=0.0,
                             rtol=1e-10, maxiter=10, keep_checkpoint=True)
        import os

        assert os.path.exists(path)
        # fresh process-equivalent: resume to convergence
        res = solve_resumable_df64(a, b, path, segment_iters=25, tol=0.0,
                                   rtol=1e-10, maxiter=2000)
        full = cg_df64(a, b, tol=0.0, rtol=1e-10, maxiter=2000)
        assert bool(res.converged)
        assert int(res.iterations) == int(full.iterations)
        np.testing.assert_array_equal(np.asarray(res.x_hi),
                                      np.asarray(full.x_hi))


class TestDF64ResidentResumable:
    """engine='resident' replay segmentation (round 4): segments on the
    VMEM-resident df64 kernel, bitwise-identical to an uninterrupted
    resident solve (the traced iter_cap replays the exact prefix)."""

    def _problem(self, rng, nx=16, ny=128):
        import jax.numpy as jnp

        from cuda_mpi_parallel_tpu.models import poisson

        a = poisson.poisson_2d_operator(nx, ny, dtype=jnp.float32)
        b = rng.standard_normal(nx * ny)
        return a, b

    def test_segmented_bitwise_matches_uninterrupted(self, tmp_path, rng):
        import numpy as np

        from cuda_mpi_parallel_tpu import cg_resident_df64
        from cuda_mpi_parallel_tpu.utils.checkpoint import (
            solve_resumable_df64,
        )

        a, b = self._problem(rng)
        path = str(tmp_path / "res_seg.npz")
        full = cg_resident_df64(a, b, tol=0.0, rtol=1e-10, maxiter=400,
                                interpret=True)
        res = solve_resumable_df64(a, b, path, segment_iters=48, tol=0.0,
                                   rtol=1e-10, maxiter=400,
                                   engine="resident", interpret=True)
        assert bool(res.converged)
        assert int(res.iterations) == int(full.iterations)
        np.testing.assert_array_equal(np.asarray(res.x_hi),
                                      np.asarray(full.x_hi))
        np.testing.assert_array_equal(np.asarray(res.x_lo),
                                      np.asarray(full.x_lo))
        import os

        assert not os.path.exists(path)  # converged run cleans up

    def test_preemption_resume_bitwise(self, tmp_path, rng):
        import os

        import numpy as np

        from cuda_mpi_parallel_tpu import cg_resident_df64
        from cuda_mpi_parallel_tpu.utils.checkpoint import (
            solve_resumable_df64,
        )

        a, b = self._problem(rng)
        path = str(tmp_path / "res_pre.npz")
        # preempted: one 32-iteration segment only
        solve_resumable_df64(a, b, path, segment_iters=32, tol=0.0,
                             rtol=1e-10, maxiter=32, engine="resident",
                             keep_checkpoint=True, interpret=True)
        assert os.path.exists(path)
        # fresh call resumes from disk to convergence
        res = solve_resumable_df64(a, b, path, segment_iters=100, tol=0.0,
                                   rtol=1e-10, maxiter=400,
                                   engine="resident", interpret=True)
        full = cg_resident_df64(a, b, tol=0.0, rtol=1e-10, maxiter=400,
                                interpret=True)
        assert bool(res.converged)
        assert int(res.iterations) == int(full.iterations)
        np.testing.assert_array_equal(np.asarray(res.x_hi),
                                      np.asarray(full.x_hi))

    def test_format_cross_engine_errors(self, tmp_path, rng):
        import pytest

        from cuda_mpi_parallel_tpu.utils.checkpoint import (
            solve_resumable_df64,
        )

        a, b = self._problem(rng)
        path = str(tmp_path / "cross.npz")
        solve_resumable_df64(a, b, path, segment_iters=32, tol=0.0,
                             rtol=1e-10, maxiter=32, engine="resident",
                             keep_checkpoint=True, interpret=True)
        # resuming a replay checkpoint with the general engine errors
        with pytest.raises(ValueError, match="replay"):
            solve_resumable_df64(a, b, path, segment_iters=32, tol=0.0,
                                 rtol=1e-10, maxiter=64, engine="general")

    def test_auto_stays_general_off_tpu(self, tmp_path, rng):
        # engine="auto" must not route into interpret-mode pallas on a
        # CPU backend (orders of magnitude slower than the general
        # solver) unless interpret=True was asked for explicitly - the
        # same gate as solve(engine="auto").
        import os

        import numpy as np

        from cuda_mpi_parallel_tpu.utils.checkpoint import (
            solve_resumable_df64,
        )

        a, b = self._problem(rng)
        path = str(tmp_path / "auto.npz")
        res = solve_resumable_df64(a, b, path, segment_iters=100, tol=0.0,
                                   rtol=1e-10, maxiter=300, engine="auto")
        assert bool(res.converged)
        # the general path went through checkpoints with full CG state
        # (a replay checkpoint would have been cleaned up identically,
        # so distinguish via the checkpoint format of a capped run)
        solve_resumable_df64(a, b, path, segment_iters=10, tol=0.0,
                             rtol=1e-10, maxiter=10, engine="auto",
                             keep_checkpoint=True)
        with np.load(path) as z:
            assert str(z["kind"]) == "df64"  # general format, not replay
        os.remove(path)

    def test_engine_resident_rejects_unsupported(self, tmp_path, rng):
        import numpy as np

        import pytest

        from cuda_mpi_parallel_tpu.models import poisson
        from cuda_mpi_parallel_tpu.utils.checkpoint import (
            solve_resumable_df64,
        )

        a = poisson.poisson_2d_csr(16, 16, dtype=np.float64)  # assembled
        b = rng.standard_normal(256)
        with pytest.raises(ValueError, match="resident"):
            solve_resumable_df64(a, b, str(tmp_path / "x.npz"),
                                 engine="resident")

    def test_warm_start_df64_kernel(self, rng):
        """x0 on the df64 resident kernel: fewer iterations to the same
        absolute target, and an explicit zero x0 matches the fast path
        bitwise."""
        import numpy as np

        import jax.numpy as jnp

        from cuda_mpi_parallel_tpu import cg_resident_df64

        a, b = self._problem(rng)
        r0 = cg_resident_df64(a, b, tol=0.0, rtol=1e-10, maxiter=200,
                              check_every=8, interpret=True)
        rz = cg_resident_df64(a, b, x0=np.zeros_like(b), tol=0.0,
                              rtol=1e-10, maxiter=200, check_every=8,
                              interpret=True)
        assert int(r0.iterations) == int(rz.iterations)
        np.testing.assert_array_equal(np.asarray(r0.x_hi),
                                      np.asarray(rz.x_hi))
        np.testing.assert_array_equal(np.asarray(r0.x_lo),
                                      np.asarray(rz.x_lo))

        x_true = rng.standard_normal(b.shape[0])
        b2 = np.asarray(a.matvec(jnp.asarray(x_true, jnp.float32)),
                        np.float64)
        warm = cg_resident_df64(a, b2, x0=x_true * (1 + 1e-6), tol=1e-6,
                                maxiter=200, check_every=4,
                                interpret=True)
        cold = cg_resident_df64(a, b2, tol=1e-6, maxiter=200,
                                check_every=4, interpret=True)
        assert bool(warm.converged)
        assert int(warm.iterations) < int(cold.iterations)


class TestFingerprintOperatorIdentity:
    """Round-4 advice (medium): two operators of the same type and shape
    but different coefficients must NOT share a fingerprint - resuming a
    checkpoint against such a different system would silently continue
    the wrong trajectory."""

    def test_stencil_scale_changes_fingerprint(self):
        import numpy as np

        from cuda_mpi_parallel_tpu.models.operators import Stencil2D
        from cuda_mpi_parallel_tpu.utils.checkpoint import (
            problem_fingerprint,
        )

        b = np.ones(16 * 16, dtype=np.float32)
        a1 = Stencil2D.create(16, 16, dtype=jnp.float32)
        a2 = Stencil2D.create(16, 16, scale=2.0, dtype=jnp.float32)
        assert problem_fingerprint(a1, b) != problem_fingerprint(a2, b)
        # determinism: same system -> same fingerprint
        a1b = Stencil2D.create(16, 16, dtype=jnp.float32)
        assert problem_fingerprint(a1, b) == problem_fingerprint(a1b, b)

    def test_backend_choice_does_not_change_fingerprint(self):
        # backend selects a kernel, not a linear system: a checkpoint
        # must resume under either execution strategy
        import numpy as np

        from cuda_mpi_parallel_tpu.models.operators import Stencil2D
        from cuda_mpi_parallel_tpu.utils.checkpoint import (
            problem_fingerprint,
        )

        b = np.ones(16 * 128, dtype=np.float32)  # pallas tiling: ny%128
        a_xla = Stencil2D.create(16, 128, dtype=jnp.float32, backend="xla")
        a_pal = Stencil2D.create(16, 128, dtype=jnp.float32,
                                 backend="pallas")
        assert problem_fingerprint(a_xla, b) == problem_fingerprint(a_pal, b)

    def test_csr_values_change_fingerprint(self):
        import dataclasses

        import numpy as np

        from cuda_mpi_parallel_tpu.models import poisson
        from cuda_mpi_parallel_tpu.utils.checkpoint import (
            problem_fingerprint,
        )

        b = np.ones(8 * 8, dtype=np.float32)
        a1 = poisson.poisson_2d_csr(8, 8, dtype=np.float32)
        a2 = dataclasses.replace(a1, data=a1.data * 1.5)
        assert problem_fingerprint(a1, b) != problem_fingerprint(a2, b)

    def test_grid_dims_change_fingerprint(self):
        import numpy as np

        from cuda_mpi_parallel_tpu.models.operators import Stencil2D
        from cuda_mpi_parallel_tpu.utils.checkpoint import (
            problem_fingerprint,
        )

        # same N, same type, different grid SHAPE (static metadata via
        # the treedef): 8x32 vs 32x8
        b = np.ones(256, dtype=np.float32)
        a1 = Stencil2D.create(8, 32, dtype=jnp.float32)
        a2 = Stencil2D.create(32, 8, dtype=jnp.float32)
        assert problem_fingerprint(a1, b) != problem_fingerprint(a2, b)

    def test_resume_against_rescaled_operator_rejected(self, tmp_path):
        import numpy as np
        import pytest as _pytest

        from cuda_mpi_parallel_tpu.models.operators import Stencil2D
        from cuda_mpi_parallel_tpu.utils.checkpoint import solve_resumable

        path = str(tmp_path / "ck.npz")
        a1 = Stencil2D.create(16, 16, dtype=jnp.float32)
        rng = np.random.default_rng(0)
        b = jnp.asarray(rng.standard_normal(256).astype(np.float32))
        solve_resumable(a1, b, path, segment_iters=3, tol=1e30,
                        maxiter=3, keep_checkpoint=True)
        a2 = Stencil2D.create(16, 16, scale=2.0, dtype=jnp.float32)
        with _pytest.raises(ValueError, match="different problem"):
            solve_resumable(a2, b, path, segment_iters=3, maxiter=6)
