"""graftlint (``cuda_mpi_parallel_tpu.analysis``): the static-analysis
gate that catches Mosaic-tiling, VMEM-budget, collective-safety,
DMA-pairing and host-sync bugs before they reach hardware.

Fixture contract (``tests/fixtures/graftlint``): every line a rule must
flag carries a trailing ``# gl-expect: <rule-name>`` marker, and each
``bad_*`` test asserts the linter's ``(line, rule)`` set equals the
marker set EXACTLY - over-firing anywhere in a fixture is as much a
failure as missing the marked line.  ``bad_tiling.py`` reconstructs
the round-5 allreduce 1-row RDMA verbatim and ``bad_collective.py``'s
contested ppermute is the rho-buffer-race class, so the two round-5
advisor findings are pinned as regression tests.

The package itself must lint clean (the acceptance gate
``python -m cuda_mpi_parallel_tpu.analysis cuda_mpi_parallel_tpu/``).
"""
import os
import re
import textwrap

import pytest

import cuda_mpi_parallel_tpu
from cuda_mpi_parallel_tpu.analysis import (
    REGISTRY,
    Severity,
    all_rules,
    lint_file,
    lint_paths,
    lint_source,
    resolve_rules,
)
from cuda_mpi_parallel_tpu.analysis.__main__ import main as lint_main

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures", "graftlint")
PACKAGE_DIR = os.path.dirname(cuda_mpi_parallel_tpu.__file__)

_EXPECT_RE = re.compile(r"#\s*gl-expect:\s*([a-z0-9\-]+(?:\s*,\s*"
                        r"[a-z0-9\-]+)*)")


def expected_findings(path):
    out = set()
    with open(path) as f:
        for lineno, line in enumerate(f, start=1):
            m = _EXPECT_RE.search(line)
            if m:
                for tok in m.group(1).split(","):
                    out.add((lineno, tok.strip()))
    return out


def actual_findings(path):
    return {(d.line, d.rule_name) for d in lint_file(path)}


class TestFixtures:
    """Each rule fires exactly where its known-bad fixture says, and
    nowhere in its known-good twin."""

    BAD = ["bad_tiling", "bad_vmem", "bad_collective", "bad_dma",
           "bad_hostsync", "bad_cachekey", "bad_locks", "bad_events",
           "bad_stale"]
    GOOD = ["good_tiling", "good_vmem", "good_collective", "good_dma",
            "good_hostsync", "good_cachekey", "good_locks",
            "good_events", "good_stale"]

    @pytest.mark.parametrize("name", BAD)
    def test_bad_fixture_fires_exactly(self, name):
        path = os.path.join(FIXTURES, name + ".py")
        expected = expected_findings(path)
        assert expected, f"{name} declares no gl-expect markers"
        assert actual_findings(path) == expected

    @pytest.mark.parametrize("name", GOOD)
    def test_good_fixture_clean(self, name):
        path = os.path.join(FIXTURES, name + ".py")
        assert actual_findings(path) == set()

    def test_every_rule_has_a_firing_fixture(self):
        """The rule catalog is fully exercised: every registered rule
        appears in at least one bad fixture's expectations."""
        covered = set()
        for name in self.BAD:
            covered |= {r for _, r in expected_findings(
                os.path.join(FIXTURES, name + ".py"))}
        assert covered == {r.name for r in all_rules()}

    def test_round5_allreduce_pattern_flagged(self):
        """The unfixed round-5 1-row-RDMA allreduce (reconstructed in
        bad_tiling.py) is caught by mosaic-tiling - the rule that
        would have stopped ADVICE.md finding #1 pre-hardware."""
        path = os.path.join(FIXTURES, "bad_tiling.py")
        diags = [d for d in lint_file(path) if d.rule_name ==
                 "mosaic-tiling" and "dynamic" in d.message]
        assert len(diags) >= 2  # src and dst of the RDMA


class TestPackageClean:
    def test_package_lints_clean(self):
        """The acceptance gate: graftlint over the package itself."""
        assert lint_paths([PACKAGE_DIR]) == []

    def test_resident_dist_suppression_is_load_bearing(self):
        """The allreduce's known tiling hazard is suppressed, not
        invisible: stripping graftlint comments re-fires GL101 (guards
        against the rule silently losing the pattern)."""
        path = os.path.join(PACKAGE_DIR, "ops", "pallas",
                            "resident_dist.py")
        with open(path) as f:
            src = f.read()
        stripped = re.sub(r"#\s*graftlint:[^\n]*", "", src)
        diags = lint_source(stripped, path=path)
        assert any(d.rule_name == "mosaic-tiling" for d in diags)


class TestSuppressions:
    SRC = textwrap.dedent("""\
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        def f(buf, send, recv, my_id, tgt):
            dma = pltpu.make_async_remote_copy(
                buf.at[pl.ds(my_id, 1)],{c1}
                buf.at[pl.ds(my_id, 1)],{c2}
                send, recv, device_id=tgt)
            dma.start()
            dma.wait()
        """)

    def _lint(self, c1="", c2=""):
        return lint_source(self.SRC.format(c1=c1, c2=c2), path="t.py")

    def test_unsuppressed_fires(self):
        assert len(self._lint()) == 2

    def test_same_line_suppression(self):
        diags = self._lint(c1="  # graftlint: disable=mosaic-tiling")
        assert len(diags) == 1 and diags[0].line == 7

    def test_by_id_and_all(self):
        assert len(self._lint(c1="  # graftlint: disable=GL101",
                              c2="  # graftlint: disable=all")) == 0

    def test_previous_line_covers_next(self):
        src = self.SRC.format(c1="", c2="").replace(
            "    dma = pltpu.make_async_remote_copy(",
            "    # graftlint: disable=mosaic-tiling\n"
            "    dma = pltpu.make_async_remote_copy(")
        # the comment's next line is the call line, not the pl.ds
        # lines - so both still fire, AND the disable that covered
        # nothing is now itself reported stale (GL109)
        diags = lint_source(src, path="t.py")
        assert len([d for d in diags
                    if d.rule_name == "mosaic-tiling"]) == 2
        assert len([d for d in diags
                    if d.rule_name == "stale-suppression"]) == 1

    def test_stale_not_reported_on_partial_run(self):
        """A --select run that never checks a comment's rule says
        nothing about that comment: no GL109."""
        src = self.SRC.format(
            c1="  # graftlint: disable=mosaic-tiling", c2="")
        fixed = src.replace("pl.ds(my_id, 1)", "pl.ds(0, 8)")
        full = lint_source(fixed, path="t.py")
        assert {d.rule_name for d in full} == {"stale-suppression"}
        partial = lint_source(
            fixed, path="t.py",
            rules=resolve_rules(select=["vmem-budget",
                                        "stale-suppression"]))
        assert partial == []

    def test_file_level_suppression(self):
        src = "# graftlint: disable-file=mosaic-tiling\n" \
            + self.SRC.format(c1="", c2="")
        assert lint_source(src, path="t.py") == []

    def test_unknown_rule_name_errors(self):
        with pytest.raises(ValueError, match="unknown rule"):
            resolve_rules(select=["not-a-rule"])


class TestRegistry:
    def test_catalog(self):
        rules = all_rules()
        assert [r.id for r in rules] == ["GL101", "GL102", "GL103",
                                         "GL104", "GL105", "GL106",
                                         "GL107", "GL108", "GL109"]
        assert {r.name for r in rules} == {
            "mosaic-tiling", "vmem-budget", "collective-safety",
            "dma-pairing", "host-sync", "cache-key",
            "lock-discipline", "event-schema", "stale-suppression"}
        # addressable by id and by name
        assert REGISTRY["gl101"] is REGISTRY["mosaic-tiling"]
        assert REGISTRY["gl106"] is REGISTRY["cache-key"]
        # per-rule severity: hardware-fatal and silent-wrong-result
        # classes are errors; host-sync and stale-suppression advise
        # at warning (still gate by default)
        sev = {r.id: r.severity for r in rules}
        assert sev["GL101"] == Severity.ERROR
        assert sev["GL105"] == Severity.WARNING
        assert sev["GL106"] == Severity.ERROR
        assert sev["GL107"] == Severity.ERROR
        assert sev["GL108"] == Severity.ERROR
        assert sev["GL109"] == Severity.WARNING

    def test_lazy_reexports(self):
        from cuda_mpi_parallel_tpu import analysis

        assert analysis.RaceDetectorUnavailable is not None
        assert callable(analysis.check_races)
        assert callable(analysis.check_collective_axes)
        with pytest.raises(AttributeError):
            analysis.no_such_symbol

    def test_select_ignore(self):
        only = resolve_rules(select=["mosaic-tiling", "GL102"])
        assert [r.id for r in only] == ["GL101", "GL102"]
        rest = resolve_rules(ignore=["host-sync"])
        assert [r.id for r in rest] == ["GL101", "GL102", "GL103",
                                        "GL104", "GL106", "GL107",
                                        "GL108", "GL109"]

    def test_severity_ordering(self):
        assert Severity.parse("error") > Severity.parse("warning")
        with pytest.raises(ValueError, match="unknown severity"):
            Severity.parse("fatal")


class TestCLIEntry:
    def test_clean_run_exits_zero(self, capsys):
        assert lint_main([PACKAGE_DIR]) == 0
        assert capsys.readouterr().out == ""

    def test_bad_fixture_exits_nonzero(self, capsys):
        rc = lint_main([os.path.join(FIXTURES, "bad_tiling.py")])
        out = capsys.readouterr().out
        assert rc == 1
        assert "GL101" in out and "mosaic-tiling" in out
        assert "finding(s)" in out

    def test_json_output(self, capsys):
        import json

        rc = lint_main(["--json",
                        os.path.join(FIXTURES, "bad_vmem.py")])
        recs = json.loads(capsys.readouterr().out)
        assert rc == 1
        assert {r["rule_id"] for r in recs} == {"GL102"}
        assert all(r["severity"] == "error" for r in recs)

    def test_select_skips_other_rules(self, capsys):
        rc = lint_main(["--select", "host-sync",
                        os.path.join(FIXTURES, "bad_tiling.py")])
        capsys.readouterr()
        assert rc == 0

    def test_list_rules(self, capsys):
        assert lint_main(["--list-rules"]) == 0
        out = capsys.readouterr().out
        for rid in ("GL101", "GL102", "GL103", "GL104", "GL105",
                    "GL106", "GL107", "GL108", "GL109"):
            assert rid in out

    def test_baseline_gates_only_new_findings(self, tmp_path, capsys):
        """--baseline makes the gate 'no NEW findings': a prior --json
        report forgives its own findings but nothing else."""
        import json

        bad = os.path.join(FIXTURES, "bad_vmem.py")
        assert lint_main(["--json", bad]) == 1
        base = tmp_path / "base.json"
        base.write_text(capsys.readouterr().out)
        # same findings, baselined away -> clean exit, empty output
        assert lint_main([bad, "--baseline", str(base)]) == 0
        assert capsys.readouterr().out == ""
        # a different file's findings are NOT forgiven
        other = os.path.join(FIXTURES, "bad_tiling.py")
        assert lint_main([other, "--baseline", str(base)]) == 1
        assert "GL101" in capsys.readouterr().out

    def test_bad_baseline_errors(self, tmp_path, capsys):
        p = tmp_path / "nonsense.json"
        p.write_text("{\"not\": \"a list\"}")
        rc = lint_main([os.path.join(FIXTURES, "bad_vmem.py"),
                        "--baseline", str(p)])
        assert rc == 2
        assert "baseline" in capsys.readouterr().err

    def test_missing_path_errors(self, capsys):
        assert lint_main(["no/such/path.py"]) == 2
        assert "error" in capsys.readouterr().err

    def test_syntax_error_reported(self, tmp_path, capsys):
        p = tmp_path / "broken.py"
        p.write_text("def f(:\n")
        rc = lint_main([str(p)])
        out = capsys.readouterr().out
        assert rc == 1 and "GL000" in out

    def test_cli_lint_subcommand(self, capsys):
        from cuda_mpi_parallel_tpu import cli

        assert cli.main(["lint", PACKAGE_DIR]) == 0


class TestCLIHistoryRejection:
    """Satellite (ADVICE.md round 5, revised by the flight recorder):
    --history with --mesh > 1 and the resident/streaming engines was
    silently dropped, then dead-ended; the bare flag is still rejected
    (never silently dropped), but the error now points at
    --flight-record, which carries the trace through the recorder."""

    @pytest.mark.parametrize("engine", ["resident", "streaming"])
    def test_bare_history_points_at_flight_record(self, engine):
        from cuda_mpi_parallel_tpu import cli

        with pytest.raises(SystemExit,
                           match="flight-record") as excinfo:
            cli.main(["--problem", "poisson2d", "--n", "32", "--device",
                      "cpu", "--matrix-free", "--mesh", "2", "--engine",
                      engine, "--history"])
        assert "--history" in str(excinfo.value)

    def test_general_engine_keeps_history(self, capsys):
        from cuda_mpi_parallel_tpu import cli
        from cuda_mpi_parallel_tpu.utils.compat import has_shard_map

        if not has_shard_map():
            pytest.skip("no shard_map spelling available (distributed "
                        "paths unavailable)")
        rc = cli.main(["--problem", "poisson2d", "--n", "16", "--device",
                       "cpu", "--mesh", "2", "--matrix-free", "--engine",
                       "general", "--history", "--tol", "1e-6"])
        out = capsys.readouterr().out
        assert rc == 0 and "||r||" in out


class TestRuntimePermValidation:
    """validate_permutation (parallel/halo.py): the dynamic-perm twin
    of GL103 - trace-time schedules GL103 cannot see as literals."""

    def test_builders_validate(self):
        from cuda_mpi_parallel_tpu.parallel.halo import (
            neighbor_shift_perms,
            validate_permutation,
        )

        fwd, bwd = neighbor_shift_perms(4)
        assert fwd == [(0, 1), (1, 2), (2, 3)]
        assert bwd == [(1, 0), (2, 1), (3, 2)]
        ring = validate_permutation((j, (j - 1) % 4) for j in range(4))
        assert len(ring) == 4

    def test_contested_destination_rejected(self):
        from cuda_mpi_parallel_tpu.parallel.halo import (
            validate_permutation,
        )

        with pytest.raises(ValueError, match="destination twice"):
            validate_permutation([(0, 1), (1, 1)])
        with pytest.raises(ValueError, match="source twice"):
            validate_permutation([(0, 1), (0, 2)])


class TestJaxprLevel:
    """The jaxpr half of the framework: axis names resolved after
    tracing (what the AST rules must trust, this layer verifies)."""

    def test_collective_axes_walks_subjaxprs(self):
        import jax
        import jax.numpy as jnp
        from jax import lax

        from cuda_mpi_parallel_tpu.analysis.jaxpr import (
            check_collective_axes,
            collective_axes,
        )

        def f(x):
            def body(i, v):
                return lax.psum(v, "rows") * 0.5

            return lax.fori_loop(0, 3, body, x)

        jaxpr = jax.make_jaxpr(f, axis_env=[("rows", 2)])(jnp.ones(4))
        assert collective_axes(jaxpr) == {"rows"}
        assert check_collective_axes(jaxpr, ["rows"]) == []
        assert check_collective_axes(jaxpr, ["cols"]) == ["rows"]

    def test_accepts_mesh_like(self):
        import jax
        import jax.numpy as jnp
        from jax import lax

        from cuda_mpi_parallel_tpu.analysis.jaxpr import (
            check_collective_axes,
        )

        class MeshLike:
            axis_names = ("rows",)

        jaxpr = jax.make_jaxpr(
            lambda x: lax.psum(x, "rows"),
            axis_env=[("rows", 2)])(jnp.ones(4))
        assert check_collective_axes(jaxpr, MeshLike()) == []
