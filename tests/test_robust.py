"""robust/: deterministic fault injection + self-healing solves.

The acceptance story is "inject any fault the harness can spell; the
solve either recovers to the fault-free answer or fails typed and
loud, never silently wrong":

* the chaos matrix - every injection site (halo round, local SpMV,
  reduction scalar) x mesh {1, 4} is DETECTED within ``check_every``
  iterations (typed BREAKDOWN whose iteration count names the poisoned
  step) and the recovered solution matches the fault-free solve;
* with no ``FaultPlan`` the solve body jaxpr is proven bit-identical
  to a call that never mentions injection (TestZeroPerturbation);
* a distributed ``solve_resumable`` segment killed mid-run resumes
  from its checkpoint with the exact iterate trajectory, and a resume
  under a mismatched plan/exchange fingerprint fails with a loud typed
  error;
* the serve layer retries ERROR/BREAKDOWN lanes with backoff, opens a
  per-handle circuit breaker on consecutive failures (typed REFUSED
  results, half-open probe), and degrades tolerance under queue
  pressure.
"""
import os
import tempfile

import jax
import numpy as np
import pytest

from cuda_mpi_parallel_tpu.models import mmio
from cuda_mpi_parallel_tpu.models.poisson import poisson_2d_csr
from cuda_mpi_parallel_tpu.parallel import (
    make_mesh,
    solve_distributed,
)
from cuda_mpi_parallel_tpu.robust import (
    FaultPlan,
    PreemptedError,
    Preemption,
    RecoveryPolicy,
    check_finite_rhs,
    solve_with_recovery,
)
from cuda_mpi_parallel_tpu.solver import solve, solve_many
from cuda_mpi_parallel_tpu.solver.cg import cg
from cuda_mpi_parallel_tpu.solver.status import CGStatus
from cuda_mpi_parallel_tpu.telemetry import events
from cuda_mpi_parallel_tpu.utils import compat
from cuda_mpi_parallel_tpu.utils.checkpoint import (
    CheckpointMismatch,
    solve_resumable_distributed,
)

needs_mesh = pytest.mark.skipif(
    not compat.has_shard_map() or len(jax.devices()) < 4,
    reason="needs shard_map and >= 4 (virtual) devices")

FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures",
                       "skewed_spd_240.mtx")


@pytest.fixture(scope="module")
def fixture_problem():
    a = mmio.load_matrix_market(FIXTURE)
    b = np.random.default_rng(0).standard_normal(240)
    return a, b


def _status(res) -> str:
    return CGStatus(int(res.status)).name


class TestFaultPlan:
    def test_parse(self):
        p = FaultPlan.parse("halo:10")
        assert (p.site, p.iteration, p.shard) == ("halo", 10, 0)
        p = FaultPlan.parse("spmv:25:2")
        assert (p.site, p.iteration, p.shard) == ("spmv", 25, 2)

    @pytest.mark.parametrize("bad", ["halo", "nope:3", "halo:x",
                                     "halo:1:2:3", "spmv:-1"])
    def test_parse_rejects(self, bad):
        with pytest.raises(ValueError):
            FaultPlan.parse(bad)

    def test_static_hashable_identity(self):
        """Two identical plans must be EQUAL and hash-equal (they ride
        jit static args and solver-cache keys; a NaN-valued float
        field would break this - hence the string-spelled value)."""
        a = FaultPlan(site="halo", iteration=10, shard=1)
        b = FaultPlan(site="halo", iteration=10, shard=1)
        assert a == b and hash(a) == hash(b)
        assert a.fingerprint() == b.fingerprint()
        assert a.fingerprint() != FaultPlan(
            site="halo", iteration=11, shard=1).fingerprint()

    def test_after_restart(self):
        assert FaultPlan(site="spmv", iteration=3).after_restart() \
            is None
        sticky = FaultPlan(site="spmv", iteration=3, sticky=True)
        assert sticky.after_restart() is sticky

    def test_validation(self):
        with pytest.raises(ValueError, match="site"):
            FaultPlan(site="wire", iteration=1)
        with pytest.raises(ValueError, match="value"):
            FaultPlan(site="halo", iteration=1, value="7.0")
        with pytest.raises(ValueError):
            FaultPlan(site="halo", iteration=-1)


@needs_mesh
class TestChaosMatrix:
    """Every injection site x mesh {1, 4}: typed BREAKDOWN within
    check_every of the poisoned step, and recovery reaches the
    fault-free answer."""

    @pytest.mark.parametrize("site", ["halo", "spmv", "reduction"])
    @pytest.mark.parametrize("n_shards", [1, 4])
    def test_detected_and_recovered(self, site, n_shards,
                                    fixture_problem):
        a, b = fixture_problem
        mesh = make_mesh(n_shards)
        shard = 0 if n_shards == 1 else 2
        plan = FaultPlan(site=site, iteration=10, shard=shard)
        clean = solve_distributed(a, b, mesh=mesh, tol=1e-8,
                                  maxiter=500)
        assert _status(clean) == "CONVERGED"

        broken = solve_distributed(a, b, mesh=mesh, tol=1e-8,
                                   maxiter=500, inject=plan)
        assert _status(broken) == "BREAKDOWN"
        # detection within one check_every(=1) block of the poisoned
        # step (the step that computes iteration 11 is the faulted one)
        assert 10 <= int(broken.iterations) <= 11

        rr = solve_with_recovery(a, b, mesh=mesh, tol=1e-8,
                                 maxiter=500, inject=plan)
        assert rr.recovered and rr.restarts == 1
        assert _status(rr.result) == "CONVERGED"
        err = float(np.max(np.abs(np.asarray(rr.result.x)
                                  - np.asarray(clean.x))))
        assert err < 1e-5

    def test_detection_within_check_every_block(self, fixture_problem):
        a, b = fixture_problem
        res = solve_distributed(
            a, b, mesh=make_mesh(4), tol=1e-8, maxiter=500,
            check_every=8,
            inject=FaultPlan(site="spmv", iteration=10))
        assert _status(res) == "BREAKDOWN"
        assert int(res.iterations) - 10 <= 8 + 1

    def test_gather_lane_halo_fault(self, fixture_problem):
        """The packed-round gather exchange carries the same halo
        injection site (the extended-x region is the received
        payload)."""
        a, b = fixture_problem
        res = solve_distributed(
            a, b, mesh=make_mesh(4), tol=1e-8, maxiter=500,
            exchange="gather",
            inject=FaultPlan(site="halo", iteration=10, shard=1))
        assert _status(res) == "BREAKDOWN"
        assert 10 <= int(res.iterations) <= 11

    def test_ring_lane_refuses(self, fixture_problem):
        a, b = fixture_problem
        with pytest.raises(ValueError, match="allgather/gather"):
            solve_distributed(a, b, mesh=make_mesh(4), csr_comm="ring",
                              inject=FaultPlan(site="spmv",
                                               iteration=5))


class TestSingleDevice:
    def test_spmv_and_reduction_breakdown(self):
        a = poisson_2d_csr(8, 8)
        b = np.asarray(
            a @ np.random.default_rng(1).standard_normal(64))
        for site in ("spmv", "reduction"):
            res = solve(a, b, tol=1e-9, maxiter=200,
                        fault=FaultPlan(site=site, iteration=3))
            assert _status(res) == "BREAKDOWN"
            assert 3 <= int(res.iterations) <= 4

    def test_halo_refuses_without_exchange(self):
        a = poisson_2d_csr(8, 8)
        with pytest.raises(ValueError, match="halo"):
            solve(a, np.ones(64),
                  fault=FaultPlan(site="halo", iteration=3))

    def test_variant_methods_refuse(self):
        a = poisson_2d_csr(8, 8)
        for method in ("cg1", "pipecg", "minres"):
            with pytest.raises(ValueError, match="method='cg'"):
                solve(a, np.ones(64), method=method,
                      fault=FaultPlan(site="spmv", iteration=3))

    def test_single_device_recovery(self):
        a = poisson_2d_csr(8, 8)
        rng = np.random.default_rng(2)
        x_true = rng.standard_normal(64)
        b = np.asarray(a @ x_true)
        clean = solve(a, b, tol=1e-10, maxiter=200)
        rr = solve_with_recovery(
            a, b, tol=1e-10, maxiter=200,
            inject=FaultPlan(site="reduction", iteration=5))
        assert rr.recovered
        np.testing.assert_allclose(np.asarray(rr.result.x),
                                   np.asarray(clean.x), atol=1e-8)


class TestManyRHSLaneIsolation:
    def test_reduction_fault_breaks_only_its_lane(self):
        """The chaos proof that per-lane failure isolation is real: a
        poisoned reduction scalar on lane 2 exits THAT lane with a
        typed BREAKDOWN while its batchmates converge."""
        a = poisson_2d_csr(8, 8)
        rng = np.random.default_rng(3)
        x_true = rng.standard_normal((64, 4))
        b = np.asarray(a.matmat(x_true))
        res = solve_many(a, b, tol=1e-9, maxiter=200,
                         fault=FaultPlan(site="reduction", iteration=5,
                                         lane=2))
        statuses = [s.name for s in res.status_enums()]
        assert statuses[2] == "BREAKDOWN"
        assert [s for i, s in enumerate(statuses) if i != 2] \
            == ["CONVERGED"] * 3
        # the poisoned lane froze at its breakdown step; the others
        # ran to convergence
        iters = np.asarray(res.iterations)
        assert int(iters[2]) <= 6 < int(iters[0])

    def test_block_method_refuses(self):
        a = poisson_2d_csr(8, 8)
        with pytest.raises(ValueError, match="batched"):
            solve_many(a, np.ones((64, 2)), method="block",
                       fault=FaultPlan(site="spmv", iteration=5))


class TestRecoveryPolicy:
    def test_sticky_fault_exhausts_budget_typed(self):
        a = poisson_2d_csr(8, 8)
        b = np.asarray(
            a @ np.random.default_rng(4).standard_normal(64))
        rr = solve_with_recovery(
            a, b, tol=1e-9, maxiter=200,
            policy=RecoveryPolicy(max_restarts=2),
            inject=FaultPlan(site="spmv", iteration=3, sticky=True))
        assert not rr.recovered
        assert rr.restarts == 2 and rr.attempts == 3
        assert _status(rr.result) == "BREAKDOWN"
        assert len(rr.faults) == 3

    def test_zero_restarts_detect_only(self):
        a = poisson_2d_csr(8, 8)
        b = np.ones(64)
        rr = solve_with_recovery(
            a, b, tol=1e-9, maxiter=200,
            policy=RecoveryPolicy(max_restarts=0),
            inject=FaultPlan(site="spmv", iteration=3))
        assert not rr.recovered and rr.attempts == 1
        assert _status(rr.result) == "BREAKDOWN"

    def test_snapshot_every_restarts_from_finite_iterate(self):
        """With segment snapshots, a late fault restarts from a finite
        PRE-fault iterate (not zero) and still lands on the fault-free
        answer."""
        a = poisson_2d_csr(8, 8)
        b = np.asarray(
            a @ np.random.default_rng(5).standard_normal(64))
        clean = solve(a, b, tol=1e-10, maxiter=200)
        seen = []
        with events.capture() as buf:
            rr = solve_with_recovery(
                a, b, tol=1e-10, maxiter=200,
                policy=RecoveryPolicy(max_restarts=1,
                                      snapshot_every=10),
                inject=FaultPlan(site="spmv", iteration=25))
        import json

        seen = [json.loads(ln) for ln in
                buf.getvalue().splitlines() if ln.strip()]
        assert rr.recovered
        restarts = [e for e in seen if e["event"] == "solve_recovery"
                    and e["action"] == "restart"]
        assert restarts and restarts[0]["seed"] \
            == "last_finite_segment"
        np.testing.assert_allclose(np.asarray(rr.result.x),
                                   np.asarray(clean.x), atol=1e-8)

    def test_events_and_counters(self):
        from cuda_mpi_parallel_tpu.telemetry.registry import REGISTRY

        a = poisson_2d_csr(8, 8)
        b = np.ones(64)
        with events.capture() as buf:
            solve_with_recovery(
                a, b, tol=1e-9, maxiter=200,
                inject=FaultPlan(site="reduction", iteration=3))
        import json

        recs = [json.loads(ln) for ln in
                buf.getvalue().splitlines() if ln.strip()]
        faults = [events.validate_event(e) for e in recs
                  if e["event"] == "solve_fault"]
        recovs = [events.validate_event(e) for e in recs
                  if e["event"] == "solve_recovery"]
        assert faults and faults[0]["site"] == "reduction"
        assert {e["action"] for e in recovs} \
            == {"restart", "recovered"}
        snap = REGISTRY.snapshot()
        assert "solve_breakdowns_total" in snap
        assert "solve_recoveries_total" in snap


@needs_mesh
class TestPreemptionDrill:
    """Kill a distributed resumable segment; resume; the final
    trajectory bit-matches the uninterrupted run (p and rho restored,
    not restarted)."""

    def test_resume_bitwise_trajectory(self, fixture_problem,
                                       tmp_path):
        a, b = fixture_problem
        mesh = make_mesh(4)
        full_path = str(tmp_path / "full.npz")
        full = solve_resumable_distributed(
            a, b, full_path, mesh=mesh, segment_iters=20, tol=1e-8,
            maxiter=500)
        assert bool(full.converged)

        ck = str(tmp_path / "preempted.npz")
        with pytest.raises(PreemptedError):
            solve_resumable_distributed(
                a, b, ck, mesh=mesh, segment_iters=20, tol=1e-8,
                maxiter=500, preempt=Preemption(after_segments=1))
        assert os.path.exists(ck)
        resumed = solve_resumable_distributed(
            a, b, ck, mesh=mesh, segment_iters=20, tol=1e-8,
            maxiter=500)
        assert bool(resumed.converged)
        assert int(resumed.iterations) == int(full.iterations)
        # bit-match: resuming restored the exact recurrence state
        assert np.array_equal(np.asarray(resumed.x),
                              np.asarray(full.x))

    def test_mismatched_layout_fails_typed(self, fixture_problem,
                                           tmp_path):
        a, b = fixture_problem
        mesh = make_mesh(4)
        ck = str(tmp_path / "layout.npz")
        with pytest.raises(PreemptedError):
            solve_resumable_distributed(
                a, b, ck, mesh=mesh, segment_iters=20, tol=1e-8,
                maxiter=500, preempt=Preemption(after_segments=1))
        # a different exchange lane is a different layout fingerprint
        with pytest.raises(CheckpointMismatch):
            solve_resumable_distributed(
                a, b, ck, mesh=mesh, segment_iters=20, tol=1e-8,
                maxiter=500, exchange="gather")
        # ... and a different mesh size too
        with pytest.raises(CheckpointMismatch):
            solve_resumable_distributed(
                a, b, ck, mesh=make_mesh(2), segment_iters=20,
                tol=1e-8, maxiter=500)

    def test_breakdown_segment_preserves_last_good_checkpoint(
            self, fixture_problem, tmp_path):
        """A breakdown mid-segment must NOT overwrite the last good
        checkpoint with non-finite state: the pre-fault progress on
        disk is exactly what recovery restarts from."""
        from cuda_mpi_parallel_tpu.utils.checkpoint import (
            load_checkpoint,
        )

        a, b = fixture_problem
        mesh = make_mesh(4)
        ck = str(tmp_path / "broke.npz")
        res = solve_resumable_distributed(
            a, b, ck, mesh=mesh, segment_iters=20, tol=1e-8,
            maxiter=500,
            inject=FaultPlan(site="spmv", iteration=30, sticky=True))
        assert _status(res) == "BREAKDOWN"
        # the file still holds segment 1's FINITE state (k=20)
        saved = load_checkpoint(ck)
        assert int(saved.k) == 20
        assert np.isfinite(np.asarray(saved.x)).all()
        # a clean re-run resumes from it and converges
        clean = solve_distributed(a, b, mesh=mesh, tol=1e-8,
                                  maxiter=500)
        resumed = solve_resumable_distributed(
            a, b, ck, mesh=mesh, segment_iters=20, tol=1e-8,
            maxiter=500)
        assert bool(resumed.converged)
        np.testing.assert_allclose(np.asarray(resumed.x),
                                   np.asarray(clean.x), atol=1e-6)

    def test_segments_share_one_executable(self, fixture_problem,
                                           tmp_path):
        """Every segment re-dispatches the SAME compiled solver (only
        the traced iter_cap advances): the body traces once."""
        from cuda_mpi_parallel_tpu.parallel import dist_cg

        a, b = fixture_problem
        dist_cg.clear_solver_cache()
        before = dist_cg._TRACE_COUNT[0]
        solve_resumable_distributed(
            a, b, str(tmp_path / "one.npz"), mesh=make_mesh(4),
            segment_iters=10, tol=1e-8, maxiter=500)
        traces = dist_cg._TRACE_COUNT[0] - before
        # one trace for the no-resume first segment, one for the
        # resumed-segment signature; later segments reuse both
        assert traces <= 2


class TestValidation:
    def test_check_finite_rhs(self):
        check_finite_rhs(np.ones(4))
        with pytest.raises(ValueError, match="non-finite"):
            check_finite_rhs(np.array([1.0, np.nan]))
        with pytest.raises(ValueError, match="non-finite"):
            check_finite_rhs(np.array([1.0, np.inf]))

    @needs_mesh
    def test_solve_distributed_rejects_nan_b(self, fixture_problem):
        a, _ = fixture_problem
        bad = np.ones(240)
        bad[7] = np.nan
        with pytest.raises(ValueError, match="non-finite"):
            solve_distributed(a, bad, mesh=make_mesh(4))

    @needs_mesh
    def test_opt_out_reaches_typed_breakdown(self, fixture_problem):
        """validate=False stages the poisoned system deliberately; the
        in-loop guard still exits typed (never silently wrong)."""
        a, _ = fixture_problem
        bad = np.ones(240)
        bad[7] = np.nan
        res = solve_distributed(a, bad, mesh=make_mesh(4), tol=1e-8,
                                maxiter=50, validate=False)
        assert _status(res) == "BREAKDOWN"
        assert int(res.iterations) <= 1

    def test_poisoned_matrix_rejected(self):
        from cuda_mpi_parallel_tpu.robust.validate import (
            check_finite_problem,
        )

        a = poisson_2d_csr(8, 8)
        bad = type(a).from_arrays(
            np.where(np.arange(a.data.shape[0]) == 3, np.nan,
                     np.asarray(a.data)),
            np.asarray(a.indices), np.asarray(a.indptr))
        with pytest.raises(ValueError, match="non-finite"):
            check_finite_problem(bad, np.ones(64))


class TestServeRobustness:
    """Fake-clock service drills: retry/backoff, circuit breaker
    open -> refuse -> half-open probe, and tolerance degradation."""

    def _service(self, **cfg_kw):
        from cuda_mpi_parallel_tpu.serve import (
            ServiceConfig,
            SolverService,
        )

        t = [0.0]
        cfg = ServiceConfig(clock=lambda: t[0], **cfg_kw)
        return SolverService(cfg), t

    def _problem(self):
        a = poisson_2d_csr(8, 8)
        b = np.asarray(
            a @ np.random.default_rng(7).standard_normal(64))
        return a, b

    def test_retry_recovers_transient_engine_error(self):
        from cuda_mpi_parallel_tpu.serve import RetryPolicy

        a, b = self._problem()
        svc, t = self._service(
            max_batch=2, max_wait_s=0.0,
            retry=RetryPolicy(max_retries=2, backoff_s=1.0))
        try:
            h = svc.register(a)
            fails = [1]
            orig = svc._engine

            def flaky(handle, b_stack, tols):
                if fails[0] > 0:
                    fails[0] -= 1
                    raise RuntimeError("transient blowup")
                return orig(handle, b_stack, tols)

            svc._engine = flaky
            fut = svc.submit(h, b, tol=1e-9)
            svc.pump()              # fails -> re-enqueued with backoff
            assert not fut.done()
            svc.pump()              # backoff gate holds it
            assert not fut.done()
            t[0] = 1.5
            svc.pump()              # retry dispatches and succeeds
            res = fut.result(timeout=5)
            assert res.status == "CONVERGED" and res.attempts == 2
            assert svc.stats()["retries"] == 1
        finally:
            svc._engine = orig
            svc.close()

    def test_breakdown_retried_and_typed_distinct_from_error(self):
        from cuda_mpi_parallel_tpu.serve import RetryPolicy

        a, b = self._problem()
        svc, t = self._service(
            max_batch=2, max_wait_s=0.0,
            retry=RetryPolicy(max_retries=1, backoff_s=0.0))
        try:
            h = svc.register(a, inject=FaultPlan(
                site="spmv", iteration=2, sticky=True))
            fut = svc.submit(h, b, tol=1e-9)
            svc.pump()
            svc.pump()              # the retry fails the same way
            res = fut.result(timeout=5)
            assert res.status == "BREAKDOWN"
            assert res.attempts == 2
            assert res.failure_kind == "problem"   # not "engine"
        finally:
            svc.close()

    def test_breaker_opens_refuses_and_half_open_probes(self):
        a, b = self._problem()
        svc, t = self._service(max_batch=1, max_wait_s=0.0,
                               breaker_threshold=2,
                               breaker_cooldown_s=5.0)
        try:
            h = svc.register(a, inject=FaultPlan(
                site="reduction", iteration=1, sticky=True))
            with events.capture() as buf:
                for _ in range(2):
                    f = svc.submit(h, b)
                    svc.pump()
                    assert f.result(timeout=5).status == "BREAKDOWN"
                assert svc.breaker_state(h) == "open"
                refused = svc.submit(h, b).result(timeout=5)
                assert refused.status == "REFUSED"
                assert refused.failure_kind == "breaker"
                t[0] = 6.0          # past cooldown: one probe admitted
                probe = svc.submit(h, b)
                assert svc.breaker_state(h) == "half_open"
                second = svc.submit(h, b).result(timeout=5)
                assert second.status == "REFUSED"
                svc.pump()
                assert probe.result(timeout=5).status == "BREAKDOWN"
                assert svc.breaker_state(h) == "open"  # probe failed
            import json

            recs = [json.loads(ln) for ln in
                    buf.getvalue().splitlines() if ln.strip()]
            states = [e["state"] for e in recs
                      if e["event"] == "breaker_transition"]
            assert states == ["open", "half_open", "open"]
            assert svc.stats()["refused"] == 2
        finally:
            svc.close()

    def test_breaker_closes_on_successful_probe(self):
        a, b = self._problem()
        svc, t = self._service(max_batch=1, max_wait_s=0.0,
                               breaker_threshold=1,
                               breaker_cooldown_s=5.0)
        try:
            h = svc.register(a)
            orig = svc._engine
            fails = [1]

            def flaky(handle, b_stack, tols):
                if fails[0] > 0:
                    fails[0] -= 1
                    raise RuntimeError("boom")
                return orig(handle, b_stack, tols)

            svc._engine = flaky
            f = svc.submit(h, b)
            svc.pump()
            assert f.result(timeout=5).status == "ERROR"
            assert svc.breaker_state(h) == "open"
            t[0] = 6.0
            probe = svc.submit(h, b)
            svc.pump()
            assert probe.result(timeout=5).status == "CONVERGED"
            assert svc.breaker_state(h) == "closed"
        finally:
            svc._engine = orig
            svc.close()

    def test_probe_timeout_releases_breaker_slot(self):
        """A half-open probe that expires its deadline in queue never
        dispatched: the probe slot must free so the NEXT submit can
        probe (a wedged handle would refuse forever)."""
        a, b = self._problem()
        svc, t = self._service(max_batch=1, max_wait_s=100.0,
                               breaker_threshold=1,
                               breaker_cooldown_s=5.0)
        try:
            h = svc.register(a, inject=FaultPlan(
                site="reduction", iteration=1, sticky=True))
            f = svc.submit(h, b)
            svc.pump()
            assert f.result(timeout=5).status == "BREAKDOWN"
            assert svc.breaker_state(h) == "open"
            t[0] = 6.0
            probe = svc.submit(h, b, deadline_s=1.0)
            assert svc.breaker_state(h) == "half_open"
            t[0] = 8.0          # deadline expired before any dispatch
            svc.pump()
            assert probe.result(timeout=5).status == "TIMEOUT"
            # the slot is free: a new submit is admitted as the probe
            # (queued), not REFUSED
            probe2 = svc.submit(h, b)
            svc.pump()
            assert probe2.result(timeout=5).status == "BREAKDOWN"
        finally:
            svc.close()

    def test_degrades_tolerance_under_pressure(self):
        a, b = self._problem()
        svc, t = self._service(max_batch=8, max_wait_s=100.0,
                               degrade_depth=2)
        try:
            h = svc.register(a)
            f1 = svc.submit(h, b, tol=1e-9)
            f2 = svc.submit(h, b, tol=1e-9)
            f3 = svc.submit(h, b, tol=1e-9)   # depth >= 2: degraded
            svc._step(svc._clock(), drain=True)
            assert not f1.result(5).degraded
            assert not f2.result(5).degraded
            r3 = f3.result(5)
            assert r3.degraded and r3.status == "CONVERGED"
            assert svc.stats()["degraded"] == 1
        finally:
            svc.close()

    def test_submit_rejects_nan_b(self):
        a, b = self._problem()
        svc, t = self._service(max_batch=2)
        try:
            h = svc.register(a)
            bad = b.copy()
            bad[3] = np.nan
            with pytest.raises(ValueError, match="non-finite"):
                svc.submit(h, bad)
        finally:
            svc.close()


class TestZeroPerturbation:
    """``fault=None`` / ``inject=None`` (the defaults) must leave
    every solve body jaxpr BIT-identical to a call that never mentions
    the chaos harness."""

    def test_cg_fault_none_jaxpr_identical(self):
        a = poisson_2d_csr(8, 8)
        b = np.ones(64)
        base = str(jax.make_jaxpr(lambda v: cg(a, v, maxiter=25))(b))
        off = str(jax.make_jaxpr(
            lambda v: cg(a, v, maxiter=25, fault=None))(b))
        assert off == base
        armed = str(jax.make_jaxpr(
            lambda v: cg(a, v, maxiter=25,
                         fault=FaultPlan(site="spmv", iteration=5)))(b))
        assert armed != base

    def test_cg_many_fault_none_jaxpr_identical(self):
        from cuda_mpi_parallel_tpu.solver.many import cg_many

        a = poisson_2d_csr(8, 8)
        b = np.ones((64, 3))
        base = str(jax.make_jaxpr(
            lambda v: cg_many(a, v, maxiter=25))(b))
        off = str(jax.make_jaxpr(
            lambda v: cg_many(a, v, maxiter=25, fault=None))(b))
        assert off == base
        armed = str(jax.make_jaxpr(
            lambda v: cg_many(a, v, maxiter=25,
                              fault=FaultPlan(site="reduction",
                                              iteration=5)))(b))
        assert armed != base

    @needs_mesh
    def test_distributed_solve_body_jaxpr_identical(self,
                                                    fixture_problem):
        """inject=None and the resumable machinery OFF leave the
        traced distributed solve body bit-identical to pre-PR (the
        same capture mechanism as test_exchange's zero-perturbation
        proof)."""
        from cuda_mpi_parallel_tpu.parallel import dist_cg
        from cuda_mpi_parallel_tpu.telemetry import (
            shardscope as ss,
        )

        a, b = fixture_problem
        mesh = make_mesh(4)

        def traced_jaxpr(**kw):
            dist_cg.clear_solver_cache()
            captured = {}
            orig = dist_cg._cached_solver

            def wrapper(key, build, cost_ctx=None, cost_args=None):
                captured["jaxpr"] = jax.make_jaxpr(build())(*cost_args)
                return orig(key, build, cost_ctx, cost_args)

            dist_cg._cached_solver = wrapper
            try:
                dist_cg.solve_distributed(a, b, mesh=mesh, tol=1e-8,
                                          maxiter=500, **kw)
            finally:
                ss.reset_last_shard_report()
                dist_cg._cached_solver = orig
                dist_cg.clear_solver_cache()
            return str(captured["jaxpr"])

        legacy = traced_jaxpr()
        explicit_off = traced_jaxpr(inject=None)
        assert explicit_off == legacy
        validated = traced_jaxpr(validate=True)
        assert validated == legacy
        armed = traced_jaxpr(
            inject=FaultPlan(site="spmv", iteration=10))
        assert armed != legacy
        # the resumable lane genuinely changes the program too (extra
        # in/outputs), under its own cache key
        capped = traced_jaxpr(iter_cap=50)
        assert capped != legacy