"""Pencil (2-axis) decomposition tests on a 4x2 virtual mesh.

The 1-D slab partition stops scaling at n_shards == nx and moves a full
ny*nz plane per neighbor; the pencil partitions two grid axes over a 2-D
mesh.  Oracles: matvec equality against the single-device stencil,
solve parity against the 1-D mesh and the single device, and the
preconditioned (psum over BOTH axes) path.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cuda_mpi_parallel_tpu.utils.compat import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from cuda_mpi_parallel_tpu import solve
from cuda_mpi_parallel_tpu.models.operators import Stencil3D
from cuda_mpi_parallel_tpu.parallel import (
    DistStencil3DPencil,
    make_mesh,
    make_mesh_2d,
    solve_distributed,
)

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (virtual) devices")

GRID = (16, 8, 8)


def _mesh42():
    return make_mesh_2d((4, 2))


class TestPencilMatvec:
    def test_matches_single_device(self, rng):
        mesh = _mesh42()
        nx, ny, nz = GRID
        a_global = Stencil3D.create(*GRID, dtype=jnp.float64)
        local = DistStencil3DPencil.create(GRID, (4, 2),
                                           dtype=jnp.float64)
        x = rng.standard_normal(nx * ny * nz)
        want = np.asarray(a_global @ jnp.asarray(x))

        x3 = jax.device_put(jnp.asarray(x).reshape(GRID),
                            NamedSharding(mesh, P("rows", "cols")))

        @jax.jit
        @shard_map(mesh=mesh, in_specs=P("rows", "cols"),
                       out_specs=P("rows", "cols"))
        def apply(u):
            return (local @ u.reshape(-1)).reshape(local.local_grid)

        got = np.asarray(apply(x3)).reshape(-1)
        np.testing.assert_allclose(got, want, rtol=1e-13, atol=1e-13)

    def test_indivisible_grid_rejected(self):
        with pytest.raises(ValueError, match="not divisible"):
            DistStencil3DPencil.create((10, 8, 8), (4, 2))


class TestPencilSolve:
    def test_matches_single_and_slab(self):
        a = Stencil3D.create(*GRID, dtype=jnp.float64)
        rng = np.random.default_rng(31)
        x_true = rng.standard_normal(a.shape[0])
        b = a @ jnp.asarray(x_true)

        single = solve(a, b, tol=0.0, rtol=1e-9, maxiter=500)
        slab = solve_distributed(a, b, mesh=make_mesh(8), tol=0.0,
                                 rtol=1e-9, maxiter=500)
        pencil = solve_distributed(a, b, mesh=_mesh42(), tol=0.0,
                                   rtol=1e-9, maxiter=500)
        assert bool(pencil.converged)
        assert int(pencil.iterations) == int(slab.iterations)
        assert abs(int(pencil.iterations) - int(single.iterations)) <= 1
        np.testing.assert_allclose(np.asarray(pencil.x), x_true, atol=1e-7)
        np.testing.assert_allclose(np.asarray(pencil.x),
                                   np.asarray(slab.x), rtol=1e-9,
                                   atol=1e-11)

    def test_chebyshev_on_pencil(self):
        """Chebyshev's power iteration and application psum over BOTH
        mesh axes."""
        a = Stencil3D.create(*GRID, dtype=jnp.float64)
        rng = np.random.default_rng(32)
        x_true = rng.standard_normal(a.shape[0])
        b = a @ jnp.asarray(x_true)
        from cuda_mpi_parallel_tpu.models.precond import (
            ChebyshevPreconditioner,
        )

        single = solve(a, b, tol=0.0, rtol=1e-9, maxiter=500,
                       m=ChebyshevPreconditioner.from_operator(a, degree=3))
        pencil = solve_distributed(a, b, mesh=_mesh42(), tol=0.0,
                                   rtol=1e-9, maxiter=500,
                                   preconditioner="chebyshev",
                                   precond_degree=3)
        assert bool(pencil.converged)
        assert abs(int(pencil.iterations) - int(single.iterations)) <= 2
        np.testing.assert_allclose(np.asarray(pencil.x), x_true, atol=1e-7)

    def test_pipecg_on_pencil(self):
        a = Stencil3D.create(*GRID, dtype=jnp.float64)
        rng = np.random.default_rng(33)
        b = jnp.asarray(rng.standard_normal(a.shape[0]))
        res = solve_distributed(a, b, mesh=_mesh42(), tol=0.0, rtol=1e-8,
                                maxiter=500, method="pipecg")
        assert bool(res.converged)

    def test_mg_on_pencil_iteration_parity(self):
        """The V-cycle's transfers halo-exchange over BOTH mesh axes and
        its gather level all_gathers over both; the combined hierarchy is
        exactly the single-device hierarchy, so iteration counts match."""
        a = Stencil3D.create(*GRID, dtype=jnp.float64)
        rng = np.random.default_rng(34)
        x_true = rng.standard_normal(a.shape[0])
        b = a @ jnp.asarray(x_true)
        from cuda_mpi_parallel_tpu.models.multigrid import (
            MultigridPreconditioner,
        )

        single = solve(a, b, tol=0.0, rtol=1e-9, maxiter=200,
                       m=MultigridPreconditioner.from_operator(a))
        pencil = solve_distributed(a, b, mesh=_mesh42(), tol=0.0,
                                   rtol=1e-9, maxiter=200,
                                   preconditioner="mg")
        slab = solve_distributed(a, b, mesh=make_mesh(8), tol=0.0,
                                 rtol=1e-9, maxiter=200,
                                 preconditioner="mg")
        assert bool(pencil.converged)
        assert int(pencil.iterations) == int(single.iterations)
        assert int(slab.iterations) == int(single.iterations)
        np.testing.assert_allclose(np.asarray(pencil.x), x_true, atol=1e-7)

    def test_unknown_preconditioner_rejected_on_pencil(self):
        a = Stencil3D.create(*GRID, dtype=jnp.float64)
        b = jnp.ones(a.shape[0])
        with pytest.raises(ValueError, match="unknown preconditioner"):
            solve_distributed(a, b, mesh=_mesh42(), preconditioner="jacob")
        with pytest.raises(ValueError, match="single-device"):
            solve_distributed(a, b, mesh=_mesh42(),
                              preconditioner="bjacobi")

    def test_pallas_backend_rejected_on_pencil(self):
        a = Stencil3D.create(128, 128, 128, dtype=jnp.float32,
                             backend="pallas")
        b = jnp.ones(a.shape[0], jnp.float32)
        with pytest.raises(ValueError, match="pallas"):
            solve_distributed(a, b, mesh=_mesh42())

    def test_wrong_rhs_length_clear_error(self):
        a = Stencil3D.create(*GRID, dtype=jnp.float64)
        with pytest.raises(ValueError, match="does not match rhs"):
            solve_distributed(a, jnp.ones(17), mesh=_mesh42())

    def test_2d_mesh_rejects_non_stencil3d(self):
        from cuda_mpi_parallel_tpu.models import poisson

        a = poisson.poisson_2d_csr(8, 8)
        b = jnp.ones(64)
        with pytest.raises(TypeError, match="Stencil3D"):
            solve_distributed(a, b, mesh=_mesh42())
