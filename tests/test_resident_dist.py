"""Distributed VMEM-resident CG (``ops/pallas/resident_dist.py`` +
``parallel/resident.py``): the flagship engine's multi-chip form.

Round-4 verdict item 3's done-criterion and beyond: N-device
TPU-interpret runs (the simulator models remote DMAs, semaphores and
happens-before ordering) with iteration parity against the
single-device resident kernel, plus a race-detector pass.  The
COMPILED form was verified on a real v5e in its 1-shard degenerate
(round 5): bitwise-identical x and iteration count vs ``cg_resident``
at 1024^2, with the self-RDMA ring active.
"""
import numpy as np
import pytest

import jax.numpy as jnp

from cuda_mpi_parallel_tpu import cg_resident
from cuda_mpi_parallel_tpu.analysis.runtime import (
    RaceDetectorUnavailable,
    check_races,
)
from cuda_mpi_parallel_tpu.models import poisson
from cuda_mpi_parallel_tpu.parallel import make_mesh
from cuda_mpi_parallel_tpu.parallel.resident import (
    solve_distributed_resident,
)


def _single(op, b, **kw):
    return cg_resident(op, b, interpret=True, **kw)


def _check_races_or_skip(kernel):
    """Run ``kernel`` under analysis.runtime.check_races (the promoted
    form of this file's original jax-internal import), skipping when
    the running jax has no TPU-interpret race detector."""
    try:
        return check_races(kernel)
    except RaceDetectorUnavailable as e:
        pytest.skip(str(e))


class TestParity2D:
    def _problem(self, nx=32, ny=128, seed=0):
        op = poisson.poisson_2d_operator(nx, ny, dtype=jnp.float32)
        rng = np.random.default_rng(seed)
        return op, rng.standard_normal(nx * ny).astype(np.float32)

    @pytest.mark.parametrize("n_shards", [2, 4])
    def test_iteration_parity_vs_single_kernel(self, n_shards):
        op, b = self._problem()
        single = _single(op, b, tol=1e-3, maxiter=300, check_every=8)
        dist = solve_distributed_resident(
            op, b, mesh=make_mesh(n_shards), tol=1e-3, maxiter=300,
            check_every=8)
        assert bool(dist.converged)
        assert int(dist.iterations) == int(single.iterations)
        # dots: per-shard partials summed in fixed order vs the single
        # kernel's full-slab reduction - f32 reduction-order rounding
        assert np.abs(np.asarray(dist.x)
                      - np.asarray(single.x)).max() < 1e-4

    @pytest.mark.parametrize("n_shards", [2, 4])
    def test_race_detector_clean(self, n_shards):
        # the simulator's happens-before checker over the kernel's
        # remote DMAs and semaphores: the no-barrier single-buffer
        # design must be provably race-free, not just numerically lucky.
        # n=4 matters: orderings that hold between ring NEIGHBORS do
        # not automatically hold between non-neighbors (the round-5
        # rho-buffer race was exactly that, invisible at n=2).
        # check_races (analysis/runtime.py) passes detect_races=True
        # through the **kw and resets the sticky simulator state.
        op, b = self._problem(32, 128)
        report = _check_races_or_skip(
            lambda **kw: solve_distributed_resident(
                op, b, mesh=make_mesh(n_shards), tol=1e-3, maxiter=100,
                check_every=8, **kw))
        assert not report.races_found

    def test_solution_correct(self):
        op = poisson.poisson_2d_operator(32, 128, dtype=jnp.float32)
        rng = np.random.default_rng(3)
        x_true = rng.standard_normal(op.shape[0]).astype(np.float32)
        b = np.asarray(op @ jnp.asarray(x_true))
        dist = solve_distributed_resident(
            op, b, mesh=make_mesh(4), tol=0.0, rtol=1e-5, maxiter=2000,
            check_every=16)
        assert bool(dist.converged)
        assert np.abs(np.asarray(dist.x) - x_true).max() < 1e-2


class TestParity3D:
    def test_iteration_parity_4dev(self):
        op = poisson.poisson_3d_operator(8, 8, 128, dtype=jnp.float32)
        rng = np.random.default_rng(1)
        b = rng.standard_normal(op.shape[0]).astype(np.float32)
        single = _single(op, b, tol=1e-3, maxiter=300, check_every=8)
        dist = solve_distributed_resident(
            op, b, mesh=make_mesh(4), tol=1e-3, maxiter=300,
            check_every=8)
        assert bool(dist.converged)
        assert int(dist.iterations) == int(single.iterations)
        assert np.abs(np.asarray(dist.x)
                      - np.asarray(single.x)).max() < 1e-4

    def test_single_plane_shards(self):
        # per-shard nx == 1: the corr-row special case (both neighbor
        # corrections land on the same plane)
        op = poisson.poisson_3d_operator(8, 8, 128, dtype=jnp.float32)
        rng = np.random.default_rng(2)
        b = rng.standard_normal(op.shape[0]).astype(np.float32)
        single = _single(op, b, tol=1e-3, maxiter=300, check_every=8)
        dist = solve_distributed_resident(
            op, b, mesh=make_mesh(8), tol=1e-3, maxiter=300,
            check_every=8)
        assert bool(dist.converged)
        assert int(dist.iterations) == int(single.iterations)


class TestGateAndErrors:
    def test_rejections(self):
        op = poisson.poisson_2d_operator(32, 128, dtype=jnp.float32)
        b = np.ones(32 * 128, np.float32)
        # per-shard nx % 8 != 0 (2D sublane tiling)
        with pytest.raises(ValueError, match="resident gate"):
            solve_distributed_resident(op, b, mesh=make_mesh(8))
        # non-dividing leading axis
        op2 = poisson.poisson_2d_operator(20, 128, dtype=jnp.float32)
        b2 = np.ones(20 * 128, np.float32)
        with pytest.raises(ValueError, match="divide"):
            solve_distributed_resident(op2, b2, mesh=make_mesh(8))
        # non-stencil operator
        a_csr = poisson.poisson_2d_csr(16, 16, dtype=np.float32)
        with pytest.raises(TypeError, match="Stencil"):
            solve_distributed_resident(a_csr, np.ones(256, np.float32),
                                       mesh=make_mesh(2))
        # f64 operator
        op64 = poisson.poisson_2d_operator(32, 128, dtype=jnp.float64)
        with pytest.raises(ValueError, match="float32"):
            solve_distributed_resident(op64, b, mesh=make_mesh(2))

    def test_maxiter_status(self):
        from cuda_mpi_parallel_tpu.solver.status import CGStatus

        op = poisson.poisson_2d_operator(16, 128, dtype=jnp.float32)
        rng = np.random.default_rng(5)
        b = rng.standard_normal(op.shape[0]).astype(np.float32)
        dist = solve_distributed_resident(
            op, b, mesh=make_mesh(2), tol=1e-30, maxiter=8,
            check_every=8)
        assert not bool(dist.converged)
        assert int(dist.iterations) == 8
        assert int(dist.status) == int(CGStatus.MAXITER)


class TestChebyshevDistributed:
    """In-kernel Chebyshev on the distributed resident engine (round 5):
    each cheb step applies the stencil to a fresh z, so each step runs
    its own halo exchange - parity-double-buffered z slots (consecutive
    steps alternate; two-apart steps are ordered by the halo-wait
    chain), plus one extra allreduce (rho = r . z) per iteration.
    Compiled 1-shard form verified BITWISE vs cg_resident(m=cheb) on a
    real v5e (672 == 672 at 1024^2, round 5)."""

    def _cheb(self, op, degree):
        from cuda_mpi_parallel_tpu.models.precond import (
            ChebyshevPreconditioner,
        )

        return ChebyshevPreconditioner.from_operator(op, degree=degree)

    @pytest.mark.parametrize("n_shards", [2, 4])
    def test_2d_parity_vs_single_kernel(self, n_shards):
        op = poisson.poisson_2d_operator(32, 128, dtype=jnp.float32)
        rng = np.random.default_rng(0)
        b = rng.standard_normal(op.shape[0]).astype(np.float32)
        m = self._cheb(op, 4)
        single = _single(op, b, tol=1e-3, maxiter=300, check_every=8, m=m)
        dist = solve_distributed_resident(
            op, b, mesh=make_mesh(n_shards), tol=1e-3, maxiter=300,
            check_every=8, m=m)
        assert bool(dist.converged)
        assert int(dist.iterations) == int(single.iterations)
        # fewer iterations than unpreconditioned (the polynomial works)
        plain = _single(op, b, tol=1e-3, maxiter=300, check_every=8)
        assert int(dist.iterations) < int(plain.iterations)

    @pytest.mark.parametrize("degree", [3, 4])
    def test_3d_parity_and_races(self, degree):
        # degree 4 matters for the race check: its three cheb steps
        # REUSE a z-halo parity slot (steps 0 and 2), exercising the
        # j/j+2 happens-before chain the kernel's safety argument
        # relies on - degree 3 never revisits a slot
        op = poisson.poisson_3d_operator(8, 8, 128, dtype=jnp.float32)
        rng = np.random.default_rng(1)
        b = rng.standard_normal(op.shape[0]).astype(np.float32)
        m = self._cheb(op, degree)
        single = _single(op, b, tol=1e-3, maxiter=300, check_every=8, m=m)
        report = _check_races_or_skip(
            lambda **kw: solve_distributed_resident(
                op, b, mesh=make_mesh(4), tol=1e-3, maxiter=300,
                check_every=8, m=m, **kw))
        dist = report.result
        assert bool(dist.converged)
        assert int(dist.iterations) == int(single.iterations)
        # the parity-double-buffered z exchanges must be provably
        # race-free, not numerically lucky
        assert not report.races_found

    def test_foreign_preconditioner_rejected(self):
        op = poisson.poisson_2d_operator(32, 128, dtype=jnp.float32)
        other = poisson.poisson_2d_operator(16, 128, dtype=jnp.float32)
        b = np.ones(op.shape[0], np.float32)
        with pytest.raises(ValueError, match="same stencil"):
            solve_distributed_resident(op, b, mesh=make_mesh(2),
                                       m=self._cheb(other, 4))
        from cuda_mpi_parallel_tpu.models.operators import (
            JacobiPreconditioner,
        )

        with pytest.raises(TypeError, match="Chebyshev"):
            solve_distributed_resident(
                op, b, mesh=make_mesh(2),
                m=JacobiPreconditioner.from_operator(op))
