"""Preconditioner tests: Chebyshev polynomial and block-Jacobi.

The reference has no preconditioning (its CG is the bare recurrence,
``CUDACG.cu:269-352``); these are capability additions, so the oracles are
mathematical: SPD-ness of M^-1, iteration-count reduction versus
unpreconditioned CG at equal tolerance, spectral-estimate accuracy against
the analytic Laplacian spectrum, and 1-vs-8-device trajectory parity.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
import scipy.sparse as sp

from cuda_mpi_parallel_tpu import solve
from cuda_mpi_parallel_tpu.models import poisson
from cuda_mpi_parallel_tpu.models.operators import (
    JacobiPreconditioner,
    Stencil2D,
)
from cuda_mpi_parallel_tpu.models.precond import (
    BlockJacobiPreconditioner,
    ChebyshevPreconditioner,
    estimate_lmax,
)


def _laplacian_2d_lmax(n: int) -> float:
    """Analytic largest eigenvalue of the n x n 5-point Dirichlet
    Laplacian: 8 sin^2(n pi / (2(n+1)))."""
    return 8.0 * np.sin(n * np.pi / (2 * (n + 1))) ** 2


def _random_spd_csr(rng, n=96, density=0.05):
    seed = int(rng.integers(2 ** 31))
    m = sp.random(n, n, density=density,
                  random_state=np.random.RandomState(seed), format="csr")
    m = m + m.T + sp.eye(n) * (np.abs(m).sum(axis=1).max() + 1.0)
    m = m.tocsr()
    m.sort_indices()
    from cuda_mpi_parallel_tpu.models.operators import CSRMatrix

    return CSRMatrix.from_scipy(m), m


class TestEstimateLmax:
    def test_poisson2d_matches_analytic(self):
        # the top Laplacian eigenvalues cluster, so power iteration needs
        # a few hundred steps for percent-level Rayleigh accuracy
        n = 16
        a = poisson.poisson_2d_operator(n, n, dtype=jnp.float64)
        est = float(estimate_lmax(a, iters=200, safety=1.0))
        exact = _laplacian_2d_lmax(n)
        assert abs(est - exact) / exact < 0.02

    def test_jittable(self):
        a = poisson.poisson_2d_operator(8, 8, dtype=jnp.float64)
        est = jax.jit(lambda op: estimate_lmax(op, iters=20))(a)
        assert float(est) > 0


class TestChebyshev:
    def test_symmetric_positive_definite(self, rng):
        """M^-1 must be symmetric (w . M^-1 v == v . M^-1 w) and positive
        definite (v . M^-1 v > 0) for CG theory to apply."""
        n = 16
        a = poisson.poisson_2d_operator(n, n, dtype=jnp.float64)
        m = ChebyshevPreconditioner.from_operator(a, degree=5)
        v = jnp.asarray(rng.standard_normal(n * n))
        w = jnp.asarray(rng.standard_normal(n * n))
        sym_lhs = float(jnp.vdot(w, m @ v))
        sym_rhs = float(jnp.vdot(v, m @ w))
        assert abs(sym_lhs - sym_rhs) < 1e-10 * max(1, abs(sym_lhs))
        assert float(jnp.vdot(v, m @ v)) > 0

    def test_degree_one_is_scaled_identity(self, rng):
        a = poisson.poisson_2d_operator(8, 8, dtype=jnp.float64)
        m = ChebyshevPreconditioner.from_operator(a, degree=1, lmax=8.0,
                                                  lmin=1.0)
        v = jnp.asarray(rng.standard_normal(64))
        np.testing.assert_allclose(np.asarray(m @ v),
                                   np.asarray(v) / 4.5, rtol=1e-12)

    def test_reduces_iterations_on_poisson(self):
        n = 48
        a = poisson.poisson_2d_operator(n, n, dtype=jnp.float64)
        x_true = np.random.default_rng(3).standard_normal(n * n)
        b = a @ jnp.asarray(x_true)
        plain = solve(a, b, tol=0.0, rtol=1e-8, maxiter=2000)
        m = ChebyshevPreconditioner.from_operator(a, degree=4)
        pcg = solve(a, b, tol=0.0, rtol=1e-8, maxiter=2000, m=m)
        assert bool(plain.converged) and bool(pcg.converged)
        # degree-4 Chebyshev should cut the iteration count by > 2.5x
        assert int(pcg.iterations) * 2.5 < int(plain.iterations)
        np.testing.assert_allclose(np.asarray(pcg.x), x_true, atol=1e-6)

    def test_beats_jacobi_on_poisson(self):
        """On the constant-diagonal Laplacian, Jacobi is a no-op scaling;
        Chebyshev must genuinely beat it."""
        n = 48
        a = poisson.poisson_2d_operator(n, n, dtype=jnp.float64)
        b = jnp.asarray(np.random.default_rng(4).standard_normal(n * n))
        jac = solve(a, b, tol=0.0, rtol=1e-8, maxiter=4000,
                    m=JacobiPreconditioner.from_operator(a))
        cheb = solve(a, b, tol=0.0, rtol=1e-8, maxiter=4000,
                     m=ChebyshevPreconditioner.from_operator(a, degree=4))
        assert int(cheb.iterations) < int(jac.iterations)

    def test_works_on_csr(self, rng):
        a, m_sp = _random_spd_csr(rng)
        x_true = rng.standard_normal(a.shape[0])
        b = jnp.asarray(m_sp @ x_true)
        m = ChebyshevPreconditioner.from_operator(a, degree=3)
        res = solve(a, b, tol=0.0, rtol=1e-10, maxiter=500, m=m)
        assert bool(res.converged)
        np.testing.assert_allclose(np.asarray(res.x), x_true, atol=1e-7)


class TestBlockJacobi:
    def test_block_size_one_equals_jacobi(self, rng):
        a, _ = _random_spd_csr(rng)
        bj = BlockJacobiPreconditioner.from_operator(a, block_size=1)
        j = JacobiPreconditioner.from_operator(a)
        v = jnp.asarray(rng.standard_normal(a.shape[0]))
        np.testing.assert_allclose(np.asarray(bj @ v), np.asarray(j @ v),
                                   rtol=1e-12)

    def test_symmetric_positive_definite(self, rng):
        a, _ = _random_spd_csr(rng)
        m = BlockJacobiPreconditioner.from_operator(a, block_size=8)
        v = jnp.asarray(rng.standard_normal(a.shape[0]))
        w = jnp.asarray(rng.standard_normal(a.shape[0]))
        assert abs(float(jnp.vdot(w, m @ v)) - float(jnp.vdot(v, m @ w))) \
            < 1e-10
        assert float(jnp.vdot(v, m @ v)) > 0

    def test_exact_on_block_diagonal_matrix(self, rng):
        """If A IS block diagonal, block-Jacobi PCG converges in one
        iteration (M^-1 A = I)."""
        bs, nb = 4, 6
        blocks = []
        for _ in range(nb):
            q = rng.standard_normal((bs, bs))
            blocks.append(q @ q.T + bs * np.eye(bs))
        dense = np.zeros((bs * nb, bs * nb))
        for k, blk in enumerate(blocks):
            dense[k * bs:(k + 1) * bs, k * bs:(k + 1) * bs] = blk
        from cuda_mpi_parallel_tpu.models.operators import CSRMatrix

        a = CSRMatrix.from_dense(dense)
        m = BlockJacobiPreconditioner.from_operator(a, block_size=bs)
        b = jnp.asarray(rng.standard_normal(bs * nb))
        res = solve(a, b, tol=1e-10, maxiter=50, m=m)
        assert bool(res.converged)
        assert int(res.iterations) <= 2
        np.testing.assert_allclose(np.asarray(res.x),
                                   np.linalg.solve(dense, np.asarray(b)),
                                   atol=1e-8)

    def test_ragged_tail(self, rng):
        """n not divisible by block_size: padded identity tail."""
        a, m_sp = _random_spd_csr(rng, n=50)
        m = BlockJacobiPreconditioner.from_operator(a, block_size=8)
        x_true = rng.standard_normal(50)
        b = jnp.asarray(m_sp @ x_true)
        res = solve(a, b, tol=0.0, rtol=1e-10, maxiter=500, m=m)
        assert bool(res.converged)
        np.testing.assert_allclose(np.asarray(res.x), x_true, atol=1e-7)

    def test_reduces_iterations(self, rng):
        """Banded SPD system with strong in-block coupling: block-Jacobi
        must beat point-Jacobi."""
        n, bs = 128, 8
        main = 4.0 + rng.random(n)
        off = -1.5 * np.ones(n - 1)
        dense = np.diag(main) + np.diag(off, 1) + np.diag(off, -1)
        from cuda_mpi_parallel_tpu.models.operators import CSRMatrix

        a = CSRMatrix.from_dense(dense)
        b = jnp.asarray(rng.standard_normal(n))
        jac = solve(a, b, tol=0.0, rtol=1e-10, maxiter=1000,
                    m=JacobiPreconditioner.from_operator(a))
        bj = solve(a, b, tol=0.0, rtol=1e-10, maxiter=1000,
                   m=BlockJacobiPreconditioner.from_operator(a, block_size=bs))
        assert int(bj.iterations) < int(jac.iterations)


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")
class TestDistributedChebyshev:
    def test_matches_single_device_trajectory(self):
        from cuda_mpi_parallel_tpu.parallel import make_mesh, solve_distributed

        n = 32
        a = Stencil2D.create(n, n, dtype=jnp.float64)
        x_true = np.random.default_rng(9).standard_normal(n * n)
        b = a @ jnp.asarray(x_true)

        single = solve(a, b, tol=0.0, rtol=1e-9, maxiter=800,
                       m=ChebyshevPreconditioner.from_operator(a, degree=4))
        dist = solve_distributed(a, b, mesh=make_mesh(8), tol=0.0,
                                 rtol=1e-9, maxiter=800,
                                 preconditioner="chebyshev",
                                 precond_degree=4)
        assert bool(dist.converged)
        # same algorithm; spectral estimates differ only through psum
        # rounding, so iteration counts should agree to +-2
        assert abs(int(dist.iterations) - int(single.iterations)) <= 2
        np.testing.assert_allclose(np.asarray(dist.x), x_true, atol=1e-6)
