"""Solver-variant tests: check_every, single-reduction CG, compensated dot.

These cover the SURVEY SS7 "hard parts" the reference never faced:

* check-every-k convergence (the reference checks every iteration on the
  host, ``CUDACG.cu:333``; our k-deep inner loop must NOT change the
  trajectory - inner steps are masked after convergence);
* the Chronopoulos-Gear single-reduction recurrence (``method="cg1"``) -
  algebraically identical iterates, one fused reduction per iteration;
* f32 + compensated (double-float) inner products versus the reference's
  native f64 (``CUDA_R_64F``, ``CUDACG.cu:216``) - TPUs have no native f64.
"""
import jax.numpy as jnp
import numpy as np
import pytest

from cuda_mpi_parallel_tpu import CGStatus, solve
from cuda_mpi_parallel_tpu.models import poisson, random_spd
from cuda_mpi_parallel_tpu.ops import blas1


class TestCheckEvery:
    @pytest.mark.parametrize("k", [2, 3, 8])
    def test_block_semantics(self, k):
        """Same iterates as k=1 up to the convergence point; the count
        lands on the block boundary and extra steps only improve x."""
        op = poisson.poisson_2d_operator(16, 16, dtype=jnp.float64)
        rng = np.random.default_rng(7)
        b = jnp.asarray(rng.standard_normal(256))
        base = solve(op, b, tol=1e-9, record_history=True)
        var = solve(op, b, tol=1e-9, record_history=True, check_every=k)
        kb, kv = int(base.iterations), int(var.iterations)
        assert kb <= kv <= kb + k - 1
        assert kv % k == 0
        # identical trajectory up to the k=1 stopping point
        np.testing.assert_allclose(
            np.asarray(var.residual_history)[: kb + 1],
            np.asarray(base.residual_history)[: kb + 1], rtol=1e-12)
        a64 = np.asarray(op.to_dense())
        res_base = np.linalg.norm(np.asarray(b) - a64 @ np.asarray(base.x))
        res_var = np.linalg.norm(np.asarray(b) - a64 @ np.asarray(var.x))
        assert res_var <= res_base * (1 + 1e-9)

    def test_oracle_with_check_every(self):
        a, b, x_expected = poisson.oracle_system()
        res = solve(a, b, check_every=4)
        assert int(res.iterations) == 4  # 3 rounded up to the block edge
        np.testing.assert_allclose(np.asarray(res.x), x_expected, atol=1e-10)

    def test_invalid_check_every(self):
        a, b, _ = poisson.oracle_system()
        with pytest.raises(ValueError, match="check_every"):
            solve(a, b, check_every=0)

    @pytest.mark.parametrize("method", ["cg", "cg1"])
    def test_no_spurious_indefinite_past_exact_solve(self, method):
        """A block overshooting an exact solve freezes (p = 0, p.Ap = 0);
        that must not be reported as indefiniteness on an SPD system."""
        a = jnp.eye(8)
        b = jnp.ones(8)
        res = solve(a, b, check_every=4, method=method)
        assert bool(res.converged)
        assert not bool(res.indefinite)

    def test_maxiter_never_overshot_by_blocks(self):
        """maxiter not divisible by check_every: the tail loop finishes
        per-iteration, so the cap is exact (review finding: blocks used
        to run past maxiter with k clamped, mislabeling the iterate)."""
        op = poisson.poisson_2d_operator(16, 16, dtype=jnp.float64)
        rng = np.random.default_rng(21)
        b = jnp.asarray(rng.standard_normal(256))
        exact = solve(op, b, tol=1e-30, maxiter=10, record_history=True)
        blocked = solve(op, b, tol=1e-30, maxiter=10, record_history=True,
                        check_every=4)
        assert int(blocked.iterations) == 10
        np.testing.assert_allclose(np.asarray(blocked.x),
                                   np.asarray(exact.x), rtol=1e-12)
        np.testing.assert_allclose(
            np.asarray(blocked.residual_history)[:11],
            np.asarray(exact.residual_history)[:11], rtol=1e-12)


class TestSingleReductionCG:
    def test_oracle_parity(self):
        """cg1 reproduces the 3x3 oracle: same count, same solution."""
        a, b, x_expected = poisson.oracle_system()
        res = solve(a, b, method="cg1", record_history=True)
        assert int(res.iterations) == 3
        np.testing.assert_allclose(np.asarray(res.x), x_expected, atol=1e-9)
        assert bool(res.indefinite)  # quirk Q1 still observed via denom<=0
        assert res.status_enum() == CGStatus.CONVERGED

    def test_trajectory_matches_cg(self):
        """Same alpha_k/beta_k in exact arithmetic: residual histories agree
        to rounding on a well-conditioned SPD system."""
        op = poisson.poisson_2d_operator(16, 16, dtype=jnp.float64)
        rng = np.random.default_rng(11)
        b = jnp.asarray(rng.standard_normal(256))
        r1 = solve(op, b, tol=1e-10, record_history=True)
        r2 = solve(op, b, tol=1e-10, record_history=True, method="cg1")
        k1, k2 = int(r1.iterations), int(r2.iterations)
        assert abs(k1 - k2) <= 2   # rounding may shift the stop by a step
        h1 = np.asarray(r1.residual_history)[: min(k1, k2)]
        h2 = np.asarray(r2.residual_history)[: min(k1, k2)]
        np.testing.assert_allclose(h1, h2, rtol=1e-6)
        np.testing.assert_allclose(np.asarray(r1.x), np.asarray(r2.x),
                                   rtol=1e-8, atol=1e-10)

    def test_preconditioned_cg1(self):
        from cuda_mpi_parallel_tpu import JacobiPreconditioner

        op = random_spd.random_spd_dense(96, cond=1000.0, seed=5,
                                         dtype=np.float64)
        rng = np.random.default_rng(0)
        b = jnp.asarray(rng.standard_normal(96))
        m = JacobiPreconditioner.from_operator(op)
        plain = solve(op, b, tol=1e-9, m=m)
        fused = solve(op, b, tol=1e-9, m=m, method="cg1")
        assert bool(fused.converged)
        np.testing.assert_allclose(np.asarray(fused.x), np.asarray(plain.x),
                                   rtol=1e-6, atol=1e-8)

    def test_cg1_with_check_every(self):
        op = poisson.poisson_2d_operator(16, 16, dtype=jnp.float64)
        rng = np.random.default_rng(3)
        b = jnp.asarray(rng.standard_normal(256))
        base = solve(op, b, tol=1e-9, method="cg1")
        var = solve(op, b, tol=1e-9, method="cg1", check_every=5)
        kb, kv = int(base.iterations), int(var.iterations)
        assert kb <= kv <= kb + 4
        np.testing.assert_allclose(np.asarray(var.x), np.asarray(base.x),
                                   rtol=1e-10, atol=1e-10)

    def test_cg1_rejects_checkpointing(self):
        a, b, _ = poisson.oracle_system()
        with pytest.raises(ValueError, match="method='cg'"):
            solve(a, b, method="cg1", return_checkpoint=True)

    def test_unknown_method(self):
        a, b, _ = poisson.oracle_system()
        with pytest.raises(ValueError, match="unknown method"):
            solve(a, b, method="bicg")


class TestCompensatedDot:
    def test_accuracy_vs_f64(self, rng):
        """f32 compensated dot lands within a few ulp of the f64 result;
        the plain f32 dot does measurably worse on a cancellation-heavy
        vector."""
        n = 1 << 16
        x = (rng.standard_normal(n) * np.logspace(0, 4, n)).astype(np.float32)
        y = rng.standard_normal(n).astype(np.float32)
        y[::2] = -y[1::2] * x[1::2] / np.maximum(np.abs(x[::2]), 1e-3)
        exact = float(np.dot(x.astype(np.float64), y.astype(np.float64)))
        plain = float(blas1.dot(jnp.asarray(x), jnp.asarray(y)))
        comp = float(blas1.dot_compensated(jnp.asarray(x), jnp.asarray(y)))
        scale = float(np.dot(np.abs(x).astype(np.float64),
                             np.abs(y).astype(np.float64)))
        assert abs(comp - exact) <= 1e-6 * scale
        assert abs(comp - exact) <= abs(plain - exact) + 1e-7 * scale

    def test_two_prod_exact(self, rng):
        x = rng.standard_normal(128).astype(np.float32)
        y = rng.standard_normal(128).astype(np.float32)
        p, e = blas1._two_prod(jnp.asarray(x), jnp.asarray(y))
        exact = x.astype(np.float64) * y.astype(np.float64)
        np.testing.assert_array_equal(
            np.asarray(p, dtype=np.float64) + np.asarray(e, dtype=np.float64),
            exact)

    def test_sum_df_exact_on_adversarial_input(self):
        """1e8 + many tiny values: plain f32 sum loses them, df sum keeps
        them."""
        n = 4096
        v = np.full(n, 1e-2, dtype=np.float32)
        v[0] = 1e8
        hi, lo = blas1._sum_df(jnp.asarray(v))
        exact = float(np.sum(v.astype(np.float64)))
        assert abs((float(hi) + float(lo)) - exact) < 1e-1
        plain = float(jnp.sum(jnp.asarray(v)))
        assert abs(plain - exact) >= abs((float(hi) + float(lo)) - exact)

    def test_cg_compensated_f32_converges_deeper(self, rng):
        """On an ill-conditioned f32 system, compensated dots must not be
        worse than plain f32, and the solve still converges."""
        op = random_spd.random_spd_dense(128, cond=1e4, seed=9,
                                         dtype=np.float32)
        b = jnp.asarray(rng.standard_normal(128).astype(np.float32))
        plain = solve(op, b, tol=0.0, rtol=1e-5, maxiter=2000)
        comp = solve(op, b, tol=0.0, rtol=1e-5, maxiter=2000,
                     compensated=True)
        assert bool(comp.converged)
        a64 = np.asarray(op.a, dtype=np.float64)
        b64 = np.asarray(b, dtype=np.float64)
        res_plain = np.linalg.norm(b64 - a64 @ np.asarray(plain.x, np.float64))
        res_comp = np.linalg.norm(b64 - a64 @ np.asarray(comp.x, np.float64))
        assert res_comp <= res_plain * 2.0


class TestPreconditionerBreakdown:
    @pytest.mark.parametrize("method", ["cg", "cg1"])
    def test_non_spd_preconditioner_reports_breakdown(self, method):
        """M with a zero row gives rho = r.Mr = 0 while r != 0: must stop
        immediately with BREAKDOWN, not freeze to maxiter (review
        finding on _safe_div)."""
        from cuda_mpi_parallel_tpu.models.operators import (
            JacobiPreconditioner,
        )

        op = poisson.poisson_2d_operator(4, 4, dtype=jnp.float64)
        m = JacobiPreconditioner(inv_diag=jnp.zeros(16, dtype=jnp.float64))
        b = jnp.ones(16, dtype=jnp.float64)
        res = solve(op, b, m=m, maxiter=500, method=method)
        assert not bool(res.converged)
        assert res.status_enum() == CGStatus.BREAKDOWN
        assert int(res.iterations) <= 1


class TestPipelinedCG:
    def test_oracle_parity(self):
        """pipecg reproduces the 3x3 oracle: same count, same solution."""
        a, b, x_expected = poisson.oracle_system()
        res = solve(a, b, method="pipecg", record_history=True)
        assert int(res.iterations) == 3
        np.testing.assert_allclose(np.asarray(res.x), x_expected, atol=1e-9)
        assert bool(res.indefinite)  # quirk Q1 observed via denom <= 0
        assert res.status_enum() == CGStatus.CONVERGED

    def test_trajectory_matches_cg(self):
        """Same alpha_k/beta_k in exact arithmetic: residual histories agree
        to rounding on a well-conditioned SPD system."""
        op = poisson.poisson_2d_operator(16, 16, dtype=jnp.float64)
        rng = np.random.default_rng(12)
        b = jnp.asarray(rng.standard_normal(256))
        r1 = solve(op, b, tol=1e-10, record_history=True)
        r2 = solve(op, b, tol=1e-10, record_history=True, method="pipecg")
        k1, k2 = int(r1.iterations), int(r2.iterations)
        assert abs(k1 - k2) <= 2
        h1 = np.asarray(r1.residual_history)[: min(k1, k2)]
        h2 = np.asarray(r2.residual_history)[: min(k1, k2)]
        # pipelined CG's recurrence drifts by O(eps * ||r0||) absolute -
        # visible as relative error once the residual is ~1e-10 of r0
        np.testing.assert_allclose(h1, h2, rtol=1e-4, atol=1e-12 * h1[0])
        np.testing.assert_allclose(np.asarray(r1.x), np.asarray(r2.x),
                                   rtol=1e-7, atol=1e-10)

    def test_preconditioned_pipecg(self):
        from cuda_mpi_parallel_tpu import JacobiPreconditioner

        op = random_spd.random_spd_sparse(200, seed=3, dtype=np.float64)
        rng = np.random.default_rng(13)
        x_true = rng.standard_normal(200)
        b = op @ jnp.asarray(x_true)
        m = JacobiPreconditioner.from_operator(op)
        base = solve(op, b, tol=1e-9, m=m)
        pipe = solve(op, b, tol=1e-9, m=m, method="pipecg")
        assert bool(pipe.converged)
        assert abs(int(pipe.iterations) - int(base.iterations)) <= 2
        np.testing.assert_allclose(np.asarray(pipe.x), x_true, atol=1e-6)

    def test_pipecg_with_check_every(self):
        op = poisson.poisson_2d_operator(12, 12, dtype=jnp.float64)
        rng = np.random.default_rng(14)
        b = jnp.asarray(rng.standard_normal(144))
        base = solve(op, b, tol=1e-9, method="pipecg")
        var = solve(op, b, tol=1e-9, method="pipecg", check_every=5)
        # up to k-1 extra iterations run past convergence; they keep
        # refining x below the 1e-9 residual threshold
        np.testing.assert_allclose(np.asarray(var.x), np.asarray(base.x),
                                   rtol=1e-6, atol=1e-9)

    def test_f32_residual_replacement_stability(self):
        """Without periodic residual replacement, f32 pipecg stalls ~3
        orders of magnitude above the tolerance on 128^2 Poisson (the
        recurrence residual separates from the true residual); with the
        cadence-16 replacement it must match cg's iteration count."""
        n = 128
        op = poisson.poisson_2d_operator(n, n, dtype=jnp.float32)
        rng = np.random.default_rng(16)
        x_true = rng.standard_normal(n * n).astype(np.float32)
        b = op @ jnp.asarray(x_true)
        base = solve(op, b, tol=0.0, rtol=1e-5, maxiter=2000)
        pipe = solve(op, b, tol=0.0, rtol=1e-5, maxiter=2000,
                     method="pipecg")
        assert bool(pipe.converged)
        assert abs(int(pipe.iterations) - int(base.iterations)) <= 3
        # the TRUE residual (not just the recurrence) must meet rtol
        true_r = float(jnp.linalg.norm(b - op @ pipe.x))
        assert true_r <= 2e-5 * float(jnp.linalg.norm(b))

    def test_pipecg_rejects_checkpointing(self):
        a, b, _ = poisson.oracle_system()
        with pytest.raises(ValueError, match="method='cg'"):
            solve(a, b, method="pipecg", return_checkpoint=True)

    def test_distributed_pipecg_matches_single(self):
        import jax

        if len(jax.devices()) < 8:
            pytest.skip("needs 8 virtual devices")
        from cuda_mpi_parallel_tpu.models.operators import Stencil2D
        from cuda_mpi_parallel_tpu.parallel import make_mesh, solve_distributed

        n = 32
        a = Stencil2D.create(n, n, dtype=jnp.float64)
        rng = np.random.default_rng(15)
        x_true = rng.standard_normal(n * n)
        b = a @ jnp.asarray(x_true)
        single = solve(a, b, tol=0.0, rtol=1e-9, maxiter=800,
                       method="pipecg")
        dist = solve_distributed(a, b, mesh=make_mesh(8), tol=0.0,
                                 rtol=1e-9, maxiter=800, method="pipecg")
        assert bool(dist.converged)
        assert abs(int(dist.iterations) - int(single.iterations)) <= 2
        np.testing.assert_allclose(np.asarray(dist.x), x_true, atol=1e-6)
