"""Elastic solves: checkpoint migration across mesh shapes, corrupt-
checkpoint survival, and straggler-triggered self-healing.

The acceptance story (ISSUE 15):

* a mesh-4 checkpoint resumed on mesh 2 (and 2->4) converges with
  final x within 1e-5 of the uninterrupted run, on BOTH exchange lanes
  and under plan=None/auto/explicit - with residual continuity across
  the migration seam (the first post-migration ``||r||`` is the
  checkpointed one);
* ``CheckpointMismatch`` splits migratable (layout differs) from fatal
  (operator/rhs fingerprint differs);
* a torn-write newest checkpoint is a typed ``CheckpointCorrupt`` and
  resume falls back to the previous retained snapshot (``keep_last``);
* the ``shard_slow`` drill makes the straggler watchdog emit typed
  ``shard_degraded`` events from its REAL detection path and the
  elastic loop migrate off the slow shard's mesh; ``shard_loss``
  migrates without a watchdog;
* ``SolverService.migrate`` preserves queued requests (zero drops)
  with zero post-rewarm cache misses;
* the elastic=False / no-watchdog path dispatches the exact same
  compiled solver as before (zero extra traces, bitwise-equal x) -
  the TestZeroPerturbation discipline.
"""
import dataclasses
import json
import os

import jax
import numpy as np
import pytest

from cuda_mpi_parallel_tpu.models import mmio
from cuda_mpi_parallel_tpu.parallel import make_mesh, solve_distributed
from cuda_mpi_parallel_tpu.robust import (
    FaultPlan,
    MigrationSeamError,
    PreemptedError,
    Preemption,
    StragglerWatchdog,
    lift_checkpoint,
    migrate_checkpoint,
)
from cuda_mpi_parallel_tpu.solver.status import CGStatus
from cuda_mpi_parallel_tpu.telemetry import events
from cuda_mpi_parallel_tpu.telemetry.phasetrace import PhaseProfile
from cuda_mpi_parallel_tpu.utils import compat
from cuda_mpi_parallel_tpu.utils.checkpoint import (
    CheckpointCorrupt,
    CheckpointMismatch,
    load_checkpoint,
    solve_resumable_distributed,
)

needs_mesh = pytest.mark.skipif(
    not compat.has_shard_map() or len(jax.devices()) < 4,
    reason="needs shard_map and >= 4 (virtual) devices")

FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures",
                       "skewed_spd_240.mtx")


@pytest.fixture(scope="module")
def fixture_problem():
    a = mmio.load_matrix_market(FIXTURE)
    b = np.random.default_rng(0).standard_normal(240)
    return a, b


def _preempted_checkpoint(a, b, path, *, n_shards, segments=1,
                          **kw):
    """Run a resumable solve killed after ``segments`` segments."""
    with pytest.raises(PreemptedError):
        solve_resumable_distributed(
            a, b, path, mesh=make_mesh(n_shards), segment_iters=20,
            tol=1e-8, maxiter=500,
            preempt=Preemption(after_segments=segments), **kw)
    assert os.path.exists(path)


def _captured(buf):
    recs = [json.loads(ln) for ln in buf.getvalue().splitlines()
            if ln.strip()]
    for r in recs:
        events.validate_event(r)
    return recs


@needs_mesh
class TestMigrateCheckpoint:
    """The pure migration math, no solve loop."""

    def test_lift_matches_seam_and_roundtrips(self, fixture_problem,
                                              tmp_path):
        a, b = fixture_problem
        ck_path = str(tmp_path / "m.npz")
        _preempted_checkpoint(a, b, ck_path, n_shards=4)
        ck = load_checkpoint(ck_path)
        lifted = lift_checkpoint(ck, 240, n_shards=4, plan=None)
        # residual continuity: the lifted r carries the psum'd norm
        r_norm = float(np.linalg.norm(np.asarray(lifted.r)))
        assert r_norm == pytest.approx(
            float(np.sqrt(np.asarray(ck.rr))), rel=1e-10)
        # 4 -> 2 (even): repadding then lifting again is the identity
        mig = migrate_checkpoint(ck, 2, a=a, n_shards_old=4,
                                 plan_old=None, plan=None)
        back = lift_checkpoint(mig.checkpoint, 240, n_shards=2,
                               plan=None)
        for leaf in ("x", "r", "p"):
            np.testing.assert_array_equal(
                np.asarray(getattr(back, leaf)),
                np.asarray(getattr(lifted, leaf)))
        # scalars pass through bitwise
        for leaf in ("rho", "rr", "nrm0", "k", "indefinite"):
            np.testing.assert_array_equal(
                np.asarray(getattr(mig.checkpoint, leaf)),
                np.asarray(getattr(ck, leaf)))
        assert mig.seam_rel_err < 1e-10
        assert (mig.n_shards_from, mig.n_shards_to) == (4, 2)

    def test_broken_seam_refuses(self, fixture_problem, tmp_path):
        a, b = fixture_problem
        ck_path = str(tmp_path / "seam.npz")
        _preempted_checkpoint(a, b, ck_path, n_shards=4)
        ck = load_checkpoint(ck_path)
        bad = dataclasses.replace(
            ck, r=np.asarray(ck.r) * 3.0)   # norm no longer matches rr
        with pytest.raises(MigrationSeamError, match="seam"):
            migrate_checkpoint(bad, 2, a=a, n_shards_old=4,
                               plan_old=None, plan=None)

    def test_wrong_declared_layout_refuses(self, fixture_problem,
                                           tmp_path):
        a, b = fixture_problem
        ck_path = str(tmp_path / "lay.npz")
        _preempted_checkpoint(a, b, ck_path, n_shards=4)
        ck = load_checkpoint(ck_path)
        with pytest.raises(ValueError, match="padded rows"):
            lift_checkpoint(ck, 240, n_shards=7, plan=None)


@needs_mesh
class TestElasticResume:
    """Kill on one mesh, resume on another: converges to the
    uninterrupted answer, residual-continuous across the seam."""

    @pytest.mark.parametrize(
        "n_from,n_to,exchange,plan",
        [(4, 2, None, None),
         (4, 2, "gather", "auto"),
         (2, 4, None, "auto"),
         (2, 4, "gather", None)])
    def test_mesh_roundtrip(self, fixture_problem, tmp_path,
                            n_from, n_to, exchange, plan):
        a, b = fixture_problem
        clean = solve_distributed(a, b, mesh=make_mesh(n_from),
                                  tol=1e-8, maxiter=500,
                                  exchange=exchange, plan=plan)
        assert bool(clean.converged)
        ck = str(tmp_path / f"el_{n_from}_{n_to}.npz")
        _preempted_checkpoint(a, b, ck, n_shards=n_from,
                              exchange=exchange, plan=plan)
        with events.capture() as buf:
            res = solve_resumable_distributed(
                a, b, ck, mesh=make_mesh(n_to), segment_iters=20,
                tol=1e-8, maxiter=500, exchange=exchange, plan=plan,
                elastic=True)
        assert bool(res.converged)
        # final x within 1e-5 of the uninterrupted run (bitwise is
        # impossible - psum order changed with the mesh)
        err = float(np.max(np.abs(np.asarray(res.x)
                                  - np.asarray(clean.x))))
        assert err < 1e-5, err
        # the asserted seam contract: first post-migration ||r|| IS
        # the checkpointed one (the solve_migration event carries the
        # recomputed norm and its relative disagreement)
        migs = [e for e in _captured(buf)
                if e["event"] == "solve_migration"]
        assert len(migs) == 1
        m = migs[0]
        assert (m["n_shards_from"], m["n_shards_to"]) == (n_from, n_to)
        assert m["reason"] == "resume_mesh_change"
        assert m["seam_rel_err"] < 1e-8
        assert m["r_norm"] == pytest.approx(m["checkpoint_r_norm"],
                                            rel=1e-8)

    def test_explicit_plan_resume(self, fixture_problem, tmp_path):
        from cuda_mpi_parallel_tpu.balance import plan_partition

        a, b = fixture_problem
        clean = solve_distributed(a, b, mesh=make_mesh(4), tol=1e-8,
                                  maxiter=500)
        ck = str(tmp_path / "el_plan.npz")
        _preempted_checkpoint(a, b, ck, n_shards=4)
        plan2 = plan_partition(a, 2)
        res = solve_resumable_distributed(
            a, b, ck, mesh=make_mesh(2), segment_iters=20, tol=1e-8,
            maxiter=500, plan=plan2, elastic=True)
        assert bool(res.converged)
        err = float(np.max(np.abs(np.asarray(res.x)
                                  - np.asarray(clean.x))))
        assert err < 1e-5, err

    def test_mismatch_matrix(self, fixture_problem, tmp_path):
        """Migratable (layout differs) vs fatal (problem differs)."""
        a, b = fixture_problem
        ck = str(tmp_path / "mm.npz")
        _preempted_checkpoint(a, b, ck, n_shards=4)
        # layout-only difference without elastic: migratable=True
        with pytest.raises(CheckpointMismatch) as ei:
            solve_resumable_distributed(
                a, b, ck, mesh=make_mesh(2), segment_iters=20,
                tol=1e-8, maxiter=500)
        assert ei.value.migratable
        assert ei.value.stored_layout["n_shards"] == 4
        # exchange-lane difference is migratable too
        with pytest.raises(CheckpointMismatch) as ei:
            solve_resumable_distributed(
                a, b, ck, mesh=make_mesh(4), segment_iters=20,
                tol=1e-8, maxiter=500, exchange="gather")
        assert ei.value.migratable
        # a DIFFERENT problem is fatal - elastic cannot save it
        b2 = b + 1.0
        with pytest.raises(CheckpointMismatch) as ei:
            solve_resumable_distributed(
                a, b2, ck, mesh=make_mesh(4), segment_iters=20,
                tol=1e-8, maxiter=500, elastic=True)
        assert not ei.value.migratable

    def test_same_layout_elastic_resume_is_bitwise(
            self, fixture_problem, tmp_path):
        """elastic=True with NO layout change must not migrate: the
        resumed trajectory stays bit-exact (the PR 12 contract)."""
        a, b = fixture_problem
        full = solve_resumable_distributed(
            a, b, str(tmp_path / "f.npz"), mesh=make_mesh(4),
            segment_iters=20, tol=1e-8, maxiter=500)
        ck = str(tmp_path / "same.npz")
        _preempted_checkpoint(a, b, ck, n_shards=4)
        with events.capture() as buf:
            res = solve_resumable_distributed(
                a, b, ck, mesh=make_mesh(4), segment_iters=20,
                tol=1e-8, maxiter=500, elastic=True)
        assert not [e for e in _captured(buf)
                    if e["event"] == "solve_migration"]
        assert np.array_equal(np.asarray(res.x), np.asarray(full.x))


@needs_mesh
class TestCorruptCheckpoint:
    def test_torn_write_is_typed(self, fixture_problem, tmp_path):
        a, b = fixture_problem
        ck = str(tmp_path / "torn.npz")
        _preempted_checkpoint(a, b, ck, n_shards=4)
        blob = open(ck, "rb").read()
        with open(ck, "wb") as f:
            f.write(blob[: len(blob) // 3])   # torn mid-write
        with pytest.raises(CheckpointCorrupt, match="unreadable"):
            load_checkpoint(ck)

    def test_fallback_to_previous_snapshot(self, fixture_problem,
                                           tmp_path):
        """keep_last=2: a torn newest file falls back to .prev1 and
        the resume still bit-matches the uninterrupted run (the
        fallback snapshot is an exact earlier trajectory point)."""
        a, b = fixture_problem
        full = solve_resumable_distributed(
            a, b, str(tmp_path / "full.npz"), mesh=make_mesh(4),
            segment_iters=20, tol=1e-8, maxiter=500)
        ck = str(tmp_path / "fb.npz")
        _preempted_checkpoint(a, b, ck, n_shards=4, segments=2,
                              keep_last=2)
        assert os.path.exists(ck + ".prev1")
        blob = open(ck, "rb").read()
        with open(ck, "wb") as f:
            f.write(blob[: len(blob) // 3])
        with events.capture() as buf:
            res = solve_resumable_distributed(
                a, b, ck, mesh=make_mesh(4), segment_iters=20,
                tol=1e-8, maxiter=500, keep_last=2)
        falls = [e for e in _captured(buf)
                 if e["event"] == "solve_recovery"
                 and e["action"] == "checkpoint_fallback"]
        assert len(falls) == 1 and falls[0]["skipped"] == 1
        assert bool(res.converged)
        assert np.array_equal(np.asarray(res.x), np.asarray(full.x))

    def test_fallback_never_rotates_corrupt_over_good(
            self, fixture_problem, tmp_path):
        """The corrupt newest snapshot is REMOVED during the fallback,
        so the first post-resume rotation can never shift it over the
        good snapshot (a preemption in that window would otherwise
        lose every recoverable state)."""
        a, b = fixture_problem
        ck = str(tmp_path / "rot.npz")
        _preempted_checkpoint(a, b, ck, n_shards=4, segments=2,
                              keep_last=2)
        blob = open(ck, "rb").read()
        with open(ck, "wb") as f:
            f.write(blob[: len(blob) // 3])
        with pytest.raises(PreemptedError):
            solve_resumable_distributed(
                a, b, ck, mesh=make_mesh(4), segment_iters=20,
                tol=1e-8, maxiter=500, keep_last=2,
                preempt=Preemption(after_segments=1))
        # after the fallback resume's first save, BOTH retained
        # snapshots are readable - the torn file is gone for good
        load_checkpoint(ck)
        load_checkpoint(ck + ".prev1")

    def test_every_snapshot_corrupt_raises(self, fixture_problem,
                                           tmp_path):
        a, b = fixture_problem
        ck = str(tmp_path / "allbad.npz")
        _preempted_checkpoint(a, b, ck, n_shards=4, segments=2,
                              keep_last=2)
        for p in (ck, ck + ".prev1"):
            with open(p, "wb") as f:
                f.write(b"not a zip at all")
        with pytest.raises(CheckpointCorrupt):
            solve_resumable_distributed(
                a, b, ck, mesh=make_mesh(4), segment_iters=20,
                tol=1e-8, maxiter=500, keep_last=2)

    def test_converged_run_removes_all_snapshots(self, fixture_problem,
                                                 tmp_path):
        a, b = fixture_problem
        ck = str(tmp_path / "done.npz")
        res = solve_resumable_distributed(
            a, b, ck, mesh=make_mesh(4), segment_iters=20, tol=1e-8,
            maxiter=500, keep_last=3)
        assert bool(res.converged)
        assert not os.path.exists(ck)
        assert not os.path.exists(ck + ".prev1")


def _profile(spmv, links=(), n_shards=None):
    spmv = np.asarray(spmv, dtype=float)
    n = int(n_shards or spmv.shape[0])
    return PhaseProfile(
        kind="csr", exchange="allgather", n_shards=n,
        n_local=60, itemsize=8, repeats=4, spmv_s=spmv,
        spmv_mesh_s=float(spmv.sum()), halo_s=1e-5,
        reduction_s=1e-6, step_s=float(spmv.sum()) + 2e-5,
        links=tuple(links))


class TestWatchdog:
    def test_peer_baseline_detects_first_profile(self):
        wd = StragglerWatchdog(persist=False)
        with events.capture() as buf:
            found = wd.observe(_profile([1e-4, 8e-4, 1e-4, 1e-4]))
        assert [d.shard for d in found] == [1]
        assert found[0].phase == "spmv"
        assert found[0].ratio == pytest.approx(8.0, rel=1e-6)
        degs = [e for e in _captured(buf)
                if e["event"] == "shard_degraded"]
        assert len(degs) == 1 and degs[0]["shard"] == 1

    def test_two_shard_straggler_detects(self):
        """The peer baseline excludes the shard under test: on a
        2-shard mesh the straggler's only peer IS the healthy shard,
        so the very first profile detects (a median over both would
        hide it forever and poison the EWMA)."""
        wd = StragglerWatchdog(persist=False)
        found = wd.observe(_profile([1e-4, 8e-4]))
        assert [d.shard for d in found] == [1]
        assert found[0].ratio == pytest.approx(8.0, rel=1e-6)
        # the degraded reading never became its own baseline
        assert "2:1" not in wd._spmv

    def test_healthy_observations_fold_into_ewma(self):
        wd = StragglerWatchdog(persist=False, alpha=0.5)
        assert wd.observe(_profile([1e-4] * 4)) == []
        assert wd.observe(_profile([2e-4] * 4)) == []
        # EWMA moved halfway; a 2.1x-of-baseline shard now fires
        assert wd._spmv["4:0"] == pytest.approx(1.5e-4)
        found = wd.observe(_profile([3.2e-4, 1.5e-4, 1.5e-4, 1.5e-4]))
        assert [d.shard for d in found] == [0]
        # the degraded shard's own baseline did NOT absorb the spike
        assert wd._spmv["4:0"] == pytest.approx(1.5e-4)

    def test_link_degradation_needs_history(self):
        wd = StragglerWatchdog(persist=False)
        link = {"shift": 1, "bytes_per_s": 1e9}
        assert wd.observe(_profile([1e-4] * 4, links=[link])) == []
        slow = {"shift": 1, "bytes_per_s": 1e8}   # 10x slower
        found = wd.observe(_profile([1e-4] * 4, links=[slow]))
        assert [(d.phase, d.shard) for d in found] == [("link", 1)]

    def test_threshold_validation(self):
        with pytest.raises(ValueError, match="ratio"):
            StragglerWatchdog(threshold=0.5)

    def test_shard_slow_doctor(self):
        plan = FaultPlan.parse("shard_slow:1:2")
        prof = _profile([1e-4] * 4)
        doc = plan.doctor_profile(prof, 1)
        assert doc.spmv_s[2] == pytest.approx(8e-4)
        assert doc.spmv_s[0] == pytest.approx(1e-4)
        # segment gate: nothing before segment 1
        assert plan.doctor_profile(prof, 0) is prof


@needs_mesh
class TestElasticDrills:
    def test_shard_slow_watchdog_migration(self, fixture_problem,
                                           tmp_path):
        """The acceptance drill: watchdog emits shard_degraded from
        the doctored-but-real measured profile, the elastic loop
        migrates off the slow shard's mesh, the solve completes to
        the fault-free answer."""
        a, b = fixture_problem
        clean = solve_distributed(a, b, mesh=make_mesh(4), tol=1e-8,
                                  maxiter=500)
        wd = StragglerWatchdog(profile_repeats=2, persist=False)
        with events.capture() as buf:
            res = solve_resumable_distributed(
                a, b, str(tmp_path / "slow.npz"), mesh=make_mesh(4),
                segment_iters=15, tol=1e-8, maxiter=500, elastic=True,
                watchdog=wd, inject=FaultPlan.parse("shard_slow:1:1"))
        assert bool(res.converged)
        recs = _captured(buf)
        degs = [e for e in recs if e["event"] == "shard_degraded"]
        migs = [e for e in recs if e["event"] == "solve_migration"]
        assert degs and degs[0]["shard"] == 1
        assert migs and migs[0]["reason"] == "shard_degraded"
        assert migs[0]["n_shards_to"] == 3   # without the slow shard
        err = float(np.max(np.abs(np.asarray(res.x)
                                  - np.asarray(clean.x))))
        assert err < 1e-5, err

    def test_shard_loss_migration(self, fixture_problem, tmp_path):
        a, b = fixture_problem
        clean = solve_distributed(a, b, mesh=make_mesh(4), tol=1e-8,
                                  maxiter=500)
        with events.capture() as buf:
            res = solve_resumable_distributed(
                a, b, str(tmp_path / "loss.npz"), mesh=make_mesh(4),
                segment_iters=15, tol=1e-8, maxiter=500, elastic=True,
                inject=FaultPlan.parse("shard_loss:1:2"))
        assert bool(res.converged)
        migs = [e for e in _captured(buf)
                if e["event"] == "solve_migration"]
        assert migs and migs[0]["reason"] == "shard_loss"
        assert migs[0]["lost_shard"] == 2
        err = float(np.max(np.abs(np.asarray(res.x)
                                  - np.asarray(clean.x))))
        assert err < 1e-5, err

    def test_host_site_refusals(self, fixture_problem, tmp_path):
        a, b = fixture_problem
        # shard_slow without a watchdog
        with pytest.raises(ValueError, match="watchdog"):
            solve_resumable_distributed(
                a, b, str(tmp_path / "r1.npz"), mesh=make_mesh(4),
                segment_iters=15, tol=1e-8, maxiter=500, elastic=True,
                inject=FaultPlan.parse("shard_slow:1:1"))
        # shard_loss without elastic: the TYPED refusal orchestration
        # layers branch on ("re-run elastic")
        from cuda_mpi_parallel_tpu.robust import ShardLostError

        with pytest.raises(ShardLostError, match="elastic"):
            solve_resumable_distributed(
                a, b, str(tmp_path / "r2.npz"), mesh=make_mesh(4),
                segment_iters=15, tol=1e-8, maxiter=500,
                inject=FaultPlan.parse("shard_loss:1:1"))
        # host sites never enter a compiled solve
        with pytest.raises(ValueError, match="host-level"):
            solve_distributed(a, b, mesh=make_mesh(4), tol=1e-8,
                              maxiter=500,
                              inject=FaultPlan.parse("shard_slow:1:1"))

    def test_orbax_lane_refuses_elastic(self, fixture_problem,
                                        tmp_path):
        a, b = fixture_problem
        with pytest.raises(ValueError, match="npz"):
            solve_resumable_distributed(
                a, b, str(tmp_path / "o"), mesh=make_mesh(4),
                backend="orbax", elastic=True)


@needs_mesh
class TestServeMigrate:
    def _misses(self):
        from cuda_mpi_parallel_tpu.telemetry.registry import REGISTRY

        snap = REGISTRY.snapshot().get("dist_solver_cache_misses_total")
        if not snap:
            return 0.0
        return sum(s["value"] for s in snap["series"]
                   if s["labels"].get("phase") == "solve")

    def test_live_migration_preserves_queue(self, fixture_problem):
        """Queued requests survive a live 4 -> 2 migration with zero
        drops and zero post-rewarm cache misses."""
        from cuda_mpi_parallel_tpu.serve import (
            ServiceConfig,
            SolverService,
        )

        a, _ = fixture_problem
        rng = np.random.default_rng(11)
        clk = [0.0]
        svc = SolverService(ServiceConfig(max_batch=4,
                                          clock=lambda: clk[0]))
        try:
            with events.capture() as buf:
                h = svc.register(a, mesh=make_mesh(4))
                xs = [rng.standard_normal(240) for _ in range(5)]
                futs = [svc.submit(
                    h, np.asarray(a @ jax.numpy.asarray(x)), tol=1e-9)
                    for x in xs]
                svc.migrate(h, n_devices=2)
                before = self._misses()
                clk[0] += 1.0
                svc.pump()
                assert self._misses() == before   # zero post-rewarm
                results = [f.result(timeout=10) for f in futs]
            assert [r.status for r in results] == ["CONVERGED"] * 5
            for r, x in zip(results, xs):
                assert float(np.max(np.abs(r.x - x))) < 1e-5
            migs = [e for e in _captured(buf)
                    if e["event"] == "handle_migrated"]
            assert len(migs) == 1
            assert (migs[0]["n_shards_from"],
                    migs[0]["n_shards_to"]) == (4, 2)
            assert int(h.mesh.devices.size) == 2
            assert svc.stats()["migrations"] == 1
        finally:
            svc.close()

    def test_migrate_drops_recycle_space(self, fixture_problem):
        """A space harvested under the old layout must not survive
        the seam: migrate drops it defensively (re-harvest on the new
        mesh is the conservative contract)."""
        from cuda_mpi_parallel_tpu.serve import (
            ServiceConfig,
            SolverService,
        )

        a, _ = fixture_problem
        clk = [0.0]
        svc = SolverService(ServiceConfig(max_batch=2,
                                          clock=lambda: clk[0]))
        try:
            h = svc.register(a, mesh=make_mesh(4))
            h.recycle_space = object()   # stand-in harvested space
            h.recycle_harvests = 1
            svc.migrate(h, n_devices=2)
            assert h.recycle_space is None       # dropped defensively
            assert svc.stats()["migrations"] == 1
        finally:
            svc.close()

    def test_migrate_refusals(self, fixture_problem):
        from cuda_mpi_parallel_tpu.serve import (
            ServiceConfig,
            SolverService,
        )

        a, _ = fixture_problem
        clk = [0.0]
        svc = SolverService(ServiceConfig(clock=lambda: clk[0]))
        try:
            h1 = svc.register(a)                      # single-device
            with pytest.raises(ValueError, match="single-device"):
                svc.migrate(h1, n_devices=2)
            h2 = svc.register(a, mesh=make_mesh(2))
            with pytest.raises(ValueError, match="mesh="):
                svc.migrate(h2)
            other = SolverService(ServiceConfig(clock=lambda: clk[0]))
            try:
                with pytest.raises(ValueError, match="unknown handle"):
                    other.migrate(h2, n_devices=4)
            finally:
                other.close()
        finally:
            svc.close()


@needs_mesh
class TestZeroPerturbation:
    """The discipline every subsystem upholds: feature off == feature
    never mentioned."""

    def test_elastic_flag_off_same_executable(self, fixture_problem,
                                              tmp_path):
        """elastic=True with no layout change dispatches the SAME
        compiled solver entries as the pre-elastic loop (zero extra
        traces) and bit-matches its x."""
        from cuda_mpi_parallel_tpu.parallel import dist_cg

        a, b = fixture_problem
        base = solve_resumable_distributed(
            a, b, str(tmp_path / "z0.npz"), mesh=make_mesh(4),
            segment_iters=20, tol=1e-8, maxiter=500)
        before = dist_cg._TRACE_COUNT[0]
        res = solve_resumable_distributed(
            a, b, str(tmp_path / "z1.npz"), mesh=make_mesh(4),
            segment_iters=20, tol=1e-8, maxiter=500, elastic=True,
            keep_last=2)
        assert dist_cg._TRACE_COUNT[0] == before   # cache hits only
        assert np.array_equal(np.asarray(res.x), np.asarray(base.x))

    def test_host_sites_rejected_by_trace_lanes(self, fixture_problem):
        from cuda_mpi_parallel_tpu.parallel.dist_cg import (
            ManyRHSDispatcher,
        )

        a, b = fixture_problem
        plan = FaultPlan.parse("shard_loss:1:0")
        with pytest.raises(ValueError, match="host-level"):
            solve_distributed(a, b, mesh=make_mesh(4), inject=plan)
        with pytest.raises(ValueError, match="host-level"):
            ManyRHSDispatcher(a, mesh=make_mesh(4), inject=plan)
        with pytest.raises(ValueError, match="host-level"):
            plan.apply_matvec(None, np.ones(4), 0)
