"""Known-good GL105 patterns: predicates on device, host coercions
only at host level (result wrappers / problem setup), constant
coercions that cannot sync."""
import numpy as np

import jax.numpy as jnp
from jax import lax


def solve(matvec, b, tol2, maxiter):
    def cond(state):
        x, r, k = state
        return (jnp.vdot(r, r) > tol2) & (k < maxiter)

    def body(state):
        x, r, k = state
        ap = matvec(r)
        alpha = jnp.vdot(r, r) / jnp.vdot(r, ap)
        return x + alpha * r, r - alpha * ap, k + 1

    return lax.while_loop(cond, body, (b, b, jnp.int32(0)))


def host_wrapper(matvec, b, tol, maxiter):
    """Host level: float()/np.asarray of a FINISHED result is fine."""
    x, r, k = solve(matvec, b, float(tol) ** 2, int(maxiter))
    return np.asarray(x), float(jnp.vdot(r, r)), int(k)


def constant_fold_in_body(r0):
    def step(i, acc):
        return acc * float(0.5) + int(2)  # constants: no traced value

    return lax.fori_loop(0, 10, step, r0)


def _fmt(v):
    return float(v)


def format_rows(rows):
    """Host-level builtin map() must not be confused with lax.map:
    _fmt is plain host code, its float() is fine."""
    return list(map(_fmt, rows))


def init_shares_a_function_name(r0, helper):
    # only the BODY position (args[2]) is traced; an init value that
    # happens to be named like a module function is not a body
    return lax.fori_loop(0, 3, lambda i, v: v * 0.5, helper)


def helper(x):
    return float(np.asarray(x).sum())
