"""Known-bad GL104 dma-pairing patterns.

A started-never-waited named descriptor (buffer reuse while the copy
is in flight + a semaphore that never rebalances), a module whose
anonymous start/wait counts don't balance, and a remote copy driven
through one shared semaphore.
"""
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def leaky_kernel(x_hbm, y_ref, sem):
    dma = pltpu.make_async_copy(x_hbm, y_ref, sem)  # gl-expect: dma-pairing
    dma.start()
    return y_ref[0:8]  # read while the copy may still be in flight


def fire_and_forget(src, dst, send, recv, tgt):
    pltpu.make_async_remote_copy(  # gl-expect: dma-pairing
        src, dst, send, recv, device_id=tgt,
        device_id_type=pltpu.DeviceIdType.LOGICAL).start()


def shared_sem_remote(src, dst, sem, tgt):
    dma = pltpu.make_async_remote_copy(src, dst, sem, device_id=tgt)  # gl-expect: dma-pairing
    dma.start()
    dma.wait()
