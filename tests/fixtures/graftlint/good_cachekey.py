"""Known-good twin of bad_cachekey: every static the build closes
over reaches the cache key - directly, through a derived local
(``gather`` inherits soundness from the keyed ``resolved``), through a
keyed ``self._key_base`` prefix, or through a conditional suffix
append (the deflate/resumable lane idiom)."""

_SOLVER_CACHE = {}


def _cached_solver(key, build):
    fn = _SOLVER_CACHE.get(key)
    if fn is None:
        fn = _SOLVER_CACHE[key] = build()
    return fn


def cache_key_parts(kind, **parts):
    return (kind,) + tuple(sorted(
        (n, v) for n, v in parts.items() if v is not None))


def solve_toy(local_grid, axis, precond, flight):
    key = cache_key_parts("toy", local_grid=local_grid, axis=axis,
                          precond=precond, flight=flight)

    def build():
        def run(x):
            stride = flight.stride if flight is not None else 0
            return x * local_grid + precond + stride

        return run

    return _cached_solver(key, build)


def solve_derived(exchange, n_local, deflate):
    # forward derivation: ``gather`` is computed FROM the keyed
    # ``resolved``, so the build consuming it is covered
    resolved = "gather" if exchange in (None, "auto") else exchange
    key = cache_key_parts("toy", resolved=resolved, n_local=n_local)
    if deflate is not None:
        key = key + (("deflate", int(deflate.k)),)
        space_k = int(deflate.k)

    def build():
        from math import sqrt

        def run(x):
            y = x * sqrt(n_local)
            if resolved == "gather":
                y = y + 1
            if deflate is not None:
                y = y + space_k
            return y

        return run

    return _cached_solver(key, build)


class Dispatcher:
    def __init__(self, method, check_every):
        self._key_base = cache_key_parts(
            "many", method=method, check_every=check_every)
        self.method = method
        self.check_every = check_every

    def solve(self, b):
        n_rhs = int(b.shape[1])
        key = self._key_base + (("n_rhs", n_rhs),)
        method, check_every = self.method, self.check_every

        def build():
            def run(x):
                return x + check_every + (1 if method == "block" else 0)

            return run

        return _cached_solver(key, build)(b)
