"""Known-bad: suppressions that outlived their bugs (GL109
stale-suppression).

The first disable once silenced a real mosaic-tiling finding; the
slicing was fixed but the comment stayed - a standing exemption on
that line.  The second names a rule that never existed (a typo'd
token protects nothing).  Both are flagged at the comment, so the
cleanup is mechanical."""
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

ROW_SLOT = 8


def healthy_copy(buf, send, recv, tgt):
    # graftlint: disable=mosaic-tiling  # gl-expect: stale-suppression
    dma = pltpu.make_async_remote_copy(
        buf.at[pl.ds(0, ROW_SLOT)],
        buf.at[pl.ds(0, ROW_SLOT)],
        send, recv, device_id=tgt)
    dma.start()
    dma.wait()


def typo(x):
    return x + 1  # graftlint: disable=mosiac-tiling  # gl-expect: stale-suppression
