"""Known-good GL101 patterns: the halo-path discipline.

Full 8-row edge blocks at 8-aligned (or parametrized) offsets - the
redesign ``resident_dist.py``'s halo exchange adopted after Mosaic
rejected single-row slices, plus the 8-row-slot form of the scalar
exchange the advisor recommends.
"""
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _remote_row_copy(src, dst, send, recv, target):
    return pltpu.make_async_remote_copy(
        src, dst, send, recv, device_id=target,
        device_id_type=pltpu.DeviceIdType.LOGICAL)


def exchange_halo(v_ref, buf, send, recv, left, right, nxl):
    down = _remote_row_copy(v_ref.at[pl.ds(nxl - 8, 8)],
                            buf.at[pl.ds(0, 8)],
                            send.at[0], recv.at[0], right)
    up = _remote_row_copy(v_ref.at[pl.ds(0, 8)],
                          buf.at[pl.ds(8, 8)],
                          send.at[1], recv.at[1], left)
    down.start()
    up.start()
    down.wait()
    up.wait()


def aligned_slot_push(buf, send_sems, recv_sems, n_shards, axis_name):
    """The 8-row-aligned scalar-exchange slot (buffer (8 * n_shards,
    128), slot my_id * 8): what the round-5 allreduce should become."""
    my_id = lax.axis_index(axis_name)
    dmas = []
    for step in range(1, n_shards):
        tgt = lax.rem(my_id + jnp.int32(step), jnp.int32(n_shards))
        dma = _remote_row_copy(
            buf.at[pl.ds(my_id * 8, 8)],
            buf.at[pl.ds(my_id * 8, 8)],
            send_sems.at[step - 1], recv_sems.at[step - 1], tgt)
        dma.start()
        dmas.append(dma)
    for dma in dmas:
        dma.wait()
