"""Known-bad GL103 collective-safety patterns.

A psum over an axis no mesh in this file declares (a typo'd name
fails at trace time on hardware - or, on a 2-D mesh, silently reduces
over the WRONG axis), and a ppermute permutation sending two sources
into one destination (last-writer-wins on ICI, nondeterministic in
the simulator - the same contested-slot class as the round-5
rho-buffer race).
"""
import numpy as np
from jax import lax
from jax.sharding import Mesh

ROWS_AXIS = "rows"


def make_row_mesh(devices):
    return Mesh(np.asarray(devices), ("rows",))


def mistyped_reduce(x):
    return lax.psum(x, "cols")  # gl-expect: collective-safety


def mistyped_axis_index():
    # axis_index carries its axis FIRST positionally - a typo here
    # silently computes the wrong shard id
    return lax.axis_index("rowz")  # gl-expect: collective-safety


def contested_ring(x):
    return lax.ppermute(
        x, "rows",
        perm=[(0, 1), (1, 1), (2, 0)])  # gl-expect: collective-safety


def double_sender(x):
    return lax.ppermute(
        x, "rows",
        perm=[(0, 1), (0, 2)])  # gl-expect: collective-safety
