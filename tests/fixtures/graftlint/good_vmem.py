"""Known-good GL102 patterns: clamped or provably-fitting budgets."""
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from cuda_mpi_parallel_tpu.ops.pallas.resident import vmem_bytes

_VMEM_BUDGET = 64 * 1024 * 1024


def launch_clamped(kernel, local_shape, degree):
    """The satellite fix: shape-dependent limit clamped to the part."""
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(local_shape, jnp.float32),
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=min(
                (13 if degree > 0 else 10)
                * math.prod(local_shape) * 4 + (8 << 20),
                vmem_bytes())),
    )()


def launch_constant_budget(kernel):
    """fused_cg.py's discipline: a constant below the 128 MiB part,
    with the declared scratch fitting inside it."""
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((1024, 1024), jnp.float32),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=_VMEM_BUDGET),
    )()


def launch_default_budget(kernel):
    """No compiler_params at all: the compiler default is conservative."""
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
    )()
