"""Known-good twin of bad_stale: a load-bearing suppression.

The 1-row RDMA below genuinely trips mosaic-tiling (it is the round-5
pattern), and the disable comments still suppress it - so GL109 stays
silent: the tokens vindicated themselves this run."""
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def legacy_allreduce_row(buf, send, recv, my_id, tgt):
    # KNOWN hazard, suppressed with a revisit condition (see
    # ops/pallas/resident_dist.py for the real instance + rationale)
    dma = pltpu.make_async_remote_copy(
        buf.at[pl.ds(my_id, 1)],  # graftlint: disable=mosaic-tiling
        buf.at[pl.ds(my_id, 1)],  # graftlint: disable=mosaic-tiling
        send, recv, device_id=tgt)
    dma.start()
    dma.wait()
