"""Known-good GL104 patterns: every pairing discipline the codebase
uses - named descriptors, list indirection, and the stencil.py-style
split copy/wait helpers whose anonymous descriptors balance
module-wide."""
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def paired_named(x_hbm, y_ref, sem):
    dma = pltpu.make_async_copy(x_hbm, y_ref, sem)
    dma.start()
    dma.wait()
    return y_ref[0:8]


def paired_through_list(srcs, dsts, sems, n):
    dmas = []
    for i in range(n):
        dma = pltpu.make_async_copy(srcs.at[i], dsts.at[i], sems.at[i])
        dma.start()
        dmas.append(dma)
    for dma in dmas:
        dma.wait()


def slab_copy(x_hbm, slab_buf, sem, bm):
    """stencil.py discipline: the start half of a split pair."""
    pltpu.make_async_copy(
        x_hbm.at[pl.ds(0, bm)],
        slab_buf.at[pl.ds(8, bm)], sem).start()


def slab_wait(x_hbm, slab_buf, sem, bm):
    """...and the identically-shaped wait half, in a sibling helper."""
    pltpu.make_async_copy(
        x_hbm.at[pl.ds(0, bm)],
        slab_buf.at[pl.ds(8, bm)], sem).wait()


def remote_with_both_sems(src, dst, send, recv, tgt):
    dma = pltpu.make_async_remote_copy(
        src, dst, send, recv, device_id=tgt,
        device_id_type=pltpu.DeviceIdType.LOGICAL)
    dma.start()
    dma.wait()
