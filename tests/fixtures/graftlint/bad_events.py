"""Known-bad: event emissions that runtime validation only catches
once a trace sink is configured (GL108 event-schema).

With tracing off, ``emit`` returns before validating - so a
misspelled type or a dropped required field ships silently and
crashes the first ``--trace-events`` run."""
from cuda_mpi_parallel_tpu.telemetry import events


def report(key, hit, n):
    events.emit("dist_cache_hitt", key=key)  # gl-expect: event-schema
    events.emit("dist_cache_hit")  # gl-expect: event-schema
    events.emit(  # gl-expect: event-schema
        "batch_dispatch", handle="h", bucket=n)
    events.emit(("solve_start"  # gl-expect: event-schema
                 if hit else "solve_stat"), label="x")
