"""Known-bad GL101 mosaic-tiling patterns.

``allreduce_push`` reconstructs the round-5 ``resident_dist.py``
allreduce finding verbatim: a 1-row RDMA of a (n_shards, 128) VMEM
buffer at dynamic row offset ``my_id`` - rows 1..7 are unaligned under
the (8, 128) f32 sublane tiling, so Mosaic rejects the slice on real
chips while interpret mode happily runs it.
"""
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _remote_row_copy(src, dst, send, recv, target):
    return pltpu.make_async_remote_copy(
        src, dst, send, recv, device_id=target,
        device_id_type=pltpu.DeviceIdType.LOGICAL)


def allreduce_push(buf, send_sems, recv_sems, n_shards, axis_name):
    my_id = lax.axis_index(axis_name)
    dmas = []
    for step in range(1, n_shards):
        tgt = lax.rem(my_id + jnp.int32(step), jnp.int32(n_shards))
        dma = _remote_row_copy(
            buf.at[pl.ds(my_id, 1)],  # gl-expect: mosaic-tiling
            buf.at[pl.ds(my_id, 1)],  # gl-expect: mosaic-tiling
            send_sems.at[step - 1], recv_sems.at[step - 1], tgt)
        dma.start()
        dmas.append(dma)
    for dma in dmas:
        dma.wait()


def misaligned_block_start(x_ref, out_ref, sem):
    pltpu.make_async_copy(
        x_ref.at[pl.ds(4, 8)],  # gl-expect: mosaic-tiling
        out_ref.at[pl.ds(0, 8)], sem).start()
    pltpu.make_async_copy(
        x_ref.at[pl.ds(4, 8)],  # gl-expect: mosaic-tiling
        out_ref.at[pl.ds(0, 8)], sem).wait()


def odd_everything(x_ref, out_ref, sem):
    pltpu.make_async_copy(
        x_ref.at[pl.ds(3, 5)],  # gl-expect: mosaic-tiling
        out_ref.at[pl.ds(0, 8)], sem).start()
    pltpu.make_async_copy(
        x_ref.at[pl.ds(3, 5)],  # gl-expect: mosaic-tiling
        out_ref.at[pl.ds(0, 8)], sem).wait()
