"""Known-good GL103 patterns: declared axes, well-formed rings,
dynamic axis names threaded from the mesh (trusted - the codebase's
own idiom)."""
import numpy as np
from jax import lax
from jax.sharding import Mesh

ROWS_AXIS = "rows"
COLS_AXIS = "cols"


def make_mesh_2d(devices, shape):
    return Mesh(np.asarray(devices).reshape(shape), ("rows", "cols"))


def row_reduce(x):
    return lax.psum(x, "rows")


def both_axis_reduce(x):
    return lax.psum(x, ("rows", "cols"))


def neighbor_shift(x, n_shards):
    fwd = [(i, i + 1) for i in range(n_shards - 1)]
    return lax.ppermute(x, "rows", perm=fwd)


def unique_ring(x):
    return lax.ppermute(x, "cols", perm=[(0, 1), (1, 2), (2, 0)])


def my_shard_id():
    return lax.axis_index("rows")


def dynamic_axis_reduce(x, mesh):
    # axis names resolved at run time are trusted (unverifiable here)
    return lax.psum(x, mesh.axis_names[0])
