"""Known-bad GL102 vmem-budget patterns.

``launch_unclamped`` is the ``resident_dist.py:434`` finding: a
shape-dependent ``vmem_limit_bytes`` with no device-ceiling clamp -
at gate-boundary slab sizes the computed limit exceeds physical VMEM.
The other two are the statically-decidable literal forms.
"""
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def launch_unclamped(kernel, local_shape, degree):
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(local_shape, jnp.float32),
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=(13 if degree > 0 else 10)  # gl-expect: vmem-budget
            * math.prod(local_shape) * 4 + (8 << 20)),
    )()


def launch_over_ceiling(kernel):
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=256 * 1024 * 1024),  # gl-expect: vmem-budget
    )()


def launch_scratch_overrun(kernel):
    # 4096 * 4096 * 4 = 64 MiB of declared scratch vs a 32 MiB limit
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((4096, 4096), jnp.float32),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=32 * 1024 * 1024),  # gl-expect: vmem-budget
    )()
