"""Known-bad: compiled-solver cache keys missing a static the build
closure consumes (GL106 cache-key).

The seeded hole mirrors the real bug class: ``flight`` configures the
traced program (its stride is baked into the compiled loop) but the
key tuple never mentions it, so a flight-on caller after a flight-off
caller silently gets the flight-off compiled solver from the cache.
"""

_SOLVER_CACHE = {}


def _cached_solver(key, build):
    fn = _SOLVER_CACHE.get(key)
    if fn is None:
        fn = _SOLVER_CACHE[key] = build()
    return fn


def solve_toy(local_grid, axis, precond, flight):
    key = ("toy", local_grid, axis, precond)

    def build():
        def run(x):
            stride = flight.stride if flight is not None else 0
            return x * local_grid + precond + stride

        return run

    return _cached_solver(key, build)  # gl-expect: cache-key


def solve_two_holes(n_local, method, check_every, fault):
    # two statics missing from one key: still one marked line (the
    # dispatch site), but the rule names each omission
    key = ("toy2", n_local)

    def build():
        def run(x):
            y = x + check_every
            if fault is not None:
                y = y + fault.iteration
            return y

        return run

    return _cached_solver(key, build)  # gl-expect: cache-key
