"""Known-bad GL105 host-sync patterns.

The reference's anti-pattern (host-side ``while`` on a device scalar,
one transfer per iteration) expressed the ways it actually sneaks
into jax code: builtin coercions, ``.item()`` and numpy
materialization inside ``lax`` loop/branch bodies.
"""
import numpy as np

import jax.numpy as jnp
from jax import lax


def solve(matvec, b, tol, maxiter):
    def cond(state):
        x, r, k = state
        return bool(jnp.vdot(r, r) > tol) and k < maxiter  # gl-expect: host-sync

    def body(state):
        x, r, k = state
        alpha = float(jnp.vdot(r, r))  # gl-expect: host-sync
        trace = np.asarray(r)  # gl-expect: host-sync
        del trace
        return x + alpha * r, r - alpha * matvec(r), k + 1

    return lax.while_loop(cond, body, (b, b, 0))


def count_steps(r0, thresh):
    def step(i, acc):
        err = acc.sum().item()  # gl-expect: host-sync
        return acc * 0.5 + err

    return lax.fori_loop(0, 10, step, r0)


def branchy(pred, x):
    return lax.cond(
        pred,
        lambda v: v * int(v.sum()),  # gl-expect: host-sync
        lambda v: v,
        x)
