"""Known-good twin of bad_events: schema'd types with their required
floor spelled as literal keywords, the conditional-type idiom over
two valid names, splatted payloads (membership-checked only), and
dynamic forwarding (runtime validation's territory)."""
from cuda_mpi_parallel_tpu.telemetry import events


def report(key, hit, payload, event_type):
    events.emit("dist_cache_hit" if hit else "dist_cache_miss",
                key=key)
    events.emit("batch_dispatch", handle="h", bucket=4, n_requests=3,
                reason="full")
    events.emit("solve_start", label="poisson2d", extra="fine")
    # **payload makes the field floor unknowable statically: the
    # membership check still guards the type name
    events.emit("shard_profile", **payload)
    # dynamic type: forwarded wrappers re-validate at runtime
    events.emit(event_type, **payload)
