"""Known-bad: blocking work under dispatch/cache locks and a lock
order inversion (GL107 lock-discipline).

Each seeded violation is a de-anonymized version of a race the serve
layer was reviewed OUT of: tracing inside the solver-cache lock (the
LRU-eviction convoy), dispatching a solve while holding the batch
lock, event-file I/O in a critical section, and the two-path
dispatch/state lock inversion."""
import threading

import jax

_CACHE_LOCK = threading.Lock()
_SOLVER_CACHE = {}


def cached_solver_traced_under_lock(key, build):
    with _CACHE_LOCK:
        fn = _SOLVER_CACHE.get(key)
        if fn is None:
            fn = jax.jit(build())  # gl-expect: lock-discipline
            _SOLVER_CACHE[key] = fn
        return fn


class Service:
    def __init__(self):
        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)
        self._dispatch_lock = threading.Lock()

    def step(self, batch):
        with self._dispatch_lock:
            res = solve_distributed_many(  # gl-expect: lock-discipline
                batch.a, batch.b)
            events.emit("batch_dispatch",  # gl-expect: lock-discipline
                        handle=batch.handle, bucket=len(batch.b),
                        n_requests=len(batch.b), reason="full")
        return res

    def migrate(self, handle):
        with self._dispatch_lock:
            with self._lock:
                self._handles[handle.key] = handle

    def snapshot(self):
        with self._lock:
            with self._dispatch_lock:  # gl-expect: lock-discipline
                return dict(self._handles)
