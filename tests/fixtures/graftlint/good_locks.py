"""Known-good twin of bad_locks: the shipped discipline.

Trace OUTSIDE the cache lock with a double-checked insert, dispatch
and emit after the critical section, only attribute swaps under the
nested dispatch->state locks, and every nesting in ONE global order
(Condition(self._lock) nests with its own lock - an alias, not an
ordering edge)."""
import threading

import jax

_CACHE_LOCK = threading.Lock()
_SOLVER_CACHE = {}


def cached_solver(key, build):
    with _CACHE_LOCK:
        fn = _SOLVER_CACHE.get(key)
    if fn is not None:
        return fn
    fn = jax.jit(build())  # traced with the lock RELEASED
    with _CACHE_LOCK:
        cur = _SOLVER_CACHE.get(key)
        if cur is None:
            _SOLVER_CACHE[key] = cur = fn
    events.emit("dist_cache_miss", key=str(key))
    return cur


class Service:
    def __init__(self):
        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)
        self._dispatch_lock = threading.Lock()

    def step(self, batch):
        with self._dispatch_lock:
            plan = self._pop_ready(batch)
        res = solve_distributed_many(plan.a, plan.b)
        events.emit("batch_dispatch", handle=plan.handle,
                    bucket=len(plan.b), n_requests=len(plan.b),
                    reason="full")
        return res

    def migrate(self, handle):
        with self._dispatch_lock:
            with self._lock:
                self._handles[handle.key] = handle

    def publish(self, handle):
        # same global order as migrate: dispatch -> state
        with self._dispatch_lock:
            with self._lock:
                self._latest = handle.key

    def wait_idle(self):
        with self._cond:
            with self._lock:  # reentry via the Condition alias
                return len(self._handles)
