"""Multigrid-preconditioned df64 CG: f64-class accuracy at O(1) iterations.

The reference solves with bare f64 CG (``CUDACG.cu:269-352``): on the
Laplacian that is O(grid extent) iterations.  The framework's df64 tier
composes its f64-class storage with the geometric multigrid V-cycle
(``models.multigrid``) as a MIXED-PRECISION preconditioner: the cycle
smooths the residual's hi word in f32, while the CG recurrence (dots,
axpys, convergence) stays full df64.  A preconditioner is just a fixed
SPD operator, so its application precision does not bound the attainable
residual - these tests pin exactly that: grid-independent iteration
counts AND ~1e-9-class solution error, simultaneously.
"""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import scipy.sparse as sp
import scipy.sparse.linalg as spla

from cuda_mpi_parallel_tpu import cg_df64
from cuda_mpi_parallel_tpu.models.poisson import (
    poisson_2d_csr,
    poisson_2d_operator,
    poisson_3d_operator,
)
from cuda_mpi_parallel_tpu.solver.status import CGStatus


def _scipy_solution(csr, b):
    a = sp.csr_matrix((np.asarray(csr.data), np.asarray(csr.indices),
                       np.asarray(csr.indptr)), shape=csr.shape)
    return spla.spsolve(a.tocsc(), b)


class TestDF64MGSingleDevice:
    def test_beats_plain_and_reaches_f64_accuracy(self, rng):
        """The headline property: far fewer iterations than plain df64
        CG at the same deep tolerance, and the solution still lands at
        f64-class error (the f32 V-cycle does not cap accuracy)."""
        nx = ny = 64
        a = poisson_2d_operator(nx, ny)
        b = rng.standard_normal(nx * ny)
        plain = cg_df64(a, b, tol=0.0, rtol=1e-11, maxiter=2000)
        mg = cg_df64(a, b, tol=0.0, rtol=1e-11, maxiter=2000,
                     preconditioner="mg")
        assert bool(mg.converged)
        assert mg.status_enum() is CGStatus.CONVERGED
        assert int(mg.iterations) < int(plain.iterations) // 3
        x_true = _scipy_solution(poisson_2d_csr(nx, ny), b)
        err = np.max(np.abs(mg.x() - x_true)) / np.max(np.abs(x_true))
        assert err < 1e-8

    def test_grid_independent_iterations(self, rng):
        """MG-PCG iteration counts stay O(1) as the grid refines - at
        df64 depth (rtol 1e-10), where unpreconditioned CG scales like
        O(extent)."""
        counts = []
        for nx in (32, 64, 128):
            a = poisson_2d_operator(nx, nx)
            b = rng.standard_normal(nx * nx)
            res = cg_df64(a, b, tol=0.0, rtol=1e-10, maxiter=500,
                          preconditioner="mg")
            assert bool(res.converged)
            counts.append(int(res.iterations))
        assert max(counts) <= min(counts) + 4
        assert max(counts) < 40

    def test_3d(self, rng):
        grid = (16, 16, 16)
        a = poisson_3d_operator(*grid)
        b = rng.standard_normal(int(np.prod(grid)))
        plain = cg_df64(a, b, tol=0.0, rtol=1e-10, maxiter=1000)
        res = cg_df64(a, b, tol=0.0, rtol=1e-10, maxiter=1000,
                      preconditioner="mg")
        assert bool(res.converged)
        assert int(res.iterations) < int(plain.iterations)
        # residual claim is real: recompute ||b - A x|| in f64 on host
        xs = res.x()
        from cuda_mpi_parallel_tpu.models.poisson import poisson_3d_csr

        a_sp = poisson_3d_csr(*grid)
        mat = sp.csr_matrix((np.asarray(a_sp.data),
                             np.asarray(a_sp.indices),
                             np.asarray(a_sp.indptr)), shape=a_sp.shape)
        r = b - mat @ xs
        assert np.linalg.norm(r) <= 1e-10 * np.linalg.norm(b) * 10

    def test_check_every_composes(self, rng):
        """check_every>1 runs the identical trajectory (block boundary
        semantics) under the mg preconditioner."""
        nx = 32
        a = poisson_2d_operator(nx, nx)
        b = rng.standard_normal(nx * nx)
        every = cg_df64(a, b, tol=0.0, rtol=1e-10, maxiter=64,
                        preconditioner="mg", check_every=1)
        blocked = cg_df64(a, b, tol=0.0, rtol=1e-10, maxiter=64,
                          preconditioner="mg", check_every=4)
        # blocked may overrun by up to 3 iterations but never fewer
        assert int(every.iterations) <= int(blocked.iterations) \
            <= int(every.iterations) + 3

    def test_resume_continues_trajectory(self, rng):
        """Checkpoint mid-solve, resume, land on the uninterrupted
        result (MG hierarchy is rebuilt deterministically from the
        operator)."""
        nx = 32
        a = poisson_2d_operator(nx, nx)
        b = rng.standard_normal(nx * nx)
        full = cg_df64(a, b, tol=0.0, rtol=1e-10, maxiter=100,
                       preconditioner="mg")
        part1 = cg_df64(a, b, tol=0.0, rtol=1e-10, maxiter=5,
                        preconditioner="mg", return_checkpoint=True)
        part2 = cg_df64(a, b, tol=0.0, rtol=1e-10, maxiter=100,
                        preconditioner="mg",
                        resume_from=part1.checkpoint)
        assert int(part2.iterations) == int(full.iterations)
        np.testing.assert_array_equal(np.asarray(part2.x_hi),
                                      np.asarray(full.x_hi))

    def test_rejections(self, rng):
        a_csr = poisson_2d_csr(8, 8)
        b = np.ones(64)
        with pytest.raises(ValueError, match="mg"):
            cg_df64(a_csr, b, preconditioner="mg")
        a = poisson_2d_operator(8, 8)
        with pytest.raises(ValueError, match="method='cg'"):
            cg_df64(a, b, preconditioner="mg", method="cg1")

    def test_bf16_stencil_promoted(self, rng):
        """A non-f32 stencil still builds the MG hierarchy in f32."""
        a = poisson_2d_operator(16, 16, dtype=jnp.bfloat16)
        b = rng.standard_normal(256)
        res = cg_df64(a, b, tol=0.0, rtol=1e-8, maxiter=200,
                      preconditioner="mg")
        assert bool(res.converged)


@pytest.mark.skipif(len(jax.devices()) < 8,
                    reason="needs 8 (virtual) devices")
@pytest.mark.slow
# distributed df64 multigrid: minutes of XLA:CPU compile on a small
# host - past the tier-1 870s budget; runs in the untimed full suite
class TestDF64MGDistributed:
    def test_slab_iteration_parity_2d(self, rng):
        """8-device mg-df64 == 1-device mg-df64 in iteration count (the
        distributed hierarchy IS the single-device hierarchy; only psum
        order differs)."""
        from cuda_mpi_parallel_tpu.parallel import make_mesh
        from cuda_mpi_parallel_tpu.parallel.df64 import (
            solve_distributed_df64,
        )

        nx, ny = 32, 33
        a = poisson_2d_operator(nx, ny)
        b = rng.standard_normal(nx * ny)
        single = cg_df64(a, b, tol=0.0, rtol=1e-10, maxiter=500,
                         preconditioner="mg")
        dist = solve_distributed_df64(a, b, mesh=make_mesh(8), tol=0.0,
                                      rtol=1e-10, maxiter=500,
                                      preconditioner="mg")
        assert bool(dist.converged)
        assert int(dist.iterations) == int(single.iterations)
        np.testing.assert_allclose(dist.x(), single.x(), rtol=0,
                                   atol=1e-9 * np.max(np.abs(single.x())))

    def test_slab_3d_converges_fast(self, rng):
        from cuda_mpi_parallel_tpu.parallel import make_mesh
        from cuda_mpi_parallel_tpu.parallel.df64 import (
            solve_distributed_df64,
        )

        grid = (16, 12, 10)
        a = poisson_3d_operator(*grid)
        b = rng.standard_normal(int(np.prod(grid)))
        plain = solve_distributed_df64(a, b, mesh=make_mesh(8), tol=0.0,
                                       rtol=1e-10, maxiter=500)
        mg = solve_distributed_df64(a, b, mesh=make_mesh(8), tol=0.0,
                                    rtol=1e-10, maxiter=500,
                                    preconditioner="mg")
        assert bool(mg.converged)
        assert int(mg.iterations) < int(plain.iterations)

    def test_pencil_iteration_parity(self, rng):
        """Pencil mesh (4x2) mg-df64 matches the single-device count."""
        from cuda_mpi_parallel_tpu.parallel.mesh import make_mesh_2d
        from cuda_mpi_parallel_tpu.parallel.df64 import (
            solve_distributed_df64,
        )

        grid = (16, 16, 6)
        a = poisson_3d_operator(*grid)
        b = rng.standard_normal(int(np.prod(grid)))
        single = cg_df64(a, b, tol=0.0, rtol=1e-10, maxiter=500,
                         preconditioner="mg")
        mesh = make_mesh_2d((4, 2))
        dist = solve_distributed_df64(a, b, mesh=mesh, tol=0.0,
                                      rtol=1e-10, maxiter=500,
                                      preconditioner="mg")
        assert bool(dist.converged)
        assert int(dist.iterations) == int(single.iterations)

    def test_csr_rejected(self, rng):
        from cuda_mpi_parallel_tpu.parallel.df64 import (
            solve_distributed_df64,
        )

        a = poisson_2d_csr(8, 8)
        with pytest.raises(ValueError, match="mg"):
            solve_distributed_df64(a, np.ones(64), n_devices=8,
                                   preconditioner="mg")
