"""telemetry.registry / telemetry.events / telemetry.session units.

The observability contract: metrics and events are host-side Python
state, strict-JSON serializable, opt-in, and schema-checked - the
structured replacement for the reference's printf of the solution
vector (CUDACG.cu:361-365, SURVEY quirk Q7).
"""
import json

import numpy as np
import pytest

from cuda_mpi_parallel_tpu.solver.status import CGStatus
from cuda_mpi_parallel_tpu.telemetry import events, session
from cuda_mpi_parallel_tpu.telemetry.registry import (
    REGISTRY,
    MetricsRegistry,
)


class TestRegistry:
    def test_counter_accumulates_per_labelset(self):
        r = MetricsRegistry()
        c = r.counter("req_total", "requests", ("engine",))
        c.inc(engine="resident")
        c.inc(2, engine="resident")
        c.inc(engine="general")
        assert c.value(engine="resident") == 3
        assert c.value(engine="general") == 1
        assert c.value(engine="never") == 0

    def test_counter_rejects_negative_and_bad_labels(self):
        r = MetricsRegistry()
        c = r.counter("x_total", labelnames=("a",))
        with pytest.raises(ValueError, match="only go up"):
            c.inc(-1, a="v")
        with pytest.raises(ValueError, match="labels"):
            c.inc(b="v")

    def test_get_or_create_same_metric_kind_conflict_raises(self):
        r = MetricsRegistry()
        first = r.counter("m", "h", ("l",))
        assert r.counter("m", "h", ("l",)) is first
        with pytest.raises(ValueError, match="already registered"):
            r.gauge("m", "h", ("l",))
        with pytest.raises(ValueError, match="already registered"):
            r.counter("m", "h", ("other",))

    def test_gauge_set_inc_dec(self):
        r = MetricsRegistry()
        g = r.gauge("depth")
        g.set(5)
        g.inc(2)
        g.dec()
        assert g.value() == 6

    def test_histogram_buckets_cumulative(self):
        r = MetricsRegistry()
        h = r.histogram("lat", buckets=(0.1, 1.0, 10.0))
        for v in (0.05, 0.5, 0.5, 50.0):
            h.observe(v)
        snap = h.snapshot()[0]
        assert snap["buckets"] == {"0.1": 1, "1": 3, "10": 3}
        assert snap["count"] == 4
        assert snap["sum"] == pytest.approx(51.05)

    def test_prometheus_text_format(self):
        r = MetricsRegistry()
        r.counter("a_total", "things", ("k",)).inc(3, k="v1")
        r.histogram("b_seconds", buckets=(1.0,)).observe(0.5)
        text = r.to_prometheus()
        assert '# TYPE a_total counter' in text
        assert 'a_total{k="v1"} 3' in text
        assert 'b_seconds_bucket{le="1"} 1' in text
        assert 'b_seconds_bucket{le="+Inf"} 1' in text
        assert 'b_seconds_count 1' in text

    def test_prometheus_nonfinite_values_render(self):
        # Prometheus text supports NaN/+Inf/-Inf literals; one bad
        # gauge value must not poison every later scrape
        r = MetricsRegistry()
        r.gauge("g_nan").set(float("nan"))
        r.gauge("g_ninf").set(float("-inf"))
        text = r.to_prometheus()
        assert "g_nan NaN" in text
        assert "g_ninf -Inf" in text

    def test_histogram_bucket_mismatch_raises(self):
        r = MetricsRegistry()
        r.histogram("h_seconds", buckets=(0.1, 1.0))
        with pytest.raises(ValueError, match="buckets"):
            r.histogram("h_seconds", buckets=(0.5, 5.0))

    def test_snapshot_is_strict_json(self):
        r = MetricsRegistry()
        r.counter("c_total", labelnames=("x",)).inc(x="y")
        r.gauge("g").set(1.5)
        r.histogram("h").observe(2.0)
        parsed = json.loads(r.to_json())
        assert parsed["c_total"]["kind"] == "counter"
        assert parsed["g"]["series"][0]["value"] == 1.5

    def test_process_registry_exists(self):
        # the default registry is the shared instrument target
        c = REGISTRY.counter("test_events_metrics_probe_total")
        c.inc()
        assert c.value() >= 1


class TestEvents:
    def test_emit_without_sink_is_noop(self):
        events.configure(None)
        assert not events.active()
        assert events.emit("solve_start", label="x") is None

    def test_capture_and_schema_roundtrip(self):
        with events.capture() as buf:
            with events.solve_scope() as sid:
                events.emit("solve_start", label="t", extra_field=1)
                events.emit("engine_selected", engine="general",
                            method="cg")
            events.emit("solve_start", label="outside-scope")
        lines = [json.loads(ln) for ln in buf.getvalue().splitlines()]
        assert [l["event"] for l in lines] == [
            "solve_start", "engine_selected", "solve_start"]
        for line in lines:
            events.validate_event(line)
        assert lines[0]["solve_id"] == sid == lines[1]["solve_id"]
        assert lines[2]["solve_id"] is None
        assert lines[0]["extra_field"] == 1
        # monotonic timestamps within the stream
        assert lines[0]["t"] <= lines[1]["t"] <= lines[2]["t"]

    def test_unknown_type_and_missing_fields_raise(self):
        with events.capture():
            with pytest.raises(ValueError, match="unknown event type"):
                events.emit("not_a_type", x=1)
            with pytest.raises(ValueError, match="missing required"):
                events.emit("engine_selected", engine="general")

    def test_nonfinite_floats_sanitized_to_null(self):
        with events.capture() as buf:
            events.emit("solve_end", status="BREAKDOWN", iterations=7,
                        residual_norm=float("nan"),
                        nested={"inf": float("inf")})
        line = buf.getvalue().strip()
        rec = json.loads(line)
        assert rec["residual_norm"] is None
        assert rec["nested"]["inf"] is None
        assert "NaN" not in line and "Infinity" not in line

    def test_validate_event_rejects_bad_records(self):
        with pytest.raises(ValueError):
            events.validate_event({"event": "nope", "t": 0.0})
        with pytest.raises(ValueError):
            events.validate_event({"event": "solve_start", "t": "late"})
        with pytest.raises(ValueError):
            events.validate_event(
                {"event": "solve_end", "t": 0.0, "status": "X",
                 "iterations": 1})  # missing residual_norm
        events.validate_event(
            {"event": "solve_start", "t": 0.0, "label": "ok",
             "solve_id": None})


def _fake_result(iterations=8, residual=1e-9, history=None,
                 status=CGStatus.CONVERGED):
    class R:
        pass

    r = R()
    r.iterations = iterations
    r.residual_norm = residual
    r.converged = status == CGStatus.CONVERGED
    r.indefinite = False
    r.residual_history = history
    r.status_enum = lambda: status
    return r


class TestObserveSolve:
    def test_full_cycle_events_and_metrics(self):
        counters = session.solve_metrics()
        before = counters["solves"].value(engine="unit-test",
                                          status="CONVERGED")
        with events.capture() as buf:
            with session.observe_solve("unit solve", engine="unit-test",
                                       problem="fake") as obs:
                with obs.section("build"):
                    pass
                obs.finish(_fake_result(), elapsed_s=0.25)
        lines = [json.loads(ln) for ln in buf.getvalue().splitlines()]
        for line in lines:
            events.validate_event(line)
        kinds = [l["event"] for l in lines]
        assert kinds[0] == "solve_start" and kinds[-1] == "solve_end"
        assert "check_block" in kinds
        end = lines[-1]
        assert end["status"] == "CONVERGED" and end["iterations"] == 8
        assert end["solve_id"] == lines[0]["solve_id"]
        assert "build" in end["sections"]
        after = counters["solves"].value(engine="unit-test",
                                         status="CONVERGED")
        assert after == before + 1

    def test_check_block_events_from_history(self):
        hist = np.full(101, np.nan)
        boundaries = [0, 4, 8, 12, 14]
        for i in boundaries:
            hist[i] = 1.0 / (i + 1)
        with events.capture() as buf:
            with session.observe_solve("blocked", engine="general",
                                       check_every=4) as obs:
                obs.finish(_fake_result(iterations=14, history=hist))
        blocks = [json.loads(ln) for ln in buf.getvalue().splitlines()
                  if json.loads(ln)["event"] == "check_block"]
        assert [b["iteration"] for b in blocks] == [4, 8, 12, 14]
        # the final (converged) boundary is present and flagged
        assert blocks[-1]["final"] is True
        assert blocks[-1]["residual_norm"] == pytest.approx(1.0 / 15)

    def test_check_block_event_count_capped(self):
        hist = np.arange(2001.0) + 1.0
        with events.capture() as buf:
            with session.observe_solve("long", check_every=1) as obs:
                obs.finish(_fake_result(iterations=2000, history=hist))
        blocks = [json.loads(ln) for ln in buf.getvalue().splitlines()
                  if json.loads(ln)["event"] == "check_block"]
        assert 0 < len(blocks) <= session.MAX_CHECK_BLOCK_EVENTS + 1
        assert blocks[-1]["iteration"] == 2000

    def test_unfinished_scope_emits_solve_end(self):
        with events.capture() as buf:
            with session.observe_solve("abandoned"):
                pass
        lines = [json.loads(ln) for ln in buf.getvalue().splitlines()]
        assert lines[-1]["event"] == "solve_end"
        assert lines[-1]["status"] == "unobserved"

    def test_exception_still_closes_the_trace(self):
        """No dangling solve_start on the error path: the exception
        propagates AND the scope emits a status='error' solve_end."""
        with events.capture() as buf:
            with pytest.raises(RuntimeError, match="boom"):
                with session.observe_solve("exploding"):
                    raise RuntimeError("boom")
        lines = [json.loads(ln) for ln in buf.getvalue().splitlines()]
        assert lines[-1]["event"] == "solve_end"
        assert lines[-1]["status"] == "error"
        assert lines[-1]["error"] == "RuntimeError"
        assert lines[-1]["solve_id"] == lines[0]["solve_id"]

    def test_scoped_fields_ride_on_events(self):
        with events.capture() as buf:
            with events.scoped(phase="warmup"):
                events.emit("solve_start", label="w")
            events.emit("solve_start", label="t")
        lines = [json.loads(ln) for ln in buf.getvalue().splitlines()]
        assert lines[0]["phase"] == "warmup"
        assert "phase" not in lines[1]
