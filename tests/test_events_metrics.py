"""telemetry.registry / telemetry.events / telemetry.session units.

The observability contract: metrics and events are host-side Python
state, strict-JSON serializable, opt-in, and schema-checked - the
structured replacement for the reference's printf of the solution
vector (CUDACG.cu:361-365, SURVEY quirk Q7).
"""
import json

import numpy as np
import pytest

from cuda_mpi_parallel_tpu.solver.status import CGStatus
from cuda_mpi_parallel_tpu.telemetry import events, session
from cuda_mpi_parallel_tpu.telemetry.registry import (
    REGISTRY,
    MetricsRegistry,
)


class TestRegistry:
    def test_counter_accumulates_per_labelset(self):
        r = MetricsRegistry()
        c = r.counter("req_total", "requests", ("engine",))
        c.inc(engine="resident")
        c.inc(2, engine="resident")
        c.inc(engine="general")
        assert c.value(engine="resident") == 3
        assert c.value(engine="general") == 1
        assert c.value(engine="never") == 0

    def test_counter_rejects_negative_and_bad_labels(self):
        r = MetricsRegistry()
        c = r.counter("x_total", labelnames=("a",))
        with pytest.raises(ValueError, match="only go up"):
            c.inc(-1, a="v")
        with pytest.raises(ValueError, match="labels"):
            c.inc(b="v")

    def test_get_or_create_same_metric_kind_conflict_raises(self):
        r = MetricsRegistry()
        first = r.counter("m", "h", ("l",))
        assert r.counter("m", "h", ("l",)) is first
        with pytest.raises(ValueError, match="already registered"):
            r.gauge("m", "h", ("l",))
        with pytest.raises(ValueError, match="already registered"):
            r.counter("m", "h", ("other",))

    def test_gauge_set_inc_dec(self):
        r = MetricsRegistry()
        g = r.gauge("depth")
        g.set(5)
        g.inc(2)
        g.dec()
        assert g.value() == 6

    def test_histogram_buckets_cumulative(self):
        r = MetricsRegistry()
        h = r.histogram("lat", buckets=(0.1, 1.0, 10.0))
        for v in (0.05, 0.5, 0.5, 50.0):
            h.observe(v)
        snap = h.snapshot()[0]
        assert snap["buckets"] == {"0.1": 1, "1": 3, "10": 3}
        assert snap["count"] == 4
        assert snap["sum"] == pytest.approx(51.05)

    def test_prometheus_text_format(self):
        r = MetricsRegistry()
        r.counter("a_total", "things", ("k",)).inc(3, k="v1")
        r.histogram("b_seconds", buckets=(1.0,)).observe(0.5)
        text = r.to_prometheus()
        assert '# TYPE a_total counter' in text
        assert 'a_total{k="v1"} 3' in text
        assert 'b_seconds_bucket{le="1"} 1' in text
        assert 'b_seconds_bucket{le="+Inf"} 1' in text
        assert 'b_seconds_count 1' in text

    def test_prometheus_nonfinite_values_render(self):
        # Prometheus text supports NaN/+Inf/-Inf literals; one bad
        # gauge value must not poison every later scrape
        r = MetricsRegistry()
        r.gauge("g_nan").set(float("nan"))
        r.gauge("g_ninf").set(float("-inf"))
        text = r.to_prometheus()
        assert "g_nan NaN" in text
        assert "g_ninf -Inf" in text

    def test_prometheus_label_value_escaping(self):
        # Exposition-format spec: backslash, double-quote and newline
        # must be escaped inside label values.  The PR-4 regression
        # case: a label carrying '"' and '\n' must stay ONE sample
        # line with escaped characters, not split the scrape.
        r = MetricsRegistry()
        r.gauge("esc", "escaping probe", ("label",)).set(
            1.0, label='a"b\nc\\d')
        text = r.to_prometheus()
        line = [ln for ln in text.splitlines()
                if ln.startswith("esc{")]
        assert line == ['esc{label="a\\"b\\nc\\\\d"} 1']
        # every sample stays on its own line (the raw newline would
        # have produced a dangling 'c\\d"} 1' fragment line)
        assert not any(ln.startswith("c") for ln in text.splitlines())

    def test_histogram_bucket_mismatch_raises(self):
        r = MetricsRegistry()
        r.histogram("h_seconds", buckets=(0.1, 1.0))
        with pytest.raises(ValueError, match="buckets"):
            r.histogram("h_seconds", buckets=(0.5, 5.0))

    def test_histogram_quantile_math_hand_built(self):
        """Regression pin for the percentile readout: hand-built
        cumulative buckets with known exact histogram_quantile
        answers (linear interpolation inside the landing bucket,
        lower bound 0 for the first)."""
        r = MetricsRegistry()
        h = r.histogram("q_seconds", buckets=(1.0, 2.0, 4.0))
        for _ in range(50):
            h.observe(0.5)            # bucket <= 1: 50
        for _ in range(50):
            h.observe(3.0)            # bucket <= 4: 100
        # p50: target 50 lands exactly on bucket 1's cumulative 50 ->
        # 0 + 1 * (50 - 0)/50 = 1.0
        assert h.quantile(0.50) == pytest.approx(1.0)
        # p95: target 95 lands in (2, 4] (prev cumulative 50, 50
        # inside) -> 2 + 2 * (95 - 50)/50 = 3.8
        assert h.quantile(0.95) == pytest.approx(3.8)
        # p25: halfway into the first bucket -> 0 + 1 * 25/50 = 0.5
        assert h.quantile(0.25) == pytest.approx(0.5)
        with pytest.raises(ValueError):
            h.quantile(1.5)

    def test_histogram_quantile_inf_bucket_clamps(self):
        r = MetricsRegistry()
        h = r.histogram("clamp_seconds", buckets=(1.0, 2.0))
        h.observe(100.0)              # beyond every finite bound
        # the honest bucketed answer: the highest finite bound
        assert h.quantile(0.99) == 2.0
        assert h.quantile(0.99, ) is not None
        empty = r.histogram("empty_seconds", buckets=(1.0,))
        assert empty.quantile(0.5) is None

    def test_histogram_percentiles_in_snapshot_and_prometheus(self):
        r = MetricsRegistry()
        h = r.histogram("lat_seconds", "latency", ("handle",),
                        buckets=(1.0, 2.0, 4.0))
        for _ in range(50):
            h.observe(0.5, handle="h1")
        for _ in range(50):
            h.observe(3.0, handle="h1")
        snap = h.snapshot()[0]
        assert snap["percentiles"]["p50"] == pytest.approx(1.0)
        assert snap["percentiles"]["p95"] == pytest.approx(3.8)
        # snapshot stays strict JSON with the percentiles attached
        json.loads(r.to_json())
        text = r.to_prometheus()
        assert '# TYPE lat_seconds_p50 gauge' in text
        assert 'lat_seconds_p50{handle="h1"} 1' in text
        assert 'lat_seconds_p95{handle="h1"} 3.8' in text
        assert 'lat_seconds_p99{handle="h1"}' in text

    def test_snapshot_is_strict_json(self):
        r = MetricsRegistry()
        r.counter("c_total", labelnames=("x",)).inc(x="y")
        r.gauge("g").set(1.5)
        r.histogram("h").observe(2.0)
        parsed = json.loads(r.to_json())
        assert parsed["c_total"]["kind"] == "counter"
        assert parsed["g"]["series"][0]["value"] == 1.5

    def test_process_registry_exists(self):
        # the default registry is the shared instrument target
        c = REGISTRY.counter("test_events_metrics_probe_total")
        c.inc()
        assert c.value() >= 1


class TestEvents:
    def test_emit_without_sink_is_noop(self):
        events.configure(None)
        assert not events.active()
        assert events.emit("solve_start", label="x") is None

    def test_capture_and_schema_roundtrip(self):
        with events.capture() as buf:
            with events.solve_scope() as sid:
                events.emit("solve_start", label="t", extra_field=1)
                events.emit("engine_selected", engine="general",
                            method="cg")
            events.emit("solve_start", label="outside-scope")
        lines = [json.loads(ln) for ln in buf.getvalue().splitlines()]
        assert [l["event"] for l in lines] == [
            "solve_start", "engine_selected", "solve_start"]
        for line in lines:
            events.validate_event(line)
        assert lines[0]["solve_id"] == sid == lines[1]["solve_id"]
        assert lines[2]["solve_id"] is None
        assert lines[0]["extra_field"] == 1
        # monotonic timestamps within the stream
        assert lines[0]["t"] <= lines[1]["t"] <= lines[2]["t"]

    def test_unknown_type_and_missing_fields_raise(self):
        with events.capture():
            with pytest.raises(ValueError, match="unknown event type"):
                events.emit("not_a_type", x=1)
            with pytest.raises(ValueError, match="missing required"):
                events.emit("engine_selected", engine="general")

    def test_nonfinite_floats_sanitized_to_null(self):
        with events.capture() as buf:
            events.emit("solve_end", status="BREAKDOWN", iterations=7,
                        residual_norm=float("nan"),
                        nested={"inf": float("inf")})
        line = buf.getvalue().strip()
        rec = json.loads(line)
        assert rec["residual_norm"] is None
        assert rec["nested"]["inf"] is None
        assert "NaN" not in line and "Infinity" not in line

    def test_validate_event_rejects_bad_records(self):
        with pytest.raises(ValueError):
            events.validate_event({"event": "nope", "t": 0.0})
        with pytest.raises(ValueError):
            events.validate_event({"event": "solve_start", "t": "late"})
        with pytest.raises(ValueError):
            events.validate_event(
                {"event": "solve_end", "t": 0.0, "status": "X",
                 "iterations": 1})  # missing residual_norm
        events.validate_event(
            {"event": "solve_start", "t": 0.0, "label": "ok",
             "solve_id": None})


def _fake_result(iterations=8, residual=1e-9, history=None,
                 status=CGStatus.CONVERGED):
    class R:
        pass

    r = R()
    r.iterations = iterations
    r.residual_norm = residual
    r.converged = status == CGStatus.CONVERGED
    r.indefinite = False
    r.residual_history = history
    r.status_enum = lambda: status
    return r


class TestObserveSolve:
    def test_full_cycle_events_and_metrics(self):
        counters = session.solve_metrics()
        before = counters["solves"].value(engine="unit-test",
                                          status="CONVERGED")
        with events.capture() as buf:
            with session.observe_solve("unit solve", engine="unit-test",
                                       problem="fake") as obs:
                with obs.section("build"):
                    pass
                obs.finish(_fake_result(), elapsed_s=0.25)
        lines = [json.loads(ln) for ln in buf.getvalue().splitlines()]
        for line in lines:
            events.validate_event(line)
        kinds = [l["event"] for l in lines]
        assert kinds[0] == "solve_start" and kinds[-1] == "solve_end"
        assert "check_block" in kinds
        end = lines[-1]
        assert end["status"] == "CONVERGED" and end["iterations"] == 8
        assert end["solve_id"] == lines[0]["solve_id"]
        assert "build" in end["sections"]
        after = counters["solves"].value(engine="unit-test",
                                         status="CONVERGED")
        assert after == before + 1

    def test_check_block_events_from_history(self):
        hist = np.full(101, np.nan)
        boundaries = [0, 4, 8, 12, 14]
        for i in boundaries:
            hist[i] = 1.0 / (i + 1)
        with events.capture() as buf:
            with session.observe_solve("blocked", engine="general",
                                       check_every=4) as obs:
                obs.finish(_fake_result(iterations=14, history=hist))
        blocks = [json.loads(ln) for ln in buf.getvalue().splitlines()
                  if json.loads(ln)["event"] == "check_block"]
        assert [b["iteration"] for b in blocks] == [4, 8, 12, 14]
        # the final (converged) boundary is present and flagged
        assert blocks[-1]["final"] is True
        assert blocks[-1]["residual_norm"] == pytest.approx(1.0 / 15)

    def test_check_block_event_count_capped(self):
        hist = np.arange(2001.0) + 1.0
        with events.capture() as buf:
            with session.observe_solve("long", check_every=1) as obs:
                obs.finish(_fake_result(iterations=2000, history=hist))
        blocks = [json.loads(ln) for ln in buf.getvalue().splitlines()
                  if json.loads(ln)["event"] == "check_block"]
        assert 0 < len(blocks) <= session.MAX_CHECK_BLOCK_EVENTS + 1
        assert blocks[-1]["iteration"] == 2000

    def test_unfinished_scope_emits_solve_end(self):
        with events.capture() as buf:
            with session.observe_solve("abandoned"):
                pass
        lines = [json.loads(ln) for ln in buf.getvalue().splitlines()]
        assert lines[-1]["event"] == "solve_end"
        assert lines[-1]["status"] == "unobserved"

    def test_exception_still_closes_the_trace(self):
        """No dangling solve_start on the error path: the exception
        propagates AND the scope emits a status='error' solve_end."""
        with events.capture() as buf:
            with pytest.raises(RuntimeError, match="boom"):
                with session.observe_solve("exploding"):
                    raise RuntimeError("boom")
        lines = [json.loads(ln) for ln in buf.getvalue().splitlines()]
        assert lines[-1]["event"] == "solve_end"
        assert lines[-1]["status"] == "error"
        assert lines[-1]["error"] == "RuntimeError"
        assert lines[-1]["solve_id"] == lines[0]["solve_id"]

    def test_scoped_fields_ride_on_events(self):
        with events.capture() as buf:
            with events.scoped(phase="warmup"):
                events.emit("solve_start", label="w")
            events.emit("solve_start", label="t")
        lines = [json.loads(ln) for ln in buf.getvalue().splitlines()]
        assert lines[0]["phase"] == "warmup"
        assert "phase" not in lines[1]


class TestFlightRecorder:
    """telemetry.flight: the in-loop convergence recorder.

    Load-bearing properties: a stride-1 record reproduces the dense
    ``record_history`` trace BIT-FOR-BIT (same rr scalars, correctly
    rounded sqrt), decimation and the ring wrap keep exactly the
    documented rows, and the recorder-off path is proven untouched in
    tests/test_cost_accounting.py::TestZeroPerturbation.
    """

    def _poisson(self, n=24):
        import jax.numpy as jnp

        from cuda_mpi_parallel_tpu.models.operators import Stencil2D

        a = Stencil2D.create(n, n, dtype=jnp.float32)
        rng = np.random.default_rng(7)
        b = jnp.asarray(rng.standard_normal(n * n).astype(np.float32))
        return a, b

    def test_config_validation(self):
        from cuda_mpi_parallel_tpu.telemetry.flight import FlightConfig

        with pytest.raises(ValueError, match="capacity"):
            FlightConfig(capacity=0)
        with pytest.raises(ValueError, match="stride"):
            FlightConfig(stride=0)
        with pytest.raises(ValueError, match="heartbeat"):
            FlightConfig(heartbeat=-1)
        cfg = FlightConfig.for_solve(100, stride=4)
        assert cfg.capacity == 26 and cfg.stride == 4
        # capacity is capped (the carried buffer stays bounded)
        assert FlightConfig.for_solve(10 ** 9).capacity == 4096

    def test_stride1_matches_dense_history_bit_for_bit(self):
        from cuda_mpi_parallel_tpu.solver.cg import solve
        from cuda_mpi_parallel_tpu.telemetry.flight import (
            FlightConfig,
            FlightRecord,
        )

        a, b = self._poisson()
        res = solve(a, b, tol=1e-5, maxiter=400, record_history=True,
                    flight=FlightConfig.for_solve(400, stride=1))
        assert bool(res.converged)
        rec = FlightRecord.from_buffer(res.flight, stride=1)
        hist = np.asarray(res.residual_history)
        dense = hist[np.isfinite(hist)].astype(np.float32)
        k = int(res.iterations)
        assert len(rec) == dense.shape[0] == k + 1
        assert np.array_equal(rec.iterations, np.arange(k + 1))
        # BIT-FOR-BIT: both sides are sqrt of the identical rr scalar
        # (f64 sqrt of an f32 value rounds to the f32 sqrt exactly)
        assert np.array_equal(rec.residuals.astype(np.float32), dense)

    def test_stride1_matches_dense_history_cg1_pipecg(self):
        from cuda_mpi_parallel_tpu.solver.cg import solve
        from cuda_mpi_parallel_tpu.telemetry.flight import (
            FlightConfig,
            FlightRecord,
        )

        a, b = self._poisson()
        for method in ("cg1", "pipecg"):
            res = solve(a, b, tol=1e-5, maxiter=400, method=method,
                        record_history=True,
                        flight=FlightConfig.for_solve(400, stride=1))
            rec = FlightRecord.from_buffer(res.flight, stride=1)
            hist = np.asarray(res.residual_history)
            dense = hist[np.isfinite(hist)].astype(np.float32)
            assert np.array_equal(rec.residuals.astype(np.float32),
                                  dense), method

    def test_decimation_records_every_nth(self):
        from cuda_mpi_parallel_tpu.solver.cg import solve
        from cuda_mpi_parallel_tpu.telemetry.flight import (
            FlightConfig,
            FlightRecord,
        )

        a, b = self._poisson()
        res = solve(a, b, tol=1e-5, maxiter=400, record_history=True,
                    flight=FlightConfig.for_solve(400, stride=8))
        rec = FlightRecord.from_buffer(res.flight)
        assert rec.stride == 8
        assert np.all(rec.iterations % 8 == 0)
        assert np.all(np.diff(rec.iterations) == 8)  # monotone, gapless
        # decimated rows equal the dense trace at the sampled indices
        hist = np.asarray(res.residual_history)
        assert np.array_equal(rec.residuals.astype(np.float32),
                              hist[rec.iterations].astype(np.float32))

    def test_ring_wrap_keeps_last_window(self):
        from cuda_mpi_parallel_tpu.solver.cg import solve
        from cuda_mpi_parallel_tpu.telemetry.flight import (
            FlightConfig,
            FlightRecord,
        )

        a, b = self._poisson()
        # 16-row ring on a ~hundreds-iteration solve: must keep the
        # LAST 16 sampled iterations, consecutively
        res = solve(a, b, tol=1e-5, maxiter=400,
                    flight=FlightConfig(capacity=16, stride=1))
        k = int(res.iterations)
        rec = FlightRecord.from_buffer(res.flight, stride=1)
        assert len(rec) == 16
        assert rec.iterations[-1] == k
        assert np.array_equal(rec.iterations,
                              np.arange(k - 15, k + 1))

    def test_alpha_beta_columns_recorded(self):
        from cuda_mpi_parallel_tpu.solver.cg import solve
        from cuda_mpi_parallel_tpu.telemetry.flight import (
            FlightConfig,
            FlightRecord,
        )

        a, b = self._poisson()
        res = solve(a, b, tol=1e-5, maxiter=400,
                    flight=FlightConfig.for_solve(400))
        rec = FlightRecord.from_buffer(res.flight)
        # row 0 is the initial state (no step ran): NaN alpha/beta;
        # every later row holds the step's positive SPD scalars
        assert np.isnan(rec.alphas[0]) and np.isnan(rec.betas[0])
        assert np.all(rec.alphas[1:] > 0)
        assert np.all(rec.betas[1:] >= 0)

    def test_from_history_and_to_history_roundtrip(self):
        from cuda_mpi_parallel_tpu.telemetry.flight import FlightRecord

        hist = np.full(33, np.nan)
        its = np.array([0, 8, 16, 24, 32])
        hist[its] = [1.0, 0.5, 0.25, 0.125, 0.0625]
        rec = FlightRecord.from_history(hist)
        assert np.array_equal(rec.iterations, its)
        assert rec.stride == 8
        back = rec.to_history(32)
        assert np.array_equal(np.isfinite(back), np.isfinite(hist))
        np.testing.assert_allclose(back[its], hist[its], rtol=1e-12)

    def test_summary_and_decay_rate(self):
        from cuda_mpi_parallel_tpu.telemetry.flight import FlightRecord

        # exactly one decade per 10 iterations -> decay_rate = -0.1
        its = np.arange(0, 101, 10)
        hist = np.full(101, np.nan)
        hist[its] = 10.0 ** (-its / 10.0)
        rec = FlightRecord.from_history(hist)
        assert rec.decay_rate() == pytest.approx(-0.1, rel=1e-9)
        s = rec.summary()
        assert s["n_records"] == 11 and s["stride"] == 10
        assert s["last_iteration"] == 100
        assert s["decay_rate"] == pytest.approx(-0.1, rel=1e-9)

    def test_engine_selected_carries_flight_stride(self):
        from cuda_mpi_parallel_tpu.solver.cg import solve
        from cuda_mpi_parallel_tpu.telemetry.flight import FlightConfig

        a, b = self._poisson()
        with events.capture() as buf:
            solve(a, b, tol=1e-5, maxiter=50,
                  flight=FlightConfig.for_solve(50, stride=3))
            solve(a, b, tol=1e-5, maxiter=50)
        lines = [json.loads(ln) for ln in buf.getvalue().splitlines()
                 if json.loads(ln)["event"] == "engine_selected"]
        assert lines[0]["flight_stride"] == 3
        assert "flight_stride" not in lines[-1]

    def test_heartbeat_off_means_no_callback_in_jaxpr(self):
        import jax
        import jax.numpy as jnp

        from cuda_mpi_parallel_tpu.models.operators import Stencil2D
        from cuda_mpi_parallel_tpu.solver.cg import cg
        from cuda_mpi_parallel_tpu.telemetry.flight import FlightConfig

        a = Stencil2D.create(16, 16, dtype=jnp.float32)
        b = jnp.ones(256, jnp.float32)
        off = str(jax.make_jaxpr(lambda v: cg(
            a, v, maxiter=25, flight=FlightConfig(capacity=8)))(b))
        on = str(jax.make_jaxpr(lambda v: cg(
            a, v, maxiter=25,
            flight=FlightConfig(capacity=8, heartbeat=5)))(b))
        assert "callback" not in off      # GL105: hot loop untouched
        assert "callback" in on           # opt-in sampled heartbeat

    def test_heartbeat_emits_sampled_events(self):
        from cuda_mpi_parallel_tpu.solver.cg import solve
        from cuda_mpi_parallel_tpu.telemetry.flight import FlightConfig

        import jax

        a, b = self._poisson()
        with events.capture() as buf:
            res = solve(a, b, tol=1e-5, maxiter=400,
                        flight=FlightConfig.for_solve(
                            400, heartbeat=50))
            np.asarray(res.x)
            jax.effects_barrier()         # callbacks delivered
        beats = [json.loads(ln) for ln in buf.getvalue().splitlines()
                 if json.loads(ln)["event"] == "flight_heartbeat"]
        assert beats, "heartbeat events must arrive"
        assert all(b["iteration"] % 50 == 0 for b in beats)

    def test_heartbeat_carries_solve_scope(self):
        """Heartbeats run on jax's callback thread where the event
        contextvars are empty: the dispatch-time ambient snapshot must
        keep them correlated with the in-flight solve (solve_id AND
        scoped fields like the CLI's phase="warmup")."""
        from cuda_mpi_parallel_tpu.solver.cg import solve
        from cuda_mpi_parallel_tpu.telemetry.flight import FlightConfig

        import jax

        a, b = self._poisson()
        with events.capture() as buf, \
                events.solve_scope("hb-probe"), \
                events.scoped(phase="warmup"):
            res = solve(a, b, tol=1e-5, maxiter=400,
                        flight=FlightConfig.for_solve(
                            400, heartbeat=50))
            np.asarray(res.x)
            jax.effects_barrier()         # callbacks delivered
        beats = [json.loads(ln) for ln in buf.getvalue().splitlines()
                 if json.loads(ln)["event"] == "flight_heartbeat"]
        assert beats
        assert all(b["solve_id"] == "hb-probe" for b in beats)
        assert all(b.get("phase") == "warmup" for b in beats)


class TestSolveHealth:
    """telemetry.health: the trace classification + spectral estimate
    that turn 'MAXITER' into a diagnosis (the reference printed
    'Success' unconditionally, CUDACG.cu:365)."""

    def _record(self, residuals, its=None):
        from cuda_mpi_parallel_tpu.telemetry.flight import FlightRecord

        residuals = np.asarray(residuals, dtype=np.float64)
        if its is None:
            its = np.arange(residuals.shape[0])
        buf = np.full((residuals.shape[0], 4), np.nan)
        buf[:, 0] = its
        buf[:, 1] = residuals ** 2
        return FlightRecord.from_buffer(buf, stride=1)

    def test_new_status_codes_describe(self):
        assert "stagnated" in CGStatus.STAGNATED.describe()
        assert "diverged" in CGStatus.DIVERGED.describe()
        # device-produced codes unchanged
        assert CGStatus.CONVERGED == 0 and CGStatus.MAXITER == 1 \
            and CGStatus.BREAKDOWN == 2

    def test_condition_estimate_known_spectrum(self):
        """Diagonal operator with eigenvalues linspace(1, 100): the CG
        Lanczos tridiagonal's extreme Ritz values must recover
        kappa = 100 from the recorded alpha/beta (inner bound)."""
        import jax.numpy as jnp

        from cuda_mpi_parallel_tpu.solver.cg import solve
        from cuda_mpi_parallel_tpu.telemetry.flight import (
            FlightConfig,
            FlightRecord,
        )
        from cuda_mpi_parallel_tpu.telemetry.health import (
            estimate_condition,
        )

        eigs = np.linspace(1.0, 100.0, 40)
        a = jnp.asarray(np.diag(eigs))
        rng = np.random.default_rng(3)
        b = jnp.asarray(rng.standard_normal(40))
        res = solve(a, b, tol=1e-12, maxiter=80,
                    flight=FlightConfig.for_solve(80))
        rec = FlightRecord.from_buffer(res.flight)
        lmin, lmax, kappa = estimate_condition(rec)
        assert lmin >= 1.0 - 1e-6 and lmax <= 100.0 + 1e-6  # inner
        assert kappa == pytest.approx(100.0, rel=0.05)

    def test_condition_estimate_pipecg_rounding_floor(self):
        """pipecg driven past its accuracy floor records a run of
        negative trailing alphas; the estimate must truncate to the
        clean leading rows (which define a valid tridiagonal) instead
        of declining outright."""
        import jax.numpy as jnp

        from cuda_mpi_parallel_tpu.solver.cg import solve
        from cuda_mpi_parallel_tpu.telemetry.flight import (
            FlightConfig,
            FlightRecord,
        )
        from cuda_mpi_parallel_tpu.telemetry.health import (
            estimate_condition,
        )

        eigs = np.linspace(1.0, 100.0, 40)
        a = jnp.asarray(np.diag(eigs))
        rng = np.random.default_rng(3)
        b = jnp.asarray(rng.standard_normal(40))
        res = solve(a, b, tol=1e-12, maxiter=80, method="pipecg",
                    flight=FlightConfig.for_solve(80))
        rec = FlightRecord.from_buffer(res.flight)
        _, _, kappa = estimate_condition(rec)
        assert kappa == pytest.approx(100.0, rel=0.05)

    def test_condition_estimate_needs_stride1_alpha_beta(self):
        from cuda_mpi_parallel_tpu.telemetry.health import (
            estimate_condition,
        )

        # NaN alpha/beta columns (a from_history record) cannot support
        # the tridiagonal: the estimate must decline, not guess
        rec = self._record(10.0 ** -np.arange(20.0))
        assert estimate_condition(rec) == (None, None, None)

    def test_classify_converged_wins(self):
        from cuda_mpi_parallel_tpu.telemetry.health import classify_trace

        rec = self._record([1.0, 0.1, 0.01, 0.001])
        cls, _, _, msg = classify_trace(rec, converged=True)
        assert cls == CGStatus.CONVERGED and msg == "converged"

    def test_classify_stagnation(self):
        from cuda_mpi_parallel_tpu.telemetry.health import classify_trace

        # decays two decades then flatlines for 60 iterations
        res = np.concatenate([10.0 ** -np.arange(0, 2, 0.1),
                              np.full(60, 1e-2)])
        res *= 1.0 + 1e-4 * np.sin(np.arange(res.shape[0]))  # noise
        cls, rate, plateau, msg = classify_trace(rec := self._record(res),
                                                 converged=False)
        assert cls == CGStatus.STAGNATED
        assert abs(rate) < 1e-3
        assert "flatlined" in msg

    def test_classify_divergence(self):
        from cuda_mpi_parallel_tpu.telemetry.health import classify_trace

        res = np.concatenate([10.0 ** -np.arange(0, 3, 0.5),
                              10.0 ** np.arange(-3, 1, 0.5)])
        cls, _, plateau, msg = classify_trace(self._record(res),
                                              converged=False)
        assert cls == CGStatus.DIVERGED
        assert "grew" in msg

    def test_classify_maxiter_still_converging(self):
        from cuda_mpi_parallel_tpu.telemetry.health import classify_trace

        res = 10.0 ** (-0.05 * np.arange(100.0))  # healthy steady decay
        cls, rate, _, msg = classify_trace(self._record(res),
                                           converged=False)
        assert cls == CGStatus.MAXITER
        assert rate == pytest.approx(-0.05, rel=1e-6)
        assert "still converging" in msg

    def test_stagnating_f32_solve_yields_noncconverged_health(self):
        """ISSUE acceptance: a stagnating system (f32 attainable-
        accuracy floor, kappa ~ 1e8) yields a solve_health event with a
        non-CONVERGED classification through the PR-2 stack."""
        import jax.numpy as jnp

        from cuda_mpi_parallel_tpu.solver.cg import solve
        from cuda_mpi_parallel_tpu.telemetry import session
        from cuda_mpi_parallel_tpu.telemetry.flight import (
            FlightConfig,
            FlightRecord,
        )
        from cuda_mpi_parallel_tpu.telemetry.health import (
            assess_solve_health,
        )

        eigs = np.logspace(0, -8, 48)            # kappa = 1e8 in f32
        a = jnp.asarray(np.diag(eigs).astype(np.float32))
        b = jnp.ones(48, jnp.float32)
        res = solve(a, b, tol=1e-12, maxiter=400,
                    flight=FlightConfig.for_solve(400))
        assert not bool(res.converged)           # the floor is real
        rec = FlightRecord.from_buffer(res.flight)
        health = assess_solve_health(
            rec, converged=bool(res.converged), status=int(res.status),
            iterations=int(res.iterations))
        assert health.classification in (CGStatus.STAGNATED,
                                         CGStatus.DIVERGED)
        assert health.classification != CGStatus.CONVERGED
        with events.capture() as buf:
            with session.observe_solve("stagnation probe",
                                       engine="general") as obs:
                obs.finish(res, elapsed_s=0.1, health=health)
        lines = [json.loads(ln) for ln in buf.getvalue().splitlines()]
        hl = [ln for ln in lines if ln["event"] == "solve_health"]
        assert len(hl) == 1
        assert hl[0]["classification"] == health.classification.name
        assert hl[0]["converged"] is False
        events.validate_event(hl[0])
        # the verdict rides the solve_end payload too
        end = [ln for ln in lines if ln["event"] == "solve_end"][-1]
        assert end["health"]["classification"] == \
            health.classification.name

    def test_emit_solve_health_sets_gauges(self):
        from cuda_mpi_parallel_tpu.telemetry.health import (
            assess_solve_health,
            emit_solve_health,
        )

        rec = self._record(10.0 ** (-0.05 * np.arange(100.0)))
        health = assess_solve_health(rec, converged=False)
        emit_solve_health(health, engine="general")
        snap = REGISTRY.snapshot()
        series = snap["solve_residual_decay_rate"]["series"]
        mine = [s for s in series
                if s["labels"].get("engine") == "general"]
        assert mine and mine[0]["value"] == pytest.approx(-0.05,
                                                          rel=1e-6)

    def test_healthy_solve_health_in_iteration_histogram(self):
        """observe_solve feeds the per-solve iteration histogram (the
        PR-3 metrics satellite)."""
        from cuda_mpi_parallel_tpu.telemetry.session import solve_metrics

        class R:
            iterations = 37
            converged = True
            residual_norm = 1e-8

            @staticmethod
            def status_enum():
                return CGStatus.CONVERGED

            residual_history = None

        before = REGISTRY.snapshot().get(
            "solve_iterations_per_solve", {"series": []})
        with events.capture():
            with session.observe_solve("hist probe",
                                       engine="general") as obs:
                obs.finish(R())
        snap = REGISTRY.snapshot()["solve_iterations_per_solve"]
        series = [s for s in snap["series"]
                  if s["labels"].get("engine") == "general"]
        assert series and series[0]["count"] >= 1
