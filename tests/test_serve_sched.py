"""Multi-tenant serving: admission control, weighted-fair dispatch,
and the shed-before-collapse ladder (serve.admission / serve.sched).

Policy tests drive the service in MANUAL mode with a fake clock (no
worker thread, time advances only when the test says so), so every
token-refill, ladder-transition and scheduler branch is deterministic.
The threaded tests at the bottom cover what a fake clock cannot: lost
wakeups, multi-worker dispatch, and drain()/close() under submitter
concurrency.
"""
import json
import threading

import numpy as np
import pytest

from cuda_mpi_parallel_tpu.models import poisson
from cuda_mpi_parallel_tpu.serve import (
    AdmissionConfig,
    AdmissionController,
    MicroBatchQueue,
    QueueFull,
    RecyclePolicy,
    RetryPolicy,
    SchedConfig,
    ServiceConfig,
    ShedConfig,
    ShedLadder,
    SLOClass,
    SolverService,
    TokenBucket,
    WeightedFairScheduler,
    WorkloadRequest,
    load_workload,
    save_workload,
    synthetic_poisson,
    synthetic_tenant_mix,
)
from cuda_mpi_parallel_tpu.serve.queue import QueuedRequest
from cuda_mpi_parallel_tpu.telemetry import events


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> float:
        self.t += dt
        return self.t


def manual_service(**kw):
    clock = FakeClock()
    kw.setdefault("max_batch", 4)
    kw.setdefault("max_wait_s", 0.010)
    kw.setdefault("maxiter", 500)
    svc = SolverService(ServiceConfig(clock=clock, **kw))
    return svc, clock


def poisson_csr(n=10, dtype=np.float64):
    return poisson.poisson_2d_csr(n, n, dtype=dtype)


def rhs_batch(a, count, seed=0):
    rng = np.random.default_rng(seed)
    return [np.asarray(a @ rng.standard_normal(a.shape[0]))
            for _ in range(count)]


# ---------------------------------------------------------------------------
# token buckets (pure, fake times)


class TestTokenBucket:
    def test_burst_then_rate_then_refill(self):
        ctl = AdmissionController(AdmissionConfig(
            default=TokenBucket(rate=10.0, burst=2)))
        d1 = ctl.admit("t", 0.0)
        d2 = ctl.admit("t", 0.0)
        assert d1.admitted and d2.admitted
        d3 = ctl.admit("t", 0.0)
        assert not d3.admitted and d3.reason == "tokens"
        # empty bucket refills at 1 token / 0.1 s
        assert d3.retry_after_s == pytest.approx(0.1)
        assert ctl.admit("t", 0.05).admitted is False
        assert ctl.admit("t", 0.101).admitted is True

    def test_burst_caps_banked_tokens(self):
        ctl = AdmissionController(AdmissionConfig(
            default=TokenBucket(rate=100.0, burst=3)))
        # a long-idle tenant banks at most `burst`
        assert ctl.tokens("t", 100.0) == pytest.approx(3.0)
        for _ in range(3):
            assert ctl.admit("t", 100.0).admitted
        assert not ctl.admit("t", 100.0).admitted

    def test_per_tenant_isolation_and_unmetered_default(self):
        ctl = AdmissionController(AdmissionConfig(
            default=None,
            tenants=(("hot", TokenBucket(rate=1.0, burst=1)),)))
        assert ctl.admit("hot", 0.0).admitted
        assert not ctl.admit("hot", 0.0).admitted
        # unlisted tenants are unmetered when default is None
        for _ in range(50):
            assert ctl.admit("other", 0.0).admitted
        assert ctl.tokens("other", 0.0) is None

    def test_validation(self):
        with pytest.raises(ValueError):
            TokenBucket(rate=0.0, burst=1)
        with pytest.raises(ValueError):
            TokenBucket(rate=1.0, burst=0)


# ---------------------------------------------------------------------------
# deficit round-robin (pure)


class TestWeightedFairScheduler:
    def _flow(self, tenant, cls="silver", handle="h"):
        return (handle, tenant, cls)

    def test_equal_weights_round_robin(self):
        sched = WeightedFairScheduler(SchedConfig())
        a, b = self._flow("a"), self._flow("b")
        cands = {a: 1.0, b: 1.0}
        picks = [sched.pick(cands) for _ in range(6)]
        assert picks == [a, b, a, b, a, b]

    def test_weight_ratio_is_dispatch_share(self):
        sched = WeightedFairScheduler(SchedConfig())
        gold = self._flow("t", "gold")
        bulk = self._flow("t", "bulk")
        cands = {bulk: 1.0, gold: 1.0}     # bulk registered first
        picks = [sched.pick(cands) for _ in range(90)]
        n_gold = sum(1 for p in picks if p == gold)
        n_bulk = len(picks) - n_gold
        # 8:1 weights -> 8:1 dispatches (exact over whole rotations)
        assert n_gold / n_bulk == pytest.approx(8.0, rel=0.15)

    def test_starvation_bound(self):
        """A backlogged min-weight flow dispatches at least once per
        ceil(w_max / w_min) + 1 rotations - the class bound the
        10:1-hot-tenant acceptance rides on."""
        sched = WeightedFairScheduler(SchedConfig())
        gold = self._flow("hot", "gold")
        bulk = self._flow("cold", "bulk")
        cands = {gold: 1.0, bulk: 1.0}
        gap, worst = 0, 0
        for _ in range(200):
            if sched.pick(cands) == bulk:
                worst, gap = max(worst, gap), 0
            else:
                gap += 1
        assert worst <= 9, f"bulk starved for {worst} consecutive picks"

    def test_idle_flow_deficit_resets(self):
        """A flow absent from the candidates loses its banked credit -
        a quiet tenant cannot hoard and then burst past everyone."""
        sched = WeightedFairScheduler(SchedConfig())
        a, b = self._flow("a"), self._flow("b")
        sched.pick({a: 1.0})
        sched.pick({a: 1.0})
        # b was never a candidate: joining now starts from zero
        assert sched.pick({a: 1.0, b: 1.0}) in (a, b)
        assert all(v < 2.0 for v in sched.deficits().values())

    def test_tenant_weight_multiplier(self):
        sched = WeightedFairScheduler(SchedConfig(
            tenant_weights=(("vip", 4.0),)))
        vip = self._flow("vip")
        std = self._flow("std")
        picks = [sched.pick({std: 1.0, vip: 1.0}) for _ in range(50)]
        n_vip = sum(1 for p in picks if p == vip)
        assert n_vip / (len(picks) - n_vip) == pytest.approx(4.0,
                                                            rel=0.2)

    def test_config_validation(self):
        with pytest.raises(ValueError):
            SchedConfig(classes=(SLOClass("a"), SLOClass("a")))
        with pytest.raises(ValueError):
            SchedConfig(tenant_weights=(("t", 0.0),))
        with pytest.raises(ValueError):
            SLOClass("x", weight=-1.0)


# ---------------------------------------------------------------------------
# shed ladder (pure)


class TestShedLadderUnit:
    def test_transitions_and_hysteresis(self):
        ladder = ShedLadder(ShedConfig(degrade_depth=4, defer_depth=8,
                                       reject_depth=12))
        assert not ladder.evaluate(3)
        assert ladder.evaluate(4) and ladder.level == 1
        assert ladder.evaluate(9) and ladder.level == 2
        assert ladder.evaluate(30) and ladder.level == 3
        # descent is hysteretic: a held level only drops once depth
        # clears exit_fraction x its ENTRY threshold (12 * 0.5 = 6)
        assert not ladder.evaluate(7)          # 7 > 6: still reject
        assert ladder.evaluate(6) and ladder.level == 2
        assert not ladder.evaluate(5)          # 5 > 8 * 0.5: held
        assert ladder.evaluate(4) and ladder.level == 1
        assert ladder.evaluate(1) and ladder.level == 0
        assert ladder.transitions == 6

    def test_disabled_rungs(self):
        ladder = ShedLadder(ShedConfig(degrade_depth=2))
        ladder.evaluate(100)
        assert ladder.level == 1               # defer/reject off

    def test_auto_thresholds_from_capacity(self):
        cfg = ShedConfig(auto=True, horizon_s=0.25)
        assert cfg.thresholds(None) == (None, None, None)
        assert cfg.thresholds(40.0) == (10, 20, 40)
        # explicit depth wins over the derivation
        cfg2 = ShedConfig(degrade_depth=3, auto=True, horizon_s=0.25)
        assert cfg2.thresholds(40.0) == (3, 20, 40)

    def test_misordered_depths_refused(self):
        with pytest.raises(ValueError):
            ShedConfig(degrade_depth=8, defer_depth=4)


# ---------------------------------------------------------------------------
# admission at the service level (fake clock)


class TestServiceAdmission:
    def test_typed_rejection_with_retry_hint(self):
        svc, clock = manual_service(admission=AdmissionConfig(
            default=TokenBucket(rate=10.0, burst=2)))
        a = poisson_csr()
        h = svc.register(a)
        bs = rhs_batch(a, 3, seed=1)
        with events.capture() as buf:
            f1 = svc.submit(h, bs[0], tol=1e-8)
            f2 = svc.submit(h, bs[1], tol=1e-8)
            f3 = svc.submit(h, bs[2], tol=1e-8)
            res = f3.result(timeout=1)     # resolved immediately
            assert res.status == "ADMISSION_REJECTED"
            assert res.failure_kind == "admission"
            assert res.retry_after_s == pytest.approx(0.1)
            assert res.x is None and not res.converged
            # refill on the service clock: the same tenant is welcome
            # again after 1/rate seconds
            clock.advance(0.101)
            f4 = svc.submit(h, bs[2], tol=1e-8)
            svc.drain()
            assert f1.result().converged and f2.result().converged
            assert f4.result().converged
        recs = [json.loads(ln) for ln in buf.getvalue().splitlines()
                if ln.strip()]
        for rec in recs:
            events.validate_event(rec)
        rej = [r for r in recs if r["event"] == "admission"]
        assert len(rej) == 1 and rej[0]["decision"] == "rejected"
        assert rej[0]["reason"] == "tokens"
        stats = svc.stats()
        assert stats["shed"]["admission_rejected"] == 1
        assert stats["tenants"]["default"]["rejected"] == 1
        svc.close()

    def test_per_tenant_buckets_do_not_interfere(self):
        svc, clock = manual_service(admission=AdmissionConfig(
            tenants=(("hot", TokenBucket(rate=1.0, burst=1)),)))
        a = poisson_csr()
        h = svc.register(a)
        bs = rhs_batch(a, 4, seed=2)
        assert svc.submit(h, bs[0], tenant="hot") is not None
        r = svc.submit(h, bs[1], tenant="hot").result()
        assert r.status == "ADMISSION_REJECTED" and r.tenant == "hot"
        # the unmetered tenant is untouched by hot's exhaustion
        f = svc.submit(h, bs[2], tenant="quiet")
        svc.drain()
        assert f.result().converged
        svc.close()

    def test_unknown_slo_class_refused(self):
        svc, _ = manual_service()
        a = poisson_csr()
        h = svc.register(a)
        with pytest.raises(ValueError, match="SLO class"):
            svc.submit(h, np.ones(a.shape[0]), slo_class="platinum")
        svc.close()


# ---------------------------------------------------------------------------
# shed ladder at the service level (fake clock)


class TestServiceShedLadder:
    def test_defer_holds_bulk_until_pressure_clears(self):
        """Level 2 holds an aged bulk queue while silver drains; the
        ladder's descent mid-pass releases it - and a drain() flushes
        it regardless (close() must terminate)."""
        # degrade rung OFF so the silver requests keep one tol class
        # (degradation would split them across two queues); this test
        # is about the defer rung alone
        svc, clock = manual_service(
            shed=ShedConfig(defer_depth=3, reject_depth=50))
        a = poisson_csr()
        h = svc.register(a)
        bs = rhs_batch(a, 6, seed=3)
        with events.capture() as buf:
            fb = svc.submit(h, bs[0], tol=1e-8, slo_class="bulk")
            clock.advance(0.005)
            fs = [svc.submit(h, b, tol=1e-8) for b in bs[1:4]]
            # t=0.011: bulk is aged past max_wait but depth 4 >= 3
            # holds it; silver (3 < max_batch) is still young ->
            # NOTHING dispatches
            clock.advance(0.006)
            assert svc.pump() == 0
            assert svc.queue_depth() == 4
            assert not fb.done()
            # t=0.016: silver aged -> dispatches; depth falls, the
            # ladder descends mid-pass and releases bulk IN THE SAME
            # pump
            clock.advance(0.005)
            assert svc.pump() == 2
        assert fb.result(timeout=1).converged
        assert all(f.result().converged for f in fs)
        recs = [json.loads(ln) for ln in buf.getvalue().splitlines()
                if ln.strip()]
        defer = [r for r in recs if r["event"] == "sched_dispatch"
                 and r["decision"] == "defer"]
        assert defer and defer[0]["slo_class"] == "bulk"
        # dispatch order: silver first (bulk was held), bulk second
        disp = [r for r in recs if r["event"] == "batch_dispatch"
                and r.get("phase") != "warmup"]
        assert len(disp) == 2
        log = svc.batch_log()
        assert len(log[0]["request_ids"]) == 3      # the silver batch
        assert fb.result().request_id in log[1]["request_ids"]
        svc.close()

    def test_ladder_orders_degrade_defer_reject(self):
        """The ordering contract on one fake clock: tolerance widens
        first, bulk defers second, rejection is last - and gold is
        admitted at every level, undegraded."""
        svc, clock = manual_service(
            shed=ShedConfig(degrade_depth=2, defer_depth=4,
                            reject_depth=6))
        a = poisson_csr()
        h = svc.register(a)
        bs = rhs_batch(a, 10, seed=4)
        f0 = svc.submit(h, bs[0], tol=1e-8)            # depth 0
        f1 = svc.submit(h, bs[1], tol=1e-8)            # depth 1
        f2 = svc.submit(h, bs[2], tol=1e-8)            # depth 2: degrade
        f3 = svc.submit(h, bs[3], tol=1e-8, slo_class="bulk")
        f4 = svc.submit(h, bs[4], tol=1e-8)            # depth 4: defer on
        f5 = svc.submit(h, bs[5], tol=1e-8)
        r6 = svc.submit(h, bs[6], tol=1e-8).result()   # depth 6: reject
        assert r6.status == "ADMISSION_REJECTED"
        assert r6.retry_after_s and r6.retry_after_s > 0
        gold = svc.submit(h, bs[7], tol=1e-8, slo_class="gold")
        clock.advance(0.011)
        svc.pump()
        svc.drain()
        assert not f0.result().degraded and not f1.result().degraded
        assert f2.result().degraded and f4.result().degraded
        assert f3.result().degraded          # bulk degrades too
        gr = gold.result()
        assert gr.converged and not gr.degraded
        assert f5.result().converged
        stats = svc.stats()
        assert stats["shed"]["level"] == 0   # descended after drain
        assert stats["classes"]["gold"]["in_slo"] == 1
        svc.close()

    def test_all_bulk_backlog_is_never_wedged(self):
        """Deferral is a RELATIVE priority: with nothing non-deferred
        queued or in flight, holding an all-bulk backlog would serve
        nobody and - with no deadlines to expire - wedge it forever
        (depth can only fall by dispatching, and the ladder can only
        descend when depth falls).  The hold must release."""
        svc, clock = manual_service(
            shed=ShedConfig(defer_depth=2, reject_depth=50))
        a = poisson_csr()
        h = svc.register(a)
        futs = [svc.submit(h, b, tol=1e-8, slo_class="bulk")
                for b in rhs_batch(a, 3, seed=15)]
        clock.advance(0.011)
        assert svc.pump() >= 1, "all-bulk backlog wedged by defer rung"
        assert all(f.result(timeout=1).converged for f in futs)
        svc.close()

    def test_all_bulk_backlog_resolves_threaded(self):
        """The same invariant end-to-end on the real-clock worker: an
        all-bulk backlog past the defer depth resolves without any
        follow-up submit to nudge the worker."""
        a = poisson_csr(8)
        svc = SolverService(ServiceConfig(
            max_batch=2, max_wait_s=0.005, maxiter=300,
            shed=ShedConfig(defer_depth=1, reject_depth=50)))
        try:
            h = svc.register(a)
            futs = [svc.submit(h, b, tol=1e-6, slo_class="bulk")
                    for b in rhs_batch(a, 3, seed=16)]
            results = [f.result(timeout=20) for f in futs]
            assert all(r.converged for r in results)
        finally:
            svc.close()

    def test_custom_class_table_reject_exemption(self):
        """The reject rung keys off SLOClass.reject_exempt, not the
        literal name 'gold' - a custom class table keeps its top tier
        admitted at level 3."""
        classes = (SLOClass("platinum", weight=16.0, degrade_ok=False,
                            defer_ok=False, reject_exempt=True),
                   SLOClass("economy", weight=1.0, degrade_ok=True,
                            defer_ok=True))
        svc, clock = manual_service(
            sched=SchedConfig(classes=classes),
            shed=ShedConfig(reject_depth=2))
        a = poisson_csr()
        h = svc.register(a)
        bs = rhs_batch(a, 4, seed=17)
        svc.submit(h, bs[0], tol=1e-8, slo_class="economy")
        svc.submit(h, bs[1], tol=1e-8, slo_class="economy")
        # depth 2 = reject level: economy refused, platinum admitted
        rej = svc.submit(h, bs[2], tol=1e-8, slo_class="economy")
        assert rej.result().status == "ADMISSION_REJECTED"
        plat = svc.submit(h, bs[3], tol=1e-8, slo_class="platinum")
        clock.advance(0.011)
        svc.pump()
        svc.drain()
        assert plat.result(timeout=1).converged
        svc.close()

    def test_legacy_degrade_depth_maps_to_ladder(self):
        """PR 12's ServiceConfig(degrade_depth=N) is the ladder's
        first rung - same observable behavior, no second code path."""
        svc, clock = manual_service(degrade_depth=2, max_batch=8,
                                    max_wait_s=100.0)
        a = poisson_csr()
        h = svc.register(a)
        bs = rhs_batch(a, 3, seed=5)
        f1 = svc.submit(h, bs[0], tol=1e-9)
        f2 = svc.submit(h, bs[1], tol=1e-9)
        f3 = svc.submit(h, bs[2], tol=1e-9)
        svc._step(svc._clock(), drain=True)
        assert not f1.result(5).degraded
        assert not f2.result(5).degraded
        assert f3.result(5).degraded
        assert svc.stats()["degraded"] == 1
        svc.close()

    def test_conflicting_shed_and_degrade_depth_refused(self):
        with pytest.raises(ValueError, match="degrade"):
            SolverService(ServiceConfig(
                clock=FakeClock(), degrade_depth=3,
                shed=ShedConfig(defer_depth=8)))


# ---------------------------------------------------------------------------
# weighted-fair dispatch at the service level (fake clock)


class TestServiceFairness:
    def test_hot_tenant_cannot_starve_cold_tenant(self):
        """10:1 offered load: the cold tenant's lone request is
        dispatched second (one hot batch ahead at equal weights),
        not behind the hot tenant's whole backlog - the starvation
        bound the DRR scheduler guarantees."""
        svc, clock = manual_service()
        a = poisson_csr()
        h = svc.register(a)
        hot = rhs_batch(a, 10, seed=6)
        cold = rhs_batch(a, 1, seed=7)
        hot_futs = [svc.submit(h, b, tol=1e-8, tenant="hot")
                    for b in hot]
        cold_fut = svc.submit(h, cold[0], tol=1e-8, tenant="cold")
        clock.advance(0.011)
        svc.pump()
        svc.drain()
        assert cold_fut.result().converged
        assert all(f.result().converged for f in hot_futs)
        log = svc.batch_log()
        cold_rid = cold_fut.result().request_id
        cold_pos = next(i for i, b in enumerate(log)
                        if cold_rid in b["request_ids"])
        assert cold_pos <= 1, \
            f"cold tenant's batch dispatched {cold_pos + 1}th of " \
            f"{len(log)} - starved behind the hot backlog"
        svc.close()

    def test_gold_class_preempts_bulk_backlog(self):
        """Class weights: a full gold batch dispatches before a bulk
        backlog that arrived FIRST."""
        svc, clock = manual_service()
        a = poisson_csr()
        h = svc.register(a)
        bulk = [svc.submit(h, b, tol=1e-8, slo_class="bulk")
                for b in rhs_batch(a, 8, seed=8)]
        gold = [svc.submit(h, b, tol=1e-8, slo_class="gold")
                for b in rhs_batch(a, 4, seed=9)]
        clock.advance(0.011)
        svc.pump()
        [f.result() for f in bulk + gold]
        log = svc.batch_log()
        gold_rid = gold[0].result().request_id
        assert gold_rid in log[0]["request_ids"], \
            "gold batch did not dispatch first"
        svc.close()

    def test_all_off_matches_legacy_pop_bit_for_bit(self):
        """The acceptance compat proof: one tenant, no admission, no
        shed - the weighted-fair service replays a mixed-tol workload
        with IDENTICAL batch composition, dispatch order, and
        bit-identical solutions to the PR 10 pop
        (SchedConfig(fair=False))."""
        a = poisson_csr(10)
        workload = synthetic_poisson(12, 3000.0, seed=11)
        rng = np.random.default_rng(12)
        bs = [np.asarray(a @ rng.standard_normal(a.shape[0]))
              for _ in workload]
        tols = [1e-8 if i % 3 else 1e-5 for i in range(len(workload))]

        def replay(fair):
            svc, clock = manual_service(
                sched=SchedConfig(fair=fair))
            h = svc.register(a)
            futs = []
            for r, b, tol in zip(workload, bs, tols):
                clock.t = r.t
                futs.append(svc.submit(h, b, tol=tol))
                svc.pump()
            clock.advance(0.011)
            svc.pump()
            svc.drain()
            results = [f.result() for f in futs]
            log = [(e["bucket"], e["n_requests"],
                    tuple(e["request_ids"])) for e in svc.batch_log()]
            svc.close()
            return results, log

        fair_res, fair_log = replay(True)
        legacy_res, legacy_log = replay(False)
        assert fair_log == legacy_log
        for rf, rl in zip(fair_res, legacy_res):
            assert rf.status == rl.status == "CONVERGED"
            assert rf.iterations == rl.iterations
            assert np.array_equal(rf.x, rl.x)


# ---------------------------------------------------------------------------
# workload files: tenant/slo_class fields


class TestWorkloadTenants:
    def test_roundtrip_with_tenant_fields(self, tmp_path):
        path = str(tmp_path / "wl.json")
        reqs = [WorkloadRequest(t=0.0, seed=1),
                WorkloadRequest(t=0.5, seed=2, tol=1e-5,
                                deadline_s=0.25, tenant="hot",
                                slo_class="bulk")]
        save_workload(path, reqs)
        assert load_workload(path) == reqs
        # the untagged request stays None -> replay defaults apply
        assert load_workload(path)[0].tenant is None

    def test_pre_multitenant_file_still_loads(self, tmp_path):
        path = str(tmp_path / "old.json")
        with open(path, "w") as f:
            json.dump({"version": 1,
                       "requests": [{"t": 0.0, "seed": 3}]}, f)
        (req,) = load_workload(path)
        assert req.tenant is None and req.slo_class is None

    def test_tenant_mix_deterministic_and_shared(self):
        tenants = (("hot", 10.0, "bulk"), ("web", 4.0, "silver"),
                   ("pay", 1.0, "gold"))
        w1 = synthetic_tenant_mix(64, 1000.0, tenants, seed=5)
        w2 = synthetic_tenant_mix(64, 1000.0, tenants, seed=5)
        assert w1 == w2                      # replay determinism
        names = {r.tenant for r in w1}
        assert names <= {"hot", "web", "pay"}
        hot = sum(1 for r in w1 if r.tenant == "hot")
        assert hot > len(w1) // 2            # 10/15 share dominates
        assert all(r.slo_class == "bulk" for r in w1
                   if r.tenant == "hot")
        with pytest.raises(ValueError):
            synthetic_tenant_mix(4, 100.0, ())
        with pytest.raises(ValueError):
            synthetic_tenant_mix(4, 100.0, (("t", 0.0, "silver"),))

    def test_synthetic_poisson_tags_passthrough(self):
        w = synthetic_poisson(4, 100.0, seed=1, tenant="t",
                              slo_class="gold")
        assert all(r.tenant == "t" and r.slo_class == "gold"
                   for r in w)


# ---------------------------------------------------------------------------
# parked-retry wake regression (the PR 12 next_wake fold, pinned)


class TestParkedRetryWake:
    def _req(self, i, t, ready_t=None, deadline_t=None):
        from concurrent.futures import Future

        return QueuedRequest(request_id=f"r{i}", handle_key="h",
                             b=np.zeros(3), dtype="float64", tol=1e-7,
                             enqueue_t=t, deadline_t=deadline_t,
                             future=Future(), ready_t=ready_t)

    def test_parked_ready_t_drives_next_wake(self):
        """A queue holding ONLY a backoff-parked retry must wake at
        its ready_t - not sleep forever until the next unrelated
        submit (the oversleep this regression test pins)."""
        q = MicroBatchQueue(max_batch=4, max_wait_s=0.010)
        q.push(self._req(0, t=0.0, ready_t=5.0))
        assert q.next_wake(1.0) == pytest.approx(5.0)
        # a deadline earlier than the backoff still wins
        q.push(self._req(1, t=0.0, ready_t=5.0, deadline_t=2.0))
        assert q.next_wake(1.0) == pytest.approx(2.0)

    def test_deferred_queue_still_wakes_for_deadline_and_ready_t(self):
        q = MicroBatchQueue(max_batch=4, max_wait_s=0.010)
        req = self._req(0, t=0.0, ready_t=5.0, deadline_t=2.0)
        req.slo_class = "bulk"
        q.push(req)
        # held by the shed ladder: no max_wait wake, but the deadline
        # sweep and the parked retry must still fire on time
        assert q.next_wake(1.0, defer=frozenset({"bulk"})) \
            == pytest.approx(2.0)
        aged = self._req(1, t=0.0)
        aged.slo_class = "bulk"
        q2 = MicroBatchQueue(max_batch=4, max_wait_s=0.010)
        q2.push(aged)
        assert q2.next_wake(1.0) == pytest.approx(0.010)
        assert q2.next_wake(1.0, defer=frozenset({"bulk"})) is None

    def test_threaded_worker_wakes_for_retry_backoff(self):
        """End-to-end: an idle real-clock worker resolves a parked
        retry within its backoff window, with no follow-up submit to
        nudge it."""
        import time

        a = poisson_csr(8)
        svc = SolverService(ServiceConfig(
            max_batch=2, max_wait_s=0.005,
            retry=RetryPolicy(max_retries=1, backoff_s=0.15)))
        try:
            h = svc.register(a)
            orig, calls = svc._engine, [0]

            def flaky(*args, **kw):
                calls[0] += 1
                if calls[0] == 1:
                    raise RuntimeError("boom")
                return orig(*args, **kw)

            svc._engine = flaky
            b = np.asarray(a @ np.random.default_rng(0)
                           .standard_normal(a.shape[0]))
            t0 = time.monotonic()
            res = svc.submit(h, b, tol=1e-8).result(timeout=10)
            elapsed = time.monotonic() - t0
            assert res.status == "CONVERGED" and res.attempts == 2
            assert 0.15 <= elapsed < 5.0, \
                f"retry resolved after {elapsed:.3f}s (backoff 0.15s)"
        finally:
            svc.close()


# ---------------------------------------------------------------------------
# multi-worker pool + threaded concurrency stress


class TestMultiWorker:
    def test_two_workers_end_to_end(self):
        svc = SolverService(ServiceConfig(
            max_batch=2, max_wait_s=0.002, maxiter=500, workers=2))
        try:
            a = poisson_csr()
            h = svc.register(a)
            rng = np.random.default_rng(13)
            futs = [svc.submit(h, np.asarray(
                a @ rng.standard_normal(a.shape[0])), tol=1e-8)
                for _ in range(8)]
            results = [f.result(timeout=30) for f in futs]
            assert all(r.converged for r in results)
            assert svc.stats()["completed"] == 8
        finally:
            svc.close()

    def test_recycle_refuses_worker_pool(self):
        with pytest.raises(ValueError, match="workers"):
            SolverService(ServiceConfig(workers=2,
                                        recycle=RecyclePolicy()))

    def test_negative_workers_refused(self):
        with pytest.raises(ValueError, match="workers"):
            SolverService(ServiceConfig(clock=FakeClock(), workers=-1))


class TestThreadedStress:
    def test_concurrent_submitters_small_queue_all_typed(self):
        """4 submitter threads against a tiny queue_limit + admission
        metering: every future resolves to a TYPED result, nothing
        deadlocks, and the books balance (no lost wakeups, no lost
        requests)."""
        a = poisson_csr(8)
        svc = SolverService(ServiceConfig(
            max_batch=4, max_wait_s=0.001, queue_limit=8, maxiter=300,
            workers=2,
            admission=AdmissionConfig(
                default=TokenBucket(rate=2000.0, burst=40)),
            shed=ShedConfig(degrade_depth=4, defer_depth=6,
                            reject_depth=8)))
        per_thread, n_threads = 15, 4
        outcomes, queue_full = [], [0]
        lock = threading.Lock()
        try:
            h = svc.register(a)
            b = np.asarray(a @ np.random.default_rng(1)
                           .standard_normal(a.shape[0]))

            def submitter(tid):
                classes = ("gold", "silver", "bulk")
                for i in range(per_thread):
                    try:
                        fut = svc.submit(
                            h, b, tol=1e-6, tenant=f"t{tid}",
                            slo_class=classes[i % 3])
                    except QueueFull:
                        with lock:
                            queue_full[0] += 1
                        continue
                    res = fut.result(timeout=30)
                    with lock:
                        outcomes.append(res.status)

            threads = [threading.Thread(target=submitter, args=(i,))
                       for i in range(n_threads)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60)
            assert not any(t.is_alive() for t in threads), \
                "submitter thread wedged (lost wakeup or deadlock)"
            svc.drain()
            assert svc.queue_depth() == 0
        finally:
            svc.close()                     # close() must not deadlock
        assert len(outcomes) + queue_full[0] \
            == per_thread * n_threads
        assert outcomes, "no request ever resolved"
        allowed = {"CONVERGED", "MAXITER", "ADMISSION_REJECTED"}
        assert set(outcomes) <= allowed, set(outcomes)
        # the stress must actually solve things, not just shed
        assert outcomes.count("CONVERGED") >= per_thread


# ---------------------------------------------------------------------------
# stats + report surface


class TestOverloadObservability:
    def test_stats_and_report_lines(self):
        from cuda_mpi_parallel_tpu.telemetry.report import service_lines

        svc, clock = manual_service(
            admission=AdmissionConfig(
                default=TokenBucket(rate=10.0, burst=3)),
            shed=ShedConfig(degrade_depth=2, defer_depth=50,
                            reject_depth=60))
        a = poisson_csr()
        h = svc.register(a)
        bs = rhs_batch(a, 4, seed=14)
        futs = [svc.submit(h, bs[i], tol=1e-8,
                           tenant=("hot" if i < 3 else "cold"),
                           slo_class=("gold" if i == 3 else "silver"))
                for i in range(3)]
        futs.append(svc.submit(h, bs[3], tol=1e-8, tenant="cold",
                               slo_class="gold"))
        rej = svc.submit(h, bs[0], tol=1e-8, tenant="hot")
        assert rej.result().status == "ADMISSION_REJECTED"
        clock.advance(0.011)
        svc.pump()
        svc.drain()
        [f.result() for f in futs]
        stats = svc.stats()
        assert stats["tenants"]["hot"]["submitted"] == 3
        assert stats["tenants"]["hot"]["rejected"] == 1
        assert stats["tenants"]["cold"]["completed"] == 1
        assert stats["classes"]["gold"]["in_slo"] == 1
        assert stats["classes"]["gold"]["p99_s"] is not None
        assert stats["shed"]["admission_rejected"] == 1
        text = "\n".join(service_lines(stats))
        assert "tenant" in text and "class" in text and "shed" in text
        svc.close()
