"""Robustness tiers from SURVEY SS4/SS5: jax_debug_nans runs (the 'race
detection / sanitizer' analogue - any NaN produced inside the jitted solve
raises immediately), property-style sharded-vs-unsharded equivalence over
random shard counts, and the orbax checkpoint backend."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cuda_mpi_parallel_tpu import solve
from cuda_mpi_parallel_tpu.models import poisson
from cuda_mpi_parallel_tpu.models.operators import Stencil2D


class TestDebugNans:
    """The solver's guarded arithmetic (_safe_div, breakdown predicates)
    must never produce NaN on healthy paths - verified by running under
    jax_debug_nans, which raises on any NaN appearing in any primitive
    output."""

    def _with_debug_nans(self, fn):
        jax.config.update("jax_debug_nans", True)
        try:
            return fn()
        finally:
            jax.config.update("jax_debug_nans", False)

    def test_oracle_solve(self):
        a, b, x_exp = poisson.oracle_system()
        res = self._with_debug_nans(lambda: solve(a, b))
        assert bool(res.converged)
        np.testing.assert_allclose(np.asarray(res.x), x_exp, atol=1e-9)

    @pytest.mark.parametrize("method", ["cg", "cg1", "pipecg"])
    def test_methods_past_exact_convergence(self, method):
        """check_every blocks run iterations past an exact solve; the
        0/0 cases must freeze, not NaN (quirk-Q4 divergence)."""
        a, b, _ = poisson.oracle_system()
        res = self._with_debug_nans(
            lambda: solve(a, b, check_every=8, method=method))
        assert bool(res.converged)

    def test_multigrid_solve(self):
        op = poisson.poisson_2d_operator(16, 16, dtype=jnp.float64)
        from cuda_mpi_parallel_tpu.models.multigrid import (
            MultigridPreconditioner,
        )

        m = MultigridPreconditioner.from_operator(op)
        res = self._with_debug_nans(
            lambda: solve(op, jnp.ones(256), rtol=1e-8, tol=0.0,
                          maxiter=100, m=m))
        assert bool(res.converged)

    def test_resident_past_exact_convergence(self):
        """The resident kernel's in-SMEM freeze (_safe_div analogue)
        must hold under debug-NaNs too, including iterations running
        past an exact solve inside a check block."""
        from cuda_mpi_parallel_tpu import cg_resident

        nx, ny = 8, 128
        op = poisson.poisson_2d_operator(nx, ny, dtype=jnp.float32)
        x_true = np.zeros((nx, ny), np.float32)
        x_true[4, 64] = 1.0
        b = jnp.asarray(np.asarray(
            op.matvec(jnp.asarray(x_true.ravel()))).reshape(nx, ny))
        res = self._with_debug_nans(
            lambda: cg_resident(op, b, tol=1e-6, maxiter=200,
                                check_every=8, interpret=True))
        assert bool(res.converged)
        assert np.all(np.isfinite(np.asarray(res.x)))


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")
class TestShardCountInvariance:
    """Property tier (SURVEY SS4): the SAME system solved over 1, 2, 4 and
    8 shards must produce the same trajectory to rounding."""

    @pytest.mark.parametrize("n_shards", [2, 4, 8])
    def test_stencil_2d(self, n_shards):
        from cuda_mpi_parallel_tpu.parallel import make_mesh, solve_distributed

        n = 32
        a = Stencil2D.create(n, n, dtype=jnp.float64)
        x_true = np.random.default_rng(51).standard_normal(n * n)
        b = a @ jnp.asarray(x_true)
        single = solve(a, b, tol=0.0, rtol=1e-9, maxiter=400)
        dist = solve_distributed(a, b, mesh=make_mesh(n_shards), tol=0.0,
                                 rtol=1e-9, maxiter=400)
        assert bool(dist.converged)
        assert abs(int(dist.iterations) - int(single.iterations)) <= 1
        np.testing.assert_allclose(np.asarray(dist.x),
                                   np.asarray(single.x),
                                   rtol=1e-9, atol=1e-11)

    @pytest.mark.parametrize("n_shards", [2, 8])
    def test_csr_ring(self, n_shards):
        import scipy.sparse as sp

        from cuda_mpi_parallel_tpu.models.operators import CSRMatrix
        from cuda_mpi_parallel_tpu.parallel import make_mesh, solve_distributed

        n = 72
        m = sp.random(n, n, density=0.08,
                      random_state=np.random.RandomState(13), format="csr")
        m = m + m.T + sp.eye(n) * (np.abs(m).sum(axis=1).max() + 1.0)
        m = m.tocsr()
        m.sort_indices()
        a = CSRMatrix.from_scipy(m)
        x_true = np.random.default_rng(52).standard_normal(n)
        b = jnp.asarray(m @ x_true)
        single = solve(a, b, tol=0.0, rtol=1e-10, maxiter=400)
        dist = solve_distributed(a, b, mesh=make_mesh(n_shards), tol=0.0,
                                 rtol=1e-10, maxiter=400, csr_comm="ring")
        assert bool(dist.converged)
        assert abs(int(dist.iterations) - int(single.iterations)) <= 1
        np.testing.assert_allclose(np.asarray(dist.x), x_true, atol=1e-7)


class TestOrbaxCheckpoint:
    def test_roundtrip(self, tmp_path, rng):
        from cuda_mpi_parallel_tpu.utils import checkpoint as ckpt

        a = poisson.poisson_2d_operator(8, 8, dtype=jnp.float64)
        b = jnp.asarray(rng.standard_normal(64))
        res = solve(a, b, tol=0.0, rtol=1e-3, maxiter=50,
                    return_checkpoint=True)
        path = str(tmp_path / "orbax_ckpt")
        fp = ckpt.problem_fingerprint(a, b)
        ckpt.save_checkpoint_orbax(path, res.checkpoint, fingerprint=fp)
        loaded = ckpt.load_checkpoint_orbax(path, expect_fingerprint=fp)
        for field in ("x", "r", "p", "rho", "rr", "nrm0", "k",
                      "indefinite"):
            np.testing.assert_array_equal(
                np.asarray(getattr(loaded, field)),
                np.asarray(getattr(res.checkpoint, field)))

    def test_fingerprint_mismatch(self, tmp_path, rng):
        from cuda_mpi_parallel_tpu.utils import checkpoint as ckpt

        a = poisson.poisson_2d_operator(8, 8, dtype=jnp.float64)
        b = jnp.asarray(rng.standard_normal(64))
        res = solve(a, b, tol=0.0, rtol=1e-3, maxiter=50,
                    return_checkpoint=True)
        path = str(tmp_path / "orbax_ckpt")
        ckpt.save_checkpoint_orbax(path, res.checkpoint, fingerprint="aaaa")
        with pytest.raises(ValueError, match="different problem"):
            ckpt.load_checkpoint_orbax(path, expect_fingerprint="bbbb")

    def test_resume_continues_exact_trajectory(self, tmp_path, rng):
        """Orbax round-trip feeds resume_from and reproduces the
        uninterrupted trajectory bit-for-bit."""
        from cuda_mpi_parallel_tpu.utils import checkpoint as ckpt

        a = poisson.poisson_2d_operator(12, 12, dtype=jnp.float64)
        b = jnp.asarray(rng.standard_normal(144))
        full = solve(a, b, tol=0.0, rtol=1e-10, maxiter=400)
        part = solve(a, b, tol=0.0, rtol=1e-10, maxiter=400,
                     iter_cap=20, return_checkpoint=True)
        path = str(tmp_path / "orbax_ckpt")
        ckpt.save_checkpoint_orbax(path, part.checkpoint)
        loaded = ckpt.load_checkpoint_orbax(path)
        resumed = solve(a, b, tol=0.0, rtol=1e-10, maxiter=400,
                        resume_from=loaded)
        assert int(resumed.iterations) == int(full.iterations)
        np.testing.assert_array_equal(np.asarray(resumed.x),
                                      np.asarray(full.x))

    def test_solve_resumable_orbax_backend(self, tmp_path, rng):
        from cuda_mpi_parallel_tpu.utils import checkpoint as ckpt

        a = poisson.poisson_2d_operator(12, 12, dtype=jnp.float64)
        b = jnp.asarray(rng.standard_normal(144))
        path = str(tmp_path / "resume_dir")
        res = ckpt.solve_resumable(a, b, path, segment_iters=25,
                                   tol=0.0, rtol=1e-9, maxiter=500,
                                   backend="orbax")
        assert bool(res.converged)
        full = solve(a, b, tol=0.0, rtol=1e-9, maxiter=500)
        assert int(res.iterations) == int(full.iterations)
        assert not jnp.any(jnp.isnan(res.x))
        import os

        assert not os.path.exists(path)  # removed on convergence

    def test_solve_resumable_unknown_backend(self, tmp_path):
        from cuda_mpi_parallel_tpu.utils import checkpoint as ckpt

        a = poisson.poisson_2d_operator(4, 4, dtype=jnp.float64)
        with pytest.raises(ValueError, match="backend"):
            ckpt.solve_resumable(a, jnp.ones(16), str(tmp_path / "x"),
                                 backend="pickle")

    def test_backend_mismatch_clear_error(self, tmp_path, rng):
        from cuda_mpi_parallel_tpu.utils import checkpoint as ckpt

        a = poisson.poisson_2d_operator(8, 8, dtype=jnp.float64)
        b = jnp.asarray(rng.standard_normal(64))
        path = str(tmp_path / "ck")
        ckpt.solve_resumable(a, b, path, segment_iters=10, maxiter=20,
                             backend="orbax", keep_checkpoint=True)
        with pytest.raises(ValueError, match="orbax format"):
            ckpt.solve_resumable(a, b, path, segment_iters=10, maxiter=40)

    def test_restore_with_live_template(self, tmp_path, rng):
        """like= restores shards onto the current topology (no stale
        file shardings, no orbax warning)."""
        import warnings

        import jax as _jax

        from cuda_mpi_parallel_tpu.solver.cg import CGCheckpoint
        from cuda_mpi_parallel_tpu.utils import checkpoint as ckpt

        a = poisson.poisson_2d_operator(8, 8, dtype=jnp.float64)
        b = jnp.asarray(rng.standard_normal(64))
        res = solve(a, b, tol=0.0, rtol=1e-3, maxiter=50,
                    return_checkpoint=True)
        path = str(tmp_path / "ck_like")
        ckpt.save_checkpoint_orbax(path, res.checkpoint)
        z = jnp.zeros(64, jnp.float64)
        s = jnp.zeros((), jnp.float64)
        template = CGCheckpoint(x=z, r=z, p=z, rho=s, rr=s, nrm0=s,
                                k=jnp.zeros((), jnp.int32),
                                indefinite=jnp.zeros((), bool))
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            loaded = ckpt.load_checkpoint_orbax(path, like=template)
            sharding_warns = [x for x in w
                              if "harding" in str(x.message)]
        assert not sharding_warns
        np.testing.assert_array_equal(np.asarray(loaded.x),
                                      np.asarray(res.checkpoint.x))
