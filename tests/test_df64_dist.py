"""Distributed df64: f64-class CG over a virtual 8-device mesh.

The reference's f64 (``CUDA_R_64F``, ``CUDACG.cu:216``) x the repo-name's
promised MPI tier, realized as shard_map + psum + df64 halo exchange
(``parallel.df64``).  Load-bearing property, as for the f32 distributed
path: an N-device run is the same algorithm as the 1-device run -
iteration counts match and solutions agree to df64 rounding (the only
difference is psum summation order in the dots).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cuda_mpi_parallel_tpu.utils.compat import shard_map
from jax.sharding import PartitionSpec as P

from cuda_mpi_parallel_tpu import cg_df64
from cuda_mpi_parallel_tpu.models.operators import Stencil2D, Stencil3D
from cuda_mpi_parallel_tpu.ops import df64 as df
from cuda_mpi_parallel_tpu.parallel import make_mesh
from cuda_mpi_parallel_tpu.parallel.df64 import (
    DistStencilDF64,
    solve_distributed_df64,
)

pytestmark = [
    pytest.mark.skipif(len(jax.devices()) < 8,
                       reason="needs 8 (virtual) devices"),
    # df64 pair-arithmetic shard_map solves take minutes of XLA:CPU
    # compile+run per test on a small host - far past the tier-1 870s
    # budget (ROADMAP.md).  They run in the untimed full suite
    # (pytest tests/ without -m 'not slow').
    pytest.mark.slow,
]


class TestDistMatvecDF64:
    @pytest.mark.parametrize("grid,cls", [
        ((16, 5), Stencil2D), ((16, 5, 7), Stencil3D)])
    def test_sharded_matvec_equals_global(self, rng, grid, cls):
        """Sharded df64 SpMV == unsharded df64 SpMV, bitwise on both
        planes: the halo formulation runs the identical per-element EFT
        sequence."""
        mesh = make_mesh(8)
        scale = 1.7
        n = int(np.prod(grid))
        x64 = rng.standard_normal(n)
        xh, xl = (jnp.asarray(v) for v in df.split_f64(x64))
        fn = (df.stencil2d_matvec if cls is Stencil2D
              else df.stencil3d_matvec)
        want_h, want_l = jax.jit(
            lambda p: fn(p, grid, df.const(scale)))((xh, xl))

        local = DistStencilDF64.create(grid, 8, scale=scale)
        got_h, got_l = jax.jit(shard_map(
            lambda p: local.matvec_df(p), mesh=mesh,
            in_specs=(P("rows"),), out_specs=(P("rows"), P("rows"))))(
                (xh, xl))
        np.testing.assert_array_equal(np.asarray(got_h),
                                      np.asarray(want_h))
        np.testing.assert_array_equal(np.asarray(got_l),
                                      np.asarray(want_l))


class TestDistSolveDF64:
    def test_2d_trajectory_matches_single_device(self, rng):
        """Fixed-iteration trajectory parity: the 8-device run follows
        the 1-device residual history iterate for iterate (the dots'
        psum summation order contributes only ulp-level drift; histories
        are compared at their f32 storage resolution)."""
        nx = ny = 16
        a = Stencil2D.create(nx, ny, dtype=jnp.float32)
        op64 = Stencil2D.create(nx, ny, dtype=jnp.float64)
        x_true = rng.standard_normal(nx * ny)
        b = np.asarray(op64 @ jnp.asarray(x_true), dtype=np.float64)
        single = cg_df64(a, b, tol=0.0, maxiter=40, record_history=True)
        dist = solve_distributed_df64(a, b, mesh=make_mesh(8), tol=0.0,
                                      maxiter=40, record_history=True)
        np.testing.assert_allclose(
            np.asarray(dist.residual_history),
            np.asarray(single.residual_history), rtol=1e-4)
        np.testing.assert_allclose(dist.x(), single.x(), atol=1e-7)

    def test_2d_convergence_matches_single_device(self, rng):
        """At a sharp tolerance both runs converge with near-identical
        iteration counts (exact equality is not stable at f64-class
        depth: CG amplifies ulp-level perturbations)."""
        nx = ny = 16
        a = Stencil2D.create(nx, ny, dtype=jnp.float32)
        op64 = Stencil2D.create(nx, ny, dtype=jnp.float64)
        x_true = rng.standard_normal(nx * ny)
        b = np.asarray(op64 @ jnp.asarray(x_true), dtype=np.float64)
        single = cg_df64(a, b, tol=0.0, rtol=1e-9, maxiter=2000)
        dist = solve_distributed_df64(a, b, mesh=make_mesh(8), tol=0.0,
                                      rtol=1e-9, maxiter=2000)
        assert bool(single.converged) and bool(dist.converged)
        assert abs(int(dist.iterations) - int(single.iterations)) <= 5
        np.testing.assert_allclose(dist.x(), x_true, atol=1e-8)

    def test_3d_reaches_f64_depth(self, rng):
        """rtol 1e-11 on the north-star operator family - beyond plain
        f32's reach - over 8 shards."""
        grid = (16, 6, 5)
        a = Stencil3D.create(*grid, dtype=jnp.float32)
        op64 = Stencil3D.create(*grid, dtype=jnp.float64)
        x_true = rng.standard_normal(int(np.prod(grid)))
        b = np.asarray(op64 @ jnp.asarray(x_true), dtype=np.float64)
        r = solve_distributed_df64(a, b, mesh=make_mesh(8), tol=0.0,
                                   rtol=1e-11, maxiter=3000)
        assert bool(r.converged)
        np.testing.assert_allclose(r.x(), x_true, atol=1e-8)
        # threshold is rtol * ||r0||: converged means below it
        assert r.residual_norm() <= 1e-11 * np.linalg.norm(b) * 1.01

    def test_jacobi_and_check_every(self, rng):
        grid = (16, 12)
        a = Stencil2D.create(*grid, dtype=jnp.float32)
        op64 = Stencil2D.create(*grid, dtype=jnp.float64)
        x_true = rng.standard_normal(int(np.prod(grid)))
        b = np.asarray(op64 @ jnp.asarray(x_true), dtype=np.float64)
        r1 = solve_distributed_df64(a, b, mesh=make_mesh(8), tol=1e-10,
                                    maxiter=2000, preconditioner="jacobi")
        rk = solve_distributed_df64(a, b, mesh=make_mesh(8), tol=1e-10,
                                    maxiter=2000, preconditioner="jacobi",
                                    check_every=8)
        assert bool(r1.converged) and bool(rk.converged)
        k1, kk = int(r1.iterations), int(rk.iterations)
        assert k1 <= kk < k1 + 8
        np.testing.assert_allclose(rk.x(), x_true, atol=1e-7)

    def test_history_replicated_and_norm_semantics(self, rng):
        grid = (8, 8)
        a = Stencil2D.create(*grid, dtype=jnp.float32)
        b = rng.standard_normal(64)
        r = solve_distributed_df64(a, b, mesh=make_mesh(8), tol=0.0,
                                   rtol=1e-9, maxiter=500,
                                   record_history=True)
        k = int(r.iterations)
        hist = np.asarray(r.residual_history)
        assert np.all(np.isfinite(hist[: k + 1]))
        assert np.all(np.isnan(hist[k + 1:]))
        np.testing.assert_allclose(hist[k], r.residual_norm(), rtol=1e-5)

    def test_rejects_unsupported(self):
        from cuda_mpi_parallel_tpu.models.operators import DenseOperator

        a_dense = DenseOperator(a=jnp.eye(8))
        with pytest.raises(TypeError, match="Stencil2D"):
            solve_distributed_df64(a_dense, np.ones(8), mesh=make_mesh(2))
        a = Stencil2D.create(8, 8)
        with pytest.raises(ValueError, match="jacobi"):
            solve_distributed_df64(a, np.ones(64), mesh=make_mesh(2),
                                   preconditioner="ssor")


class TestDistVariantsDF64:
    """Distributed cg1/pipecg: the fused single-psum recurrences over
    the mesh - the configuration these variants exist for (one
    collective per iteration instead of two), exercising fused_dots'
    stacked-psum branch."""

    @pytest.mark.parametrize("method", ["cg1", "pipecg"])
    def test_matches_cg_on_mesh(self, rng, method):
        grid = (16, 12)
        a = Stencil2D.create(*grid, dtype=jnp.float32)
        op64 = Stencil2D.create(*grid, dtype=jnp.float64)
        x_true = rng.standard_normal(int(np.prod(grid)))
        b = np.asarray(op64 @ jnp.asarray(x_true), dtype=np.float64)
        base = solve_distributed_df64(a, b, mesh=make_mesh(8), tol=0.0,
                                      rtol=1e-10, maxiter=2000)
        var = solve_distributed_df64(a, b, mesh=make_mesh(8), tol=0.0,
                                     rtol=1e-10, maxiter=2000,
                                     method=method)
        assert bool(var.converged)
        assert abs(int(var.iterations) - int(base.iterations)) <= 3
        np.testing.assert_allclose(var.x(), x_true, atol=1e-7)

    def test_fused_dots_psum_branch(self, rng):
        """fused_dots under shard_map: one stacked psum, per-pair df64
        results matching the full-vector dots."""
        import jax
        from jax.sharding import PartitionSpec as P

        mesh = make_mesh(8)
        n = 64
        (ah, al), va = (lambda v: (df.split_f64(v), v))(
            rng.standard_normal(n))
        (bh, bl), vb = (lambda v: (df.split_f64(v), v))(
            rng.standard_normal(n))
        a_pair = (jnp.asarray(ah), jnp.asarray(al))
        b_pair = (jnp.asarray(bh), jnp.asarray(bl))

        def body(a, b):
            [d1, d2] = df.fused_dots([(a, b), (a, a)], axis_name="rows")
            return d1, d2

        (d1, d2) = jax.jit(shard_map(
            body, mesh=mesh, in_specs=(P("rows"), P("rows")),
            out_specs=(P(), P())))(a_pair, b_pair)
        np.testing.assert_allclose(df.to_f64(*jax.tree.map(np.asarray, d1)),
                                   float(va @ vb), rtol=1e-13)
        np.testing.assert_allclose(df.to_f64(*jax.tree.map(np.asarray, d2)),
                                   float(va @ va), rtol=1e-13)


class TestRingShiftELLDF64:
    """Assembled-CSR distributed df64: the ring schedule with df64
    shift-ELL slabs - the reference's CUDA_R_64F CSR SpMV
    (CUDACG.cu:216,288) over the mesh."""

    def _system(self, rng, n=24):
        from cuda_mpi_parallel_tpu.models import poisson

        a = poisson.poisson_2d_csr(n, n, dtype=np.float64)
        x_true = rng.standard_normal(a.shape[0])
        b = np.asarray(a.to_dense(), np.float64) @ x_true
        return a, b, x_true

    def test_matvec_parity(self, rng):
        """Ring df64 matvec under shard_map == host f64 matvec."""
        from cuda_mpi_parallel_tpu.parallel import DistShiftELLDF64Ring
        from cuda_mpi_parallel_tpu.parallel import partition as part
        from functools import partial

        a, _, _ = self._system(rng, n=16)
        parts = part.ring_partition_shiftell_df64(a, 8)
        mesh = make_mesh(8)
        x64 = rng.standard_normal(parts.n_global_padded)
        xh, xl = (jnp.asarray(v) for v in df.split_f64(x64))

        def body(xp, vh, vl, meta, blks, dh, dl):
            strip = partial(jax.tree.map, lambda v: v[0])
            op = DistShiftELLDF64Ring(
                vals_hi=strip(vh), vals_lo=strip(vl),
                lane_idx=strip(meta), chunk_blocks=strip(blks),
                diag_hi=dh, diag_lo=dl, h=parts.h, kc=parts.kc,
                n_local=parts.n_local, axis_name="rows", n_shards=8)
            return op.matvec_df(xp)

        sh = lambda t: jax.tree.map(jnp.asarray, t)
        got_h, got_l = jax.jit(shard_map(
            body, mesh=mesh, check_vma=False,
            in_specs=(P("rows"), P("rows"), P("rows"), P("rows"),
                      P("rows"), P("rows"), P("rows")),
            out_specs=(P("rows"), P("rows"))))(
            (xh, xl), sh(parts.vals_hi), sh(parts.vals_lo),
            sh(parts.lane_idx), sh(parts.chunk_blocks),
            jnp.asarray(parts.diag_hi.reshape(-1)),
            jnp.asarray(parts.diag_lo.reshape(-1)))
        n = a.shape[0]
        want = np.asarray(a.to_dense(), np.float64) @ x64[:n]
        got = df.to_f64(np.asarray(got_h), np.asarray(got_l))[:n]
        np.testing.assert_allclose(got, want, rtol=1e-12, atol=1e-12)

    def test_solve_matches_single_device(self, rng):
        a, b, x_true = self._system(rng)
        single = cg_df64(a.to_shiftell_df64(), b, tol=0.0, rtol=1e-11,
                         maxiter=3000)
        dist = solve_distributed_df64(a, b, mesh=make_mesh(8), tol=0.0,
                                      rtol=1e-11, maxiter=3000)
        assert bool(dist.converged)
        assert abs(int(dist.iterations) - int(single.iterations)) <= 2
        np.testing.assert_allclose(dist.x(), x_true, atol=1e-8)

    def test_jacobi_variants_check_every(self, rng):
        a, b, x_true = self._system(rng)
        for method in ("cg1", "pipecg"):
            r = solve_distributed_df64(
                a, b, mesh=make_mesh(8), tol=0.0, rtol=1e-10,
                maxiter=3000, preconditioner="jacobi", method=method,
                check_every=4)
            assert bool(r.converged), method
            np.testing.assert_allclose(r.x(), x_true, atol=1e-7)

    def test_padding_rows_stripped(self, rng):
        """n not divisible by the shard count: unit-diagonal padding rows
        are solved as zeros and stripped from the returned x."""
        from cuda_mpi_parallel_tpu.models import poisson

        a = poisson.poisson_2d_csr(18, 17, dtype=np.float64)  # 306 rows
        x_true = rng.standard_normal(a.shape[0])
        b = np.asarray(a.to_dense(), np.float64) @ x_true
        r = solve_distributed_df64(a, b, mesh=make_mesh(8), tol=0.0,
                                   rtol=1e-10, maxiter=3000)
        assert bool(r.converged)
        assert r.x_hi.shape[0] == a.shape[0]
        np.testing.assert_allclose(r.x(), x_true, atol=1e-7)


class TestPencilDF64:
    """2-D mesh (pencil) df64: two halo ppermute pairs per matvec, dots
    reduced over BOTH mesh axes at df64 accuracy."""

    def _system(self, rng, grid=(16, 8, 6)):
        a = Stencil3D.create(*grid, dtype=jnp.float32)
        a64 = Stencil3D.create(*grid, dtype=jnp.float64)
        x_true = rng.standard_normal(int(np.prod(grid)))
        b = np.asarray(a64 @ jnp.asarray(x_true), dtype=np.float64)
        return a, b, x_true

    def test_matvec_parity_bitwise(self, rng):
        """Pencil df64 matvec == global df64 matvec, bitwise on both
        planes (identical per-element EFT sequence)."""
        from cuda_mpi_parallel_tpu.parallel import make_mesh_2d
        from cuda_mpi_parallel_tpu.parallel.df64 import (
            DistStencilDF64Pencil,
        )

        grid = (8, 4, 6)
        mesh = make_mesh_2d((4, 2))
        n = int(np.prod(grid))
        x64 = rng.standard_normal(n)
        xh, xl = (jnp.asarray(v) for v in df.split_f64(x64))
        want_h, want_l = jax.jit(
            lambda p: df.stencil3d_matvec(p, grid, df.const(1.3)))(
            (xh, xl))

        local = DistStencilDF64Pencil.create(grid, (4, 2), scale=1.3)
        xg = jnp.stack([xh.reshape(grid), xl.reshape(grid)])

        def body(x2):
            lh = x2[0].reshape(-1)
            ll = x2[1].reshape(-1)
            yh, yl = local.matvec_df((lh, ll))
            lg = local.local_grid
            return yh.reshape(lg), yl.reshape(lg)

        got_h, got_l = jax.jit(shard_map(
            body, mesh=mesh,
            in_specs=(P(None, "rows", "cols"),),
            out_specs=(P("rows", "cols"), P("rows", "cols"))))(xg)
        np.testing.assert_array_equal(
            np.asarray(got_h).reshape(-1), np.asarray(want_h))
        np.testing.assert_array_equal(
            np.asarray(got_l).reshape(-1), np.asarray(want_l))

    def test_solve_matches_single_device(self, rng):
        from cuda_mpi_parallel_tpu.parallel import make_mesh_2d

        a, b, x_true = self._system(rng)
        single = cg_df64(a, b, tol=0.0, rtol=1e-10, maxiter=2000)
        dist = solve_distributed_df64(a, b, mesh=make_mesh_2d((4, 2)),
                                      tol=0.0, rtol=1e-10, maxiter=2000)
        assert bool(dist.converged)
        assert abs(int(dist.iterations) - int(single.iterations)) <= 2
        np.testing.assert_allclose(dist.x(), x_true, atol=1e-8)

    def test_jacobi_and_variants(self, rng):
        from cuda_mpi_parallel_tpu.parallel import make_mesh_2d

        a, b, x_true = self._system(rng)
        for method in ("cg1", "pipecg"):
            r = solve_distributed_df64(
                a, b, mesh=make_mesh_2d((4, 2)), tol=0.0, rtol=1e-9,
                maxiter=2000, preconditioner="jacobi", method=method,
                check_every=4)
            assert bool(r.converged), method
            np.testing.assert_allclose(r.x(), x_true, atol=1e-6)

    def test_pencil_rejects_non_stencil3d(self):
        from cuda_mpi_parallel_tpu.parallel import make_mesh_2d

        a2 = Stencil2D.create(8, 8)
        with pytest.raises(TypeError, match="Stencil3D"):
            solve_distributed_df64(a2, np.ones(64),
                                   mesh=make_mesh_2d((4, 2)))


class TestChebyshevDF64Dist:
    """df64 Chebyshev over meshes: the polynomial inherits the operator's
    communication (halo ppermutes / ring rotations), the interval comes
    from the global operator host-side."""

    def test_slab_matches_single_device(self, rng):
        grid = (16, 8, 6)
        a = Stencil3D.create(*grid, dtype=jnp.float32)
        a64 = Stencil3D.create(*grid, dtype=jnp.float64)
        x_true = rng.standard_normal(int(np.prod(grid)))
        b = np.asarray(a64 @ jnp.asarray(x_true), dtype=np.float64)
        single = cg_df64(a, b, tol=0.0, rtol=1e-10, maxiter=2000,
                         preconditioner="chebyshev")
        dist = solve_distributed_df64(a, b, mesh=make_mesh(8), tol=0.0,
                                      rtol=1e-10, maxiter=2000,
                                      preconditioner="chebyshev")
        assert bool(dist.converged)
        assert abs(int(dist.iterations) - int(single.iterations)) <= 2
        np.testing.assert_allclose(dist.x(), x_true, atol=1e-8)

    def test_ring_csr_chebyshev(self, rng):
        from cuda_mpi_parallel_tpu.models import poisson

        a = poisson.poisson_2d_csr(24, 24, dtype=np.float64)
        x_true = rng.standard_normal(a.shape[0])
        b = np.asarray(a.to_dense(), np.float64) @ x_true
        plain = solve_distributed_df64(a, b, mesh=make_mesh(8), tol=0.0,
                                       rtol=1e-10, maxiter=3000)
        cheb = solve_distributed_df64(a, b, mesh=make_mesh(8), tol=0.0,
                                      rtol=1e-10, maxiter=3000,
                                      preconditioner="chebyshev")
        assert bool(cheb.converged)
        assert int(cheb.iterations) * 2 < int(plain.iterations)
        np.testing.assert_allclose(cheb.x(), x_true, atol=1e-7)
