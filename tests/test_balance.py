"""balance/: imbalance-aware partition planning.

The planner's claims are all quantitative, so every test here is a
hand-computable number: the chains-on-chains splitter must hit the
exact optimal bottleneck (brute-forced on small chains), the planned
distributed solve must match the single-device solution in the
CALLER's row ordering (permutation round-trip), variable-row padding
must never index out of range, and on the committed skewed fixture at
mesh 4 ``plan="auto"`` must cut the measured nnz stall factor by >= 2x
(the ISSUE 5 acceptance).
"""
import itertools
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cuda_mpi_parallel_tpu import solve, telemetry
from cuda_mpi_parallel_tpu.balance import (
    GREEDY_REORDER_LIMIT,
    PartitionPlan,
    balanced_nnz_ranges,
    even_ranges,
    greedy_nnz_reorder,
    inverse_permutation,
    plan_partition,
    rcm_reorder,
    validate_ranges,
)
from cuda_mpi_parallel_tpu.models import mmio, poisson
from cuda_mpi_parallel_tpu.models.operators import CSRMatrix
from cuda_mpi_parallel_tpu.parallel import partition as part
from cuda_mpi_parallel_tpu.telemetry import events
from cuda_mpi_parallel_tpu.telemetry import shardscope as ss
from cuda_mpi_parallel_tpu.utils import compat

needs_mesh = pytest.mark.skipif(
    not compat.has_shard_map() or len(jax.devices()) < 4,
    reason="needs shard_map and >= 4 (virtual) devices")

FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures",
                       "skewed_spd_240.mtx")


def skewed_block_csr(n=32, c=8, dtype=np.float64):
    """n x n SPD CSR with one DENSE c-row coupling block (rows 0..c-1
    fully coupled) over a bare-diagonal tail - maximal contiguous row
    skew with exactly known per-range counts."""
    rows, cols, vals = [], [], []
    for i in range(c):
        for j in range(c):
            rows.append(i)
            cols.append(j)
            vals.append(float(c) if i == j else -0.5)
    for i in range(c, n):
        rows.append(i)
        cols.append(i)
        vals.append(2.0)
    return CSRMatrix.from_coo(np.array(rows), np.array(cols),
                              np.array(vals, dtype=dtype), n, dtype=dtype)


class TestNnzSplit:
    def test_even_ranges_matches_legacy_partition_geometry(self):
        for n, p in ((12, 4), (13, 4), (7, 8), (8, 3)):
            ranges = even_ranges(n, p)
            n_local = -(-n // p)
            assert len(ranges) == p
            for s, (lo, hi) in enumerate(ranges):
                assert lo == min(s * n_local, n)
                assert hi == min((s + 1) * n_local, n)
            validate_ranges(ranges, n, p)

    def test_single_heavy_row_isolated(self):
        # nnz per row: [10, 1, 1, 1, 1, 1, 1, 1]; optimal 2-chain
        # bottleneck is 10 -> the heavy row sits alone
        indptr = np.concatenate([[0], np.cumsum([10] + [1] * 7)])
        ranges = balanced_nnz_ranges(indptr, 2)
        nnz = [int(indptr[hi] - indptr[lo]) for lo, hi in ranges]
        assert max(nnz) == 10
        assert ranges[0] == (0, 1)

    def test_uniform_rows_split_evenly(self):
        indptr = np.arange(0, 101, 1) * 3  # 100 rows x 3 nnz
        ranges = balanced_nnz_ranges(indptr, 4)
        assert ranges == ((0, 25), (25, 50), (50, 75), (75, 100))

    @pytest.mark.parametrize("n_shards", [2, 3])
    def test_bottleneck_is_exactly_optimal(self, rng, n_shards):
        """Brute-force every contiguous divider placement on a small
        random chain; the splitter must hit the optimal bottleneck."""
        row_nnz = rng.integers(1, 20, size=10)
        indptr = np.concatenate([[0], np.cumsum(row_nnz)])
        ranges = balanced_nnz_ranges(indptr, n_shards)
        got = max(int(indptr[hi] - indptr[lo]) for lo, hi in ranges)
        best = None
        for divs in itertools.combinations(range(1, 10), n_shards - 1):
            bounds = (0,) + divs + (10,)
            bottleneck = max(int(indptr[bounds[i + 1]] - indptr[bounds[i]])
                             for i in range(n_shards))
            best = bottleneck if best is None else min(best, bottleneck)
        assert got == best

    def test_max_local_rows_cap_respected(self):
        indptr = np.arange(0, 101, 1)  # 100 rows x 1 nnz
        ranges = balanced_nnz_ranges(indptr, 4, max_local_rows=30)
        assert max(hi - lo for lo, hi in ranges) <= 30
        validate_ranges(ranges, 100, 4)

    def test_infeasible_cap_ignored(self):
        indptr = np.arange(0, 101, 1)
        ranges = balanced_nnz_ranges(indptr, 4, max_local_rows=10)
        validate_ranges(ranges, 100, 4)  # still covers all 100 rows

    def test_validate_ranges_rejects_bad_covers(self):
        with pytest.raises(ValueError):
            validate_ranges(((0, 5), (6, 10)), 10, 2)   # gap
        with pytest.raises(ValueError):
            validate_ranges(((0, 6), (5, 10)), 10, 2)   # overlap
        with pytest.raises(ValueError):
            validate_ranges(((0, 5), (5, 9)), 10, 2)    # short cover
        with pytest.raises(ValueError):
            validate_ranges(((0, 10),), 10, 2)          # wrong count


class TestReorder:
    def test_greedy_is_a_permutation(self):
        a = skewed_block_csr()
        perm = greedy_nnz_reorder(a)
        assert np.array_equal(np.sort(perm), np.arange(a.shape[0]))

    def test_inverse_permutation_roundtrip(self, rng):
        perm = rng.permutation(37)
        inv = inverse_permutation(perm)
        assert np.array_equal(perm[inv], np.arange(37))
        assert np.array_equal(inv[perm], np.arange(37))

    def test_rcm_wrapper_matches_operator_method(self):
        a = poisson.poisson_2d_csr(6, 6)
        assert np.array_equal(rcm_reorder(a),
                              np.asarray(a.rcm_permutation()))

    def test_greedy_reduces_coupling_of_scrambled_band(self, rng):
        """Scramble a banded Laplacian, reorder greedily: the total
        cross-shard coupling of a 4-way contiguous split must come back
        down (the envelope-reduction claim, measured by the same
        accounting the planner scores with)."""
        a = poisson.poisson_2d_csr(8, 8)
        scram = rng.permutation(a.shape[0])
        a_s = a.permuted(scram)

        def coupling(op):
            rep = ss.report_for_ranges(
                op, even_ranges(op.shape[0], 4))
            return int(rep.halo_send_bytes.sum())

        a_g = a_s.permuted(greedy_nnz_reorder(a_s))
        assert coupling(a_g) < coupling(a_s)

    def test_permutation_roundtrip_solves_same_system(self, rng):
        """P^T A P with b[perm] solves to x[perm] - scattering back
        through the inverse must reproduce the unpermuted solution."""
        a = skewed_block_csr(24, 6)
        x_true = rng.standard_normal(24)
        b = np.asarray(a @ jnp.asarray(x_true))
        perm = greedy_nnz_reorder(a)
        ap = a.permuted(perm)
        res = solve(ap, jnp.asarray(b[perm]), tol=1e-12, maxiter=500)
        x_back = np.asarray(res.x)[inverse_permutation(perm)]
        ref = solve(a, jnp.asarray(b), tol=1e-12, maxiter=500)
        np.testing.assert_allclose(x_back, np.asarray(ref.x), atol=1e-8)
        np.testing.assert_allclose(x_back, x_true, atol=1e-6)


class TestPlanPartition:
    @pytest.mark.parametrize("n_shards", [2, 4, 8])
    def test_skewed_block_imbalance_drops(self, n_shards):
        """ISSUE 5 satellite: a hand-built dense-row-block CSR through
        plan_partition at 2/4/8 shards - the predicted nnz stall factor
        must strictly beat the even split's.  Scored under the
        stall-factor objective: the default time objective may rightly
        KEEP the even split when the padded-row cost outweighs the
        rebalance (e.g. this matrix at 2 shards), which the fixture
        acceptance test covers separately."""
        a = skewed_block_csr(64, 16)
        plan = plan_partition(a, n_shards, objective="nnz")
        even = plan.baseline_imbalance["nnz_max_over_mean"]
        planned = plan.report.imbalance()["nnz_max_over_mean"]
        assert planned < even
        assert len(plan.row_ranges) == n_shards
        validate_ranges(plan.row_ranges, 64, n_shards)
        if plan.permutation is not None:
            assert np.array_equal(np.sort(plan.permutation),
                                  np.arange(64))

    def test_objective_nnz_minimizes_stall_factor(self):
        a = skewed_block_csr(64, 16)
        plan = plan_partition(a, 4, objective="nnz")
        # score IS the stall factor under this objective
        assert plan.score == pytest.approx(
            plan.report.imbalance()["nnz_max_over_mean"])
        assert plan.score < plan.baseline_imbalance["nnz_max_over_mean"]

    def test_balanced_structured_system_keeps_simplest_lane(self):
        """A uniform Poisson band is already balanced: the planner must
        keep the trivial LAYOUT (no permutation, even ranges) - since
        the exchange lane joined the search it may still upgrade the
        WIRE (the band's coupling is tiny, so the gather halo beats the
        fixed allgather payload), but reordering a healthy system for a
        wire win the trivial layout gets for free would be churn."""
        a = poisson.poisson_2d_csr(16, 16)
        plan = plan_partition(a, 4)
        assert plan.reorder == "none" and plan.split == "even"
        assert plan.permutation is None
        assert plan.row_ranges == even_ranges(256, 4)
        # the band couples only adjacent shards: the gather wire wins
        assert plan.exchange == "gather"
        # pinning the legacy wire recovers the fully trivial plan
        pinned = plan_partition(a, 4, exchange="allgather")
        assert pinned.is_trivial()

    def test_unknown_objective_and_shards_rejected(self):
        a = skewed_block_csr()
        with pytest.raises(ValueError):
            plan_partition(a, 4, objective="vibes")
        with pytest.raises(ValueError):
            plan_partition(a, 0)

    def test_greedy_dropped_past_limit(self, monkeypatch):
        import cuda_mpi_parallel_tpu.balance.plan as plan_mod

        calls = []
        monkeypatch.setattr(
            plan_mod.reorder_mod, "greedy_nnz_reorder",
            lambda a: calls.append(1) or np.arange(a.shape[0]))
        monkeypatch.setattr(plan_mod, "GREEDY_REORDER_LIMIT", 10)
        plan_partition(skewed_block_csr(32, 8), 2)
        assert not calls  # 32 rows > patched limit of 10
        assert GREEDY_REORDER_LIMIT > 10_000  # the real limit is large

    def test_json_roundtrip_and_fingerprint(self, tmp_path):
        a = skewed_block_csr(64, 16)
        plan = plan_partition(a, 4)
        blob = json.dumps(plan.to_json())
        back = PartitionPlan.from_json(json.loads(blob))
        assert back.fingerprint() == plan.fingerprint()
        assert back.row_ranges == plan.row_ranges
        assert back.label == plan.label
        if plan.permutation is None:
            assert back.permutation is None
        else:
            assert np.array_equal(back.permutation, plan.permutation)
        path = tmp_path / "plan.json"
        plan.save(str(path))
        assert PartitionPlan.load(str(path)).fingerprint() \
            == plan.fingerprint()

    def test_validate_for_rejects_wrong_matrix(self):
        plan = plan_partition(skewed_block_csr(64, 16), 4)
        with pytest.raises(ValueError):
            plan.validate_for(skewed_block_csr(32, 8))

    def test_validate_for_rejects_corrupt_permutation(self):
        """A saved-plan file with a non-bijective permutation must be
        rejected at validation (downstream gathers clamp out-of-range
        indices and would return a silently wrong x)."""
        a = skewed_block_csr(64, 16)
        plan = plan_partition(a, 4)
        corrupt = PartitionPlan.from_json(dict(
            plan.to_json(), permutation=[0] * 64))
        with pytest.raises(ValueError, match="permutation"):
            corrupt.validate_for(a)

    def test_trivial_plan_collapses_to_none(self):
        """A plan that IS the legacy layout (no permutation, even
        ranges, fixed-payload wire) resolves to None, so an
        auto-planned solve of a balanced system shares the unplanned
        executable.  A gather-lane plan never collapses: even on even
        ranges its wire differs from the legacy schedule."""
        from cuda_mpi_parallel_tpu.parallel.dist_cg import resolve_plan

        a = poisson.poisson_2d_csr(16, 16)
        plan = plan_partition(a, 4, exchange="allgather")
        assert plan.is_trivial()
        assert resolve_plan(plan, a, 4) is None
        gather = plan_partition(a, 4, exchange="gather")
        assert not gather.is_trivial()
        assert resolve_plan(gather, a, 4) is gather
        skewed = plan_partition(skewed_block_csr(64, 16), 4,
                                objective="nnz")
        assert not skewed.is_trivial()


class TestPlannedPartitioners:
    """Variable-row padding: the plan-driven partitioners must build
    exactly the embedded system (real block + unit-diagonal padding)
    and never index outside the padded global range."""

    def _ranges(self, a, n_shards):
        return balanced_nnz_ranges(np.asarray(a.indptr), n_shards)

    def test_partition_csr_ranges_reassembles_embedded_system(self):
        a = skewed_block_csr(32, 8)
        ranges = self._ranges(a, 4)
        p = part.partition_csr(a, 4, row_ranges=ranges)
        assert p.row_ranges == ranges
        n_pad = p.n_global_padded
        assert n_pad == p.n_local * 4
        g = part.gather_indices(ranges, p.n_local)
        dense = np.zeros((n_pad, n_pad))
        for s in range(4):
            # padding never reads out of range (the satellite claim)
            assert p.cols[s].min() >= 0 and p.cols[s].max() < n_pad
            assert p.local_rows[s].max() < p.n_local
            live = p.data[s] != 0
            np.add.at(dense,
                      (p.local_rows[s][live] + s * p.n_local,
                       p.cols[s][live]), p.data[s][live])
        a_dense = np.asarray(a.to_dense())
        np.testing.assert_allclose(dense[np.ix_(g, g)], a_dense)
        pad_mask = np.ones(n_pad, bool)
        pad_mask[g] = False
        np.testing.assert_allclose(
            dense[np.ix_(pad_mask, pad_mask)],
            np.eye(int(pad_mask.sum())))
        assert np.all(dense[np.ix_(pad_mask, ~pad_mask)] == 0)
        assert np.all(dense[np.ix_(~pad_mask, pad_mask)] == 0)

    def test_ring_ranges_matches_row_partition(self):
        a = skewed_block_csr(32, 8)
        ranges = self._ranges(a, 4)
        p = part.partition_csr(a, 4, row_ranges=ranges)
        r = part.ring_partition_csr(a, 4, row_ranges=ranges)
        assert r.n_local == p.n_local and r.row_ranges == ranges
        n_pad = r.n_global_padded
        dense_r = np.zeros((n_pad, n_pad))
        for t in range(4):
            for s in range(4):
                blk = (s + t) % 4
                d = r.data[t][s]
                live = d != 0
                cols = r.cols[t][s][live] + blk * r.n_local
                assert cols.size == 0 or (cols.min() >= 0
                                          and cols.max() < n_pad)
                np.add.at(dense_r,
                          (r.local_rows[t][s][live] + s * r.n_local,
                           cols), d[live])
        dense_p = np.zeros((n_pad, n_pad))
        for s in range(4):
            live = p.data[s] != 0
            np.add.at(dense_p,
                      (p.local_rows[s][live] + s * p.n_local,
                       p.cols[s][live]), p.data[s][live])
        np.testing.assert_allclose(dense_r, dense_p)

    def test_shiftell_ranges_diag_scatter(self):
        a = skewed_block_csr(32, 8)
        ranges = self._ranges(a, 4)
        p = part.ring_partition_shiftell(a, 4, row_ranges=ranges)
        g = part.gather_indices(ranges, p.n_local)
        diag = np.asarray(p.diag).reshape(-1)
        np.testing.assert_allclose(diag[g], np.asarray(a.diagonal()))
        pad_mask = np.ones(diag.shape[0], bool)
        pad_mask[g] = False
        np.testing.assert_allclose(diag[pad_mask], 1.0)

    def test_pad_vector_ranges_roundtrip(self, rng):
        ranges = ((0, 3), (3, 10), (10, 12))
        b = rng.standard_normal(12)
        bp = part.pad_vector_ranges(b, ranges, 7)
        assert bp.shape == (21,)
        g = part.gather_indices(ranges, 7)
        np.testing.assert_allclose(bp[g], b)
        assert np.count_nonzero(bp) <= 12

    def test_shard_count_mismatch_rejected(self):
        a = skewed_block_csr(32, 8)
        three = self._ranges(a, 3)
        with pytest.raises(ValueError, match="expected 4 row ranges"):
            part.partition_csr(a, 4, row_ranges=three)

    def test_row_ranges_none_is_byte_identical_to_legacy(self):
        """plan=None's partition path IS the legacy one: identical
        arrays, not merely equivalent ones."""
        a = skewed_block_csr(30, 8)  # 30 rows over 4: uneven tail
        legacy = part.partition_csr(a, 4)
        explicit = part.partition_csr(a, 4, row_ranges=None)
        for f in ("data", "cols", "local_rows"):
            assert np.array_equal(getattr(legacy, f),
                                  getattr(explicit, f))
        assert legacy.row_ranges is None and explicit.row_ranges is None


class TestReportForRanges:
    def test_hand_computed_coupling(self):
        """4x4 chain matrix (tridiagonal), split 2+2: each shard
        references exactly ONE off-range column (the boundary), so the
        coupling halo is itemsize bytes each way."""
        a = CSRMatrix.from_coo(
            np.array([0, 0, 1, 1, 1, 2, 2, 2, 3, 3]),
            np.array([0, 1, 0, 1, 2, 1, 2, 3, 2, 3]),
            np.array([2.0, -1, -1, 2, -1, -1, 2, -1, -1, 2]),
            4, dtype=np.float64)
        rep = ss.report_for_ranges(a, ((0, 2), (2, 4)))
        assert list(rep.rows) == [2, 2]
        assert list(rep.nnz) == [5, 5]
        assert list(rep.halo_recv_bytes) == [8, 8]
        assert list(rep.halo_send_bytes) == [8, 8]
        assert rep.neighbors == (((1, 8),), ((0, 8),))
        assert rep.imbalance()["nnz_max_over_mean"] == 1.0

    def test_slots_match_partitioner_allocation(self):
        """The helper's slot prediction must equal what partition_csr
        actually allocates for the same ranges - planner and builder
        agreeing is the whole point of one code path."""
        a = skewed_block_csr(32, 8)
        for ranges in (even_ranges(32, 4),
                       balanced_nnz_ranges(np.asarray(a.indptr), 4)):
            rep = ss.report_for_ranges(a, ranges)
            p = part.partition_csr(a, 4, row_ranges=ranges)
            assert int(rep.slots[0]) == p.data.shape[1]
            assert rep.n_local == p.n_local
            assert list(rep.nnz) == [
                int(np.asarray(a.indptr)[hi] - np.asarray(a.indptr)[lo])
                for lo, hi in ranges]

    def test_plan_label_rides_report_json(self):
        a = skewed_block_csr(16, 4)
        rep = ss.report_for_ranges(a, even_ranges(16, 2),
                                   plan="rcm+nnz")
        blob = rep.to_json()
        assert blob["plan"] == "rcm+nnz"
        back = ss.ShardReport.from_json(blob)
        assert back.plan == "rcm+nnz"
        # pre-PR payloads (no plan key) default to "even"
        del blob["plan"]
        assert ss.ShardReport.from_json(blob).plan == "even"


@needs_mesh
class TestPlannedDistributedSolve:
    def _fixture(self):
        return mmio.load_matrix_market(FIXTURE)

    def setup_method(self):
        from cuda_mpi_parallel_tpu.parallel import dist_cg

        dist_cg.clear_solver_cache()

    def test_fixture_chain_parse_plan_solve(self):
        """ISSUE 5 satellite + acceptance: the native parser ->
        planner -> distributed solve chain on the committed fixture.
        plan='auto' must (a) cut the measured nnz stall factor >= 2x
        vs the even split and (b) match the single-device solution to
        solver tolerance."""
        from cuda_mpi_parallel_tpu.parallel import (
            make_mesh,
            solve_distributed,
        )

        a = self._fixture()
        rng = np.random.default_rng(3)
        b = rng.standard_normal(240)
        ref = solve(a, jnp.asarray(b), tol=1e-10, maxiter=2000)
        assert bool(ref.converged)

        mesh = make_mesh(4)
        try:
            with events.capture() as buf:
                telemetry.force_active(True)
                res = solve_distributed(a, b, mesh=mesh, tol=1e-10,
                                        maxiter=2000, plan="auto")
        finally:
            telemetry.force_active(False)
            ss.reset_last_shard_report()
        assert bool(res.converged)
        np.testing.assert_allclose(np.asarray(res.x),
                                   np.asarray(ref.x), atol=1e-7)
        lines = [json.loads(ln)
                 for ln in buf.getvalue().strip().splitlines()]
        for ev in lines:
            events.validate_event(ev)
        plan_events = [e for e in lines
                       if e["event"] == "partition_plan"]
        assert len(plan_events) == 1
        ev = plan_events[0]
        even = ev["predicted"]  # planner prediction for ITS layout
        measured = ev["measured"]["nnz_max_over_mean"]
        # the measured schedule report and the planner's prediction
        # agree on the stall factor (same ranges, same indptr)
        assert measured == pytest.approx(
            even["nnz_max_over_mean"], rel=1e-12)
        # the >= 2x acceptance, against the even-split baseline
        baseline = plan_partition(a, 4).baseline_imbalance
        assert baseline["nnz_max_over_mean"] / measured >= 2.0

    @pytest.mark.parametrize("csr_comm",
                             ["allgather", "ring", "ring-shiftell"])
    def test_all_schedules_match_reference(self, csr_comm):
        from cuda_mpi_parallel_tpu.parallel import (
            make_mesh,
            solve_distributed,
        )

        a = self._fixture()
        rng = np.random.default_rng(5)
        x_true = rng.standard_normal(240)
        b = np.asarray(a @ jnp.asarray(x_true))
        res = solve_distributed(a, b, mesh=make_mesh(4), tol=1e-10,
                                maxiter=2000, csr_comm=csr_comm,
                                plan="auto")
        assert bool(res.converged)
        # x comes back in the CALLER's ordering despite the plan's
        # internal permutation + variable-row padding
        np.testing.assert_allclose(np.asarray(res.x), x_true,
                                   atol=1e-6)

    def test_explicit_plan_and_cache_fingerprint(self):
        from cuda_mpi_parallel_tpu.parallel import (
            dist_cg,
            make_mesh,
            solve_distributed,
        )

        a = self._fixture()
        b = np.random.default_rng(0).standard_normal(240)
        mesh = make_mesh(4)
        plan = plan_partition(a, 4)
        solve_distributed(a, b, mesh=mesh, tol=1e-8, maxiter=500,
                          plan=plan)
        keys = list(dist_cg._SOLVER_CACHE)
        assert any(plan.fingerprint() in str(k) for k in keys), \
            "plan fingerprint must ride the solver cache key"
        solve_distributed(a, b, mesh=mesh, tol=1e-8, maxiter=500)
        keys2 = list(dist_cg._SOLVER_CACHE)
        assert len(keys2) == len(keys) + 1, \
            "plan=None must compile its own (legacy) cache entry"

    def test_plan_rejections(self):
        from cuda_mpi_parallel_tpu.parallel import (
            make_mesh,
            solve_distributed,
        )

        mesh = make_mesh(4)
        stencil = poisson.poisson_2d_operator(16, 16)
        with pytest.raises(ValueError, match="plan="):
            solve_distributed(stencil, np.ones(256), mesh=mesh,
                              plan="auto")
        a = self._fixture()
        with pytest.raises(ValueError, match="auto"):
            solve_distributed(a, np.ones(240), mesh=mesh,
                              plan="fastest")
        wrong_mesh_plan = plan_partition(a, 2)
        with pytest.raises(ValueError, match="shards"):
            solve_distributed(a, np.ones(240), mesh=mesh,
                              plan=wrong_mesh_plan)
        with pytest.raises(TypeError):
            solve_distributed(a, np.ones(240), mesh=mesh,
                              plan=object())

    @pytest.mark.slow
    def test_df64_planned_solve_matches_reference(self):
        from cuda_mpi_parallel_tpu.parallel import make_mesh
        from cuda_mpi_parallel_tpu.parallel.df64 import (
            solve_distributed_df64,
        )

        a = self._fixture()
        rng = np.random.default_rng(3)
        b = rng.standard_normal(240)
        ref = solve(a, jnp.asarray(b), tol=1e-10, maxiter=2000)
        res = solve_distributed_df64(a, b, mesh=make_mesh(4),
                                     tol=1e-10, maxiter=500,
                                     plan="auto")
        assert bool(res.converged)
        np.testing.assert_allclose(res.x(), np.asarray(ref.x),
                                   atol=1e-8)


@needs_mesh
class TestPlanCLI:
    def test_mesh4_plan_auto_json_record(self, capsys):
        from cuda_mpi_parallel_tpu import cli
        from cuda_mpi_parallel_tpu.parallel import dist_cg

        dist_cg.clear_solver_cache()
        try:
            rc = cli.main(["--problem", "mm", "--file", FIXTURE,
                           "--mesh", "4", "--device", "cpu",
                           "--tol", "1e-8", "--maxiter", "500",
                           "--plan", "auto", "--report", "-",
                           "--json"])
        finally:
            telemetry.configure(None)
            telemetry.force_active(False)
            dist_cg.clear_solver_cache()
            ss.reset_last_shard_report()
        assert rc == 0
        rec = json.loads(capsys.readouterr().out)
        plan = rec["plan"]
        assert plan["split"] == "nnz"
        even = plan["even_imbalance"]["nnz_max_over_mean"]
        measured = plan["measured_imbalance"]["nnz_max_over_mean"]
        assert even / measured >= 2.0  # the CLI-level acceptance
        # the report embeds the shard profile labeled with the plan lane
        assert rec["solve_report"]["shard_profile"]["plan"] \
            == plan["label"]

    def test_plan_file_roundtrip_and_refusals(self, tmp_path, capsys):
        from cuda_mpi_parallel_tpu import cli
        from cuda_mpi_parallel_tpu.parallel import dist_cg

        a = mmio.load_matrix_market(FIXTURE)
        path = tmp_path / "plan.json"
        plan_partition(a, 4).save(str(path))
        dist_cg.clear_solver_cache()
        try:
            rc = cli.main(["--problem", "mm", "--file", FIXTURE,
                           "--mesh", "4", "--device", "cpu",
                           "--tol", "1e-8", "--maxiter", "500",
                           "--plan", str(path), "--json"])
        finally:
            dist_cg.clear_solver_cache()
        assert rc == 0
        rec = json.loads(capsys.readouterr().out)
        assert rec["plan"]["fingerprint"] == \
            plan_partition(a, 4).fingerprint()
        # wrong-mesh plan file: a clean refusal, not a traceback
        with pytest.raises(SystemExit, match="shards"):
            cli.main(["--problem", "mm", "--file", FIXTURE,
                      "--mesh", "2", "--device", "cpu",
                      "--plan", str(path)])
        with pytest.raises(SystemExit, match="mesh"):
            cli.main(["--problem", "mm", "--file", FIXTURE,
                      "--plan", "auto"])
        with pytest.raises(SystemExit, match="assembled-CSR"):
            cli.main(["--problem", "poisson2d", "--n", "8",
                      "--matrix-free", "--mesh", "4",
                      "--device", "cpu", "--plan", "auto"])
