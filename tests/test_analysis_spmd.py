"""graftverify SPMD contracts (analysis.spmd + analysis.jaxpr, ISSUE 16).

Three whole-trace contracts, each tested against a seeded violation AND
the healthy twin: (1) replication consistency - a ``while_loop``
predicate or ``cond`` selector fed by a shard-varying value (a local
residual norm whose psum was dropped, an ``axis_index`` leak) is caught
by name as ``shard-varying-predicate``, while the psum-laundered and
trace-constant forms verify green; (2) mesh-validated collectives -
undeclared axis names and ``ppermute`` endpoints outside the actual
mesh (the elastic-migration seam: a ring schedule built for mesh-4
replayed on mesh-2) are caught; (3) the collective budget - the named
:func:`verify_collective_budget` API holds on an identical lane and
raises :class:`CollectiveBudgetError` on a lane that genuinely changes
the per-iteration inventory (ring vs allgather exchange).

The shipped mesh-4 CSR lanes (allgather/gather/ring exchange, deflated,
fault-armed) are verified green end-to-end by tracing the EXACT build
the solver cache would compile, captured via the cache-key audit's
dispatch probe - trace-only, no compile, no device run.
"""
import types

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cuda_mpi_parallel_tpu.analysis import (
    CollectiveBudgetError,
    SpmdViolation,
    collective_budget,
    mesh_collective_findings,
    replication_findings,
    verify_collective_budget,
    verify_spmd,
)
from cuda_mpi_parallel_tpu.analysis.cachekey import (
    _synthetic_space,
    probe_dispatch,
)
from cuda_mpi_parallel_tpu.models import poisson
from cuda_mpi_parallel_tpu.parallel import make_mesh, solve_distributed
from cuda_mpi_parallel_tpu.robust.inject import FaultPlan
from cuda_mpi_parallel_tpu.utils import compat

needs_mesh = pytest.mark.skipif(
    not compat.has_shard_map() or len(jax.devices()) < 4,
    reason="needs shard_map and >= 4 (virtual) devices")

P = jax.sharding.PartitionSpec

AXIS = "rows"


def _sharded(fn, mesh, out_specs=None):
    """shard_map over the 1-D rows mesh, replication check off (the
    seeded-bad bodies are exactly what the checker would reject)."""
    return compat.shard_map(fn, mesh=mesh, in_specs=P(AXIS),
                            out_specs=(out_specs if out_specs is not None
                                       else P(AXIS)),
                            check_vma=False)


def _fake_mesh(axes):
    """Anything with ``axis_names`` and ``shape`` validates - the
    elastic seam replays a traced schedule against a DIFFERENT mesh."""
    return types.SimpleNamespace(axis_names=tuple(n for n, _ in axes),
                                 shape=dict(axes))


class TestReplicationWalker:
    """Seeded-broken control flow caught by name; healthy twins green."""

    @needs_mesh
    def test_dropped_psum_while_predicate_caught(self):
        """The canonical bug: a CG-style loop whose convergence check
        reads the LOCAL partial residual norm - the psum was dropped -
        so each shard decides its own trip count."""
        mesh = make_mesh(4)

        def local(r):
            def cond(carry):
                _, rr = carry
                return jnp.sum(rr * rr) > 1e-6  # local partial: varying

            def body(carry):
                i, rr = carry
                return i + 1, rr * 0.5

            _, out = jax.lax.while_loop(cond, body, (0, r))
            return out

        fn = _sharded(local, mesh)
        with pytest.raises(SpmdViolation) as exc:
            verify_spmd(fn, jnp.ones(16), mesh=mesh)
        kinds = {f.kind for f in exc.value.findings}
        assert kinds == {"shard-varying-predicate"}
        assert any("while" in f.where for f in exc.value.findings)
        assert "desynchronize" in str(exc.value)

    @needs_mesh
    def test_psum_laundering_is_green(self):
        """Same loop with the psum restored: the predicate derives from
        a replicated value, so the contract verifies green."""
        mesh = make_mesh(4)

        def local(r):
            def cond(carry):
                _, rr = carry
                return jax.lax.psum(jnp.sum(rr * rr), AXIS) > 1e-6

            def body(carry):
                i, rr = carry
                return i + 1, rr * 0.5

            _, out = jax.lax.while_loop(cond, body, (0, r))
            return out

        report = verify_spmd(_sharded(local, mesh), jnp.ones(16),
                             mesh=mesh)
        assert report.ok
        assert report.axes_used == (AXIS,)

    @needs_mesh
    def test_trace_constant_counter_is_green(self):
        """A fixed trip count is replicated by construction even when
        the body churns shard-varying data."""
        mesh = make_mesh(4)

        def local(r):
            def cond(carry):
                i, _ = carry
                return i < 7

            def body(carry):
                i, rr = carry
                return i + 1, rr * 0.5

            _, out = jax.lax.while_loop(cond, body, (0, r))
            return out

        assert verify_spmd(_sharded(local, mesh), jnp.ones(16),
                           mesh=mesh).ok

    @needs_mesh
    def test_axis_index_leak_caught(self):
        """``axis_index`` introduces varying-ness out of nothing: a
        shard-id-gated loop bound desynchronizes even with fully
        replicated data inputs."""
        mesh = make_mesh(4)

        def local(r):
            me = jax.lax.axis_index(AXIS)

            def cond(carry):
                i, _ = carry
                return i < me + 3  # shard-id-dependent trip count

            def body(carry):
                i, rr = carry
                return i + 1, rr + 1.0

            _, out = jax.lax.while_loop(cond, body, (0, r))
            return out

        with pytest.raises(SpmdViolation) as exc:
            verify_spmd(_sharded(local, mesh), jnp.ones(16), mesh=mesh)
        assert {f.kind for f in exc.value.findings} \
            == {"shard-varying-predicate"}

    @needs_mesh
    def test_shard_gated_cond_selector_caught(self):
        """A ``cond`` whose branch selector is a local sum: shards take
        different branches and issue mismatched collectives."""
        mesh = make_mesh(4)

        def local(r):
            return jax.lax.cond(jnp.sum(r) > 0.0,
                                lambda x: x * 2.0,
                                lambda x: x * 0.5, r)

        with pytest.raises(SpmdViolation) as exc:
            verify_spmd(_sharded(local, mesh), jnp.ones(16), mesh=mesh)
        f, = exc.value.findings
        assert f.kind == "shard-varying-predicate"
        assert "cond" in f.where
        assert "branch" in f.message

    @needs_mesh
    def test_replication_findings_on_raw_jaxpr(self):
        """The walker is usable on an already-traced jaxpr (what the
        gate script does with probed builds)."""
        mesh = make_mesh(4)

        def local(r):
            def cond(carry):
                _, rr = carry
                return jnp.sum(rr * rr) > 1e-6

            def body(carry):
                i, rr = carry
                return i + 1, rr * 0.5

            _, out = jax.lax.while_loop(cond, body, (0, r))
            return out

        closed = jax.make_jaxpr(_sharded(local, mesh))(jnp.ones(16))
        findings = replication_findings(closed)
        assert findings
        assert findings[0].kind == "shard-varying-predicate"
        assert findings[0].describe().startswith(
            "[shard-varying-predicate]")


class TestMeshValidation:
    """Collectives checked against the ACTUAL mesh geometry."""

    @needs_mesh
    def test_undeclared_axis_caught(self):
        mesh = make_mesh(4)

        def local(r):
            return jax.lax.psum(r, AXIS)

        closed = jax.make_jaxpr(_sharded(local, mesh, out_specs=P()))(
            jnp.ones(16))
        findings = mesh_collective_findings(
            closed, _fake_mesh([("shards", 4)]))
        assert [k for k, _ in findings] == ["undeclared-axis"]
        assert "'rows'" in findings[0][1]

    @needs_mesh
    def test_permutation_out_of_range_caught(self):
        """The elastic-migration seam: a ring schedule traced for
        mesh-4 references shards 2 and 3, which a shrunken mesh-2 does
        not have - a deadlock on chip, a finding here."""
        mesh = make_mesh(4)
        perm = [(i, (i + 1) % 4) for i in range(4)]

        def local(r):
            return jax.lax.ppermute(r, AXIS, perm)

        closed = jax.make_jaxpr(_sharded(local, mesh))(jnp.ones(16))
        assert mesh_collective_findings(closed, mesh) == []
        findings = mesh_collective_findings(
            closed, _fake_mesh([(AXIS, 2)]))
        assert [k for k, _ in findings] == ["permutation-out-of-range"]
        assert "[2, 3]" in findings[0][1]

    @needs_mesh
    def test_verify_spmd_applies_mesh_checks(self):
        """``verify_spmd(..., mesh=)`` folds geometry findings into the
        same report/exception as the replication walk."""
        mesh = make_mesh(4)
        perm = [(i, (i + 1) % 4) for i in range(4)]

        def local(r):
            return jax.lax.ppermute(r, AXIS, perm)

        fn = _sharded(local, mesh)
        assert verify_spmd(fn, jnp.ones(16), mesh=mesh).ok
        with pytest.raises(SpmdViolation) as exc:
            verify_spmd(fn, jnp.ones(16), mesh=_fake_mesh([(AXIS, 2)]))
        assert {f.kind for f in exc.value.findings} \
            == {"permutation-out-of-range"}


@needs_mesh
class TestShippedLanes:
    """The exact solver bodies the cache would compile verify green:
    the probe intercepts ``_cached_solver`` and hands back the build/
    args pair, which ``verify_spmd`` re-traces (never compiles)."""

    def _system(self):
        a = poisson.poisson_2d_csr(10, 10)
        rng = np.random.default_rng(0)
        return a, rng.standard_normal(int(a.shape[0]))

    @pytest.mark.parametrize("lane,overrides", [
        ("allgather", {}),
        ("gather", {"exchange": "gather"}),
        ("ring", {"exchange": "ring"}),
        ("deflated", {"deflate": "SPACE"}),
        ("fault-armed", {"inject": FaultPlan(site="reduction",
                                             iteration=2)}),
    ])
    def test_lane_is_spmd_clean(self, lane, overrides):
        a, b = self._system()
        mesh = make_mesh(4)
        kw = dict(overrides)
        if kw.get("deflate") == "SPACE":
            kw["deflate"] = _synthetic_space(a)
        probe = probe_dispatch(
            lambda: solve_distributed(a, b, mesh=mesh, tol=1e-8,
                                      maxiter=200, **kw))
        report = verify_spmd(probe.build(), *probe.args, mesh=mesh)
        assert report.ok
        # non-vacuous: the trace really contains mesh collectives
        assert AXIS in report.axes_used


class TestCollectiveBudget:
    """The named per-iteration budget API (contract of PR 13; the
    deflated-vs-baseline instance is machine-checked at fixture scale
    in test_recycle.py / test_many_rhs.py)."""

    def _system(self):
        a = poisson.poisson_2d_csr(8, 8)
        rng = np.random.default_rng(1)
        return a, rng.standard_normal(int(a.shape[0]))

    def test_rejects_non_dispatch(self):
        with pytest.raises(TypeError, match="zero-arg dispatch"):
            collective_budget(42)

    def test_rejects_dispatch_that_skips_the_cache(self):
        with pytest.raises(ValueError, match="did not route"):
            collective_budget(lambda: None)

    @needs_mesh
    def test_identical_lane_is_green(self):
        a, b = self._system()
        mesh = make_mesh(4)

        def dispatch():
            return solve_distributed(a, b, mesh=mesh, tol=1e-6,
                                     maxiter=60)

        report = verify_collective_budget(dispatch, dispatch)
        assert report.ok
        assert report.deltas() == {"psum": 0, "ppermute": 0,
                                   "all_gather": 0}

    @needs_mesh
    def test_budget_drift_caught(self):
        """A variant that genuinely changes the inventory - the ring
        exchange trades the all_gather for per-iteration ppermutes -
        raises with the drifted ops and the caller's label."""
        a, b = self._system()
        mesh = make_mesh(4)

        def baseline():
            return solve_distributed(a, b, mesh=mesh, tol=1e-6,
                                     maxiter=60)

        def ring():
            return solve_distributed(a, b, mesh=mesh, tol=1e-6,
                                     maxiter=60, exchange="ring")

        with pytest.raises(CollectiveBudgetError) as exc:
            verify_collective_budget(ring, baseline,
                                     what="seeded ring-vs-allgather")
        msg = str(exc.value)
        assert "seeded ring-vs-allgather" in msg
        assert "ppermute" in msg or "all_gather" in msg

    @needs_mesh
    def test_solvecost_passthrough(self):
        """Precomputed ``SolveCost`` objects short-circuit the dispatch
        (the form test_many_rhs uses to also assert wire bytes)."""
        a, b = self._system()
        mesh = make_mesh(4)
        sc = collective_budget(
            lambda: solve_distributed(a, b, mesh=mesh, tol=1e-6,
                                      maxiter=60))
        assert collective_budget(sc) is sc
        assert verify_collective_budget(sc, sc).ok
