"""telemetry.roofline + telemetry.report: the machine model, the
fused solve report, and the Perfetto timeline exporter - including the
ISSUE-4 acceptance: a mesh-4 CLI solve whose ``--report -`` output
carries the per-shard table, an imbalance factor and a roofline
efficiency %, and whose ``--trace-perfetto`` file validates
structurally.
"""
import json

import numpy as np
import pytest

from cuda_mpi_parallel_tpu import cli
from cuda_mpi_parallel_tpu.telemetry import report as treport
from cuda_mpi_parallel_tpu.telemetry import roofline as roof
from cuda_mpi_parallel_tpu.telemetry import shardscope as ss


MODEL = roof.MachineModel(name="unit-test", mem_bytes_per_s=1e9,
                          flops_per_s=1e9, net_bytes_per_s=1e9,
                          source="table")


class TestTrafficModel:
    def test_cg_traffic_hand_computed(self):
        t = roof.solve_traffic(10, 30, 4, method="cg")
        # cg: 1 spmv, 2 dots, 3 axpy per iteration
        assert t["flops"] == 2 * 30 + 2 * (2 * 10) + 3 * (2 * 10)
        assert t["mem_bytes"] == ((30 * 8 + 2 * 10 * 4)
                                  + 2 * (2 * 10 * 4) + 3 * (3 * 10 * 4))

    def test_preconditioned_adds_work(self):
        plain = roof.solve_traffic(100, 500, 4)
        pre = roof.solve_traffic(100, 500, 4, preconditioned=True,
                                 precond_matvecs=3)
        assert pre["flops"] > plain["flops"]
        assert pre["ops"]["spmv"] == 4 and pre["ops"]["dot"] == 3

    def test_operator_nnz(self):
        from cuda_mpi_parallel_tpu.models import poisson
        from cuda_mpi_parallel_tpu.models.operators import Stencil2D

        a = poisson.poisson_2d_csr(8, 8)
        assert roof.operator_nnz(a) == int(a.nnz)
        s = Stencil2D.create(8, 8)
        assert roof.operator_nnz(s) == 5 * 64


class TestAnalyze:
    def test_memory_bound_efficiency_exact(self):
        # model time/iter = mem term = 840 B / 1e9 B/s; measured at
        # exactly that rate -> 100%
        t = roof.solve_traffic(10, 30, 4)
        r = roof.analyze(n=10, nnz=30, itemsize=4, iterations=10,
                         elapsed_s=10 * t["mem_bytes"] / 1e9,
                         model=MODEL)
        assert r.bound == "memory"
        assert r.efficiency_pct == pytest.approx(100.0)
        assert r.arithmetic_intensity == pytest.approx(
            t["flops"] / t["mem_bytes"])

    def test_communication_bound(self):
        slow_net = roof.MachineModel(name="t", mem_bytes_per_s=1e12,
                                     flops_per_s=1e12,
                                     net_bytes_per_s=1e6, source="table")
        r = roof.analyze(n=10, nnz=30, itemsize=4, iterations=5,
                         elapsed_s=1.0, comm_bytes_per_iteration=1e6,
                         model=slow_net)
        assert r.bound == "communication"
        assert r.t_comm_s == pytest.approx(1.0)

    def test_compute_bound(self):
        m = roof.MachineModel(name="t", mem_bytes_per_s=1e15,
                              flops_per_s=1e3, net_bytes_per_s=1e15,
                              source="table")
        r = roof.analyze(n=10, nnz=30, itemsize=4, iterations=1,
                         elapsed_s=1.0, model=m)
        assert r.bound == "compute"

    def test_cpu_model_calibrates_once(self):
        m1 = roof.machine_model("cpu")
        m2 = roof.machine_model("cpu")
        assert m1 is m2
        assert m1.source == "calibrated"
        assert m1.mem_bytes_per_s > 0 and m1.flops_per_s > 0

    def test_table_models(self):
        assert roof.machine_model("tpu").source == "table"
        assert roof.machine_model("weird").name == "generic"
        r = roof.machine_model("tpu")
        assert r.ridge_flops_per_byte == pytest.approx(
            r.flops_per_s / r.mem_bytes_per_s)

    def test_json_roundtrip(self):
        r = roof.analyze(n=10, nnz=30, itemsize=4, iterations=2,
                         elapsed_s=0.1, model=MODEL)
        j = json.loads(json.dumps(r.to_json()))
        assert j["bound"] == r.bound
        assert j["model"]["name"] == "unit-test"
        assert "roofline" in r.describe() or "%" in r.describe()


def synthetic_shard_report():
    return ss.ShardReport.from_json({
        "kind": "csr-allgather", "n_shards": 4, "n_global": 16,
        "n_global_padded": 16, "n_local": 4,
        "rows": [4, 4, 4, 4], "nnz": [19, 4, 4, 4],
        "slots": [19, 19, 19, 19],
        "halo_send_bytes": [16, 16, 16, 16],
        "halo_recv_bytes": [48, 48, 48, 48],
        "neighbors": [[[-1, 16]]] * 4,
    })


class TestSolveReportText:
    def test_sections_render(self):
        rep = treport.SolveReport(
            record={"problem": "unit", "status": "CONVERGED",
                    "iterations": 7, "residual_norm": 1e-8,
                    "elapsed_s": 0.01, "iters_per_sec": 700.0,
                    "device": "cpu", "mesh": 4, "dtype": "float32"},
            shard=synthetic_shard_report(),
            roofline=roof.analyze(n=16, nnz=31, itemsize=4,
                                  iterations=7, elapsed_s=0.01,
                                  model=MODEL),
            comm={"psum": 14, "ppermute": 0, "all_gather": 7,
                  "comm_bytes": 448,
                  "per_iteration": {"comm_bytes": 64}},
            sections=(("solve", 0.01),))
        text = rep.to_text()
        for token in ("per-shard profile", "shard", "nnz",
                      "halo out B/mv", "imbalance", "roofline",
                      "efficiency", "%", "memory-bound",
                      "host timer sections"):
            assert token in text, token
        j = rep.to_json()
        json.dumps(j, allow_nan=False)
        assert j["shard_profile"]["nnz"] == [19, 4, 4, 4]

    def test_minimal_report_renders(self):
        rep = treport.SolveReport(record={"problem": "tiny",
                                          "status": "CONVERGED",
                                          "iterations": 1,
                                          "residual_norm": None})
        assert "tiny" in rep.to_text()


class TestPerfetto:
    def test_structure_and_tracks(self):
        trace = treport.perfetto_trace(
            iterations=10, elapsed_s=0.02,
            shard=synthetic_shard_report(),
            sections=(("build", 0.001), ("solve", 0.02)),
            flight_history=np.array([1.0, 0.5, np.nan, 0.1]))
        treport.validate_perfetto(trace)
        evs = trace["traceEvents"]
        shard_tids = {ev["tid"] for ev in evs
                      if ev["pid"] == 1 and ev["ph"] == "X"}
        assert shard_tids == {0, 1, 2, 3}
        names = {ev["name"] for ev in evs if ev["ph"] == "X"}
        assert {"halo", "spmv", "reduction", "build", "solve"} <= names
        counters = [ev for ev in evs if ev["ph"] == "C"]
        assert len(counters) == 3  # finite residual entries only
        # the JSON is strict (loadable by chrome://tracing)
        json.dumps(trace, allow_nan=False)

    def test_iteration_cap_recorded(self):
        trace = treport.perfetto_trace(iterations=10_000, elapsed_s=1.0,
                                       n_shards=2)
        treport.validate_perfetto(trace)
        assert trace["metadata"]["truncated"] is True
        assert trace["metadata"]["drawn_iterations"] == \
            treport.MAX_DRAWN_ITERATIONS

    def test_validator_rejects_malformed(self):
        with pytest.raises(ValueError, match="traceEvents"):
            treport.validate_perfetto({"traceEvents": []})
        with pytest.raises(ValueError, match="missing required key"):
            treport.validate_perfetto(
                {"traceEvents": [{"ph": "X", "ts": 0, "pid": 0}]})
        bad = {"traceEvents": [
            {"ph": "X", "ts": 5, "dur": 1, "pid": 0, "tid": 0,
             "name": "a"},
            {"ph": "X", "ts": 1, "dur": 1, "pid": 0, "tid": 0,
             "name": "b"},
        ]}
        with pytest.raises(ValueError, match="backwards"):
            treport.validate_perfetto(bad)
        with pytest.raises(ValueError, match="no complete"):
            treport.validate_perfetto(
                {"traceEvents": [{"ph": "M", "ts": 0, "pid": 0,
                                  "tid": 0}]})

    def test_straggler_fills_its_slot(self):
        """The skewed shard's spmv wedge is the longest; balanced
        shards spend the difference in 'reduction' (the psum wait)."""
        trace = treport.perfetto_trace(iterations=1, elapsed_s=0.001,
                                       shard=synthetic_shard_report())
        evs = [ev for ev in trace["traceEvents"] if ev["ph"] == "X"]

        def dur(tid, name):
            return sum(ev["dur"] for ev in evs
                       if ev["pid"] == 1 and ev["tid"] == tid
                       and ev["name"] == name)

        # identical slot geometry here (slots are uniform), so spmv is
        # equal - but recv-heavy halo and the barrier bookkeeping must
        # keep every shard's slot ending together
        ends = {}
        for ev in evs:
            if ev["pid"] == 1:
                ends[ev["tid"]] = max(ends.get(ev["tid"], 0.0),
                                      ev["ts"] + ev["dur"])
        assert max(ends.values()) - min(ends.values()) < 1.0  # us


class TestCLIAcceptance:
    """ISSUE 4 acceptance: mesh-4 CLI --report - / --trace-perfetto."""

    def test_mesh4_report_and_perfetto(self, tmp_path, capsys):
        pf = tmp_path / "trace.json"
        rc = cli.main(["--problem", "poisson2d", "--n", "16",
                       "--mesh", "4", "--device", "cpu",
                       "--tol", "1e-6", "--maxiter", "200",
                       "--report", "-",
                       "--trace-perfetto", str(pf)])
        out = capsys.readouterr().out
        assert rc == 0
        # per-shard table with rows/nnz/halo-bytes columns
        assert "per-shard profile" in out
        assert "rows" in out and "nnz" in out and "halo out B/mv" in out
        # an imbalance factor and a roofline efficiency %
        assert "imbalance" in out and "max/mean" in out
        assert "roofline" in out and "efficiency" in out and "%" in out
        # the Perfetto file is loadable and structurally valid, with
        # one track per shard
        trace = json.loads(pf.read_text())
        treport.validate_perfetto(trace)
        shard_tids = {ev["tid"] for ev in trace["traceEvents"]
                      if ev["pid"] == 1 and ev["ph"] == "X"}
        assert shard_tids == {0, 1, 2, 3}

    def test_report_to_file_and_json_embed(self, tmp_path, capsys):
        path = tmp_path / "report.txt"
        rc = cli.main(["--problem", "poisson2d", "--n", "12",
                       "--device", "cpu", "--tol", "1e-7",
                       "--report", str(path), "--json"])
        rec = json.loads(capsys.readouterr().out)
        assert rc == 0
        text = path.read_text()
        assert "roofline" in text and "efficiency" in text
        assert "solve_report" in rec
        assert rec["solve_report"]["roofline"]["efficiency_pct"] >= 0
