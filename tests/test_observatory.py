"""Request observatory tests: causal tracing, SLO burn accounting,
metered usage (telemetry.tracing / telemetry.slo / serve.usage).

The acceptance surface of the observatory PR:

* trace completeness - EVERY terminal path of the service (success,
  ERROR-retry, TIMEOUT, breaker REFUSED, ADMISSION_REJECTED, mesh
  migration) produces a span chain reachable from its ``submit``
  root, with zero orphans, on both the manual fake-clock harness and
  a threaded mesh-4 replay;
* ``solve`` spans carry the real ``solve_id`` of their batch
  dispatch, joining the request view to the solve-level telemetry;
* SLO burn-rate trips are edge-triggered and bit-deterministic on
  the fake clock;
* the usage ledger's per-tenant shares reconcile with its batch
  totals to float round-off (gated 1e-9);
* zero perturbation - with tracing + SLO + usage all active the
  traced solve's jaxpr is bit-identical, and a traced replay's batch
  log matches an untraced one bit-for-bit;
* the registry's label-cardinality cap and the event sink's size
  rotation (satellites) hold under abuse.
"""
from __future__ import annotations

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cuda_mpi_parallel_tpu import telemetry
from cuda_mpi_parallel_tpu.models import poisson
from cuda_mpi_parallel_tpu.parallel import make_mesh
from cuda_mpi_parallel_tpu.serve import (
    AdmissionConfig,
    RetryPolicy,
    ServiceConfig,
    SolverService,
    TokenBucket,
    UsageLedger,
)
from cuda_mpi_parallel_tpu.telemetry import events, registry, tracing
from cuda_mpi_parallel_tpu.telemetry.slo import (
    SLOConfig,
    SLOTracker,
    SLOWindow,
)


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> float:
        self.t += dt
        return self.t


def manual_service(**kw):
    clock = FakeClock()
    kw.setdefault("max_batch", 4)
    kw.setdefault("max_wait_s", 0.010)
    kw.setdefault("maxiter", 500)
    svc = SolverService(ServiceConfig(clock=clock, **kw))
    return svc, clock


def poisson_csr(n=12, dtype=np.float64):
    return poisson.poisson_2d_csr(n, n, dtype=dtype)


def _captured(buf):
    return [json.loads(ln) for ln in buf.getvalue().splitlines()
            if ln.strip()]


def _rhs(a, rng):
    return np.asarray(a @ rng.standard_normal(a.shape[0]))


# ---------------------------------------------------------------------------
# W3C trace-context plumbing


class TestTraceparent:
    def test_round_trip(self):
        tid, sid = tracing.new_trace_id(), tracing.new_span_id()
        assert len(tid) == 32 and len(sid) == 16
        header = tracing.format_traceparent(tid, sid)
        assert tracing.parse_traceparent(header) == (tid, sid)

    def test_ids_unique_and_hex(self):
        tids = {tracing.new_trace_id() for _ in range(64)}
        assert len(tids) == 64
        assert all(not t.strip("0123456789abcdef") for t in tids)

    @pytest.mark.parametrize("bad", [
        "",
        "00-abc-def-01",
        "01-" + "a" * 32 + "-" + "b" * 16 + "-01",   # wrong version
        "00-" + "A" * 32 + "-" + "b" * 16 + "-01",   # uppercase hex
        "00-" + "0" * 32 + "-" + "b" * 16 + "-01",   # all-zero id
        "00-" + "a" * 32 + "-" + "b" * 16,           # missing flags
    ])
    def test_malformed_rejected(self, bad):
        with pytest.raises(ValueError):
            tracing.parse_traceparent(bad)

    def test_span_events_carry_traceparent(self):
        with events.capture() as buf:
            tr = tracing.RequestTrace("r-1")
            tr.span("submit", start_s=0.0, duration_s=0.0, root=True)
            tr.span("result", start_s=1.0, duration_s=0.0,
                    status="CONVERGED")
        recs = _captured(buf)
        assert all(
            tracing.parse_traceparent(r["traceparent"])
            == (r["trace_id"], r["span_id"]) for r in recs)


# ---------------------------------------------------------------------------
# forest analysis primitives


class TestSpanForest:
    def _chain(self):
        with events.capture() as buf:
            tr = tracing.RequestTrace("r-1")
            tr.span("submit", start_s=0.0, duration_s=0.0, root=True)
            tr.span("admission", start_s=0.0, duration_s=0.0,
                    decision="accepted")
            tr.span("queue_wait", start_s=0.0, duration_s=0.5)
            tr.span("result", start_s=0.5, duration_s=0.0,
                    status="CONVERGED")
        return _captured(buf)

    def test_complete_chain_has_no_orphans(self):
        recs = self._chain()
        assert tracing.orphan_spans(recs) == []
        forest = tracing.build_forest(recs)
        assert len(forest) == 1
        (tree,) = forest.values()
        assert tree["root"]["name"] == "submit"
        assert len(tree["spans"]) == 4

    def test_missing_root_orphans_whole_trace(self):
        recs = [r for r in self._chain() if r["name"] != "submit"]
        assert len(tracing.orphan_spans(recs)) == 3

    def test_dangling_parent_is_orphan(self):
        recs = self._chain()
        recs[2]["parent_span_id"] = "f" * 16    # nonexistent parent
        orphans = tracing.orphan_spans(recs)
        # queue_wait and everything chained under it fall off the tree
        assert {o["name"] for o in orphans} == {"queue_wait", "result"}

    def test_render_tree_relative_times(self):
        recs = self._chain()
        out = tracing.render_tree(recs, recs[0]["trace_id"])
        assert "submit" in out and "result" in out
        assert "[status=CONVERGED]" in out

    def test_unknown_span_name_rejected(self):
        tr = tracing.RequestTrace("r-1")
        with pytest.raises(ValueError, match="unknown span name"):
            tr.span("teleport", start_s=0.0, duration_s=0.0)


# ---------------------------------------------------------------------------
# trace completeness: every terminal path of the service


class TestTraceCompleteness:
    def test_success_path_full_chain(self):
        svc, clock = manual_service()
        a = poisson_csr()
        rng = np.random.default_rng(2)
        with events.capture() as buf:
            h = svc.register(a)
            futs = [svc.submit(h, _rhs(a, rng), tol=1e-8)
                    for _ in range(3)]
            clock.advance(0.011)
            svc.pump()
        try:
            assert all(f.result(timeout=10).converged for f in futs)
        finally:
            svc.close()
        recs = _captured(buf)
        assert tracing.orphan_spans(recs) == []
        forest = tracing.build_forest(recs)
        assert len(forest) == 3
        dispatch_ids = {e["solve_id"] for e in recs
                        if e["event"] == "batch_dispatch"}
        for tree in forest.values():
            names = [s["name"] for s in sorted(
                tree["spans"].values(),
                key=lambda s: (s["start_s"], s["span_id"]))]
            assert names[0] == "submit" and names[-1] == "result"
            assert set(names) == {"submit", "admission", "queue_wait",
                                  "sched", "solve", "result"}
            solve = next(s for s in tree["spans"].values()
                         if s["name"] == "solve")
            assert solve["solve_id"] in dispatch_ids
            result = next(s for s in tree["spans"].values()
                          if s["name"] == "result")
            assert result["status"] == "CONVERGED"

    def test_timeout_path_terminal_span(self):
        svc, clock = manual_service()
        a = poisson_csr()
        rng = np.random.default_rng(3)
        with events.capture() as buf:
            h = svc.register(a)
            fut = svc.submit(h, _rhs(a, rng), tol=1e-8,
                             deadline_s=0.001)
            clock.advance(0.011)
            svc.pump()
        try:
            assert fut.result(timeout=10).status == "TIMEOUT"
        finally:
            svc.close()
        recs = _captured(buf)
        assert tracing.orphan_spans(recs) == []
        results = [s for s in tracing.span_events(recs)
                   if s["name"] == "result"]
        assert [s["status"] for s in results] == ["TIMEOUT"]
        waits = [s for s in tracing.span_events(recs)
                 if s["name"] == "queue_wait"]
        assert waits and waits[0]["duration_s"] == pytest.approx(0.011)

    def test_admission_rejected_path(self):
        svc, clock = manual_service(
            admission=AdmissionConfig(
                default=TokenBucket(rate=0.001, burst=1)))
        a = poisson_csr()
        rng = np.random.default_rng(4)
        with events.capture() as buf:
            h = svc.register(a)
            ok = svc.submit(h, _rhs(a, rng), tol=1e-8)
            rejected = svc.submit(h, _rhs(a, rng), tol=1e-8)
            clock.advance(0.011)
            svc.pump()
        try:
            assert ok.result(timeout=10).converged
            assert rejected.result(timeout=10).status \
                == "ADMISSION_REJECTED"
        finally:
            svc.close()
        recs = _captured(buf)
        assert tracing.orphan_spans(recs) == []
        forest = tracing.build_forest(recs)
        rej_tree = next(
            t for t in forest.values()
            if any(s["name"] == "result"
                   and s["status"] == "ADMISSION_REJECTED"
                   for s in t["spans"].values()))
        admission = next(s for s in rej_tree["spans"].values()
                         if s["name"] == "admission")
        assert admission["decision"] == "rejected"
        # the rejected request never reached the queue or a solve
        assert {s["name"] for s in rej_tree["spans"].values()} \
            == {"submit", "admission", "result"}

    def test_refused_breaker_path(self):
        svc, clock = manual_service(max_batch=1, max_wait_s=0.0,
                                    breaker_threshold=1,
                                    breaker_cooldown_s=5.0)
        a = poisson_csr(8)
        rng = np.random.default_rng(5)

        def explode(*args, **kw):
            raise RuntimeError("engine down")

        with events.capture() as buf:
            h = svc.register(a)
            svc._engine = explode
            failed = svc.submit(h, _rhs(a, rng), tol=1e-8)
            svc.pump()
            refused = svc.submit(h, _rhs(a, rng), tol=1e-8)
        try:
            assert failed.result(timeout=10).status == "ERROR"
            assert refused.result(timeout=10).status == "REFUSED"
        finally:
            svc.close()
        recs = _captured(buf)
        assert tracing.orphan_spans(recs) == []
        forest = tracing.build_forest(recs)
        statuses = sorted(
            s["status"] for t in forest.values()
            for s in t["spans"].values() if s["name"] == "result")
        assert statuses == ["ERROR", "REFUSED"]
        ref_tree = next(
            t for t in forest.values()
            if any(s.get("status") == "REFUSED"
                   for s in t["spans"].values()))
        admission = next(s for s in ref_tree["spans"].values()
                         if s["name"] == "admission")
        assert admission["decision"] == "refused"
        assert admission["reason"] == "breaker_open"

    def test_retry_chains_attempts_in_one_trace(self):
        svc, clock = manual_service(
            max_batch=1, max_wait_s=0.0,
            retry=RetryPolicy(max_retries=1, backoff_s=0.5))
        a = poisson_csr(8)
        rng = np.random.default_rng(6)
        with events.capture() as buf:
            h = svc.register(a)
            orig, calls = svc._engine, [0]

            def flaky(*args, **kw):
                calls[0] += 1
                if calls[0] == 1:
                    raise RuntimeError("transient")
                return orig(*args, **kw)

            svc._engine = flaky
            fut = svc.submit(h, _rhs(a, rng), tol=1e-8)
            svc.pump()                   # attempt 1 fails, parks retry
            clock.advance(0.6)
            svc.pump()                   # attempt 2 converges
        try:
            res = fut.result(timeout=10)
            assert res.status == "CONVERGED" and res.attempts == 2
        finally:
            svc.close()
        recs = _captured(buf)
        assert tracing.orphan_spans(recs) == []
        forest = tracing.build_forest(recs)
        assert len(forest) == 1          # both attempts share ONE trace
        (tree,) = forest.values()
        names = [s["name"] for s in tree["spans"].values()]
        assert names.count("solve") == 2
        assert names.count("retry") == 1
        assert names.count("result") == 1
        solves = sorted((s for s in tree["spans"].values()
                         if s["name"] == "solve"),
                        key=lambda s: s["start_s"])
        assert solves[0]["status"] == "ERROR"
        assert solves[1]["status"] == "CONVERGED"

    def test_migration_span_joins_queued_traces(self):
        a = poisson_csr(16)    # 240-ish rows not needed; mesh divides
        svc, clock = manual_service()
        rng = np.random.default_rng(7)
        with events.capture() as buf:
            h = svc.register(a, mesh=make_mesh(4))
            futs = [svc.submit(h, _rhs(a, rng), tol=1e-8)
                    for _ in range(3)]
            svc.migrate(h, n_devices=2)
            clock.advance(1.0)
            svc.pump()
        try:
            assert all(f.result(timeout=30).converged for f in futs)
        finally:
            svc.close()
        recs = _captured(buf)
        assert tracing.orphan_spans(recs) == []
        forest = tracing.build_forest(recs)
        assert len(forest) == 3
        for tree in forest.values():
            mig = [s for s in tree["spans"].values()
                   if s["name"] == "migration"]
            assert len(mig) == 1
            assert (mig[0]["n_shards_from"],
                    mig[0]["n_shards_to"]) == (4, 2)

    @pytest.mark.parametrize("mesh_n", [4])
    def test_threaded_mesh_replay_every_done_traced(self, mesh_n,
                                                    tmp_path):
        """Real-clock threaded worker on a mesh-4 operator: every
        request_done event's request has a terminal result span and
        the forest has zero orphans - completeness under concurrency,
        not just under the manual pump."""
        path = str(tmp_path / "events.jsonl")
        telemetry.configure(path)
        a = poisson_csr(16)
        rng = np.random.default_rng(8)
        try:
            svc = SolverService(ServiceConfig(
                max_batch=4, max_wait_s=0.002, maxiter=500,
                usage=True))
            try:
                h = svc.register(a, mesh=make_mesh(mesh_n))
                futs = [svc.submit(h, _rhs(a, rng), tol=1e-8,
                                   tenant=f"t{i % 3}")
                        for i in range(12)]
                results = [f.result(timeout=60) for f in futs]
                ledger = svc.usage_ledger()
                assert ledger is not None
                assert ledger.reconcile() < 1e-9
            finally:
                svc.close()
        finally:
            telemetry.configure(None)
        assert all(r.converged for r in results)
        recs = events.read_events(path)
        assert tracing.orphan_spans(recs) == []
        spans = tracing.span_events(recs)
        result_rids = {s["request_id"] for s in spans
                       if s["name"] == "result"}
        done_rids = {e["request_id"] for e in recs
                     if e["event"] == "request_done"}
        assert done_rids and done_rids <= result_rids
        # solve spans join the batch telemetry by solve_id
        dispatch_ids = {e["solve_id"] for e in recs
                        if e["event"] == "batch_dispatch"}
        assert {s["solve_id"] for s in spans
                if s["name"] == "solve"} <= dispatch_ids


# ---------------------------------------------------------------------------
# SLO burn accounting


class TestSLOBurn:
    def _config(self, **kw):
        kw.setdefault("windows", (SLOWindow("fast", 10.0, 2.0),))
        kw.setdefault("budget", 0.1)
        kw.setdefault("min_samples", 4)
        return SLOConfig(**kw)

    def test_burn_trips_edge_triggered_and_rearms(self):
        tracker = SLOTracker(self._config())
        with events.capture() as buf:
            for i in range(4):
                tracker.observe("acme", "gold", float(i) * 0.1, True)
            # 4 good, then bad ones: at 4g/1b bad_ratio=0.2, burn=2.0
            tracker.observe("acme", "gold", 0.5, False)
            tracker.observe("acme", "gold", 0.6, False)   # still tripped
        burns = [r for r in _captured(buf) if r["event"] == "slo_burn"]
        assert len(burns) == 1            # edge-triggered, not repeated
        assert burns[0]["tenant"] == "acme"
        assert burns[0]["window"] == "fast"
        assert burns[0]["burn_rate"] >= 2.0
        # window rolls past the bad samples -> re-arms -> trips again
        with events.capture() as buf2:
            for i in range(8):
                tracker.observe("acme", "gold", 20.0 + i * 0.1, True)
            for i in range(3):
                tracker.observe("acme", "gold", 21.0 + i * 0.1, False)
        burns2 = [r for r in _captured(buf2)
                  if r["event"] == "slo_burn"]
        assert len(burns2) == 1

    def test_min_samples_floor_suppresses_cold_start(self):
        tracker = SLOTracker(self._config(min_samples=8))
        with events.capture() as buf:
            tracker.observe("acme", "gold", 0.0, False)
            tracker.observe("acme", "gold", 0.1, False)
        assert [r for r in _captured(buf)
                if r["event"] == "slo_burn"] == []
        assert tracker.burn_rate("acme", "gold", 0.2) == 0.0

    def test_burn_rate_hook_and_unknown_flow(self):
        tracker = SLOTracker(self._config())
        for i in range(4):
            tracker.observe("acme", "gold", float(i) * 0.01,
                            i % 2 == 0)   # 2 good / 2 bad
        assert tracker.burn_rate("acme", "gold", 0.05) \
            == pytest.approx(0.5 / 0.1)
        assert tracker.burn_rate("ghost", "gold", 0.05) == 0.0
        with pytest.raises(ValueError, match="unknown SLO window"):
            tracker.burn_rate("acme", "gold", 0.05, window="nope")

    def test_fake_clock_service_burn_deterministic(self):
        """The same scripted workload trips the same burn at the same
        service time, twice - rejections burn the rejected flow's
        budget and the trip count is exactly reproducible."""

        def run():
            svc, clock = manual_service(
                slo=SLOConfig(windows=(SLOWindow("fast", 5.0, 2.0),),
                              budget=0.1, min_samples=2),
                admission=AdmissionConfig(
                    default=TokenBucket(rate=0.001, burst=2)))
            a = poisson_csr()
            rng = np.random.default_rng(9)
            with events.capture() as buf:
                h = svc.register(a)
                futs = [svc.submit(h, _rhs(a, rng), tol=1e-8,
                                   tenant="hot")
                        for _ in range(2)]
                rejected = [svc.submit(h, _rhs(a, rng), tol=1e-8,
                                       tenant="hot")
                            for _ in range(2)]
                clock.advance(0.011)
                svc.pump()
            try:
                [f.result(timeout=10) for f in futs + rejected]
            finally:
                svc.close()
            return [
                (r["tenant"], r["slo_class"], r["window"],
                 r["burn_rate"], r["t_service"])
                for r in _captured(buf) if r["event"] == "slo_burn"]

        first, second = run(), run()
        assert first and first == second

    def test_stats_section_present(self):
        svc, clock = manual_service(slo=SLOConfig(min_samples=1))
        a = poisson_csr()
        rng = np.random.default_rng(10)
        try:
            h = svc.register(a)
            fut = svc.submit(h, _rhs(a, rng), tol=1e-8)
            clock.advance(0.011)
            svc.pump()
            assert fut.result(timeout=10).converged
            snap = svc.stats()["slo"]
            assert snap["budget"] == pytest.approx(0.01)
            (flow,) = snap["flows"].values()
            assert flow["fast"]["n"] == 1
            assert flow["fast"]["tripped"] is False
        finally:
            svc.close()

    def test_invalid_configs_rejected(self):
        with pytest.raises(ValueError, match="at least one window"):
            SLOConfig(windows=())
        with pytest.raises(ValueError, match="budget"):
            SLOConfig(budget=0.0)
        with pytest.raises(ValueError, match="seconds"):
            SLOWindow("w", 0.0, 1.0)
        with pytest.raises(TypeError):
            SolverService(ServiceConfig(slo=object()))


# ---------------------------------------------------------------------------
# metered usage


class TestUsageLedger:
    def test_apportionment_reconciles_exactly(self):
        ledger = UsageLedger()
        rng = np.random.default_rng(11)
        for i in range(50):
            m = int(rng.integers(1, 7))
            ledger.note_batch(
                solve_id=f"s{i}", handle="h", solve_s=float(
                    rng.uniform(1e-4, 0.3)),
                mesh_size=int(rng.integers(1, 5)),
                batch_iterations=int(rng.integers(1, 400)),
                wire_bytes_per_iteration=float(
                    rng.uniform(0.0, 1e6)),
                lanes=[{"request_id": f"r{i}-{j}",
                        "tenant": f"t{int(rng.integers(0, 5))}",
                        "slo_class": "silver", "iterations": 10,
                        "trace_id": None} for j in range(m)])
        assert ledger.reconcile() < 1e-9
        totals = ledger.batch_totals()
        per_tenant = ledger.per_tenant()
        assert totals["requests"] == sum(
            v["requests"] for v in per_tenant.values())

    def test_empty_batch_ignored(self):
        ledger = UsageLedger()
        ledger.note_batch(solve_id="s0", handle="h", solve_s=1.0,
                          mesh_size=4, batch_iterations=10,
                          wire_bytes_per_iteration=100.0, lanes=[])
        assert ledger.batch_totals()["batches"] == 0

    def test_device_seconds_scale_with_mesh(self):
        ledger = UsageLedger()
        ledger.note_batch(solve_id="s0", handle="h", solve_s=0.5,
                          mesh_size=4, batch_iterations=10,
                          wire_bytes_per_iteration=8.0,
                          lanes=[{"request_id": "r0", "tenant": "a",
                                  "slo_class": "gold",
                                  "iterations": 10,
                                  "trace_id": None}])
        totals = ledger.batch_totals()
        assert totals["device_seconds"] == pytest.approx(2.0)
        assert totals["wire_bytes"] == pytest.approx(80.0)

    def test_export_round_trips_through_usage_report(self, tmp_path):
        ledger = UsageLedger()
        for i in range(3):
            ledger.note_batch(
                solve_id=f"s{i}", handle="h", solve_s=0.1,
                mesh_size=2, batch_iterations=20,
                wire_bytes_per_iteration=64.0,
                lanes=[{"request_id": f"r{i}-{j}",
                        "tenant": ["acme", "bulkco"][j % 2],
                        "slo_class": "silver", "iterations": 20,
                        "trace_id": None} for j in range(3)])
        path = str(tmp_path / "usage.jsonl")
        n = ledger.export_jsonl(path)
        assert n == 9 + 3 + 1          # requests + batches + summary
        import subprocess
        import sys
        out = subprocess.run(
            [sys.executable, "tools/usage_report.py", path, "--json"],
            capture_output=True, text=True,
            cwd=os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))))
        assert out.returncode == 0, out.stderr
        rec = json.loads(out.stdout)
        assert rec["ok"] is True
        assert rec["per_tenant"]["acme"]["requests"] == 6
        assert rec["per_tenant"]["bulkco"]["requests"] == 3

    def test_usage_report_rejects_tampered_ledger(self, tmp_path):
        ledger = UsageLedger()
        ledger.note_batch(
            solve_id="s0", handle="h", solve_s=0.1, mesh_size=2,
            batch_iterations=20, wire_bytes_per_iteration=64.0,
            lanes=[{"request_id": "r0", "tenant": "acme",
                    "slo_class": "silver", "iterations": 20,
                    "trace_id": None}])
        path = str(tmp_path / "usage.jsonl")
        ledger.export_jsonl(path)
        lines = open(path).read().splitlines()
        doctored = []
        for ln in lines:
            rec = json.loads(ln)
            if rec["kind"] == "request":
                rec["device_seconds"] *= 2.0   # cook the books
            doctored.append(json.dumps(rec))
        with open(path, "w") as f:
            f.write("\n".join(doctored) + "\n")
        import subprocess
        import sys
        out = subprocess.run(
            [sys.executable, "tools/usage_report.py", path],
            capture_output=True, text=True,
            cwd=os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))))
        assert out.returncode == 1
        assert "reconcile" in out.stderr

    def test_service_meters_batches_and_emits_usage_events(self):
        svc, clock = manual_service(usage=True)
        a = poisson_csr()
        rng = np.random.default_rng(12)
        with events.capture() as buf:
            h = svc.register(a)
            futs = [svc.submit(h, _rhs(a, rng), tol=1e-8,
                               tenant=["acme", "bulkco"][i % 2])
                    for i in range(4)]
            clock.advance(0.011)
            svc.pump()
        try:
            assert all(f.result(timeout=10).converged for f in futs)
            snap = svc.stats()["usage"]
        finally:
            svc.close()
        assert snap["totals"]["requests"] == 4
        assert snap["reconcile_max_rel_err"] < 1e-9
        assert set(snap["per_tenant"]) == {"acme", "bulkco"}
        usages = [r for r in _captured(buf) if r["event"] == "usage"]
        assert usages                       # one per metered batch
        assert sum(u["n_requests"] for u in usages) == 4
        assert all(u["device_seconds"] > 0.0 for u in usages)

    def test_usage_off_is_free(self):
        svc, _ = manual_service()
        try:
            assert svc.usage_ledger() is None
            assert "usage" not in svc.stats()
        finally:
            svc.close()


# ---------------------------------------------------------------------------
# zero perturbation: the observatory must not touch the computation


class TestZeroPerturbation:
    def test_solver_jaxpr_identical_with_observatory_active(self):
        """The traced solve is bit-identical with tracing + SLO +
        usage all live (everything is host-side post-solve work)."""
        from cuda_mpi_parallel_tpu.solver import cg
        from cuda_mpi_parallel_tpu.models.operators import Stencil2D

        a = Stencil2D.create(16, 16, dtype=jnp.float64)
        b = jnp.ones(256)

        def jaxpr():
            return str(jax.make_jaxpr(
                lambda v: cg(a, v, maxiter=25))(b))

        telemetry.configure(None)
        telemetry.force_active(False)
        base = jaxpr()
        try:
            with events.capture():
                telemetry.force_active(True)
                tr = tracing.RequestTrace("probe")
                tr.span("submit", start_s=0.0, duration_s=0.0,
                        root=True)
                tracker = SLOTracker(SLOConfig(min_samples=1))
                tracker.observe("t", "gold", 0.0, True)
                ledger = UsageLedger()
                ledger.note_batch(
                    solve_id="s", handle="h", solve_s=0.1,
                    mesh_size=1, batch_iterations=1,
                    wire_bytes_per_iteration=0.0,
                    lanes=[{"request_id": "r", "tenant": "t",
                            "slo_class": "gold", "iterations": 1,
                            "trace_id": tr.trace_id}])
                instrumented = jaxpr()
        finally:
            telemetry.force_active(False)
        assert instrumented == base

    def test_batch_log_bit_identical_traced_vs_untraced(self):
        """The same fake-clock workload produces the same batch log -
        same solve outcomes, iterations, residuals - whether or not
        the observatory watched it."""

        def run(traced):
            svc, clock = manual_service(
                usage=traced,
                slo=SLOConfig(min_samples=1) if traced else None)
            a = poisson_csr()
            rng = np.random.default_rng(13)
            try:
                if traced:
                    with events.capture():
                        h = svc.register(a)
                        futs = [svc.submit(h, _rhs(a, rng), tol=1e-8)
                                for _ in range(4)]
                        clock.advance(0.011)
                        svc.pump()
                        results = [f.result(timeout=10) for f in futs]
                else:
                    h = svc.register(a)
                    futs = [svc.submit(h, _rhs(a, rng), tol=1e-8)
                            for _ in range(4)]
                    clock.advance(0.011)
                    svc.pump()
                    results = [f.result(timeout=10) for f in futs]
                log = svc.batch_log()
            finally:
                svc.close()
            outcomes = [(r.status, r.iterations,
                         float(r.residual_norm),
                         r.x.tobytes() if r.x is not None else None)
                        for r in results]
            # solve_id is per-run entropy and solve_s is real wall
            # time - both vary run to run with or without tracing
            slim = [{k: v for k, v in b.items()
                     if k not in ("solve_id", "solve_s")}
                    for b in log]
            return outcomes, slim

        assert run(traced=False) == run(traced=True)


# ---------------------------------------------------------------------------
# satellites: registry cardinality cap + event sink rotation


class TestLabelCardinalityCap:
    def test_ten_thousand_tenants_bounded(self, monkeypatch):
        monkeypatch.setattr(registry, "MAX_LABEL_SETS", 32)
        reg = registry.MetricsRegistry()
        c = reg.counter("tenant_requests_total", "per-tenant",
                        labelnames=("tenant",))
        for i in range(10_000):
            c.inc(1.0, tenant=f"tenant-{i}")
        series = c.snapshot()
        # 32 real series + the __other__ bucket, never 10k
        assert len(series) <= 33
        assert c.label_overflow == 10_000 - 32
        assert c.value(tenant="__other__") == 10_000 - 32
        # aggregate mass preserved
        assert sum(s["value"] for s in series) == 10_000
        text = reg.to_prometheus()
        assert "tenant_requests_total_label_overflow" in text
        assert text.count('tenant="tenant-') <= 32

    def test_existing_series_keep_updating_past_cap(self, monkeypatch):
        monkeypatch.setattr(registry, "MAX_LABEL_SETS", 2)
        reg = registry.MetricsRegistry()
        g = reg.gauge("tenant_depth", "", labelnames=("tenant",))
        g.set(1.0, tenant="a")
        g.set(2.0, tenant="b")
        g.set(9.0, tenant="c")           # new set past cap -> __other__
        g.set(5.0, tenant="a")           # existing set still addressable
        assert g.value(tenant="a") == 5.0
        assert g.value(tenant="__other__") == 9.0
        assert g.label_overflow == 1

    def test_histogram_capped_too(self, monkeypatch):
        monkeypatch.setattr(registry, "MAX_LABEL_SETS", 2)
        reg = registry.MetricsRegistry()
        hist = reg.histogram("lat", "", labelnames=("tenant",))
        for i in range(10):
            hist.observe(0.01, tenant=f"t{i}")
        assert hist.label_overflow == 8
        snap = reg.snapshot()["lat"]
        assert snap["label_overflow"] == 8


class TestEventRotation:
    def test_rotate_at_size_threshold(self, tmp_path):
        path = str(tmp_path / "ev.jsonl")
        telemetry.configure(path, rotate_bytes=2000)
        try:
            for i in range(100):
                events.emit("solve_start", label=f"solve-{i}",
                            padding="x" * 50)
        finally:
            telemetry.configure(None)
        rotated = path + ".1"
        assert os.path.exists(rotated)
        # the live file is bounded: rotation fires right after the
        # write that crosses the threshold
        assert os.path.getsize(path) < 2000 + 200
        assert os.path.getsize(rotated) < 2000 + 200
        # single-slot rotation: old generations are dropped, but the
        # retained tail is a torn-line-free contiguous suffix
        all_lines = (open(rotated).read().splitlines()
                     + open(path).read().splitlines())
        recs = [json.loads(ln) for ln in all_lines if ln.strip()]
        labels = [r["label"] for r in recs]
        n = len(labels)
        assert 0 < n < 100
        assert labels == [f"solve-{i}" for i in range(100 - n, 100)]

    def test_no_rotation_without_opt_in(self, tmp_path):
        path = str(tmp_path / "ev.jsonl")
        telemetry.configure(path)
        try:
            for i in range(50):
                events.emit("solve_start", label=f"s{i}",
                            padding="y" * 100)
        finally:
            telemetry.configure(None)
        assert not os.path.exists(path + ".1")
        assert len(events.read_events(path)) == 50

    def test_stream_sink_ignores_rotation(self):
        import io
        buf = io.StringIO()
        stream = events.EventStream(buf, rotate_bytes=100)
        for i in range(20):
            stream.emit("solve_start", label=f"s{i}")
        recs = [json.loads(ln) for ln in
                buf.getvalue().splitlines() if ln.strip()]
        assert len(recs) == 20
