"""Distributed-path tests on 8 virtual CPU devices (SURVEY SS4 'Distributed
without a cluster'): psum dots, ppermute halo exchange, shard_map CG.

The load-bearing property: an N-device run is the *same algorithm* as the
1-device run - trajectories (iteration counts, residuals, solutions) must
match to rounding.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cuda_mpi_parallel_tpu.utils.compat import shard_map
from jax.sharding import PartitionSpec as P

from cuda_mpi_parallel_tpu import solve
from cuda_mpi_parallel_tpu.models import poisson
from cuda_mpi_parallel_tpu.models.operators import Stencil2D, Stencil3D
from cuda_mpi_parallel_tpu.parallel import (
    DistStencil3D,
    exchange_halo,
    make_mesh,
    partition_csr,
    solve_distributed,
)

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (virtual) devices")


class TestHalo:
    def test_exchange_matches_neighbor_planes(self):
        mesh = make_mesh(8)
        n_per = 4
        u = jnp.arange(8 * n_per * 3, dtype=jnp.float64).reshape(8 * n_per, 3)

        def body(u_local):
            lo, hi = exchange_halo(u_local, "rows", 8)
            return lo, hi

        lo, hi = jax.jit(shard_map(
            body, mesh=mesh, in_specs=P("rows"),
            out_specs=(P("rows"), P("rows"))))(u)
        lo = np.asarray(lo).reshape(8, 3)
        hi = np.asarray(hi).reshape(8, 3)
        un = np.asarray(u).reshape(8, n_per, 3)
        # shard 0 has no lower neighbor -> zeros (Dirichlet for free)
        np.testing.assert_array_equal(lo[0], np.zeros(3))
        np.testing.assert_array_equal(hi[7], np.zeros(3))
        for s in range(1, 8):
            np.testing.assert_array_equal(lo[s], un[s - 1, -1])
        for s in range(7):
            np.testing.assert_array_equal(hi[s], un[s + 1, 0])


class TestDistStencilSpMV:
    def test_3d_sharded_matvec_equals_global(self):
        """Property: sharded SpMV == unsharded SpMV (SURVEY SS4)."""
        nx, ny, nz = 16, 5, 7
        mesh = make_mesh(8)
        op_global = Stencil3D.create(nx, ny, nz, scale=1.7, dtype=jnp.float64)
        x = jnp.asarray(np.random.default_rng(0).standard_normal(nx * ny * nz))
        want = op_global @ x

        local = DistStencil3D.create((nx, ny, nz), 8, scale=1.7,
                                     dtype=jnp.float64)
        got = jax.jit(shard_map(
            lambda v: local @ v, mesh=mesh, in_specs=P("rows"),
            out_specs=P("rows")))(x)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-12, atol=1e-12)

    def test_2d_solve_matches_single_device(self):
        nx = ny = 16
        a = Stencil2D.create(nx, ny, dtype=jnp.float64)
        b = jnp.asarray(np.random.default_rng(1).standard_normal(nx * ny))
        single = solve(a, b, tol=1e-10, maxiter=600)
        dist = solve_distributed(a, b, mesh=make_mesh(8), tol=1e-10,
                                 maxiter=600)
        assert bool(dist.converged)
        assert int(dist.iterations) == int(single.iterations)
        np.testing.assert_allclose(np.asarray(dist.x), np.asarray(single.x),
                                   atol=1e-8)

    def test_3d_solve_converges(self):
        a = Stencil3D.create(16, 6, 6, dtype=jnp.float64)
        x_true = np.random.default_rng(2).standard_normal(16 * 36)
        b = a @ jnp.asarray(x_true)
        res = solve_distributed(a, b, mesh=make_mesh(8), tol=1e-9,
                                maxiter=600)
        assert bool(res.converged)
        np.testing.assert_allclose(np.asarray(res.x), x_true, atol=1e-6)

    def test_short_rhs_raises(self):
        """A wrong-length b must be rejected, not silently zero-padded."""
        a = poisson.poisson_2d_csr(6, 7)  # n=42
        with pytest.raises(ValueError, match="does not match"):
            solve_distributed(a, jnp.ones(30), mesh=make_mesh(8))

    def test_indivisible_grid_raises(self):
        a = Stencil2D.create(10, 10, dtype=jnp.float64)
        with pytest.raises(ValueError, match="not divisible"):
            solve_distributed(a, jnp.ones(100), mesh=make_mesh(8))


class TestDistCSR:
    def test_partition_reassembles(self):
        a = poisson.poisson_2d_csr(6, 7)  # n=42, not divisible by 8
        parts = partition_csr(a, 8)
        assert parts.n_global == 42
        assert parts.n_global_padded == 48
        dense = np.zeros((48, 48))
        for s in range(8):
            for e in range(parts.data.shape[1]):
                r = parts.local_rows[s, e] + s * parts.n_local
                dense[r, parts.cols[s, e]] += parts.data[s, e]
        want = np.zeros((48, 48))
        want[:42, :42] = np.asarray(a.to_dense())
        want[42:, 42:] = np.eye(6)  # unit-diagonal padding rows
        np.testing.assert_allclose(dense, want)

    def test_csr_solve_matches_single_device(self):
        a = poisson.poisson_2d_csr(9, 6)  # n=54, padded to 56
        b = jnp.asarray(np.random.default_rng(3).standard_normal(54))
        single = solve(a, b, tol=1e-10, maxiter=400)
        dist = solve_distributed(a, b, mesh=make_mesh(8), tol=1e-10,
                                 maxiter=400)
        assert bool(dist.converged)
        assert dist.x.shape == (54,)
        np.testing.assert_allclose(np.asarray(dist.x), np.asarray(single.x),
                                   atol=1e-8)
        assert abs(int(dist.iterations) - int(single.iterations)) <= 1

    def test_csr_jacobi_distributed(self):
        a = poisson.poisson_2d_csr(8, 8)
        b = jnp.asarray(np.random.default_rng(4).standard_normal(64))
        dist = solve_distributed(a, b, mesh=make_mesh(8), tol=1e-10,
                                 maxiter=400, preconditioner="jacobi")
        assert bool(dist.converged)
        np.testing.assert_allclose(
            np.asarray(a @ dist.x), np.asarray(b), atol=1e-8)

    def test_oracle_distributed(self):
        """The 3x3 reference system, row-partitioned over 8 devices (5 of
        which hold only padding rows) - must still converge to the
        documented solution."""
        a, b, x_expected = poisson.oracle_system()
        res = solve_distributed(a, b, mesh=make_mesh(8), tol=1e-7,
                                maxiter=2000)
        assert bool(res.converged)
        np.testing.assert_allclose(np.asarray(res.x), x_expected, atol=1e-8)


class TestMeshSizes:
    @pytest.mark.parametrize("ndev", [1, 2, 4, 8])
    def test_solution_invariant_across_mesh_sizes(self, ndev):
        a = Stencil2D.create(16, 12, dtype=jnp.float64)
        b = jnp.asarray(np.random.default_rng(5).standard_normal(192))
        res = solve_distributed(a, b, mesh=make_mesh(ndev), tol=1e-10,
                                maxiter=500)
        assert bool(res.converged)
        np.testing.assert_allclose(np.asarray(a @ res.x), np.asarray(b),
                                   atol=1e-8)


class TestDistributedVariants:
    """cg1 / check_every / compensated under shard_map (one psum per
    iteration for cg1 - the distributed raison d'etre of the variant)."""

    def test_cg1_distributed_matches_single(self):
        a = Stencil2D.create(16, 16, dtype=jnp.float64)
        b = jnp.asarray(np.random.default_rng(6).standard_normal(256))
        single = solve(a, b, tol=1e-10, maxiter=600, method="cg1")
        dist = solve_distributed(a, b, mesh=make_mesh(8), tol=1e-10,
                                 maxiter=600, method="cg1")
        assert bool(dist.converged)
        assert abs(int(dist.iterations) - int(single.iterations)) <= 1
        np.testing.assert_allclose(np.asarray(dist.x), np.asarray(single.x),
                                   atol=1e-8)

    def test_cg1_single_psum_per_iteration(self):
        """Structural check: the compiled cg1 body contains ONE all-reduce
        per iteration, the textbook body two (count in compiled HLO)."""
        from functools import partial
        from jax.sharding import PartitionSpec as P2

        from cuda_mpi_parallel_tpu.parallel import DistStencil2D
        from cuda_mpi_parallel_tpu.solver.cg import cg

        mesh = make_mesh(8)
        local = DistStencil2D.create((16, 16), 8, dtype=jnp.float64)
        b = jnp.asarray(np.random.default_rng(7).standard_normal(256))

        def counts(method):
            @partial(shard_map, mesh=mesh, in_specs=P2("rows"),
                     out_specs=P2("rows"))
            def run(b_local):
                return cg(local, b_local, tol=1e-10, maxiter=50,
                          axis_name="rows", method=method).x

            hlo = jax.jit(run).lower(b).compile().as_text()
            body = [ln for ln in hlo.splitlines() if "all-reduce" in ln
                    and "start" not in ln]
            return len(body)

        # Loop-body all-reduces only (init ones are outside the while);
        # exact totals depend on XLA fusion, so compare relative counts.
        assert counts("cg1") < counts("cg")

    def test_check_every_distributed(self):
        a = Stencil2D.create(16, 12, dtype=jnp.float64)
        b = jnp.asarray(np.random.default_rng(8).standard_normal(192))
        base = solve_distributed(a, b, mesh=make_mesh(8), tol=1e-10,
                                 maxiter=500)
        var = solve_distributed(a, b, mesh=make_mesh(8), tol=1e-10,
                                maxiter=500, check_every=4)
        kb, kv = int(base.iterations), int(var.iterations)
        assert kb <= kv <= kb + 3
        # extra block iterations only improve the residual
        res_base = float(jnp.max(jnp.abs(a @ base.x - b)))
        res_var = float(jnp.max(jnp.abs(a @ var.x - b)))
        assert res_var <= res_base * (1 + 1e-9)
        # and the blocked run matches the single-device blocked run
        single = solve(a, b, tol=1e-10, maxiter=500, check_every=4)
        np.testing.assert_allclose(np.asarray(var.x), np.asarray(single.x),
                                   rtol=1e-9, atol=1e-11)

    def test_compensated_distributed_f32(self):
        a = Stencil2D.create(16, 16, dtype=jnp.float32)
        b = jnp.asarray(
            np.random.default_rng(9).standard_normal(256).astype(np.float32))
        res = solve_distributed(a, b, mesh=make_mesh(8), tol=0.0, rtol=1e-5,
                                maxiter=800, compensated=True)
        assert bool(res.converged)
        np.testing.assert_allclose(np.asarray(a @ res.x), np.asarray(b),
                                   atol=2e-3)


class TestFlightRecorderDistributed:
    """The convergence flight recorder under shard_map: the recorded
    scalars are the psum'd globals, so the fetched record must be the
    single trace of the GLOBAL solve - monotone, decimated, and
    matching the dense distributed history at the sampled iterations.
    """

    def _system(self, n=24):
        a = Stencil2D.create(n, n, dtype=jnp.float32)
        rng = np.random.default_rng(11)
        b = np.asarray(rng.standard_normal(n * n), dtype=np.float32)
        return a, b

    def test_mesh4_flight_monotone_decimated(self):
        from cuda_mpi_parallel_tpu.telemetry.flight import (
            FlightConfig,
            FlightRecord,
        )

        a, b = self._system()
        res = solve_distributed(
            a, b, mesh=make_mesh(4), tol=1e-5, maxiter=400,
            record_history=True,
            flight=FlightConfig.for_solve(400, stride=3))
        rec = FlightRecord.from_buffer(res.flight)
        assert rec.stride == 3
        assert len(rec) >= 4
        assert np.all(np.diff(rec.iterations) == 3)   # monotone, gapless
        assert np.all(rec.iterations % 3 == 0)
        # the decimated rows ARE the dense distributed trace sampled:
        # the loop's psum'd rr feeds both
        hist = np.asarray(res.residual_history)
        assert np.array_equal(rec.residuals.astype(np.float32),
                              hist[rec.iterations].astype(np.float32))

    def test_mesh4_stride1_matches_single_device_trajectory(self):
        from cuda_mpi_parallel_tpu.telemetry.flight import (
            FlightConfig,
            FlightRecord,
        )

        a, b = self._system()
        cfg = FlightConfig.for_solve(400, stride=1)
        res_d = solve_distributed(a, b, mesh=make_mesh(4), tol=1e-5,
                                  maxiter=400, flight=cfg)
        res_s = solve(a, jnp.asarray(b), tol=1e-5, maxiter=400,
                      flight=cfg)
        rec_d = FlightRecord.from_buffer(res_d.flight)
        rec_s = FlightRecord.from_buffer(res_s.flight)
        assert rec_d.iterations[-1] == rec_s.iterations[-1]
        # same algorithm: trajectories agree to psum-tree rounding
        np.testing.assert_allclose(rec_d.residuals, rec_s.residuals,
                                   rtol=2e-3)

    def test_mesh4_cli_flight_record_history(self, tmp_path, capsys):
        """ISSUE acceptance: with --flight-record, --history works
        under --mesh 4 and the solve_health verdict rides the record;
        the printed decimated trace is monotone."""
        import json as _json

        from cuda_mpi_parallel_tpu import cli
        from cuda_mpi_parallel_tpu.parallel import dist_cg
        from cuda_mpi_parallel_tpu.telemetry import (
            configure as _tconf,
            force_active as _tforce,
        )

        trace = tmp_path / "flight_trace.jsonl"
        dist_cg.clear_solver_cache()
        try:
            rc = cli.main(["--problem", "poisson2d", "--n", "32",
                           "--matrix-free", "--mesh", "4",
                           "--tol", "1e-5", "--flight-record", "2",
                           "--history", "--json",
                           "--trace-events", str(trace)])
        finally:
            _tconf(None)
            _tforce(False)
            dist_cg.clear_solver_cache()
        assert rc == 0
        rec = _json.loads(capsys.readouterr().out)
        assert rec["converged"] is True
        assert rec["flight"]["stride"] == 2
        assert rec["flight"]["n_records"] >= 4
        assert rec["health"]["classification"] == "CONVERGED"
        lines = [_json.loads(ln)
                 for ln in trace.read_text().splitlines()]
        sel = [ln for ln in lines if ln["event"] == "engine_selected"]
        assert sel and all(ln["flight_stride"] == 2 for ln in sel)
        assert any(ln["event"] == "solve_health" for ln in lines)
