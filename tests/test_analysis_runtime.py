"""``analysis.runtime.check_races``: the promoted interpret-mode race
gate (satellite of the graftlint PR).

The reconstruction kernels re-create the round-5 rho-buffer race in
miniature: an all-to-all scalar exchange where every shard RDMA-pushes
its row into a peer buffer.  Pushing into the sender-OWNED row (the
shipped allreduce design) is race-free by construction; pushing into a
single CONTESTED row reproduces the bug class the advisor caught -
two non-neighbor writers racing into one slot, invisible at 2 shards
where every pair is a neighbor pair.  The tests assert the detector
(via check_races) distinguishes the two, i.e. the gate actually gates.

Everything here skips cleanly on jax builds without the TPU-interpret
simulator - but check_races must then RAISE, never report a false
"no races" (asserted below in the env-independent test).
"""
import numpy as np
import pytest

from cuda_mpi_parallel_tpu.utils.compat import shard_map

from cuda_mpi_parallel_tpu.analysis.runtime import (
    RaceDetectorUnavailable,
    RaceReport,
    check_races,
)


def _detector_available() -> bool:
    from cuda_mpi_parallel_tpu.analysis.runtime import _detector_module

    try:
        _detector_module()
        return True
    except RaceDetectorUnavailable:
        return False


def test_unavailable_detector_raises_not_lies():
    """A missing simulator must be loud: silently returning
    races_found=False would turn the race gate into a rubber stamp."""
    if _detector_available():
        pytest.skip("detector present; the negative path is moot here")
    with pytest.raises(RaceDetectorUnavailable, match="race detector"):
        check_races(lambda: None)


def test_report_truthiness():
    assert bool(RaceReport(races_found=True))
    assert not bool(RaceReport(races_found=False))


def test_unconfirmable_detection_warns():
    """A kernel with no detect_races knob cannot be rubber-stamped: the
    helper must warn and record detection_confirmed=False."""
    if not _detector_available():
        pytest.skip("needs the detector (the unavailable path raises "
                    "before the trust-boundary warning)")
    with pytest.warns(RuntimeWarning, match="detect_races"):
        report = check_races(lambda: None)
    assert report.detection_confirmed is False


def _row_push(n_shards: int, contested: bool, detect_races: bool = True):
    """All-to-all row push over a 1-D mesh, one pallas kernel per shard.

    ``contested=False``: each shard's row lands in row ``my_id`` of
    every peer's buffer (sender-owned slots - resident_dist.py's
    allreduce).  ``contested=True``: every shard pushes into row 0
    (the rho-buffer-reuse class: with n >= 3, two writers race).
    """
    import functools

    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    from jax.sharding import PartitionSpec as P

    from cuda_mpi_parallel_tpu.parallel import make_mesh

    mesh = make_mesh(n_shards)
    axis = mesh.axis_names[0]

    def kernel(x_ref, out_ref, buf, send, recv):
        my_id = lax.axis_index(axis)
        ns = jnp.int32(n_shards)
        buf[pl.ds(my_id, 1)] = x_ref[:]
        dmas = []
        for step in range(1, n_shards):
            tgt = lax.rem(my_id + jnp.int32(step), ns)
            dst = (buf.at[pl.ds(0, 1)] if contested  # graftlint: disable=mosaic-tiling
                   else buf.at[pl.ds(my_id, 1)])  # graftlint: disable=mosaic-tiling
            dma = pltpu.make_async_remote_copy(
                buf.at[pl.ds(my_id, 1)],  # graftlint: disable=mosaic-tiling
                dst, send.at[step - 1], recv.at[step - 1],
                device_id=tgt,
                device_id_type=pltpu.DeviceIdType.LOGICAL)
            dma.start()
            dmas.append(dma)
        for dma in dmas:
            dma.wait()
        out_ref[:] = jnp.sum(buf[:], axis=0, keepdims=True)

    @functools.partial(shard_map, mesh=mesh, in_specs=(P(axis),),
                       out_specs=P(axis), check_vma=False)
    def run(x_local):
        return pl.pallas_call(
            kernel,
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((1, 128), jnp.float32),
            scratch_shapes=[
                pltpu.VMEM((n_shards, 128), jnp.float32),
                pltpu.SemaphoreType.DMA((max(n_shards - 1, 1),)),
                pltpu.SemaphoreType.DMA((max(n_shards - 1, 1),)),
            ],
            interpret=pltpu.InterpretParams(
                dma_execution_mode="eager",
                uninitialized_memory="zero",
                detect_races=detect_races),
        )(x_local)

    x = jnp.asarray(
        np.arange(n_shards * 128, dtype=np.float32).reshape(n_shards, 128))
    return run(x)


@pytest.mark.skipif(not _detector_available(),
                    reason="this jax has no TPU-interpret race detector")
class TestRhoBufferReconstruction:
    def test_contested_slot_race_detected(self):
        # n=4, not 2: the round-5 race only exists between
        # NON-neighbors, and every 2-shard pair is a neighbor pair.
        # The **kw passthrough lets check_races inject detect_races
        # itself (detection_confirmed must come back True).
        report = check_races(
            lambda **kw: _row_push(4, contested=True, **kw))
        assert report.races_found
        assert report.detection_confirmed

    def test_owned_slot_clean(self):
        report = check_races(
            lambda **kw: _row_push(4, contested=False, **kw))
        assert not report.races_found
        assert report.detection_confirmed

    def test_state_resets_between_checks(self):
        # a racy run must not poison the next clean run's verdict
        racy = check_races(
            lambda **kw: _row_push(4, contested=True, **kw))
        clean = check_races(
            lambda **kw: _row_push(4, contested=False, **kw))
        assert racy.races_found and not clean.races_found
