"""utils.logging regression tests: strict-JSON records and complete
residual traces.

Two observability bugs fixed in the telemetry PR:

* ``solve_record``/``emit_json`` produced the non-JSON ``NaN`` /
  ``Infinity`` literals whenever a BREAKDOWN solve carried a non-finite
  ``residual_norm`` (quirk-Q4 solves do, by definition);
* ``format_history(every=k)`` silently dropped the final converged
  iteration whenever k did not divide ``result.iterations``.
"""
import io
import json
import math

import numpy as np
import pytest

from cuda_mpi_parallel_tpu.solver.status import CGStatus
from cuda_mpi_parallel_tpu.utils import logging as ulog


def _result(iterations=7, residual=1e-8, status=CGStatus.CONVERGED,
            history=None, indefinite=False):
    class R:
        pass

    r = R()
    r.iterations = iterations
    r.residual_norm = residual
    r.converged = status == CGStatus.CONVERGED
    r.indefinite = indefinite
    r.residual_history = history
    r.status_enum = lambda: status
    return r


class TestSanitize:
    def test_nonfinite_floats_become_null(self):
        rec = ulog.sanitize({"a": float("nan"), "b": float("inf"),
                             "c": [1.0, float("-inf")], "d": "NaN-str",
                             "e": 2})
        assert rec["a"] is None and rec["b"] is None
        assert rec["c"] == [1.0, None]
        assert rec["d"] == "NaN-str" and rec["e"] == 2

    def test_numpy_scalars_unwrapped(self):
        rec = ulog.sanitize({"f": np.float64("nan"),
                             "i": np.int32(3),
                             "ok": np.float32(1.5)})
        assert rec["f"] is None
        assert rec["i"] == 3 and isinstance(rec["i"], int)
        assert rec["ok"] == 1.5


class TestEmitJsonBreakdown:
    def test_breakdown_record_is_valid_json(self):
        """Regression: a NaN residual used to serialize as the literal
        ``NaN``, which strict JSON parsers reject."""
        res = _result(iterations=12, residual=float("nan"),
                      status=CGStatus.BREAKDOWN)
        rec = ulog.solve_record(res, elapsed_s=0.5, problem="breakdown")
        buf = io.StringIO()
        ulog.emit_json(rec, stream=buf)
        line = buf.getvalue()
        assert "NaN" not in line and "Infinity" not in line
        parsed = json.loads(line)
        assert parsed["status"] == "BREAKDOWN"
        assert parsed["residual_norm"] is None
        assert parsed["iterations"] == 12

    def test_finite_record_roundtrips_unchanged(self):
        res = _result()
        rec = ulog.solve_record(res, elapsed_s=2.0, extra="kept")
        buf = io.StringIO()
        ulog.emit_json(rec, stream=buf)
        parsed = json.loads(buf.getvalue())
        assert parsed["residual_norm"] == pytest.approx(1e-8)
        assert parsed["iters_per_sec"] == pytest.approx(3.5)
        assert parsed["extra"] == "kept"


class TestFormatHistory:
    def _hist(self, k, maxiter=32):
        h = np.full(maxiter + 1, np.nan)
        h[: k + 1] = np.logspace(0, -k, k + 1)
        return h

    def test_every_divides_keeps_last(self):
        res = _result(iterations=6, history=self._hist(6))
        out = ulog.format_history(res, every=3)
        assert "iter     6" in out

    def test_final_entry_always_printed(self):
        """Regression: every=k with k not dividing iterations dropped
        the converged iteration's line entirely."""
        res = _result(iterations=7, history=self._hist(7))
        out = ulog.format_history(res, every=3)
        lines = out.splitlines()
        assert any("iter     7" in ln for ln in lines)
        # stride entries still present, in order, no duplicates
        iters = [int(ln.split()[1]) for ln in lines]
        assert iters == [0, 3, 6, 7]

    def test_block_granular_trace_falls_back_to_last_finite(self):
        # resident-engine style trace: values only at block boundaries
        h = np.full(33, np.nan)
        h[0], h[8], h[16] = 1.0, 0.1, 0.01
        res = _result(iterations=20, history=h)
        out = ulog.format_history(res, every=16)
        iters = [int(ln.split()[1]) for ln in out.splitlines()]
        # 20 is NaN in the trace; the last finite entry (16) must close
        # the trace instead of vanishing
        assert iters == [0, 16]

    def test_no_history(self):
        assert "not recorded" in ulog.format_history(_result())
