"""Fused-iteration HBM-streaming CG engine (``ops/pallas/fused_cg.py`` +
``solver/streaming.py``).

All kernel runs use interpret mode (CPU CI); parity is checked against
the general ``solver.cg`` path (oracle-verified in ``test_cg.py``) and
the raw passes against the reference operators.  On hardware the engine
targets BASELINE config #4 (256^3): 8 HBM plane-passes per iteration vs
the general solver's ~16.
"""
import numpy as np
import pytest

import jax.numpy as jnp

from cuda_mpi_parallel_tpu import cg_streaming, solve, supports_streaming_op
from cuda_mpi_parallel_tpu.models import poisson
from cuda_mpi_parallel_tpu.models.operators import Stencil2D, Stencil3D
from cuda_mpi_parallel_tpu.ops.pallas.fused_cg import (
    fused_cg_pass_a,
    fused_cg_pass_b,
    pick_block_streaming,
    supports_streaming,
)
from cuda_mpi_parallel_tpu.solver.status import CGStatus
from cuda_mpi_parallel_tpu.solver.streaming import streaming_eligible


def _problem_2d(nx=32, ny=128, seed=0):
    op = poisson.poisson_2d_operator(nx, ny, dtype=jnp.float32)
    rng = np.random.default_rng(seed)
    b = rng.standard_normal(nx * ny).astype(np.float32)
    return op, b


class TestPasses:
    """The two slab-streaming passes against the reference operators."""

    def test_pass_a_matches_reference_2d(self):
        nx, ny = 32, 128
        op = Stencil2D.create(nx, ny, scale=0.25, dtype=jnp.float32)
        rng = np.random.default_rng(1)
        r = rng.standard_normal((nx, ny)).astype(np.float32)
        p = rng.standard_normal((nx, ny)).astype(np.float32)
        beta = np.float32(0.37)
        bm = pick_block_streaming((nx, ny))
        pnew, pap = fused_cg_pass_a(0.25, beta, jnp.asarray(r),
                                    jnp.asarray(p), bm=bm, interpret=True)
        pnew_ref = r + beta * p
        ap_ref = np.asarray(
            op.matvec(jnp.asarray(pnew_ref.ravel()))).reshape(nx, ny)
        np.testing.assert_allclose(np.asarray(pnew), pnew_ref,
                                   rtol=1e-4, atol=1e-6)
        np.testing.assert_allclose(float(pap),
                                   float((pnew_ref * ap_ref).sum()),
                                   rtol=1e-4)

    def test_pass_b_matches_reference_2d(self):
        nx, ny = 32, 128
        op = Stencil2D.create(nx, ny, scale=0.25, dtype=jnp.float32)
        rng = np.random.default_rng(2)
        pnew = rng.standard_normal((nx, ny)).astype(np.float32)
        x = rng.standard_normal((nx, ny)).astype(np.float32)
        r = rng.standard_normal((nx, ny)).astype(np.float32)
        alpha = np.float32(0.11)
        bm = pick_block_streaming((nx, ny))
        xn, rn, rr = fused_cg_pass_b(0.25, alpha, jnp.asarray(pnew),
                                     jnp.asarray(x), jnp.asarray(r),
                                     bm=bm, interpret=True)
        ap_ref = np.asarray(
            op.matvec(jnp.asarray(pnew.ravel()))).reshape(nx, ny)
        np.testing.assert_allclose(np.asarray(xn), x + alpha * pnew,
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(rn), r - alpha * ap_ref,
                                   rtol=1e-3, atol=1e-5)
        np.testing.assert_allclose(
            float(rr), float(((r - alpha * ap_ref) ** 2).sum()), rtol=1e-3)

    def test_passes_match_reference_3d(self):
        g3 = (8, 16, 128)
        op3 = Stencil3D.create(*g3, scale=0.5, dtype=jnp.float32)
        rng = np.random.default_rng(3)
        r3 = rng.standard_normal(g3).astype(np.float32)
        p3 = rng.standard_normal(g3).astype(np.float32)
        x3 = rng.standard_normal(g3).astype(np.float32)
        beta, alpha = np.float32(0.37), np.float32(0.11)
        bm = pick_block_streaming(g3)
        pn3, pap3 = fused_cg_pass_a(0.5, beta, jnp.asarray(r3),
                                    jnp.asarray(p3), bm=bm, interpret=True)
        pn3_ref = r3 + beta * p3
        ap3_ref = np.asarray(
            op3.matvec(jnp.asarray(pn3_ref.ravel()))).reshape(g3)
        np.testing.assert_allclose(np.asarray(pn3), pn3_ref,
                                   rtol=1e-4, atol=1e-6)
        np.testing.assert_allclose(float(pap3),
                                   float((pn3_ref * ap3_ref).sum()),
                                   rtol=1e-4)
        xn3, rn3, rr3 = fused_cg_pass_b(0.5, alpha, pn3, jnp.asarray(x3),
                                        jnp.asarray(r3), bm=bm,
                                        interpret=True)
        np.testing.assert_allclose(np.asarray(xn3), x3 + alpha * pn3_ref,
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(rn3), r3 - alpha * ap3_ref,
                                   rtol=1e-3, atol=1e-5)
        np.testing.assert_allclose(
            float(rr3), float(((r3 - alpha * ap3_ref) ** 2).sum()),
            rtol=1e-3)

    def test_single_block_grid(self):
        # nblocks == 1 exercises the clamped-DMA edge branch
        nx, ny = 8, 128
        rng = np.random.default_rng(4)
        r = rng.standard_normal((nx, ny)).astype(np.float32)
        p = rng.standard_normal((nx, ny)).astype(np.float32)
        op = Stencil2D.create(nx, ny, scale=1.0, dtype=jnp.float32)
        pnew, pap = fused_cg_pass_a(1.0, np.float32(0.5), jnp.asarray(r),
                                    jnp.asarray(p), bm=8, interpret=True)
        pnew_ref = r + 0.5 * p
        ap_ref = np.asarray(
            op.matvec(jnp.asarray(pnew_ref.ravel()))).reshape(nx, ny)
        np.testing.assert_allclose(np.asarray(pnew), pnew_ref,
                                   rtol=1e-4, atol=1e-6)
        np.testing.assert_allclose(float(pap),
                                   float((pnew_ref * ap_ref).sum()),
                                   rtol=1e-4)


class TestTrajectoryParity:
    """Iteration counts equal to the general solver at equal tolerances
    (the VERDICT bar for the 256^3 fused path)."""

    def test_2d_iteration_exact(self):
        op, b = _problem_2d()
        ref = solve(op, jnp.asarray(b), tol=1e-5, maxiter=500,
                    check_every=1)
        res = cg_streaming(op, jnp.asarray(b), tol=1e-5, maxiter=500,
                           check_every=1, interpret=True)
        assert int(res.iterations) == int(ref.iterations)
        assert bool(res.converged)
        np.testing.assert_allclose(np.asarray(res.x),
                                   np.asarray(ref.x), rtol=0, atol=1e-4)

    def test_2d_blocked_iteration_exact(self):
        op, b = _problem_2d()
        ref = solve(op, jnp.asarray(b), tol=1e-5, maxiter=500,
                    check_every=32)
        res = cg_streaming(op, jnp.asarray(b), tol=1e-5, maxiter=500,
                           check_every=32, interpret=True)
        assert int(res.iterations) == int(ref.iterations)
        assert int(res.iterations) % 32 == 0

    def test_3d_iteration_exact(self):
        op3 = poisson.poisson_3d_operator(8, 16, 128, dtype=jnp.float32)
        rng = np.random.default_rng(5)
        b3 = jnp.asarray(rng.standard_normal(8 * 16 * 128)
                         .astype(np.float32))
        ref = solve(op3, b3, tol=1e-4, maxiter=300, check_every=1)
        res = cg_streaming(op3, b3, tol=1e-4, maxiter=300, check_every=1,
                           interpret=True)
        assert int(res.iterations) == int(ref.iterations)
        assert bool(res.converged)

    def test_rtol_threshold(self):
        op, b = _problem_2d()
        ref = solve(op, jnp.asarray(b), tol=0.0, rtol=1e-4, maxiter=500)
        res = cg_streaming(op, jnp.asarray(b), tol=0.0, rtol=1e-4,
                           maxiter=500, check_every=1, interpret=True)
        refs1 = solve(op, jnp.asarray(b), tol=0.0, rtol=1e-4, maxiter=500,
                      check_every=1)
        assert int(res.iterations) == int(refs1.iterations)
        assert bool(res.converged) and bool(ref.converged)

    def test_warm_start(self):
        op, b = _problem_2d()
        rng = np.random.default_rng(6)
        x_true = rng.standard_normal(32 * 128).astype(np.float32)
        b2 = op @ jnp.asarray(x_true)
        warm = cg_streaming(op, b2, x0=x_true * np.float32(1 + 1e-3),
                            tol=1e-4, maxiter=500, check_every=1,
                            interpret=True)
        cold = cg_streaming(op, b2, tol=1e-4, maxiter=500, check_every=1,
                            interpret=True)
        assert bool(warm.converged)
        assert int(warm.iterations) < int(cold.iterations)

    def test_history_per_iteration(self):
        op, b = _problem_2d()
        ref = solve(op, jnp.asarray(b), tol=1e-5, maxiter=500,
                    check_every=1, record_history=True)
        res = cg_streaming(op, jnp.asarray(b), tol=1e-5, maxiter=500,
                           check_every=1, record_history=True,
                           interpret=True)
        h, hr = np.asarray(res.residual_history), \
            np.asarray(ref.residual_history)
        assert h.shape == hr.shape
        k = int(res.iterations)
        np.testing.assert_allclose(h[:k + 1], hr[:k + 1], rtol=1e-2)
        assert np.isnan(h[k + 1:]).all()

    def test_iter_cap_traced(self):
        op, b = _problem_2d()
        res_full = cg_streaming(op, jnp.asarray(b), tol=0.0, maxiter=64,
                                check_every=8, interpret=True)
        res_cap = cg_streaming(op, jnp.asarray(b), tol=0.0, maxiter=64,
                               check_every=8, iter_cap=16, interpret=True)
        assert int(res_full.iterations) == 64
        assert int(res_cap.iterations) == 16

    def test_maxiter_status(self):
        op, b = _problem_2d()
        res = cg_streaming(op, jnp.asarray(b), tol=1e-30, maxiter=8,
                           check_every=4, interpret=True)
        assert not bool(res.converged)
        assert res.status_enum() is CGStatus.MAXITER
        assert int(res.iterations) == 8


class TestGateAndRouting:
    def test_supports(self):
        op, _ = _problem_2d()
        assert supports_streaming_op(op)
        assert supports_streaming((32, 128))
        assert not supports_streaming((33, 128))   # row tiling
        assert not supports_streaming((32, 100))   # lane tiling
        assert not supports_streaming((32,))       # rank

    def test_eligibility(self):
        op, _ = _problem_2d()
        assert streaming_eligible(op)
        assert streaming_eligible(op, record_history=True)
        assert not streaming_eligible(op, m=object())
        assert not streaming_eligible(op, method="pipecg")
        assert not streaming_eligible(op, return_checkpoint=True)
        from cuda_mpi_parallel_tpu.models import poisson as _p
        a_csr = _p.poisson_2d_csr(16, 16, dtype=np.float32)
        assert not streaming_eligible(a_csr)

    def test_solve_engine_streaming(self):
        op, b = _problem_2d()
        ref = solve(op, jnp.asarray(b), tol=1e-5, maxiter=500,
                    check_every=1)
        res = solve(op, jnp.asarray(b), tol=1e-5, maxiter=500,
                    check_every=1, engine="streaming")
        assert int(res.iterations) == int(ref.iterations)

    def test_solve_engine_streaming_rejects_unsupported(self):
        a_csr = poisson.poisson_2d_csr(16, 16, dtype=np.float32)
        rng = np.random.default_rng(7)
        b = jnp.asarray(rng.standard_normal(256).astype(np.float32))
        with pytest.raises(ValueError, match="streaming"):
            solve(a_csr, b, engine="streaming")

    def test_wrong_dtype_rejected(self):
        op, b = _problem_2d()
        with pytest.raises(ValueError, match="float32"):
            cg_streaming(op, jnp.asarray(b).astype(jnp.float64),
                         interpret=True)

    def test_breakdown_matches_general(self):
        # A = 0: genuine breakdown surfaces as BREAKDOWN on both engines
        op = Stencil2D.create(8, 128, scale=0.0, dtype=jnp.float32)
        rng = np.random.default_rng(8)
        b = jnp.asarray(rng.standard_normal(8 * 128).astype(np.float32))
        ref = solve(op, b, tol=1e-7, maxiter=64, check_every=1)
        res = cg_streaming(op, b, tol=1e-7, maxiter=64, check_every=1,
                           interpret=True)
        assert ref.status_enum() is CGStatus.BREAKDOWN
        assert res.status_enum() is CGStatus.BREAKDOWN
        assert bool(res.indefinite)
        assert int(res.iterations) == int(ref.iterations)


class TestDistributedStreaming:
    """Fused streaming kernels under a row-partitioned mesh
    (``parallel/streaming.py``): 1-vs-8-device iteration equality - the
    per-chip HBM-pass win must survive sharding (verdict item 7)."""

    def test_2d_matches_single_device(self):
        from cuda_mpi_parallel_tpu.parallel import (
            make_mesh,
            solve_distributed_streaming,
        )

        op = poisson.poisson_2d_operator(64, 128, dtype=jnp.float32)
        rng = np.random.default_rng(10)
        b = rng.standard_normal(64 * 128).astype(np.float32)
        single = cg_streaming(op, jnp.asarray(b), tol=1e-4, maxiter=400,
                              check_every=1, interpret=True)
        dist = solve_distributed_streaming(op, b, mesh=make_mesh(8),
                                           tol=1e-4, maxiter=400,
                                           check_every=1)
        assert bool(dist.converged)
        assert int(dist.iterations) == int(single.iterations)
        np.testing.assert_allclose(np.asarray(dist.x),
                                   np.asarray(single.x), atol=1e-4)

    def test_3d_matches_single_device(self):
        from cuda_mpi_parallel_tpu.parallel import (
            make_mesh,
            solve_distributed_streaming,
        )

        op3 = poisson.poisson_3d_operator(16, 16, 128, dtype=jnp.float32)
        rng = np.random.default_rng(11)
        b3 = rng.standard_normal(16 * 16 * 128).astype(np.float32)
        single = cg_streaming(op3, jnp.asarray(b3), tol=1e-3, maxiter=300,
                              check_every=1, interpret=True)
        dist = solve_distributed_streaming(op3, b3, mesh=make_mesh(8),
                                           tol=1e-3, maxiter=300,
                                           check_every=1)
        assert bool(dist.converged)
        assert int(dist.iterations) == int(single.iterations)

    def test_matches_general_distributed(self):
        # same iteration count as the general distributed solver too
        from cuda_mpi_parallel_tpu.parallel import (
            make_mesh,
            solve_distributed,
            solve_distributed_streaming,
        )

        op = poisson.poisson_2d_operator(64, 128, dtype=jnp.float32)
        rng = np.random.default_rng(12)
        b = rng.standard_normal(64 * 128).astype(np.float32)
        mesh = make_mesh(8)
        gen = solve_distributed(op, jnp.asarray(b), mesh=mesh, tol=1e-4,
                                maxiter=400)
        stream = solve_distributed_streaming(op, b, mesh=mesh, tol=1e-4,
                                             maxiter=400, check_every=1)
        assert int(gen.iterations) == int(stream.iterations)

    def test_blocked_check_every(self):
        from cuda_mpi_parallel_tpu.parallel import (
            make_mesh,
            solve_distributed_streaming,
        )

        op = poisson.poisson_2d_operator(64, 128, dtype=jnp.float32)
        rng = np.random.default_rng(13)
        b = rng.standard_normal(64 * 128).astype(np.float32)
        one = solve_distributed_streaming(op, b, mesh=make_mesh(8),
                                          tol=1e-4, maxiter=400,
                                          check_every=1)
        blk = solve_distributed_streaming(op, b, mesh=make_mesh(8),
                                          tol=1e-4, maxiter=400,
                                          check_every=32)
        # blocked checks overshoot to the next boundary, never undershoot
        assert int(blk.iterations) >= int(one.iterations)
        assert int(blk.iterations) % 32 == 0
        assert bool(blk.converged)

    def test_rejects_bad_shapes(self):
        from cuda_mpi_parallel_tpu.parallel import (
            make_mesh,
            solve_distributed_streaming,
        )

        rng = np.random.default_rng(14)
        op = poisson.poisson_2d_operator(12, 128, dtype=jnp.float32)
        b = rng.standard_normal(12 * 128).astype(np.float32)
        with pytest.raises(ValueError, match="divide"):
            solve_distributed_streaming(op, b, mesh=make_mesh(8))
        a_csr = poisson.poisson_2d_csr(16, 16, dtype=np.float32)
        with pytest.raises(TypeError, match="Stencil"):
            solve_distributed_streaming(
                a_csr, rng.standard_normal(256).astype(np.float32),
                mesh=make_mesh(8))


class TestDF64Streaming:
    """f64-class fused streaming (``cg_streaming_df64``): the reference's
    defining precision at the north-star scale.  Solver-level parity is
    tested in 2D only - the 3D interpret-mode executable takes ~30 min
    to compile on XLA:CPU (emulating the slab DMA + EFT chains; not
    representative of Mosaic).  The 3D kernel bodies are covered at the
    pass level below; solver-level 3D was verified once out-of-suite
    (iteration parity 42 == 42 vs cg_df64, x agreement 1.4e-14) and
    re-validates on-chip in the hardware window
    (tools/HW_WINDOW.md)."""

    def test_pass_a_b_3d_match_f64_reference(self):
        from cuda_mpi_parallel_tpu.ops import df64 as df
        from cuda_mpi_parallel_tpu.ops.pallas.fused_cg import (
            fused_cg_pass_a_df64,
            fused_cg_pass_b_df64,
            pick_block_streaming,
        )

        rng = np.random.default_rng(1)
        g3 = (4, 8, 128)
        scale64 = np.float64(0.5)
        scale = tuple(jnp.asarray(v) for v in df.split_f64(scale64))

        def pair(a64):
            h, l = df.split_f64(a64)
            return (jnp.asarray(h), jnp.asarray(l))

        r64 = rng.standard_normal(g3)
        p64 = rng.standard_normal(g3)
        x64 = rng.standard_normal(g3)
        beta64, alpha64 = np.float64(0.37), np.float64(0.11)
        # itemsize=8: the bm the PRODUCTION df64 call sites compute
        # (hi/lo pairs double the slabs per block-height)
        bm = pick_block_streaming(g3, itemsize=8)
        pn, pap = fused_cg_pass_a_df64(
            scale, pair(np.asarray(beta64)), pair(r64), pair(p64),
            bm=bm, interpret=True)
        pn_ref = r64 + beta64 * p64

        def lap(u):
            out = 6 * u.copy()
            out[:-1] -= u[1:]
            out[1:] -= u[:-1]
            out[:, :-1] -= u[:, 1:]
            out[:, 1:] -= u[:, :-1]
            out[:, :, :-1] -= u[:, :, 1:]
            out[:, :, 1:] -= u[:, :, :-1]
            return scale64 * out

        ap_ref = lap(pn_ref)
        got_pn = df.to_f64(pn[0], pn[1]).reshape(g3)
        # atol for near-zero entries: elementwise rtol alone inflates
        # the df64 rounding of O(1e-16) absolute errors at tiny values
        np.testing.assert_allclose(got_pn, pn_ref, rtol=1e-12,
                                   atol=1e-13)
        pap64 = float(np.float64(np.asarray(pap[0]))
                      + np.float64(np.asarray(pap[1])))
        np.testing.assert_allclose(pap64, (pn_ref * ap_ref).sum(),
                                   rtol=1e-12)
        xn, rn, rr = fused_cg_pass_b_df64(
            scale, pair(np.asarray(alpha64)), pn, pair(x64), pair(r64),
            bm=bm, interpret=True)
        np.testing.assert_allclose(
            df.to_f64(xn[0], xn[1]).reshape(g3), x64 + alpha64 * pn_ref,
            atol=1e-13)
        np.testing.assert_allclose(
            df.to_f64(rn[0], rn[1]).reshape(g3), r64 - alpha64 * ap_ref,
            atol=1e-12)

    def test_2d_solver_parity_and_depth(self):
        from cuda_mpi_parallel_tpu.solver.df64 import cg_df64
        from cuda_mpi_parallel_tpu.solver.streaming import (
            cg_streaming_df64,
        )

        op = poisson.poisson_2d_operator(16, 128, dtype=jnp.float32)
        rng = np.random.default_rng(0)
        b = rng.standard_normal(16 * 128)
        ref = cg_df64(op, b, tol=0.0, rtol=1e-10, maxiter=400,
                      check_every=1)
        res = cg_streaming_df64(op, b, tol=0.0, rtol=1e-10, maxiter=400,
                                check_every=1, interpret=True)
        assert bool(res.converged)
        assert int(res.iterations) == int(ref.iterations)
        assert np.abs(res.x() - ref.x()).max() < 1e-10
        # f64-class depth: true residual far below the f32 floor
        ad = np.asarray(
            poisson.poisson_2d_csr(16, 128, dtype=np.float64).to_dense())
        tr = np.linalg.norm(b - ad @ res.x()) / np.linalg.norm(b)
        assert tr < 5e-10

    def test_rejections(self):
        from cuda_mpi_parallel_tpu.solver.streaming import (
            cg_streaming_df64,
            supports_streaming_df64,
        )

        a_csr = poisson.poisson_2d_csr(16, 16, dtype=np.float32)
        assert not supports_streaming_df64(a_csr)
        with pytest.raises(TypeError, match="Stencil"):
            cg_streaming_df64(a_csr, np.ones(256))
        op_bad = poisson.poisson_2d_operator(12, 100, dtype=jnp.float32)
        with pytest.raises(ValueError, match="tiling"):
            cg_streaming_df64(op_bad, np.ones(1200))


class TestHaloBranches:
    """The has_halo branches of the fused passes, exercised directly on
    a single device with known neighbor rows (no mesh needed): the
    kernels' edge slabs must read the supplied halos in place of the
    Dirichlet zero fill."""

    @staticmethod
    def _lap2d_with_halo(u, lo, hi, scale):
        ext = np.concatenate([lo, u, hi], axis=0)
        out = 4 * ext.copy()
        out[:-1] -= ext[1:]
        out[1:] -= ext[:-1]
        out[:, :-1] -= ext[:, 1:]
        out[:, 1:] -= ext[:, :-1]
        return (scale * out)[1:-1]

    def test_pass_a_f32_with_halos(self):
        rng = np.random.default_rng(20)
        nx, ny = 16, 128
        scale = 0.25
        r = rng.standard_normal((nx, ny)).astype(np.float32)
        p = rng.standard_normal((nx, ny)).astype(np.float32)
        halos = tuple(
            jnp.asarray(rng.standard_normal((1, ny)).astype(np.float32))
            for _ in range(4))
        beta = np.float32(0.4)
        bm = pick_block_streaming((nx, ny))
        pn, pap = fused_cg_pass_a(scale, beta, jnp.asarray(r),
                                  jnp.asarray(p), halos, bm=bm,
                                  interpret=True)
        r_lo, r_hi, p_lo, p_hi = (np.asarray(h) for h in halos)
        pn_ref = r + beta * p
        pn_lo = r_lo + beta * p_lo
        pn_hi = r_hi + beta * p_hi
        ap_ref = self._lap2d_with_halo(
            pn_ref.astype(np.float64), pn_lo.astype(np.float64),
            pn_hi.astype(np.float64), scale)
        np.testing.assert_allclose(np.asarray(pn), pn_ref, rtol=1e-4,
                                   atol=1e-6)
        np.testing.assert_allclose(float(pap),
                                   (pn_ref.astype(np.float64)
                                    * ap_ref).sum(), rtol=1e-4)

    def test_pass_b_f32_with_halos(self):
        rng = np.random.default_rng(21)
        nx, ny = 16, 128
        scale = 0.25
        pnew = rng.standard_normal((nx, ny)).astype(np.float32)
        x = rng.standard_normal((nx, ny)).astype(np.float32)
        r = rng.standard_normal((nx, ny)).astype(np.float32)
        pn_lo = rng.standard_normal((1, ny)).astype(np.float32)
        pn_hi = rng.standard_normal((1, ny)).astype(np.float32)
        alpha = np.float32(0.2)
        bm = pick_block_streaming((nx, ny))
        xn, rn, rr = fused_cg_pass_b(
            scale, alpha, jnp.asarray(pnew), jnp.asarray(x),
            jnp.asarray(r), (jnp.asarray(pn_lo), jnp.asarray(pn_hi)),
            bm=bm, interpret=True)
        ap_ref = self._lap2d_with_halo(
            pnew.astype(np.float64), pn_lo.astype(np.float64),
            pn_hi.astype(np.float64), scale)
        np.testing.assert_allclose(np.asarray(xn), x + alpha * pnew,
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(rn),
                                   r - alpha * ap_ref.astype(np.float32),
                                   rtol=1e-3, atol=1e-4)

    def test_pass_a_df64_with_halos(self):
        from cuda_mpi_parallel_tpu.ops import df64 as df
        from cuda_mpi_parallel_tpu.ops.pallas.fused_cg import (
            fused_cg_pass_a_df64,
        )

        rng = np.random.default_rng(22)
        nx, ny = 16, 128
        scale64 = np.float64(0.25)
        scale = tuple(jnp.asarray(v) for v in df.split_f64(scale64))

        def pair(a64):
            h, l = df.split_f64(a64)
            return (jnp.asarray(h), jnp.asarray(l))

        r64 = rng.standard_normal((nx, ny))
        p64 = rng.standard_normal((nx, ny))
        h64 = [rng.standard_normal((1, ny)) for _ in range(4)]
        beta64 = np.float64(0.4)
        bm = pick_block_streaming((nx, ny))
        pn, pap = fused_cg_pass_a_df64(
            scale, pair(np.asarray(beta64)), pair(r64), pair(p64),
            tuple(pair(h) for h in h64), bm=bm, interpret=True)
        r_lo, r_hi, p_lo, p_hi = h64
        pn_ref = r64 + beta64 * p64
        ap_ref = self._lap2d_with_halo(
            pn_ref, r_lo + beta64 * p_lo, r_hi + beta64 * p_hi, scale64)
        got = df.to_f64(pn[0], pn[1]).reshape(nx, ny)
        np.testing.assert_allclose(got, pn_ref, rtol=1e-12, atol=1e-13)
        pap64 = float(np.float64(np.asarray(pap[0]))
                      + np.float64(np.asarray(pap[1])))
        np.testing.assert_allclose(pap64, (pn_ref * ap_ref).sum(),
                                   rtol=1e-12)


class TestDistributedDF64Streaming:
    """Distributed df64 streaming (``solve_distributed_streaming_df64``):
    2-shard mesh in-suite (compiles in seconds); the 8-shard form hits
    a pathological XLA:CPU compile specific to wider exact-allreduce
    programs and re-validates on-chip (tools/HW_WINDOW.md)."""

    def test_2shard_bitwise_matches_single_device(self):
        from cuda_mpi_parallel_tpu.parallel import make_mesh
        from cuda_mpi_parallel_tpu.parallel.streaming import (
            solve_distributed_streaming_df64,
        )
        from cuda_mpi_parallel_tpu.solver.streaming import (
            cg_streaming_df64,
        )

        op = poisson.poisson_2d_operator(16, 128, dtype=jnp.float32)
        rng = np.random.default_rng(0)
        b = rng.standard_normal(16 * 128)
        single = cg_streaming_df64(op, b, tol=0.0, rtol=1e-9,
                                   maxiter=300, check_every=1,
                                   interpret=True)
        dist = solve_distributed_streaming_df64(
            op, b, mesh=make_mesh(2), tol=0.0, rtol=1e-9, maxiter=300,
            check_every=1)
        assert bool(dist.converged)
        assert int(dist.iterations) == int(single.iterations)
        # hi words are bitwise equal; lo words may differ by the
        # reduction order of the exact allreduce vs the local fold -
        # the recombined f64 values agree to df64 depth
        np.testing.assert_array_equal(np.asarray(dist.x_hi),
                                      np.asarray(single.x_hi))
        np.testing.assert_allclose(dist.x(), single.x(), rtol=0,
                                   atol=1e-12)

    def test_rejections(self):
        from cuda_mpi_parallel_tpu.parallel import make_mesh
        from cuda_mpi_parallel_tpu.parallel.streaming import (
            solve_distributed_streaming_df64,
        )

        a_csr = poisson.poisson_2d_csr(16, 16, dtype=np.float32)
        with pytest.raises(TypeError, match="Stencil"):
            solve_distributed_streaming_df64(
                a_csr, np.ones(256), mesh=make_mesh(2))
        op = poisson.poisson_2d_operator(18, 128, dtype=jnp.float32)
        with pytest.raises(ValueError, match="divide"):
            solve_distributed_streaming_df64(
                op, np.ones(18 * 128), mesh=make_mesh(4))


class TestDefaultCheckEvery:
    """Round-4 advice (low): cg_streaming's default check_every must
    match solve()'s (1) so direct callers at defaults get the exact
    iteration counts the docstring promises."""

    def test_default_is_one(self):
        import inspect

        sig = inspect.signature(cg_streaming)
        assert sig.parameters["check_every"].default == 1

    def test_default_counts_match_solve_defaults(self):
        op = Stencil2D.create(16, 128, dtype=jnp.float32)
        rng = np.random.default_rng(3)
        b = jnp.asarray(
            rng.standard_normal(op.shape[0]).astype(np.float32))
        ref = solve(op, b, tol=1e-4, maxiter=300)
        res = cg_streaming(op, b, tol=1e-4, maxiter=300, interpret=True)
        assert int(res.iterations) == int(ref.iterations)


class TestChebyshevStreaming:
    """Streamed Chebyshev preconditioning (round-4 verdict item 4): the
    past-VMEM engine competing on time-to-tolerance, not just iters/s.

    Degree 1 folds into the existing passes (pass A's theta divisor +
    pass B's fused rho accumulation - zero extra plane-passes); degree
    k >= 2 runs (k - 1) ``fused_cheb_step`` launches per iteration with
    the PCG reduction fused into the last.  The parity bar is the
    engine's own: iteration counts EQUAL to the general cheb-CG at
    equal tolerances (interpret mode matched bit-exactly at review
    time, but only count equality plus f32-level x agreement is
    contractual).
    """

    def _cheb(self, op, degree):
        from cuda_mpi_parallel_tpu.models.precond import (
            ChebyshevPreconditioner,
        )

        return ChebyshevPreconditioner.from_operator(op, degree=degree)

    @pytest.mark.parametrize("degree", [1, 2, 4])
    def test_2d_parity_vs_general(self, degree):
        op, b = _problem_2d(16, 128)
        m = self._cheb(op, degree)
        ref = solve(op, b, tol=1e-4, maxiter=400, m=m)
        res = cg_streaming(op, b, tol=1e-4, maxiter=400, m=m,
                           interpret=True)
        assert bool(res.converged)
        assert int(res.iterations) == int(ref.iterations)
        if degree >= 2:
            # degree 1 is a pure Richardson scaling (z = r/theta): same
            # search directions, no count reduction expected
            assert int(res.iterations) < int(
                solve(op, b, tol=1e-4, maxiter=400).iterations)
        np.testing.assert_allclose(np.asarray(res.x), np.asarray(ref.x),
                                   rtol=0, atol=1e-5)

    def test_3d_parity_vs_general(self):
        op = poisson.poisson_3d_operator(4, 8, 128, dtype=jnp.float32)
        rng = np.random.default_rng(1)
        b = rng.standard_normal(op.shape[0]).astype(np.float32)
        m = self._cheb(op, 4)
        ref = solve(op, b, tol=1e-4, maxiter=400, m=m)
        res = cg_streaming(op, b, tol=1e-4, maxiter=400, m=m,
                           interpret=True)
        assert bool(res.converged)
        assert int(res.iterations) == int(ref.iterations)
        np.testing.assert_allclose(np.asarray(res.x), np.asarray(ref.x),
                                   rtol=0, atol=1e-5)

    def test_warm_start_and_history(self):
        op, b = _problem_2d(16, 128)
        m = self._cheb(op, 2)
        rng = np.random.default_rng(7)
        x0 = rng.standard_normal(op.shape[0]).astype(np.float32)
        ref = solve(op, b, x0=x0, tol=1e-4, maxiter=400, m=m,
                    record_history=True)
        res = cg_streaming(op, b, x0=x0, tol=1e-4, maxiter=400, m=m,
                           record_history=True, interpret=True)
        assert bool(res.converged)
        assert int(res.iterations) == int(ref.iterations)
        k = int(res.iterations)
        hist = np.asarray(res.residual_history)
        # per-iteration trace: slot k holds the final ||r||
        np.testing.assert_allclose(hist[k], float(res.residual_norm),
                                   rtol=1e-6)
        ref_hist = np.asarray(ref.residual_history)
        np.testing.assert_allclose(hist[:k + 1], ref_hist[:k + 1],
                                   rtol=1e-4)

    def test_eligibility_and_routing(self):
        from cuda_mpi_parallel_tpu.models.operators import (
            JacobiPreconditioner,
        )

        op, b = _problem_2d(16, 128)
        m = self._cheb(op, 4)
        assert streaming_eligible(op, b, m)
        # a cheb built over a DIFFERENT operator must not be eligible
        other = poisson.poisson_2d_operator(8, 128, dtype=jnp.float32)
        assert not streaming_eligible(op, b, self._cheb(other, 4))
        # non-chebyshev preconditioners stay on the general engine
        mj = JacobiPreconditioner.from_operator(op)
        assert not streaming_eligible(op, b, mj)
        with pytest.raises(TypeError, match="Chebyshev"):
            cg_streaming(op, b, m=mj, interpret=True)
        with pytest.raises(ValueError, match="same stencil"):
            cg_streaming(op, b, m=self._cheb(other, 4), interpret=True)
        # engine="streaming" routes a matching cheb through the engine
        res = solve(op, b, tol=1e-4, maxiter=400, m=m, engine="streaming")
        ref = solve(op, b, tol=1e-4, maxiter=400, m=m)
        assert int(res.iterations) == int(ref.iterations)

    def test_unpreconditioned_trajectory_untouched(self):
        # theta defaults to an exact 1.0 divide: the m=None path must
        # stay BITWISE identical to the pre-theta kernels' trajectory,
        # represented here by the general solver's count at equal tol
        op, b = _problem_2d(16, 128)
        ref = solve(op, b, tol=1e-4, maxiter=400)
        res = cg_streaming(op, b, tol=1e-4, maxiter=400, interpret=True)
        assert int(res.iterations) == int(ref.iterations)


class TestDF64Streaming3DSolver:
    """Round-4 verdict item 5: 3D df64-streaming solver-level parity was
    verified once out-of-suite because the interpret executable was
    thought to take ~30 min to compile on XLA:CPU.  Round-5 bisection:
    the blowup is caused by the 8-virtual-device CPU backend
    (--xla_force_host_platform_device_count=8, which conftest sets for
    the whole suite) - the SAME program compiles in ~7 s on a plain
    single-device CPU backend.  So the parity assertion runs in a
    clean single-device subprocess: same code, same assertions, CI
    cost ~30 s instead of ~11 min.
    """

    def test_3d_solver_parity_vs_cg_df64(self):
        import os
        import subprocess
        import sys

        code = """
import numpy as np, jax.numpy as jnp
from cuda_mpi_parallel_tpu.models import poisson
from cuda_mpi_parallel_tpu.solver.df64 import cg_df64
from cuda_mpi_parallel_tpu.solver.streaming import cg_streaming_df64

op = poisson.poisson_3d_operator(2, 8, 128, dtype=jnp.float32)
rng = np.random.default_rng(0)
b = rng.standard_normal(2 * 8 * 128)
ref = cg_df64(op, b, tol=0.0, rtol=1e-10, maxiter=300, check_every=1)
res = cg_streaming_df64(op, b, tol=0.0, rtol=1e-10, maxiter=300,
                        check_every=1, interpret=True)
assert bool(res.converged), "did not converge"
assert int(res.iterations) == int(ref.iterations), (
    int(res.iterations), int(ref.iterations))
xerr = np.abs(res.x() - ref.x()).max()
assert xerr < 1e-10, xerr
print("PARITY_OK", int(res.iterations))
"""
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("XLA_FLAGS", None)  # single-device CPU: the fast path
        proc = subprocess.run(
            [sys.executable, "-c", code], cwd=repo, env=env,
            capture_output=True, text=True, timeout=420)
        assert proc.returncode == 0, (
            f"subprocess failed:\n{proc.stdout[-800:]}\n"
            f"{proc.stderr[-800:]}")
        assert "PARITY_OK" in proc.stdout


class TestDistributedDF64Streaming4Shard:
    """Round-4 verdict item 5's wider-mesh gap: the round-4 suite
    stopped at 2 shards, blaming a 'pathological XLA:CPU compile' at 8.
    Round-5 measurement showed the cost is interpret RUNTIME (~4.4 s
    per iteration at (64, 128)), not compile - so the wider-mesh parity
    assertion runs here at a FIXED short iteration count (the 8-shard
    form runs in ``__graft_entry__.dryrun_multichip`` the same way;
    a 300-iteration 8-shard probe agreed with single-device to
    3.4e-13).
    """

    def test_4shard_fixed_count_bitwise_x_hi(self):
        from cuda_mpi_parallel_tpu.parallel import make_mesh
        from cuda_mpi_parallel_tpu.parallel.streaming import (
            solve_distributed_streaming_df64,
        )
        from cuda_mpi_parallel_tpu.solver.streaming import (
            cg_streaming_df64,
        )

        op = poisson.poisson_2d_operator(32, 128, dtype=jnp.float32)
        rng = np.random.default_rng(0)
        b = rng.standard_normal(32 * 128)
        single = cg_streaming_df64(op, b, tol=0.0, maxiter=24,
                                   check_every=8, interpret=True)
        dist = solve_distributed_streaming_df64(
            op, b, mesh=make_mesh(4), tol=0.0, maxiter=24, check_every=8)
        assert int(dist.iterations) == int(single.iterations) == 24
        np.testing.assert_array_equal(np.asarray(dist.x_hi),
                                      np.asarray(single.x_hi))
        np.testing.assert_allclose(dist.x(), single.x(), rtol=0,
                                   atol=1e-12)
