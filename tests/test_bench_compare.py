"""tools/bench_compare.py: the bench regression gate, on synthetic
records (no device, no bench run - pure JSON plumbing)."""
import importlib.util
import io
import json
import pathlib
import sys

import pytest

_TOOL = pathlib.Path(__file__).resolve().parents[1] / "tools" \
    / "bench_compare.py"
spec = importlib.util.spec_from_file_location("bench_compare", _TOOL)
bench_compare = importlib.util.module_from_spec(spec)
spec.loader.exec_module(bench_compare)

HK = bench_compare.HEADLINE_KEY


def _write(tmp_path, name, data):
    path = tmp_path / name
    path.write_text(json.dumps(data))
    return str(path)


def _sweep(headline=148519.5, tts=2.0, iters=500, converged=True,
           decay=-0.05, classification="CONVERGED"):
    return {
        HK: {"metric": "cg_iters_per_sec_poisson2d_1M_f32",
             "value": headline, "unit": "iters/s",
             "iterations": 1462, "converged": True},
        f"{HK}__done": {"section_s": 1.0},
        "__meta__": {"git_rev": "abc"},
        "poisson2d_512_none_rtol1e-6": {
            "time_to_tol_s": tts, "iterations": iters,
            "converged": converged,
            "flight": {"decay_rate": decay, "kappa_estimate": 441.0,
                       "classification": classification},
        },
    }


class TestLoadSections:
    def test_sweep_shape_skips_bookkeeping(self, tmp_path):
        sections = bench_compare.load_sections(
            _write(tmp_path, "a.json", _sweep()))
        assert set(sections) == {HK, "poisson2d_512_none_rtol1e-6"}

    def test_flat_headline_record_normalizes(self, tmp_path):
        rec = {"metric": "cg_iters_per_sec_poisson2d_1M_f32",
               "value": 100.0, "vs_baseline": 0.02}
        sections = bench_compare.load_sections(
            _write(tmp_path, "b.json", rec))
        assert set(sections) == {HK}
        assert sections[HK]["value"] == 100.0

    def test_empty_file_raises(self, tmp_path):
        with pytest.raises(ValueError):
            bench_compare.load_sections(
                _write(tmp_path, "c.json", {"__meta__": {}}))


class TestCompareGate:
    def _run(self, tmp_path, old, new, threshold=0.10):
        out = io.StringIO()
        rc = bench_compare.compare(
            bench_compare.load_sections(_write(tmp_path, "old.json", old)),
            bench_compare.load_sections(_write(tmp_path, "new.json", new)),
            threshold, out=out)
        return rc, out.getvalue()

    def test_identical_passes(self, tmp_path):
        rc, out = self._run(tmp_path, _sweep(), _sweep())
        assert rc == 0
        assert "no gated regressions" in out

    def test_small_headline_dip_passes(self, tmp_path):
        rc, _ = self._run(tmp_path, _sweep(headline=100000.0),
                          _sweep(headline=95000.0))
        assert rc == 0

    def test_headline_regression_fails(self, tmp_path):
        rc, out = self._run(tmp_path, _sweep(headline=100000.0),
                            _sweep(headline=85000.0))
        assert rc == 1
        assert "REGRESSIONS" in out
        assert f"{HK}.value" in out

    def test_headline_improvement_passes(self, tmp_path):
        rc, _ = self._run(tmp_path, _sweep(headline=100000.0),
                          _sweep(headline=150000.0))
        assert rc == 0

    def test_time_to_tol_regression_fails(self, tmp_path):
        rc, out = self._run(tmp_path, _sweep(tts=2.0), _sweep(tts=2.5))
        assert rc == 1
        assert "time_to_tol_s" in out

    def test_iteration_count_regression_fails(self, tmp_path):
        # more iterations to the same tolerance = convergence regression
        rc, out = self._run(tmp_path, _sweep(iters=500),
                            _sweep(iters=700))
        assert rc == 1
        assert "iterations" in out

    def test_converged_flip_fails(self, tmp_path):
        rc, out = self._run(tmp_path, _sweep(converged=True),
                            _sweep(converged=False,
                                   classification="STAGNATED"))
        assert rc == 1
        assert "converged true -> false" in out

    def test_health_classification_flip_fails(self, tmp_path):
        rc, out = self._run(tmp_path, _sweep(classification="CONVERGED"),
                            _sweep(classification="STAGNATED"))
        assert rc == 1
        assert "STAGNATED" in out

    def test_threshold_is_configurable(self, tmp_path):
        old, new = _sweep(headline=100000.0), _sweep(headline=95000.0)
        rc, _ = self._run(tmp_path, old, new, threshold=0.02)
        assert rc == 1

    def test_disjoint_sections_reported_not_failed(self, tmp_path):
        old = {"only_old": {"iters_per_sec": 1.0}}
        new = {"only_new": {"iters_per_sec": 2.0}}
        rc, out = self._run(tmp_path, old, new)
        assert rc == 0
        assert "only in OLD: only_old" in out
        assert "only in NEW: only_new" in out

    def test_flight_decay_reported_in_table(self, tmp_path):
        rc, out = self._run(tmp_path, _sweep(decay=-0.05),
                            _sweep(decay=-0.01))
        # reported (not gated): decay_rate rides the table only
        assert "flight.decay_rate" in out
        assert rc == 0

    def test_planner_columns_reported_never_gated(self, tmp_path):
        """PR-5: the partition-planner columns ride the table but a
        'worse' imbalance never fails the gate (they track the bench
        problem's structure, not the code), and an OLD file without
        them degrades to n/a, not a KeyError."""
        planner = {"n_shards": 4, "label": "rcm+nnz",
                   "nnz_imbalance_even": 2.8,
                   "nnz_imbalance_planned": 1.1,
                   "plan_time_s": 0.4}
        worse = dict(planner, nnz_imbalance_planned=2.5,
                     plan_time_s=9.0)
        old = _sweep()
        new = _sweep()
        old["unstructured_fem"] = {"iters_per_sec": 100.0,
                                   "planner": planner}
        new["unstructured_fem"] = {"iters_per_sec": 100.0,
                                   "planner": worse}
        rc, out = self._run(tmp_path, old, new)
        assert rc == 0            # reported, never gated
        assert "planner.nnz_imbalance_planned" in out
        assert "planner.plan_time_s" in out
        # old file predates the planner entirely -> n/a cells + warning
        del old["unstructured_fem"]["planner"]
        rc, out = self._run(tmp_path, old, new)
        assert rc == 0
        assert "n/a" in out
        assert "planner.nnz_imbalance_planned" in out


    def test_serve_overload_retention_gates(self, tmp_path):
        """The overload bench's goodput retention at 2x GATES
        (higher-better): a service that starts collapsing under
        overload fails the compare; the other serve_overload.*
        columns are reported only, and an OLD file without the
        section degrades to 'only in NEW', not a KeyError."""
        row = {"serve_overload": {
            "probe_capacity_rhs_per_sec": 400.0,
            "max_sustained_rhs_per_sec": 350.0,
            "goodput_retention_2x": 0.92,
            "gold_p99_s": 0.11, "gold_timeouts_2x": 0,
            "rejected_2x": 20, "degraded_2x": 9, "timeouts_2x": 1,
            "shed_transitions_2x": 4, "workers": 2}}
        collapsed = {"serve_overload": dict(
            row["serve_overload"], goodput_retention_2x=0.40,
            gold_p99_s=2.0, rejected_2x=60)}
        old, new = _sweep(), _sweep()
        old["serve_overload"] = row
        new["serve_overload"] = collapsed
        rc, out = self._run(tmp_path, old, new)
        assert rc == 1            # retention regressed past threshold
        assert "serve_overload.goodput_retention_2x" in out
        assert "REGRESSIONS" in out
        # a worse gold p99 / rejection count alone never gates
        mild = {"serve_overload": dict(
            row["serve_overload"], gold_p99_s=5.0, rejected_2x=999)}
        new["serve_overload"] = mild
        rc, out = self._run(tmp_path, old, new)
        assert rc == 0
        assert "serve_overload.gold_p99_s" in out
        # old file predates the section entirely -> n/a-safe
        del old["serve_overload"]
        rc, out = self._run(tmp_path, old, new)
        assert rc == 0
        assert "only in NEW: serve_overload" in out

    def test_many_rhs_columns_reported_never_gated(self, tmp_path):
        """PR-8: the many-RHS batching columns ride the table but a
        'worse' amortization or iteration count never fails the gate
        (throughput tracks host weather, iteration counts the bench
        problem), and an OLD file without the section degrades to
        'only in NEW', not a KeyError."""
        row = {"rhs_iters_per_sec_k8": 600.0,
               "sequential_rhs_iters_per_sec_k8": 120.0,
               "amortization_x_k8": 5.0,
               "batched_iterations_k8": 211,
               "block_iterations_k8": 145,
               "many_wire": {"wire_bytes_per_solve_batched": 167040,
                             "wire_bytes_per_solve_sequential8": 236640,
                             "wire_amortization_x": 1.42}}
        worse = dict(row, rhs_iters_per_sec_k8=60.0,
                     amortization_x_k8=0.5, block_iterations_k8=500,
                     many_wire=dict(row["many_wire"],
                                    wire_amortization_x=0.7))
        old = _sweep()
        new = _sweep()
        old["many_rhs"] = row
        new["many_rhs"] = worse
        rc, out = self._run(tmp_path, old, new)
        assert rc == 0            # reported, never gated
        assert "rhs_iters_per_sec_k8" in out
        assert "amortization_x_k8" in out
        assert "many_wire.wire_amortization_x" in out
        # old file predates the section entirely -> reported as new
        del old["many_rhs"]
        rc, out = self._run(tmp_path, old, new)
        assert rc == 0
        assert "only in NEW: many_rhs" in out


class TestMainCli:
    def test_main_regression_exit_codes(self, tmp_path, capsys):
        old = _write(tmp_path, "o.json", _sweep(headline=100000.0))
        new = _write(tmp_path, "n.json", _sweep(headline=50000.0))
        assert bench_compare.main([old, new]) == 1
        assert bench_compare.main([old, old]) == 0

    def test_main_unreadable_is_2(self, tmp_path):
        old = _write(tmp_path, "o.json", _sweep())
        assert bench_compare.main([old, str(tmp_path / "nope.json")]) == 2

    def test_main_bad_threshold_is_2(self, tmp_path):
        old = _write(tmp_path, "o.json", _sweep())
        assert bench_compare.main(["--threshold", "0", old, old]) == 2


def test_headline_key_matches_bench():
    # bench_compare cannot import bench.py (it must run without jax), so
    # its HEADLINE_KEY is a copy; if bench.py ever renames the headline
    # section the gate would silently stop matching anything.  Pull the
    # constant out of bench.py's AST (no import, no side effects).
    import ast

    tree = ast.parse((_TOOL.parents[1] / "bench.py").read_text())
    vals = [n.value.value for n in ast.walk(tree)
            if isinstance(n, ast.Assign)
            and any(isinstance(t, ast.Name) and t.id == "HEADLINE_KEY"
                    for t in n.targets)]
    assert vals == [bench_compare.HEADLINE_KEY]


class TestOldFormatDegradation:
    """PR-4 satellite: a pre-PR-3 row (no ``flight``/``iterations``
    columns, e.g. bench_results_r03.json) must degrade to "n/a" cells
    plus a warning - never a KeyError traceback."""

    def _run(self, tmp_path, old, new):
        out = io.StringIO()
        rc = bench_compare.compare(
            bench_compare.load_sections(_write(tmp_path, "old.json", old)),
            bench_compare.load_sections(_write(tmp_path, "new.json", new)),
            0.10, out=out)
        return rc, out.getvalue()

    def test_old_row_missing_flight_and_iterations(self, tmp_path):
        old = {"sec": {"iters_per_sec": 100.0, "us_per_iter": 10.0}}
        new = {"sec": {"iters_per_sec": 101.0, "us_per_iter": 9.9,
                       "iterations": 50, "converged": True,
                       "flight": {"decay_rate": -0.05,
                                  "kappa_estimate": 12.0}}}
        rc, out = self._run(tmp_path, old, new)
        assert rc == 0
        # n/a cells for the columns the old format lacks, not a drop
        assert "iterations" in out and "n/a" in out
        assert "flight.decay_rate" in out
        assert "warning" in out and "old-format" in out
        # the symmetric direction (new row lost a metric) also warns
        rc2, out2 = self._run(tmp_path, new, old)
        assert rc2 == 0
        assert "NEW row lacks" in out2

    def test_real_pre_pr3_snapshot_never_raises(self):
        """The actual committed old-format file: bench_results_r03.json
        predates the flight/iterations columns entirely."""
        root = _TOOL.parents[1]
        old_p = root / "bench_results_r03.json"
        new_p = root / "bench_results_r05.json"
        if not (old_p.exists() and new_p.exists()):
            pytest.skip("round snapshots not present")
        out = io.StringIO()
        rc = bench_compare.compare(
            bench_compare.load_sections(str(old_p)),
            bench_compare.load_sections(str(new_p)), 0.10, out=out)
        assert rc in (0, 1)  # a gate verdict, never a traceback
        assert "section" in out.getvalue()

    def test_roofline_column_reported_not_gated(self, tmp_path):
        old = {"sec": {"iters_per_sec": 100.0,
                       "roofline": {"efficiency_pct": 80.0}}}
        new = {"sec": {"iters_per_sec": 100.0,
                       "roofline": {"efficiency_pct": 8.0}}}
        rc, out = self._run(tmp_path, old, new)
        assert rc == 0  # a 10x efficiency drop reports but never gates
        assert "roofline.efficiency_pct" in out

    def test_non_dict_entry_contributes_nothing(self):
        assert bench_compare._metrics("not a dict") == {}
        assert bench_compare._metrics({"flight": "old-string-form"}) == {}
