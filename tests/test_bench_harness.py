"""Bench-harness provenance: last-known-good records and UTC/rev stamps.

The round-3 failure mode this guards against: the driver's bench capture
hit a multi-hour tunnel outage and recorded ``value 0.0`` while the
already-measured 148.5k headline sat unreferenced in a gitignored file.
Round 4 added the opposite lesson: the driver kills bench.py from
OUTSIDE (~30 min, rc 124), so waiting out the outage in-process lost the
round anyway.  The harness now (a) keeps stdout's tail always holding a
parseable record (provisional at startup + after every failed probe,
SIGTERM handler for the external kill), (b) sizes its default windows to
fire inside the external budget, (c) stamps every flushed results file
with git rev + UTC, and (d) embeds a provenance-marked
``last_known_good`` block in every structured failure record, sourced
from the flushed results file or the newest committed round snapshot
(``bench_results_rNN.json``), sorted by parsed round number.
"""
import json
import os

import pytest

import bench


@pytest.fixture
def in_tmp(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    return tmp_path


def _write(path, data):
    with open(path, "w") as f:
        json.dump(data, f)


class TestLastKnownGood:
    def test_no_files_returns_none(self, in_tmp):
        assert bench._last_known_good() is None

    def test_reads_live_results_file(self, in_tmp):
        _write(bench.RESULTS_PATH, {
            bench.HEADLINE_KEY: {"value": 12345.0, "engine": "resident"},
            f"{bench.HEADLINE_KEY}__done": {"section_s": 1.0,
                                            "utc": "2026-01-01T00:00:00Z"},
            "__meta__": {"git_rev": "abc1234", "utc": "2026-01-01T00:00:00Z"},
        })
        lkg = bench._last_known_good()
        assert lkg["source_file"] == bench.RESULTS_PATH
        assert lkg["headline_value"] == 12345.0
        assert lkg["headline_engine"] == "resident"
        assert lkg["git_rev"] == "abc1234"
        assert lkg["stale"] is True
        # markers and meta are not sections
        assert set(lkg["sections"]) == {bench.HEADLINE_KEY}

    def test_falls_back_to_newest_round_snapshot(self, in_tmp):
        _write("bench_results_r03.json",
               {"dia": {"us_per_iter": 246.0}, "__meta__": {}})
        _write("bench_results_r04.json",
               {bench.HEADLINE_KEY: {"value": 99.0}, "__meta__": {}})
        lkg = bench._last_known_good()
        assert lkg["source_file"] == "bench_results_r04.json"
        assert lkg["headline_value"] == 99.0

    def test_skips_corrupt_and_empty_files(self, in_tmp):
        with open(bench.RESULTS_PATH, "w") as f:
            f.write("{not json")
        _write("bench_results_r03.json", {"__meta__": {}})  # no sections
        _write("bench_results_r02.json", {"row": {"iters_per_sec": 5.0}})
        lkg = bench._last_known_good()
        assert lkg["source_file"] == "bench_results_r02.json"

    def test_headline_own_stamp_beats_file_meta(self, in_tmp):
        # A headline persisted by a headline-only run at rev B must not
        # be attributed to the older rev A that produced the file's
        # other sections (and vice versa).
        _write(bench.RESULTS_PATH, {
            "dia": {"us_per_iter": 246.0},
            bench.HEADLINE_KEY: {"value": 150000.0, "git_rev": "revB",
                                 "utc": "2026-02-02T00:00:00Z"},
            "__meta__": {"git_rev": "revA", "utc": "2026-01-01T00:00:00Z"},
        })
        lkg = bench._last_known_good()
        assert lkg["git_rev"] == "revB"
        assert lkg["measured_utc"] == "2026-02-02T00:00:00Z"

    def test_partial_live_file_does_not_shadow_snapshot_headline(self,
                                                                 in_tmp):
        # Outage before the headline section: the live file holds only
        # dense_spd_1024, while the round snapshot has the real
        # headline - the snapshot must win.
        _write(bench.RESULTS_PATH, {"dense_spd_1024": {"us_per_iter": 1.0}})
        _write("bench_results_r03.json",
               {bench.HEADLINE_KEY: {"value": 148519.5}, "__meta__": {}})
        lkg = bench._last_known_good()
        assert lkg["source_file"] == "bench_results_r03.json"
        assert lkg["headline_value"] == 148519.5

    def test_headline_absent_is_none_not_crash(self, in_tmp):
        _write(bench.RESULTS_PATH, {"dia": {"us_per_iter": 1.0}})
        lkg = bench._last_known_good()
        assert lkg["headline_value"] is None
        assert lkg["sections"] == {"dia": {"us_per_iter": 1.0}}


class TestFailureRecord:
    def test_carries_last_known_good(self, in_tmp):
        _write(bench.RESULTS_PATH,
               {bench.HEADLINE_KEY: {"value": 148519.5}})
        rec = bench._failure_record("device_unreachable", "outage")
        assert rec["value"] == 0.0
        assert rec["last_known_good"]["headline_value"] == 148519.5
        assert rec["last_known_good"]["stale"] is True
        json.dumps(rec)  # must stay one serializable JSON line

    def test_no_artifacts_no_block(self, in_tmp):
        rec = bench._failure_record("device_unreachable", "outage")
        assert "last_known_good" not in rec


class TestStamps:
    def test_run_section_stamps_utc(self, in_tmp):
        results = bench._FlushingResults(bench.RESULTS_PATH)
        bench._run_section(results, "s1", lambda: None)
        done = results["s1__done"]
        assert done["utc"].endswith("Z") and "T" in done["utc"]
        on_disk = json.load(open(bench.RESULTS_PATH))
        assert on_disk["s1__done"]["utc"] == done["utc"]

    def test_git_rev_none_outside_repo(self, in_tmp):
        # tmp_path is not a git repo; must degrade to None, not raise
        assert bench._git_rev() is None or isinstance(bench._git_rev(), str)


class TestDefaults:
    def test_acquire_default_fits_driver_budget(self):
        # Round 4: the hour-long default was still waiting when the
        # driver's ~30-min external kill (rc 124) landed, and no record
        # was printed.  The knob the driver path actually uses is the
        # ARGPARSE default (main always passes args.acquire_wait); it
        # plus the headline watchdog margin must fire INSIDE that
        # external budget.
        args = bench._build_parser().parse_args([])
        assert args.acquire_wait + 900 <= 1700.0  # watchdog < ~28 min
        assert args.acquire_wait == bench.DEFAULT_ACQUIRE_WAIT

    def test_repo_has_round_snapshot(self):
        # evidence must exist at HEAD: at least the retroactive r03
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        snaps = [p for p in os.listdir(repo)
                 if p.startswith("bench_results_r") and p.endswith(".json")]
        assert snaps, "no committed bench_results_rNN.json snapshot"
        data = json.load(open(os.path.join(repo, snaps[0])))
        assert any(not k.startswith("__") and not k.endswith("__done")
                   for k in data)


class TestRoundNumberSort:
    def test_three_digit_rounds_sort_numerically(self, in_tmp):
        # ADVICE r4 (low): reverse-lexicographic filename sort ranks
        # r99 above r100; provenance must track the PARSED round number.
        _write("bench_results_r99.json",
               {bench.HEADLINE_KEY: {"value": 1.0}})
        _write("bench_results_r100.json",
               {bench.HEADLINE_KEY: {"value": 2.0}})
        lkg = bench._last_known_good()
        assert lkg["source_file"] == "bench_results_r100.json"
        assert lkg["headline_value"] == 2.0


def _outage_driver(tmp_path, repo):
    """Write a driver script that runs bench.main under a simulated
    permanent outage (every backend probe fails instantly)."""
    script = tmp_path / "driver.py"
    script.write_text(
        "import sys\n"
        f"sys.path.insert(0, {str(repo)!r})\n"
        "import bench\n"
        "bench._probe_backend_once = "
        "lambda timeout=0: (False, 'simulated outage')\n"
        "sys.exit(bench.main(['--acquire-wait', '300']))\n")
    return script


class TestExternalKillRehearsal:
    """Round-4 headline failure: an external kill mid-acquire left no
    record.  These rehearse the two kill modes the driver can deliver."""

    @pytest.fixture
    def repo_root(self):
        return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    def _spawn(self, tmp_path, repo_root):
        import subprocess
        import sys as _sys
        _write(str(tmp_path / "bench_results_r03.json"),
               {bench.HEADLINE_KEY: {"value": 148519.5,
                                     "engine": "resident"}})
        script = _outage_driver(tmp_path, repo_root)
        return subprocess.Popen(
            [_sys.executable, str(script)], cwd=tmp_path,
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True)

    def _last_record(self, stdout_text):
        lines = [ln for ln in stdout_text.strip().splitlines()
                 if ln.startswith("{")]
        assert lines, f"no JSON record in stdout: {stdout_text[-400:]!r}"
        return json.loads(lines[-1])

    def test_sigkill_mid_acquire_leaves_provisional_record(self, tmp_path,
                                                           repo_root):
        import signal
        import time as _time
        proc = self._spawn(tmp_path, repo_root)
        _time.sleep(7.0)  # through startup + >=2 failed probes (5s backoff)
        proc.send_signal(signal.SIGKILL)
        out, _ = proc.communicate(timeout=30)
        rec = self._last_record(out)
        assert rec["provisional"] is True
        assert rec["metric"] == bench.HEADLINE_METRIC
        assert rec["last_known_good"]["headline_value"] == 148519.5
        # the record is in the TAIL the driver reads (last ~10 lines)
        tail = out.strip().splitlines()[-10:]
        assert any(ln.startswith("{") for ln in tail)

    def test_sigterm_mid_acquire_emits_final_record(self, tmp_path,
                                                    repo_root):
        import signal
        proc = self._spawn(tmp_path, repo_root)
        # Wait for the provisional startup record: it prints AFTER the
        # SIGTERM handler is installed, so it is the deterministic
        # "handler is live" signal (a fixed sleep raced interpreter
        # startup under load and the default handler won, rc -15).
        # Bounded: a wedged child must fail the test, not hang CI.
        import threading

        lines = []
        reader = threading.Thread(
            target=lambda: lines.append(proc.stdout.readline()),
            daemon=True)
        reader.start()
        reader.join(timeout=120)
        if not lines:
            proc.kill()
            proc.communicate(timeout=30)
            pytest.fail("child produced no startup record within 120s")
        first = lines[0]
        assert first.startswith("{"), f"unexpected first line: {first!r}"
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=30)
        assert proc.returncode == 1
        rec = self._last_record(first + out)
        assert rec["error_kind"] == "terminated"
        assert rec["last_known_good"]["headline_value"] == 148519.5


class TestSectionPriority:
    """Round-4 weak #6: a short hardware window must land the headline
    and north-star rows before any slow low-value section."""

    def _collect_order(self, monkeypatch, sections=None):
        ran = []
        monkeypatch.setattr(
            bench, "_run_section",
            lambda results, name, thunk: ran.append(name))
        bench.bench_all({}, sections=sections)
        return ran

    def test_all_registered_sections_are_prioritized(self, monkeypatch):
        ran = self._collect_order(monkeypatch)
        assert set(ran) == set(bench.SECTION_PRIORITY), (
            "every registered section must appear in SECTION_PRIORITY "
            "(new sections need an explicit priority slot)")

    def test_headline_then_northstars_first_csr_last(self, monkeypatch):
        ran = self._collect_order(monkeypatch)
        assert ran[0] == bench.HEADLINE_KEY
        assert ran[1] == "northstar256"
        assert ran[2] == "northstar256_df64"
        assert ran[3] == "northstar256_cheb_streaming"
        assert ran[4] == "poisson2d_1M_stencil_resident_cg1"
        assert ran[5] == "poisson2d_4M_stencil_resident"
        assert ran[-1] == "poisson2d_1M_csr"

    def test_sections_filter(self, monkeypatch):
        ran = self._collect_order(
            monkeypatch, sections={"northstar256", bench.HEADLINE_KEY})
        assert ran == [bench.HEADLINE_KEY, "northstar256"]

    def test_unknown_section_raises_with_available(self, monkeypatch):
        with pytest.raises(ValueError, match="unknown sections"):
            self._collect_order(monkeypatch, sections={"nope"})

    def test_cli_sections_implies_all(self):
        args = bench._build_parser().parse_args(
            ["--sections", "northstar256"])
        assert args.sections == "northstar256"
        assert not args.all  # main() promotes it; parser leaves it
