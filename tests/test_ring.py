"""Ring-scheduled distributed CSR SpMV tests (8 virtual devices).

The ring schedule rotates x-blocks via ``lax.ppermute`` instead of
all-gathering x - O(n/P) memory per device, the same communication shape
ring attention uses for KV blocks.  Oracles: slab-partition layout
equality, matvec equality against the global matrix and against the
all-gather operator, and full-solve parity.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cuda_mpi_parallel_tpu.utils.compat import shard_map
import scipy.sparse as sp
from jax.sharding import PartitionSpec as P

from cuda_mpi_parallel_tpu import solve
from cuda_mpi_parallel_tpu.models.operators import CSRMatrix
from cuda_mpi_parallel_tpu.parallel import (
    DistCSRRing,
    make_mesh,
    ring_partition_csr,
    shard_vector,
    solve_distributed,
)

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (virtual) devices")


def _random_spd(n=96, density=0.06, seed=17):
    m = sp.random(n, n, density=density,
                  random_state=np.random.RandomState(seed), format="csr")
    m = m + m.T + sp.eye(n) * (np.abs(m).sum(axis=1).max() + 1.0)
    m = m.tocsr()
    m.sort_indices()
    return CSRMatrix.from_scipy(m), m


def _shard_tree(tree, mesh):
    return jax.tree.map(
        lambda v: shard_vector(jnp.asarray(v), mesh, "rows"), tree)


def _ring_matvec(a, x, n_shards=8):
    mesh = make_mesh(n_shards)
    parts = ring_partition_csr(a, n_shards)
    from cuda_mpi_parallel_tpu.parallel.partition import pad_vector

    x_pad = pad_vector(np.asarray(x), parts.n_global_padded)
    xd = shard_vector(jnp.asarray(x_pad), mesh, "rows")
    data = _shard_tree(parts.data, mesh)
    cols = _shard_tree(parts.cols, mesh)
    rows = _shard_tree(parts.local_rows, mesh)

    @jax.jit
    @shard_map(mesh=mesh, in_specs=(P("rows"),) * 4,
                   out_specs=P("rows"))
    def apply(x_l, d, c, r):
        strip = lambda t: jax.tree.map(lambda v: v[0], t)  # noqa: E731
        op = DistCSRRing(data=strip(d), cols=strip(c), local_rows=strip(r),
                         n_local=parts.n_local, axis_name="rows",
                         n_shards=n_shards)
        return op @ x_l

    return np.asarray(apply(xd, data, cols, rows))[: parts.n_global], parts


def _allgather_matvec(a, x, n_shards=8):
    from cuda_mpi_parallel_tpu.parallel import DistCSR, partition_csr
    from cuda_mpi_parallel_tpu.parallel.partition import pad_vector

    mesh = make_mesh(n_shards)
    parts = partition_csr(a, n_shards)
    x_pad = pad_vector(np.asarray(x), parts.n_global_padded)
    xd = shard_vector(jnp.asarray(x_pad), mesh, "rows")
    data = _shard_tree(parts.data, mesh)
    cols = _shard_tree(parts.cols, mesh)
    rows = _shard_tree(parts.local_rows, mesh)

    @jax.jit
    @shard_map(mesh=mesh, in_specs=(P("rows"),) * 4,
                   out_specs=P("rows"))
    def apply(x_l, d, c, r):
        op = DistCSR(data=d[0], cols=c[0], local_rows=r[0],
                     n_local=parts.n_local, axis_name="rows",
                     n_shards=n_shards)
        return op @ x_l

    return np.asarray(apply(xd, data, cols, rows))[: parts.n_global]


class TestRingPartition:
    def test_slabs_reassemble_matrix(self, rng):
        a, m = _random_spd()
        parts = ring_partition_csr(a, 8)
        n_local = parts.n_local
        dense = np.zeros((8 * n_local, 8 * n_local))
        for s in range(8):
            for t in range(8):
                b = (s + t) % 8
                d = parts.data[t][s]
                live = d != 0
                rows_g = parts.local_rows[t][s][live] + s * n_local
                cols_g = parts.cols[t][s][live] + b * n_local
                np.add.at(dense, (rows_g, cols_g), d[live])
        want = np.zeros_like(dense)
        want[: m.shape[0], : m.shape[1]] = m.toarray()
        np.fill_diagonal(want[m.shape[0]:, m.shape[1]:], 1.0)  # padding
        np.testing.assert_allclose(dense, want, rtol=1e-13, atol=1e-13)

    def test_per_step_padding_not_global(self):
        """A tridiagonal matrix's own-block slab dominates; other steps
        must NOT be padded to its size (the review finding: global-max
        padding inflated per-matvec work ~n_shards x)."""
        import scipy.sparse as sp2

        n = 64
        m = sp2.diags([np.ones(n - 1), 4 * np.ones(n), np.ones(n - 1)],
                      [-1, 0, 1], format="csr")
        m.sort_indices()
        parts = ring_partition_csr(CSRMatrix.from_scipy(m), 8)
        own = parts.data[0].shape[1]
        neighbor = parts.data[1].shape[1]
        far = parts.data[4].shape[1]
        assert own >= 3 * 8 - 2  # ~3 nnz/row * 8 local rows
        assert neighbor <= 2     # one coupling entry at the block edge
        assert far == 1          # empty step, minimum pad


class TestRingMatvec:
    def test_matches_global(self, rng):
        a, m = _random_spd()
        x = rng.standard_normal(a.shape[0])
        got, _ = _ring_matvec(a, x)
        np.testing.assert_allclose(got, m @ x, rtol=1e-12, atol=1e-12)

    def test_matches_allgather_operator(self, rng):
        a, _ = _random_spd(n=64, seed=19)
        x = rng.standard_normal(64)
        got, _ = _ring_matvec(a, x)
        want = _allgather_matvec(a, x)
        np.testing.assert_allclose(got, want, rtol=1e-12, atol=1e-12)

    def test_non_divisible_n(self, rng):
        """n = 50 over 8 shards: padding rows keep shapes uniform."""
        a, m = _random_spd(n=50, density=0.15, seed=23)
        x = rng.standard_normal(50)
        got, parts = _ring_matvec(a, x)
        assert parts.n_global_padded == 56
        np.testing.assert_allclose(got, m @ x, rtol=1e-12, atol=1e-12)


class TestRingSolve:
    def test_matches_allgather_solve(self, rng):
        a, m = _random_spd()
        x_true = rng.standard_normal(a.shape[0])
        b = jnp.asarray(m @ x_true)
        ag = solve_distributed(a, b, mesh=make_mesh(8), tol=0.0,
                               rtol=1e-10, maxiter=500)
        ring = solve_distributed(a, b, mesh=make_mesh(8), tol=0.0,
                                 rtol=1e-10, maxiter=500, csr_comm="ring")
        assert bool(ring.converged)
        assert int(ring.iterations) == int(ag.iterations)
        np.testing.assert_allclose(np.asarray(ring.x), x_true, atol=1e-7)
        np.testing.assert_allclose(np.asarray(ring.x), np.asarray(ag.x),
                                   rtol=1e-10, atol=1e-12)

    def test_ring_with_jacobi(self, rng):
        a, m = _random_spd(seed=29)
        x_true = rng.standard_normal(a.shape[0])
        b = jnp.asarray(m @ x_true)
        res = solve_distributed(a, b, mesh=make_mesh(8), tol=0.0,
                                rtol=1e-10, maxiter=500, csr_comm="ring",
                                preconditioner="jacobi")
        assert bool(res.converged)
        np.testing.assert_allclose(np.asarray(res.x), x_true, atol=1e-7)

    def test_unknown_csr_comm(self):
        a, _ = _random_spd()
        with pytest.raises(ValueError, match="csr_comm"):
            solve_distributed(a, jnp.ones(a.shape[0]), mesh=make_mesh(8),
                              csr_comm="broadcast")
