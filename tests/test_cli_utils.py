"""CLI, Matrix Market I/O, and utility-layer tests."""
import json

import jax.numpy as jnp
import numpy as np
import pytest

from cuda_mpi_parallel_tpu import cli, solve
from cuda_mpi_parallel_tpu.models import mmio, poisson
from cuda_mpi_parallel_tpu.utils import logging as ulog
from cuda_mpi_parallel_tpu.utils.timing import Timer, time_fn


class TestCLI:
    def test_oracle_text(self, capsys):
        rc = cli.main(["--problem", "oracle", "--device", "cpu"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "CONVERGED" in out
        # reference prints the solution vector (CUDACG.cu:361-364)
        assert "0.500000" in out and "0.750000" in out and "1.000000" in out

    def test_poisson2d_json(self, capsys):
        rc = cli.main(["--problem", "poisson2d", "--n", "12", "--device",
                       "cpu", "--tol", "1e-9", "--json"])
        rec = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert rec["converged"] is True
        assert rec["n"] == 144
        assert rec["max_abs_error"] < 1e-6

    def test_jacobi_flag(self, capsys):
        rc = cli.main(["--problem", "poisson2d", "--n", "10", "--device",
                       "cpu", "--precond", "jacobi", "--json"])
        rec = json.loads(capsys.readouterr().out)
        assert rc == 0 and rec["precond"] == "jacobi"

    def test_mesh_flag_distributed(self, capsys):
        rc = cli.main(["--problem", "poisson2d", "--n", "16", "--device",
                       "cpu", "--mesh", "8", "--matrix-free", "--tol",
                       "1e-8", "--json"])
        rec = json.loads(capsys.readouterr().out)
        assert rc == 0 and rec["mesh"] == 8 and rec["converged"]

    def test_history_flag(self, capsys):
        rc = cli.main(["--problem", "oracle", "--device", "cpu",
                       "--history"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "||r||" in out

    def test_nonconverged_exit_code(self, capsys):
        rc = cli.main(["--problem", "poisson2d", "--n", "16", "--device",
                       "cpu", "--maxiter", "2", "--json"])
        rec = json.loads(capsys.readouterr().out)
        assert rc == 1
        assert rec["status"] == "MAXITER"

    def test_mm_requires_file(self):
        with pytest.raises(SystemExit):
            cli.main(["--problem", "mm", "--device", "cpu"])

    def test_dtype_auto_resolves_per_platform(self, capsys):
        """auto -> float64 on CPU hosts (this test process); the record
        reports the resolved dtype, not the sentinel."""
        rc = cli.main(["--problem", "oracle", "--device", "cpu", "--json"])
        rec = json.loads(capsys.readouterr().out)
        assert rc == 0 and rec["dtype"] == "float64"

    def test_backend_without_matrix_free_rejected(self):
        with pytest.raises(SystemExit, match="matrix-free"):
            cli.main(["--problem", "poisson2d", "--n", "8", "--device",
                      "cpu", "--backend", "pallas"])

    def test_bfloat16_unreachable_tol_rejected(self):
        with pytest.raises(SystemExit, match="bfloat16"):
            cli.main(["--problem", "poisson2d", "--n", "8", "--device",
                      "cpu", "--dtype", "bfloat16", "--tol", "1e-7"])

    def test_dtype_df64(self, capsys):
        """df64 reaches tolerances plain f32 cannot (rtol 1e-12)."""
        rc = cli.main(["--problem", "poisson2d", "--n", "16", "--device",
                       "cpu", "--dtype", "df64", "--tol", "0", "--rtol",
                       "1e-12", "--json"])
        rec = json.loads(capsys.readouterr().out)
        assert rc == 0 and rec["converged"] and rec["dtype"] == "df64"
        # ||r|| ~ 1e-11: unreachable for f32 storage (floors near 1e-6);
        # max_abs_error stays ~1e-6 because the CLI builds b in f32
        assert rec["residual_norm"] < 1e-9

    def test_df64_jacobi_supported(self, capsys):
        rc = cli.main(["--problem", "poisson2d", "--n", "12", "--device",
                       "cpu", "--dtype", "df64", "--precond", "jacobi",
                       "--tol", "0", "--rtol", "1e-10", "--json"])
        rec = json.loads(capsys.readouterr().out)
        assert rc == 0 and rec["converged"] and rec["precond"] == "jacobi"

    def test_df64_rejects_unsupported(self):
        # mg on an ASSEMBLED operator has no geometric grid to coarsen
        with pytest.raises(SystemExit, match="df64"):
            cli.main(["--problem", "poisson2d", "--n", "8", "--device",
                      "cpu", "--dtype", "df64", "--precond", "mg"])
        # dense operators have no distributed df64 route
        with pytest.raises(SystemExit, match="df64"):
            cli.main(["--problem", "random-spd", "--n", "8", "--device",
                      "cpu", "--dtype", "df64", "--mesh", "2"])
        # pre-converted formats don't combine with a mesh
        with pytest.raises(SystemExit, match="ring-shiftell"):
            cli.main(["--problem", "poisson2d", "--n", "8", "--device",
                      "cpu", "--dtype", "df64", "--mesh", "2",
                      "--format", "shiftell"])
        with pytest.raises(SystemExit, match="DenseOperator"):
            cli.main(["--problem", "random-spd", "--n", "8", "--device",
                      "cpu", "--dtype", "df64"])
        # dia rejected BEFORE any format conversion work (round-2 advice:
        # fail fast, not after the doomed packing)
        with pytest.raises(SystemExit, match="dia"):
            cli.main(["--problem", "poisson2d", "--n", "8", "--device",
                      "cpu", "--dtype", "df64", "--format", "dia"])

    def test_df64_shiftell(self, capsys):
        """--dtype df64 --format shiftell: the pallas double-float
        lane-gather kernel on an assembled matrix (the reference's
        CUDA_R_64F CSR configuration, CUDACG.cu:216,288)."""
        rc = cli.main(["--problem", "poisson2d", "--n", "16", "--device",
                       "cpu", "--dtype", "df64", "--format", "shiftell",
                       "--tol", "0", "--rtol", "1e-11", "--json"])
        rec = json.loads(capsys.readouterr().out)
        assert rc == 0 and rec["converged"] and rec["dtype"] == "df64"
        assert rec["residual_norm"] < 1e-8

    def test_df64_mesh(self, capsys):
        """--dtype df64 --mesh 2: distributed df64 over a slab mesh
        (matrix-free stencil)."""
        rc = cli.main(["--problem", "poisson2d", "--n", "16", "--device",
                       "cpu", "--dtype", "df64", "--matrix-free",
                       "--mesh", "2", "--tol", "0", "--rtol", "1e-10",
                       "--json"])
        rec = json.loads(capsys.readouterr().out)
        assert rc == 0 and rec["converged"] and rec["mesh"] == 2
        assert rec["residual_norm"] < 1e-7

    def test_shiftell_bfloat16_rejected_cleanly(self):
        """shift-ELL metadata rides the value plane: f32/f64 only, and
        the CLI must surface that as a clean error."""
        with pytest.raises(SystemExit, match="float32/float64"):
            cli.main(["--problem", "poisson2d", "--n", "16", "--device",
                      "cpu", "--format", "shiftell", "--dtype", "bfloat16",
                      "--tol", "1e-2"])

    def test_format_shiftell(self, capsys):
        rc = cli.main(["--problem", "poisson2d", "--n", "16", "--device",
                       "cpu", "--format", "shiftell", "--tol", "1e-8",
                       "--json"])
        rec = json.loads(capsys.readouterr().out)
        assert rc == 0 and rec["converged"] and rec["max_abs_error"] < 1e-5

    def test_bfloat16_loose_rtol_accepted(self, capsys):
        """A loose rtol alone makes the threshold reachable (convergence
        is max(tol, rtol*||r0||)); the guard must not trip."""
        rc = cli.main(["--problem", "poisson2d", "--n", "8", "--device",
                      "cpu", "--dtype", "bfloat16", "--rtol", "1e-1",
                       "--json"])
        rec = json.loads(capsys.readouterr().out)
        assert rec["dtype"] == "bfloat16"
        assert rc in (0, 1)  # reachable: guard passed; convergence may vary


class TestMMIO:
    def test_roundtrip(self, tmp_path):
        a = poisson.poisson_2d_csr(6, 6)
        path = str(tmp_path / "m.mtx")
        mmio.save_matrix_market(path, a)
        a2 = mmio.load_matrix_market(path)
        np.testing.assert_allclose(np.asarray(a2.to_dense()),
                                   np.asarray(a.to_dense()), rtol=1e-12)

    def test_solve_loaded_matrix(self, tmp_path):
        a = poisson.poisson_2d_csr(8, 8)
        path = str(tmp_path / "p.mtx")
        mmio.save_matrix_market(path, a)
        a2 = mmio.load_matrix_market(path)
        b = jnp.asarray(np.random.default_rng(0).standard_normal(64))
        res = solve(a2, b, tol=1e-9, maxiter=300)
        assert bool(res.converged)

    def test_rejects_nonsymmetric(self, tmp_path):
        import scipy.io
        import scipy.sparse as sp

        m = sp.csr_matrix(np.triu(np.ones((4, 4))))
        path = str(tmp_path / "ns.mtx")
        scipy.io.mmwrite(path, m)
        with pytest.raises(ValueError, match="not symmetric"):
            mmio.load_matrix_market(path)

    def test_rejects_rectangular(self, tmp_path):
        import scipy.io
        import scipy.sparse as sp

        m = sp.csr_matrix(np.ones((3, 5)))
        path = str(tmp_path / "rect.mtx")
        scipy.io.mmwrite(path, m)
        with pytest.raises(ValueError, match="not square"):
            mmio.load_matrix_market(path)


class TestUtils:
    def test_time_fn_returns_result(self):
        a, b, _ = poisson.oracle_system()
        el, res = time_fn(lambda: solve(a, b), warmup=1, repeats=2)
        assert el > 0
        assert bool(res.converged)

    def test_paired_delta_rate_counts_and_cancels_overhead(self):
        """The interleaved-pair estimator divides the iteration gap by
        per-pair time deltas: with a fake clock charging a fixed per-call
        overhead plus a constant per-iteration cost, the overhead must
        cancel exactly and the call pattern must be warmup(lo, hi) then
        `pairs` interleaved (lo, hi) pairs."""
        from cuda_mpi_parallel_tpu.utils import timing

        calls = []
        fake_now = [0.0]

        def fake_wall():
            return fake_now[0]

        def run(it):
            calls.append(it)
            fake_now[0] += 0.5 + it * 1e-3   # 0.5s dispatch + 1ms/iter
            return None

        real_wall, real_block = timing.wall_seconds, timing._block
        timing.wall_seconds = fake_wall
        timing._block = lambda tree: None
        try:
            rate = timing.paired_delta_rate(run, 10, 110, pairs=3)
        finally:
            timing.wall_seconds = real_wall
            timing._block = real_block
        assert calls == [10, 110] + [10, 110] * 3
        assert rate == pytest.approx(1000.0)  # 1ms/iter, overhead gone

    def test_timer_sections(self):
        t = Timer()
        with t.section("a"):
            pass
        with t.section("b"):
            pass
        assert [name for name, _ in t.sections] == ["a", "b"]
        assert "a" in t.report()

    def test_solve_record(self):
        a, b, _ = poisson.oracle_system()
        res = solve(a, b, record_history=True)
        rec = ulog.solve_record(res, elapsed_s=0.5, problem="oracle")
        assert rec["iterations"] == 3
        assert rec["status"] == "CONVERGED"
        assert rec["iters_per_sec"] == pytest.approx(6.0)
        assert "iter " in ulog.format_history(res)


def test_df64_variant_methods(capsys):
    """--dtype df64 --method cg1/pipecg: the fused single-collective df64
    recurrences reach f64-class depth through the CLI."""
    import json as _json

    for method in ("cg1", "pipecg"):
        rc = cli.main(["--problem", "poisson2d", "--n", "16", "--device",
                       "cpu", "--dtype", "df64", "--method", method,
                       "--tol", "0", "--rtol", "1e-10", "--json"])
        rec = _json.loads(capsys.readouterr().out)
        assert rc == 0 and rec["converged"], method
        assert rec["residual_norm"] < 1e-7


def test_df64_mesh_csr_ring(capsys):
    """--dtype df64 --mesh N on an assembled-CSR problem: routed through
    the df64 ring-shiftell schedule."""
    import json as _json

    rc = cli.main(["--problem", "poisson2d", "--n", "16", "--device",
                   "cpu", "--dtype", "df64", "--mesh", "2", "--tol", "0",
                   "--rtol", "1e-10", "--json"])
    rec = _json.loads(capsys.readouterr().out)
    assert rc == 0 and rec["converged"] and rec["mesh"] == 2
    assert rec["residual_norm"] < 1e-7


def test_df64_chebyshev_cli(capsys):
    """--dtype df64 --precond chebyshev: the polynomial preconditioner at
    f64-class precision."""
    import json as _json

    rc = cli.main(["--problem", "poisson2d", "--n", "16", "--device",
                   "cpu", "--dtype", "df64", "--precond", "chebyshev",
                   "--tol", "0", "--rtol", "1e-10", "--json"])
    rec = _json.loads(capsys.readouterr().out)
    assert rc == 0 and rec["converged"] and rec["precond"] == "chebyshev"
    with pytest.raises(SystemExit, match="chebyshev"):
        cli.main(["--problem", "poisson2d", "--n", "8", "--device", "cpu",
                  "--dtype", "df64", "--precond", "chebyshev",
                  "--method", "cg1"])


def test_df64_mg_cli(capsys):
    """--dtype df64 --precond mg: mixed-precision multigrid PCG (f32
    V-cycle on the hi word, df64 recurrence) - single-device and over a
    mesh."""
    import json as _json

    rc = cli.main(["--problem", "poisson2d", "--n", "32", "--device",
                   "cpu", "--dtype", "df64", "--precond", "mg",
                   "--matrix-free", "--tol", "0", "--rtol", "1e-10",
                   "--json"])
    rec = _json.loads(capsys.readouterr().out)
    assert rc == 0 and rec["converged"] and rec["precond"] == "mg"
    assert rec["iterations"] < 40  # grid-independent count, not O(n)
    rc = cli.main(["--problem", "poisson2d", "--n", "32", "--device",
                   "cpu", "--dtype", "df64", "--precond", "mg",
                   "--matrix-free", "--mesh", "8", "--tol", "0",
                   "--rtol", "1e-10", "--json"])
    rec = _json.loads(capsys.readouterr().out)
    assert rc == 0 and rec["converged"] and rec["iterations"] < 40
