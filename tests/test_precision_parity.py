"""f64 residual/trajectory parity evidence (reference is all-f64,
``CUDACG.cu:216``: CUDA_R_64F descriptors).

The framework's answer to f64 on a TPU is f32 storage with optional
compensated (double-float) reductions.  These tests pin the measured
behavior documented in README "f64 story":

* moderate conditioning: f32 CG matches the f64 *iteration count* to
  recursive rtol 1e-10 (XLA's pairwise-tree reductions keep dot error
  ~O(eps log n));
* extreme conditioning (diagonally-scaled Poisson): plain f32 pays a
  delayed-convergence penalty and ``compensated=True`` recovers part of
  it - the rest is f32 storage error no reduction fix can remove.

Runs on CPU x64 (conftest) so the f64 trajectory is the native one.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cuda_mpi_parallel_tpu import solve
from cuda_mpi_parallel_tpu.models import poisson
from cuda_mpi_parallel_tpu.models.fem import random_fem_2d
from cuda_mpi_parallel_tpu.models.operators import CSRMatrix


def _as_f32(a64):
    return jax.tree.map(
        lambda v: v.astype(jnp.float32) if v.dtype == jnp.float64 else v,
        a64)


def _iters(a, b, *, compensated=False, rtol=1e-10, maxiter=200_000):
    r = solve(a, b, tol=0.0, rtol=rtol, maxiter=maxiter,
              compensated=compensated)
    assert bool(r.converged), r.status_enum()
    return int(r.iterations)


class TestModerateConditioning:
    """f32 (plain and compensated) matches the f64 iteration count."""

    @pytest.mark.parametrize("make", [
        lambda: poisson.poisson_2d_csr(96, 96),
        lambda: random_fem_2d(8_000, seed=3),
    ])
    def test_iteration_count_parity(self, make, rng):
        a64 = make()
        n = a64.shape[0]
        b64 = a64 @ jnp.asarray(rng.standard_normal(n))
        a32 = _as_f32(a64)
        b32 = jnp.asarray(np.asarray(b64).astype(np.float32))
        it64 = _iters(a64, b64, maxiter=20_000)
        it32 = _iters(a32, b32, maxiter=20_000)
        it32c = _iters(a32, b32, compensated=True, maxiter=20_000)
        assert abs(it32 - it64) <= max(3, it64 // 20)
        assert abs(it32c - it64) <= max(3, it64 // 20)


def _scaled_poisson(nx: int, spread: float, seed: int) -> CSRMatrix:
    """D A D with log-uniform diagonal scaling 10^[-spread, spread]:
    condition number ~ cond(A) * 10^(2*spread)."""
    import scipy.sparse as sp

    rng = np.random.default_rng(seed)
    a = poisson.poisson_2d_csr(nx, nx)
    d = 10.0 ** rng.uniform(-spread, spread, a.shape[0])
    m = sp.csr_matrix((np.asarray(a.data), np.asarray(a.indices),
                       np.asarray(a.indptr)), shape=a.shape)
    return CSRMatrix.from_scipy((sp.diags(d) @ m @ sp.diags(d)).tocsr())


class TestExtremeConditioning:
    def test_compensated_recovers_part_of_the_gap(self, rng):
        """cond ~ 1e9: f32 needs measurably more iterations than f64;
        compensated dots close part of that gap and never widen it."""
        a64 = _scaled_poisson(32, 2.0, seed=0)
        b64 = a64 @ jnp.asarray(rng.standard_normal(a64.shape[0]))
        a32 = _as_f32(a64)
        b32 = jnp.asarray(np.asarray(b64).astype(np.float32))
        it64 = _iters(a64, b64)
        it32 = _iters(a32, b32)
        it32c = _iters(a32, b32, compensated=True)
        assert it32 > it64 * 1.03          # the f32 penalty is real
        assert it32c <= it32 * 1.01        # compensation does not hurt
        # compensated lands closer to (or at least as close to) f64
        assert abs(it32c - it64) <= abs(it32 - it64) * 1.01
