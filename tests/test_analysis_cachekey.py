"""graftverify cache-key soundness audit (analysis.cachekey, ISSUE 16).

The differential contract: perturbing a static argument that changes
the traced solve body MUST change the solver-cache key - same key +
different jaxpr means a second caller silently reuses the wrong
compiled solver.  Tested three ways: (1) toy ``_cached_solver``
dispatches with a DELIBERATELY unsound key (a static kwarg omitted)
are caught by name via :class:`CacheKeyAuditError`, and the sound /
over-keyed twins classify correctly; (2) the audit's own guard rails -
base-determinism re-probe, no-cache-consult and missing-example-args
errors, recorder restoration; (3) the shipped surfaces -
``solve_distributed`` across every static lane and
``ManyRHSDispatcher`` constructor + per-dispatch suffix lanes - audit
green on a mesh-4 CSR system, trace-only (no compile, no device run).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cuda_mpi_parallel_tpu.analysis import (
    CacheKeyAuditError,
    audit_dispatches,
    audit_many_rhs,
    audit_solve_distributed,
    probe_dispatch,
    record_dispatch,
)
from cuda_mpi_parallel_tpu.models import poisson
from cuda_mpi_parallel_tpu.parallel import dist_cg, make_mesh
from cuda_mpi_parallel_tpu.utils import compat

needs_mesh = pytest.mark.skipif(
    not compat.has_shard_map() or len(jax.devices()) < 4,
    reason="needs shard_map and >= 4 (virtual) devices")


def _toy_dispatch(key, scale):
    """A dispatch through the real ``dist_cg._cached_solver`` choke
    point whose build bakes the static ``scale`` into the trace.  The
    caller decides whether ``scale`` makes it into the key - the audit
    must notice when it does not."""
    def build():
        return lambda x: x * scale

    return lambda: dist_cg._cached_solver(
        key, build, None, (jnp.ones(4),))


class TestToySeededViolations:
    """ISSUE satellite: a static kwarg omitted from a toy cache key is
    caught by name."""

    def test_omitted_static_caught_by_name(self):
        base = _toy_dispatch(("toy",), scale=2.0)
        # scale changed the program; the key did not - unsound
        broken = {"scale_omitted": _toy_dispatch(("toy",), scale=3.0)}
        with pytest.raises(CacheKeyAuditError) as exc:
            audit_dispatches(base, broken)
        msg = str(exc.value)
        assert "scale_omitted" in msg
        assert "wrong compiled solver" in msg

    def test_sound_key_is_green(self):
        base = _toy_dispatch(("toy", ("scale", 2.0)), scale=2.0)
        report = audit_dispatches(base, {
            "scale": _toy_dispatch(("toy", ("scale", 3.0)), scale=3.0),
        })
        assert report.ok
        case, = report.cases
        assert case.key_changed and case.jaxpr_changed
        assert not case.unsound and not case.over_keyed

    def test_over_keyed_recorded_not_flagged(self):
        """Key moved, program identical: a wasted compile slot, never a
        correctness finding."""
        base = _toy_dispatch(("toy", ("pad", 0)), scale=2.0)
        report = audit_dispatches(base, {
            "pad": _toy_dispatch(("toy", ("pad", 1)), scale=2.0),
        })
        assert report.ok
        case, = report.cases
        assert case.over_keyed and not case.unsound

    def test_check_false_returns_report(self):
        base = _toy_dispatch(("toy",), scale=2.0)
        report = audit_dispatches(
            base, {"scale_omitted": _toy_dispatch(("toy",), scale=3.0)},
            check=False)
        assert not report.ok
        assert [c.name for c in report.unsound] == ["scale_omitted"]
        assert "UNSOUND" in report.describe()


class TestAuditGuardRails:
    def test_base_nondeterminism_rejected(self):
        """An unstable base key would let every case pass vacuously;
        the re-probe refuses to audit against noise."""
        calls = [0]

        def flaky():
            calls[0] += 1
            return dist_cg._cached_solver(
                ("toy", ("nonce", calls[0])),
                lambda: (lambda x: x * 2.0), None, (jnp.ones(4),))

        with pytest.raises(RuntimeError, match="not deterministic"):
            audit_dispatches(flaky, {})

    def test_dispatch_must_consult_the_cache(self):
        with pytest.raises(RuntimeError, match="without consulting"):
            probe_dispatch(lambda: None)

    def test_dispatch_must_carry_example_args(self):
        """A ``_cached_solver`` call without cost_args cannot be traced
        differentially - loud refusal, not a silent pass."""
        with pytest.raises(RuntimeError, match="example args"):
            probe_dispatch(lambda: dist_cg._cached_solver(
                ("toy",), lambda: (lambda x: x), None, None))

    def test_recorder_always_restored(self):
        original = dist_cg._cached_solver
        probe_dispatch(_toy_dispatch(("toy",), scale=2.0))
        assert dist_cg._cached_solver is original
        with pytest.raises(RuntimeError):
            with record_dispatch():
                raise RuntimeError("caller explodes mid-audit")
        assert dist_cg._cached_solver is original

    def test_probe_never_compiles(self):
        """The probe aborts at the cache boundary: the key it reports
        is exactly what would have been cached, and nothing was."""
        before = dict(dist_cg._SOLVER_CACHE) \
            if hasattr(dist_cg, "_SOLVER_CACHE") else None
        probe = probe_dispatch(
            _toy_dispatch(("toy", ("scale", 2.0)), scale=2.0))
        assert probe.key == ("toy", ("scale", 2.0))
        assert len(probe.jaxpr_digest) == 40  # sha1 hex
        assert probe.args[0].shape == (4,)
        if before is not None:
            assert dict(dist_cg._SOLVER_CACHE) == before


@needs_mesh
class TestShippedSurfaces:
    """The shipped keys are sound: every static lane of both dispatch
    surfaces moves the key whenever it moves the program."""

    def _system(self):
        a = poisson.poisson_2d_csr(10, 10)
        rng = np.random.default_rng(2)
        return a, rng.standard_normal(int(a.shape[0]))

    def test_solve_distributed_key_sound(self):
        a, b = self._system()
        report = audit_solve_distributed(a, b, make_mesh(4))
        assert report.ok
        names = {c.name for c in report.cases}
        assert {"method", "check_every", "preconditioner", "maxiter",
                "exchange", "plan_fingerprint", "flight", "fault",
                "deflate_k", "resumable"} <= names
        # every shipped perturbation is load-bearing: it changes the
        # program AND the key (none vacuous, none over-keyed)
        assert all(c.key_changed and c.jaxpr_changed
                   for c in report.cases), report.describe()

    def test_many_rhs_key_sound(self):
        a, b = self._system()
        b_stack = np.stack([b, 2 * b, 3 * b, 4 * b], axis=1)
        report = audit_many_rhs(a, b_stack, make_mesh(4))
        assert report.ok
        names = {c.name for c in report.cases}
        assert {"method", "compensated", "n_rhs", "flight_override",
                "deflate_k"} <= names
        assert all(c.key_changed and c.jaxpr_changed
                   for c in report.cases), report.describe()

    def test_seeded_regression_on_real_surface(self):
        """Simulate the historical bug on the real lane: a perturbation
        the caller KNOWS changes the program, dispatched so the key
        stays at baseline.  The differential audit - with no list of
        what the key should contain - still catches it."""
        from cuda_mpi_parallel_tpu.parallel import solve_distributed

        a, b = self._system()
        mesh = make_mesh(4)
        base = lambda: solve_distributed(a, b, mesh=mesh, tol=1e-8,
                                         maxiter=300)
        ref = probe_dispatch(base)

        def impostor():
            # trace the jacobi-preconditioned body, then dispatch it
            # under the BASELINE key - the pre-PR-16 failure shape
            probe = probe_dispatch(
                lambda: solve_distributed(a, b, mesh=mesh, tol=1e-8,
                                          maxiter=300,
                                          preconditioner="jacobi"))
            return dist_cg._cached_solver(ref.key, probe.build, None,
                                          probe.args)

        with pytest.raises(CacheKeyAuditError) as exc:
            audit_dispatches(base, {"preconditioner_unkeyed": impostor})
        assert "preconditioner_unkeyed" in str(exc.value)
