"""df64 (double-float) arithmetic + f64-parity CG.

The claim under test (README "f64 story", the reference's CUDA_R_64F
semantics): with df64 storage the CG trajectory matches the native-f64
(x64) solver's - including on ill-conditioned systems where plain f32
pays a measurable delayed-convergence penalty - and final residuals
reach f64 levels, not the f32 ~1e-7 floor.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cuda_mpi_parallel_tpu.utils.compat import shard_map

from cuda_mpi_parallel_tpu import cg_df64, solve
from cuda_mpi_parallel_tpu.models import poisson
from cuda_mpi_parallel_tpu.models.operators import CSRMatrix
from cuda_mpi_parallel_tpu.ops import df64 as df


def _rand_df(rng, n, scale=1.0):
    v = rng.standard_normal(n) * scale
    hi, lo = df.split_f64(v)
    return (jnp.asarray(hi), jnp.asarray(lo)), v


class TestArithmetic:
    def test_split_roundtrip(self, rng):
        v = rng.standard_normal(1000) * 1e3
        hi, lo = df.split_f64(v)
        # df64 carries ~48 of f64's 53 significand bits: relative error
        # bounded by 2^-48 (not an exact roundtrip)
        np.testing.assert_allclose(df.to_f64(hi, lo), v, rtol=2.0 ** -47)
        # |lo| <= ulp_f32(hi)/2: the pair is normalized
        assert np.all(np.abs(lo) <=
                      np.spacing(np.abs(hi).astype(np.float32)) / 2)

    @pytest.mark.parametrize("op,npop", [
        (df.add, np.add), (df.sub, np.subtract), (df.mul, np.multiply),
        (df.div, np.divide),
    ])
    def test_binary_ops_match_f64(self, rng, op, npop):
        a, va = _rand_df(rng, 4096)
        b, vb = _rand_df(rng, 4096)
        if npop is np.divide:
            vb = np.abs(vb) + 0.5
            b = (jnp.abs(b[0]) + 0.5, jnp.where(b[0] < 0, -b[1], b[1]))
        got = df.to_f64(*jax.jit(op)(a, b))
        want = npop(va, df.to_f64(*b) if npop is np.divide else vb)
        # df64 carries ~48 bits: worst-case relative error ~2^-46 for
        # mul/div (the dropped lo*lo term); add/sub cancellation
        # amplifies the *input* representation error, bounded absolutely
        # by ~|operand| * 2^-48 (the atol term)
        np.testing.assert_allclose(got, want, rtol=3e-14, atol=2e-14)

    def test_dot_matches_f64(self, rng):
        a, va = _rand_df(rng, 100_000)
        b, vb = _rand_df(rng, 100_000)
        hi, lo = jax.jit(df.dot)(a, b)
        got = float(np.float64(np.asarray(hi)) + np.float64(np.asarray(lo)))
        want = float(va @ vb)
        # absolute error scales with sum(|x*y|) * 2^-48, not with the
        # (possibly cancelled) result
        scale = float(np.abs(va * vb).sum())
        assert abs(got - want) <= 1e-12 * scale

    def test_dot_cancellation(self):
        """Catastrophic cancellation: +1/-1 blocks that cancel exactly
        plus a 1e-3 tail.  Plain f32 recovers the tail only to ~1e-7
        absolute (partial sums of magnitude ~1); df64 keeps it to
        ~2^-48.  (No fixed-precision method survives arbitrarily wide
        dynamic range: 1e8-magnitude partials would bury a 1e-11-level
        lo word even in f64.)"""
        n = 1024
        v = np.zeros(n)
        v[:500] = 1.0
        v[500:1000] = -1.0   # exactly cancels the positive block
        v[-1] = 1e-3
        a = tuple(jnp.asarray(w) for w in df.split_f64(v))
        ones = tuple(jnp.asarray(w) for w in df.split_f64(np.ones(n)))
        hi, lo = df.dot(a, ones)
        got = float(np.float64(np.asarray(hi)) + np.float64(np.asarray(lo)))
        assert got == pytest.approx(1e-3, rel=1e-9)


class TestMatvec:
    def test_ell_matches_f64_csr(self, rng):
        a = poisson.poisson_2d_csr(24, 24)  # x64: data is f64
        x, vx = _rand_df(rng, 576)
        op = __import__(
            "cuda_mpi_parallel_tpu.solver.df64", fromlist=["x"]
        )._prepare_operator(a)
        yh, yl = op.matvec(x)
        want = np.asarray(a @ jnp.asarray(vx))
        np.testing.assert_allclose(df.to_f64(yh, yl), want, rtol=1e-13,
                                   atol=1e-13)

    @pytest.mark.parametrize("dims", [(17, 23), (9, 11, 13)])
    def test_stencil_matches_x64(self, rng, dims):
        if len(dims) == 2:
            op64 = poisson.poisson_2d_operator(*dims, scale=0.3,
                                               dtype=jnp.float64)
        else:
            op64 = poisson.poisson_3d_operator(*dims, scale=0.3,
                                               dtype=jnp.float64)
        n = int(np.prod(dims))
        x, vx = _rand_df(rng, n)
        sdf = df.const(0.3)
        if len(dims) == 2:
            yh, yl = df.stencil2d_matvec(x, dims, sdf)
        else:
            yh, yl = df.stencil3d_matvec(x, dims, sdf)
        want = np.asarray(op64 @ jnp.asarray(vx))
        np.testing.assert_allclose(df.to_f64(yh, yl), want, rtol=1e-13,
                                   atol=1e-13)


def _scaled_poisson(nx, spread, seed):
    import scipy.sparse as sp

    rng = np.random.default_rng(seed)
    a = poisson.poisson_2d_csr(nx, nx)
    d = 10.0 ** rng.uniform(-spread, spread, a.shape[0])
    m = sp.csr_matrix((np.asarray(a.data), np.asarray(a.indices),
                       np.asarray(a.indptr)), shape=a.shape)
    return CSRMatrix.from_scipy((sp.diags(d) @ m @ sp.diags(d)).tocsr())


class TestCGParity:
    def test_oracle_trajectory(self):
        """The reference's 3x3 system: 3 iterations, f64-class residual
        (the f64 replay reached ~8e-15; plain f32 floors at ~1e-6)."""
        a, b, x_exp = poisson.oracle_system()
        r = cg_df64(a, np.asarray(b, dtype=np.float64))
        assert int(r.iterations) == 3
        assert r.status_enum().name == "CONVERGED"
        assert r.residual_norm() < 1e-12
        assert bool(r.indefinite)  # quirk Q1 is visible in df64 too
        np.testing.assert_allclose(r.x(), np.asarray(x_exp), atol=1e-12)

    def test_poisson_iterations_match_x64(self, rng):
        a = poisson.poisson_2d_csr(48, 48)   # f64 data under x64
        x_true = rng.standard_normal(48 * 48)
        b = np.asarray(a @ jnp.asarray(x_true), dtype=np.float64)
        r64 = solve(a, jnp.asarray(b), tol=0.0, rtol=1e-10, maxiter=10000)
        rdf = cg_df64(a, b, tol=0.0, rtol=1e-10, maxiter=10000)
        assert int(rdf.iterations) == int(r64.iterations)
        np.testing.assert_allclose(rdf.x(), x_true, atol=1e-8)

    def test_ill_conditioned_tracks_x64_where_f32_cannot(self, rng):
        """cond ~ 1e9 diag-scaled Poisson to rtol 1e-10: plain f32 pays
        a large delayed-convergence penalty (measured +180%); df64 must
        land within ~25% of the x64 count and recover at least 80% of
        the f32 penalty (measured: +15%)."""
        a = _scaled_poisson(16, 2.0, seed=0)
        x_true = rng.standard_normal(256)
        b = np.asarray(a @ jnp.asarray(x_true), dtype=np.float64)
        r64 = solve(a, jnp.asarray(b), tol=0.0, rtol=1e-10, maxiter=200_000)
        a32 = jax.tree.map(
            lambda v: v.astype(jnp.float32)
            if v.dtype == jnp.float64 else v, a)
        r32 = solve(a32, jnp.asarray(b).astype(jnp.float32), tol=0.0,
                    rtol=1e-10, maxiter=200_000)
        rdf = cg_df64(a, b, tol=0.0, rtol=1e-10, maxiter=200_000)
        assert bool(r64.converged) and bool(rdf.converged)
        it64, it32, itdf = (int(r64.iterations), int(r32.iterations),
                            int(rdf.iterations))
        assert it32 > it64 * 1.5           # the f32 penalty is real here
        assert itdf <= it64 * 1.25         # df64 tracks f64
        assert (itdf - it64) <= 0.2 * (it32 - it64)
        # at cond ~ 1e9 the x-error bound is cond * rtol ~ 0.1 for ANY
        # arithmetic; the meaningful check is the true f64 residual
        dense = np.asarray(a.to_dense(), dtype=np.float64)
        rel_res = (np.linalg.norm(b - dense @ rdf.x())
                   / np.linalg.norm(b))
        assert rel_res < 1e-9

    def test_stencil_history_and_rtol(self, rng):
        op = poisson.poisson_2d_operator(32, 32, dtype=jnp.float64)
        x_true = rng.standard_normal(1024)
        b = np.asarray(op @ jnp.asarray(x_true), dtype=np.float64)
        r = cg_df64(op, b, tol=0.0, rtol=1e-9, maxiter=5000,
                    record_history=True)
        assert bool(r.converged)
        hist = np.asarray(r.residual_history)[: int(r.iterations) + 1]
        assert hist[0] > hist[int(r.iterations)]
        np.testing.assert_allclose(r.x(), x_true, atol=1e-7)

    def test_jacobi_matches_x64_jacobi_pcg(self, rng):
        """Jacobi-PCG in df64: same iteration count as the x64 solver's
        Jacobi path on a diag-scaled system (where Jacobi actually
        helps), converging to a depth f32 cannot reach."""
        from cuda_mpi_parallel_tpu.models.operators import (
            JacobiPreconditioner,
        )

        a = _scaled_poisson(16, 1.0, seed=1)
        x_true = rng.standard_normal(256)
        b = np.asarray(a @ jnp.asarray(x_true), dtype=np.float64)
        r64 = solve(a, jnp.asarray(b), tol=0.0, rtol=1e-10, maxiter=50_000,
                    m=JacobiPreconditioner.from_operator(a))
        rdf = cg_df64(a, b, tol=0.0, rtol=1e-10, maxiter=50_000,
                      preconditioner="jacobi")
        rplain = cg_df64(a, b, tol=0.0, rtol=1e-10, maxiter=50_000)
        assert bool(rdf.converged)
        it64, itdf = int(r64.iterations), int(rdf.iterations)
        assert abs(itdf - it64) <= max(2, it64 // 20)
        assert itdf < int(rplain.iterations)  # jacobi helps here
        dense = np.asarray(a.to_dense(), dtype=np.float64)
        assert (np.linalg.norm(b - dense @ rdf.x())
                / np.linalg.norm(b)) < 1e-9

    def test_distributed_axis_name_matches_single(self, rng):
        """The psum path: a block-diagonal ELL system row-sharded over 8
        devices inside shard_map must reproduce the single-device df64
        trajectory (each shard's block only references local x)."""
        from functools import partial

        import scipy.sparse as sp
        from jax.sharding import PartitionSpec as P

        from cuda_mpi_parallel_tpu.parallel import make_mesh
        from cuda_mpi_parallel_tpu.solver import df64 as sdf

        n_shards, n_local = 8, 128
        # well-conditioned tridiagonal (cond ~ 3): iteration counts are
        # insensitive to the different dot-reduction orderings of the
        # sharded vs single-device runs
        m = sp.diags([-np.ones(n_local - 1), 4 * np.ones(n_local),
                      -np.ones(n_local - 1)], [-1, 0, 1]).tocsr()
        block = CSRMatrix.from_scipy(m)
        ell = block.to_ell()
        vh, vl = df.split_f64(np.asarray(ell.vals, dtype=np.float64))
        dh, dl = df.split_f64(np.asarray(block.diagonal(),
                                         dtype=np.float64))
        zero = jnp.zeros((), jnp.float32)

        n = n_shards * n_local
        b = rng.standard_normal(n)
        bh, bl = df.split_f64(b)
        tol2 = df.const(0.0)
        rtol2 = df.const(1e-20)  # rtol 1e-10 squared

        mesh = make_mesh(n_shards)
        axis = mesh.axis_names[0]

        @partial(shard_map, mesh=mesh, in_specs=(P(axis), P(axis)),
                 out_specs=sdf.DF64CGResult(
                     x_hi=P(axis), x_lo=P(axis), iterations=P(),
                     residual_norm_sq_hi=P(), residual_norm_sq_lo=P(),
                     converged=P(), status=P(), indefinite=P(),
                     residual_history=None))
        def run(bh_l, bl_l):
            op = sdf._DF64Operator(
                vals_hi=jnp.asarray(vh), vals_lo=jnp.asarray(vl),
                cols=ell.cols, scale_hi=zero, scale_lo=zero,
                diag_hi=jnp.asarray(dh), diag_lo=jnp.asarray(dl),
                kind="ell", grid=())
            return sdf._solve(op, (bh_l, bl_l), tol2, rtol2, None,
                              maxiter=2000, record_history=False,
                              jacobi=False, axis_name=axis)

        r_dist = run(jnp.asarray(bh), jnp.asarray(bl))

        # single-device reference: block-diagonal global system
        mg = sp.block_diag([sp.csr_matrix(np.asarray(block.to_dense()))
                            ] * n_shards).tocsr()
        r_one = cg_df64(CSRMatrix.from_scipy(mg), b, tol=0.0, rtol=1e-10,
                        maxiter=2000)
        assert bool(r_dist.converged)
        assert int(r_dist.iterations) == int(r_one.iterations)
        np.testing.assert_allclose(
            df.to_f64(r_dist.x_hi, r_dist.x_lo), r_one.x(), rtol=1e-12,
            atol=1e-13)

    def test_checkpoint_resume_exact_trajectory(self, rng):
        """Segmented df64 solve == uninterrupted: same iteration count
        and bitwise-identical solution pairs (mirror of the f32 solver's
        checkpoint guarantee)."""
        a = poisson.poisson_2d_csr(24, 24)
        x_true = rng.standard_normal(576)
        b = np.asarray(a @ jnp.asarray(x_true), dtype=np.float64)
        full = cg_df64(a, b, tol=0.0, rtol=1e-10, maxiter=2000)
        part = cg_df64(a, b, tol=0.0, rtol=1e-10, maxiter=30,
                       return_checkpoint=True)
        assert int(part.iterations) == 30
        resumed = cg_df64(a, b, tol=0.0, rtol=1e-10, maxiter=2000,
                          resume_from=part.checkpoint)
        assert int(resumed.iterations) == int(full.iterations)
        np.testing.assert_array_equal(np.asarray(resumed.x_hi),
                                      np.asarray(full.x_hi))
        np.testing.assert_array_equal(np.asarray(resumed.x_lo),
                                      np.asarray(full.x_lo))

    def test_resume_rtol_uses_original_rr0(self, rng):
        """The rtol threshold must reference the ORIGINAL rhs norm, not
        the (smaller) residual at the checkpoint."""
        a = poisson.poisson_2d_csr(16, 16)
        b = np.asarray(a @ jnp.asarray(rng.standard_normal(256)),
                       dtype=np.float64)
        part = cg_df64(a, b, tol=0.0, rtol=1e-8, maxiter=20,
                       return_checkpoint=True)
        resumed = cg_df64(a, b, tol=0.0, rtol=1e-8, maxiter=2000,
                          resume_from=part.checkpoint)
        full = cg_df64(a, b, tol=0.0, rtol=1e-8, maxiter=2000)
        assert int(resumed.iterations) == int(full.iterations)

    def test_final_residual_reaches_f64_levels(self, rng):
        """Drive to rtol 1e-13: unreachable for f32 storage, routine for
        df64."""
        a = poisson.poisson_2d_csr(24, 24)
        x_true = rng.standard_normal(576)
        b = np.asarray(a @ jnp.asarray(x_true), dtype=np.float64)
        r = cg_df64(a, b, tol=0.0, rtol=1e-13, maxiter=20000)
        assert bool(r.converged)
        true_res = np.linalg.norm(
            b - np.asarray(a.to_dense(), dtype=np.float64) @ r.x())
        assert true_res / np.linalg.norm(b) < 1e-11


class TestDF64Variants:
    """cg1 (single-reduction) and pipecg (overlapped) df64 variants:
    same iterates as the textbook recurrence in exact arithmetic, one
    fused collective per iteration on a mesh (ops.df64.fused_dots)."""

    def _system(self, rng, n=20):
        op = poisson.poisson_2d_operator(n, n, dtype=jnp.float64)
        x_true = rng.standard_normal(n * n)
        b = np.asarray(op @ jnp.asarray(x_true), dtype=np.float64)
        op32 = poisson.poisson_2d_operator(n, n, dtype=jnp.float32)
        return op32, b, x_true

    @pytest.mark.parametrize("method", ["cg1", "pipecg"])
    def test_trajectory_parity_with_cg(self, rng, method):
        op, b, _ = self._system(rng)
        base = cg_df64(op, b, tol=0.0, maxiter=30, record_history=True)
        var = cg_df64(op, b, tol=0.0, maxiter=30, record_history=True,
                      method=method)
        # identical recurrence in exact arithmetic: histories agree far
        # beyond f32 depth (compared at the f32 storage resolution)
        np.testing.assert_allclose(
            np.asarray(var.residual_history),
            np.asarray(base.residual_history), rtol=1e-4)

    @pytest.mark.parametrize("method", ["cg1", "pipecg"])
    def test_reaches_f64_depth(self, rng, method):
        op, b, x_true = self._system(rng)
        r = cg_df64(op, b, tol=0.0, rtol=1e-11, maxiter=5000,
                    method=method)
        assert bool(r.converged)
        np.testing.assert_allclose(r.x(), x_true, atol=1e-7)

    @pytest.mark.parametrize("method", ["cg1", "pipecg"])
    def test_jacobi_and_check_every(self, rng, method):
        op, b, x_true = self._system(rng)
        r = cg_df64(op, b, tol=0.0, rtol=1e-10, maxiter=5000,
                    method=method, preconditioner="jacobi",
                    check_every=8)
        assert bool(r.converged)
        np.testing.assert_allclose(r.x(), x_true, atol=1e-6)

    def test_oracle_cg1(self):
        """The reference's indefinite 3x3 system through the
        single-reduction recurrence (quirk Q1 still recorded)."""
        a, b, x_exp = poisson.oracle_system(dtype=jnp.float64)
        r = cg_df64(a, np.asarray(b, np.float64), tol=1e-7, method="cg1")
        assert bool(r.converged) and bool(r.indefinite)
        assert int(r.iterations) == 3
        np.testing.assert_allclose(r.x(), np.asarray(x_exp), atol=1e-10)

    def test_exact_solve_freeze(self, rng):
        """A = I under check_every blocking: overrun steps freeze via
        _safe_div in the variants too."""
        n = 64
        rows = np.arange(n, dtype=np.int32)
        a = CSRMatrix.from_coo(rows, rows, np.ones(n), n,
                               dtype=np.float64)
        b = rng.standard_normal(n)
        for method in ("cg1", "pipecg"):
            r = cg_df64(a.to_ell(), b, tol=1e-12, maxiter=64,
                        check_every=8, method=method)
            assert bool(r.converged), method
            np.testing.assert_allclose(r.x(), b, rtol=1e-13)

    def test_checkpoint_requires_cg(self, rng):
        op, b, _ = self._system(rng, n=8)
        with pytest.raises(ValueError, match="method='cg'"):
            cg_df64(op, b, method="cg1", return_checkpoint=True)

    def test_fused_dots_matches_dot(self, rng):
        a, va = _rand_df(rng, 4096)
        b, vb = _rand_df(rng, 4096)
        [d1, d2] = df.fused_dots([(a, b), (a, a)])
        np.testing.assert_allclose(df.to_f64(*d1), float(va @ vb),
                                   rtol=1e-13)
        np.testing.assert_allclose(df.to_f64(*d2), float(va @ va),
                                   rtol=1e-13)


class TestCompilerEFTSafety:
    """Regression: XLA:CPU duplicates cheap products into consumer
    fusions and contracts them into FMAs, which broke the classic Dekker
    two-prod (error computed against the UNROUNDED product - df64 axpy
    degraded to 5e-9).  The add-only two_prod formulation must hold df64
    accuracy under jit in exactly the fusion contexts that failed."""

    def test_jitted_axpy_with_negated_scalar(self, rng):
        n = 4096
        (q, qv) = _rand_df(rng, n)
        (u, uv) = _rand_df(rng, n)
        ah, al = df.split_f64(np.float64(-0.037123456789))
        alpha = (jnp.asarray(ah), jnp.asarray(al))
        av = float(np.float64(ah) + np.float64(al))

        j = jax.jit(lambda a, x, y: df.axpy(df.neg(a), x, y))(alpha, q, u)
        err = np.max(np.abs(df.to_f64(*j) - (-av * qv + uv)))
        assert err < 1e-12, f"df64 axpy degraded under jit: {err:.3e}"

    def test_jitted_paired_axpys_share_scalar(self, rng):
        """The pipecg shape that exposed the bug: two axpys sharing a
        negated scalar inside ONE jit."""
        n = 4096
        (q, qv) = _rand_df(rng, n)
        (u, uv) = _rand_df(rng, n)
        (s, sv) = _rand_df(rng, n)
        (r, rv) = _rand_df(rng, n)
        ah, al = df.split_f64(np.float64(-0.037123456789))
        alpha = (jnp.asarray(ah), jnp.asarray(al))
        av = float(np.float64(ah) + np.float64(al))

        def two(a, q, u, s, r):
            return (df.axpy(df.neg(a), s, r), df.axpy(df.neg(a), q, u))

        jr, ju = jax.jit(two)(alpha, q, u, s, r)
        assert np.max(np.abs(df.to_f64(*jr) - (-av * sv + rv))) < 1e-12
        assert np.max(np.abs(df.to_f64(*ju) - (-av * qv + uv))) < 1e-12

    def test_two_prod_exactness(self, rng):
        """p + err == a*b to O(eps^2): the add-only decomposition keeps
        the two-prod contract the compensated dots rely on."""
        from cuda_mpi_parallel_tpu.ops.blas1 import _two_prod

        a = jnp.asarray(rng.standard_normal(10000), jnp.float32)
        b = jnp.asarray(rng.standard_normal(10000), jnp.float32)
        p, e = jax.jit(_two_prod)(a, b)
        exact = (np.asarray(a, np.float64) * np.asarray(b, np.float64))
        got = np.asarray(p, np.float64) + np.asarray(e, np.float64)
        rel = np.max(np.abs(got - exact) / np.maximum(np.abs(exact), 1e-30))
        assert rel < 2.0 ** -45


def test_large_assembled_gather_path_warns(rng):
    """Round-2 verdict weakness: nothing warned that df64 on a large
    assembled csr/ell matrix is ~400x off the pallas rate.  Now the
    operator preparation does (and points at to_shiftell_df64)."""
    import warnings

    from cuda_mpi_parallel_tpu.solver.df64 import _prepare_operator

    n = 250_000
    rows = np.arange(n, dtype=np.int32)
    a = CSRMatrix.from_coo(rows, rows, np.ones(n), n, dtype=np.float64)
    with pytest.warns(UserWarning, match="to_shiftell_df64"):
        _prepare_operator(a)
    # small systems stay silent
    a_small = poisson.poisson_2d_csr(8, 8)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        _prepare_operator(a_small)


class TestDF64Chebyshev:
    """Chebyshev polynomial preconditioning in df64 (BASELINE config #3's
    strong preconditioner at f64-class precision; spectral interval from
    a host-side power iteration - chebyshev_interval)."""

    def _system(self, rng, n=24):
        op = poisson.poisson_2d_operator(n, n, dtype=jnp.float32)
        op64 = poisson.poisson_2d_operator(n, n, dtype=jnp.float64)
        x_true = rng.standard_normal(n * n)
        b = np.asarray(op64 @ jnp.asarray(x_true), dtype=np.float64)
        return op, b, x_true

    def test_cuts_iterations_and_reaches_depth(self, rng):
        op, b, x_true = self._system(rng)
        plain = cg_df64(op, b, tol=0.0, rtol=1e-11, maxiter=5000)
        cheb = cg_df64(op, b, tol=0.0, rtol=1e-11, maxiter=5000,
                       preconditioner="chebyshev", precond_degree=4)
        assert bool(cheb.converged)
        # degree-4 Chebyshev should cut the count by >~2x on Poisson
        assert int(cheb.iterations) * 2 < int(plain.iterations)
        np.testing.assert_allclose(cheb.x(), x_true, atol=1e-8)

    def test_interval_is_deterministic(self, rng):
        from cuda_mpi_parallel_tpu.solver.df64 import chebyshev_interval

        op, _, _ = self._system(rng, n=12)
        t1, d1 = chebyshev_interval(op)
        t2, d2 = chebyshev_interval(op)
        assert float(t1[0]) == float(t2[0])
        assert float(d1[0]) == float(d2[0])
        # 2D 5-point Laplacian: lmax < 8, so theta ~ (lmax*1.1*(1+1/30))/2
        assert 3.0 < float(t1[0]) < 5.0

    def test_interval_from_df64_operator(self, rng):
        """ShiftELLDF64Matrix has no f32 matvec: the interval comes from
        the eager hi-word power iteration."""
        from cuda_mpi_parallel_tpu.solver.df64 import chebyshev_interval

        a = poisson.poisson_2d_csr(12, 12, dtype=np.float64)
        t_sell, _ = chebyshev_interval(a.to_shiftell_df64(h=2))
        t_csr, _ = chebyshev_interval(a)
        # two independent 30-step power iterations on the slow-gap
        # Laplacian spectrum: ~percent-level agreement, not exactness
        np.testing.assert_allclose(float(t_sell[0]), float(t_csr[0]),
                                   rtol=0.1)

    def test_rejects_variants(self, rng):
        op, b, _ = self._system(rng, n=8)
        with pytest.raises(ValueError, match="method='cg'"):
            cg_df64(op, b, preconditioner="chebyshev", method="cg1")

    def test_check_every_composes(self, rng):
        op, b, x_true = self._system(rng)
        r = cg_df64(op, b, tol=0.0, rtol=1e-10, maxiter=5000,
                    preconditioner="chebyshev", check_every=8)
        assert bool(r.converged)
        np.testing.assert_allclose(r.x(), x_true, atol=1e-7)
