"""telemetry.shardscope: static per-shard load/imbalance accounting.

Every number here is hand-computed from a deliberately skewed matrix -
the accounting layer must report exactly the skew the partition has,
or imbalance-driven decisions (ROADMAP: repartitioning) inherit the
error.
"""
import json

import numpy as np
import pytest

from cuda_mpi_parallel_tpu import telemetry
from cuda_mpi_parallel_tpu.models.operators import CSRMatrix
from cuda_mpi_parallel_tpu.parallel import partition as part
from cuda_mpi_parallel_tpu.telemetry import events
from cuda_mpi_parallel_tpu.telemetry import shardscope as ss


def skewed_csr(n=8, fat_row=0, dtype=np.float32):
    """n x n CSR: one dense row (n entries), every other row a bare
    unit diagonal - maximal row skew with trivially known counts."""
    rows, cols, vals = [], [], []
    for i in range(n):
        if i == fat_row:
            for j in range(n):
                rows.append(i)
                cols.append(j)
                vals.append(2.0 if i == j else 0.5)
        else:
            rows.append(i)
            cols.append(i)
            vals.append(2.0)
    return CSRMatrix.from_coo(np.array(rows), np.array(cols),
                              np.array(vals, dtype=dtype), n, dtype=dtype)


class TestImbalanceMath:
    def test_max_over_mean(self):
        assert ss.max_over_mean([4, 4, 4, 4]) == 1.0
        assert ss.max_over_mean([11, 4]) == pytest.approx(11 / 7.5)
        assert ss.max_over_mean([]) == 1.0
        assert ss.max_over_mean([0, 0]) == 1.0

    def test_gini_uniform_and_concentrated(self):
        assert ss.gini([5, 5, 5, 5]) == 0.0
        # all load on one of P shards -> (P - 1) / P
        assert ss.gini([12, 0, 0, 0]) == pytest.approx(0.75)
        # hand: [11, 4] -> sum|xi-xj| = 14, / (2 * 4 * 7.5)
        assert ss.gini([11, 4]) == pytest.approx(14 / 60)


class TestPartitionCSRReport:
    def test_skewed_counts_hand_computed(self):
        # 8 rows over 2 shards: shard 0 owns the fat row (8 entries)
        # plus 3 diagonals = 11 nnz; shard 1 owns 4 diagonals.
        a = skewed_csr(8)
        parts = part.partition_csr(a, 2)
        rep = ss.report_partition_csr(a, parts)
        assert rep.kind == "csr-allgather"
        assert rep.n_local == 4 and rep.n_global == 8
        np.testing.assert_array_equal(rep.rows, [4, 4])
        np.testing.assert_array_equal(rep.nnz, [11, 4])
        # both shards are padded to the max entry count (11)
        np.testing.assert_array_equal(rep.slots, [11, 11])
        pad = rep.padding_overhead()
        assert pad[0] == 0.0
        assert pad[1] == pytest.approx(7 / 11)
        imb = rep.imbalance()
        assert imb["nnz_max_over_mean"] == pytest.approx(11 / 7.5)
        assert imb["nnz_gini"] == pytest.approx(14 / 60)
        assert imb["rows_max_over_mean"] == 1.0
        assert imb["padding_overhead_total"] == pytest.approx(7 / 22)

    def test_allgather_halo_payload(self):
        # payload semantics: each shard contributes its n_local block
        # (f32) and receives the other P-1 blocks
        a = skewed_csr(8)
        rep = ss.report_partition_csr(a, part.partition_csr(a, 2))
        np.testing.assert_array_equal(rep.halo_send_bytes, [16, 16])
        np.testing.assert_array_equal(rep.halo_recv_bytes, [16, 16])
        a4 = skewed_csr(16)
        rep4 = ss.report_partition_csr(a4, part.partition_csr(a4, 4))
        np.testing.assert_array_equal(rep4.halo_send_bytes, [16] * 4)
        np.testing.assert_array_equal(rep4.halo_recv_bytes, [48] * 4)

    def test_padding_rows_counted_as_overhead_not_nnz(self):
        # n=5 over 2 shards: n_local=3, shard 1 owns rows 3,4 plus one
        # synthetic unit-diagonal padding row.  Real nnz must exclude
        # the synthetic entry; slots must include it.
        a = skewed_csr(5)
        parts = part.partition_csr(a, 2)
        rep = ss.report_partition_csr(a, parts)
        np.testing.assert_array_equal(rep.rows, [3, 2])
        np.testing.assert_array_equal(rep.nnz, [7, 2])  # 5+1+1, 1+1
        # shard 1's count: 2 real + 1 padding-diag = 3 -> m = max(7, 3)
        np.testing.assert_array_equal(rep.slots, [7, 7])


class TestRingReports:
    def test_ring_csr_neighbors_and_slots(self):
        a = skewed_csr(16)
        parts = part.ring_partition_csr(a, 4)
        rep = ss.report_ring_csr(a, parts)
        assert rep.kind == "csr-ring"
        np.testing.assert_array_equal(rep.nnz, [19, 4, 4, 4])
        # x-block rotation: P-1 ppermute steps x n_local f32 payload
        np.testing.assert_array_equal(rep.halo_send_bytes, [48] * 4)
        np.testing.assert_array_equal(rep.halo_recv_bytes, [48] * 4)
        # shard k sends to (k - 1) % P
        assert rep.neighbors[0] == ((3, 48),)
        assert rep.neighbors[2] == ((1, 48),)
        # slots: per-step max padded across owners, summed over steps
        expected_slots = sum(d.shape[1] for d in parts.data)
        np.testing.assert_array_equal(rep.slots, [expected_slots] * 4)
        assert int(rep.slots[0]) >= int(rep.nnz.max())

    def test_ring_shiftell_hand_checked(self):
        """The satellite case: a row-skewed unstructured CSR through
        ring_partition_shiftell - nnz/halo from first principles, slot
        geometry from the packed sheet shapes."""
        a = skewed_csr(512, fat_row=3)
        parts = part.ring_partition_shiftell(a, 4, h=2, kc=4)
        rep = ss.report_ring_shiftell(a, parts)
        assert rep.kind == "ring-shiftell"
        assert rep.n_local == 128
        # shard 0 holds the fat row: 512 + 127 diagonals; others 128
        np.testing.assert_array_equal(rep.nnz, [639, 128, 128, 128])
        assert rep.imbalance()["nnz_max_over_mean"] == pytest.approx(
            639 / (1023 / 4))
        # ring payload: 3 steps x 128 rows x 4 B
        np.testing.assert_array_equal(rep.halo_send_bytes, [1536] * 4)
        # slot geometry == the packed value planes (C_t * kc * (h+1) * 128)
        expected = sum(int(np.prod(v.shape[1:])) for v in parts.vals)
        np.testing.assert_array_equal(rep.slots, [expected] * 4)
        # padding overhead is real here: sheet packing rounds up
        assert (rep.padding_overhead() > 0).all()

    def test_ring_shiftell_df64_doubles_payload(self):
        a = skewed_csr(512, fat_row=3)
        parts = part.ring_partition_shiftell_df64(a, 4, h=2, kc=4)
        rep = ss.report_ring_shiftell(a, parts)
        assert rep.kind == "ring-shiftell-df64"
        # both (hi, lo) f32 planes rotate in ONE stacked ppermute
        np.testing.assert_array_equal(rep.halo_send_bytes, [3072] * 4)
        np.testing.assert_array_equal(rep.nnz, [639, 128, 128, 128])

    def test_dispatch(self):
        a = skewed_csr(16)
        assert ss.shard_report(
            a, part.partition_csr(a, 2)).kind == "csr-allgather"
        assert ss.shard_report(
            a, part.ring_partition_csr(a, 2)).kind == "csr-ring"
        with pytest.raises(TypeError, match="no shard accounting"):
            ss.shard_report(a, object())


class TestStencilReport:
    def test_edge_vs_interior_halo(self):
        rep = ss.report_stencil((8, 16), 4, 4, points=5, kind="stencil2d")
        plane = 16 * 4
        np.testing.assert_array_equal(
            rep.halo_send_bytes, [plane, 2 * plane, 2 * plane, plane])
        np.testing.assert_array_equal(rep.halo_recv_bytes,
                                      rep.halo_send_bytes)
        assert rep.neighbors[0] == ((1, plane),)
        assert rep.neighbors[1] == ((2, plane), (0, plane))
        np.testing.assert_array_equal(rep.rows, [128] * 4)
        np.testing.assert_array_equal(rep.nnz, [640] * 4)
        imb = rep.imbalance()
        assert imb["halo_send_max_over_mean"] == pytest.approx(4 / 3)
        assert imb["nnz_max_over_mean"] == 1.0


class TestEmission:
    def test_note_report_event_and_gauges(self):
        from cuda_mpi_parallel_tpu.telemetry.registry import REGISTRY

        a = skewed_csr(8)
        rep = ss.report_partition_csr(a, part.partition_csr(a, 2))
        ss.reset_last_shard_report()
        try:
            with events.capture() as buf:
                telemetry.force_active(True)
                ss.note_report(rep)
            lines = [json.loads(ln) for ln in
                     buf.getvalue().strip().splitlines()]
            profs = [ev for ev in lines if ev["event"] == "shard_profile"]
            assert len(profs) == 1
            events.validate_event(profs[0])
            assert profs[0]["kind"] == "csr-allgather"
            assert profs[0]["nnz"] == [11, 4]
            # the event payload round-trips to an identical report
            rt = ss.ShardReport.from_json(profs[0])
            np.testing.assert_array_equal(rt.nnz, rep.nnz)
            np.testing.assert_array_equal(rt.halo_send_bytes,
                                          rep.halo_send_bytes)
            assert ss.last_shard_report() is rep
            g = REGISTRY.gauge("shard_nnz",
                               labelnames=("kind", "shard"))
            assert g.value(kind="csr-allgather", shard="0") == 11.0
            assert g.value(kind="csr-allgather", shard="1") == 4.0
            imb = REGISTRY.gauge("shard_nnz_imbalance",
                                 labelnames=("kind",))
            assert imb.value(kind="csr-allgather") == pytest.approx(
                11 / 7.5)
        finally:
            telemetry.force_active(False)
            ss.reset_last_shard_report()

    def test_inactive_still_parks_report(self):
        a = skewed_csr(8)
        rep = ss.report_partition_csr(a, part.partition_csr(a, 2))
        ss.reset_last_shard_report()
        telemetry.force_active(False)
        events.configure(None)
        ss.note_report(rep)
        assert ss.last_shard_report() is rep
        ss.reset_last_shard_report()
        assert ss.last_shard_report() is None
