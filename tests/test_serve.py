"""The microbatching solver service (cuda_mpi_parallel_tpu.serve).

Policy tests drive the service in MANUAL mode with a fake clock - no
worker thread, time advances only when the test says so - so every
timing branch (max_wait vs max_batch ordering, deadline expiry) is
deterministic.  The end-to-end tests prove the service is a pure
batcher: replayed answers BIT-match direct ``solve_many`` /
``solve_distributed_many`` calls on the same padded buckets, and
post-warmup traffic triggers zero new traces.
"""
import json

import jax
import numpy as np
import pytest

from cuda_mpi_parallel_tpu.models.operators import CSRMatrix
from cuda_mpi_parallel_tpu.models import poisson
from cuda_mpi_parallel_tpu.serve import (
    MicroBatchQueue,
    QueueFull,
    ServiceClosed,
    ServiceConfig,
    SolverService,
    WorkloadRequest,
    bucket_for,
    bucket_sizes,
    load_workload,
    rhs_for,
    save_workload,
    synthetic_poisson,
    tol_class,
)
from cuda_mpi_parallel_tpu.serve.queue import QueuedRequest
from cuda_mpi_parallel_tpu.solver.many import stack_columns
from cuda_mpi_parallel_tpu.telemetry import events


class FakeClock:
    """The test harness's clock: starts at 0, advances on demand."""

    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> float:
        self.t += dt
        return self.t


def manual_service(**kw) -> "tuple[SolverService, FakeClock]":
    clock = FakeClock()
    kw.setdefault("max_batch", 4)
    kw.setdefault("max_wait_s", 0.010)
    kw.setdefault("maxiter", 500)
    svc = SolverService(ServiceConfig(clock=clock, **kw))
    return svc, clock


def poisson_csr(n=12, dtype=np.float64):
    return poisson.poisson_2d_csr(n, n, dtype=dtype)


# ---------------------------------------------------------------------------
# bucket / tol-class math


class TestBucketMath:
    def test_bucket_sizes_powers_of_two_plus_cap(self):
        assert bucket_sizes(1) == (1,)
        assert bucket_sizes(2) == (1, 2)
        assert bucket_sizes(8) == (1, 2, 4, 8)
        assert bucket_sizes(6) == (1, 2, 4, 6)
        assert bucket_sizes(32) == (1, 2, 4, 8, 16, 32)
        with pytest.raises(ValueError):
            bucket_sizes(0)

    def test_bucket_for_smallest_fit(self):
        assert bucket_for(1, 8) == 1
        assert bucket_for(2, 8) == 2
        assert bucket_for(3, 8) == 4
        assert bucket_for(5, 8) == 8
        assert bucket_for(5, 6) == 6
        with pytest.raises(ValueError):
            bucket_for(9, 8)
        with pytest.raises(ValueError):
            bucket_for(0, 8)

    def test_tol_class_decades(self):
        assert tol_class(1e-7) == tol_class(2e-7)
        assert tol_class(1e-7) != tol_class(1e-3)
        assert tol_class(0.0) == "exact"

    def test_stack_columns_pads_with_zeros(self):
        cols = [np.ones(5), 2 * np.ones(5), 3 * np.ones(5)]
        out = stack_columns(cols, 4)
        assert out.shape == (5, 4)
        np.testing.assert_array_equal(out[:, 2], 3.0)
        np.testing.assert_array_equal(out[:, 3], 0.0)
        with pytest.raises(ValueError):
            stack_columns(cols, 2)     # 3 columns cannot fit k=2
        with pytest.raises(ValueError):
            stack_columns([], 2)

    def test_zero_pad_lane_freezes_at_iteration_zero(self):
        """The padding contract: a zero-RHS lane costs 0 iterations."""
        from cuda_mpi_parallel_tpu.solver import solve_many

        a = poisson_csr(8)
        rng = np.random.default_rng(3)
        b = stack_columns([rng.standard_normal(a.shape[0])], 4)
        res = solve_many(a, b, tol=1e-9, maxiter=400)
        iters = np.asarray(res.iterations)
        assert iters[0] > 0
        np.testing.assert_array_equal(iters[1:], 0)
        assert bool(np.asarray(res.converged).all())


# ---------------------------------------------------------------------------
# queue policy (pure, no service)


def _req(i, t, tol=1e-7, deadline_t=None, handle="h", dtype="float64"):
    from concurrent.futures import Future

    return QueuedRequest(request_id=f"r{i}", handle_key=handle,
                         b=np.zeros(3), dtype=dtype, tol=tol,
                         enqueue_t=t, deadline_t=deadline_t,
                         future=Future())


class TestMicroBatchQueue:
    def test_full_bucket_dispatches_immediately(self):
        q = MicroBatchQueue(max_batch=4, max_wait_s=1.0)
        for i in range(4):
            q.push(_req(i, t=0.0))
        batches, timeouts = q.pop_ready(now=0.0)
        assert not timeouts
        assert len(batches) == 1 and batches[0].reason == "full"
        assert batches[0].bucket == 4 and q.depth() == 0

    def test_partial_waits_for_max_wait_then_buckets_up(self):
        q = MicroBatchQueue(max_batch=4, max_wait_s=0.010)
        for i in range(3):
            q.push(_req(i, t=0.0))
        assert q.pop_ready(now=0.005) == ([], [])   # young: hold
        batches, _ = q.pop_ready(now=0.010)
        assert len(batches) == 1
        b = batches[0]
        assert b.reason == "max_wait" and b.bucket == 4
        assert len(b.requests) == 3
        assert b.occupancy == 0.75 and b.padding_fraction == 0.25

    def test_full_cut_leaves_remainder_on_its_own_clock(self):
        """5 pending at max_batch=4: the full cut goes now, the
        leftover waits for ITS max_wait (dispatch ordering)."""
        q = MicroBatchQueue(max_batch=4, max_wait_s=0.010)
        for i in range(4):
            q.push(_req(i, t=0.0))
        q.push(_req(4, t=0.008))
        batches, _ = q.pop_ready(now=0.008)
        assert [b.reason for b in batches] == ["full"]
        assert q.depth() == 1
        assert q.pop_ready(now=0.012) == ([], [])   # 4 ms old: hold
        batches, _ = q.pop_ready(now=0.019)
        assert [b.reason for b in batches] == ["max_wait"]
        assert batches[0].bucket == 1

    def test_keys_partition_by_handle_dtype_and_tol_class(self):
        q = MicroBatchQueue(max_batch=4, max_wait_s=0.0)
        q.push(_req(0, 0.0, tol=1e-7))
        q.push(_req(1, 0.0, tol=1e-3))
        q.push(_req(2, 0.0, tol=1.5e-7))
        q.push(_req(3, 0.0, tol=1e-7, handle="other"))
        batches, _ = q.pop_ready(now=0.0)
        got = sorted((b.key[0], tuple(r.request_id for r in b.requests))
                     for b in batches)
        assert got == [("h", ("r0", "r2")), ("h", ("r1",)),
                       ("other", ("r3",))]

    def test_expired_deadlines_leave_first_and_never_dispatch(self):
        q = MicroBatchQueue(max_batch=2, max_wait_s=10.0)
        q.push(_req(0, 0.0, deadline_t=0.005))
        q.push(_req(1, 0.0))
        batches, timeouts = q.pop_ready(now=0.006)
        assert [r.request_id for r in timeouts] == ["r0"]
        assert not batches and q.depth() == 1

    def test_next_wake_is_min_of_max_wait_and_deadline(self):
        q = MicroBatchQueue(max_batch=4, max_wait_s=0.010)
        assert q.next_wake(0.0) is None
        q.push(_req(0, 0.0))
        assert q.next_wake(0.0) == pytest.approx(0.010)
        q.push(_req(1, 0.001, deadline_t=0.004))
        assert q.next_wake(0.002) == pytest.approx(0.004)

    def test_drain_flushes_regardless_of_age(self):
        q = MicroBatchQueue(max_batch=4, max_wait_s=10.0)
        q.push(_req(0, 0.0))
        batches, _ = q.pop_ready(now=0.0, drain=True)
        assert [b.reason for b in batches] == ["drain"]

    def test_queue_limit_backpressure(self):
        q = MicroBatchQueue(max_batch=4, max_wait_s=1.0, queue_limit=2)
        q.push(_req(0, 0.0))
        q.push(_req(1, 0.0))
        with pytest.raises(QueueFull):
            q.push(_req(2, 0.0))


# ---------------------------------------------------------------------------
# service semantics (manual mode, fake clock)


class TestServicePolicy:
    def test_max_wait_vs_max_batch_ordering(self):
        svc, clock = manual_service()
        a = poisson_csr()
        h = svc.register(a)
        rng = np.random.default_rng(0)
        bs = [np.asarray(a @ rng.standard_normal(a.shape[0]))
              for _ in range(5)]
        futs = [svc.submit(h, b, tol=1e-8) for b in bs[:3]]
        assert svc.pump() == 0           # 3 < max_batch, 0 ms old
        futs.append(svc.submit(h, bs[3], tol=1e-8))
        assert svc.pump() == 1           # 4th filled the bucket: now
        assert all(f.result().status == "CONVERGED" for f in futs)
        assert futs[0].result().bucket == 4
        f5 = svc.submit(h, bs[4], tol=1e-8)
        assert svc.pump() == 0           # partial again: held
        clock.advance(0.010)
        assert svc.pump() == 1           # max_wait elapsed
        assert f5.result().bucket == 1
        svc.close()

    def test_deadline_timeout_is_a_typed_result_not_an_exception(self):
        svc, clock = manual_service()
        a = poisson_csr()
        h = svc.register(a)
        fut = svc.submit(h, np.ones(a.shape[0]), tol=1e-8,
                         deadline_s=0.004)
        clock.advance(0.005)
        assert svc.pump() == 0
        res = fut.result(timeout=1)      # resolves, no exception
        assert res.timed_out and res.status == "TIMEOUT"
        assert res.x is None and not res.converged
        assert svc.stats()["timeouts"] == 1
        svc.close()

    def test_per_lane_failure_isolation(self):
        """One batch, one hopeless lane: diag(1..32) gives b=e_1 a
        1-iteration solve while b=ones needs 32 Krylov dimensions -
        at maxiter=5 the second lane fails ALONE with a typed
        MAXITER result."""
        svc, clock = manual_service(max_batch=2, maxiter=5)
        n = 32
        a = CSRMatrix.from_dense(np.diag(np.arange(1.0, n + 1)))
        h = svc.register(a, maxiter=5)
        e1 = np.zeros(n)
        e1[1] = 1.0
        f_easy = svc.submit(h, e1, tol=1e-10)
        f_hard = svc.submit(h, np.ones(n), tol=1e-10)
        assert svc.pump() == 1
        easy, hard = f_easy.result(), f_hard.result()
        assert easy.status == "CONVERGED" and easy.converged
        np.testing.assert_allclose(easy.x, e1 / 2.0, atol=1e-12)
        assert hard.status == "MAXITER" and not hard.converged
        assert hard.iterations == 5
        assert easy.solve_id == hard.solve_id   # same batch
        svc.close()

    def test_engine_error_is_a_typed_result_and_worker_survives(self):
        """An engine exception resolves every lane to a typed
        status='ERROR' result (a raised future would blow up any
        fut.result() loop), and the service keeps serving."""
        svc, clock = manual_service(max_batch=2)
        a = poisson_csr()
        h = svc.register(a)
        orig_engine = svc._engine
        svc._engine = lambda *args, **kw: (_ for _ in ()).throw(
            RuntimeError("boom"))
        f1 = svc.submit(h, np.ones(a.shape[0]), tol=1e-8)
        f2 = svc.submit(h, np.ones(a.shape[0]), tol=1e-8)
        assert svc.pump() == 1
        for f in (f1, f2):
            res = f.result(timeout=1)          # resolves, never raises
            assert res.status == "ERROR"
            assert not res.converged and not res.timed_out
            assert res.x is None
        assert svc.stats()["errors"] == 2
        svc._engine = orig_engine              # service lives on
        f3 = svc.submit(h, np.ones(a.shape[0]), tol=1e-8)
        svc.drain()
        assert f3.result().status == "CONVERGED"
        svc.close()

    def test_drain_and_close_semantics(self):
        svc, clock = manual_service()
        a = poisson_csr()
        h = svc.register(a)
        rng = np.random.default_rng(1)
        futs = [svc.submit(h, np.asarray(a @ rng.standard_normal(
            a.shape[0])), tol=1e-8) for _ in range(2)]
        assert svc.pump() == 0           # young partial batch: held
        svc.drain()                      # flushes regardless of age
        assert all(f.result().converged for f in futs)
        assert svc.queue_depth() == 0
        svc.close()
        svc.close()                      # idempotent
        with pytest.raises(ServiceClosed):
            svc.submit(h, np.ones(a.shape[0]))

    def test_backpressure_bounded_queue(self):
        svc, clock = manual_service(queue_limit=2, max_batch=8)
        a = poisson_csr()
        h = svc.register(a)
        svc.submit(h, np.ones(a.shape[0]))
        svc.submit(h, np.ones(a.shape[0]))
        with pytest.raises(QueueFull):
            svc.submit(h, np.ones(a.shape[0]))
        svc.drain()
        svc.close()

    def test_register_is_idempotent_and_validates(self):
        svc, _ = manual_service()
        a = poisson_csr()
        h1 = svc.register(a)
        h2 = svc.register(a)
        assert h1 is h2
        with pytest.raises(ValueError):
            svc.register(a, precond="chebyshev")
        with pytest.raises(ValueError):
            svc.register(a, method="nope")
        with pytest.raises(ValueError):
            svc.register(a, exchange="gather")   # needs a mesh
        with pytest.raises(ValueError):
            svc.submit(h1, np.ones(3))           # wrong length
        svc.close()

    def test_reregister_warms_a_deferred_handle(self):
        """register(warm=False) then register() must pay the warmup on
        the second call - the dedup early-return must not leave live
        traffic compiling inside request latency."""
        svc, _ = manual_service()
        a = poisson_csr()
        warms = []
        orig = svc._warm
        svc._warm = lambda h: (warms.append(h.key), orig(h))[1]
        h1 = svc.register(a, warm=False)
        assert warms == [] and not h1.warmed
        h2 = svc.register(a)
        assert h2 is h1 and warms == [h1.key] and h1.warmed
        svc.register(a)                  # already warmed: no re-warm
        assert warms == [h1.key]
        svc.close()

    def test_submit_unknown_handle_refuses(self):
        svc, _ = manual_service(warm=False)
        other, _ = manual_service(warm=False)
        a = poisson_csr()
        h = other.register(a, warm=False)
        with pytest.raises(ValueError):
            svc.submit(h, np.ones(a.shape[0]))
        svc.close()
        other.close()


# ---------------------------------------------------------------------------
# observability


class TestServiceObservability:
    def test_events_schema_valid_and_solve_id_linked(self):
        svc, clock = manual_service()
        a = poisson_csr()
        with events.capture() as buf:
            h = svc.register(a)
            rng = np.random.default_rng(2)
            futs = [svc.submit(h, np.asarray(
                a @ rng.standard_normal(a.shape[0])), tol=1e-8)
                for _ in range(4)]
            svc.pump()
        [f.result() for f in futs]
        recs = [json.loads(ln) for ln in
                buf.getvalue().splitlines() if ln.strip()]
        for rec in recs:
            events.validate_event(rec)
        enq = [r for r in recs if r["event"] == "request_enqueued"]
        disp = [r for r in recs if r["event"] == "batch_dispatch"
                and r.get("phase") != "warmup"]
        done = [r for r in recs if r["event"] == "request_done"]
        assert len(enq) == 4 and len(done) == 4
        assert len(disp) == 1
        assert disp[0]["n_requests"] == 4 and disp[0]["bucket"] == 4
        # linkage: the dispatch, its engine selection and every
        # request_done share ONE solve_id
        sid = disp[0]["solve_id"]
        assert sid is not None
        engines = [r for r in recs if r["event"] == "engine_selected"
                   and r["solve_id"] == sid]
        assert engines and engines[0]["engine"] == "many"
        assert all(r["solve_id"] == sid for r in done)
        assert {r["status"] for r in done} == {"CONVERGED"}
        svc.close()

    def test_metrics_gauges_and_latency_percentiles(self):
        from cuda_mpi_parallel_tpu.telemetry.registry import REGISTRY

        svc, clock = manual_service()
        a = poisson_csr()
        h = svc.register(a)
        rng = np.random.default_rng(4)
        futs = [svc.submit(h, np.asarray(
            a @ rng.standard_normal(a.shape[0])), tol=1e-8)
            for _ in range(3)]
        clock.advance(0.020)
        svc.pump()
        [f.result() for f in futs]
        occ = REGISTRY.gauge("serve_batch_occupancy",
                             labelnames=("handle",))
        assert occ.value(handle=h.key) == 0.75
        pad = REGISTRY.gauge("serve_batch_padding_fraction",
                             labelnames=("handle",))
        assert pad.value(handle=h.key) == 0.25
        from cuda_mpi_parallel_tpu.serve.service import LATENCY_BUCKETS

        hist = REGISTRY.histogram(
            "serve_request_latency_seconds", labelnames=("handle",),
            buckets=LATENCY_BUCKETS)
        assert hist.value(handle=h.key)["count"] >= 3
        assert hist.quantile(0.95, handle=h.key) is not None
        stats = svc.stats()
        assert stats["latency"]["p50_s"] is not None
        assert stats["latency"]["p95_s"] >= stats["latency"]["p50_s"]
        assert stats["occupancy_mean"] == 0.75
        assert stats["bucket_counts"] == {"4": 1}
        svc.close()

    def test_wait_vs_solve_latency_split(self):
        """stats() reports queue wait and batched solve wall as
        SEPARATE percentile families (ISSUE 11 satellite), and the
        report renderer prints both lines."""
        from cuda_mpi_parallel_tpu.telemetry.report import (
            service_lines,
        )

        svc, clock = manual_service()
        a = poisson_csr()
        h = svc.register(a)
        rng = np.random.default_rng(5)
        futs = [svc.submit(h, np.asarray(
            a @ rng.standard_normal(a.shape[0])), tol=1e-8)
            for _ in range(3)]
        clock.advance(0.020)   # requests wait 20 ms on the fake clock
        svc.pump()
        results = [f.result() for f in futs]
        stats = svc.stats()
        for key in ("wait", "solve"):
            sub = stats[key]
            assert sub["count"] == 3
            for q in ("p50_s", "p95_s", "p99_s"):
                assert sub[q] is not None
        # the fake clock pins wait at exactly 20 ms for every request;
        # solve wall is real time (perf_counter) and must be recorded
        assert stats["wait"]["p50_s"] == pytest.approx(0.020)
        assert stats["solve"]["p50_s"] > 0.0
        # latency = wait + solve per request, so the split is a true
        # decomposition of the end-to-end story
        r = results[0]
        assert r.latency_s == pytest.approx(r.wait_s + r.solve_s)
        lines = "\n".join(service_lines(stats))
        assert "wait" in lines and "solve" in lines
        svc.close()

    def test_timeout_wait_lands_in_wait_distribution(self):
        svc, clock = manual_service()
        a = poisson_csr()
        h = svc.register(a)
        fut = svc.submit(h, np.ones(a.shape[0]), tol=1e-8,
                         deadline_s=0.001)
        clock.advance(0.005)   # expire it before any dispatch
        svc.pump()
        assert fut.result().timed_out
        stats = svc.stats()
        assert stats["wait"]["count"] == 1
        assert stats["wait"]["p50_s"] == pytest.approx(0.005)
        assert stats["solve"]["count"] == 0
        svc.close()


# ---------------------------------------------------------------------------
# workload files


class TestWorkload:
    def test_synthetic_poisson_shape_and_determinism(self):
        w1 = synthetic_poisson(16, 1000.0, seed=5)
        w2 = synthetic_poisson(16, 1000.0, seed=5)
        assert w1 == w2
        assert w1[0].t == 0.0
        assert all(b.t >= a.t for a, b in zip(w1, w1[1:]))
        assert len({r.seed for r in w1}) == 16

    def test_roundtrip_and_validation(self, tmp_path):
        path = str(tmp_path / "wl.json")
        reqs = [WorkloadRequest(t=0.0, seed=1),
                WorkloadRequest(t=0.5, seed=2, tol=1e-5,
                                deadline_s=0.25)]
        save_workload(path, reqs)
        assert load_workload(path) == reqs
        bad = str(tmp_path / "bad.json")
        with open(bad, "w") as f:
            json.dump({"version": 2}, f)
        with pytest.raises(ValueError):
            load_workload(bad)

    def test_rhs_for_known_solution(self):
        a = poisson_csr(8)
        b, x_true = rhs_for(a, seed=7)
        np.testing.assert_allclose(
            b, np.asarray(a.to_dense() @ x_true), atol=1e-12)


# ---------------------------------------------------------------------------
# end-to-end: the service is a pure batcher


class TestEndToEnd:
    def test_replay_bit_matches_direct_solve_many(self):
        """3 requests pad to a k=4 bucket; the dispatched answer must
        BIT-match a direct solve_many call on the same padded stack
        (the service adds queueing, never arithmetic)."""
        from cuda_mpi_parallel_tpu.solver import solve_many

        svc, clock = manual_service()
        a = poisson_csr(10)
        h = svc.register(a)
        rng = np.random.default_rng(6)
        cols = [np.asarray(a @ rng.standard_normal(a.shape[0]))
                for _ in range(3)]
        tol = 1e-9
        futs = [svc.submit(h, c, tol=tol) for c in cols]
        clock.advance(0.010)
        assert svc.pump() == 1
        results = [f.result() for f in futs]
        b_direct = stack_columns(cols, 4, dtype=np.float64)
        tols = np.full((4,), tol)
        direct = solve_many(a, b_direct, tol=tols,
                            maxiter=svc.config.maxiter)
        dx = np.asarray(direct.x)
        for j, res in enumerate(results):
            assert np.array_equal(res.x, dx[:, j])
            assert res.iterations == int(direct.iterations[j])
        svc.close()

    @pytest.mark.skipif(len(jax.devices()) < 4,
                        reason="needs 4 (virtual) devices")
    def test_mesh4_replay_bit_matches_and_never_retraces(self):
        """Mesh-4 end-to-end: a replayed bursty workload's answers
        bit-match direct solve_distributed_many calls on the same
        buckets, and the second identical bucket triggers ZERO new
        traces (the dist_cg solver cache serves it)."""
        from cuda_mpi_parallel_tpu.models import mmio
        from cuda_mpi_parallel_tpu.parallel import (
            dist_cg,
            make_mesh,
            solve_distributed_many,
        )

        dist_cg.clear_solver_cache()
        a = mmio.load_matrix_market(
            "tests/fixtures/skewed_spd_240.mtx", dtype=np.float64)
        mesh = make_mesh(4)
        svc, clock = manual_service(max_batch=4, maxiter=500)
        h = svc.register(a, mesh=mesh)
        tol = 1e-8
        rng = np.random.default_rng(8)
        cols = [np.asarray(a @ rng.standard_normal(a.shape[0]))
                for _ in range(8)]
        # burst 1: full bucket; burst 2: same bucket shape again
        futs1 = [svc.submit(h, c, tol=tol) for c in cols[:4]]
        assert svc.pump() == 1
        traces_after_first = dist_cg._TRACE_COUNT[0]
        futs2 = [svc.submit(h, c, tol=tol) for c in cols[4:]]
        assert svc.pump() == 1
        assert dist_cg._TRACE_COUNT[0] == traces_after_first, \
            "second identical bucket re-traced the solver"
        results = [f.result() for f in futs1 + futs2]
        assert all(r.status == "CONVERGED" for r in results)
        for burst, offset in ((cols[:4], 0), (cols[4:], 4)):
            direct = solve_distributed_many(
                a, stack_columns(burst, 4, dtype=np.float64),
                mesh=mesh, tol=np.full((4,), tol), maxiter=500)
            dx = np.asarray(direct.x)
            for j in range(4):
                assert np.array_equal(results[offset + j].x, dx[:, j])
        svc.close()

    @pytest.mark.skipif(len(jax.devices()) < 4,
                        reason="needs 4 (virtual) devices")
    def test_mesh_register_partitions_once(self, monkeypatch):
        """The dispatch hot path never re-runs the O(nnz) host setup:
        register() partitions once (ManyRHSDispatcher); every later
        batch only pads/shards b."""
        from cuda_mpi_parallel_tpu.models import mmio
        from cuda_mpi_parallel_tpu.parallel import dist_cg, make_mesh

        calls = [0]
        orig = dist_cg.part.partition_csr

        def counting(*a, **kw):
            calls[0] += 1
            return orig(*a, **kw)

        monkeypatch.setattr(dist_cg.part, "partition_csr", counting)
        a = mmio.load_matrix_market(
            "tests/fixtures/skewed_spd_240.mtx", dtype=np.float64)
        svc, clock = manual_service(max_batch=2, maxiter=500)
        h = svc.register(a, mesh=make_mesh(4))
        assert calls[0] == 1
        rng = np.random.default_rng(12)
        futs = [svc.submit(h, np.asarray(
            a @ rng.standard_normal(a.shape[0])), tol=1e-8)
            for _ in range(4)]
        svc.drain()
        assert all(f.result().converged for f in futs)
        assert calls[0] == 1, "a dispatch re-partitioned the operator"
        svc.close()

    @pytest.mark.skipif(len(jax.devices()) < 4,
                        reason="needs 4 (virtual) devices")
    def test_mesh4_zero_cache_misses_after_warmup(self):
        """The zero-retrace acceptance at the metrics level: after
        register()'s per-bucket warmup, a whole replayed workload adds
        ZERO dist_cache_miss (phase='solve') counts."""
        from cuda_mpi_parallel_tpu.models import mmio
        from cuda_mpi_parallel_tpu.parallel import dist_cg, make_mesh
        from cuda_mpi_parallel_tpu.telemetry.registry import REGISTRY

        dist_cg.clear_solver_cache()
        a = mmio.load_matrix_market(
            "tests/fixtures/skewed_spd_240.mtx", dtype=np.float64)
        svc, clock = manual_service(max_batch=4, maxiter=500)
        h = svc.register(a, mesh=make_mesh(4))
        misses = REGISTRY.counter("dist_solver_cache_misses_total",
                                  labelnames=("phase",))
        before = misses.value(phase="solve")
        rng = np.random.default_rng(9)
        futs = []
        for i in range(10):
            futs.append(svc.submit(
                h, np.asarray(a @ rng.standard_normal(a.shape[0])),
                tol=1e-8))
            clock.advance(0.011)
            svc.pump()            # mixed bucket sizes: 1s and stragglers
        svc.drain()
        assert all(f.result().converged for f in futs)
        assert misses.value(phase="solve") == before, \
            "post-warmup service traffic missed the solver cache"
        svc.close()


class TestThreadedMode:
    def test_threaded_service_end_to_end(self):
        """The real-clock worker thread: submit a burst, futures
        resolve without any pump() calls."""
        svc = SolverService(ServiceConfig(
            max_batch=4, max_wait_s=0.005, maxiter=500))
        try:
            a = poisson_csr()
            h = svc.register(a)
            rng = np.random.default_rng(11)
            futs = [svc.submit(h, np.asarray(
                a @ rng.standard_normal(a.shape[0])), tol=1e-8)
                for _ in range(6)]
            results = [f.result(timeout=30) for f in futs]
            assert all(r.converged for r in results)
            # at least one batch coalesced >= 2 requests (exact
            # bucketing depends on thread scheduling - submits race
            # the worker's max_wait clock)
            assert max(r.bucket for r in results) >= 2
            assert svc.stats()["completed"] == 6
        finally:
            svc.close()
