"""Pallas kernel tests, run in interpret mode on CPU (SURVEY SS5: interpret
mode is the framework's sanitizer - it catches OOB indexing the way compute-
sanitizer would for the reference's CUDA kernels, if it had any).

On real TPU hardware the same kernels compile through Mosaic; bench.py
compares them against the XLA formulation there.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cuda_mpi_parallel_tpu.utils.compat import shard_map
from jax.sharding import PartitionSpec as P

from cuda_mpi_parallel_tpu import Stencil2D, Stencil3D, solve
from cuda_mpi_parallel_tpu.ops.pallas import stencil as pk
from cuda_mpi_parallel_tpu.parallel import (
    DistStencil3D,
    make_mesh,
    solve_distributed,
)


def ref_stencil2d(x, scale=1.0):
    u = np.pad(x, 1)
    return scale * (4.0 * x - u[:-2, 1:-1] - u[2:, 1:-1]
                    - u[1:-1, :-2] - u[1:-1, 2:])


def ref_stencil3d(x, scale=1.0):
    u = np.pad(x, 1)
    return scale * (6.0 * x - u[:-2, 1:-1, 1:-1] - u[2:, 1:-1, 1:-1]
                    - u[1:-1, :-2, 1:-1] - u[1:-1, 2:, 1:-1]
                    - u[1:-1, 1:-1, :-2] - u[1:-1, 1:-1, 2:])


class TestStencil2DKernel:
    @pytest.mark.parametrize("shape,bm", [((64, 128), 16), ((64, 128), 64),
                                          ((128, 256), 32)])
    def test_matches_reference(self, rng, shape, bm):
        x = rng.standard_normal(shape).astype(np.float32)
        y = pk.stencil2d_apply(jnp.asarray(x), 1.5, bm=bm, interpret=True)
        np.testing.assert_allclose(np.asarray(y), ref_stencil2d(x, 1.5),
                                   rtol=1e-5, atol=1e-5)

    def test_single_block_grid(self, rng):
        """first == last block: both boundary fills active."""
        x = rng.standard_normal((32, 128)).astype(np.float32)
        y = pk.stencil2d_apply(jnp.asarray(x), 1.0, bm=32, interpret=True)
        np.testing.assert_allclose(np.asarray(y), ref_stencil2d(x),
                                   rtol=1e-5, atol=1e-5)

    def test_indivisible_raises(self, rng):
        x = jnp.zeros((60, 128), dtype=jnp.float32)
        with pytest.raises(ValueError, match="not divisible"):
            pk.stencil2d_apply(x, 1.0, bm=32, interpret=True)


class TestStencil3DKernel:
    @pytest.mark.parametrize("shape,bm", [((16, 16, 128), 4),
                                          ((16, 16, 128), 16),
                                          ((32, 8, 256), 8)])
    def test_matches_reference(self, rng, shape, bm):
        x = rng.standard_normal(shape).astype(np.float32)
        y = pk.stencil3d_apply(jnp.asarray(x), 2.0, bm=bm, interpret=True)
        np.testing.assert_allclose(np.asarray(y), ref_stencil3d(x, 2.0),
                                   rtol=1e-5, atol=1e-5)


class TestOperatorBackend:
    def test_stencil2d_backends_agree(self, rng):
        a_x = Stencil2D.create(64, 128, scale=1.3, dtype=jnp.float32)
        a_p = Stencil2D.create(64, 128, scale=1.3, dtype=jnp.float32,
                               backend="pallas")
        x = jnp.asarray(rng.standard_normal(64 * 128).astype(np.float32))
        np.testing.assert_allclose(np.asarray(a_p @ x), np.asarray(a_x @ x),
                                   rtol=1e-5, atol=1e-5)

    def test_stencil3d_backends_agree(self, rng):
        a_x = Stencil3D.create(16, 16, 128, dtype=jnp.float32)
        a_p = Stencil3D.create(16, 16, 128, dtype=jnp.float32,
                               backend="pallas")
        x = jnp.asarray(rng.standard_normal(a_x.shape[0]).astype(np.float32))
        np.testing.assert_allclose(np.asarray(a_p @ x), np.asarray(a_x @ x),
                                   rtol=1e-5, atol=1e-5)

    def test_unsupported_shape_rejected(self):
        with pytest.raises(ValueError, match="pallas 2D stencil needs"):
            Stencil2D.create(64, 100, backend="pallas")
        with pytest.raises(ValueError, match="pallas 3D stencil needs"):
            Stencil3D.create(16, 16, 100, backend="pallas")

    def test_bogus_backend_rejected(self):
        with pytest.raises(ValueError, match="unknown backend"):
            Stencil2D.create(64, 128, backend="cuda")

    def test_scale_sweep_reuses_executable(self, rng):
        """scale is a traced leaf (and an SMEM operand in the pallas
        kernels): sweeping it must not recompile the jitted solve."""
        from cuda_mpi_parallel_tpu.solver.cg import _solve_jit

        b = jnp.asarray(rng.standard_normal(64 * 128).astype(np.float32))
        solve(Stencil2D.create(64, 128, scale=1.0, dtype=jnp.float32), b,
              tol=1e-3, maxiter=5)
        n0 = _solve_jit._cache_size()
        solve(Stencil2D.create(64, 128, scale=2.5, dtype=jnp.float32), b,
              tol=1e-3, maxiter=5)
        assert _solve_jit._cache_size() == n0

    def test_dist_backend_validated(self):
        with pytest.raises(ValueError, match="unknown backend"):
            DistStencil3D.create((32, 8, 128), 8, backend="Pallas")

    def test_auto_backend_resolution(self):
        """auto -> xla for VMEM-resident grids, pallas for HBM-bound ones
        with supported shapes, xla when shapes are unsupported."""
        assert Stencil2D.create(64, 128, backend="auto").backend == "xla"
        assert Stencil2D.create(4096, 4096,
                                backend="auto").backend == "pallas"
        assert Stencil2D.create(4096, 4100,
                                backend="auto").backend == "xla"
        assert Stencil3D.create(256, 256, 256,
                                backend="auto").backend == "pallas"

    def test_solve_with_pallas_backend(self, rng):
        """End-to-end: CG over the pallas matvec reproduces the XLA solve."""
        a_x = Stencil2D.create(32, 128, dtype=jnp.float32)
        a_p = Stencil2D.create(32, 128, dtype=jnp.float32, backend="pallas")
        x_true = rng.standard_normal(32 * 128).astype(np.float32)
        b = a_x @ jnp.asarray(x_true)
        r_x = solve(a_x, b, tol=1e-3, maxiter=400)
        r_p = solve(a_p, b, tol=1e-3, maxiter=400)
        assert bool(r_p.converged)
        assert int(r_p.iterations) == int(r_x.iterations)
        np.testing.assert_allclose(np.asarray(r_p.x), np.asarray(r_x.x),
                                   rtol=1e-4, atol=1e-4)


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")
class TestDistributedPallas:
    def test_dist_3d_pallas_matvec_equals_global(self, rng):
        """Sharded pallas matvec (interior kernel + halo correction) must
        equal the global XLA stencil."""
        nx, ny, nz = 32, 8, 128
        mesh = make_mesh(8)
        x = jnp.asarray(
            rng.standard_normal(nx * ny * nz).astype(np.float32))
        want = Stencil3D.create(nx, ny, nz, dtype=jnp.float32) @ x
        local = DistStencil3D.create((nx, ny, nz), 8, dtype=jnp.float32,
                                     backend="pallas")
        got = jax.jit(shard_map(
            lambda v: local @ v, mesh=mesh, in_specs=P("rows"),
            out_specs=P("rows")))(x)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)

    def test_dist_solve_pallas(self, rng):
        a = Stencil3D.create(32, 8, 128, dtype=jnp.float32,
                             backend="pallas")
        x_true = rng.standard_normal(a.shape[0]).astype(np.float32)
        b = Stencil3D.create(32, 8, 128, dtype=jnp.float32) @ jnp.asarray(
            x_true)
        res = solve_distributed(a, b, mesh=make_mesh(8), tol=1e-3,
                                maxiter=500)
        assert bool(res.converged)
