"""Krylov subspace recycling (solver.recycle, ISSUE 13).

Covers the harvest math (windowed Lanczos-Ritz extraction against a
known spectrum), the deflated-CG lane (single-device, batched and
distributed - answers match undeflated solves to tolerance, iterations
strictly fall across a replayed repeat-traffic sequence, the
per-iteration collective count is unchanged), the RecycleSpace cache
lifecycle (typed wrong-space refusal, LRU-eviction drop in the serve
tier), the stride-1 harvest refusal, and the deflate=None /
basis=None jaxpr bit-identity proofs.
"""
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cuda_mpi_parallel_tpu import telemetry
from cuda_mpi_parallel_tpu.models import mmio, poisson
from cuda_mpi_parallel_tpu.models.operators import CSRMatrix, Stencil2D
from cuda_mpi_parallel_tpu.solver import recycle as rec
from cuda_mpi_parallel_tpu.solver import solve, solve_many
from cuda_mpi_parallel_tpu.solver.cg import cg
from cuda_mpi_parallel_tpu.solver.many import cg_many
from cuda_mpi_parallel_tpu.telemetry import events, health
from cuda_mpi_parallel_tpu.telemetry.flight import (
    FlightConfig,
    FlightRecord,
    lanes_from_buffer,
)

needs_mesh = pytest.mark.skipif(
    jax.device_count() < 4, reason="needs >= 4 (virtual) devices")

FIXTURE = "tests/fixtures/skewed_spd_240.mtx"


def _fixture():
    return mmio.load_matrix_market(FIXTURE, dtype=jnp.float64)


def _solve_kwargs(maxiter=500):
    return dict(tol=1e-8, maxiter=maxiter,
                flight=FlightConfig.for_solve(maxiter, stride=1),
                basis=rec.BasisConfig.for_solve(maxiter))


class TestBasisConfig:
    def test_validation(self):
        with pytest.raises(ValueError, match="capacity"):
            rec.BasisConfig(capacity=1)
        with pytest.raises(ValueError, match="BASIS_CAPACITY_LIMIT"):
            rec.BasisConfig(capacity=rec.BASIS_CAPACITY_LIMIT + 1)
        with pytest.raises(ValueError, match="stride"):
            rec.BasisConfig(capacity=8, stride=0)
        with pytest.raises(ValueError, match="lane"):
            rec.BasisConfig(capacity=8, lane=-1)

    def test_for_solve_caps(self):
        cfg = rec.BasisConfig.for_solve(10)
        assert cfg.capacity == 11
        cfg = rec.BasisConfig.for_solve(10_000)
        assert cfg.capacity == rec.BASIS_CAPACITY_LIMIT

    def test_hashable_static(self):
        assert hash(rec.BasisConfig(capacity=8)) \
            == hash(rec.BasisConfig(capacity=8))


class TestHarvest:
    def test_known_spectrum_recovery(self, rng):
        """Harvested Ritz values of a diagonal operator converge to
        its smallest eigenvalues, and the kept pairs' residual
        quality is small."""
        diag = np.linspace(1.0, 50.0, 64)
        a = jnp.diag(jnp.asarray(diag))
        b = rng.standard_normal(64)
        res = solve(a, b, **_solve_kwargs(200))
        assert bool(res.converged)
        space, info = rec.harvest_space(a, res, k=4, note=False)
        assert space.k == 4
        np.testing.assert_allclose(np.asarray(info.ritz),
                                   diag[:4], rtol=1e-4)
        assert max(info.quality) < 1e-2
        # W spans the small-eigenvalue eigenvectors: A W ~ W diag(ritz)
        w = np.asarray(space.w)
        aw = np.asarray(space.aw)
        assert np.linalg.norm(aw - w * np.asarray(info.ritz)) < 1e-2

    def test_harvest_requires_basis_and_flight(self, rng):
        a = _fixture()
        b = rng.standard_normal(240)
        bare = solve(a, b, tol=1e-8, maxiter=500)
        with pytest.raises(rec.HarvestError, match="basis"):
            rec.harvest_space(a, bare, k=4)
        flight_only = solve(a, b, tol=1e-8, maxiter=500,
                            flight=FlightConfig.for_solve(500))
        with pytest.raises(rec.HarvestError, match="basis"):
            rec.harvest_space(a, flight_only, k=4)

    def test_stride_decimated_record_refuses(self, rng):
        """ISSUE 13 satellite: harvesting from a stride-decimated
        flight ring refuses LOUDLY - stride-1 requirement named in the
        error - instead of silently producing junk Ritz values."""
        a = _fixture()
        b = rng.standard_normal(240)
        res = solve(a, b, tol=1e-8, maxiter=500,
                    flight=FlightConfig(capacity=128, stride=4),
                    basis=rec.BasisConfig(capacity=64, stride=4))
        with pytest.raises(rec.HarvestError, match="stride-4"):
            rec.harvest_space(a, res, k=4)

    def test_lanczos_tridiagonal_stride_refusal_names_stride1(self):
        record = FlightRecord(
            iterations=np.arange(0, 20, 2),
            residual_sq=np.ones(10), alphas=np.ones(10),
            betas=np.ones(10), stride=2)
        with pytest.raises(ValueError, match="stride 1"):
            health.lanczos_tridiagonal(record)

    def test_lanczos_tridiagonal_matches_full_t(self, rng):
        """The windowed tridiagonal is the EXACT principal submatrix:
        on an unwrapped record its eigenvalues match ritz_values'."""
        a = _fixture()
        b = rng.standard_normal(240)
        res = solve(a, b, tol=1e-8, maxiter=500,
                    flight=FlightConfig.for_solve(500, stride=1))
        record = FlightRecord.from_buffer(res.flight)
        diag, off, its = health.lanczos_tridiagonal(record)
        t = np.diag(diag) + np.diag(off, 1) + np.diag(off, -1)
        lam = np.linalg.eigvalsh(t)
        ritz = health.ritz_values(record)
        np.testing.assert_allclose(np.sort(lam), np.sort(ritz),
                                   rtol=1e-10)
        assert its[0] == 0 and np.all(np.diff(its) == 1)

    def test_harvest_emits_event_and_gauges(self, rng):
        a = _fixture()
        b = rng.standard_normal(240)
        res = solve(a, b, **_solve_kwargs())
        with events.capture() as buf:
            telemetry.force_active(True)
            try:
                _, info = rec.harvest_space(a, res, k=6)
            finally:
                telemetry.force_active(False)
        lines = [json.loads(ln) for ln in buf.getvalue().splitlines()]
        harvests = [e for e in lines if e["event"] == "recycle_harvest"]
        assert len(harvests) == 1
        assert harvests[0]["k"] == info.k
        assert harvests[0]["window"] == info.window
        from cuda_mpi_parallel_tpu.telemetry.registry import REGISTRY

        assert REGISTRY.gauge("recycle_space_k").value() == info.k


class TestDeflatedSolve:
    def test_deflated_matches_undeflated_to_tolerance(self, rng):
        """ISSUE 13 satellite: a deflated solve's solution matches the
        undeflated one to tolerance on the committed skewed fixture -
        and takes strictly fewer iterations."""
        a = _fixture()
        b1 = rng.standard_normal(240)
        src = solve(a, b1, **_solve_kwargs())
        space, _ = rec.harvest_space(a, src, k=8, note=False)
        b2 = rng.standard_normal(240)
        plain = solve(a, b2, tol=1e-8, maxiter=500)
        defl = solve(a, b2, tol=1e-8, maxiter=500, deflate=space)
        assert bool(defl.converged)
        assert np.max(np.abs(np.asarray(defl.x) - np.asarray(plain.x))) \
            < 1e-6
        assert int(defl.iterations) < int(plain.iterations)

    def test_sequence_iterations_strictly_fall(self, rng):
        """ISSUE 13 acceptance: measured iters/solve strictly
        decreases across a replayed fresh-RHS workload (accumulated
        harvests), with per-solve health verdicts CONVERGED."""
        a = _fixture()
        rhs = [rng.standard_normal(240) for _ in range(5)]
        seq = rec.recycled_sequence(a, rhs[0], repeats=5, k=12,
                                    maxiter=500, tol=1e-8,
                                    rhs_for=lambda i: rhs[i])
        its = seq.iterations()
        assert its[-1] < its[0]
        # monotone non-increasing up to 1-iteration jitter
        assert all(b <= a_ + 1 for a_, b in zip(its, its[1:]))
        for e in seq.entries:
            assert bool(e.result.converged)
            record = FlightRecord.from_buffer(e.result.flight)
            verdict = health.assess_solve_health(
                record, converged=bool(e.result.converged))
            assert verdict.classification.name == "CONVERGED"
        summary = seq.summary()
        assert summary["final_solve_iterations"] \
            < summary["first_solve_iterations"]
        assert summary["harvest_overhead_pct"] >= 0.0

    def test_preconditioned_deflation(self, rng):
        from cuda_mpi_parallel_tpu.models.operators import (
            JacobiPreconditioner,
        )

        a = _fixture()
        m = JacobiPreconditioner.from_operator(a)
        b1 = rng.standard_normal(240)
        src = solve(a, b1, m=m, **_solve_kwargs())
        space, _ = rec.harvest_space(a, src, k=8, note=False)
        b2 = rng.standard_normal(240)
        plain = solve(a, b2, tol=1e-8, maxiter=500, m=m)
        defl = solve(a, b2, tol=1e-8, maxiter=500, m=m, deflate=space)
        assert bool(defl.converged)
        assert int(defl.iterations) <= int(plain.iterations)
        assert np.max(np.abs(np.asarray(defl.x) - np.asarray(plain.x))) \
            < 1e-6

    def test_batched_deflation_and_lane_health(self, rng):
        """Batched lanes deflate column-wise; per-lane health verdicts
        prove deflation never breaks convergence (ISSUE acceptance)."""
        a = poisson.poisson_2d_csr(24, 24, dtype=np.float64)
        n = 576
        x_true = rng.standard_normal((n, 4))
        b = np.asarray(a.matmat(jnp.asarray(x_true)))
        kw = dict(tol=1e-8, maxiter=800,
                  flight=FlightConfig.for_solve(800, stride=1),
                  basis=rec.BasisConfig.for_solve(800))
        src = solve_many(a, b, **kw)
        space, _ = rec.harvest_space(a, src, k=8, n_rhs=4, note=False)
        x2 = rng.standard_normal((n, 4))
        b2 = np.asarray(a.matmat(jnp.asarray(x2)))
        plain = solve_many(a, b2, tol=1e-8, maxiter=800)
        defl = solve_many(a, b2, tol=1e-8, maxiter=800, deflate=space,
                          flight=FlightConfig.for_solve(800, stride=1))
        assert np.asarray(defl.converged).all()
        assert np.max(np.abs(np.asarray(defl.x) - x2)) < 1e-6
        assert (np.asarray(defl.iterations)
                < np.asarray(plain.iterations)).all()
        lanes = lanes_from_buffer(defl.flight, 4)
        verdicts = health.assess_lanes(
            lanes, converged=defl.converged, statuses=defl.status,
            iterations=defl.iterations)
        assert all(v.classification.name == "CONVERGED"
                   for v in verdicts)

    def test_wrong_space_typed_refusal(self, rng):
        """ISSUE 13 satellite: a fingerprint/layout mismatch raises a
        typed RecycleMismatch - never a wrong-space deflation."""
        a = _fixture()
        src = solve(a, rng.standard_normal(240), **_solve_kwargs())
        space, _ = rec.harvest_space(a, src, k=4, note=False)
        other = poisson.poisson_2d_csr(16, 16, dtype=np.float64)
        with pytest.raises(rec.RecycleMismatch):
            solve(other, np.ones(256), deflate=space)
        with pytest.raises(rec.RecycleMismatch):
            solve_many(other, np.ones((256, 2)), deflate=space)
        # same-shape different matrix still refuses (fingerprint, not
        # just row count)
        a2 = CSRMatrix.from_dense(2.0 * np.asarray(a.to_dense()))
        with pytest.raises(rec.RecycleMismatch):
            solve(a2, np.ones(240), deflate=space)

    def test_refusal_matrix(self, rng):
        a = _fixture()
        b = rng.standard_normal(240)
        src = solve(a, b, **_solve_kwargs())
        space, _ = rec.harvest_space(a, src, k=4, note=False)
        with pytest.raises(ValueError, match="method='cg'"):
            cg(a, b, method="cg1", deflate=space)
        with pytest.raises(ValueError, match="compensated"):
            cg(a, b, deflate=space, compensated=True)
        with pytest.raises(ValueError, match="flight"):
            cg(a, b, basis=rec.BasisConfig(capacity=8))
        with pytest.raises(TypeError, match="RecycleSpace"):
            cg(a, b, deflate="nope")
        with pytest.raises(ValueError, match="engine"):
            solve(a, b, engine="streaming", deflate=space)
        with pytest.raises(ValueError, match="batched"):
            solve_many(a, np.ones((240, 2)), method="block",
                       deflate=space)


class TestZeroPerturbation:
    """deflate=None / basis=None leave the traced jaxpr BIT-identical
    (the recycling lanes compile to nothing when off)."""

    def test_cg_deflate_off_jaxpr_identical(self):
        a = Stencil2D.create(16, 16, dtype=jnp.float64)
        b = jnp.ones(256)
        base = str(jax.make_jaxpr(lambda v: cg(a, v, maxiter=25))(b))
        off = str(jax.make_jaxpr(
            lambda v: cg(a, v, maxiter=25, deflate=None,
                         basis=None))(b))
        assert off == base
        # and with a space, the jaxpr genuinely differs
        diag = jnp.diag(jnp.arange(1.0, 257.0))
        res = solve(diag, jnp.ones(256), **_solve_kwargs(300))
        space, _ = rec.harvest_space(diag, res, k=4, note=False)
        on = str(jax.make_jaxpr(
            lambda v: cg(a, v, maxiter=25, deflate=space))(b))
        assert on != base

    def test_cg_basis_off_jaxpr_identical(self):
        a = Stencil2D.create(16, 16, dtype=jnp.float64)
        b = jnp.ones(256)
        fl = FlightConfig(capacity=7, stride=1)
        base = str(jax.make_jaxpr(
            lambda v: cg(a, v, maxiter=25, flight=fl))(b))
        off = str(jax.make_jaxpr(
            lambda v: cg(a, v, maxiter=25, flight=fl, basis=None))(b))
        assert off == base
        cfg = rec.BasisConfig(capacity=5)
        on = str(jax.make_jaxpr(
            lambda v: cg(a, v, maxiter=25, flight=fl, basis=cfg))(b))
        assert on != base
        assert "5,256" in on.replace(" ", "")   # the (capacity, n) ring
        assert "5,256" not in base.replace(" ", "")

    def test_cg_many_deflate_off_jaxpr_identical(self):
        a = Stencil2D.create(16, 16, dtype=jnp.float64)
        b = jnp.ones((256, 3))
        base = str(jax.make_jaxpr(
            lambda v: cg_many(a, v, maxiter=25))(b))
        off = str(jax.make_jaxpr(
            lambda v: cg_many(a, v, maxiter=25, deflate=None,
                              basis=None))(b))
        assert off == base

    @needs_mesh
    def test_distributed_deflate_off_jaxpr_identical(self):
        from cuda_mpi_parallel_tpu.parallel import (
            dist_cg,
            make_mesh,
            solve_distributed,
        )

        a = poisson.poisson_2d_csr(8, 8)
        b = np.ones(64)
        mesh = make_mesh(4)

        def traced_jaxpr(**kw):
            dist_cg.clear_solver_cache()
            captured = {}
            orig = dist_cg._cached_solver

            def wrapper(key, build, cost_ctx=None, cost_args=None):
                captured["jaxpr"] = jax.make_jaxpr(build())(*cost_args)
                return orig(key, build, cost_ctx, cost_args)

            dist_cg._cached_solver = wrapper
            try:
                solve_distributed(a, b, mesh=mesh, tol=1e-8,
                                  maxiter=200, **kw)
            finally:
                dist_cg._cached_solver = orig
                dist_cg.clear_solver_cache()
            return str(captured["jaxpr"])

        assert traced_jaxpr() \
            == traced_jaxpr(deflate=None, basis=None)


@needs_mesh
class TestDistributedRecycle:
    def setup_method(self):
        from cuda_mpi_parallel_tpu.parallel import dist_cg

        telemetry.configure(None)
        telemetry.force_active(False)
        dist_cg.clear_solver_cache()

    teardown_method = setup_method

    def _mesh(self):
        from cuda_mpi_parallel_tpu.parallel import make_mesh

        return make_mesh(4)

    def test_distributed_deflated_matches_and_saves_iters(self, rng):
        from cuda_mpi_parallel_tpu.parallel import solve_distributed

        a = _fixture()
        mesh = self._mesh()
        b1 = rng.standard_normal(240)
        src = solve_distributed(a, b1, mesh=mesh, **_solve_kwargs())
        space, _ = rec.harvest_space(a, src, k=8, note=False)
        b2 = rng.standard_normal(240)
        plain = solve_distributed(a, b2, mesh=mesh, tol=1e-8,
                                  maxiter=500)
        defl = solve_distributed(a, b2, mesh=mesh, tol=1e-8,
                                 maxiter=500, deflate=space)
        assert bool(defl.converged)
        assert int(defl.iterations) < int(plain.iterations)
        assert np.max(np.abs(np.asarray(defl.x) - np.asarray(plain.x))) \
            < 1e-6

    def test_collective_count_unchanged(self, rng):
        """ISSUE 13 acceptance: the deflated distributed solve issues
        the SAME per-iteration collective inventory as the undeflated
        one - the (k,)-wide projection reduction fused into the
        residual psum (jaxpr-derived comm_cost proof, machine-checked
        by the named budget API instead of a hand-rolled psum count)."""
        from cuda_mpi_parallel_tpu.analysis.spmd import (
            verify_collective_budget,
        )
        from cuda_mpi_parallel_tpu.parallel import solve_distributed

        a = _fixture()
        mesh = self._mesh()
        b = rng.standard_normal(240)
        src = solve_distributed(a, b, mesh=mesh, **_solve_kwargs())
        space, _ = rec.harvest_space(a, src, k=8, note=False)

        with events.capture():
            report = verify_collective_budget(
                lambda: solve_distributed(a, b, mesh=mesh, tol=1e-8,
                                          maxiter=500, deflate=space),
                lambda: solve_distributed(a, b, mesh=mesh, tol=1e-8,
                                          maxiter=500),
                what="deflated lane vs baseline")
        assert report.ok
        # psum, ppermute AND all_gather all held, not just psum
        assert report.deltas() == {"psum": 0, "ppermute": 0,
                                   "all_gather": 0}

    def test_plan_and_gather_compose(self, rng):
        from cuda_mpi_parallel_tpu.parallel import solve_distributed

        a = _fixture()
        mesh = self._mesh()
        b1 = rng.standard_normal(240)
        src = solve_distributed(a, b1, mesh=mesh, plan="auto",
                                exchange="gather", **_solve_kwargs())
        space, _ = rec.harvest_space(a, src, k=8, note=False)
        b2 = rng.standard_normal(240)
        plain = solve_distributed(a, b2, mesh=mesh, tol=1e-8,
                                  maxiter=500)
        defl = solve_distributed(a, b2, mesh=mesh, tol=1e-8,
                                 maxiter=500, deflate=space,
                                 plan="auto", exchange="gather")
        assert bool(defl.converged)
        assert np.max(np.abs(np.asarray(defl.x) - np.asarray(plain.x))) \
            < 1e-6

    def test_distributed_refusals(self, rng):
        from cuda_mpi_parallel_tpu.parallel import solve_distributed
        from cuda_mpi_parallel_tpu.robust import FaultPlan

        a = _fixture()
        mesh = self._mesh()
        b = rng.standard_normal(240)
        src = solve(a, b, **_solve_kwargs())
        space, _ = rec.harvest_space(a, src, k=4, note=False)
        with pytest.raises(ValueError, match="allgather/gather"):
            solve_distributed(a, b, mesh=mesh, deflate=space,
                              csr_comm="ring")
        with pytest.raises(ValueError, match="method='cg'"):
            solve_distributed(a, b, mesh=mesh, deflate=space,
                              method="cg1")
        with pytest.raises(ValueError, match="fault"):
            solve_distributed(a, b, mesh=mesh, deflate=space,
                              inject=FaultPlan(site="spmv",
                                               iteration=10))
        with pytest.raises(ValueError, match="checkpoint"):
            solve_distributed(a, b, mesh=mesh, deflate=space,
                              return_checkpoint=True)
        with pytest.raises(ValueError, match="flight"):
            solve_distributed(a, b, mesh=mesh,
                              basis=rec.BasisConfig(capacity=8))

    def test_dispatcher_mismatch_refusal(self, rng):
        from cuda_mpi_parallel_tpu.parallel.dist_cg import (
            ManyRHSDispatcher,
        )

        a = _fixture()
        src = solve(a, rng.standard_normal(240), **_solve_kwargs())
        space, _ = rec.harvest_space(a, src, k=4, note=False)
        other = poisson.poisson_2d_csr(16, 16, dtype=np.float64)
        disp = ManyRHSDispatcher(other, mesh=self._mesh(), maxiter=200)
        with pytest.raises(rec.RecycleMismatch):
            disp.solve(np.ones((256, 2)), deflate=space)


@needs_mesh
class TestServeRecycle:
    def setup_method(self):
        from cuda_mpi_parallel_tpu.parallel import dist_cg

        telemetry.configure(None)
        telemetry.force_active(False)
        dist_cg.clear_solver_cache()

    teardown_method = setup_method

    def _service(self, **cfg):
        from cuda_mpi_parallel_tpu.serve import (
            ServiceConfig,
            SolverService,
        )
        from cuda_mpi_parallel_tpu.serve.service import RecyclePolicy

        clock = [0.0]
        svc = SolverService(ServiceConfig(
            max_batch=4, max_wait_s=0.01, maxiter=500,
            clock=lambda: clock[0],
            recycle=RecyclePolicy(k=12, **cfg)))
        return svc, clock

    def _drive(self, svc, clock, handle, a, dispatches, seed0=0):
        from cuda_mpi_parallel_tpu.serve import workload as wl

        means = []
        for i in range(dispatches):
            futs = []
            for j in range(4):
                b, x_true = wl.rhs_for(a, seed=seed0 + i * 10 + j,
                                       dtype=np.float64)
                futs.append((svc.submit(handle, b, tol=1e-8), x_true))
            clock[0] += 1.0
            svc.pump()
            for fut, x_true in futs:
                r = fut.result()
                assert r.status == "CONVERGED", r.status
                assert np.max(np.abs(r.x - x_true)) < 1e-6
            means.append(np.mean([f.result().iterations
                                  for f, _ in futs]))
        return means

    def test_service_gets_faster_every_solve(self):
        from cuda_mpi_parallel_tpu.parallel import make_mesh

        a = _fixture()
        svc, clock = self._service()
        try:
            h = svc.register(a, mesh=make_mesh(4), exchange="gather")
            means = self._drive(svc, clock, h, a, 5)
        finally:
            svc.close()
        assert means[-1] < means[0]
        stats = svc.stats()["recycle"]
        assert stats["harvests"] >= 1
        assert stats["applied"] >= 1
        assert stats["last_solve_iterations"] \
            < stats["first_solve_iterations"]
        assert h.recycle_space is not None
        assert h.recycle_space.k == 12

    def test_quality_schedule_freezes(self):
        """Once harvests stop improving the mean iteration count, the
        recorders drop off (frozen) and dispatches keep deflating."""
        from cuda_mpi_parallel_tpu.parallel import make_mesh

        a = _fixture()
        svc, clock = self._service(patience=1, min_improvement=100.0)
        try:
            h = svc.register(a, mesh=make_mesh(4))
            # harvest on dispatch 1; dispatch 2's harvest cannot clear
            # the absurd min_improvement -> frozen
            self._drive(svc, clock, h, a, 3)
            assert h.recycle_frozen
            assert h.recycle_space is not None
            frozen_harvests = h.recycle_harvests
            self._drive(svc, clock, h, a, 2, seed0=500)
            assert h.recycle_harvests == frozen_harvests
        finally:
            svc.close()

    def test_lru_eviction_drops_space(self, monkeypatch):
        """ISSUE 13 satellite: evicting the handle's compiled solvers
        from the dist_cg LRU drops its RecycleSpace too."""
        from cuda_mpi_parallel_tpu.parallel import dist_cg, make_mesh

        monkeypatch.setenv(dist_cg.DIST_CACHE_CAP_ENV, "2")
        a = _fixture()
        svc, clock = self._service()
        try:
            mesh = make_mesh(4)
            h = svc.register(a, mesh=mesh, warm=False)
            self._drive(svc, clock, h, a, 2)
            assert h.recycle_space is not None
            # churn the tiny cache with other operators' solves until
            # the handle's entries are gone
            from cuda_mpi_parallel_tpu.parallel import solve_distributed

            for grid in (8, 10, 12):
                p = poisson.poisson_2d_csr(grid, grid,
                                           dtype=np.float64)
                solve_distributed(p, np.ones(grid * grid), mesh=mesh,
                                  tol=1e-6, maxiter=50)
            assert h.recycle_space is None
            assert svc.stats()["recycle"]["dropped"] >= 1
        finally:
            svc.close()

    def test_register_refusals(self):
        from cuda_mpi_parallel_tpu.parallel import make_mesh
        from cuda_mpi_parallel_tpu.robust import FaultPlan

        a = _fixture()
        svc, _ = self._service()
        try:
            with pytest.raises(ValueError, match="batched"):
                svc.register(a, mesh=make_mesh(4), method="block")
            with pytest.raises(ValueError, match="inject"):
                svc.register(a, mesh=make_mesh(4),
                             inject=FaultPlan(site="spmv",
                                              iteration=10))
        finally:
            svc.close()


@needs_mesh
class TestRecycleCLI:
    def test_cli_recycle_record(self, capsys):
        from cuda_mpi_parallel_tpu.cli import main

        rc = main(["--problem", "mm", "--file", FIXTURE,
                   "--mesh", "4", "--device", "cpu",
                   "--tol", "1e-8", "--maxiter", "500",
                   "--repeat", "3", "--recycle", "12", "--json"])
        assert rc == 0
        record = json.loads(capsys.readouterr().out.strip()
                            .splitlines()[-1])
        r = record["recycle"]
        assert r["repeats"] == 3
        assert r["final_solve_iterations"] \
            < r["first_solve_iterations"]
        assert r["k"] == 12
        assert record["status"] == "CONVERGED"

    @pytest.mark.parametrize("argv,msg", [
        (["--recycle"], "--repeat"),
        (["--repeat", "2", "--recycle", "--replan"], "--replan"),
        (["--repeat", "2", "--recycle", "--method", "cg1"],
         "--method cg"),
        (["--repeat", "2", "--recycle", "--csr-comm", "ring"],
         "allgather/gather"),
        (["--repeat", "2", "--recycle", "--flight-record", "4"],
         "stride-1"),
    ])
    def test_cli_recycle_refusals(self, argv, msg):
        from cuda_mpi_parallel_tpu.cli import main

        with pytest.raises(SystemExit, match=msg):
            main(["--problem", "mm", "--file", FIXTURE,
                  "--mesh", "4", "--device", "cpu"] + argv)
