"""calibra: runtime-measured machine model, drift tracking, replanning.

The calibrator's claims are quantitative, so the tests are numeric:
the least-squares fit must RECOVER hand-chosen bandwidths from
synthetic timings, the disk cache must honor staleness, drift must be
the exact predicted-vs-measured ratio, the mesh-4 sequence on the
committed skewed fixture must run solve 2 on a plan scored by the
solve-1-calibrated model with the ``replan`` event fired, and with
calibration off the traced solve must be jaxpr-bit-identical to
pre-calibra behavior (ISSUE 6 acceptance).
"""
import json
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from cuda_mpi_parallel_tpu import solve, telemetry
from cuda_mpi_parallel_tpu.balance import (
    plan_partition,
    reference_model,
    score_report,
)
from cuda_mpi_parallel_tpu.models import mmio
from cuda_mpi_parallel_tpu.telemetry import calibrate as cal
from cuda_mpi_parallel_tpu.telemetry import events
from cuda_mpi_parallel_tpu.telemetry import roofline as roof
from cuda_mpi_parallel_tpu.telemetry import shardscope as ss
from cuda_mpi_parallel_tpu.telemetry.registry import REGISTRY
from cuda_mpi_parallel_tpu.utils import compat
from cuda_mpi_parallel_tpu.utils.tune import JsonCache, host_fingerprint

needs_mesh = pytest.mark.skipif(
    not compat.has_shard_map() or len(jax.devices()) < 4,
    reason="needs shard_map and >= 4 (virtual) devices")

FIXTURE = "tests/fixtures/skewed_spd_240.mtx"

BASE = roof.MachineModel(
    name="unit-base", mem_bytes_per_s=8.0e11, flops_per_s=2.0e13,
    net_bytes_per_s=4.5e10, source="table", gather_slowdown=8.0)


def synthetic_obs(gather_bw, net_bw, gather_bytes, net_bytes,
                  iterations=100, label=""):
    """An observation whose per-iteration time is EXACTLY the model at
    the given bandwidths - what a noiseless measurement would see."""
    t_iter = gather_bytes / gather_bw + net_bytes / net_bw
    return cal.PhaseObservation(
        iterations=iterations, elapsed_s=t_iter * iterations,
        gather_bytes_per_iteration=gather_bytes,
        net_bytes_per_iteration=net_bytes, label=label)


class TestFit:
    def test_two_observations_recover_known_bandwidths(self):
        gather_bw, net_bw = 2.0e10, 5.0e9
        obs = [synthetic_obs(gather_bw, net_bw, 1e6, 1e5),
               synthetic_obs(gather_bw, net_bw, 4e6, 2e5)]
        fit = cal.fit_machine_model(obs, base=BASE, backend="unit")
        assert fit.method == "lstsq2"
        assert fit.model.net_bytes_per_s == pytest.approx(net_bw,
                                                          rel=1e-6)
        # gather_slowdown = stream_bw / fitted gather_bw
        assert fit.model.gather_slowdown == pytest.approx(
            BASE.mem_bytes_per_s / gather_bw, rel=1e-6)
        assert fit.residual_rel == pytest.approx(0.0, abs=1e-9)
        assert fit.confident
        assert fit.model.source == "calibrated"
        assert fit.model.created_at is not None
        assert fit.backend == "unit"

    def test_single_observation_pins_net_at_base(self):
        gather_bw = 1.0e10
        obs = [synthetic_obs(gather_bw, BASE.net_bytes_per_s, 2e6, 3e5)]
        fit = cal.fit_machine_model(obs, base=BASE, backend="unit")
        assert fit.method == "fixed-net"
        assert fit.model.net_bytes_per_s == pytest.approx(
            BASE.net_bytes_per_s)
        assert fit.model.gather_slowdown == pytest.approx(
            BASE.mem_bytes_per_s / gather_bw, rel=1e-6)
        assert fit.confident  # 100 iterations, exact fit

    def test_too_few_iterations_not_confident(self):
        obs = [synthetic_obs(1e10, BASE.net_bytes_per_s, 2e6, 3e5,
                             iterations=3)]
        fit = cal.fit_machine_model(obs, base=BASE, backend="unit")
        assert fit.total_iterations == 3 \
            < cal.MIN_CALIBRATION_ITERATIONS
        assert not fit.confident

    def test_inexplicable_data_falls_back_proportional(self):
        # measured time SMALLER than the net term alone at base
        # bandwidth: no positive gather bandwidth explains it
        t_net_alone = 3e5 / BASE.net_bytes_per_s
        obs = [cal.PhaseObservation(
            iterations=100, elapsed_s=0.1 * t_net_alone * 100,
            gather_bytes_per_iteration=2e6,
            net_bytes_per_iteration=3e5)]
        fit = cal.fit_machine_model(obs, base=BASE, backend="unit")
        assert fit.method == "proportional"
        assert not fit.confident
        assert fit.model.gather_slowdown > 0
        assert (fit.model.net_bytes_per_s or 0) > 0

    def test_noisy_fit_reports_residual(self):
        gather_bw = 1.0e10
        clean = synthetic_obs(gather_bw, BASE.net_bytes_per_s, 2e6, 3e5)
        noisy = cal.PhaseObservation(
            iterations=100, elapsed_s=clean.elapsed_s * 3.0,
            gather_bytes_per_iteration=2e6,
            net_bytes_per_iteration=3e5)
        fit = cal.fit_machine_model([clean, noisy], base=BASE,
                                    backend="unit")
        assert fit.residual_rel > cal.CONFIDENT_RESIDUAL
        assert not fit.confident

    def test_empty_observations_raise(self):
        with pytest.raises(ValueError, match="observation"):
            cal.fit_machine_model([], base=BASE, backend="unit")

    def test_observation_validation(self):
        with pytest.raises(ValueError):
            cal.PhaseObservation(0, 1.0, 1.0, 1.0)
        with pytest.raises(ValueError):
            cal.PhaseObservation(10, 0.0, 1.0, 1.0)

    def test_fit_json_roundtrip(self):
        obs = [synthetic_obs(1e10, 5e9, 1e6, 1e5),
               synthetic_obs(1e10, 5e9, 4e6, 2e5)]
        fit = cal.fit_machine_model(obs, base=BASE, backend="unit")
        back = cal.CalibrationFit.from_json(
            json.loads(json.dumps(fit.to_json())))
        assert back.model.gather_slowdown == pytest.approx(
            fit.model.gather_slowdown)
        assert back.confident == fit.confident
        assert back.method == fit.method
        assert "gather" in fit.describe()


class TestObservationFor:
    def test_bytes_match_planner_terms(self):
        rep = ss.ShardReport.from_json({
            "kind": "ranges", "n_shards": 4, "n_global": 16,
            "n_global_padded": 16, "n_local": 4,
            "rows": [4, 4, 4, 4], "nnz": [19, 4, 4, 4],
            "slots": [19, 19, 19, 19],
            "halo_send_bytes": [16, 16, 16, 16],
            "halo_recv_bytes": [48, 48, 48, 48],
            "neighbors": [[[-1, 16]]] * 4,
        })
        obs = cal.observation_for(rep, 10, 0.5, itemsize=8)
        assert obs.gather_bytes_per_iteration == 19 * (8 + 4)
        # allgather lane: the fixed x-rotation payload, FULL stop - the
        # historical 0.25 coupling fudge is gone (the wire either
        # ignores coupling entirely, or honors it exactly via the
        # gather lane below)
        assert obs.net_bytes_per_iteration == pytest.approx(
            (4 - 1) * 4 * 8)
        assert obs.s_per_iteration == pytest.approx(0.05)
        # the jaxpr-derived wire, when known, replaces the analytic term
        obs2 = cal.observation_for(rep, 10, 0.5, itemsize=8,
                                   comm_bytes_per_iteration=1000.0)
        assert obs2.net_bytes_per_iteration == pytest.approx(1000.0)

    def test_gather_lane_prices_coupled_wire(self):
        """exchange='gather' observations price the packed coupled
        rounds (balance.plan.wire_bytes_for == shardscope.
        gather_wire_bytes), full weight - the same term score_report
        charges, so predicted and measured stay one model."""
        from cuda_mpi_parallel_tpu.balance.plan import wire_bytes_for

        rep = ss.ShardReport.from_json({
            "kind": "ranges", "n_shards": 4, "n_global": 16,
            "n_global_padded": 16, "n_local": 4,
            "rows": [4, 4, 4, 4], "nnz": [19, 4, 4, 4],
            "slots": [19, 19, 19, 19],
            "halo_send_bytes": [16, 16, 16, 16],
            "halo_recv_bytes": [48, 48, 48, 48],
            # shard k sends 16 B to its forward neighbor only: rounds
            # shift=1 (max 16 B) and nothing else -> wire = 16 B
            "neighbors": [[[(k + 1) % 4, 16]] for k in range(4)],
        })
        obs = cal.observation_for(rep, 10, 0.5, itemsize=8,
                                  exchange="gather")
        assert obs.net_bytes_per_iteration == pytest.approx(
            ss.gather_wire_bytes(rep))
        assert obs.net_bytes_per_iteration == pytest.approx(
            wire_bytes_for(rep, "gather", 8)) == 16.0


class TestJsonCache:
    def test_roundtrip(self, tmp_path):
        c = JsonCache(str(tmp_path))
        c.put("some key/with:odd chars", {"x": 1.5})
        entry = c.get("some key/with:odd chars")
        assert entry["payload"] == {"x": 1.5}
        assert entry["created_at"] == pytest.approx(time.time(), abs=60)

    def test_staleness(self, tmp_path):
        c = JsonCache(str(tmp_path))
        c.put("k", {"v": 1}, created_at=time.time() - 100.0)
        assert c.get("k") is not None
        assert c.get("k", max_age_s=50.0) is None
        assert c.get("k", max_age_s=1000.0) is not None

    def test_corrupt_and_missing_are_misses(self, tmp_path):
        c = JsonCache(str(tmp_path))
        assert c.get("absent") is None
        with open(c.path("bad"), "w") as f:
            f.write("{not json")
        assert c.get("bad") is None
        with open(c.path("shapeless"), "w") as f:
            json.dump({"no": "envelope"}, f)
        assert c.get("shapeless") is None

    def test_delete(self, tmp_path):
        c = JsonCache(str(tmp_path))
        c.put("k", {"v": 1})
        c.delete("k")
        assert c.get("k") is None
        c.delete("k")  # idempotent

    def test_host_fingerprint_stable(self):
        assert host_fingerprint() == host_fingerprint()
        assert len(host_fingerprint()) == 12


class TestPersistence:
    def _fit(self, confident=True):
        iters = 100 if confident else 2
        obs = [synthetic_obs(1e10, 5e9, 1e6, 1e5, iterations=iters),
               synthetic_obs(1e10, 5e9, 4e6, 2e5, iterations=iters)]
        return cal.fit_machine_model(obs, base=BASE, backend="cpu")

    def test_store_load_roundtrip(self, tmp_path):
        c = JsonCache(str(tmp_path))
        fit = self._fit()
        assert cal.store_calibration(fit, cache=c) is not None
        back = cal.load_calibration("cpu", cache=c)
        assert back is not None
        assert back.model.gather_slowdown == pytest.approx(
            fit.model.gather_slowdown)

    def test_preferred_model_requires_confidence(self, tmp_path):
        c = JsonCache(str(tmp_path))
        assert cal.preferred_model("cpu", cache=c) is None
        unconfident = self._fit(confident=False)
        assert not unconfident.confident
        cal.store_calibration(unconfident, cache=c)
        assert cal.preferred_model("cpu", cache=c) is None
        cal.store_calibration(self._fit(), cache=c)
        m = cal.preferred_model("cpu", cache=c)
        assert m is not None and m.source == "calibrated"

    def test_auto_plan_prefers_stored_calibration(self, tmp_path,
                                                  monkeypatch):
        """A confident calibration in the (env-pointed) default cache
        steers plan='auto' - the documented preference, exercised
        through resolve_plan exactly as solve_distributed hits it."""
        from cuda_mpi_parallel_tpu.parallel.dist_cg import resolve_plan

        monkeypatch.setenv("CUDA_MPI_PARALLEL_TPU_CACHE_DIR",
                           str(tmp_path))
        fit = self._fit()
        assert cal.store_calibration(fit) is not None
        a = mmio.load_matrix_market(FIXTURE)
        plan = resolve_plan("auto", a, 4)
        assert plan.scored_by == fit.model.name
        assert plan.scored_by.startswith("calibrated-")

    def test_preferred_model_honors_staleness(self, tmp_path):
        c = JsonCache(str(tmp_path))
        fit = self._fit()
        stale_model = roof.MachineModel(
            **{**fit.model.to_json(),
               "created_at": time.time() - 2 * cal.CALIBRATION_MAX_AGE_S})
        import dataclasses

        stale = dataclasses.replace(fit, model=stale_model)
        cal.store_calibration(stale, cache=c)
        assert cal.preferred_model("cpu", cache=c) is None


class TestDrift:
    def _report(self):
        return ss.ShardReport.from_json({
            "kind": "ranges", "n_shards": 4, "n_global": 16,
            "n_global_padded": 16, "n_local": 4,
            "rows": [4, 4, 4, 4], "nnz": [19, 4, 4, 4],
            "slots": [19, 19, 19, 19],
            "halo_send_bytes": [16, 16, 16, 16],
            "halo_recv_bytes": [48, 48, 48, 48],
            "neighbors": [[[-1, 16]]] * 4,
        })

    def test_drift_is_exact_ratio(self):
        rep = self._report()
        predicted = score_report(rep, itemsize=8, model=BASE)
        iters = 10
        dr = cal.drift_report(rep, iters, predicted * iters * 3.0,
                              itemsize=8, model=BASE)
        assert dr.predicted_s_per_iteration == pytest.approx(predicted)
        assert dr.measured_s_per_iteration == pytest.approx(
            predicted * 3.0)
        assert dr.drift_pct == pytest.approx(200.0)
        assert dr.model == "unit-base"
        assert "model error" in dr.describe()

    def test_note_drift_emits_extended_event_and_gauges(self):
        rep = self._report()
        dr = cal.drift_report(rep, 10, 0.1, itemsize=8, model=BASE)
        with events.capture() as buf:
            cal.note_drift(dr, report=rep)
        lines = [json.loads(ln)
                 for ln in buf.getvalue().strip().splitlines()]
        assert len(lines) == 1
        ev = events.validate_event(lines[0])
        assert ev["event"] == "partition_plan"
        assert ev["stage"] == "drift"
        assert ev["reorder"] == "none" and ev["split"] == "even"
        assert ev["n_shards"] == 4
        assert ev["drift_pct"] == pytest.approx(dr.drift_pct)
        assert ev["predicted_s_per_iteration"] == pytest.approx(
            dr.predicted_s_per_iteration)
        assert REGISTRY.gauge(
            "plan_drift_pct", "", labelnames=("plan",)).value(
                plan="even") == pytest.approx(dr.drift_pct)

    def test_score_report_uses_model_gather_slowdown(self):
        rep = self._report()
        fast_gather = roof.MachineModel(
            name="fast", mem_bytes_per_s=BASE.mem_bytes_per_s,
            flops_per_s=BASE.flops_per_s,
            net_bytes_per_s=BASE.net_bytes_per_s,
            gather_slowdown=1.0)
        # halving the slowdown must strictly shrink the slot term
        assert score_report(rep, itemsize=8, model=fast_gather) \
            < score_report(rep, itemsize=8, model=BASE)


class TestRooflineDiskCache:
    def test_cpu_model_round_trips_through_disk(self, tmp_path,
                                                monkeypatch):
        c = JsonCache(str(tmp_path))
        m1 = roof.machine_model("cpu", cache=c)
        assert m1.source == "calibrated"
        assert m1.created_at is not None

        def boom():  # a second call must NOT re-measure
            raise AssertionError("recalibrated despite fresh cache")

        monkeypatch.setattr(roof, "_calibrate_cpu", boom)
        m2 = roof.machine_model("cpu", cache=c)
        assert m2.created_at == pytest.approx(m1.created_at)
        assert m2.mem_bytes_per_s == pytest.approx(m1.mem_bytes_per_s)

    def test_stale_disk_model_is_remeasured(self, tmp_path):
        c = JsonCache(str(tmp_path))
        old = roof.MachineModel(
            name="cpu-calibrated", mem_bytes_per_s=1.0,
            flops_per_s=1.0, net_bytes_per_s=1.0, source="calibrated",
            created_at=time.time() - 2 * roof.CPU_MODEL_MAX_AGE_S)
        c.put(f"machine-model-cpu-{host_fingerprint()}", old.to_json(),
              created_at=old.created_at)
        fresh = roof.machine_model("cpu", cache=c)
        assert fresh.mem_bytes_per_s > 1.0

    def test_report_carries_model_age(self):
        aged = roof.MachineModel(
            name="t", mem_bytes_per_s=1e9, flops_per_s=1e9,
            source="calibrated", created_at=time.time() - 3600.0)
        r = roof.analyze(n=10, nnz=30, itemsize=4, iterations=2,
                         elapsed_s=0.1, model=aged)
        assert r.model_source == "calibrated"
        assert r.model_age_s == pytest.approx(3600.0, abs=60.0)
        assert r.to_json()["model_age_s"] == r.model_age_s
        table = roof.analyze(n=10, nnz=30, itemsize=4, iterations=2,
                             elapsed_s=0.1, model=BASE)
        assert table.model_age_s is None


@needs_mesh
class TestSequence:
    def setup_method(self):
        from cuda_mpi_parallel_tpu.parallel import dist_cg

        dist_cg.clear_solver_cache()

    def test_replan_sequence_on_skewed_fixture(self, tmp_path):
        """ISSUE 6 acceptance: on the skewed fixture at mesh 4,
        solve 2 of a --repeat 2 --replan sequence runs on a plan scored
        by the solve-1-calibrated model; the replan event records the
        decision; the drift-extended partition_plan events validate;
        and every solve still matches the single-device solution."""
        from cuda_mpi_parallel_tpu.parallel import (
            make_mesh,
            solve_sequence,
        )

        a = mmio.load_matrix_market(FIXTURE)
        rng = np.random.default_rng(3)
        b = rng.standard_normal(240)
        ref = solve(a, jnp.asarray(b), tol=1e-10, maxiter=2000)
        assert bool(ref.converged)

        cache = JsonCache(str(tmp_path))
        with events.capture() as buf:
            seq = solve_sequence(a, b, mesh=make_mesh(4), repeats=2,
                                 replan=True, tol=1e-10, maxiter=2000,
                                 calibration_cache=cache)
        assert len(seq.entries) == 2
        for entry in seq.entries:
            assert bool(entry.result.converged)
            np.testing.assert_allclose(np.asarray(entry.result.x),
                                       np.asarray(ref.x), atol=1e-7)
        # solve 1 ran the even split (plan=None default); solve 2 must
        # run on a runtime-corrected plan scored by the calibrated model
        assert seq.entries[0].plan is None
        plan2 = seq.entries[1].plan
        assert plan2 is not None
        assert plan2.scored_by == seq.entries[0].fit.model.name
        assert plan2.scored_by.startswith("calibrated-")

        lines = [json.loads(ln)
                 for ln in buf.getvalue().strip().splitlines()]
        for ev in lines:
            events.validate_event(ev)
        replans = [e for e in lines if e["event"] == "replan"]
        assert len(replans) == 1
        assert replans[0]["decision"] == "switched"
        assert replans[0]["solve_index"] == 1
        assert replans[0]["predicted_gain_pct"] > 0
        drifts = [e for e in lines if e["event"] == "partition_plan"
                  and e.get("stage") == "drift"]
        assert len(drifts) == 2  # one per solve
        # the calibration was persisted and is preferred for later
        # auto planning on this backend/host (when confident)
        fit = seq.final.fit
        stored = cal.load_calibration(cache=cache)
        assert stored is not None
        if fit.confident:
            assert cal.preferred_model(cache=cache) is not None
        summary = seq.summary()
        assert summary["repeats"] == 2
        assert summary["decisions"][0]["decision"] == "switched"
        assert any("replan" in ln for ln in seq.describe_lines())

    def test_sequence_rejects_stencils_and_bad_repeats(self):
        from cuda_mpi_parallel_tpu.models import poisson
        from cuda_mpi_parallel_tpu.parallel import (
            make_mesh,
            solve_sequence,
        )

        stencil = poisson.poisson_2d_operator(16, 16)
        with pytest.raises(ValueError, match="CSRMatrix"):
            solve_sequence(stencil, np.ones(256), mesh=make_mesh(4))
        a = mmio.load_matrix_market(FIXTURE)
        with pytest.raises(ValueError, match="repeats"):
            solve_sequence(a, np.ones(240), mesh=make_mesh(4),
                           repeats=0)

    def test_cli_repeat_replan_json_record(self, tmp_path, capsys,
                                           monkeypatch):
        from cuda_mpi_parallel_tpu import cli
        from cuda_mpi_parallel_tpu.telemetry import (
            shardscope as tshard,
        )

        # the CLI path persists to the DEFAULT cache: point it at this
        # test's own dir so the confident toy calibration can never
        # steer a later test's plan="auto" lane (the session scratch
        # cache is shared across the whole suite)
        monkeypatch.setenv("CUDA_MPI_PARALLEL_TPU_CACHE_DIR",
                           str(tmp_path))
        try:
            rc = cli.main(["--problem", "mm", "--file", FIXTURE,
                           "--mesh", "4", "--device", "cpu",
                           "--tol", "1e-8", "--maxiter", "500",
                           "--repeat", "2", "--replan", "--json"])
        finally:
            telemetry.force_active(False)
            tshard.reset_last_shard_report()
        assert rc == 0
        record = json.loads(capsys.readouterr().out.strip())
        calib = record["calibration"]
        assert calib["repeats"] == 2
        assert calib["decisions"][0]["decision"] in ("kept", "switched")
        assert "drift_pct" in calib["drift"]
        assert calib["solves"][1]["scored_by"].startswith("calibrated-")
        # the final solve's plan rides the record as usual
        assert record["plan"]["label"] != "even" \
            or calib["decisions"][0]["decision"] == "kept"

    def test_cli_repeat_refusals(self):
        from cuda_mpi_parallel_tpu import cli

        with pytest.raises(SystemExit, match="mesh"):
            cli.main(["--problem", "mm", "--file", FIXTURE,
                      "--repeat", "2"])
        with pytest.raises(SystemExit, match="repeat"):
            cli.main(["--problem", "mm", "--file", FIXTURE,
                      "--mesh", "4", "--replan"])
        with pytest.raises(SystemExit, match="CSR"):
            cli.main(["--problem", "poisson2d", "--n", "16",
                      "--matrix-free", "--mesh", "4",
                      "--repeat", "2"])


class TestZeroPerturbation:
    """Calibration/replan OFF is jaxpr-bit-identical (ISSUE 6)."""

    @needs_mesh
    def test_calibration_machinery_leaves_solve_jaxpr_identical(self):
        """Run the ENTIRE calibra pipeline (fit, persist, preferred-
        model lookup, drift + gauges + events) between two traces of
        the same distributed CSR solve body: the jaxpr must not move a
        bit - everything here is post-solve host arithmetic."""
        from cuda_mpi_parallel_tpu.models import poisson
        from cuda_mpi_parallel_tpu.parallel import make_mesh
        from cuda_mpi_parallel_tpu.parallel import partition as part
        from cuda_mpi_parallel_tpu.parallel.operators import DistCSR
        from cuda_mpi_parallel_tpu.solver.cg import cg

        a = poisson.poisson_2d_csr(8, 8)
        mesh = make_mesh(4)

        def trace():
            parts = part.partition_csr(a, 4)
            b = jnp.zeros(parts.n_global_padded)
            data = jnp.asarray(parts.data)
            cols = jnp.asarray(parts.cols)
            rows = jnp.asarray(parts.local_rows)

            @partial(compat.shard_map, mesh=mesh,
                     in_specs=(P("rows"), P("rows"), P("rows"),
                               P("rows")),
                     out_specs=P("rows"))
            def run(b_local, d, c, r):
                strip = partial(jax.tree.map, lambda v: v[0])
                op = DistCSR(data=strip(d), cols=strip(c),
                             local_rows=strip(r),
                             n_local=parts.n_local,
                             axis_name="rows", n_shards=4)
                return cg(op, b_local, axis_name="rows", maxiter=25).x

            return str(jax.make_jaxpr(run)(b, data, cols, rows))

        base = trace()
        import tempfile

        with tempfile.TemporaryDirectory() as d:
            cache = JsonCache(d)
            obs = [synthetic_obs(1e10, 5e9, 1e6, 1e5),
                   synthetic_obs(1e10, 5e9, 4e6, 2e5)]
            fit = cal.fit_machine_model(obs, base=BASE, backend="cpu")
            cal.note_calibration(fit)
            cal.store_calibration(fit, cache=cache)
            assert cal.preferred_model("cpu", cache=cache) is not None
            rep = ss.report_for_ranges(
                a, (((0, 16)), (16, 32), (32, 48), (48, 64)),
                itemsize=8)
            with events.capture():
                cal.note_drift(cal.drift_report(rep, 25, 0.1,
                                                itemsize=8),
                               report=rep)
        assert trace() == base

    def test_resolve_plan_auto_unchanged_without_calibration(self,
                                                             tmp_path,
                                                             monkeypatch):
        """With no calibration on disk, plan='auto' resolves to the
        SAME reference-scored plan as a direct plan_partition call -
        the pre-calibra behavior, bit for bit (same layout fingerprint,
        same reference scorer)."""
        from cuda_mpi_parallel_tpu.parallel.dist_cg import resolve_plan

        monkeypatch.setenv("CUDA_MPI_PARALLEL_TPU_CACHE_DIR",
                           str(tmp_path / "empty"))
        a = mmio.load_matrix_market(FIXTURE)
        direct = plan_partition(a, 4)
        resolved = resolve_plan("auto", a, 4)
        assert direct.scored_by == "reference-tpu-v5e"
        assert resolved.scored_by == "reference-tpu-v5e"
        assert resolved.fingerprint() == direct.fingerprint()
        assert resolved.score == pytest.approx(direct.score)

    def test_reference_model_matches_legacy_constants(self):
        """The promoted MachineModel fields keep the PR-5 table values:
        plans stay host-independent by default."""
        from cuda_mpi_parallel_tpu.balance.plan import GATHER_SLOWDOWN

        ref = reference_model()
        assert ref.mem_bytes_per_s == pytest.approx(8.19e11)
        assert ref.net_bytes_per_s == pytest.approx(4.5e10)
        assert ref.gather_slowdown == GATHER_SLOWDOWN == 8.0
        assert ref.source == "table"
