"""VMEM-resident single-kernel CG (``ops/pallas/resident.py``).

All kernel runs use interpret mode (CPU CI); parity is checked against
the general ``solver.cg`` path, which is itself oracle-verified in
``test_cg.py``.  On hardware the same kernel measured 6.65 us/iter at
1024^2 f32 with iteration counts identical to the general solver
(2688 == 2688 at tol 1e-4).
"""
import numpy as np
import pytest

import jax.numpy as jnp

from cuda_mpi_parallel_tpu import (
    cg_resident,
    cg_resident_df64,
    solve,
    supports_resident,
    supports_resident_df64,
)
from cuda_mpi_parallel_tpu.models import poisson
from cuda_mpi_parallel_tpu.models.operators import Stencil2D, Stencil3D
from cuda_mpi_parallel_tpu.ops.pallas import resident as rk
from cuda_mpi_parallel_tpu.solver.df64 import cg_df64
from cuda_mpi_parallel_tpu.solver.status import CGStatus


def _grid_problem(nx=16, ny=128, seed=0):
    op = poisson.poisson_2d_operator(nx, ny, dtype=jnp.float32)
    rng = np.random.default_rng(seed)
    b = rng.standard_normal((nx, ny)).astype(np.float32)
    return op, b


class TestParityVsGeneralSolver:
    def test_trajectory_matches_checkevery_cg(self):
        op, b = _grid_problem()
        ref = solve(op, jnp.asarray(b.ravel()), tol=1e-5, maxiter=500,
                    check_every=8)
        res = cg_resident(op, jnp.asarray(b), tol=1e-5, maxiter=500,
                          check_every=8, interpret=True)
        assert int(res.iterations) == int(ref.iterations)
        assert bool(res.converged)
        np.testing.assert_allclose(np.asarray(res.x).ravel(),
                                   np.asarray(ref.x), rtol=0, atol=1e-5)
        # recurrence residuals agree to f32 reduction-order rounding
        assert np.isclose(float(res.residual_norm),
                          float(ref.residual_norm), rtol=1e-2)

    def test_flat_rhs_matches_grid_rhs(self):
        op, b = _grid_problem()
        r1 = cg_resident(op, jnp.asarray(b), tol=1e-5, maxiter=200,
                         interpret=True)
        r2 = cg_resident(op, jnp.asarray(b.ravel()), tol=1e-5, maxiter=200,
                         interpret=True)
        assert r2.x.ndim == 1 and r1.x.ndim == 2
        np.testing.assert_array_equal(np.asarray(r1.x).ravel(),
                                      np.asarray(r2.x))

    def test_rtol_threshold(self):
        op, b = _grid_problem()
        res = cg_resident(op, jnp.asarray(b), tol=0.0, rtol=1e-4,
                          maxiter=500, check_every=4, interpret=True)
        assert bool(res.converged)
        assert (float(res.residual_norm)
                <= 1e-4 * np.linalg.norm(b.ravel()) + 1e-12)

    def test_x0_warm_start_matches_general(self):
        from cuda_mpi_parallel_tpu.solver.cg import cg as _cg

        op, b = _grid_problem()
        rng = np.random.default_rng(5)
        x0 = (rng.standard_normal(16 * 128) * 0.1).astype(np.float32)
        ref = _cg(op, jnp.asarray(b.ravel()), jnp.asarray(x0), tol=1e-5,
                  maxiter=500, check_every=8)
        res = cg_resident(op, jnp.asarray(b), jnp.asarray(x0), tol=1e-5,
                          maxiter=500, check_every=8, interpret=True)
        assert int(res.iterations) == int(ref.iterations)
        np.testing.assert_allclose(np.asarray(res.x).ravel(),
                                   np.asarray(ref.x), rtol=0, atol=1e-5)
        # warm start via solve(engine=) too
        res2 = solve(op, jnp.asarray(b.ravel()), jnp.asarray(x0),
                     tol=1e-5, maxiter=500, check_every=8,
                     engine="resident")
        assert int(res2.iterations) == int(ref.iterations)

    def test_x0_exact_solution_converges_immediately(self):
        op, b = _grid_problem()
        x_true = np.asarray(
            solve(op, jnp.asarray(b.ravel()), tol=1e-6, maxiter=1000).x)
        res = cg_resident(op, jnp.asarray(b), jnp.asarray(x_true),
                          tol=1e-4, maxiter=100, check_every=4,
                          interpret=True)
        assert bool(res.converged)
        assert int(res.iterations) <= 4

    def test_scale_is_applied(self):
        nx, ny = 16, 128
        op = Stencil2D.create(nx, ny, scale=3.0, dtype=jnp.float32)
        rng = np.random.default_rng(3)
        b = rng.standard_normal((nx, ny)).astype(np.float32)
        res = cg_resident(op, jnp.asarray(b), tol=1e-5, maxiter=500,
                          check_every=8, interpret=True)
        r_true = b.ravel() - np.asarray(op.matvec(jnp.asarray(
            np.asarray(res.x).ravel())))
        assert np.linalg.norm(r_true) < 1e-3


class Test3DResident:
    """7-point Stencil3D in the same one-kernel shape."""

    def _problem(self, nx=4, ny=8, nz=128, seed=0):
        op = Stencil3D.create(nx, ny, nz, dtype=jnp.float32)
        rng = np.random.default_rng(seed)
        b = rng.standard_normal(nx * ny * nz).astype(np.float32)
        return op, b

    def test_trajectory_matches_general_solver(self):
        op, b = self._problem()
        ref = solve(op, jnp.asarray(b), tol=1e-5, maxiter=300,
                    check_every=8)
        res = cg_resident(op, jnp.asarray(b), tol=1e-5, maxiter=300,
                          check_every=8, interpret=True)
        assert int(res.iterations) == int(ref.iterations)
        assert bool(res.converged)
        np.testing.assert_allclose(np.asarray(res.x).ravel(),
                                   np.asarray(ref.x), rtol=0, atol=1e-5)

    def test_grid_rhs_shape(self):
        op, b = self._problem()
        res = cg_resident(op, jnp.asarray(b.reshape(4, 8, 128)), tol=1e-5,
                          maxiter=300, check_every=8, interpret=True)
        assert res.x.shape == (4, 8, 128)

    def test_chebyshev_3d(self):
        from cuda_mpi_parallel_tpu.models.precond import (
            ChebyshevPreconditioner,
        )

        op, b = self._problem()
        m = ChebyshevPreconditioner.from_operator(op, degree=4)
        ref = solve(op, jnp.asarray(b), tol=1e-5, maxiter=300,
                    check_every=8, m=m)
        res = cg_resident(op, jnp.asarray(b), tol=1e-5, maxiter=300,
                          check_every=8, m=m, interpret=True)
        assert int(res.iterations) == int(ref.iterations)

    def test_gate_3d(self, monkeypatch):
        op, _ = self._problem()
        assert supports_resident(op)
        assert not rk.supports_resident_3d(4, 10, 128)
        assert not rk.supports_resident_3d(4, 8, 100)
        monkeypatch.setenv(rk._ENV_OVERRIDE, str(1 << 20))
        assert not rk.supports_resident_3d(64, 64, 128)
        # 256^3 north star never fits a 128 MiB part
        monkeypatch.delenv(rk._ENV_OVERRIDE)
        assert not rk.supports_resident_3d(256, 256, 256)


class TestChebyshevResident:
    """In-kernel Chebyshev polynomial preconditioning."""

    def test_trajectory_matches_preconditioned_cg(self):
        from cuda_mpi_parallel_tpu.models.precond import (
            ChebyshevPreconditioner,
        )

        op, b = _grid_problem()
        m = ChebyshevPreconditioner.from_operator(op, degree=4)
        ref = solve(op, jnp.asarray(b.ravel()), tol=1e-5, maxiter=500,
                    check_every=8, m=m)
        res = cg_resident(op, jnp.asarray(b), tol=1e-5, maxiter=500,
                          check_every=8, m=m, interpret=True)
        assert int(res.iterations) == int(ref.iterations)
        assert bool(res.converged)
        np.testing.assert_allclose(np.asarray(res.x).ravel(),
                                   np.asarray(ref.x), rtol=0, atol=1e-5)

    def test_cuts_iterations_vs_plain(self):
        from cuda_mpi_parallel_tpu.models.precond import (
            ChebyshevPreconditioner,
        )

        op, b = _grid_problem()
        m = ChebyshevPreconditioner.from_operator(op, degree=4)
        plain = cg_resident(op, jnp.asarray(b), tol=1e-5, maxiter=500,
                            check_every=8, interpret=True)
        pcg = cg_resident(op, jnp.asarray(b), tol=1e-5, maxiter=500,
                          check_every=8, m=m, interpret=True)
        assert int(pcg.iterations) < int(plain.iterations) // 2

    def test_rejects_other_preconditioners(self):
        from cuda_mpi_parallel_tpu.models.operators import (
            JacobiPreconditioner,
        )

        op, b = _grid_problem()
        mj = JacobiPreconditioner.from_operator(op)
        with pytest.raises(TypeError, match="ChebyshevPreconditioner"):
            cg_resident(op, jnp.asarray(b), m=mj, interpret=True)

    def test_rejects_mismatched_operator(self):
        from cuda_mpi_parallel_tpu.models.precond import (
            ChebyshevPreconditioner,
        )

        op, b = _grid_problem()
        other = poisson.poisson_2d_operator(8, 128, dtype=jnp.float32)
        m = ChebyshevPreconditioner.from_operator(other, degree=4)
        with pytest.raises(ValueError, match="same"):
            cg_resident(op, jnp.asarray(b), m=m, interpret=True)

    def test_rejects_same_grid_different_scale(self):
        from cuda_mpi_parallel_tpu.models.precond import (
            ChebyshevPreconditioner,
        )

        op, b = _grid_problem()
        scaled = Stencil2D.create(16, 128, scale=4.0, dtype=jnp.float32)
        m = ChebyshevPreconditioner.from_operator(scaled, degree=4)
        with pytest.raises(ValueError, match="same"):
            cg_resident(op, jnp.asarray(b), m=m, interpret=True)

    def test_bad_interval_reports_breakdown(self):
        # an interval that makes p(A) negative definite: rho0 <= 0 is a
        # preconditioner breakdown and must surface as BREAKDOWN, not
        # MAXITER (solver/cg.py health semantics).
        from cuda_mpi_parallel_tpu.models.precond import (
            ChebyshevPreconditioner,
        )

        op, b = _grid_problem()
        m = ChebyshevPreconditioner(a=op,
                                    lmin=jnp.float32(-2.0),
                                    lmax=jnp.float32(-1.0), degree=2)
        res = cg_resident(op, jnp.asarray(b), tol=1e-6, maxiter=100,
                          check_every=4, m=m, interpret=True)
        assert res.status_enum() is CGStatus.BREAKDOWN
        assert not bool(res.converged)


class TestSemantics:
    def test_maxiter_status(self):
        op, b = _grid_problem()
        res = cg_resident(op, jnp.asarray(b), tol=1e-30, maxiter=8,
                          check_every=4, interpret=True)
        assert not bool(res.converged)
        assert res.status_enum() is CGStatus.MAXITER
        assert int(res.iterations) == 8

    def test_iterations_block_aligned(self):
        op, b = _grid_problem()
        res = cg_resident(op, jnp.asarray(b), tol=1e-5, maxiter=500,
                          check_every=8, interpret=True)
        assert int(res.iterations) % 8 == 0

    def test_cap_not_multiple_of_block(self):
        # The final partial block truncates at the cap (general-solver
        # _block_fits semantics): iterations never exceed maxiter.
        op, b = _grid_problem()
        res = cg_resident(op, jnp.asarray(b), tol=1e-30, maxiter=100,
                          check_every=32, interpret=True)
        assert int(res.iterations) == 100
        res2 = cg_resident(op, jnp.asarray(b), tol=1e-30, maxiter=64,
                           check_every=8, iter_cap=12, interpret=True)
        assert int(res2.iterations) == 12

    def test_indefinite_not_set_by_exact_solve(self):
        # pap == 0 past an exact solve is a freeze, not indefiniteness
        # (solver/cg.py's (p_ap <= 0) & (rr > 0) guard).
        nx, ny = 8, 128
        op = poisson.poisson_2d_operator(nx, ny, dtype=jnp.float32)
        x_true = np.zeros((nx, ny), np.float32)
        x_true[4, 64] = 1.0
        b = np.asarray(op.matvec(jnp.asarray(x_true.ravel()))).reshape(nx, ny)
        res = cg_resident(op, jnp.asarray(b), tol=1e-6, maxiter=400,
                          check_every=4, interpret=True)
        assert bool(res.converged)
        assert not bool(res.indefinite)

    def test_iter_cap_traced(self):
        op, b = _grid_problem()
        res_full = cg_resident(op, jnp.asarray(b), tol=0.0, maxiter=64,
                               check_every=8, interpret=True)
        res_cap = cg_resident(op, jnp.asarray(b), tol=0.0, maxiter=64,
                              check_every=8, iter_cap=16, interpret=True)
        assert int(res_full.iterations) == 64
        assert int(res_cap.iterations) == 16

    def test_exact_solve_freeze(self):
        # b in the range of A with an exact representable solution: after
        # convergence to r == 0, further blocks must freeze, not NaN.
        nx, ny = 8, 128
        op = poisson.poisson_2d_operator(nx, ny, dtype=jnp.float32)
        x_true = np.zeros((nx, ny), np.float32)
        x_true[4, 64] = 1.0
        b = np.asarray(op.matvec(jnp.asarray(x_true.ravel()))).reshape(nx, ny)
        res = cg_resident(op, jnp.asarray(b), tol=1e-6, maxiter=400,
                          check_every=4, interpret=True)
        assert bool(res.converged)
        assert np.all(np.isfinite(np.asarray(res.x)))
        np.testing.assert_allclose(np.asarray(res.x), x_true, atol=1e-4)

    def test_zero_rhs(self):
        op, _ = _grid_problem()
        b = jnp.zeros((16, 128), jnp.float32)
        res = cg_resident(op, b, tol=1e-7, maxiter=100, interpret=True)
        assert bool(res.converged)
        assert int(res.iterations) == 0 or float(res.residual_norm) == 0.0
        np.testing.assert_array_equal(np.asarray(res.x), 0.0)


class TestAdvisorR3Regressions:
    """Round-3 advisor findings: maxiter=0, genuine-breakdown surfacing,
    and the exact convergence-boundary tie (all vs the general solver's
    semantics, which are the contract)."""

    def test_maxiter_zero_matches_general(self):
        # check_every = min(check_every, 0) == 0 used to divide by zero
        # in nblocks; must instead return a zero-iteration CGResult with
        # the same status the general solver reports.
        op, b = _grid_problem()
        ref = solve(op, jnp.asarray(b.ravel()), tol=1e-7, maxiter=0)
        res = cg_resident(op, jnp.asarray(b), tol=1e-7, maxiter=0,
                          interpret=True)
        assert int(res.iterations) == 0 == int(ref.iterations)
        assert bool(res.converged) == bool(ref.converged)
        assert res.status_enum() is ref.status_enum()
        np.testing.assert_array_equal(np.asarray(res.x), 0.0)

    def test_genuine_breakdown_is_breakdown_not_maxiter(self):
        # A = 0 (scale 0): p.Ap == 0 with rho != 0 on the very first
        # iteration - a genuine breakdown.  The old f32 kernel froze on
        # pap == 0 alone and silently spun to MAXITER; _safe_div
        # semantics let the inf surface so the health predicate reports
        # BREAKDOWN, exactly like the general solver.
        nx, ny = 8, 128
        op = Stencil2D.create(nx, ny, scale=0.0, dtype=jnp.float32)
        rng = np.random.default_rng(7)
        b = rng.standard_normal((nx, ny)).astype(np.float32)
        ref = solve(op, jnp.asarray(b.ravel()), tol=1e-7, maxiter=64,
                    check_every=4)
        res = cg_resident(op, jnp.asarray(b), tol=1e-7, maxiter=64,
                          check_every=4, interpret=True)
        assert ref.status_enum() is CGStatus.BREAKDOWN
        assert res.status_enum() is CGStatus.BREAKDOWN
        assert bool(res.indefinite)
        # and it must stop at the first block boundary, not spin to 64
        assert int(res.iterations) == int(ref.iterations)

    def test_genuine_breakdown_df64_matches_general(self):
        # The df64 kernel used a pap-only keep-mask that held the
        # carried scalars finite for one extra block after a genuine
        # breakdown; it must stop at the same block boundary as
        # solver.df64 (carried inf/nan -> health predicate).
        nx, ny = 8, 128
        op = Stencil2D.create(nx, ny, scale=0.0, dtype=jnp.float32)
        rng = np.random.default_rng(7)
        b = rng.standard_normal(nx * ny)
        ref = cg_df64(op, b, tol=1e-7, maxiter=64, check_every=4)
        res = cg_resident_df64(op, b, tol=1e-7, maxiter=64,
                               check_every=4, interpret=True)
        assert ref.status_enum() is CGStatus.BREAKDOWN
        assert res.status_enum() is CGStatus.BREAKDOWN
        assert int(res.iterations) == int(ref.iterations)

    def test_exact_threshold_tie_keeps_iterating(self):
        # rr0 == thresh^2 exactly (b one-hot 3.0 => rr0 = 9.0; tol 3.0
        # squares to exactly 9.0 in f32).  The general solver's cond is
        # rr >= thresh_sq (continue on the tie); the kernel used strict
        # > and stopped at zero iterations reporting converged.
        nx, ny = 8, 128
        op = poisson.poisson_2d_operator(nx, ny, dtype=jnp.float32)
        b = np.zeros((nx, ny), np.float32)
        b[4, 64] = 3.0
        ref = solve(op, jnp.asarray(b.ravel()), tol=3.0, maxiter=64,
                    check_every=4)
        res = cg_resident(op, jnp.asarray(b), tol=3.0, maxiter=64,
                          check_every=4, interpret=True)
        assert int(ref.iterations) > 0
        assert int(res.iterations) == int(ref.iterations)
        assert bool(res.converged) == bool(ref.converged)


class TestResidentCG1:
    """The in-kernel Chronopoulos-Gear single-reduction recurrence
    (roofline bottleneck-#2 experiment): algebraically the textbook
    iterates, both inner products at one evaluation point."""

    def test_iteration_parity_vs_general_cg1(self):
        op, b = _grid_problem()
        ref = solve(op, jnp.asarray(b.ravel()), tol=1e-5, maxiter=500,
                    check_every=8, method="cg1")
        res = cg_resident(op, jnp.asarray(b), tol=1e-5, maxiter=500,
                          check_every=8, method="cg1", interpret=True)
        assert int(res.iterations) == int(ref.iterations)
        assert bool(res.converged)
        np.testing.assert_allclose(np.asarray(res.x).ravel(),
                                   np.asarray(ref.x), atol=2e-4)

    def test_matches_plain_resident_trajectory(self):
        op, b = _grid_problem()
        plain = cg_resident(op, jnp.asarray(b), tol=1e-5, maxiter=500,
                            check_every=8, interpret=True)
        cg1 = cg_resident(op, jnp.asarray(b), tol=1e-5, maxiter=500,
                          check_every=8, method="cg1", interpret=True)
        # same algebra: equal block-aligned counts (at most one block
        # apart from rounding)
        assert abs(int(plain.iterations) - int(cg1.iterations)) <= 8

    def test_3d_and_warm_start_and_history(self):
        op3 = poisson.poisson_3d_operator(8, 8, 128, dtype=jnp.float32)
        rng = np.random.default_rng(4)
        x_true = rng.standard_normal(8 * 8 * 128).astype(np.float32)
        b3 = op3 @ jnp.asarray(x_true)
        warm = cg_resident(op3, b3, x0=x_true * np.float32(1 + 1e-3),
                           tol=1e-4, maxiter=300, check_every=8,
                           method="cg1", record_history=True,
                           interpret=True)
        cold = cg_resident(op3, b3, tol=1e-4, maxiter=300, check_every=8,
                           method="cg1", interpret=True)
        assert bool(warm.converged)
        assert int(warm.iterations) < int(cold.iterations)
        h = np.asarray(warm.residual_history)
        assert np.isfinite(h[0]) and np.isfinite(h[int(warm.iterations)])

    def test_breakdown_parity(self):
        op = Stencil2D.create(8, 128, scale=0.0, dtype=jnp.float32)
        rng = np.random.default_rng(7)
        b = jnp.asarray(rng.standard_normal(8 * 128).astype(np.float32))
        res = cg_resident(op, b.reshape(8, 128), tol=1e-7, maxiter=64,
                          check_every=4, method="cg1", interpret=True)
        assert res.status_enum() is CGStatus.BREAKDOWN

    def test_rejections_and_gate(self):
        op, b = _grid_problem()
        from cuda_mpi_parallel_tpu.models.precond import (
            ChebyshevPreconditioner,
        )
        from cuda_mpi_parallel_tpu.solver.resident import (
            resident_eligible,
        )

        m4 = ChebyshevPreconditioner.from_operator(op, degree=4)
        with pytest.raises(ValueError, match="cg1"):
            cg_resident(op, jnp.asarray(b), m=m4, method="cg1",
                        interpret=True)
        with pytest.raises(ValueError, match="method"):
            cg_resident(op, jnp.asarray(b), method="pipecg",
                        interpret=True)
        assert resident_eligible(op, method="cg1")
        assert not resident_eligible(op, m=m4, method="cg1")
        assert not resident_eligible(op, method="pipecg")
        # the cg1 gate budgets the extra s/w planes
        assert rk._extra_planes(False, False, cg1=True) \
            == rk._extra_planes(False, False) + 2


class TestResidentHistory:
    """Quirk Q7 closed on the flagship engine: the kernel's SMEM
    ``||r||^2`` trace surfaces as a check-block-granular
    ``residual_history``, agreeing with the general solver's
    per-iteration trace at block boundaries."""

    def test_history_matches_general_at_block_boundaries(self):
        op, b = _grid_problem()
        ce = 8
        ref = solve(op, jnp.asarray(b.ravel()), tol=1e-5, maxiter=500,
                    check_every=ce, record_history=True)
        res = cg_resident(op, jnp.asarray(b), tol=1e-5, maxiter=500,
                          check_every=ce, record_history=True,
                          interpret=True)
        hist = np.asarray(res.residual_history)
        ref_hist = np.asarray(ref.residual_history)
        assert hist.shape == ref_hist.shape == (501,)
        iters = int(res.iterations)
        boundaries = [0] + list(range(ce, iters + 1, ce))
        for k in boundaries:
            assert np.isfinite(hist[k]), k
            # f32 vs f64-capable general path: reduction-order rounding
            np.testing.assert_allclose(hist[k], ref_hist[k], rtol=2e-2)
        # non-boundary slots and never-reached blocks are NaN
        assert np.isnan(hist[1]) and np.isnan(hist[ce - 1])
        assert np.isnan(hist[iters + ce:]).all() or iters + ce > 500

    def test_history_none_by_default(self):
        op, b = _grid_problem()
        res = cg_resident(op, jnp.asarray(b), tol=1e-5, maxiter=100,
                          interpret=True)
        assert res.residual_history is None

    def test_final_partial_block_lands_on_cap(self):
        # maxiter not a multiple of check_every: the last boundary is
        # maxiter itself, with a real value, and no NaN clobbers it.
        op, b = _grid_problem()
        res = cg_resident(op, jnp.asarray(b), tol=1e-30, maxiter=20,
                          check_every=8, record_history=True,
                          interpret=True)
        hist = np.asarray(res.residual_history)
        assert hist.shape == (21,)
        assert np.isfinite(hist[0]) and np.isfinite(hist[8])
        assert np.isfinite(hist[16]) and np.isfinite(hist[20])
        assert np.isnan(hist[1]) and np.isnan(hist[19])

    def test_history_via_solve_engine_resident(self):
        op, b = _grid_problem()
        res = solve(op, jnp.asarray(b.ravel()), tol=1e-5, maxiter=200,
                    check_every=8, engine="resident",
                    record_history=True)
        assert res.residual_history is not None
        assert np.isfinite(np.asarray(res.residual_history)[0])

    def test_auto_with_history_stays_general(self):
        # auto must not switch granularity under the user: history
        # requests keep the per-iteration general path off- AND on-TPU.
        from cuda_mpi_parallel_tpu.solver.resident import (
            resident_eligible,
        )

        op, _ = _grid_problem()
        assert not resident_eligible(op, record_history=True)
        assert resident_eligible(op, record_history=False)

    def test_df64_history_matches_cg_df64_at_boundaries(self):
        op, b = _grid_problem()
        ce = 8
        b64 = np.asarray(b, np.float64).ravel()
        ref = cg_df64(op, b64, tol=0.0, rtol=1e-10, maxiter=200,
                      check_every=ce, record_history=True)
        res = cg_resident_df64(op, b64, tol=0.0, rtol=1e-10, maxiter=200,
                               check_every=ce, record_history=True,
                               interpret=True)
        hist = np.asarray(res.residual_history)
        ref_hist = np.asarray(ref.residual_history)
        assert hist.shape == ref_hist.shape == (201,)
        iters = int(res.iterations)
        for k in [0] + list(range(ce, iters + 1, ce)):
            assert np.isfinite(hist[k]), k
            np.testing.assert_allclose(hist[k], ref_hist[k], rtol=1e-5)
        assert np.isnan(hist[1])

    def test_maxiter_zero_history(self):
        op, b = _grid_problem()
        res = cg_resident(op, jnp.asarray(b), tol=1e-7, maxiter=0,
                          record_history=True, interpret=True)
        hist = np.asarray(res.residual_history)
        assert hist.shape == (1,)
        assert np.isfinite(hist[0])


class TestSolveEngineParam:
    def test_solve_engine_resident_matches_general(self):
        op, b = _grid_problem()
        bf = jnp.asarray(b.ravel())
        r1 = solve(op, bf, tol=1e-5, maxiter=500, check_every=8)
        r2 = solve(op, bf, tol=1e-5, maxiter=500, check_every=8,
                   engine="resident")
        assert int(r1.iterations) == int(r2.iterations)

    def test_solve_engine_auto_stays_general_off_tpu(self):
        op, b = _grid_problem()
        bf = jnp.asarray(b.ravel())
        r1 = solve(op, bf, tol=1e-5, maxiter=500, check_every=8)
        r3 = solve(op, bf, tol=1e-5, maxiter=500, check_every=8,
                   engine="auto")
        np.testing.assert_array_equal(np.asarray(r3.x), np.asarray(r1.x))

    def test_solve_engine_resident_rejects_unsupported(self):
        # record_history is supported (block-granular) since round 4;
        # checkpointing still is not.
        op, b = _grid_problem()
        with pytest.raises(ValueError, match="resident"):
            solve(op, jnp.asarray(b.ravel()), engine="resident",
                  return_checkpoint=True)
        with pytest.raises(ValueError, match="engine"):
            solve(op, jnp.asarray(b.ravel()), engine="warp")

    def test_non_f32_rhs_not_eligible(self):
        # the general path casts int rhs; the resident gate must exclude
        # it (engine='auto' falls back, 'resident' raises the curated
        # message, never the kernel's raw dtype error).
        op, b = _grid_problem()
        b_int = jnp.ones(16 * 128, jnp.int32)
        r = solve(op, b_int, tol=1e-5, maxiter=100, engine="auto")
        assert jnp.issubdtype(r.x.dtype, jnp.floating)  # general path cast
        with pytest.raises(ValueError, match="float32 rhs"):
            solve(op, b_int, engine="resident")

    def test_mismatched_chebyshev_not_eligible(self):
        from cuda_mpi_parallel_tpu.models.precond import (
            ChebyshevPreconditioner,
        )
        from cuda_mpi_parallel_tpu.solver.resident import resident_eligible

        op, b = _grid_problem()
        scaled = Stencil2D.create(16, 128, scale=4.0, dtype=jnp.float32)
        m_bad = ChebyshevPreconditioner.from_operator(scaled, degree=4)
        assert not resident_eligible(op, jnp.asarray(b), m_bad)
        m_ok = ChebyshevPreconditioner.from_operator(op, degree=4)
        assert resident_eligible(op, jnp.asarray(b), m_ok)
        # auto with the mismatched m must run the general path, not raise
        r = solve(op, jnp.asarray(b.ravel()), tol=1e-5, maxiter=500,
                  m=m_bad, engine="auto")
        assert int(r.iterations) > 0


class TestGate:
    def test_supports_resident_stencil2d(self):
        op, _ = _grid_problem()
        assert supports_resident(op)

    def test_rejects_non_stencil_operator(self):
        from cuda_mpi_parallel_tpu.models import random_spd

        dense = random_spd.random_spd_dense(8, dtype=np.float32)
        assert not supports_resident(dense)
        with pytest.raises(TypeError, match="Stencil"):
            cg_resident(dense, jnp.zeros(8, jnp.float32), interpret=True)

    def test_rejects_unaligned_grid(self):
        assert not rk.supports_resident_2d(10, 128)
        assert not rk.supports_resident_2d(16, 100)

    def test_rejects_over_budget_grid(self, monkeypatch):
        monkeypatch.setenv(rk._ENV_OVERRIDE, str(1 << 20))
        assert not rk.supports_resident_2d(1024, 1024)
        assert rk.supports_resident_2d(8, 128)

    def test_probe_relaxed_bound_admits_2048(self):
        # round-5 capacity probe (tools/capacity_probe_r05.json): the
        # kernel compiles and runs at 2048^2 f32 on a 128 MiB part, so
        # the gate must admit it (the old 12-plane bound routed every
        # grid past 1448^2 to slower engines)
        assert rk.supports_resident_2d(2048, 2048)
        # and the bound stays a bound: 2304^2 needs 7 * 21.2 MB > 128 MiB
        assert not rk.supports_resident_2d(2304, 2304)

    def test_env_override_validation(self, monkeypatch):
        monkeypatch.setenv(rk._ENV_OVERRIDE, "not-a-number")
        with pytest.raises(ValueError, match="integer byte count"):
            rk.vmem_bytes()
        monkeypatch.setenv(rk._ENV_OVERRIDE, "-5")
        with pytest.raises(ValueError, match="positive"):
            rk.vmem_bytes()

    def test_rejects_wrong_dtype_rhs(self):
        op, b = _grid_problem()
        with pytest.raises(ValueError, match="float32"):
            cg_resident(op, jnp.asarray(b, jnp.float64), interpret=True)

    def test_rejects_wrong_shape_rhs(self):
        op, _ = _grid_problem()
        with pytest.raises(ValueError, match="grid"):
            cg_resident(op, jnp.zeros(17, jnp.float32), interpret=True)


class TestDF64Resident:
    """df64 (double-float) resident kernel: f64-class CG in one kernel.

    Small grids and tight iteration budgets - interpret-mode df64 is
    expensive (every EFT op runs individually on CPU).
    """

    def _problem(self, nx=8, ny=128, seed=0):
        op = poisson.poisson_2d_operator(nx, ny, dtype=jnp.float32)
        rng = np.random.default_rng(seed)
        return op, rng.standard_normal(nx * ny)

    def test_fixed_iteration_trajectory_matches_cg_df64(self):
        op, b64 = self._problem()
        ref = cg_df64(op, b64, tol=0.0, maxiter=24, check_every=8)
        res = cg_resident_df64(op, b64, tol=0.0, maxiter=24,
                               check_every=8, interpret=True)
        assert int(res.iterations) == int(ref.iterations)
        x_ref, x_res = ref.x(), res.x()
        rel = np.abs(x_res - x_ref).max() / np.abs(x_ref).max()
        assert rel < 1e-11, rel
        # df64 recurrence residual agrees through both words
        assert np.isclose(res.residual_norm(), ref.residual_norm(),
                          rtol=1e-9)

    def test_converges_below_f32_depth(self):
        # the point of df64: a tolerance plain f32 cannot reach
        nx, ny = 8, 128
        op = poisson.poisson_2d_operator(nx, ny, dtype=jnp.float32)
        x_true = np.zeros((nx, ny)); x_true[4, 64] = 1.0
        from cuda_mpi_parallel_tpu.ops import df64 as df
        bh, bl = df.split_f64(x_true.ravel())
        # b = A x_true in df64 (via the reference df64 matvec)
        sc = df.const(1.0)
        ah, al = df.stencil2d_matvec(
            (jnp.asarray(bh), jnp.asarray(bl)), (nx, ny), sc)
        b64 = np.asarray(ah, np.float64) + np.asarray(al, np.float64)
        res = cg_resident_df64(op, b64, tol=1e-10, maxiter=300,
                               check_every=8, interpret=True)
        assert bool(res.converged)
        assert res.residual_norm() < 1e-10
        assert np.abs(res.x() - x_true.ravel()).max() < 1e-9

    def test_cap_truncation_and_status(self):
        op, b64 = self._problem()
        res = cg_resident_df64(op, b64, tol=1e-30, maxiter=10,
                               check_every=8, interpret=True)
        assert int(res.iterations) == 10
        assert res.status_enum() is CGStatus.MAXITER
        res2 = cg_resident_df64(op, b64, tol=0.0, maxiter=16,
                                check_every=8, iter_cap=9, interpret=True)
        assert int(res2.iterations) == 9

    def test_gate_and_errors(self, monkeypatch):
        op, b64 = self._problem()
        assert supports_resident_df64(op)
        assert not rk.supports_resident_df64_2d(10, 128)
        monkeypatch.setenv(rk._ENV_OVERRIDE, str(1 << 20))
        assert not rk.supports_resident_df64_2d(1024, 1024)
        op3 = Stencil3D.create(8, 8, 128, dtype=jnp.float32)
        assert supports_resident_df64(op3)
        assert not rk.supports_resident_df64_3d(8, 10, 128)
        from cuda_mpi_parallel_tpu.models import random_spd

        dense = random_spd.random_spd_dense(8, dtype=np.float32)
        assert not supports_resident_df64(dense)
        with pytest.raises(TypeError, match="Stencil"):
            cg_resident_df64(dense, np.zeros(8), interpret=True)
        with pytest.raises(ValueError, match="grid"):
            cg_resident_df64(op, np.zeros(17), interpret=True)

    def test_chebyshev_trajectory_matches_cg_df64(self):
        op, b64 = self._problem()
        ref = cg_df64(op, b64, tol=0.0, maxiter=16, check_every=8,
                      preconditioner="chebyshev", precond_degree=3)
        res = cg_resident_df64(op, b64, tol=0.0, maxiter=16,
                               check_every=8, preconditioner="chebyshev",
                               precond_degree=3, interpret=True)
        assert int(res.iterations) == int(ref.iterations)
        rel = np.abs(res.x() - ref.x()).max() / np.abs(ref.x()).max()
        assert rel < 1e-10, rel

    def test_chebyshev_cuts_iterations(self):
        op, b64 = self._problem()
        plain = cg_resident_df64(op, b64, tol=0.0, rtol=1e-8,
                                 maxiter=300, check_every=4,
                                 interpret=True)
        cheb = cg_resident_df64(op, b64, tol=0.0, rtol=1e-8,
                                maxiter=300, check_every=4,
                                preconditioner="chebyshev",
                                precond_degree=4, interpret=True)
        assert bool(cheb.converged)
        assert int(cheb.iterations) < int(plain.iterations) // 2

    def test_rejects_unknown_preconditioner(self):
        op, b64 = self._problem()
        with pytest.raises(ValueError, match="chebyshev"):
            cg_resident_df64(op, b64, preconditioner="jacobi",
                             interpret=True)

    def test_3d_trajectory_matches_cg_df64(self):
        op = Stencil3D.create(4, 8, 128, dtype=jnp.float32)
        rng = np.random.default_rng(2)
        b64 = rng.standard_normal(4 * 8 * 128)
        ref = cg_df64(op, b64, tol=0.0, maxiter=16, check_every=8)
        res = cg_resident_df64(op, b64, tol=0.0, maxiter=16,
                               check_every=8, interpret=True)
        assert int(res.iterations) == int(ref.iterations)
        rel = np.abs(res.x() - ref.x()).max() / np.abs(ref.x()).max()
        assert rel < 1e-11, rel

    def test_f32_rhs_lifted(self):
        op, b64 = self._problem()
        b32 = b64.astype(np.float32)
        r1 = cg_resident_df64(op, b32, tol=0.0, maxiter=8,
                              check_every=8, interpret=True)
        from cuda_mpi_parallel_tpu.ops import df64 as df
        r2 = cg_resident_df64(op, (b32, np.zeros_like(b32)), tol=0.0,
                              maxiter=8, check_every=8, interpret=True)
        np.testing.assert_array_equal(np.asarray(r1.x_hi),
                                      np.asarray(r2.x_hi))
        np.testing.assert_array_equal(np.asarray(r1.x_lo),
                                      np.asarray(r2.x_lo))


class TestFoldRadix:
    """The CMP_DF64_FOLD_RADIX experiment lever (roofline bottleneck-#2
    option (a)): radix-4 fold trees must produce the same trajectories
    as the default radix-2 (different summation order, same df64-class
    accuracy)."""

    def test_radix4_trajectory_matches_radix2(self, monkeypatch):
        op, b = _grid_problem()
        b64 = np.asarray(b, np.float64).ravel()
        r2 = cg_resident_df64(op, b64, tol=0.0, rtol=1e-10, maxiter=300,
                              check_every=8, interpret=True)
        import jax

        monkeypatch.setenv("CMP_DF64_FOLD_RADIX", "4")
        jax.clear_caches()  # the radix is baked in at trace time
        try:
            r4 = cg_resident_df64(op, b64, tol=0.0, rtol=1e-10,
                                  maxiter=300, check_every=8,
                                  interpret=True)
        finally:
            # drop the radix-4 executables so later tests with the
            # same signature do not silently reuse them after the env
            # var is restored
            jax.clear_caches()
        assert int(r2.iterations) == int(r4.iterations)
        np.testing.assert_allclose(r2.x(), r4.x(), rtol=0, atol=1e-12)

    def test_cross_radix_resume_rejected(self, tmp_path, monkeypatch):
        # replay checkpoints record the fold radix: the bitwise replay
        # guarantee depends on summation order, so a cross-radix resume
        # must fail loudly
        import os as _os

        from cuda_mpi_parallel_tpu.utils.checkpoint import (
            solve_resumable_df64,
        )

        op, b = _grid_problem()
        b64 = np.asarray(b, np.float64).ravel()
        path = str(tmp_path / "radix.npz")
        solve_resumable_df64(op, b64, path, segment_iters=16, tol=0.0,
                             rtol=1e-10, maxiter=16, engine="resident",
                             keep_checkpoint=True, interpret=True)
        assert _os.path.exists(path)
        monkeypatch.setenv("CMP_DF64_FOLD_RADIX", "4")
        with pytest.raises(ValueError, match="radix"):
            solve_resumable_df64(op, b64, path, segment_iters=16,
                                 tol=0.0, rtol=1e-10, maxiter=64,
                                 engine="resident", interpret=True)

    def test_invalid_radix_rejected(self, monkeypatch):
        from cuda_mpi_parallel_tpu.ops.pallas.resident import _fold_radix

        monkeypatch.setenv("CMP_DF64_FOLD_RADIX", "1")
        with pytest.raises(ValueError, match="RADIX"):
            _fold_radix()
