"""utils.timing coverage (previously untested).

A deterministic fake clock drives every protocol: monkeypatching
``timing.wall_seconds`` makes ``time_fn``'s best/median reductions and
``paired_delta_rate``'s interleaved-pair rate exact, checkable numbers
instead of wall-clock noise.
"""
import jax.numpy as jnp
import pytest

from cuda_mpi_parallel_tpu.utils import timing


class FakeClock:
    """Monotonic fake clock; work advances it explicitly."""

    def __init__(self):
        self.now = 0.0

    def __call__(self) -> float:
        return self.now

    def advance(self, dt: float) -> None:
        self.now += dt


@pytest.fixture
def clock(monkeypatch):
    c = FakeClock()
    monkeypatch.setattr(timing, "wall_seconds", c)
    return c


class TestTimer:
    def test_section_records_named_durations(self, clock):
        t = timing.Timer()
        with t.section("build"):
            clock.advance(0.5)
        with t.section("solve"):
            clock.advance(1.25)
        assert t.sections == [("build", 0.5), ("solve", 1.25)]

    def test_section_with_sync_blocks_device_work(self, clock):
        # sync= an actual device array exercises the _block barrier path
        t = timing.Timer()
        x = jnp.arange(8.0)
        with t.section("device", sync=x * 2):
            clock.advance(0.25)
        (name, sec), = t.sections
        assert name == "device" and sec >= 0.25

    def test_section_records_even_on_exception(self, clock):
        t = timing.Timer()
        with pytest.raises(RuntimeError):
            with t.section("boom"):
                clock.advance(0.1)
                raise RuntimeError("x")
        assert t.sections == [("boom", 0.1)]

    def test_report_formats_all_sections(self, clock):
        t = timing.Timer()
        with t.section("alpha"):
            clock.advance(0.001)
        report = t.report()
        assert "alpha" in report and "ms" in report


class TestTimeFn:
    def test_warmup_excluded_and_best_reduction(self, clock):
        durations = iter([10.0, 5.0, 1.0, 3.0])  # warmup, then repeats
        calls = []

        def fn():
            calls.append(1)
            clock.advance(next(durations))
            return 42

        sec, result = timing.time_fn(fn, warmup=1, repeats=3,
                                     reduce="best")
        assert result == 42
        assert len(calls) == 4            # 1 warmup + 3 timed
        assert sec == 1.0                 # best-of excludes the warmup

    def test_median_reduction(self, clock):
        durations = iter([9.0, 2.0, 8.0, 4.0])

        def fn():
            clock.advance(next(durations))

        sec, _ = timing.time_fn(fn, warmup=1, repeats=3, reduce="median")
        assert sec == 4.0

    def test_invalid_reduce_raises(self, clock):
        with pytest.raises(ValueError, match="unknown reduce mode"):
            timing.time_fn(lambda: None, warmup=1, repeats=1,
                           reduce="mean")


class TestPairedDeltaRate:
    def test_exact_rate_on_linear_workload(self, clock):
        # run(it) costs overhead + it / rate: the pairing cancels the
        # overhead exactly, so the measured rate is exact
        rate_true = 50_000.0
        overhead = 0.030

        def run(it):
            clock.advance(overhead + it / rate_true)
            return None

        got = timing.paired_delta_rate(run, 100, 10100, pairs=5)
        assert got == pytest.approx(rate_true, rel=1e-9)

    def test_robust_to_one_jitter_spike(self, clock):
        rate_true = 10_000.0
        spikes = {3}                      # pair index with a jitter hit
        calls = [0]

        def run(it):
            pair = calls[0] // 2 - 1      # after the 2 warmup calls
            calls[0] += 1
            extra = 0.5 if (pair in spikes and it > 100) else 0.0
            clock.advance(0.01 + it / rate_true + extra)

        got = timing.paired_delta_rate(run, 100, 1100, pairs=7)
        # median over pairs discards the spiked pair
        assert got == pytest.approx(rate_true, rel=1e-9)
