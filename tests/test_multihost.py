"""Multi-host layer tests (single-process degradation paths; real
multi-host needs pod slices CI cannot provision - SURVEY SS4)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cuda_mpi_parallel_tpu import solve
from cuda_mpi_parallel_tpu.parallel import multihost
from cuda_mpi_parallel_tpu.parallel.dist_cg import solve_distributed
from cuda_mpi_parallel_tpu.models.operators import Stencil3D


class TestSingleProcessDegradation:
    def test_process_info(self):
        idx, count = multihost.process_info()
        assert idx == 0
        assert count == 1

    def test_global_mesh_spans_all_devices(self):
        mesh = multihost.global_mesh()
        assert mesh.devices.size == len(jax.devices())
        assert mesh.axis_names == ("rows",)

    @pytest.mark.skipif(len(jax.devices()) < 8,
                        reason="needs 8 virtual devices")
    def test_shard_vector_global_roundtrip(self, rng):
        mesh = multihost.global_mesh()
        v = rng.standard_normal(64)
        arr = multihost.shard_vector_global(v, 64, mesh)
        np.testing.assert_array_equal(np.asarray(arr), v)
        # sharded over all devices
        assert len(arr.sharding.device_set) == len(jax.devices())

    def test_shard_vector_global_length_check(self, rng):
        mesh = multihost.global_mesh()
        with pytest.raises(ValueError, match="full vector"):
            multihost.shard_vector_global(rng.standard_normal(8), 64, mesh)

    @pytest.mark.skipif(len(jax.devices()) < 8,
                        reason="needs 8 virtual devices")
    def test_solve_on_global_mesh(self):
        """The multihost mesh feeds the same solve_distributed path."""
        mesh = multihost.global_mesh()
        a = Stencil3D.create(16, 8, 8, dtype=jnp.float64)
        x_true = np.random.default_rng(41).standard_normal(a.shape[0])
        b = a @ jnp.asarray(x_true)
        res = solve_distributed(a, b, mesh=mesh, tol=0.0, rtol=1e-9,
                                maxiter=500)
        assert bool(res.converged)
        np.testing.assert_allclose(np.asarray(res.x), x_true, atol=1e-7)

    def test_initialize_noop_on_single_host(self):
        """No coordinator on a plain machine: must be a silent no-op, and
        a repeated call must stay one."""
        multihost.initialize()
        multihost.initialize()

    def test_shard_vector_global_divisibility(self, rng):
        mesh = multihost.global_mesh()
        n_dev = mesh.devices.size
        if n_dev == 1:
            pytest.skip("indivisibility needs > 1 device")
        with pytest.raises(ValueError, match="divide evenly"):
            multihost.shard_vector_global(
                rng.standard_normal(n_dev * 8 + 1), n_dev * 8 + 1, mesh)


class TestMultiProcessArithmetic:
    """The multi-process offset/slice math of ``shard_vector_global``
    (``multihost._translate_to_local`` + its validation), exercised with
    MOCKED process index/count - the round-2 verdict's gap: this
    arithmetic only runs where CI has no multi-process runtime."""

    def _mock(self, monkeypatch, idx, count):
        monkeypatch.setattr(jax, "process_index", lambda: idx)
        monkeypatch.setattr(jax, "process_count", lambda: count)

    @pytest.mark.parametrize("n_proc,proc", [(2, 0), (2, 1), (4, 3)])
    def test_device_slices_translate_to_local_ranges(self, n_proc, proc):
        """Each of a process's devices maps to the right window of its
        local slice, and together the windows tile it exactly."""
        global_length, n_dev = 64, 8
        per_dev = global_length // n_dev
        per_proc = global_length // n_proc
        offset = proc * per_proc
        dev_per_proc = n_dev // n_proc
        covered = []
        for d in range(dev_per_proc):
            g0 = offset + d * per_dev
            sl = (slice(g0 if g0 else None, g0 + per_dev),)
            start, stop = multihost._translate_to_local(
                sl, offset, global_length, per_proc)
            assert (start, stop) == (d * per_dev, (d + 1) * per_dev)
            covered.append((start, stop))
        assert covered[0][0] == 0 and covered[-1][1] == per_proc
        assert all(covered[i][1] == covered[i + 1][0]
                   for i in range(len(covered) - 1))

    def test_none_endpoints_mean_array_bounds(self):
        # first device of process 0 gets slice(None, k); the LAST device
        # of the LAST process can get slice(j, None)
        start, stop = multihost._translate_to_local(
            (slice(None, 8),), 0, 64, 32)
        assert (start, stop) == (0, 8)
        start, stop = multihost._translate_to_local(
            (slice(56, None),), 32, 64, 32)
        assert (start, stop) == (24, 32)

    def test_foreign_slice_rejected(self):
        """A slice belonging to another process's rows must raise, not
        silently feed wrong data."""
        with pytest.raises(ValueError, match="process-contiguous"):
            multihost._translate_to_local((slice(0, 8),), 32, 64, 32)
        with pytest.raises(ValueError, match="process-contiguous"):
            multihost._translate_to_local((slice(56, None),), 0, 64, 32)

    def test_wrong_local_length_raises(self, rng, monkeypatch):
        """With 2 mocked processes, passing the full vector (instead of
        this process's half) is caught before any device placement."""
        mesh = multihost.global_mesh()
        if mesh.devices.size < 2:
            pytest.skip("needs > 1 device")
        self._mock(monkeypatch, 0, 2)
        with pytest.raises(ValueError, match="expected 32"):
            multihost.shard_vector_global(rng.standard_normal(64), 64, mesh)

    def test_error_message_names_process(self, rng, monkeypatch):
        mesh = multihost.global_mesh()
        if mesh.devices.size < 2:
            pytest.skip("needs > 1 device")
        self._mock(monkeypatch, 1, 2)
        with pytest.raises(ValueError, match="process 1 holds 10"):
            multihost.shard_vector_global(rng.standard_normal(10), 64, mesh)
